"""Benchmark harness: consensus rounds/sec/chip (BASELINE.md target: 1M/s).

Runs the batched sim on the default JAX platform (the real TPU chip under
the driver; CPU elsewhere) and prints ONE machine-parsable JSON line:

    {"metric": "consensus_rounds_per_sec_per_chip", "value": ...,
     "unit": "rounds/s", "vs_baseline": value / 1e6, ...extras}

Headline workload is the config-5 shape — 100K 5-node groups, steady-state
replication — timed after a warmup run that absorbs compilation and the
initial elections (compile time excluded per VERDICT round-1 item 3).
Election latency (p50/p99, in ticks) comes from fault-injected runs on
BOTH engines — the config-4 shape (leader crashes + partitions + drops
at 50K groups) and the same fault mix at the 100K config-5 shape
("Jepsen-style at 100K", VERDICT r05 weak #4) — promoted to the Pallas
kernel only when full State AND full Metrics (histogram included, so
p50/p99 are bit-identical by construction) AND the flight-recorder ring
match the XLA path at the same tick; every promoted kernel segment
carries `state_identical` in the JSON. The config-2 shape — pure
leader-election rounds, no client commands — reports elections/sec at
10K groups under constant crash churn. Per-phase detail goes to stderr.

Multi-chip (DESIGN.md §9): when more than one TPU chip is visible, the
kernel segments run the SAME fused-chunk kernel shard_map'd over the
group mesh (raft_tpu/parallel/kmesh.py) — per-device grids, no
collectives inside the timed region — and the engine string says so
(`pallas-fused-chunk-sharded-8dev`); every manifest records the mesh
shape and per-device group count. The XLA reference stays single-device.

Observability (DESIGN.md §8): both engines fold the per-tick safety bit
(every segment is a groups x ticks x k node-tick soak; `safety_ok` per
segment and globally in the JSON), both carry the on-device flight
recorder (dumped on any gate failure or safety violation), warmup
(compile-inclusive) and steady-state walls are separate fields
everywhere (ONE normalized key set, `_wall_fields`), and every segment
appends a JSONL provenance manifest (config hash, jax/jaxlib versions,
device, wall split, verdicts) to $RAFT_TPU_MANIFEST or
./bench_manifest.jsonl.

Performance observability (DESIGN.md §12): every segment and manifest
record is stamped with its roofline fields — `predicted_rounds_per_sec`
(the HBM/FLOP-bound ceiling derived from the reconciled byte model +
cost_analysis FLOPs), `attainment_pct` (null off-TPU; the prediction
side runs anywhere), and `bound` — so each number says how close it
sits to what the hardware allows. `--trace-dir DIR` writes a Chrome
trace-event timeline (segment/warmup/timed/per-chunk spans, Perfetto-
loadable) plus a soak-heartbeat JSONL (counters + flight-ring health
every N chunks); `--jax-profile` adds a per-segment device-side
profiler capture. `scripts/bench_history.py` folds the emitted
manifests plus every BENCH_r*/MULTICHIP_* snapshot into one trajectory
with a regression gate.
"""

from __future__ import annotations

import argparse
import dataclasses
import functools
import json
import os
import sys
import time

import jax
import numpy as np

from raft_tpu import sim
# Client traffic subsystem (DESIGN.md §10): open-loop exactly-once
# sessions measured as client-visible SLO next to raw rounds/s.
from raft_tpu.clients import exactly_once_report, workload_params
from raft_tpu.config import RaftConfig
# Observability layer (DESIGN.md §8/§12): flight recorder rides both
# engines; every segment emits a JSONL provenance manifest stamped
# with its roofline fields; --trace-dir adds Chrome trace-event spans
# and the soak heartbeat.
from raft_tpu.obs import (dump_flight, emit_manifest, flight_init,
                          run_recorded)
from raft_tpu.obs.manifest import (NEMESIS_KEYS, PACKING_KEYS,
                                   PRESSURE_KEYS)
from raft_tpu.obs import roofline as obs_roofline
from raft_tpu.obs import trace as obs_trace
from raft_tpu.sim.run import (latency_censored, latency_quantile,
                              metrics_init, total_client_ops,
                              total_client_retries, total_rounds,
                              unsafe_groups)
# The byte-identical comparator the test suite and kernel sweep gate
# on, applied at the shapes that produce the headline numbers
# (VERDICT r05 Missing #1); the `why` names the first divergent leaf.
from raft_tpu.utils.trees import trees_equal_why as _trees_equal_why

BASELINE_ROUNDS_PER_SEC = 1_000_000.0


def log(msg: str):
    print(msg, file=sys.stderr, flush=True)


def _device_str() -> str:
    dev = jax.devices()[0]
    return f"{dev.platform}:{dev.device_kind}"


def _kernel_mesh():
    """The kernel data-parallel mesh: every visible TPU chip, or None
    when one (or zero) chips are visible — the unsharded kstep path.
    The XLA reference engine stays single-device either way; only the
    kernel segments ride the mesh (DESIGN.md §9)."""
    devs = jax.devices()
    if devs[0].platform == "tpu" and len(devs) > 1:
        from raft_tpu import parallel
        return parallel.make_mesh(len(devs))
    return None


def _kernel_engine(cfg, n_groups: int):
    """(nd, name, kinit, kstep): the ONE kernel harness both kernel
    drivers (_pallas_segment, bench_fault_latency) share — sharded
    over every visible TPU chip, or the single-device kstep path. The
    engine NAME constructed here is load-bearing: _gate_fields and the
    fault segment decide mesh provenance by comparing the promoted
    engine string against it, so it must have exactly one producer."""
    from raft_tpu.sim import pkernel
    mesh = _kernel_mesh()
    nd = mesh.size if mesh is not None else 1
    name = ("pallas-fused-chunk" if mesh is None
            else f"pallas-fused-chunk-sharded-{nd}dev")
    if mesh is not None:
        from raft_tpu.parallel import kmesh

        def kinit(st_in):
            return kmesh.kinit_sharded(cfg, st_in, mesh,
                                       flight=flight_init(n_groups))

        def kstep(lvs, at, n):
            return kmesh.kstep_sharded(cfg, lvs, at, n, mesh)
    else:
        def kinit(st_in):
            return pkernel.kinit(cfg, st_in, flight=flight_init(n_groups))

        def kstep(lvs, at, n):
            return pkernel.kstep(cfg, lvs, at, n)
    return nd, name, kinit, kstep


def _mesh_fields(n_groups: int, nd: int) -> dict:
    """Provenance for every manifest record: the device-mesh shape the
    segment's PROMOTED engine actually ran on — a segment that fell
    back to the single-device XLA scan (kernel unsupported, mismatch,
    or error) must say mesh_shape=[1] or a reader would divide its
    rate across chips that never ran it. Callers pass the device count
    as a VALUE (the kernel harness knows it), never re-derived from a
    display string."""
    return {"mesh_shape": [nd], "groups_per_device": -(-n_groups // nd)}


# The canonical wall-clock key set every segment dict (and hence every
# manifest record) carries — r07 grew `xla_wall_s`/`kernel_wall_s` on
# the from-tick-0 segments while the steady-state segments said
# `timed_wall_s`/`pallas_warmup_wall_s`, and the fault segment had no
# `timed_wall_s` at all; one producer (`_wall_fields`), pinned by
# tests/test_perf_obs.py, ends the drift. `timed_wall_s` is always the
# PROMOTED engine's steady-state wall; nulls mean "that engine did not
# run", never "unrecorded".
SEGMENT_WALL_KEYS = ("timed_wall_s", "xla_wall_s", "xla_warmup_wall_s",
                     "kernel_wall_s", "kernel_warmup_wall_s")


def _wall_fields(timed_wall_s, xla_wall_s=None, xla_warmup_wall_s=None,
                 kernel_wall_s=None, kernel_warmup_wall_s=None) -> dict:
    """The ONE producer of the wall-clock split keys (see
    SEGMENT_WALL_KEYS). Rounds to ms precision; None passes through."""
    def r3(v):
        return round(v, 3) if v is not None else None
    return {"timed_wall_s": r3(timed_wall_s), "xla_wall_s": r3(xla_wall_s),
            "xla_warmup_wall_s": r3(xla_warmup_wall_s),
            "kernel_wall_s": r3(kernel_wall_s),
            "kernel_warmup_wall_s": r3(kernel_warmup_wall_s)}


# Filled by main() when --trace-dir is given: the Chrome trace file
# this process will save, stamped into every segment/manifest record.
_TRACE_PATH: str | None = None

# Kernel wire-layout dials applied to every segment config — filled by
# main() from --pack-wire (DESIGN.md §13). The promotion differentials
# are unchanged: a packed kernel must still be bit-identical to the
# XLA reference on full State + Metrics + flight ring, so --pack-wire
# is a measured-delta run, not a weaker gate.
_WIRE_DIALS: dict = {}


def _seg_cfg(**kwargs) -> RaftConfig:
    """A segment's RaftConfig with the run-wide wire-layout dials
    applied — the ONE place --pack-wire reaches the configs, so no
    segment can miss the dials (or double-apply them)."""
    return RaftConfig(**kwargs, **_WIRE_DIALS)


def _packing_fields(cfg) -> dict:
    """The r13 manifest stamp: which wire-layout dials this segment's
    kernel engine ran with (obs.manifest.PACKING_KEYS, null-by-default
    in every record until stamped here)."""
    return {k: getattr(cfg, k) for k in PACKING_KEYS}


def _nemesis_fields(cfg) -> dict:
    """The r14 manifest stamp: which gray-failure program this
    segment's universe ran under (obs.manifest.NEMESIS_KEYS,
    null-by-default in every record until stamped here) — derived from
    the key registry so a manifest-side rename cannot drift past this
    producer."""
    from raft_tpu import nemesis
    vals = {"nemesis_program_hash": nemesis.program_hash(cfg.nemesis),
            "nemesis_clauses": nemesis.to_json(cfg.nemesis)}
    if set(vals) != set(NEMESIS_KEYS):
        raise RuntimeError(f"obs.manifest.NEMESIS_KEYS {NEMESIS_KEYS} "
                           f"drifted from the bench producer {set(vals)}")
    return vals


def _stream_fields(cfg, pal=None) -> dict:
    """The r16/r17 manifest stamp: the residency knobs plus the
    predicted / measured overlap efficiency of the cohort paging
    pipeline AND its per-device split (obs.manifest.STREAM_KEYS +
    STREAM_MESH_KEYS, null-by-default in every record until stamped
    here; DESIGN.md §15/§16). `pal` is the kernel-side segment dict:
    its `overlap_measured` / `stream_per_device_measured` /
    `stream_slowest_device` come from a streamed run's pipeline stats
    — None on resident engines and off-TPU (predicted still derives
    whenever the segment's cfg streams, so the model stays
    inspectable on CPU boxes), and its `nd` is the device count the
    streamed engine paged over (ignored on resident configs)."""
    pal = pal or {}
    return obs_roofline.stream_segment_fields(
        cfg, measured=pal.get("overlap_measured"), chunk_ticks=CHUNK,
        n_devices=(pal.get("nd") or 1) if cfg.stream_groups else 1,
        per_device_measured=pal.get("stream_per_device_measured"),
        slowest_device=pal.get("stream_slowest_device"))


def _roofline_fields(cfg, n_groups: int, engine: str, ticks: int,
                     timed_wall_s, nd: int = 1) -> dict:
    """The roofline stamp every segment carries (DESIGN.md §12):
    predicted_rounds_per_sec / attainment_pct / bound plus the full
    derivation. The measured side is only meaningful against a real
    TPU wall — on any other backend the prediction still runs and
    attainment is null (the model stays testable on CPU boxes). The
    FLOPs probe compiles one abstract tick; off-TPU that compile can
    dwarf a --quick run on slow-compile boxes, so it is skipped there
    unless $RAFT_TPU_ROOFLINE_FLOPS=1 opts in (bound degrades to the
    hbm side, which is the binding resource for every XLA shape
    anyway, DESIGN.md §7)."""
    on_tpu = jax.devices()[0].platform == "tpu"
    flops = on_tpu or os.environ.get("RAFT_TPU_ROOFLINE_FLOPS") == "1"
    fields = obs_roofline.segment_fields(
        cfg, n_groups, engine, ticks=ticks, timed_wall_s=timed_wall_s,
        nd=nd, chunk_ticks=CHUNK, measured=on_tpu, flops=flops)
    fields["trace_path"] = _TRACE_PATH
    return fields


def _gate_fields(label: str, pal, m_ref, f_ref, n_groups: int,
                 engine: str) -> dict:
    """The verdict/mesh-provenance fields every steady-state segment
    shares (throughput / election-rounds / reads): the per-tick safety
    verdict, the kernel promotion verdicts, and the mesh fields for
    the engine that actually stood (`engine` equals the kernel's own
    name only when it was promoted; any fallback means the
    single-device XLA scan ran) — assembled once so the three segment
    dicts cannot drift apart. Wall keys live in `_wall_fields`."""
    unsafe = _safety_check(label, m_ref, f_ref, n_groups)
    nd_eff = pal["nd"] if engine == pal["engine"] else 1
    return {
        "state_identical": pal["state_identical"],
        "metrics_identical": pal["metrics_identical"],
        "flight_identical": pal["flight_identical"],
        "safety_ok": unsafe == 0,
        "unsafe_groups": unsafe,
        **_mesh_fields(n_groups, nd_eff),
    }


def _safety_check(label: str, m, flight=None, n_groups=None) -> int:
    """Per-tick safety verdict for a finished segment: logs it, dumps
    the flight recorder on violation, returns the unsafe-group count.
    Every segment is a (groups x ticks)-node-tick soak now — a
    violation is reported loudly but must not kill the bench (the JSON
    line and manifests still have to come out)."""
    unsafe = unsafe_groups(m)
    if unsafe == 0:
        log(f"  [{label}] per-tick safety fold: all groups clean")
    else:
        log(f"  [{label}] SAFETY VIOLATION: {unsafe} group(s) dropped the "
            f"per-tick safety bit")
        if flight is not None:
            dump_flight(flight, n_groups, label=label)
    return unsafe


CHUNK = 200   # ticks per device call: one compiled program, reused


def _timed_chunks(cfg, n_groups: int, ticks: int, counter_fn,
                  warmup_chunks: int = 1, label: str = "xla",
                  chunk: int | None = None):
    """Shared warmup + chunked-timing harness for every counter-delta
    bench segment. Runs in fixed-size chunks so every timed device call
    reuses the one compiled (cfg, CHUNK, pytree-shape) program — the
    warmup chunk absorbs compilation AND the initial elections, so the
    timed region measures steady state only. (Chunking also keeps
    single device programs short, which the TPU tunnel tolerates far
    better than one scan over 10^3+ ticks.)

    `counter_fn(st, m) -> int` must read a monotone event counter;
    returns (rate/s, delta, elapsed_s, timed_ticks, warmup_s, st, m, f)
    — the final state/metrics/flight let a caller extend the same
    universe without re-simulating it from tick 0. `warmup_s` is the
    compile-inclusive warmup wall; `elapsed_s` is steady-state only —
    the two are reported as SEPARATE fields everywhere (manifest +
    bench JSON) so compile cost can never blur into a throughput
    number. The flight-recorder ring rides the scan in both phases.

    Observability (DESIGN.md §12): with a tracer installed the warmup
    and timed regions are separate spans with one chunk-span per
    device call, and the soak heartbeat (when installed) snapshots
    metrics + flight-ring health after every timed chunk — a long run
    is observable mid-flight."""
    chunk = chunk or CHUNK
    st = sim.init(cfg, n_groups=n_groups)
    m = metrics_init(n_groups)
    f = flight_init(n_groups)
    t0 = time.perf_counter()
    tick_at = 0
    with obs_trace.span(f"warmup+compile xla [{label}]",
                        warmup_chunks=warmup_chunks):
        for _ in range(warmup_chunks):
            with obs_trace.chunk_span("xla", tick_at, chunk, phase="warmup"):
                st, m, f = run_recorded(cfg, st, chunk, tick_at, m, f)
                tick_at += chunk
        jax.block_until_ready(st)
    warmup_s = time.perf_counter() - t0
    log(f"  warmup {tick_at} ticks (incl. compile): {warmup_s:.1f}s")
    base = counter_fn(st, m)
    n_chunks = max(1, ticks // chunk)
    start = time.perf_counter()
    with obs_trace.span(f"timed xla [{label}]", n_chunks=n_chunks):
        for _ in range(n_chunks):
            with obs_trace.chunk_span("xla", tick_at, chunk, phase="timed"):
                st, m, f = run_recorded(cfg, st, chunk, tick_at, m, f)
                tick_at += chunk
            obs_trace.heartbeat(label, tick_at, m, f)
        jax.block_until_ready(st)
    elapsed = time.perf_counter() - start
    delta = counter_fn(st, m) - base
    return (delta / elapsed, delta, elapsed, n_chunks * chunk, warmup_s,
            st, m, f)


def _pallas_segment(cfg, n_groups: int, timed_ticks: int, counter_name,
                    st_ref, m_ref, f_ref, what: str):
    """Shared Pallas fused-chunk warmup/timing/differential harness
    (the kernel-side analogue of `_timed_chunks`; every steady-state
    kernel segment runs through here so the subtleties stay in one
    place — `bench_fault_latency` carries the same warmup/timing/
    promotion protocol in its from-tick-0 form, where the histogram
    needs every tick and no reference can be extended).
    Returns a dict {rate, count, elapsed, warmup_s, status,
    state_identical, metrics_identical, flight_identical} with status
    one of "ok" | "mismatch" | "unsupported" | an error string, and
    state_identical the FULL-State pytree comparison against the XLA
    reference at the same tick (None when the kernel never produced a
    state). Promotion requires the full State pytree, the full Metrics
    pytree (committed / leaderless / elections / histogram /
    max_latency / safety), AND the flight-recorder ring bit-identical —
    a counter-blind corruption of terms, logs, or mailbox state demotes
    the kernel exactly like a counter drift would (VERDICT r05 Missing
    #1); the per-segment counter is only the timed quantity, not the
    differential. On mismatch both engines' flight rings are dumped
    next to the leaf-level report.

    Subtleties encoded here, each learned from a wrong measurement:
    - TWO warmup launches: the first compiles for kinit's buffer
      layouts, the second for the kernel's own output layouts (a
      distinct executable — timing it once cost 13.5s of "steady
      state"); the counter fetch after each forces completion.
    - The timed region is closed by the counter fetch itself: the TPU
      tunnel's block_until_ready is not a reliable barrier.
    - The differential extends the XLA reference (already at tick
      CHUNK + timed_ticks from `_timed_chunks`) by ONE more chunk to
      the kernel's 2*CHUNK + timed_ticks endpoint, then the two
      universes must be bit-identical.
    """
    if cfg.stream_groups:   # r16: the cohort scheduler carries the kernel
        return _streamed_segment(cfg, n_groups, timed_ticks, counter_name,
                                 st_ref, m_ref, f_ref, what)
    fail = dict(rate=None, count=None, elapsed=None, warmup_s=None,
                state_identical=None, metrics_identical=None,
                flight_identical=None, engine="pallas-fused-chunk", nd=1,
                overlap_measured=None)
    try:   # kernel failure of ANY kind (incl. import) never kills the bench
        from raft_tpu.sim import pkernel
        # Sharded engine when >1 chip is visible (DESIGN.md §9): same
        # kernel, per-device grids over device-local blocks, zero
        # collectives per launch — conversion + placement happen in
        # kinit, outside the timed region.
        nd, name, kinit, kstep = _kernel_engine(cfg, n_groups)
        fail["engine"], fail["nd"] = name, nd
        if not (pkernel.supported(cfg, n_groups, nd)
                and jax.devices()[0].platform == "tpu"):
            return {**fail, "status": "unsupported"}
        counter_fn = functools.partial(
            getattr(pkernel, counter_name), cfg)
        leaves, g = kinit(sim.init(cfg, n_groups=n_groups))
        t0 = time.perf_counter()
        with obs_trace.span(f"warmup+compile pallas [{what}]"):
            leaves = kstep(leaves, 0, CHUNK)
            counter_fn(leaves, g)                        # forces compile #1
            leaves = kstep(leaves, CHUNK, CHUNK)
            base = counter_fn(leaves, g)                 # forces compile #2
        warmup_s = time.perf_counter() - t0
        log(f"  [pallas] warmup {2 * CHUNK} ticks (incl. 2 compiles): "
            f"{warmup_s:.1f}s")
        n_chunks = timed_ticks // CHUNK
        start = time.perf_counter()
        with obs_trace.span(f"timed pallas [{what}]", n_chunks=n_chunks):
            for c in range(n_chunks):
                with obs_trace.chunk_span("pallas", (c + 2) * CHUNK, CHUNK,
                                          phase="timed"):
                    leaves = kstep(leaves, (c + 2) * CHUNK, CHUNK)
                obs_trace.heartbeat_wire(f"pallas:{what}",
                                         (c + 3) * CHUNK, cfg, leaves, g)
            count = counter_fn(leaves, g) - base   # fetch closes the timer
        elapsed = time.perf_counter() - start
        rate = count / elapsed
        log(f"  [pallas{'' if nd == 1 else f' x{nd}dev'}] "
            f"{n_groups} groups x {timed_ticks} ticks: "
            f"{count} {what} in {elapsed:.2f}s -> {rate:,.0f} {what}/s "
            f"({elapsed / timed_ticks * 1e3:.2f} ms/tick)")
        st_ref, m_ref, f_ref = run_recorded(cfg, st_ref, CHUNK,
                                            CHUNK + timed_ticks, m_ref,
                                            f_ref)
        st_pal, m_pal = pkernel.kfinish(cfg, leaves, g)
        f_pal = pkernel.kflight(cfg, leaves, g)
        state_ok, s_why = _trees_equal_why(st_ref, st_pal)
        metrics_ok, m_why = _trees_equal_why(m_ref, m_pal)
        flight_ok, f_why = _trees_equal_why(f_ref, f_pal)
        verdicts = dict(state_identical=state_ok,
                        metrics_identical=metrics_ok,
                        flight_identical=flight_ok)
        if state_ok and metrics_ok and flight_ok:
            log("  [pallas] differential vs xla at same tick: full State "
                "+ full Metrics + flight ring bit-identical")
            return dict(rate=rate, count=count, elapsed=elapsed,
                        warmup_s=warmup_s, status="ok", engine=name,
                        nd=nd, overlap_measured=None, **verdicts)
        log(f"  [pallas] DIFFERENTIAL MISMATCH (state_identical={state_ok} "
            f"metrics_identical={metrics_ok} flight_identical={flight_ok})"
            f" - kernel number discarded")
        for why in (s_why, m_why, f_why):
            if why:
                log(f"  [pallas] {why}")
        dump_flight(f_ref, label="xla-ref")
        dump_flight(f_pal, label="pallas")
        # warmup_s survives: the compile/run split is provenance for
        # exactly the runs that need triage.
        return {**fail, **verdicts, "warmup_s": warmup_s,
                "status": "mismatch"}
    except Exception as e:   # kernel failure must never kill the bench
        log(f"  [pallas] failed ({type(e).__name__}: {e}); xla stands")
        return {**fail, "status": f"error: {type(e).__name__}"}


def _streamed_segment(cfg, n_groups: int, timed_ticks: int, counter_name,
                      st_ref, m_ref, f_ref, what: str):
    """--stream twin of `_pallas_segment` (DESIGN.md §15; §16): the
    cohort scheduler pages the fleet host<->HBM under the unchanged
    kernel — auto-sharded over every visible TPU chip (r17: each
    device pages its own whole-block window slice concurrently,
    engine `pallas-streamed-sharded-Ndev`), single-device otherwise.
    Same warmup/timing/promotion protocol — warmup advances the SAME
    universe by 2*CHUNK ticks (absorbing the window-shape compile), the
    timed region is one stream pass over the remaining ticks, and
    promotion requires the full State + full Metrics + flight ring
    bit-identical to the XLA reference at the same tick. Adds
    `overlap_measured` (compute_s / wall_s from the pipeline stats)
    plus the per-device split (`stream_per_device_measured` /
    `stream_slowest_device`) for the STREAM_KEYS + STREAM_MESH_KEYS
    stamp."""
    from raft_tpu.parallel import cohort
    mesh = _kernel_mesh()
    nd = mesh.size if mesh is not None else 1
    eng = cohort.sharded_engine(nd) if mesh is not None else cohort.ENGINE
    fail = dict(rate=None, count=None, elapsed=None, warmup_s=None,
                state_identical=None, metrics_identical=None,
                flight_identical=None, engine=eng, nd=nd,
                overlap_measured=None, stream_per_device_measured=None,
                stream_slowest_device=None)
    try:   # kernel failure of ANY kind never kills the bench
        from raft_tpu.sim import pkernel
        if not (pkernel.supported(cfg, n_groups, nd)
                and jax.devices()[0].platform == "tpu"):
            return {**fail, "status": "unsupported"}
        counter_fn = functools.partial(getattr(pkernel, counter_name), cfg)
        host, g = cohort.host_wire(cfg, sim.init(cfg, n_groups=n_groups),
                                   flight=flight_init(n_groups),
                                   pad_to=nd * pkernel.GB)

        def stream(h, t0s, n, stats=None):
            if mesh is not None:
                return cohort.stream_ticks_sharded(
                    cfg, h, g, t0s, n, mesh, chunk_ticks=CHUNK,
                    stats=stats)
            return cohort.stream_ticks(cfg, h, g, t0s, n,
                                       chunk_ticks=CHUNK, stats=stats)

        t0 = time.perf_counter()
        with obs_trace.span(f"warmup+compile streamed [{what}]"):
            stream(host, 0, 2 * CHUNK)
            base = counter_fn(host, g)
        warmup_s = time.perf_counter() - t0
        log(f"  [streamed{'' if nd == 1 else f' x{nd}dev'}] warmup "
            f"{2 * CHUNK} ticks (incl. compile): {warmup_s:.1f}s")
        stats: dict = {}
        start = time.perf_counter()
        with obs_trace.span(f"timed streamed [{what}]"):
            stream(host, 2 * CHUNK, timed_ticks, stats=stats)
            count = counter_fn(host, g) - base   # fetch closes the timer
        elapsed = time.perf_counter() - start
        rate = count / elapsed
        log(f"  [streamed{'' if nd == 1 else f' x{nd}dev'}] "
            f"{n_groups} groups x {timed_ticks} ticks "
            f"({stats['cohorts']} cohort windows, {stats['launches']} "
            f"launches): {count} {what} in {elapsed:.2f}s -> "
            f"{rate:,.0f} {what}/s (measured overlap "
            f"{stats['overlap_efficiency_measured']:.2f})")
        st_ref, m_ref, f_ref = run_recorded(cfg, st_ref, CHUNK,
                                            CHUNK + timed_ticks, m_ref,
                                            f_ref)
        leaves = tuple(host)
        st_s, m_s = pkernel.kfinish(cfg, leaves, g)
        f_s = pkernel.kflight(cfg, leaves, g)
        state_ok, s_why = _trees_equal_why(st_ref, st_s)
        metrics_ok, m_why = _trees_equal_why(m_ref, m_s)
        flight_ok, f_why = _trees_equal_why(f_ref, f_s)
        verdicts = dict(state_identical=state_ok,
                        metrics_identical=metrics_ok,
                        flight_identical=flight_ok)
        if state_ok and metrics_ok and flight_ok:
            log("  [streamed] differential vs xla at same tick: full State "
                "+ full Metrics + flight ring bit-identical")
            return dict(rate=rate, count=count, elapsed=elapsed,
                        warmup_s=warmup_s, status="ok",
                        engine=eng, nd=nd,
                        overlap_measured=stats.get(
                            "overlap_efficiency_measured"),
                        stream_per_device_measured=stats.get(
                            "overlap_efficiency_per_device_measured"),
                        stream_slowest_device=stats.get("slowest_device"),
                        **verdicts)
        log(f"  [streamed] DIFFERENTIAL MISMATCH (state_identical="
            f"{state_ok} metrics_identical={metrics_ok} flight_identical="
            f"{flight_ok}) - streamed number discarded")
        for why in (s_why, m_why, f_why):
            if why:
                log(f"  [streamed] {why}")
        dump_flight(f_ref, label="xla-ref")
        dump_flight(f_s, label="streamed")
        return {**fail, **verdicts, "warmup_s": warmup_s,
                "status": "mismatch"}
    except Exception as e:   # kernel failure must never kill the bench
        log(f"  [streamed] failed ({type(e).__name__}: {e}); xla stands")
        return {**fail, "status": f"error: {type(e).__name__}"}


def _pallas_full_run(cfg, n_groups: int, ticks: int, counter_name: str,
                     label: str, st_ref, m_ref, f_ref):
    """Kernel-side FROM-TICK-0 driver shared by the histogram-bearing
    segments (fault latency, client SLO) — where every tick counts and
    no reference can be extended, so `_pallas_segment`'s
    extend-the-reference protocol does not apply. Same subtleties:
    throwaway-universe warmup (2 compiles, each closed by a counter
    fetch), the timed chunk loop closed by the counter fetch, then the
    promotion differential — full State + full Metrics + flight ring
    bit-identical against the XLA reference at the same tick, flight
    rings dumped on mismatch. Returns {engine, promoted, k_elapsed,
    k_warmup_s, state_ok, metrics_ok, flight_ok, nd, k_name}; `engine`
    is the PROMOTED string ("xla-scan" or an annotated fallback).
    Kernel failure of ANY kind never raises out."""
    if cfg.stream_groups:   # r16: the cohort scheduler carries the kernel
        return _streamed_full_run(cfg, n_groups, ticks, counter_name,
                                  label, st_ref, m_ref, f_ref)
    out = dict(engine="xla-scan", promoted=False, k_elapsed=None,
               k_warmup_s=None, state_ok=None, metrics_ok=None,
               flight_ok=None, nd=1, k_name="pallas-fused-chunk",
               overlap_measured=None)
    try:
        from raft_tpu.sim import pkernel
        nd, k_name, kinit, kstep = _kernel_engine(cfg, n_groups)
        out["nd"], out["k_name"] = nd, k_name
        if not (pkernel.supported(cfg, n_groups, nd)
                and jax.devices()[0].platform == "tpu"):
            return out
        counter = functools.partial(getattr(pkernel, counter_name), cfg)
        t0 = time.perf_counter()
        with obs_trace.span(f"warmup+compile pallas [{label}]"):
            wl, wg = kinit(sim.init(cfg, n_groups=n_groups))
            wl = kstep(wl, 0, CHUNK)
            counter(wl, wg)
            wl = kstep(wl, CHUNK, CHUNK)
            counter(wl, wg)
        out["k_warmup_s"] = time.perf_counter() - t0
        log(f"  [pallas] warmup (incl. 2 compiles): "
            f"{out['k_warmup_s']:.1f}s")
        leaves, g = kinit(sim.init(cfg, n_groups=n_groups))
        start = time.perf_counter()
        with obs_trace.span(f"timed pallas [{label}]"):
            at = 0
            while at < ticks:
                n = min(CHUNK, ticks - at)
                with obs_trace.chunk_span("pallas", at, n, phase="timed"):
                    leaves = kstep(leaves, at, n)
                at += n
                obs_trace.heartbeat_wire(f"pallas:{label}", at, cfg,
                                         leaves, g)
            counter(leaves, g)   # fetch closes the timer
        out["k_elapsed"] = time.perf_counter() - start
        st_pal, m_pal = pkernel.kfinish(cfg, leaves, g)
        f_pal = pkernel.kflight(cfg, leaves, g)
        state_ok, s_why = _trees_equal_why(st_ref, st_pal)
        metrics_ok, m_why = _trees_equal_why(m_ref, m_pal)
        flight_ok, f_why = _trees_equal_why(f_ref, f_pal)
        out.update(state_ok=state_ok, metrics_ok=metrics_ok,
                   flight_ok=flight_ok)
        log(f"  [pallas{'' if nd == 1 else f' x{nd}dev'}] {label} "
            f"{n_groups} groups x {ticks} ticks in "
            f"{out['k_elapsed']:.2f}s "
            f"({out['k_elapsed'] / ticks * 1e3:.2f} ms/tick)")
        if state_ok and metrics_ok and flight_ok:
            log("  [pallas] differential vs xla at same tick: full State "
                "+ full Metrics (histograms + safety + client lanes when "
                "present) + flight ring bit-identical")
            out.update(engine=k_name, promoted=True)
        else:
            log(f"  [pallas] DIFFERENTIAL MISMATCH (state_identical="
                f"{state_ok} metrics_identical={metrics_ok} "
                f"flight_identical={flight_ok}) - kernel number discarded")
            for why in (s_why, m_why, f_why):
                if why:
                    log(f"  [pallas] {why}")
            dump_flight(f_ref, label=f"{label}:xla-ref")
            dump_flight(f_pal, label=f"{label}:pallas")
            out["engine"] = "xla-scan (pallas mismatch!)"
    except Exception as e:   # kernel failure must never kill the bench
        log(f"  [pallas] failed ({type(e).__name__}: {e}); xla stands")
        out["engine"] = f"xla-scan (pallas error: {type(e).__name__})"
    return out


def _streamed_full_run(cfg, n_groups: int, ticks: int, counter_name: str,
                       label: str, st_ref, m_ref, f_ref):
    """--stream twin of `_pallas_full_run` (DESIGN.md §15; §16): the
    from-tick-0 histogram segments under the cohort scheduler —
    auto-sharded over every visible TPU chip (r17), single-device
    otherwise. Same protocol — throwaway-universe warmup absorbs the
    window-shape compile, the timed region streams the real universe
    from tick 0, promotion requires the full State + full Metrics +
    flight ring bit-identical against the XLA reference. Fills
    `overlap_measured` plus the per-device split from the pipeline
    stats for the STREAM_KEYS + STREAM_MESH_KEYS stamp."""
    from raft_tpu.parallel import cohort
    mesh = _kernel_mesh()
    nd = mesh.size if mesh is not None else 1
    eng = cohort.sharded_engine(nd) if mesh is not None else cohort.ENGINE
    out = dict(engine="xla-scan", promoted=False, k_elapsed=None,
               k_warmup_s=None, state_ok=None, metrics_ok=None,
               flight_ok=None, nd=nd, k_name=eng,
               overlap_measured=None, stream_per_device_measured=None,
               stream_slowest_device=None)
    try:
        from raft_tpu.sim import pkernel
        if not (pkernel.supported(cfg, n_groups, nd)
                and jax.devices()[0].platform == "tpu"):
            return out
        counter = functools.partial(getattr(pkernel, counter_name), cfg)

        def stream(h, hg, t0s, n, stats=None):
            if mesh is not None:
                return cohort.stream_ticks_sharded(
                    cfg, h, hg, t0s, n, mesh, chunk_ticks=CHUNK,
                    stats=stats)
            return cohort.stream_ticks(cfg, h, hg, t0s, n,
                                       chunk_ticks=CHUNK, stats=stats)

        t0 = time.perf_counter()
        with obs_trace.span(f"warmup+compile streamed [{label}]"):
            wh, wg = cohort.host_wire(cfg,
                                      sim.init(cfg, n_groups=n_groups),
                                      flight=flight_init(n_groups),
                                      pad_to=nd * pkernel.GB)
            stream(wh, wg, 0, CHUNK)
            counter(wh, wg)
        out["k_warmup_s"] = time.perf_counter() - t0
        log(f"  [streamed{'' if nd == 1 else f' x{nd}dev'}] warmup "
            f"(incl. compile): {out['k_warmup_s']:.1f}s")
        host, g = cohort.host_wire(cfg, sim.init(cfg, n_groups=n_groups),
                                   flight=flight_init(n_groups),
                                   pad_to=nd * pkernel.GB)
        stats: dict = {}
        start = time.perf_counter()
        with obs_trace.span(f"timed streamed [{label}]"):
            stream(host, g, 0, ticks, stats=stats)
            counter(host, g)   # fetch closes the timer
        out["k_elapsed"] = time.perf_counter() - start
        out["overlap_measured"] = stats.get("overlap_efficiency_measured")
        out["stream_per_device_measured"] = stats.get(
            "overlap_efficiency_per_device_measured")
        out["stream_slowest_device"] = stats.get("slowest_device")
        leaves = tuple(host)
        st_s, m_s = pkernel.kfinish(cfg, leaves, g)
        f_s = pkernel.kflight(cfg, leaves, g)
        state_ok, s_why = _trees_equal_why(st_ref, st_s)
        metrics_ok, m_why = _trees_equal_why(m_ref, m_s)
        flight_ok, f_why = _trees_equal_why(f_ref, f_s)
        out.update(state_ok=state_ok, metrics_ok=metrics_ok,
                   flight_ok=flight_ok)
        log(f"  [streamed] {label} {n_groups} groups x {ticks} ticks "
            f"({stats['cohorts']} cohort windows) in "
            f"{out['k_elapsed']:.2f}s "
            f"({out['k_elapsed'] / ticks * 1e3:.2f} ms/tick, measured "
            f"overlap {stats['overlap_efficiency_measured']:.2f})")
        if state_ok and metrics_ok and flight_ok:
            log("  [streamed] differential vs xla at same tick: full "
                "State + full Metrics + flight ring bit-identical")
            out.update(engine=eng, promoted=True)
        else:
            log(f"  [streamed] DIFFERENTIAL MISMATCH (state_identical="
                f"{state_ok} metrics_identical={metrics_ok} "
                f"flight_identical={flight_ok}) - streamed number "
                f"discarded")
            for why in (s_why, m_why, f_why):
                if why:
                    log(f"  [streamed] {why}")
            dump_flight(f_ref, label=f"{label}:xla-ref")
            dump_flight(f_s, label=f"{label}:streamed")
            out["engine"] = "xla-scan (streamed mismatch!)"
    except Exception as e:   # kernel failure must never kill the bench
        log(f"  [streamed] failed ({type(e).__name__}: {e}); xla stands")
        out["engine"] = f"xla-scan (streamed error: {type(e).__name__})"
    return out


def bench_throughput(n_groups: int, ticks: int):
    """Config 2/3/5 shape: steady-state replication throughput.

    Runs BOTH engines at the same tick count — the XLA scan path
    (sim.run) and the Pallas fused-chunk kernel (sim.pkernel), which
    keeps a block's whole state VMEM-resident across a 200-tick chunk
    instead of streaming ~18 GB/tick of [G,K,L] intermediates through
    HBM (DESIGN.md §7). The kernel's number is promoted to the headline
    ONLY if its full State AND full Metrics pytrees are bit-identical
    to the XLA run at the same tick — a full-shape in-run differential
    on top of the CPU-interpret gate in tests/test_pkernel.py. On any
    mismatch or kernel failure the XLA number stands and the JSON says
    so (`state_identical` per segment)."""
    cfg = _seg_cfg(seed=42)
    (rps, rounds, elapsed, timed_ticks, warmup_s, st_ref, m_ref,
     f_ref) = _timed_chunks(cfg, n_groups, ticks,
                            lambda st, m: total_rounds(m),
                            label="throughput")
    log(f"  [xla] {n_groups} groups x {timed_ticks} ticks: {rounds} rounds "
        f"in {elapsed:.2f}s -> {rps:,.0f} rounds/s "
        f"({timed_ticks / elapsed:,.0f} ticks/s)")
    engine = "xla-scan"
    x_elapsed = elapsed
    pal = _pallas_segment(cfg, n_groups, timed_ticks, "kcommitted",
                          st_ref, m_ref, f_ref, "rounds")
    if pal["status"] == "ok" and pal["rate"] > rps:
        rps, rounds, elapsed = pal["rate"], pal["count"], pal["elapsed"]
        engine = pal["engine"]
    elif pal["status"] == "mismatch":
        engine = "xla-scan (pallas mismatch!)"
    ok = pal["status"] == "ok"
    seg = {
        "rounds_per_sec": round(rps, 1), "rounds": rounds,
        "ticks": timed_ticks, "engine": engine,
        "pallas_rounds_per_sec": round(pal["rate"], 1) if ok else None,
        "pallas_ms_per_tick": (round(pal["elapsed"] / timed_ticks * 1e3, 3)
                               if ok else None),
        **_wall_fields(elapsed, xla_wall_s=x_elapsed,
                       xla_warmup_wall_s=warmup_s,
                       kernel_wall_s=pal["elapsed"] if ok else None,
                       kernel_warmup_wall_s=pal["warmup_s"]),
        **_gate_fields("throughput", pal, m_ref, f_ref, n_groups,
                       engine),
        **_roofline_fields(cfg, n_groups, engine, timed_ticks, elapsed,
                           nd=pal["nd"] if engine == pal["engine"] else 1),
        **_packing_fields(cfg),
        **_stream_fields(cfg, pal),
    }
    emit_manifest("throughput", cfg, device=_device_str(),
                  n_groups=n_groups, **seg)
    return seg


def bench_fault_latency(seed: int, n_groups: int, ticks: int, label: str):
    """Fault-mix segment on BOTH engines (config-4 shape at 50K; the
    same fault knobs at the 100K config-5 shape): randomized leader
    crashes + partitions + drops; measures the election-latency
    distribution (ticks from leaderless to a new leader) AND the
    committed-round throughput under faults.

    The kernel can carry this segment now that the latency histogram is
    tracked in-kernel (per-group accumulator lanes, reduced at kfinish
    — sim/pkernel.py): both engines run the identical universe over
    ticks [0, ticks), compile excluded via a throwaway-universe warmup,
    and the kernel's numbers are promoted only when the full State AND
    full Metrics pytrees (histogram included, hence p50/p99) are
    bit-identical to the XLA path at the same tick. Returns a dict of
    segment results for the bench JSON."""
    cfg = _seg_cfg(seed=seed, crash_prob=0.3, crash_epoch=64,
                   partition_prob=0.2, partition_epoch=64, drop_prob=0.02)
    # --- XLA reference: warm the compile on a throwaway universe, then
    # time the real one end-to-end (the histogram needs every tick).
    t0 = time.perf_counter()
    with obs_trace.span(f"warmup+compile xla [{label}]"):
        wst, wm, wf = run_recorded(cfg, sim.init(cfg, n_groups=n_groups),
                                   CHUNK, 0, metrics_init(n_groups),
                                   flight_init(n_groups))
        jax.block_until_ready(wst)
    x_warmup_s = time.perf_counter() - t0
    log(f"  [xla] warmup chunk (incl. compile): {x_warmup_s:.1f}s")
    st = sim.init(cfg, n_groups=n_groups)
    m = metrics_init(n_groups)
    f = flight_init(n_groups)
    start = time.perf_counter()
    with obs_trace.span(f"timed xla [{label}]"):
        for tick_at in range(0, ticks, CHUNK):
            n = min(CHUNK, ticks - tick_at)
            with obs_trace.chunk_span("xla", tick_at, n, phase="timed"):
                st, m, f = run_recorded(cfg, st, n, tick_at, m, f)
            obs_trace.heartbeat(label, tick_at + n, m, f)
        n_elections = int(m.elections)      # fetch closes the timer
    x_elapsed = time.perf_counter() - start
    rounds = total_rounds(m)
    log(f"  [xla] {label} {n_groups} groups x {ticks} ticks in "
        f"{x_elapsed:.2f}s ({x_elapsed / ticks * 1e3:.2f} ms/tick): "
        f"{rounds} rounds, {n_elections} elections")

    pal = _pallas_full_run(cfg, n_groups, ticks, "kelections", label,
                           st, m, f)
    engine, k_elapsed, k_warmup_s = (pal["engine"], pal["k_elapsed"],
                                     pal["k_warmup_s"])
    state_ok, metrics_ok, flight_ok = (pal["state_ok"], pal["metrics_ok"],
                                       pal["flight_ok"])
    nd, k_name = pal["nd"], pal["k_name"]
    elapsed = k_elapsed if pal["promoted"] else x_elapsed

    unsafe = _safety_check(label, m, f, n_groups)
    p50 = latency_quantile(m.hist, 0.5)
    p99 = latency_quantile(m.hist, 0.99)
    censored = latency_censored(m.hist, 0.99)
    max_lat = int(m.max_latency)
    p99_note = (f"tail bounded by the fault schedule, not the protocol:"
                f" partitions hold for partition_epoch="
                f"{cfg.partition_epoch}-tick windows, so a group"
                f" partitioned away from quorum cannot elect until the"
                f" epoch rolls")
    log(f"  {label}: {n_elections} elections, p50={p50} p99={p99} "
        f"max={max_lat} ticks"
        f"{' [p99 CENSORED at histogram top bucket]' if censored else ''}"
        f" ({p99_note}); engine={engine}")
    seg = {
        "p50": p50, "p99": p99, "censored": censored, "max_lat": max_lat,
        "p99_note": p99_note, "elections": n_elections, "rounds": rounds,
        "rounds_per_sec": rounds / elapsed, "engine": engine,
        "state_identical": state_ok, "metrics_identical": metrics_ok,
        "flight_identical": flight_ok,
        "n_groups": n_groups, "ticks": ticks,
        **_wall_fields(elapsed, xla_wall_s=x_elapsed,
                       xla_warmup_wall_s=x_warmup_s,
                       kernel_wall_s=k_elapsed,
                       kernel_warmup_wall_s=k_warmup_s),
        "safety_ok": unsafe == 0, "unsafe_groups": unsafe,
        # Mesh provenance in the segment dict itself (not only the
        # manifest), matching the _gate_fields segments — the BENCH
        # JSON's fault entries must say their engine's device count too.
        **_mesh_fields(n_groups, nd if engine == k_name else 1),
        **_roofline_fields(cfg, n_groups, engine, ticks, elapsed,
                           nd=nd if engine == k_name else 1),
        **_packing_fields(cfg),
        **_stream_fields(cfg, pal),
    }
    emit_manifest(label, cfg, device=_device_str(),
                  **{k: v for k, v in seg.items() if k != "p99_note"})
    return seg


def bench_nemesis(seed: int, n_groups: int, ticks: int, label: str):
    """Gray-failure segment on BOTH engines (DESIGN.md §14): the
    canonical nemesis program (`nemesis.gray_mix` — slow-but-alive
    follower + asymmetric flaky link) composed onto light base churn.
    Where config-4/5 measure behavior under fail-STOP faults, this
    segment is the published number for behavior under fail-SLOW ones:
    committed-round throughput and the election-latency distribution
    while every group carries a degraded-but-alive node and a silently
    lossy link the whole run.

    Same from-tick-0 protocol as bench_fault_latency (histogram needs
    every tick; throwaway-universe warmups; separate walls); kernel
    promotion under the unchanged full State + Metrics + flight-ring
    bit-identity gate. The manifest/JSON carry the program's stable
    hash and clause list (obs.manifest.NEMESIS_KEYS — null on every
    other segment), so a reader can pair this number against the
    fail-stop segments without digging through config dicts."""
    from raft_tpu import nemesis
    cfg = _seg_cfg(seed=seed, crash_prob=0.1, crash_epoch=64,
                   drop_prob=0.02, nemesis=nemesis.gray_mix(ticks))
    log(f"  [{label}] program {nemesis.program_hash(cfg.nemesis)}: "
        f"{nemesis.describe(cfg.nemesis)}")
    t0 = time.perf_counter()
    with obs_trace.span(f"warmup+compile xla [{label}]"):
        wst, wm, wf = run_recorded(cfg, sim.init(cfg, n_groups=n_groups),
                                   CHUNK, 0, metrics_init(n_groups),
                                   flight_init(n_groups))
        jax.block_until_ready(wst)
    x_warmup_s = time.perf_counter() - t0
    log(f"  [xla] warmup chunk (incl. compile): {x_warmup_s:.1f}s")
    st = sim.init(cfg, n_groups=n_groups)
    m = metrics_init(n_groups)
    f = flight_init(n_groups)
    start = time.perf_counter()
    with obs_trace.span(f"timed xla [{label}]"):
        for tick_at in range(0, ticks, CHUNK):
            n = min(CHUNK, ticks - tick_at)
            with obs_trace.chunk_span("xla", tick_at, n, phase="timed"):
                st, m, f = run_recorded(cfg, st, n, tick_at, m, f)
            obs_trace.heartbeat(label, tick_at + n, m, f)
        n_elections = int(m.elections)      # fetch closes the timer
    x_elapsed = time.perf_counter() - start
    rounds = total_rounds(m)
    log(f"  [xla] {label} {n_groups} groups x {ticks} ticks in "
        f"{x_elapsed:.2f}s ({x_elapsed / ticks * 1e3:.2f} ms/tick): "
        f"{rounds} rounds, {n_elections} elections")

    pal = _pallas_full_run(cfg, n_groups, ticks, "kelections", label,
                           st, m, f)
    engine, k_elapsed, k_warmup_s = (pal["engine"], pal["k_elapsed"],
                                     pal["k_warmup_s"])
    nd, k_name = pal["nd"], pal["k_name"]
    elapsed = k_elapsed if pal["promoted"] else x_elapsed

    unsafe = _safety_check(label, m, f, n_groups)
    p50 = latency_quantile(m.hist, 0.5)
    p99 = latency_quantile(m.hist, 0.99)
    censored = latency_censored(m.hist, 0.99)
    log(f"  {label}: {rounds} rounds ({rounds / elapsed:,.0f} rounds/s "
        f"under gray failures), {n_elections} elections, p50={p50} "
        f"p99={p99} max={int(m.max_latency)} ticks"
        f"{' [p99 CENSORED at histogram top bucket]' if censored else ''}"
        f"; engine={engine}")
    seg = {
        "rounds_per_sec": rounds / elapsed, "rounds": rounds,
        "elections": n_elections,
        "p50": p50, "p99": p99, "censored": censored,
        "max_lat": int(m.max_latency),
        "engine": engine,
        "state_identical": pal["state_ok"],
        "metrics_identical": pal["metrics_ok"],
        "flight_identical": pal["flight_ok"],
        "n_groups": n_groups, "ticks": ticks,
        **_nemesis_fields(cfg),
        **_wall_fields(elapsed, xla_wall_s=x_elapsed,
                       xla_warmup_wall_s=x_warmup_s,
                       kernel_wall_s=k_elapsed,
                       kernel_warmup_wall_s=k_warmup_s),
        "safety_ok": unsafe == 0, "unsafe_groups": unsafe,
        **_mesh_fields(n_groups, nd if engine == k_name else 1),
        **_roofline_fields(cfg, n_groups, engine, ticks, elapsed,
                           nd=nd if engine == k_name else 1),
        **_packing_fields(cfg),
        **_stream_fields(cfg, pal),
    }
    emit_manifest(label, cfg, device=_device_str(), **seg)
    return seg


def bench_election_rounds(n_groups: int, ticks: int):
    """Config 2 shape: pure leader-election rounds — no client commands
    (`cmds_per_tick=0`, so no AppendEntries payload traffic and commits
    stay 0), with constant crash churn so elections keep completing.
    Reports completed leader acquisitions per second.

    What the number means: elections only complete when the crash
    schedule deposes a leader, so the measured rate is bounded above by
    the schedule's leader-crash rate, NOT by an intrinsic protocol
    limit — it is an existence proof that the batched path sustains
    config-2's election-only workload, normalized per wall-second.
    Expected value from the knobs here (crash_prob=0.5, crash_epoch=32):
    each epoch the leader crashes w.p. ~0.5 and a ~15-tick re-election
    follows, so roughly one election per group per ~2 epochs =
    ~1 / 64 ticks; at G groups and measured ticks/sec the schedule
    supports ~G x ticks_per_sec / 64 elections/sec, and the observed
    rate should sit near that ceiling (the bench JSON carries the raw
    election count so under-sampling is visible)."""
    cfg = _seg_cfg(seed=44, cmds_per_tick=0, crash_prob=0.5,
                   crash_epoch=32)
    (eps, elections, elapsed, timed_ticks, warmup_s, st_ref, m_ref,
     f_ref) = _timed_chunks(cfg, n_groups, ticks,
                            lambda st, m: int(m.elections),
                            label="election-rounds")
    log(f"  [xla] election rounds {n_groups} groups x {timed_ticks} ticks: "
        f"{elections} elections in {elapsed:.2f}s -> {eps:,.0f} elections/s")
    engine = "xla-scan"
    x_elapsed = elapsed
    pal = _pallas_segment(cfg, n_groups, timed_ticks, "kelections",
                          st_ref, m_ref, f_ref, "elections")
    if pal["status"] == "ok" and pal["rate"] > eps:
        eps, elections, elapsed = pal["rate"], pal["count"], pal["elapsed"]
        engine = pal["engine"]
    elif pal["status"] == "mismatch":
        engine = "xla-scan (pallas mismatch!)"
    ok = pal["status"] == "ok"
    seg = {
        "elections_per_sec": round(eps, 1), "elections": elections,
        "engine": engine,
        **_wall_fields(elapsed, xla_wall_s=x_elapsed,
                       xla_warmup_wall_s=warmup_s,
                       kernel_wall_s=pal["elapsed"] if ok else None,
                       kernel_warmup_wall_s=pal["warmup_s"]),
        **_gate_fields("election-rounds", pal, m_ref, f_ref, n_groups,
                       engine),
        **_roofline_fields(cfg, n_groups, engine, timed_ticks, elapsed,
                           nd=pal["nd"] if engine == pal["engine"] else 1),
        **_packing_fields(cfg),
        **_stream_fields(cfg, pal),
    }
    emit_manifest("election-rounds", cfg, device=_device_str(),
                  n_groups=n_groups, ticks=timed_ticks, **seg)
    return seg


def bench_reads(n_groups: int, ticks: int):
    """Scheduled linearizable reads at scale (DESIGN.md §2c): the
    config-5 replication workload with the ReadIndex pipeline on
    (read_every=4). Completed reads are counted from the `reads_done`
    trace field — with no fault schedule the counter is monotone (no
    restarts zero it), so the timed delta is exact. Same two-engine
    scheme as the headline: the Pallas fused-chunk number is promoted
    only when the full State pytree (reads_done included) and the full
    Metrics pytree are bit-identical to the XLA path at the same
    tick."""
    cfg = _seg_cfg(seed=45, read_every=4)
    (rps, reads, elapsed, timed_ticks, warmup_s, st_ref, m_ref,
     f_ref) = _timed_chunks(
        cfg, n_groups, ticks,
        lambda st, m: int(np.asarray(st.nodes.reads_done)
                          .astype(np.int64).sum()), label="reads")
    log(f"  [xla] linearizable reads {n_groups} groups x {timed_ticks} "
        f"ticks (read_every={cfg.read_every}): {reads} reads in "
        f"{elapsed:.2f}s -> {rps:,.0f} reads/s")
    engine = "xla-scan"
    x_elapsed = elapsed
    pal = _pallas_segment(cfg, n_groups, timed_ticks, "kreads",
                          st_ref, m_ref, f_ref, "reads")
    if pal["status"] == "ok" and pal["rate"] > rps:
        rps, reads, elapsed = pal["rate"], pal["count"], pal["elapsed"]
        engine = pal["engine"]
    elif pal["status"] == "mismatch":
        engine = "xla-scan (pallas mismatch!)"
    ok = pal["status"] == "ok"
    seg = {
        "reads_per_sec": round(rps, 1), "reads": reads, "engine": engine,
        **_wall_fields(elapsed, xla_wall_s=x_elapsed,
                       xla_warmup_wall_s=warmup_s,
                       kernel_wall_s=pal["elapsed"] if ok else None,
                       kernel_warmup_wall_s=pal["warmup_s"]),
        **_gate_fields("reads", pal, m_ref, f_ref, n_groups, engine),
        **_roofline_fields(cfg, n_groups, engine, timed_ticks, elapsed,
                           nd=pal["nd"] if engine == pal["engine"] else 1),
        **_packing_fields(cfg),
        **_stream_fields(cfg, pal),
    }
    emit_manifest("reads", cfg, device=_device_str(), n_groups=n_groups,
                  ticks=timed_ticks, **seg)
    return seg


def bench_clients(seed: int, n_groups: int, ticks: int, label: str):
    """Client-SLO segment on BOTH engines (DESIGN.md §10): the config-5
    fault mix with open-loop exactly-once session traffic replacing the
    scheduled fire-hose. What every other segment measures in
    protocol-internal rounds/s, this one measures as what a CLIENT
    sees: committed-exactly-once ops/s and the ack-latency
    distribution (submit -> durable-apply witness, in ticks), under
    leader crashes that force ambiguous-failure retries — every retry
    a potential duplicate log entry the dedup fold must apply once.

    Same from-tick-0 protocol as bench_fault_latency (the latency
    histogram needs every tick; throwaway-universe warmups absorb both
    engines' compiles; warmup and timed walls are SEPARATE fields).
    The kernel number is promoted only under the full-State
    `state_identical` gate — which now spans the session-table and
    client-state leaves — plus full Metrics (client lanes included)
    and the flight ring; the kernel self-skips off-TPU. The
    exactly-once verdict is asserted per segment: the per-tick safety
    fold (which latches check.client_safety every tick) AND the
    endpoint accounting report must both be clean."""
    cfg = _seg_cfg(seed=seed, sessions=True, cmds_per_tick=0,
                   client_rate=0.2, client_slots=4,
                   client_retry_backoff=8,
                   crash_prob=0.3, crash_epoch=64,
                   partition_prob=0.2, partition_epoch=64, drop_prob=0.02)
    t0 = time.perf_counter()
    with obs_trace.span(f"warmup+compile xla [{label}]"):
        wst, _, _ = run_recorded(cfg, sim.init(cfg, n_groups=n_groups),
                                 CHUNK, 0,
                                 metrics_init(n_groups, clients=True),
                                 flight_init(n_groups))
        jax.block_until_ready(wst)
    x_warmup_s = time.perf_counter() - t0
    log(f"  [xla] warmup chunk (incl. compile): {x_warmup_s:.1f}s")
    st = sim.init(cfg, n_groups=n_groups)
    m = metrics_init(n_groups, clients=True)
    f = flight_init(n_groups)
    start = time.perf_counter()
    with obs_trace.span(f"timed xla [{label}]"):
        for tick_at in range(0, ticks, CHUNK):
            n = min(CHUNK, ticks - tick_at)
            with obs_trace.chunk_span("xla", tick_at, n, phase="timed"):
                st, m, f = run_recorded(cfg, st, n, tick_at, m, f)
            obs_trace.heartbeat(label, tick_at + n, m, f)
        acked = total_client_ops(m)         # fetch closes the timer
    x_elapsed = time.perf_counter() - start
    retries = total_client_retries(m)
    log(f"  [xla] {label} {n_groups} groups x {ticks} ticks in "
        f"{x_elapsed:.2f}s ({x_elapsed / ticks * 1e3:.2f} ms/tick): "
        f"{acked} client ops acked, {retries} retries")

    pal = _pallas_full_run(cfg, n_groups, ticks, "kacked", label,
                           st, m, f)
    engine, k_elapsed, k_warmup_s = (pal["engine"], pal["k_elapsed"],
                                     pal["k_warmup_s"])
    state_ok, metrics_ok, flight_ok = (pal["state_ok"], pal["metrics_ok"],
                                       pal["flight_ok"])
    nd, k_name = pal["nd"], pal["k_name"]
    elapsed = k_elapsed if pal["promoted"] else x_elapsed

    unsafe = _safety_check(label, m, f, n_groups)
    eo_ok, eo_why = exactly_once_report(cfg, st, m)
    exactly_once = eo_ok and unsafe == 0
    log(f"  [{label}] exactly-once verdict: "
        f"{'PROVEN clean' if exactly_once else 'VIOLATED'} — {eo_why}; "
        f"{retries} duplicate-risk retries under the fault mix")
    p50 = latency_quantile(m.client_hist, 0.5)
    p99 = latency_quantile(m.client_hist, 0.99)
    censored = latency_censored(m.client_hist, 0.99)
    log(f"  {label}: {acked} acked ops ({acked / elapsed:,.0f} ops/s), "
        f"ack latency p50={p50} p99={p99} "
        f"max={int(m.client_max_lat)} ticks"
        f"{' [p99 CENSORED at histogram top bucket]' if censored else ''}"
        f"; engine={engine}")
    seg = {
        "client_ops_per_sec": round(acked / elapsed, 1),
        "acked_ops": acked, "retries": retries,
        "ack_p50_ticks": p50, "ack_p99_ticks": p99,
        "ack_p99_censored": censored,
        "ack_max_ticks": int(m.client_max_lat),
        "exactly_once_ok": exactly_once,
        "engine": engine,
        "state_identical": state_ok, "metrics_identical": metrics_ok,
        "flight_identical": flight_ok,
        "n_groups": n_groups, "ticks": ticks,
        **_wall_fields(elapsed, xla_wall_s=x_elapsed,
                       xla_warmup_wall_s=x_warmup_s,
                       kernel_wall_s=k_elapsed,
                       kernel_warmup_wall_s=k_warmup_s),
        "safety_ok": unsafe == 0, "unsafe_groups": unsafe,
        # Workload provenance (ISSUE r09): every client segment's
        # manifest records the open-loop parameters it measured.
        "workload": workload_params(cfg),
        **_mesh_fields(n_groups, nd if engine == k_name else 1),
        **_roofline_fields(cfg, n_groups, engine, ticks, elapsed,
                           nd=nd if engine == k_name else 1),
        **_packing_fields(cfg),
        **_stream_fields(cfg, pal),
    }
    emit_manifest(label, cfg, device=_device_str(), **seg)
    return seg


# Knee protocol (DESIGN.md §19): each load point is graded against a
# two-part SLO. (1) p99 ack latency, in ticks — six retry-backoff
# windows, so an op that rode out a full disk-full sub-epoch plus a
# handful of ambiguous-failure retries still acks inside it. (2) a
# shed-rate budget: under a BOUNDED admission queue the ack histogram
# deliberately excludes backlog queueing delay, so p99 stays flat
# while overload shows up as definitive rejects — the shed budget is
# what makes the knee interior to the swept range instead of pinned
# at the top rung.
PRESSURE_ACK_SLO_TICKS = 48
PRESSURE_SHED_SLO = 0.05

# Offered-load ladder (client arrivals per slot per tick) and the
# admission cap the whole sweep runs with. The ladder spans below and
# above the hash-gated service rate under pressure, so at least one
# point meets the SLO and at least one saturates.
PRESSURE_RATES = (0.05, 0.1, 0.2, 0.35, 0.5)
PRESSURE_QUEUE_CAP = 8


def _pressure_fields(cfg, knee: dict | None) -> dict:
    """The r20 manifest stamp (obs.manifest.PRESSURE_KEYS): the knee
    the sweep found — max sustained ops/s meeting the p99 ack SLO, the
    shed rate the admission queue ran at there, and the hash of the
    pressure program the whole sweep shared. Nulls = no load point met
    the SLO (the degradation story is then the per-point table). Same
    drift guard as _nemesis_fields: the producer is checked against
    the registry it fills."""
    from raft_tpu import nemesis
    vals = {"knee_ops_per_sec": (round(knee["ops_per_sec"], 1)
                                 if knee else None),
            "shed_rate_at_knee": (knee["shed_rate"] if knee else None),
            "pressure_program_hash": nemesis.program_hash(cfg.nemesis)}
    if set(vals) != set(PRESSURE_KEYS):
        raise RuntimeError(f"obs.manifest.PRESSURE_KEYS {PRESSURE_KEYS} "
                           f"drifted from the bench producer {set(vals)}")
    return vals


def bench_pressure(seed: int, n_groups: int, ticks: int, label: str):
    """Graceful-degradation knee segment (DESIGN.md §19): sweep offered
    client load up a fixed ladder under the canonical storage-pressure
    program (`nemesis.pressure_mix` — disk-full follower + compaction
    pressure) with the bounded admission queue ON, and report the KNEE:
    the max sustained committed-exactly-once ops/s whose p99 ack
    latency still meets the SLO, plus the shed rate the admission gate
    sustained there. Above the knee the story the table tells is
    degradation, not collapse — definitive sheds rise and p99 grows,
    but safety and exactly-once accounting (shed ledger included) stay
    clean at EVERY point, which this segment asserts.

    The sweep runs on the XLA engine (each load point is its own
    compiled universe — client_rate is static); the kernel engine then
    re-runs the KNEE point under the unchanged full State + Metrics +
    flight-ring bit-identity gate, so the published knee rate is
    promoted exactly like every other segment's number."""
    from raft_tpu import nemesis
    base = _seg_cfg(seed=seed, sessions=True, cmds_per_tick=0,
                    client_rate=PRESSURE_RATES[0], client_slots=4,
                    client_retry_backoff=8,
                    client_queue_cap=PRESSURE_QUEUE_CAP,
                    nemesis=nemesis.pressure_mix(ticks))
    log(f"  [{label}] program {nemesis.program_hash(base.nemesis)}: "
        f"{nemesis.describe(base.nemesis)}; SLO p99 <= "
        f"{PRESSURE_ACK_SLO_TICKS} ticks AND shed <= "
        f"{PRESSURE_SHED_SLO:.0%}, queue cap {PRESSURE_QUEUE_CAP}")
    points, knee, knee_run, x_warmup_s = [], None, None, None
    x_total_s = 0.0
    for rate in PRESSURE_RATES:
        cfg = dataclasses.replace(base, client_rate=rate)
        t0 = time.perf_counter()
        with obs_trace.span(f"warmup+compile xla [{label} rate={rate}]"):
            wst, _, _ = run_recorded(cfg, sim.init(cfg, n_groups=n_groups),
                                     CHUNK, 0,
                                     metrics_init(n_groups, clients=True),
                                     flight_init(n_groups))
            jax.block_until_ready(wst)
        warm = time.perf_counter() - t0
        if x_warmup_s is None:
            x_warmup_s = warm
        st = sim.init(cfg, n_groups=n_groups)
        m = metrics_init(n_groups, clients=True)
        f = flight_init(n_groups)
        start = time.perf_counter()
        with obs_trace.span(f"timed xla [{label} rate={rate}]"):
            for tick_at in range(0, ticks, CHUNK):
                n = min(CHUNK, ticks - tick_at)
                with obs_trace.chunk_span("xla", tick_at, n, phase="timed"):
                    st, m, f = run_recorded(cfg, st, n, tick_at, m, f)
            acked = total_client_ops(m)     # fetch closes the timer
        elapsed = time.perf_counter() - start
        x_total_s += elapsed
        cl = st.clients
        shed = int(np.asarray(cl.shed).astype(np.int64).sum())
        # Every offered arrival is, at the endpoint, exactly one of:
        # completed (done), still queued (backlog), in flight, or
        # definitively shed at the admission gate.
        admitted = sum(int(np.asarray(x).astype(np.int64).sum())
                       for x in (cl.done, cl.backlog, cl.inflight))
        p99 = latency_quantile(m.client_hist, 0.99)
        censored = latency_censored(m.client_hist, 0.99)
        unsafe = _safety_check(f"{label} rate={rate}", m, f, n_groups)
        eo_ok, eo_why = exactly_once_report(cfg, st, m)
        if not eo_ok:
            log(f"  [{label} rate={rate}] EXACTLY-ONCE VIOLATED: {eo_why}")
        pt = {"offered_rate": rate,
              "ops_per_sec": round(acked / elapsed, 1),
              "acked_ops": acked,
              "ack_p99_ticks": p99, "ack_p99_censored": censored,
              "shed": shed,
              "shed_rate": round(shed / max(1, shed + admitted), 4),
              "slo_ok": (p99 <= PRESSURE_ACK_SLO_TICKS and not censored
                         and shed / max(1, shed + admitted)
                         <= PRESSURE_SHED_SLO),
              "unsafe_groups": unsafe,
              "safety_ok": unsafe == 0 and eo_ok}
        points.append(pt)
        log(f"  [{label}] rate={rate}: {acked} acked "
            f"({pt['ops_per_sec']:,.0f} ops/s), p99={p99}"
            f"{' [CENSORED]' if censored else ''}, shed={shed} "
            f"({pt['shed_rate']:.2%}), "
            f"{'MEETS' if pt['slo_ok'] else 'misses'} SLO")
        if pt["slo_ok"] and pt["safety_ok"] and (
                knee is None or pt["ops_per_sec"] > knee["ops_per_sec"]):
            knee, knee_run = pt, (cfg, st, m, f)
    if knee is None:
        log(f"  [{label}] NO load point met the SLO — knee unresolved "
            f"(manifest keys stay null); the ladder needs a lower rung")
        engine, k_elapsed, k_warmup_s = "xla-scan", None, None
        state_ok = metrics_ok = flight_ok = None
        nd, k_name = 1, "pallas"
    else:
        log(f"  [{label}] knee: rate={knee['offered_rate']} -> "
            f"{knee['ops_per_sec']:,.0f} ops/s at p99="
            f"{knee['ack_p99_ticks']} ticks, shed rate "
            f"{knee['shed_rate']:.2%}")
        kcfg, st, m, f = knee_run
        pal = _pallas_full_run(kcfg, n_groups, ticks, "kacked", label,
                               st, m, f)
        engine, k_elapsed, k_warmup_s = (pal["engine"], pal["k_elapsed"],
                                         pal["k_warmup_s"])
        state_ok, metrics_ok, flight_ok = (pal["state_ok"],
                                           pal["metrics_ok"],
                                           pal["flight_ok"])
        nd, k_name = pal["nd"], pal["k_name"]
        if pal["promoted"]:
            knee["ops_per_sec"] = round(knee["acked_ops"] / k_elapsed, 1)
    cfg = knee_run[0] if knee_run else base
    seg = {
        **_pressure_fields(cfg, knee),
        "ack_slo_p99_ticks": PRESSURE_ACK_SLO_TICKS,
        "shed_slo": PRESSURE_SHED_SLO,
        "queue_cap": PRESSURE_QUEUE_CAP,
        "knee_offered_rate": knee["offered_rate"] if knee else None,
        "knee_ack_p99_ticks": knee["ack_p99_ticks"] if knee else None,
        "load_points": points,
        "exactly_once_ok": all(p["safety_ok"] for p in points),
        "engine": engine,
        "state_identical": state_ok, "metrics_identical": metrics_ok,
        "flight_identical": flight_ok,
        "n_groups": n_groups, "ticks": ticks,
        **_nemesis_fields(cfg),
        **_wall_fields(k_elapsed if knee and pal["promoted"] else x_total_s,
                       xla_wall_s=x_total_s,
                       xla_warmup_wall_s=x_warmup_s,
                       kernel_wall_s=k_elapsed,
                       kernel_warmup_wall_s=k_warmup_s),
        "safety_ok": all(p["safety_ok"] for p in points),
        "unsafe_groups": max(p["unsafe_groups"] for p in points),
        "workload": workload_params(cfg),
        **_mesh_fields(n_groups, nd if engine == k_name else 1),
        **_roofline_fields(cfg, n_groups, engine, ticks,
                           k_elapsed if knee and pal["promoted"]
                           else x_total_s,
                           nd=nd if engine == k_name else 1),
        **_packing_fields(cfg),
        **_stream_fields(cfg, pal if knee else None),
    }
    emit_manifest(label, cfg, device=_device_str(), **seg)
    return seg


def main():
    global _TRACE_PATH
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small shapes for a smoke run")
    ap.add_argument("--groups", type=int, default=None,
                    help="override the throughput-run group count")
    ap.add_argument("--trace-dir", default=None,
                    help="write a Chrome trace-event timeline "
                         "(trace_bench.json, Perfetto-loadable) and the "
                         "soak heartbeat JSONL into this directory")
    ap.add_argument("--jax-profile", action="store_true",
                    help="additionally capture a jax.profiler trace per "
                         "segment under --trace-dir/jaxprof (large; "
                         "opt-in)")
    ap.add_argument("--heartbeat-every", type=int, default=10,
                    help="chunks between soak-heartbeat snapshots "
                         "(with --trace-dir; default 10)")
    ap.add_argument("--pack-wire", action="store_true",
                    help="run every segment with the r13 packed kernel "
                         "wire (pack_bools + pack_ring + alias_wire; "
                         "DESIGN.md §13). Promotion gates are unchanged "
                         "— the packed kernel must still match the XLA "
                         "reference bit-for-bit — so this is the "
                         "measured-delta run for the layout ablation")
    ap.add_argument("--stream", action="store_true",
                    help="run every kernel segment through the r16 "
                         "cohort scheduler (stream_groups; DESIGN.md "
                         "§15): the fleet's wire lives in host RAM and "
                         "is paged block-cohorts at a time through HBM "
                         "under the unchanged kernel. Promotion gates "
                         "are unchanged; every segment additionally "
                         "stamps predicted + measured overlap "
                         "efficiency (obs.manifest.STREAM_KEYS)")
    ap.add_argument("--cohort-blocks", type=int, default=None,
                    help="with --stream: 1024-group blocks per cohort "
                         "window (default: config default, 4)")
    args = ap.parse_args()

    if args.pack_wire:
        # wire_hist stays ON: the fault/client segments' p50/p99 and the
        # full-Metrics promotion differential both need the in-kernel
        # histogram rows; the hist dial is a ceiling-run lever
        # (layout_probe --ablate / multichip_sweep --no-hist), not a
        # bench default.
        _WIRE_DIALS.update(pack_bools=True, pack_ring=True,
                           alias_wire=True)
        log("packed wire: pack_bools + pack_ring + alias_wire on for "
            "every segment (wire_hist stays on for the histograms)")

    if args.stream:
        _WIRE_DIALS.update(stream_groups=True)
        if args.cohort_blocks is not None:
            _WIRE_DIALS.update(cohort_blocks=args.cohort_blocks)
        log(f"cohort streaming: stream_groups on for every kernel "
            f"segment (cohort_blocks="
            f"{args.cohort_blocks if args.cohort_blocks is not None else 4}"
            f"; the XLA reference engine stays resident)")
    elif args.cohort_blocks is not None:
        ap.error("--cohort-blocks requires --stream")

    tracer = None
    if args.trace_dir:
        os.makedirs(args.trace_dir, exist_ok=True)
        tracer = obs_trace.Tracer()
        obs_trace.set_tracer(tracer)
        _TRACE_PATH = os.path.join(args.trace_dir, "trace_bench.json")
        obs_trace.set_heartbeat(obs_trace.Heartbeat(
            os.path.join(args.trace_dir, "heartbeat.jsonl"),
            every=args.heartbeat_every))
        log(f"tracing to {_TRACE_PATH} (heartbeat every "
            f"{args.heartbeat_every} chunks; NOTE: heartbeat snapshots "
            f"sync the device mid-segment — walls include that cost)")

    def segment(label, fn, *fargs):
        """One bench segment under its span (+ optional jax.profiler
        capture — device-side detail next to the host spans)."""
        import contextlib
        prof = contextlib.nullcontext()
        if args.jax_profile and args.trace_dir:
            prof = jax.profiler.trace(
                os.path.join(args.trace_dir, "jaxprof",
                             label.replace(" ", "_")))
        with obs_trace.span(label, cat=obs_trace.CAT_SEGMENT), prof:
            return fn(*fargs)

    # Pre-flight engine-contract audit (DESIGN.md §11): eval_shape
    # traces + AST parses only — no device programs. A drifted wire
    # registry / byte model / checkpoint contract aborts the run here,
    # so no benchmark number is ever published off a drifted layout.
    from raft_tpu import analysis
    analysis.startup_audit(level="static", log=log)

    dev = jax.devices()[0]
    log(f"platform: {dev.platform} ({dev.device_kind}), "
        f"{len(jax.devices())} device(s)")
    if args.quick:
        groups, ticks = 1_000, 200
        e_groups, e_ticks = 1_000, 200
        f_groups, f_ticks = 1_000, 200
        r_groups, r_ticks = 1_000, 200
        rd_groups, rd_ticks = 1_000, 200
        cl_groups, cl_ticks = 1_000, 200
        nm_groups, nm_ticks = 1_000, 200
        pr_groups, pr_ticks = 1_000, 200
    else:
        # The headline runs at the true config-5 shape: 100K groups.
        # (History: a TPU kernel fault at 100K groups blocked this shape
        # in round 2; it stopped reproducing in round 3 with no hot-path
        # change and has not been seen since — if a 100K run ever dies
        # in the runtime again, that regression has a precedent.)
        groups, ticks = args.groups or 100_000, 600
        e_groups, e_ticks = 50_000, 600      # config-4 shape
        f_groups, f_ticks = args.groups or 100_000, 600  # config-5 + faults
        # Config-2: 2400 ticks so the timed region is seconds, not
        # sub-second (the rate is schedule-bound; see the fn docstring).
        r_groups, r_ticks = 10_000, 2400
        rd_groups, rd_ticks = 50_000, 600   # ReadIndex-at-scale segment
        cl_groups, cl_ticks = 50_000, 600   # client-SLO-at-scale segment
        nm_groups, nm_ticks = 50_000, 600   # gray-failure segment (§14)
        # Pressure-knee sweep (§19): each of the PRESSURE_RATES rungs
        # is a full from-tick-0 run, so the per-rung shape is smaller
        # than the single-run segments to keep the sweep's total wall
        # in the same band.
        pr_groups, pr_ticks = 20_000, 600

    # The trace must survive a mid-run crash: a bench that dies in
    # segment 5 of 6 is exactly the run whose timeline is needed, so
    # the save rides a finally, not the happy path.
    try:
        log(f"throughput (config-5 shape, {groups} x 5-node groups):")
        tp = segment("throughput", bench_throughput, groups, ticks)
        log("election latency (config-4 shape, both engines):")
        c4 = segment("config-4 fault run", bench_fault_latency, 43,
                     e_groups, e_ticks, "config-4 fault run")
        log("fault-mix throughput + latency (config-5 shape, both "
            "engines):")
        c5f = segment("config-5 fault mix", bench_fault_latency, 46,
                      f_groups, f_ticks, "config-5 fault mix")
        log("election rounds (config-2 shape):")
        c2 = segment("election-rounds", bench_election_rounds, r_groups,
                     r_ticks)
        log("linearizable reads (config-5 shape + ReadIndex schedule):")
        rd = segment("reads", bench_reads, rd_groups, rd_ticks)
        log("client traffic SLO (config-5 fault mix + open-loop "
            "exactly-once sessions, both engines):")
        cl = segment("client-slo fault mix", bench_clients, 47, cl_groups,
                     cl_ticks, "client-slo fault mix")
        log("gray-failure mix (nemesis program on light churn, both "
            "engines):")
        nm = segment("nemesis gray mix", bench_nemesis, 48, nm_groups,
                     nm_ticks, "nemesis gray mix")
        log("storage-pressure knee (offered-load sweep under disk-full "
            "+ compaction pressure, bounded admission):")
        pr = segment("pressure knee", bench_pressure, 49, pr_groups,
                     pr_ticks, "pressure knee")

        # Roofline contract (DESIGN.md §12, ISSUE r12 acceptance): every
        # segment must carry the three stamp fields — a segment emitted
        # without them would publish a number that cannot explain itself.
        for name, seg in (("throughput", tp), ("config-4", c4),
                          ("config-5-faults", c5f),
                          ("election-rounds", c2), ("reads", rd),
                          ("client-slo", cl), ("nemesis", nm),
                          ("pressure", pr)):
            missing = [k for k in obs_roofline.ROOFLINE_FIELDS
                       if k not in seg]
            missing += [k for k in SEGMENT_WALL_KEYS if k not in seg]
            missing += [k for k in PACKING_KEYS if k not in seg]
            if missing:
                raise RuntimeError(
                    f"bench segment {name!r} lost contract field(s) "
                    f"{missing} — roofline/wall/packing stamping drifted")
    finally:
        if tracer is not None:
            obs_trace.set_heartbeat(None)
            obs_trace.set_tracer(None)
            log(f"trace: {len(tracer.events)} events -> "
                f"{tracer.save(_TRACE_PATH)}")

    # The client segment's per-segment exactly-once verdict (per-tick
    # fold AND endpoint accounting) folds into the global safety bit:
    # a double-apply must trip the same top-level flag automation
    # watches, not only a buried per-segment field.
    safety_ok = all(s["safety_ok"]
                    for s in (tp, c4, c5f, c2, rd, cl, nm, pr)) \
        and cl["exactly_once_ok"] and pr["exactly_once_ok"]
    if not safety_ok:
        log("SAFETY: at least one segment dropped the per-tick safety "
            "bit — see the flight-recorder dumps above")
    print(json.dumps({
        "metric": "consensus_rounds_per_sec_per_chip",
        "value": tp["rounds_per_sec"],
        "unit": "rounds/s",
        "vs_baseline": round(tp["rounds_per_sec"]
                             / BASELINE_ROUNDS_PER_SEC, 3),
        "n_groups": groups,
        "ticks": tp["ticks"],
        "wall_s": tp["timed_wall_s"],
        "warmup_wall_s": tp["xla_warmup_wall_s"],
        "engine": tp["engine"],
        "pallas_rounds_per_sec": tp["pallas_rounds_per_sec"],
        "pallas_ms_per_tick": tp["pallas_ms_per_tick"],
        "pallas_warmup_wall_s": tp["kernel_warmup_wall_s"],
        "throughput_state_identical": tp["state_identical"],
        "throughput_safety_ok": tp["safety_ok"],
        # Roofline stamp (DESIGN.md §12): the headline's predicted
        # HBM/FLOP-bound ceiling, how much of it the promoted engine
        # attained, and which resource binds. Null attainment = no TPU
        # wall to measure against (prediction still stands).
        "predicted_rounds_per_sec": tp["predicted_rounds_per_sec"],
        "attainment_pct": tp["attainment_pct"],
        "bound": tp["bound"],
        # Per-tick safety fold (DESIGN.md §8): every segment is a
        # (groups x ticks x k)-node-tick soak; True = no group violated
        # election safety / digest agreement / window bounds at ANY tick.
        "safety_ok": safety_ok,
        "p50_election_latency_ticks": c4["p50"],
        "p99_election_latency_ticks": c4["p99"],
        "p99_censored": c4["censored"],
        "max_election_latency_ticks": c4["max_lat"],
        "p99_note": c4["p99_note"],
        "elections_observed": c4["elections"],
        "config4_engine": c4["engine"],
        "config4_state_identical": c4["state_identical"],
        "config4_safety_ok": c4["safety_ok"],
        "config4_xla_wall_s": c4["xla_wall_s"],
        "config4_xla_warmup_wall_s": c4["xla_warmup_wall_s"],
        "config4_kernel_wall_s": c4["kernel_wall_s"],
        "config4_kernel_warmup_wall_s": c4["kernel_warmup_wall_s"],
        "faulted_rounds_per_sec": round(c5f["rounds_per_sec"], 1),
        "faulted_p50_election_latency_ticks": c5f["p50"],
        "faulted_p99_election_latency_ticks": c5f["p99"],
        "faulted_p99_censored": c5f["censored"],
        "faulted_elections_observed": c5f["elections"],
        "config5_fault_n_groups": c5f["n_groups"],
        "config5_fault_engine": c5f["engine"],
        "config5_fault_state_identical": c5f["state_identical"],
        "config5_fault_safety_ok": c5f["safety_ok"],
        "config5_fault_xla_wall_s": c5f["xla_wall_s"],
        "config5_fault_xla_warmup_wall_s": c5f["xla_warmup_wall_s"],
        "config5_fault_kernel_wall_s": c5f["kernel_wall_s"],
        "config5_fault_kernel_warmup_wall_s": c5f["kernel_warmup_wall_s"],
        "elections_per_sec": c2["elections_per_sec"],
        "config2_elections_observed": c2["elections"],
        "config2_engine": c2["engine"],
        "config2_state_identical": c2["state_identical"],
        "config2_safety_ok": c2["safety_ok"],
        "config2_note": "schedule-bound rate; see bench_election_rounds",
        "linearizable_reads_per_sec": rd["reads_per_sec"],
        "reads_observed": rd["reads"],
        "reads_engine": rd["engine"],
        "reads_state_identical": rd["state_identical"],
        "reads_safety_ok": rd["safety_ok"],
        # Client-visible SLO (DESIGN.md §10): committed-exactly-once
        # ops/s + ack latency under the config-5 fault mix, next to the
        # protocol-internal rounds/s above.
        "client_ops_per_sec": cl["client_ops_per_sec"],
        "client_ops_acked": cl["acked_ops"],
        "client_retries": cl["retries"],
        "client_ack_p50_ticks": cl["ack_p50_ticks"],
        "client_ack_p99_ticks": cl["ack_p99_ticks"],
        "client_ack_p99_censored": cl["ack_p99_censored"],
        "client_exactly_once_ok": cl["exactly_once_ok"],
        "client_engine": cl["engine"],
        "client_state_identical": cl["state_identical"],
        "client_safety_ok": cl["safety_ok"],
        "client_workload": cl["workload"],
        # Gray-failure SLO (DESIGN.md §14): the published number for
        # behavior under fail-SLOW faults — every group carries a
        # degraded-but-alive node and a silently lossy link the whole
        # run (nemesis.gray_mix), next to the fail-stop configs above.
        "nemesis_rounds_per_sec": round(nm["rounds_per_sec"], 1),
        "nemesis_p50_election_latency_ticks": nm["p50"],
        "nemesis_p99_election_latency_ticks": nm["p99"],
        "nemesis_p99_censored": nm["censored"],
        "nemesis_elections_observed": nm["elections"],
        "nemesis_program_hash": nm["nemesis_program_hash"],
        "nemesis_engine": nm["engine"],
        "nemesis_state_identical": nm["state_identical"],
        "nemesis_safety_ok": nm["safety_ok"],
        # Graceful-degradation knee (DESIGN.md §19): the max sustained
        # exactly-once ops/s meeting the p99 ack SLO under the canonical
        # storage-pressure program, with the shed rate the bounded
        # admission queue ran at there. Nulls = no swept load point met
        # the SLO (see the segment's load_points table).
        "knee_ops_per_sec": pr["knee_ops_per_sec"],
        "shed_rate_at_knee": pr["shed_rate_at_knee"],
        "pressure_program_hash": pr["pressure_program_hash"],
        "pressure_ack_slo_p99_ticks": pr["ack_slo_p99_ticks"],
        "pressure_shed_slo": pr["shed_slo"],
        "pressure_knee_ack_p99_ticks": pr["knee_ack_p99_ticks"],
        "pressure_queue_cap": pr["queue_cap"],
        "pressure_load_points": pr["load_points"],
        "pressure_exactly_once_ok": pr["exactly_once_ok"],
        "pressure_engine": pr["engine"],
        "pressure_state_identical": pr["state_identical"],
        "pressure_safety_ok": pr["safety_ok"],
        "device": f"{dev.platform}:{dev.device_kind}",
    }))


if __name__ == "__main__":
    main()
