"""Benchmark harness: consensus rounds/sec/chip (BASELINE.md target: 1M/s).

Runs the batched sim on the default JAX platform (the real TPU chip under
the driver; CPU elsewhere) and prints ONE machine-parsable JSON line:

    {"metric": "consensus_rounds_per_sec_per_chip", "value": ...,
     "unit": "rounds/s", "vs_baseline": value / 1e6, ...extras}

Headline workload is the config-5 shape — 100K 5-node groups, steady-state
replication — timed after a warmup run that absorbs compilation and the
initial elections (compile time excluded per VERDICT round-1 item 3).
Election latency (p50/p99, in ticks) comes from fault-injected runs on
BOTH engines — the config-4 shape (leader crashes + partitions + drops
at 50K groups) and the same fault mix at the 100K config-5 shape
("Jepsen-style at 100K", VERDICT r05 weak #4) — promoted to the Pallas
kernel only when full State AND full Metrics (histogram included, so
p50/p99 are bit-identical by construction) match the XLA path at the
same tick; every promoted kernel segment carries `state_identical` in
the JSON. The config-2 shape — pure leader-election rounds, no client
commands — reports elections/sec at 10K groups under constant crash
churn. Per-phase detail goes to stderr.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import numpy as np

from raft_tpu import sim
from raft_tpu.config import RaftConfig
from raft_tpu.sim.run import (latency_censored, latency_quantile,
                              metrics_init, total_rounds)
# The byte-identical comparator the test suite and kernel sweep gate
# on, applied at the shapes that produce the headline numbers
# (VERDICT r05 Missing #1).
from raft_tpu.utils.trees import trees_equal as _trees_equal

BASELINE_ROUNDS_PER_SEC = 1_000_000.0


def log(msg: str):
    print(msg, file=sys.stderr, flush=True)


CHUNK = 200   # ticks per device call: one compiled program, reused


def _timed_chunks(cfg, n_groups: int, ticks: int, counter_fn,
                  warmup_chunks: int = 1):
    """Shared warmup + chunked-timing harness for every counter-delta
    bench segment. Runs in fixed-size chunks so every timed device call
    reuses the one compiled (cfg, CHUNK, pytree-shape) program — the
    warmup chunk absorbs compilation AND the initial elections, so the
    timed region measures steady state only. (Chunking also keeps
    single device programs short, which the TPU tunnel tolerates far
    better than one scan over 10^3+ ticks.)

    `counter_fn(st, m) -> int` must read a monotone event counter;
    returns (rate/s, delta, elapsed_s, timed_ticks, st, m) — the final
    state/metrics let a caller extend the same universe without
    re-simulating it from tick 0."""
    st = sim.init(cfg, n_groups=n_groups)
    m = metrics_init(n_groups)
    t0 = time.perf_counter()
    tick_at = 0
    for _ in range(warmup_chunks):
        st, m = sim.run(cfg, st, CHUNK, tick_at, m)
        tick_at += CHUNK
    jax.block_until_ready(st)
    log(f"  warmup {tick_at} ticks (incl. compile): "
        f"{time.perf_counter() - t0:.1f}s")
    base = counter_fn(st, m)
    n_chunks = max(1, ticks // CHUNK)
    start = time.perf_counter()
    for _ in range(n_chunks):
        st, m = sim.run(cfg, st, CHUNK, tick_at, m)
        tick_at += CHUNK
    jax.block_until_ready(st)
    elapsed = time.perf_counter() - start
    delta = counter_fn(st, m) - base
    return delta / elapsed, delta, elapsed, n_chunks * CHUNK, st, m


def _pallas_segment(cfg, n_groups: int, timed_ticks: int, counter_name,
                    st_ref, m_ref, what: str):
    """Shared Pallas fused-chunk warmup/timing/differential harness
    (the kernel-side analogue of `_timed_chunks`; every steady-state
    kernel segment runs through here so the subtleties stay in one
    place — `bench_fault_latency` carries the same warmup/timing/
    promotion protocol in its from-tick-0 form, where the histogram
    needs every tick and no reference can be extended).
    Returns (rate, count, elapsed, status, state_identical) with status
    one of "ok" | "mismatch" | "unsupported" | an error string, and
    state_identical the FULL-State pytree comparison against the XLA
    reference at the same tick (None when the kernel never produced a
    state). Promotion requires the full State pytree AND the full
    Metrics pytree (committed / leaderless / elections / histogram /
    max_latency) bit-identical — a counter-blind corruption of terms,
    logs, or mailbox state demotes the kernel exactly like a counter
    drift would (VERDICT r05 Missing #1); the per-segment counter is
    now only the timed quantity, not the differential.

    Subtleties encoded here, each learned from a wrong measurement:
    - TWO warmup launches: the first compiles for kinit's buffer
      layouts, the second for the kernel's own output layouts (a
      distinct executable — timing it once cost 13.5s of "steady
      state"); the counter fetch after each forces completion.
    - The timed region is closed by the counter fetch itself: the TPU
      tunnel's block_until_ready is not a reliable barrier.
    - The differential extends the XLA reference (already at tick
      CHUNK + timed_ticks from `_timed_chunks`) by ONE more chunk to
      the kernel's 2*CHUNK + timed_ticks endpoint, then the two
      universes must be bit-identical.
    """
    try:   # kernel failure of ANY kind (incl. import) never kills the bench
        from raft_tpu.sim import pkernel
        if not (pkernel.supported(cfg)
                and jax.devices()[0].platform == "tpu"):
            return None, None, None, "unsupported", None
        counter_fn = getattr(pkernel, counter_name)
        leaves, g = pkernel.kinit(cfg, sim.init(cfg, n_groups=n_groups))
        t0 = time.perf_counter()
        leaves = pkernel.kstep(cfg, leaves, 0, CHUNK)
        counter_fn(leaves, g)                            # forces compile #1
        leaves = pkernel.kstep(cfg, leaves, CHUNK, CHUNK)
        base = counter_fn(leaves, g)                     # forces compile #2
        log(f"  [pallas] warmup {2 * CHUNK} ticks (incl. 2 compiles): "
            f"{time.perf_counter() - t0:.1f}s")
        n_chunks = timed_ticks // CHUNK
        start = time.perf_counter()
        for c in range(n_chunks):
            leaves = pkernel.kstep(cfg, leaves, (c + 2) * CHUNK, CHUNK)
        count = counter_fn(leaves, g) - base    # fetch closes the timer
        elapsed = time.perf_counter() - start
        rate = count / elapsed
        log(f"  [pallas] {n_groups} groups x {timed_ticks} ticks: "
            f"{count} {what} in {elapsed:.2f}s -> {rate:,.0f} {what}/s "
            f"({elapsed / timed_ticks * 1e3:.2f} ms/tick)")
        st_ref, m_ref = sim.run(cfg, st_ref, CHUNK,
                                CHUNK + timed_ticks, m_ref)
        st_pal, m_pal = pkernel.kfinish(cfg, leaves, g)
        state_ok = _trees_equal(st_ref, st_pal)
        metrics_ok = _trees_equal(m_ref, m_pal)
        if state_ok and metrics_ok:
            log("  [pallas] differential vs xla at same tick: full State "
                "+ full Metrics bit-identical")
            return rate, count, elapsed, "ok", True
        log(f"  [pallas] DIFFERENTIAL MISMATCH (state_identical={state_ok} "
            f"metrics_identical={metrics_ok}) - kernel number discarded")
        return None, None, None, "mismatch", state_ok
    except Exception as e:   # kernel failure must never kill the bench
        log(f"  [pallas] failed ({type(e).__name__}: {e}); xla stands")
        return None, None, None, f"error: {type(e).__name__}", None


def bench_throughput(n_groups: int, ticks: int):
    """Config 2/3/5 shape: steady-state replication throughput.

    Runs BOTH engines at the same tick count — the XLA scan path
    (sim.run) and the Pallas fused-chunk kernel (sim.pkernel), which
    keeps a block's whole state VMEM-resident across a 200-tick chunk
    instead of streaming ~18 GB/tick of [G,K,L] intermediates through
    HBM (DESIGN.md §7). The kernel's number is promoted to the headline
    ONLY if its full State AND full Metrics pytrees are bit-identical
    to the XLA run at the same tick — a full-shape in-run differential
    on top of the CPU-interpret gate in tests/test_pkernel.py. On any
    mismatch or kernel failure the XLA number stands and the JSON says
    so (`state_identical` per segment)."""
    cfg = RaftConfig(seed=42)
    rps, rounds, elapsed, timed_ticks, st_ref, m_ref = _timed_chunks(
        cfg, n_groups, ticks, lambda st, m: total_rounds(m))
    log(f"  [xla] {n_groups} groups x {timed_ticks} ticks: {rounds} rounds "
        f"in {elapsed:.2f}s -> {rps:,.0f} rounds/s "
        f"({timed_ticks / elapsed:,.0f} ticks/s)")
    engine = "xla-scan"
    p_rate, p_count, p_elapsed, status, state_ok = _pallas_segment(
        cfg, n_groups, timed_ticks, "kcommitted", st_ref, m_ref, "rounds")
    if status == "ok" and p_rate > rps:
        rps, rounds, elapsed = p_rate, p_count, p_elapsed
        engine = "pallas-fused-chunk"
    elif status == "mismatch":
        engine = "xla-scan (pallas mismatch!)"
    pallas_rps = p_rate if status == "ok" else None
    pallas_ms = (p_elapsed / timed_ticks * 1e3) if status == "ok" else None
    return rps, rounds, elapsed, timed_ticks, engine, pallas_rps, \
        pallas_ms, state_ok


def bench_fault_latency(seed: int, n_groups: int, ticks: int, label: str):
    """Fault-mix segment on BOTH engines (config-4 shape at 50K; the
    same fault knobs at the 100K config-5 shape): randomized leader
    crashes + partitions + drops; measures the election-latency
    distribution (ticks from leaderless to a new leader) AND the
    committed-round throughput under faults.

    The kernel can carry this segment now that the latency histogram is
    tracked in-kernel (per-group accumulator lanes, reduced at kfinish
    — sim/pkernel.py): both engines run the identical universe over
    ticks [0, ticks), compile excluded via a throwaway-universe warmup,
    and the kernel's numbers are promoted only when the full State AND
    full Metrics pytrees (histogram included, hence p50/p99) are
    bit-identical to the XLA path at the same tick. Returns a dict of
    segment results for the bench JSON."""
    cfg = RaftConfig(seed=seed, crash_prob=0.3, crash_epoch=64,
                     partition_prob=0.2, partition_epoch=64, drop_prob=0.02)
    # --- XLA reference: warm the compile on a throwaway universe, then
    # time the real one end-to-end (the histogram needs every tick).
    t0 = time.perf_counter()
    wst, wm = sim.run(cfg, sim.init(cfg, n_groups=n_groups), CHUNK, 0,
                      metrics_init(n_groups))
    jax.block_until_ready(wst)
    log(f"  [xla] warmup chunk (incl. compile): "
        f"{time.perf_counter() - t0:.1f}s")
    st = sim.init(cfg, n_groups=n_groups)
    m = metrics_init(n_groups)
    start = time.perf_counter()
    for tick_at in range(0, ticks, CHUNK):
        st, m = sim.run(cfg, st, min(CHUNK, ticks - tick_at), tick_at, m)
    n_elections = int(m.elections)          # fetch closes the timer
    x_elapsed = time.perf_counter() - start
    rounds = total_rounds(m)
    log(f"  [xla] {label} {n_groups} groups x {ticks} ticks in "
        f"{x_elapsed:.2f}s ({x_elapsed / ticks * 1e3:.2f} ms/tick): "
        f"{rounds} rounds, {n_elections} elections")

    engine, k_elapsed, state_ok = "xla-scan", None, None
    elapsed = x_elapsed
    try:   # kernel failure of ANY kind never kills the bench
        from raft_tpu.sim import pkernel
        if pkernel.supported(cfg) and jax.devices()[0].platform == "tpu":
            # Warmup on a throwaway universe: compile #1 (kinit
            # layouts) + compile #2 (kernel-chained layouts).
            t0 = time.perf_counter()
            wl, wg = pkernel.kinit(cfg, sim.init(cfg, n_groups=n_groups))
            wl = pkernel.kstep(cfg, wl, 0, CHUNK)
            pkernel.kelections(wl, wg)
            wl = pkernel.kstep(cfg, wl, CHUNK, CHUNK)
            pkernel.kelections(wl, wg)
            log(f"  [pallas] warmup (incl. 2 compiles): "
                f"{time.perf_counter() - t0:.1f}s")
            leaves, g = pkernel.kinit(cfg, sim.init(cfg, n_groups=n_groups))
            start = time.perf_counter()
            at = 0
            while at < ticks:
                n = min(CHUNK, ticks - at)
                leaves = pkernel.kstep(cfg, leaves, at, n)
                at += n
            pkernel.kelections(leaves, g)   # fetch closes the timer
            k_elapsed = time.perf_counter() - start
            st_pal, m_pal = pkernel.kfinish(cfg, leaves, g)
            state_ok = _trees_equal(st, st_pal)
            metrics_ok = _trees_equal(m, m_pal)
            log(f"  [pallas] {label} {n_groups} groups x {ticks} ticks in "
                f"{k_elapsed:.2f}s ({k_elapsed / ticks * 1e3:.2f} ms/tick)")
            if state_ok and metrics_ok:
                log("  [pallas] differential vs xla at same tick: full "
                    "State + full Metrics (incl. histogram) bit-identical")
                engine, elapsed = "pallas-fused-chunk", k_elapsed
            else:
                log(f"  [pallas] DIFFERENTIAL MISMATCH (state_identical="
                    f"{state_ok} metrics_identical={metrics_ok}) - "
                    f"kernel number discarded")
                engine = "xla-scan (pallas mismatch!)"
    except Exception as e:
        log(f"  [pallas] failed ({type(e).__name__}: {e}); xla stands")
        engine = f"xla-scan (pallas error: {type(e).__name__})"

    p50 = latency_quantile(m.hist, 0.5)
    p99 = latency_quantile(m.hist, 0.99)
    censored = latency_censored(m.hist, 0.99)
    max_lat = int(m.max_latency)
    p99_note = (f"tail bounded by the fault schedule, not the protocol:"
                f" partitions hold for partition_epoch="
                f"{cfg.partition_epoch}-tick windows, so a group"
                f" partitioned away from quorum cannot elect until the"
                f" epoch rolls")
    log(f"  {label}: {n_elections} elections, p50={p50} p99={p99} "
        f"max={max_lat} ticks"
        f"{' [p99 CENSORED at histogram top bucket]' if censored else ''}"
        f" ({p99_note}); engine={engine}")
    return {
        "p50": p50, "p99": p99, "censored": censored, "max_lat": max_lat,
        "p99_note": p99_note, "elections": n_elections, "rounds": rounds,
        "rounds_per_sec": rounds / elapsed, "engine": engine,
        "state_identical": state_ok, "n_groups": n_groups, "ticks": ticks,
        "xla_wall_s": round(x_elapsed, 3),
        "kernel_wall_s": (round(k_elapsed, 3)
                          if k_elapsed is not None else None),
    }


def bench_election_rounds(n_groups: int, ticks: int):
    """Config 2 shape: pure leader-election rounds — no client commands
    (`cmds_per_tick=0`, so no AppendEntries payload traffic and commits
    stay 0), with constant crash churn so elections keep completing.
    Reports completed leader acquisitions per second.

    What the number means: elections only complete when the crash
    schedule deposes a leader, so the measured rate is bounded above by
    the schedule's leader-crash rate, NOT by an intrinsic protocol
    limit — it is an existence proof that the batched path sustains
    config-2's election-only workload, normalized per wall-second.
    Expected value from the knobs here (crash_prob=0.5, crash_epoch=32):
    each epoch the leader crashes w.p. ~0.5 and a ~15-tick re-election
    follows, so roughly one election per group per ~2 epochs =
    ~1 / 64 ticks; at G groups and measured ticks/sec the schedule
    supports ~G x ticks_per_sec / 64 elections/sec, and the observed
    rate should sit near that ceiling (the bench JSON carries the raw
    election count so under-sampling is visible)."""
    cfg = RaftConfig(seed=44, cmds_per_tick=0, crash_prob=0.5,
                     crash_epoch=32)
    eps, elections, elapsed, timed_ticks, st_ref, m_ref = _timed_chunks(
        cfg, n_groups, ticks, lambda st, m: int(m.elections))
    log(f"  [xla] election rounds {n_groups} groups x {timed_ticks} ticks: "
        f"{elections} elections in {elapsed:.2f}s -> {eps:,.0f} elections/s")
    engine = "xla-scan"
    p_rate, p_count, _, status, state_ok = _pallas_segment(
        cfg, n_groups, timed_ticks, "kelections", st_ref, m_ref,
        "elections")
    if status == "ok" and p_rate > eps:
        eps, elections = p_rate, p_count
        engine = "pallas-fused-chunk"
    elif status == "mismatch":
        engine = "xla-scan (pallas mismatch!)"
    return eps, elections, engine, state_ok


def bench_reads(n_groups: int, ticks: int):
    """Scheduled linearizable reads at scale (DESIGN.md §2c): the
    config-5 replication workload with the ReadIndex pipeline on
    (read_every=4). Completed reads are counted from the `reads_done`
    trace field — with no fault schedule the counter is monotone (no
    restarts zero it), so the timed delta is exact. Same two-engine
    scheme as the headline: the Pallas fused-chunk number is promoted
    only when the full State pytree (reads_done included) and the full
    Metrics pytree are bit-identical to the XLA path at the same
    tick."""
    cfg = RaftConfig(seed=45, read_every=4)
    rps, reads, elapsed, timed_ticks, st_ref, m_ref = _timed_chunks(
        cfg, n_groups, ticks,
        lambda st, m: int(np.asarray(st.nodes.reads_done)
                          .astype(np.int64).sum()))
    log(f"  [xla] linearizable reads {n_groups} groups x {timed_ticks} "
        f"ticks (read_every={cfg.read_every}): {reads} reads in "
        f"{elapsed:.2f}s -> {rps:,.0f} reads/s")
    engine = "xla-scan"
    p_rate, p_count, _, status, state_ok = _pallas_segment(
        cfg, n_groups, timed_ticks, "kreads", st_ref, m_ref, "reads")
    if status == "ok" and p_rate > rps:
        rps, reads = p_rate, p_count
        engine = "pallas-fused-chunk"
    elif status == "mismatch":
        engine = "xla-scan (pallas mismatch!)"
    return rps, reads, engine, state_ok


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small shapes for a smoke run")
    ap.add_argument("--groups", type=int, default=None,
                    help="override the throughput-run group count")
    args = ap.parse_args()

    dev = jax.devices()[0]
    log(f"platform: {dev.platform} ({dev.device_kind}), "
        f"{len(jax.devices())} device(s)")
    if args.quick:
        groups, ticks = 1_000, 200
        e_groups, e_ticks = 1_000, 200
        f_groups, f_ticks = 1_000, 200
        r_groups, r_ticks = 1_000, 200
        rd_groups, rd_ticks = 1_000, 200
    else:
        # The headline runs at the true config-5 shape: 100K groups.
        # (History: a TPU kernel fault at 100K groups blocked this shape
        # in round 2; it stopped reproducing in round 3 with no hot-path
        # change and has not been seen since — if a 100K run ever dies
        # in the runtime again, that regression has a precedent.)
        groups, ticks = args.groups or 100_000, 600
        e_groups, e_ticks = 50_000, 600      # config-4 shape
        f_groups, f_ticks = args.groups or 100_000, 600  # config-5 + faults
        # Config-2: 2400 ticks so the timed region is seconds, not
        # sub-second (the rate is schedule-bound; see the fn docstring).
        r_groups, r_ticks = 10_000, 2400
        rd_groups, rd_ticks = 50_000, 600   # ReadIndex-at-scale segment

    log(f"throughput (config-5 shape, {groups} x 5-node groups):")
    (rps, rounds, elapsed, ticks, engine, pallas_rps, pallas_ms,
     tp_state_ok) = bench_throughput(groups, ticks)
    log("election latency (config-4 shape, both engines):")
    c4 = bench_fault_latency(43, e_groups, e_ticks, "config-4 fault run")
    log("fault-mix throughput + latency (config-5 shape, both engines):")
    c5f = bench_fault_latency(46, f_groups, f_ticks, "config-5 fault mix")
    log("election rounds (config-2 shape):")
    eps, n_c2_elections, c2_engine, c2_state_ok = bench_election_rounds(
        r_groups, r_ticks)
    log("linearizable reads (config-5 shape + ReadIndex schedule):")
    reads_ps, n_reads, reads_engine, rd_state_ok = bench_reads(
        rd_groups, rd_ticks)

    print(json.dumps({
        "metric": "consensus_rounds_per_sec_per_chip",
        "value": round(rps, 1),
        "unit": "rounds/s",
        "vs_baseline": round(rps / BASELINE_ROUNDS_PER_SEC, 3),
        "n_groups": groups,
        "ticks": ticks,
        "wall_s": round(elapsed, 3),
        "engine": engine,
        "pallas_rounds_per_sec": (round(pallas_rps, 1)
                                  if pallas_rps is not None else None),
        "pallas_ms_per_tick": (round(pallas_ms, 3)
                               if pallas_ms is not None else None),
        "throughput_state_identical": tp_state_ok,
        "p50_election_latency_ticks": c4["p50"],
        "p99_election_latency_ticks": c4["p99"],
        "p99_censored": c4["censored"],
        "max_election_latency_ticks": c4["max_lat"],
        "p99_note": c4["p99_note"],
        "elections_observed": c4["elections"],
        "config4_engine": c4["engine"],
        "config4_state_identical": c4["state_identical"],
        "config4_xla_wall_s": c4["xla_wall_s"],
        "config4_kernel_wall_s": c4["kernel_wall_s"],
        "faulted_rounds_per_sec": round(c5f["rounds_per_sec"], 1),
        "faulted_p50_election_latency_ticks": c5f["p50"],
        "faulted_p99_election_latency_ticks": c5f["p99"],
        "faulted_p99_censored": c5f["censored"],
        "faulted_elections_observed": c5f["elections"],
        "config5_fault_n_groups": c5f["n_groups"],
        "config5_fault_engine": c5f["engine"],
        "config5_fault_state_identical": c5f["state_identical"],
        "config5_fault_xla_wall_s": c5f["xla_wall_s"],
        "config5_fault_kernel_wall_s": c5f["kernel_wall_s"],
        "elections_per_sec": round(eps, 1),
        "config2_elections_observed": n_c2_elections,
        "config2_engine": c2_engine,
        "config2_state_identical": c2_state_ok,
        "config2_note": "schedule-bound rate; see bench_election_rounds",
        "linearizable_reads_per_sec": round(reads_ps, 1),
        "reads_observed": n_reads,
        "reads_engine": reads_engine,
        "reads_state_identical": rd_state_ok,
        "device": f"{dev.platform}:{dev.device_kind}",
    }))


if __name__ == "__main__":
    main()
