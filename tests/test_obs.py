"""Observability-layer tests (DESIGN.md §8): the comparator names the
first divergent leaf, triage bisects a synthetic corruption to the
exact tick and leaf, the flight recorder rides the scanned runner and
is chunk-invariant, the per-tick safety fold latches real violations,
metric parity between the engines is pinned statically, and manifests
round-trip.

Kernel-engine counterparts (safety-bit and flight-ring bit-parity
against the XLA path) live in tests/test_pkernel.py with the other
interpret-mode differentials."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np

from conftest import trees_equal as _trees_equal
from raft_tpu import sim
from raft_tpu.config import RaftConfig
from raft_tpu.obs import (RING, bisect_divergence, config_hash,
                          emit_manifest, flight_init, flight_rows,
                          run_recorded)
from raft_tpu.sim.run import metrics_init, metrics_update, run, unsafe_groups
from raft_tpu.utils.trees import trees_equal_why

CFG = RaftConfig(n_groups=8, k=3, seed=21, drop_prob=0.05, crash_prob=0.2,
                 crash_epoch=16, log_cap=8, compact_every=4)


def test_trees_reports_first_divergent_leaf_path():
    """The comparator names the leaf PATH, dtype/shape, and the first
    differing element — no more bare boolean False in gate output."""
    st = sim.init(CFG)
    bad = st._replace(nodes=st.nodes._replace(
        deadline=st.nodes.deadline.at[2, 1].add(5)))
    ok, why = trees_equal_why(st, bad)
    assert not ok
    assert "deadline" in why
    assert "int32" in why
    assert "[2,1]" in why
    assert "1/24 elements differ" in why
    ok, why = trees_equal_why(st, st)
    assert ok and why == ""


def test_triage_bisects_to_corrupted_tick_and_leaf():
    """Synthetic corruption: one state leaf flipped mid-run. Triage must
    name the exact first divergent tick and the corrupted leaf."""
    corrupt_at, n_ticks = 21, 32

    def clean(st, n, t):
        return run(CFG, st, n, t)[0]

    def corrupt(st, n, t0):
        # Deterministic in (state, t0): re-execution through the
        # corrupted tick reproduces the same corruption — the property
        # bisect_divergence's tick-by-tick stage relies on.
        for t in range(t0, t0 + n):
            st = run(CFG, st, 1, t)[0]
            if t == corrupt_at:
                st = st._replace(nodes=st.nodes._replace(
                    term=st.nodes.term.at[3, 1].add(7)))
        return st

    report = bisect_divergence(clean, corrupt, sim.init(CFG), n_ticks,
                               chunk=16)
    assert report is not None
    assert report["tick"] == corrupt_at
    assert report["boundary"] == (16, 32)
    assert "term" in report["leaf_report"]
    assert "[3,1]" in report["leaf_report"]
    # And a clean pair reports no divergence.
    assert bisect_divergence(clean, clean, sim.init(CFG), n_ticks,
                             chunk=16) is None


def test_triage_bisects_client_leaf_corruption():
    """The client-leaf flavor of the r07 corruption test (which only
    covers nodes.term): a session dedup-table entry (`session_seq`)
    flipped mid-run on a clients-on universe — triage must name the
    exact tick AND the session leaf. The dedup table is the
    exactly-once invariant's ground truth, so a triage that cannot
    bisect INTO it would leave the worst class of divergence (silent
    double-apply) unlocalized."""
    cfg = RaftConfig(n_groups=8, k=3, seed=21, drop_prob=0.05,
                     crash_prob=0.2, crash_epoch=16, log_cap=8,
                     compact_every=4, sessions=True, cmds_per_tick=0,
                     client_rate=0.3, client_slots=2)
    corrupt_at, n_ticks = 21, 32

    def clean(st, n, t):
        return run(cfg, st, n, t)[0]

    def corrupt(st, n, t0):
        for t in range(t0, t0 + n):
            st = run(cfg, st, 1, t)[0]
            if t == corrupt_at:
                st = st._replace(nodes=st.nodes._replace(
                    session_seq=st.nodes.session_seq.at[3, 1, 0].add(7)))
        return st

    report = bisect_divergence(clean, corrupt, sim.init(cfg), n_ticks,
                               chunk=16)
    assert report is not None
    assert report["tick"] == corrupt_at
    assert report["boundary"] == (16, 32)
    assert "session_seq" in report["leaf_report"]


def test_triage_names_kernel_wire_leaf():
    """A flipped kernel wire leaf surfaces under its State field name
    after kfinish — the kernel-state flavor of leaf naming (no kernel
    launch: kinit/kfinish round-trip only)."""
    from raft_tpu.sim import pkernel
    from raft_tpu.sim.state import PerNode

    st0 = sim.init(CFG)
    leaves, g = pkernel.kinit(CFG, st0)
    idx = PerNode._fields.index("voted_for")
    bad = list(leaves)
    bad[idx] = bad[idx].at[0, 0, 0].add(1)
    stc, _ = pkernel.kfinish(CFG, tuple(bad), g)
    ok, why = trees_equal_why(st0, stc)
    assert not ok
    assert "voted_for" in why


def test_flight_recorder_rides_the_scan():
    """run_recorded == run bit-for-bit on state+metrics, the ring holds
    one row per tick (n_ticks < RING), and the rows cross-check the
    metrics fold."""
    st0 = sim.init(CFG)
    st_ref, m_ref = run(CFG, st0, 40)
    st, m, f = run_recorded(CFG, st0, 40)
    assert _trees_equal(st_ref, st)
    assert _trees_equal(m_ref, m)
    rows = flight_rows(f)
    assert [r["tick"] for r in rows] == list(range(40))
    assert sum(r["elections"] for r in rows) == int(m.elections)
    assert all(r["unsafe_groups"] == 0 for r in rows)
    assert all(0 <= r["leaders"] <= CFG.n_groups * CFG.k for r in rows)
    # Chunk boundaries are invisible to the recording.
    st2, m2, f2 = run_recorded(CFG, st0, 24)
    st2, m2, f2 = run_recorded(CFG, st2, 16, 24, m2, f2)
    assert _trees_equal(f, f2)


def test_flight_ring_wraps():
    """Past RING ticks the ring keeps exactly the last RING ticks."""
    n_ticks = RING + 16
    _, _, f = run_recorded(CFG, sim.init(CFG), n_ticks)
    rows = flight_rows(f)
    assert [r["tick"] for r in rows] == list(range(16, n_ticks))


def test_safety_bit_latches_violations():
    """The per-tick fold stays 1 through a legitimately faulted run and
    latches 0 on a synthetic invariant violation (window bound)."""
    st, m = run(CFG, sim.init(CFG), 48)
    assert unsafe_groups(m) == 0
    assert m.safety.shape == (CFG.n_groups,)
    bad = st._replace(nodes=st.nodes._replace(
        commit=st.nodes.commit + 1000))   # commit > last_index everywhere
    m2 = metrics_update(m, bad, CFG.log_cap)
    assert unsafe_groups(m2) == CFG.n_groups
    # The AND latches: a later clean tick cannot clear it.
    m3 = metrics_update(m2, st, CFG.log_cap)
    assert unsafe_groups(m3) == CFG.n_groups


def test_metric_parity_script():
    """The static Metrics/KMetrics/Flight/ClientState parity gate runs
    clean — tier-1 coverage for scripts/check_metric_parity.py,
    client-metric lanes included (r09)."""
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "check_metric_parity.py")
    proc = subprocess.run([sys.executable, script], capture_output=True,
                          text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "metric parity ok" in proc.stdout


def test_client_metric_lanes_statically_gated():
    """Metrics grows the client-SLO lanes ONLY under clients=True
    (r09): the clients-off pytree — and hence every pre-r09 compiled
    program, checkpoint, and gate surface — is unchanged, and lane
    drift between the engines' wire orders stays rc != 0 via the
    parity script above."""
    from raft_tpu.sim.pkernel import (CLIENT_METRIC_LEAVES, KMetrics,
                                      METRIC_LEAVES)
    from raft_tpu.sim.run import Metrics

    off = metrics_init(4)
    on = metrics_init(4, clients=True)
    for name in CLIENT_METRIC_LEAVES:
        assert getattr(off, name) is None
        assert getattr(on, name) is not None
    # Field-name parity across the three surfaces.
    assert set(Metrics._fields) == set(METRIC_LEAVES) \
        == set(KMetrics._fields)
    # A clients-off Metrics flattens to the pre-r09 leaf count.
    import jax
    assert len(jax.tree.leaves(off)) == 6
    assert len(jax.tree.leaves(on)) == 10


def test_manifest_roundtrip(tmp_path):
    path = tmp_path / "manifest.jsonl"
    rec = emit_manifest("unit-test", CFG, device="cpu:test",
                        path=str(path), rate=123.4, safety_ok=True)
    lines = path.read_text().splitlines()
    assert len(lines) == 1
    loaded = json.loads(lines[0])
    assert loaded == json.loads(json.dumps(rec))
    assert loaded["segment"] == "unit-test"
    assert loaded["config_hash"] == config_hash(CFG)
    assert loaded["config"]["seed"] == CFG.seed
    assert loaded["jax"] and loaded["device"] == "cpu:test"
    assert loaded["rate"] == 123.4 and loaded["safety_ok"] is True
    # Mesh provenance keys exist in EVERY record — null until a caller
    # fills them, so "one chip" and "unrecorded" stay distinguishable.
    assert loaded["mesh_shape"] is None
    assert loaded["groups_per_device"] is None
    rec2 = emit_manifest("unit-test-mesh", CFG, device="cpu:test",
                         path="-", mesh_shape=[8], groups_per_device=8)
    assert rec2["mesh_shape"] == [8] and rec2["groups_per_device"] == 8
    # Appending and hash sensitivity.
    emit_manifest("unit-test-2", RaftConfig(seed=99), device="cpu:test",
                  path=str(path))
    lines = path.read_text().splitlines()
    assert len(lines) == 2
    assert json.loads(lines[1])["config_hash"] != config_hash(CFG)


def test_checkpointed_metrics_carry_safety(tmp_path):
    """Metrics.safety survives a save/load round trip, and a resumed
    run continues the same AND chain."""
    from raft_tpu.sim import checkpoint

    st, m = run(CFG, sim.init(CFG), 24)
    path = tmp_path / "ckpt.npz"
    checkpoint.save(path, st, 24, m, cfg=CFG)
    st2, t2, m2 = checkpoint.load(path, cfg=CFG)
    assert _trees_equal(m, m2)
    a, ma = run(CFG, st, 24, 24, m)
    b, mb = run(CFG, st2, 24, t2, m2)
    assert _trees_equal(a, b)
    assert np.array_equal(np.asarray(ma.safety), np.asarray(mb.safety))
