"""Nemesis scenario compiler (DESIGN.md §14, ISSUE r14): gray-failure
programs compile to the hashed elementwise schedule form and run
bit-identically on the CPU oracle, the XLA scan, and the Pallas kernel;
the adversarial search is deterministic; the shrinker minimizes a
seeded safety violation to a reproducer that replays to the same tick
and leaf."""

from __future__ import annotations

import dataclasses
import json

import numpy as np
import pytest

from conftest import trees_equal as _trees_equal
from raft_tpu import nemesis, sim
from raft_tpu.config import RaftConfig
from raft_tpu.nemesis import search as nsearch
from raft_tpu.sim import checkpoint, pkernel
from raft_tpu.sim.run import metrics_init, run
from raft_tpu.utils import jrng
from raft_tpu.utils import rng as pr

BASE = dict(seed=9, k=3, log_cap=8, compact_every=4, drop_prob=0.03,
            crash_prob=0.1, crash_epoch=24)


def _all_kinds_program(ticks: int) -> tuple:
    """One clause of every kind, overlapping spans — the parity tests'
    worst case (every seam active, every tag drawn). r20 grew it over
    the storage-pressure seams (disk-full appends, compaction stalls)."""
    return nemesis.program(
        nemesis.slow_follower(0, ticks, p=0.7, direction=3),
        nemesis.flaky_link(0, ticks, p=0.9, burst_epoch=8, burst_p=0.6),
        nemesis.wan_delay(0, ticks * 2 // 3, sites=2, p=0.4),
        nemesis.clock_skew(4, ticks - 8, amount=5, node_p=0.6),
        nemesis.crash_storm(8, ticks * 2 // 3, p=0.3, epoch=4),
        nemesis.partition_wave(10, ticks - 4, period=16, width=6,
                               leak_p=0.8),
        nemesis.disk_full_follower(2, ticks - 2, p=0.8, epoch=8),
        nemesis.compaction_pressure(6, ticks * 3 // 4, p=0.5, epoch=4))


# ------------------------------------------------------ compiled form


def test_nem_evaluator_parity_grids():
    """utils.rng nemesis evaluators == their utils.jrng twins on whole
    coordinate grids (the test_rng idiom) for a program with every
    clause kind active."""
    seed, K, T, G = 9, 3, 24, 5
    prog = _all_kinds_program(T)
    cfg = RaftConfig(**{**BASE, "seed": seed}, nemesis=prog)
    t = np.arange(T, dtype=np.uint32)[:, None, None, None]
    g = np.arange(G, dtype=np.uint32)[None, :, None, None]
    a = np.arange(K, dtype=np.uint32)[None, None, :, None]
    b = np.arange(K, dtype=np.uint32)[None, None, None, :]
    got_link = np.asarray(jrng.nem_link_ok(seed, cfg.nem_link, g, t,
                                           a, b, K))
    got_alive = np.asarray(jrng.nem_alive(seed, cfg.nem_crash, g, a, t))
    got_extra = np.asarray(jrng.nem_deadline_extra(seed, cfg.nem_skew,
                                                   g, a, t))
    got_disk = np.asarray(jrng.nem_disk_full(seed, cfg.nem_disk, g, a,
                                             t, K))
    got_comp = np.asarray(jrng.nem_compact_block(seed, cfg.nem_compact,
                                                 g, a, t))
    for ti in range(T):
        for gi in range(G):
            for ai in range(K):
                assert bool(got_alive[ti, gi, ai, 0]) == pr.nem_alive(
                    seed, cfg.nem_crash, gi, ai, ti)
                assert int(got_extra[ti, gi, ai, 0]) \
                    == pr.nem_deadline_extra(seed, cfg.nem_skew, gi,
                                             ai, ti)
                assert bool(got_disk[ti, gi, ai, 0]) \
                    == pr.nem_disk_full(seed, cfg.nem_disk, gi, ai,
                                        ti, K)
                assert bool(got_comp[ti, gi, ai, 0]) \
                    == pr.nem_compact_block(seed, cfg.nem_compact, gi,
                                            ai, ti)
                for bi in range(K):
                    assert bool(got_link[ti, gi, ai, bi]) \
                        == pr.nem_link_ok(seed, cfg.nem_link, gi, ti,
                                          ai, bi, K)


def test_evaluators_refuse_misfiltered_programs():
    """A seam evaluator handed a program with no relevant clause raises
    at build/trace time (never a silent no-op) — the static-gating
    contract callers rely on."""
    crash_only = (nemesis.program(nemesis.crash_storm(0, 8)),)
    for mod in (pr, jrng):
        with pytest.raises(ValueError, match="no link clause"):
            mod.nem_link_ok(1, crash_only[0], 0, 0, 0, 1, 3)
        with pytest.raises(ValueError, match="no timing clause"):
            mod.nem_deadline_extra(1, crash_only[0], 0, 0, 0)
        with pytest.raises(ValueError, match="no crash clause"):
            mod.nem_alive(1, nemesis.program(nemesis.wan_delay(0, 8)),
                          0, 0, 0)
        with pytest.raises(ValueError, match="no disk clause"):
            mod.nem_disk_full(1, crash_only[0], 0, 0, 0, 3)
        with pytest.raises(ValueError, match="no compaction clause"):
            mod.nem_compact_block(1, crash_only[0], 0, 0, 0)
    # ...but a link program whose clauses are all STATIC no-ops (a
    # flaky link in a k=1 group has no links) is legal and passes
    # everything on BOTH evaluators — no engine asymmetry.
    noop = nemesis.program(nemesis.flaky_link(0, 8))
    assert pr.nem_link_ok(1, noop, 0, 0, 0, 0, 1) is True
    assert bool(jrng.nem_link_ok(1, noop, 0, 0, 0, 0, 1))


def test_program_builders_json_hash_and_config_normalization():
    prog = _all_kinds_program(32)
    # cids are positional and stable; kinds partition across the seams
    # (r20: five seams — delivery, liveness, timing, durability,
    # compaction).
    assert [c.cid for c in prog] == list(range(8))
    cfg = RaftConfig(**BASE, nemesis=prog)
    seams = (cfg.nem_link, cfg.nem_crash, cfg.nem_skew, cfg.nem_disk,
             cfg.nem_compact)
    assert set().union(*(set(s) for s in seams)) == set(prog)
    assert sum(len(s) for s in seams) == len(prog)
    # JSON round trips: the program alone, and the whole config dict.
    assert nemesis.from_json(nemesis.to_json(prog)) == prog
    assert nemesis.from_json(json.loads(json.dumps(
        nemesis.to_json(prog)))) == prog
    d = json.loads(json.dumps(dataclasses.asdict(cfg)))
    cfg2 = RaftConfig(**d)
    assert cfg2 == cfg and hash(cfg2) == hash(cfg)
    assert nemesis.program_hash(cfg2.nemesis) \
        == nemesis.program_hash(prog)
    # Shrink edits change the hash; a re-built survivor set does not.
    assert nemesis.program_hash(prog[:2]) != nemesis.program_hash(prog)
    assert nemesis.program(*prog[1:3]) == prog[1:3]   # cids preserved


def test_config_rejects_malformed_programs():
    for bad in ((1, 2, 3),                       # not 8 fields
                (99, 0, 8, 0, 0, 0, 0, 0),       # unknown kind
                (pr.NEM_SLOW, 9, 3, 0, 0, 3, 0, 0),   # t1 < t0
                (pr.NEM_SLOW, 0, 8, 0, 0, 0, 0, 0),   # direction mask 0
                (pr.NEM_STORM, 0, 8, 0, 0, 0, 0, 0),  # epoch < 1
                (pr.NEM_WAN, 0, 8, 0, 0, 1, 0, 0),    # < 2 sites
                (pr.NEM_WAVE, 0, 8, 0, 0, 8, -1, 0),  # b outside u32
                (pr.NEM_SKEW, 0, 8, 0, 0, 2**31, 0, 0),  # a outside i32
                (pr.NEM_SLOW, 0, 8, 0, 0, 3, 0, -1)):  # unassigned cid
        with pytest.raises(ValueError):
            RaftConfig(**BASE, nemesis=(bad,))
    with pytest.raises(ValueError, match="unique"):
        RaftConfig(**BASE, nemesis=(
            (pr.NEM_SLOW, 0, 8, 0, 0, 3, 0, 0),
            (pr.NEM_WAN, 0, 8, 0, 0, 2, 0, 0)))


# ------------------------------------------- three-engine bit identity


def test_oracle_vs_xla_all_kinds_120_ticks():
    """Acceptance gate, oracle half: a program with EVERY clause kind
    runs bit-identically on the CPU oracle and the XLA scan, per node
    per tick, over a >=120-tick faulted universe (shared harness:
    obs.triage.oracle_divergence)."""
    from raft_tpu.obs.triage import oracle_divergence

    ticks = 120
    cfg = RaftConfig(**BASE, nemesis=_all_kinds_program(ticks))
    assert oracle_divergence(cfg, 8, ticks, oracle_groups=4) is None


@pytest.mark.slow
def test_gray_mix_xla_vs_kernel_120_ticks():
    """Acceptance gate, kernel half: the canonical gray mix
    (slow-follower + flaky-link) bit-identical between the XLA scan
    and the interpret-mode Pallas kernel on the FULL State + Metrics
    pytrees over a >=120-tick faulted universe, with the per-tick
    safety fold clean."""
    ticks, G = 120, 16
    cfg = RaftConfig(**BASE, nemesis=nemesis.gray_mix(ticks))
    st0 = sim.init(cfg, n_groups=G)
    xst, xm = run(cfg, st0, ticks, 0, metrics_init(G))
    kst, km = pkernel.prun(cfg, st0, ticks, 0, interpret=True)[:2]
    assert _trees_equal(xst, kst)
    assert _trees_equal(xm, km)
    assert int((np.asarray(xm.safety) == 0).sum()) == 0


def _admission_cfg(ticks: int, **over) -> RaftConfig:
    """The r20 pressure acceptance universe: the canonical pressure
    mix (disk-full follower + compaction stalls) with bounded-admission
    open-loop client traffic riding on top — every new seam active at
    once (durable-prefix NACKs, ring backpressure, definitive sheds)."""
    return RaftConfig(**{**BASE, **over}, sessions=True, cmds_per_tick=0,
                      client_rate=0.3, client_slots=2,
                      client_queue_cap=4,
                      nemesis=nemesis.pressure_mix(ticks))


def test_pressure_mix_oracle_vs_xla_120_ticks():
    """Acceptance gate, oracle half (r20): the pressure mix with
    admission-capped client traffic runs bit-identically on the CPU
    oracle and the XLA scan, per node per tick, over a >=120-tick
    faulted universe."""
    from raft_tpu.obs.triage import oracle_divergence

    ticks = 120
    cfg = _admission_cfg(ticks)
    assert oracle_divergence(cfg, 8, ticks, oracle_groups=4) is None


def test_pressure_mix_xla_vs_kernel_48_ticks():
    """Acceptance gate, kernel half (r20, smoke shape): pressure mix +
    bounded admission bit-identical between the XLA scan and the
    interpret-mode Pallas kernel on FULL State + Metrics, with the
    safety fold clean, the shed ledger non-vacuously exercised, and
    the exactly-once endpoint accounting (shed included) clean."""
    from raft_tpu.clients import exactly_once_report

    ticks, G = 48, 16
    cfg = _admission_cfg(ticks)
    st0 = sim.init(cfg, n_groups=G)
    xst, xm = run(cfg, st0, ticks, 0, metrics_init(G, clients=True))
    kst, km = pkernel.prun(cfg, st0, ticks, 0, interpret=True)[:2]
    assert _trees_equal(xst, kst)
    assert _trees_equal(xm, km)
    assert int((np.asarray(xm.safety) == 0).sum()) == 0
    assert int(np.asarray(xst.clients.shed).sum()) > 0, \
        "no sheds — the admission differential is vacuous"
    ok, why = exactly_once_report(cfg, xst, xm)
    assert ok, why


@pytest.mark.slow
def test_pressure_mix_xla_vs_kernel_64_groups_120_ticks():
    """The full r20 acceptance differential: the faulted 64-group
    universe under the pressure mix + bounded admission, XLA vs the
    interpret-mode kernel, bit-identical on FULL State + Metrics."""
    from raft_tpu.clients import exactly_once_report

    ticks, G = 120, 64
    cfg = _admission_cfg(ticks)
    st0 = sim.init(cfg, n_groups=G)
    xst, xm = run(cfg, st0, ticks, 0, metrics_init(G, clients=True))
    kst, km = pkernel.prun(cfg, st0, ticks, 0, interpret=True)[:2]
    assert _trees_equal(xst, kst)
    assert _trees_equal(xm, km)
    assert int((np.asarray(xm.safety) == 0).sum()) == 0
    assert int(np.asarray(xst.clients.shed).sum()) > 0
    ok, why = exactly_once_report(cfg, xst, xm)
    assert ok, why


def test_default_off_changes_nothing():
    """nemesis=() compiles the byte-identical pre-r14 program: same
    trajectory as a config that never mentions the knob (the cfg-gating
    contract the contracts pass proves structurally)."""
    cfg = RaftConfig(**BASE)
    assert cfg.nemesis == () and not cfg.nem_link and not cfg.nem_crash
    a, ma = run(cfg, sim.init(cfg, n_groups=8), 32, 0, metrics_init(8))
    cfg2 = dataclasses.replace(cfg, nemesis=())
    b, mb = run(cfg2, sim.init(cfg2, n_groups=8), 32, 0, metrics_init(8))
    assert _trees_equal(a, b) and _trees_equal(ma, mb)


# --------------------------------------------------- contracts auditor


def test_nemesis_contracts_clean_and_drift_named():
    from raft_tpu.analysis import contracts

    assert contracts.nemesis_problems() == []
    # Synthetic drift: a kind routed to no seam, then to two seams.
    probs = contracts.nemesis_problems(crash_kinds=())
    assert any("NO engine seam" in p for p in probs)
    probs = contracts.nemesis_problems(
        link_kinds=pr.NEM_LINK_KINDS + (pr.NEM_STORM,))
    assert any("MORE than one seam" in p for p in probs)
    probs = contracts.nemesis_problems(kinds=pr.NEM_KINDS + (9,))
    assert any("no program.py builder" in p for p in probs)


def test_manifest_r14_keys_both_directions():
    """The bench nemesis segment's manifest keys are present-but-null
    from birth and backfilled onto pre-r14 records — the same
    both-direction proof as PACKING_KEYS at r13."""
    from raft_tpu.obs import history, manifest

    assert tuple(history.R14_MANIFEST_KEYS) == tuple(manifest.NEMESIS_KEYS)
    old = {"segment": "x", "ts": 0}
    new = history.backfill_record(old)
    for k in manifest.NEMESIS_KEYS:
        assert k in new and new[k] is None
    assert "nemesis_program_hash" in manifest.NEMESIS_KEYS


# ----------------------------------------------- checkpoint round trip


def test_checkpoint_nemesis_roundtrip_and_pre_r14_backfill(tmp_path):
    """Satellite gate (ISSUE r14): a nemesis-on universe checkpoints
    and resumes bit-identically; a pre-r14 file (embedded cfg dict
    missing the knob) backfills to the empty program and loads under a
    nemesis-free cfg — and REFUSES under a nemesis-on one (a different
    universe schedule must never silently resume)."""
    ticks = 40
    cfg = RaftConfig(**BASE, nemesis=nemesis.gray_mix(80))
    st, m = run(cfg, sim.init(cfg, n_groups=8), ticks, 0, metrics_init(8))
    path = tmp_path / "nem.npz"
    checkpoint.save(path, st, ticks, m, cfg=cfg)
    st2, t2, m2 = checkpoint.load(path, cfg=cfg)
    assert t2 == ticks and _trees_equal(st, st2) and _trees_equal(m, m2)
    a, ma = run(cfg, st, 20, ticks, m)
    b, mb = run(cfg, st2, 20, t2, m2)
    assert _trees_equal(a, b) and _trees_equal(ma, mb)

    # Simulate a pre-r14 writer: strip the knob from the embedded cfg.
    off = RaftConfig(**BASE)
    st_off = sim.init(off, n_groups=8)
    old = tmp_path / "pre_r14.npz"
    checkpoint.save(tmp_path / "off.npz", st_off, 0, cfg=off)
    with np.load(tmp_path / "off.npz") as z:
        data = {k: z[k] for k in z.files}
    saved_cfg = json.loads(bytes(data["__cfg__"]).decode())
    assert saved_cfg.pop("nemesis") == []
    data["__cfg__"] = np.bytes_(json.dumps(saved_cfg, sort_keys=True))
    np.savez(old, **data)
    st3, t3, _ = checkpoint.load(old, cfg=off)      # backfills to ()
    assert t3 == 0 and _trees_equal(st_off, st3)
    with pytest.raises(ValueError, match="cfg mismatch"):
        checkpoint.load(old, cfg=cfg)


# ------------------------------------------------- search and shrinker


def test_search_is_deterministic():
    """Two hunts from the same seed produce identical corpora,
    coverage maps, and scores (every draw is a hash_u32 of
    (seed, step) — the repo's determinism rule applied to the search)."""
    base = RaftConfig(**BASE)
    a = nsearch.search(base, 8, 16, budget=2, seed=3)
    b = nsearch.search(base, 8, 16, budget=2, seed=3)
    assert a["corpus"] == b["corpus"]
    assert a["coverage"] == b["coverage"]
    assert a["best"] == b["best"] and a["best_score"] == b["best_score"]
    assert a["violations"] == b["violations"]
    # Mutation itself is pure in (prog, seed, step).
    prog = nemesis.gray_mix(16)
    for step in range(8):
        assert nsearch.mutate(prog, 16, 5, step) \
            == nsearch.mutate(prog, 16, 5, step)


def test_shrinker_seeded_violation_deterministic(tmp_path):
    """Satellite gate (ISSUE r14): a synthetic safety violation — a
    term corrupted mid-run, armed only while the program is active —
    shrinks to a <=2-clause program whose triage names the exact tick
    and leaf, deterministically across two runs; the serialized
    reproducer round-trips and replays to the same (tick, leaf)."""
    ticks, corrupt_t = 24, 9
    base = RaftConfig(**BASE)
    prog = nemesis.program(
        nemesis.slow_follower(0, ticks, p=0.7),
        nemesis.flaky_link(0, ticks, p=0.9, burst_epoch=8, burst_p=0.6))
    pair = nsearch.term_corruption_pair(corrupt_t, group=0, node=1)
    # chunk=1: one compiled program per candidate config (the shrink
    # loop's wall time is XLA compiles, not tick execution).
    repro = nsearch.divergence_repro(base, pair, 4, ticks, chunk=1)

    runs = []
    for _ in range(2):
        mini, rep = nsearch.shrink(prog, repro)
        runs.append((mini, rep["tick"], rep["leaf"]))
    assert runs[0] == runs[1], "shrink is not deterministic"
    mini, tick, leaf = runs[0]
    assert len(mini) <= 2
    assert tick == corrupt_t
    assert "term" in leaf
    # The surviving clause kept its original cid (schedule-preserving
    # minimization) and still covers the corruption tick.
    assert all(c[7] in {0, 1} for c in mini)
    assert all(c[1] <= corrupt_t < c[2] for c in mini)

    # Artifact: save -> load -> verify replays the same tick + leaf.
    cfg_min = dataclasses.replace(base, nemesis=mini)
    art = nsearch.reproducer(
        cfg_min, ticks, rep, engines="xla-vs-seeded-corruption",
        inject={"kind": "term_flip", "tick": corrupt_t,
                "group": 0, "node": 1, "bump": 4},
        n_groups=4, note="test_shrinker_seeded_violation_deterministic")
    p = tmp_path / "repro.json"
    nsearch.save_reproducer(str(p), art)
    cfg_loaded, art_loaded = nsearch.load_reproducer(str(p))
    assert cfg_loaded.nemesis == mini
    fresh = nsearch.verify_reproducer(art_loaded, repro)
    assert fresh["tick"] == corrupt_t

    # Tampered artifacts are refused, naming the drift.
    bad = dict(art, program_hash="00000000")
    nsearch.save_reproducer(str(tmp_path / "bad.json"), bad)
    with pytest.raises(ValueError, match="program_hash"):
        nsearch.load_reproducer(str(tmp_path / "bad.json"))


@pytest.mark.slow
def test_checked_in_example_reproducer_replays():
    """The checked-in artifact (NEMESIS_repro_example.json, written by
    `nemesis_search.py --seed-violation`) still replays to its recorded
    tick + leaf via bisect_divergence — a reproducer that stops
    reproducing is itself a finding."""
    import os

    path = os.path.join(os.path.dirname(__file__), "..",
                        "NEMESIS_repro_example.json")
    cfg, art = nsearch.load_reproducer(path)
    inject = art["inject"]
    pair = nsearch.term_corruption_pair(inject["tick"], inject["group"],
                                        inject["node"], inject["bump"])
    repro = nsearch.divergence_repro(cfg, pair, art["n_groups"],
                                     art["n_ticks"])
    rep = nsearch.verify_reproducer(art, repro)
    assert rep["tick"] == art["violation"]["tick"]


def test_run_signals_and_scoring_shapes():
    """The searcher's health signals come back as host ints with the
    documented keys, and the coverage key is insensitive to sub-bucket
    jitter but sensitive to a violation."""
    cfg = RaftConfig(**BASE, nemesis=nemesis.gray_mix(16))
    sig = nsearch.run_signals(cfg, 8, 16)
    assert set(sig) == {"unsafe_groups", "elections", "max_leaderless",
                        "committed", "stalled_groups",
                        "dual_leader_groups", "term_spread",
                        "storm_ticks"}
    assert all(isinstance(v, int) for v in sig.values())
    assert sig["unsafe_groups"] == 0
    assert nsearch.near_miss_score(sig) >= 0.0
    bumped = dict(sig, unsafe_groups=1)
    assert nsearch.near_miss_score(bumped) \
        > nsearch.near_miss_score(sig) + 999
    assert nsearch.coverage_key(bumped) != nsearch.coverage_key(sig)
