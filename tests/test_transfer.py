"""Leadership transfer (dissertation §3.10) on the CPU oracle: the
client API hands leadership to a caught-up voter in one election round;
the gate refuses bad targets; transfer works with PreVote on (TimeoutNow
bypasses the pre-ballot). Batched-path parity is pinned by
tests/test_differential.py::test_differential_transfer."""

from __future__ import annotations

from raft_tpu.config import RaftConfig
from raft_tpu.core.cluster import Cluster
from raft_tpu.core.node import LEADER


def _elect(c: Cluster, max_ticks: int = 300) -> int:
    for _ in range(max_ticks):
        if c.leader() is not None:
            return c.leader()
        c.tick()
    raise AssertionError("no leader elected")


def _settle_and_pick_target(c: Cluster):
    _elect(c)
    c.run(30)   # let replication catch everyone up
    lead = c.leader()
    target = (lead + 1) % c.cfg.k
    return lead, target


def test_transfer_moves_leadership_to_target():
    c = Cluster(RaftConfig(seed=90))
    lead, target = _settle_and_pick_target(c)
    assert c.nodes[lead].transfer_leadership(target) is True
    for _ in range(30):
        c.tick()
        if c.leader() == target:
            break
    assert c.leader() == target
    # The new regime commits.
    before = max(n.commit for n in c.nodes)
    c.run(20)
    assert max(n.commit for n in c.nodes) > before


def test_transfer_gate_refusals():
    c = Cluster(RaftConfig(seed=91))
    lead, target = _settle_and_pick_target(c)
    n = c.nodes[lead]
    assert n.transfer_leadership(lead) is None          # self
    # A follower can't initiate.
    assert c.nodes[target].transfer_leadership(lead) is None
    # A lagging target is refused: fake a stale match_index.
    n.match_index[target] = 0
    assert n.transfer_leadership(target) is None


def test_transfer_refuses_non_voter_target():
    c = Cluster(RaftConfig(seed=92))
    lead, victim = _settle_and_pick_target(c)
    full = (1 << c.cfg.k) - 1
    t = c.propose_reconfig(full ^ (1 << victim))
    assert t is not None
    for _ in range(100):
        if c.is_committed(t):
            break
        c.tick()
    assert c.is_committed(t)
    lead = c.leader()
    assert c.nodes[lead].transfer_leadership(victim) is None


def test_transfer_bypasses_prevote():
    """With PreVote on, every peer holds a fresh lease for the current
    leader, so an ordinary campaign by the target would be refused —
    TimeoutNow must bypass the pre-ballot and still win."""
    c = Cluster(RaftConfig(seed=93, prevote=True))
    lead, target = _settle_and_pick_target(c)
    assert c.nodes[target].leader_elapsed < c.cfg.election_min
    assert c.nodes[lead].transfer_leadership(target) is True
    for _ in range(30):
        c.tick()
        if c.leader() == target:
            break
    assert c.leader() == target


def test_timeout_now_ignored_by_candidate():
    """A CANDIDATE already started an election (possibly this tick, via
    a pre-ballot quorum processed earlier in phase D) — TimeoutNow must
    not start a second one, or two RequestVotes per destination would
    share one tick (the dense-mailbox contract violation)."""
    from raft_tpu.core import rpc
    from raft_tpu.core.node import CANDIDATE

    c = Cluster(RaftConfig(seed=95))
    lead, target = _settle_and_pick_target(c)
    n = c.nodes[target]
    n.term += 1
    n.role = CANDIDATE
    term_before = n.term
    sent_before = len(c.transport._outbox)
    n._on_tn_req(rpc.TimeoutNow(rpc.TN_REQ, src=lead, dst=target,
                                term=term_before))
    assert n.term == term_before and n.role == CANDIDATE
    assert len(c.transport._outbox) == sent_before   # no second broadcast


def test_scheduled_transfer_universe_safe_and_live():
    """The deterministic schedule churns leadership; safety checkers
    stay silent and the group keeps committing."""
    cfg = RaftConfig(seed=94, transfer_prob=0.9, transfer_epoch=32)
    c = Cluster(cfg)
    c.run(600)
    assert max(n.commit for n in c.nodes) > 300
    # Leadership actually moved at least once: more than one node has
    # ever been leader (terms advanced beyond the first election).
    assert max(n.term for n in c.nodes) > 1, (
        "transfer schedule never moved leadership — test is vacuous")
