"""The r17 sharded cohort-paging layer (DESIGN.md §16): every mesh
device pages its OWN whole-block window slice host<->HBM under the
unchanged sharded kernel.

The contract under test: sharding the paging must be invisible —
`prun_streamed_sharded` stays bit-identical to the RESIDENT sharded
kernel and the XLA path (full State + Metrics + flight ring) across
the multi-window multi-launch shape — while the modeled ceiling scales
with the device axis (host RAM is a PER-DEVICE allocation: one host
per chip group on a pod), boundary-exact at every N and re-derived
independently by analysis/bytemodel. The copy path (stream_sched)
must round-trip every byte through both the staged and naive commit
paths, split windows into whole 1024-group per-device blocks under the
r08 kleaf rule, and the per-device telemetry (STREAM_MESH_KEYS,
heartbeat lanes) must cover emit + backfill both directions.
"""

from __future__ import annotations

import dataclasses
import io
import json

import numpy as np
import pytest

import conftest  # noqa: F401  (pins the CPU platform before jax loads)

from raft_tpu.config import RaftConfig
from raft_tpu.parallel import cohort, make_mesh, stream_sched
from raft_tpu.sim import checkpoint, pkernel, state
from raft_tpu.sim.run import metrics_init, run
from raft_tpu.utils.trees import trees_equal, trees_equal_why

# The shared fast-tier differential universe (kmesh.faulted_64_cfg's
# shape): crash + partition + drop churn across the cohort windows.
FAULTED = RaftConfig(n_groups=64, k=3, seed=23, drop_prob=0.05,
                     crash_prob=0.2, crash_epoch=16, partition_prob=0.2,
                     partition_epoch=16, log_cap=8, compact_every=4)

ALL_DIALS = dict(pack_bools=True, pack_ring=True, alias_wire=True,
                 wire_hist=False)


def _headline():
    return RaftConfig(seed=42)


# ----------------------------------------------------- residency model


def test_sharded_streamed_ceiling_scales_with_devices():
    """THE r17 acceptance pin: at the headline wire over 64 GiB host
    RAM per device, the modeled sharded-streamed ceiling is exactly
    N x the single-device streamed ceiling — >= the 4x floor at 8
    devices — and, like every ceiling in this repo, the EXACT
    supported() boundary at every device count: one more block tips
    the per-device host share into one more padded block."""
    scfg = dataclasses.replace(_headline(), stream_groups=True)
    one = pkernel.streamed_ceiling_groups(scfg)
    for nd in (1, 2, 4, 8):
        ceil = pkernel.streamed_ceiling_groups(scfg, n_devices=nd)
        assert ceil == nd * one, nd
        assert ceil % pkernel.GB == 0, nd
        assert pkernel.supported(scfg, n_groups=ceil, n_devices=nd), nd
        assert not pkernel.supported(scfg, n_groups=ceil + pkernel.GB,
                                     n_devices=nd), nd
        # The per-device cohort window (not the fleet) must fit HBM.
        assert pkernel.cohort_hbm_bytes(scfg, n_devices=nd) \
            <= pkernel.HBM_LIMIT_BYTES, nd
    assert pkernel.streamed_ceiling_groups(scfg, n_devices=8) >= 4 * one
    # Whole-block per-device split: ceil-divide, never a partial block.
    assert pkernel.stream_blocks_per_device(scfg, 1) == scfg.cohort_blocks
    assert pkernel.stream_blocks_per_device(
        dataclasses.replace(scfg, cohort_blocks=3), 2) == 2


def test_sharded_streamed_supported_boundary_per_device_share():
    """supported() at n_devices budgets the PER-DEVICE host share
    (ceil(G/N), whole padded blocks): a G the single device refuses is
    fine over 8, and the 8-device boundary is where one device's share
    pads past its host allocation."""
    scfg = dataclasses.replace(_headline(), stream_groups=True)
    one = pkernel.streamed_ceiling_groups(scfg)
    assert not pkernel.supported(scfg, n_groups=one + pkernel.GB)
    assert pkernel.supported(scfg, n_groups=one + pkernel.GB, n_devices=8)
    ceil8 = pkernel.streamed_ceiling_groups(scfg, n_devices=8)
    per_block = 4 * pkernel.wire_words_per_group(scfg) * pkernel.GB
    share = -(-((ceil8 + pkernel.GB) // 8) // pkernel.GB) * per_block
    assert share > pkernel.HOST_RAM_LIMIT_BYTES   # why ceil8+GB refuses


def test_byte_model_rederives_sharded_ceiling():
    """The engine-contract auditor's INDEPENDENT derivation agrees at
    every audited layout: hbm.streamed.sharded re-derives the 8-device
    ceiling from dtype x shape, finds it boundary-exact, and clears the
    r17 >= 4x-of-1-device acceptance floor."""
    from raft_tpu.analysis import bytemodel

    for label, cfg in bytemodel.audit_cfgs():
        model = bytemodel.derived_wire_model(cfg)
        assert model["problems"] == [], (label, model["problems"])
        s = model["hbm"]["streamed"]["sharded"]
        assert s["n_devices"] == 8, label
        assert s["boundary_exact"], label
        assert s["speedup_vs_1dev"] >= 4.0, label
        assert s["ceiling_groups"] \
            == 8 * model["hbm"]["streamed"]["ceiling_groups"], label
        assert s["window_hbm_bytes_per_device"] \
            <= pkernel.HBM_LIMIT_BYTES, label


# ------------------------------------------------------------ copy path


def test_sharded_windows_split_into_whole_per_device_blocks():
    """Window geometry: host_wire(pad_to=N*GB) makes every window —
    tail included — split into EQUAL whole-1024-group-block per-device
    slices under the r08 kleaf rule, on the sharding's own index map."""
    nd = 2
    mesh = make_mesh(nd)
    cfg = dataclasses.replace(FAULTED, n_groups=2500, stream_groups=True,
                              cohort_blocks=2)
    host, g = cohort.host_wire(cfg, state.init(cfg, n_groups=2500),
                               pad_to=nd * pkernel.GB)
    assert host[0].shape[-2] % (nd * pkernel.SUB) == 0
    wins = cohort.cohort_windows(cfg, host, n_devices=nd)
    assert len(wins) >= 2
    for s0, s1 in wins:
        for leaf in host:
            slices = stream_sched.device_slices(mesh, leaf, s0, s1)
            assert len(slices) == nd
            spans = sorted(hi - lo for _, (lo, hi) in slices)
            assert spans[0] == spans[-1]            # equal shares
            assert spans[0] % pkernel.SUB == 0      # whole blocks
            covered = sorted((lo, hi) for _, (lo, hi) in slices)
            assert covered[0][0] == 0 and covered[-1][1] == s1 - s0
    # A wire padded for the wrong device count is refused loudly.
    bad, _ = cohort.host_wire(cfg, state.init(cfg, n_groups=2500))
    with pytest.raises(ValueError, match="pad_to"):
        cohort.cohort_windows(cfg, bad, n_devices=nd)


def test_staged_and_naive_put_drain_round_trip_identity():
    """Both commit paths (StagingPool + per-device device_put streams
    vs naive sharded device_put) place identical bytes under identical
    shardings, and drain_window writes every byte back — paging moves
    state, never edits it, tail window and all."""
    import jax

    from raft_tpu.parallel.kmesh import kleaf_spec

    nd = 2
    mesh = make_mesh(nd)
    cfg = dataclasses.replace(FAULTED, n_groups=2500, stream_groups=True,
                              cohort_blocks=2)
    host, g = cohort.host_wire(cfg, state.init(cfg, n_groups=2500),
                               pad_to=nd * pkernel.GB)
    before = [a.copy() for a in host]
    wins = cohort.cohort_windows(cfg, host, n_devices=nd)
    pool = stream_sched.StagingPool(host, wins[0][1] - wins[0][0])
    for i, (s0, s1) in enumerate(wins):
        staged = stream_sched.put_window(host, s0, s1, mesh, pool=pool,
                                         slot=i)
        naive = stream_sched.put_window(host, s0, s1, mesh)
        for a, b, src in zip(staged, naive, host):
            assert a.sharding.spec == kleaf_spec(src)
            assert b.sharding.spec == kleaf_spec(src)
            assert np.array_equal(np.asarray(a), np.asarray(b))
        per_dev: dict = {}
        stream_sched.drain_window(host, staged, s0, s1,
                                  per_device=per_dev)
        assert len(per_dev) == nd   # every device drained its shard
        jax.block_until_ready(naive)
    for i, (a, b) in enumerate(zip(before, host)):
        assert np.array_equal(a, b), i


def test_staging_ablation_reports_both_paths():
    """The copy-path measurement protocol (DESIGN.md §16): the ablation
    pages identical windows through both paths and reports wall + MiB/s
    + the ratio — the probe the driver's TPU column comes from. On CPU
    devices only the protocol is under test, not the bandwidth."""
    mesh = make_mesh(2)
    cfg = dataclasses.replace(_headline(), stream_groups=True,
                              cohort_blocks=1)
    rep = stream_sched.staging_ablation(cfg, mesh, n_windows=2, repeats=1)
    assert rep["n_devices"] == 2 and rep["windows"] == 2
    assert rep["staged_wall_s"] > 0 and rep["naive_wall_s"] > 0
    assert rep["staged_over_naive"] == pytest.approx(
        rep["naive_wall_s"] / rep["staged_wall_s"], rel=1e-3)


# ------------------------------------------------- engine differentials


def test_sharded_streamed_fast_gate_with_telemetry(tmp_path):
    """THE r17 fast gate: one window split over a 2-device mesh, two
    launches per residency, interpret mode — bit-identical to the XLA
    path on full State + Metrics — and the per-device telemetry rides
    along: chunk spans on the sharded-streamed engine lane carry the
    device count, the heartbeat JSONL grows one lane per device
    (`...:c0:d0` / `...:c0:d1`), and stats splits the copy wall per
    device."""
    from raft_tpu.obs import (Heartbeat, Tracer, set_heartbeat,
                              set_tracer, validate_trace)

    nd = 2
    mesh = make_mesh(nd)
    scfg = dataclasses.replace(FAULTED, stream_groups=True,
                               cohort_blocks=1)
    st0 = state.init(FAULTED)
    stx, mx = run(FAULTED, st0, 48, 0, metrics_init(64))
    t = Tracer()
    hb_path = tmp_path / "hb.jsonl"
    prev_t = set_tracer(t)
    prev_hb = set_heartbeat(Heartbeat(str(hb_path), every=1))
    stats: dict = {}
    try:
        stp, mp = cohort.prun_streamed_sharded(
            scfg, st0, 48, mesh, interpret=True, chunk_ticks=24,
            stats=stats)
    finally:
        set_tracer(prev_t)
        set_heartbeat(prev_hb)
    ok, why = trees_equal_why(stx, stp)
    assert ok, why
    ok, why = trees_equal_why(mx, mp, names=list(type(mx)._fields))
    assert ok, why
    # 64 groups pad to nd*GB: one window of one block per device,
    # chunk_ticks=24 over 48 ticks = two launches mid-residency.
    assert stats["cohorts"] == 1 and stats["launches"] == 2
    assert stats["n_devices"] == nd and stats["staging"] is True
    assert [r["device"] for r in stats["per_device"]] \
        == sorted(r["device"] for r in stats["per_device"])
    assert len(stats["per_device"]) == nd
    assert stats["slowest_device"] in [r["device"]
                                       for r in stats["per_device"]]
    for eff in stats["overlap_efficiency_per_device_measured"]:
        assert 0.0 < eff <= 1.0
    obj = t.to_json()
    assert validate_trace(obj) == []
    eng = cohort.sharded_engine(nd)
    chunks = [e for e in obj["traceEvents"] if e["cat"] == "chunk"
              and eng in e["name"]]
    assert len(chunks) == 2
    assert all(e["args"]["devices"] == nd for e in chunks)
    recs = [json.loads(ln) for ln in hb_path.read_text().splitlines()]
    lanes = {r["label"] for r in recs}
    # 64 groups pad to 2 blocks: device 0 holds every live group, so
    # ONLY its lane beats — a padding-only device must not invent one.
    assert f"{eng}:c0:d0" in lanes
    assert f"{eng}:c0:d1" not in lanes
    by_lane = {r["label"]: r for r in recs}
    assert by_lane[f"{eng}:c0:d0"]["engine"] == "pallas"
    # Once live groups span both devices, both lanes beat — off a
    # paged-in window directly (no kernel launch needed).
    cfg2 = dataclasses.replace(FAULTED, n_groups=1500,
                               stream_groups=True, cohort_blocks=1)
    host2, g2 = cohort.host_wire(cfg2, state.init(cfg2),
                                 pad_to=nd * pkernel.GB)
    wins2 = cohort.cohort_windows(cfg2, host2, n_devices=nd)
    win_leaves = stream_sched.put_window(host2, *wins2[0], mesh)
    prev_hb = set_heartbeat(Heartbeat(str(tmp_path / "hb2.jsonl"),
                                      every=1))
    try:
        cohort._heartbeat_sharded(eng, 0, 48, cfg2, win_leaves, g2,
                                  *wins2[0])
    finally:
        set_heartbeat(prev_hb)
    recs2 = [json.loads(ln)
             for ln in (tmp_path / "hb2.jsonl").read_text().splitlines()]
    assert {r["label"] for r in recs2} \
        == {f"{eng}:c0:d0", f"{eng}:c0:d1"}


@pytest.mark.slow
def test_sharded_streamed_multi_window_three_way():
    """THE r17 multi-cohort gate (slow tier: three interpret traces):
    G=2500 pads to 4 blocks over 2 devices, cohort_blocks=2 pages two
    windows of one block per device, chunk_ticks splits each residency
    into two launches — and the sharded-streamed result is
    bit-identical to the RESIDENT sharded kernel (State + Metrics +
    flight ring) AND to the XLA path (State + Metrics)."""
    from raft_tpu.obs import flight_init
    from raft_tpu.parallel import kmesh

    nd, g = 2, 2_500
    mesh = make_mesh(nd)
    cfg = dataclasses.replace(FAULTED, n_groups=g)
    scfg = dataclasses.replace(cfg, stream_groups=True, cohort_blocks=2)
    st0 = state.init(cfg)
    stx, mx = run(cfg, st0, 24, 0, metrics_init(g))
    stk, mk, flk = kmesh.prun_sharded(cfg, st0, 24, mesh, interpret=True,
                                      flight=flight_init(g))
    stats: dict = {}
    sts, ms, fls = cohort.prun_streamed_sharded(
        scfg, st0, 24, mesh, interpret=True, flight=flight_init(g),
        chunk_ticks=12, stats=stats)
    assert stats["cohorts"] == 2 and stats["launches"] == 4
    assert stats["n_devices"] == nd
    assert 0.0 < stats["overlap_efficiency_measured"] <= 1.0
    for ref_st, ref_m, what in ((stx, mx, "vs-xla"),
                                (stk, mk, "vs-resident-sharded")):
        ok, why = trees_equal_why(ref_st, sts)
        assert ok, (what, why)
        ok, why = trees_equal_why(ref_m, ms, names=list(type(ms)._fields))
        assert ok, (what, why)
    ok, why = trees_equal_why(flk, fls)
    assert ok, ("flight-ring", why)


def test_stream_mesh_contracts_clean():
    """The auditor's r17 additions hold on the clean tree: per-device
    ceiling boundaries at 2 and 8 devices, whole-block slice coverage
    through the public stream_sched seam, and the kleaf placement
    rule."""
    from raft_tpu.analysis import contracts

    assert contracts.streaming_problems() == []


# ------------------------------------------------------------ checkpoint


def test_checkpoint_hops_residency_and_mesh_axes():
    """Cross-(residency x mesh) coverage: a file saved by a 1-device
    STREAMED run loads sharded onto an 8-device mesh under the
    sharded-streamed knobs (and the loaded G admits 8-device paging
    windows), and a file saved from an 8-device-sharded state loads
    back under the 1-device resident cfg — both directions
    bit-identical. Residency knobs never block the hop; a semantic
    mismatch still refuses."""
    from raft_tpu import parallel

    cfg = FAULTED
    scfg = dataclasses.replace(cfg, stream_groups=True, cohort_blocks=1)
    mesh8 = make_mesh(8)
    st = state.init(cfg)   # 64 groups: 8 blocks when padded to 8*GB
    met = metrics_init(64)

    # 1-dev streamed ckpt -> 8-dev sharded-streamed.
    buf = io.BytesIO()
    checkpoint.save(buf, st, 7, metrics=met, cfg=scfg)
    buf.seek(0)
    st2, t2, met2 = checkpoint.load(
        buf, cfg=scfg, sharding=parallel.state_sharding(mesh8))
    assert t2 == 7 and trees_equal(st, st2) and trees_equal(met, met2)
    g = int(st2.alive_prev.shape[0])
    assert pkernel.supported(scfg, n_groups=g, n_devices=8)
    host, _ = cohort.host_wire(scfg, st2, pad_to=8 * pkernel.GB)
    wins = cohort.cohort_windows(scfg, host, n_devices=8)
    assert wins and all((s1 - s0) % (8 * pkernel.SUB) == 0
                        for s0, s1 in wins)

    # 8-dev sharded state -> 1-dev resident cfg.
    st_sh = parallel.shard_state(st, mesh8)
    buf = io.BytesIO()
    checkpoint.save(buf, st_sh, 7, metrics=met, cfg=scfg)
    buf.seek(0)
    st3, t3, _ = checkpoint.load(buf, cfg=cfg)
    assert t3 == 7 and trees_equal(st, st3)
    # A SEMANTIC mismatch still refuses, mesh and residency aside.
    buf.seek(0)
    with pytest.raises(ValueError, match="cfg mismatch"):
        checkpoint.load(buf, cfg=dataclasses.replace(scfg, seed=99))


# ------------------------------------------------------------- manifests


def test_stream_mesh_keys_present_from_birth_and_backfilled():
    """r17 satellite: STREAM_MESH_KEYS ride every manifest record from
    birth (null until stamped), history backfills them onto pre-r17
    records, the emit-side and backfill-side registries are proven
    equal, and the auditor names a side that forgot them — both
    directions."""
    from raft_tpu.analysis import contracts
    from raft_tpu.obs import history
    from raft_tpu.obs.manifest import STREAM_MESH_KEYS, emit_manifest

    assert tuple(history.R17_MANIFEST_KEYS) == tuple(STREAM_MESH_KEYS)
    rec = emit_manifest("probe", FAULTED, path="-")
    for k in STREAM_MESH_KEYS:
        assert k in rec and rec[k] is None
    old = {k: v for k, v in rec.items() if k not in STREAM_MESH_KEYS}
    back = history.backfill_record(old)
    for k in STREAM_MESH_KEYS:
        assert k in back and back[k] is None
    assert contracts.manifest_problems() == []

    class _NoMeshManifest:

        @staticmethod
        def emit_manifest(segment, cfg, device=None, path=None, **fields):
            rec = emit_manifest(segment, cfg, device=device, path="-",
                                **fields)
            return {k: v for k, v in rec.items()
                    if k not in STREAM_MESH_KEYS}

    probs = contracts.manifest_problems(manifest_mod=_NoMeshManifest)
    assert any("stream_devices" in p for p in probs)

    class _NoMeshHistory:

        @staticmethod
        def backfill_record(rec):
            out = history.backfill_record(rec)
            for k in STREAM_MESH_KEYS:
                out.pop(k, None)
            return out   # forgot the r17 keys

    probs = contracts.manifest_problems(history_mod=_NoMeshHistory)
    assert any("stream_slowest_device" in p for p in probs)


def test_stream_segment_fields_mesh_split_and_null_rule():
    """The roofline producer stamps STREAM_KEYS + STREAM_MESH_KEYS
    exactly: per-device predicted/measured splits and the slowest
    device on streamed segments, null mesh keys on RESIDENT segments
    (a resident run paged on zero devices — even a sharded one), and
    the per-device predicted model agrees with overlap_efficiency."""
    from raft_tpu.obs import roofline
    from raft_tpu.obs.manifest import STREAM_KEYS, STREAM_MESH_KEYS

    scfg = dataclasses.replace(_headline(), stream_groups=True)
    on = roofline.stream_segment_fields(
        scfg, measured=0.8125, chunk_ticks=200, n_devices=4,
        per_device_measured=[1.0, 0.9, 1.0, 0.8], slowest_device=3)
    assert set(on) == set(STREAM_KEYS) | set(STREAM_MESH_KEYS)
    assert on["stream_devices"] == 4
    assert on["stream_blocks_per_device"] == 1
    assert on["overlap_efficiency_per_device_measured"] \
        == [1.0, 0.9, 1.0, 0.8]
    assert on["stream_slowest_device"] == 3
    assert len(on["overlap_efficiency_per_device_predicted"]) == 4
    for eff in on["overlap_efficiency_per_device_predicted"]:
        assert 0.0 < eff <= 1.0
    # Resident segment: the mesh keys must not claim paging devices,
    # even when the kernel itself ran sharded over 8 chips.
    off = roofline.stream_segment_fields(_headline(), n_devices=8)
    assert off["stream_devices"] is None
    assert off["stream_blocks_per_device"] is None
    assert off["overlap_efficiency_per_device_predicted"] is None
    assert off["overlap_efficiency_per_device_measured"] is None
    assert off["stream_slowest_device"] is None
    # The per-device prediction is the single-device window model
    # evaluated on each device's equal slice: same value, N lanes.
    ov = roofline.overlap_efficiency(scfg, chunk_ticks=200, n_devices=4)
    assert ov["n_devices"] == 4
    assert ov["window_groups"] \
        == ov["window_groups_per_device"] * 4
    assert ov["overlap_efficiency_per_device_predicted"] \
        == [round(ov["overlap_efficiency_predicted"], 6)] * 4


def test_sharded_engine_classification():
    """`pallas-streamed-sharded-Ndev` strings classify as "pallas"
    (prefix rule) so the history regression gate prices them with the
    kernel byte model — and the fallback string a mismatch leaves
    behind still classifies as the XLA engine that stood."""
    from raft_tpu.obs.history import engine_class

    assert cohort.sharded_engine(8) == "pallas-streamed-sharded-8dev"
    assert engine_class("pallas-streamed-sharded-8dev") == "pallas"
    assert engine_class(cohort.ENGINE) == "pallas"
    assert engine_class("xla-scan (streamed mismatch!)") == "xla"
