"""Single-server membership change on the CPU oracle (SURVEY.md §2
row 16, DESIGN.md §2b): add/remove voters via config log entries,
voters-aware quorums, removed-leader step-down, and the single-server
gating rules. Safety checkers (election safety, commit identity) run on
every tick via the Cluster harness."""

from __future__ import annotations

import pytest

from raft_tpu.config import CONFIG_FLAG, RaftConfig
from raft_tpu.core.cluster import Cluster
from raft_tpu.core.node import FOLLOWER, LEADER


def _elect(c: Cluster, max_ticks: int = 200) -> int:
    for _ in range(max_ticks):
        if c.leader() is not None:
            return c.leader()
        c.tick()
    raise AssertionError("no leader elected")


def _commit(c: Cluster, ticket, max_ticks: int = 200):
    for _ in range(max_ticks):
        if c.is_committed(ticket):
            return
        c.tick()
    raise AssertionError(f"ticket {ticket} never committed")


def _settle(c: Cluster, ticks: int = 30):
    c.run(ticks)
    _elect(c)


FULL = 0b11111   # k = 5


def test_remove_follower_commits_and_shrinks_quorum():
    c = Cluster(RaftConfig(seed=60))
    _settle(c)
    lead = c.leader()
    victim = (lead + 1) % 5
    t = c.propose_reconfig(FULL ^ (1 << victim))
    assert t is not None and t[1] == (CONFIG_FLAG | (FULL ^ (1 << victim)))
    _commit(c, t)
    voters, _ = c.nodes[lead].current_config()
    assert voters == FULL ^ (1 << victim)
    # Liveness with the removed node AND one voter down: 3 of 4 voters
    # remain, which is a majority of the new config (but would NOT have
    # been one worth counting under the old 5-node config's rules if the
    # removed node were still required).
    other = (lead + 2) % 5
    if other == victim:
        other = (lead + 3) % 5
    c.alive_fn = lambda tk: [i != victim and i != other for i in range(5)]
    before = max(n.commit for n in c.nodes)
    c.run(60)
    assert max(n.commit for n in c.nodes) > before


def test_removed_node_never_starts_elections():
    c = Cluster(RaftConfig(seed=61))
    _settle(c)
    lead = c.leader()
    victim = (lead + 1) % 5
    t = c.propose_reconfig(FULL ^ (1 << victim))
    assert t is not None
    _commit(c, t)
    # Partition the removed node away so it would, as a voter, campaign.
    c.transport.link_filter = (
        lambda tk, s, d, v=victim: s != v and d != v)
    terms_before = c.nodes[victim].term
    for _ in range(200):
        c.tick()
        assert c.nodes[victim].role == FOLLOWER
    # It never bumped its own term through timeouts.
    assert c.nodes[victim].term == terms_before


def test_remove_leader_steps_down_and_regime_continues():
    c = Cluster(RaftConfig(seed=62))
    _settle(c)
    old = c.leader()
    t = c.propose_reconfig(FULL ^ (1 << old))
    assert t is not None
    _commit(c, t)
    # Step-down happens in the commit tick's phase A.
    assert c.nodes[old].role == FOLLOWER
    # A new leader emerges from the remaining voters and commits.
    for _ in range(200):
        c.tick()
        lead = c.leader()
        if lead is not None and lead != old:
            break
    assert lead is not None and lead != old
    before = max(n.commit for n in c.nodes)
    c.run(40)
    assert max(n.commit for n in c.nodes) > before


def test_add_server_back():
    c = Cluster(RaftConfig(seed=63))
    _settle(c)
    lead = c.leader()
    victim = (lead + 1) % 5
    t = c.propose_reconfig(FULL ^ (1 << victim))
    assert t is not None
    _commit(c, t)
    lead = _elect(c)
    t2 = c.propose_reconfig(FULL)
    assert t2 is not None
    _commit(c, t2)
    voters, _ = c.nodes[lead].current_config()
    assert voters == FULL
    # The re-added node campaigns and can be elected again eventually.
    assert c.nodes[victim].is_voter()


def test_gate_rejects_double_delta_and_inflight():
    c = Cluster(RaftConfig(seed=64))
    _settle(c)
    lead = c.leader()
    # Two-server delta: rejected.
    assert c.nodes[lead].propose_config(FULL ^ 0b11) is None
    # Valid single-server change...
    t = c.propose_reconfig(FULL ^ 0b1 if lead != 0 else FULL ^ 0b10)
    assert t is not None
    # ...blocks a second one until the first commits.
    mask2 = FULL ^ (1 << ((lead + 2) % 5))
    assert c.nodes[lead].propose_config(mask2) is None
    _commit(c, t)


def test_gate_requires_current_term_commit():
    """A fresh leader must commit an entry of its own term before any
    membership change (single-server bugfix)."""
    cfg = RaftConfig(seed=65, cmds_per_tick=0)
    c = Cluster(cfg)
    old = _elect(c)
    tk = c.propose(42)
    assert tk is not None
    _commit(c, tk)
    # Depose the leader; elect a new one with no current-term commit yet.
    c.alive_fn = lambda t, dead=old: [i != dead for i in range(5)]
    for _ in range(300):
        c.tick()
        lead = c.leader()
        if lead is not None and lead != old:
            break
    assert lead is not None and lead != old
    n = c.nodes[lead]
    if n.term_at(n.commit) != n.term:
        # Gate must hold while the takeover entry is still uncommitted.
        assert n.propose_config(FULL ^ (1 << old)) is None
    # Once a current-term entry commits, the gate opens.
    tk2 = c.propose(43)
    assert tk2 is not None
    _commit(c, tk2)
    assert c.nodes[c.leader()].propose_config(
        FULL ^ (1 << ((c.leader() + 1) % 5))) is not None


def test_scheduled_reconfig_universe_is_safe_and_live():
    """The deterministic schedule drives membership churn; harness
    invariants (election safety, commit identity) must hold throughout
    and the group must keep committing."""
    cfg = RaftConfig(seed=66, reconfig_prob=0.9, reconfig_epoch=32,
                     crash_prob=0.15, crash_epoch=48, drop_prob=0.02)
    c = Cluster(cfg)
    c.run(1200)   # safety checkers raise on any violation
    assert max(n.commit for n in c.nodes) > 100
    # The schedule actually changed membership at least once.
    masks = {n.current_config()[0] for n in c.nodes}
    assert (masks != {FULL}
            or any(n.snap_voters != FULL for n in c.nodes)), (
        "reconfig schedule never fired — test is vacuous")


def test_snapshot_carries_config():
    """A compaction folding a config entry must preserve it via
    snap_voters, and InstallSnapshot must transfer it to laggards."""
    cfg = RaftConfig(seed=67, compact_every=4, log_cap=16)
    c = Cluster(cfg)
    _settle(c)
    lead = c.leader()
    victim = (lead + 1) % 5
    # Crash the victim BEFORE the change so it must learn it by snapshot.
    c.alive_fn = lambda tk, v=victim: [i != v for i in range(5)]
    c.run(2)
    new_mask = FULL ^ (1 << victim)
    t = c.propose_reconfig(new_mask)
    assert t is not None
    _commit(c, t)
    # Run long enough that compaction passes the config entry.
    c.run(80)
    lead = c.leader()
    assert c.nodes[lead].snap_voters == new_mask
    # Revive the victim; it catches up (possibly via InstallSnapshot)
    # and learns it is no longer a voter.
    c.alive_fn = None
    c.run(120)
    assert not c.nodes[victim].is_voter()
    assert c.nodes[victim].current_config()[0] == new_mask


@pytest.mark.parametrize("seed", [70, 71, 72])
def test_no_split_brain_across_change(seed):
    """Heavy churn + reconfig: the per-term unique-leader checker and
    the commit-identity checker must stay silent."""
    cfg = RaftConfig(seed=seed, reconfig_prob=0.8, reconfig_epoch=24,
                     crash_prob=0.25, crash_epoch=32,
                     partition_prob=0.25, partition_epoch=40,
                     drop_prob=0.05)
    Cluster(cfg).run(800)
