"""Sharded-kernel differential gate (raft_tpu/parallel/kmesh.py): the
8-way shard_map'd Pallas fused-chunk engine must be bit-identical to
the unsharded kernel AND the XLA path on a faulted 64-group universe,
with the psum'd boundary counters equal to the host-side fold — the
in-repo multi-device evidence for the DESIGN.md §9 engine, on the
virtual 8-CPU mesh (conftest) in interpret mode.

The universe is `kmesh.faulted_64_cfg()` — the ONE config this suite,
the dryrun's `dryrun_pallas_mesh` segment, and multichip_sweep share
(and tests/test_pkernel.py's safety-parity test matches), so the
unsharded-kernel and XLA reference programs hit the warm compile cache
and all the drivers share the sharded program."""

from __future__ import annotations

import numpy as np
import pytest

from conftest import trees_equal as _trees_equal
from raft_tpu import parallel, sim
from raft_tpu.config import RaftConfig
from raft_tpu.parallel import kmesh
from raft_tpu.sim import pkernel
from raft_tpu.sim.run import run, unsafe_groups

CFG = kmesh.faulted_64_cfg()


def test_supported_is_mesh_aware():
    """The HBM half of the predicate: a group count one chip cannot
    hold (2x wire bytes > 16 GiB) is rejected at n_devices=1 and
    admitted once enough devices share it; the legacy 1-arg form keeps
    meaning 'per-block VMEM + k fit' only."""
    cfg = RaftConfig(seed=42)
    assert pkernel.supported(cfg)
    bpg = 4 * pkernel.wire_words_per_group(cfg)
    ceiling = pkernel.HBM_LIMIT_BYTES // (2 * bpg)
    too_many = 2 * ceiling
    assert not pkernel.supported(cfg, n_groups=too_many, n_devices=1)
    assert pkernel.supported(cfg, n_groups=too_many, n_devices=8)
    # hbm_bytes models whole padded blocks per device.
    assert pkernel.hbm_bytes(cfg, 1, 1) == 2 * bpg * pkernel.GB
    assert pkernel.hbm_bytes(cfg, 8 * pkernel.GB, 8) \
        == pkernel.hbm_bytes(cfg, pkernel.GB, 1)


def test_kinit_pad_to_validates_and_pads():
    st0 = sim.init(CFG)
    with pytest.raises(ValueError, match="multiple"):
        pkernel.kinit(CFG, st0, pad_to=pkernel.GB + 1)
    leaves, g = pkernel.kinit(CFG, st0, pad_to=8 * pkernel.GB)
    assert g == 64
    assert leaves[0].shape[-2] * leaves[0].shape[-1] == 8 * pkernel.GB


def test_wire_byte_model_matches_real_leaves():
    """The HBM cost model is pinned to REALITY, not to itself: summing
    the actual kinit wire-leaf elements per padded group must equal
    wire_words_per_group, flight off and on. A future wire leaf (the
    way r07 added the flight ring) that is not taught to the model
    fails here instead of silently skewing supported()'s G ceiling."""
    from raft_tpu.obs import flight_init

    st0 = sim.init(CFG)
    for flight in (None, flight_init(64)):
        leaves, _ = pkernel.kinit(CFG, st0, flight=flight)
        actual = sum(int(np.prod(a.shape)) for a in leaves) // pkernel.GB
        model = pkernel.wire_words_per_group(
            CFG, with_flight=flight is not None)
        assert actual == model, (
            f"wire model {model} words/group != real leaves {actual} "
            f"(flight={'on' if flight is not None else 'off'})")


def test_sharded_kernel_matches_unsharded_and_xla():
    """The tentpole gate: one 48-tick sharded launch ends bit-identical
    to both references on full State + Metrics; the wire leaves really
    live on 8 devices; kglobal's psum verdicts equal the host fold."""
    st0 = sim.init(CFG)
    stx, mx = run(CFG, st0, 48)
    stp, mp = pkernel.prun(CFG, st0, 48, interpret=True)

    mesh = parallel.make_mesh(8)
    leaves, g = kmesh.kinit_sharded(CFG, st0, mesh)
    assert g == 64
    shard_devs = {s.device for s in leaves[0].addressable_shards}
    assert len(shard_devs) == 8, "wire leaves are not actually sharded"
    leaves = kmesh.kstep_sharded(CFG, leaves, 0, 48, mesh, interpret=True)
    sts, ms = pkernel.kfinish(CFG, leaves, g)

    assert _trees_equal(stx, stp) and _trees_equal(mx, mp)
    assert _trees_equal(stx, sts), "sharded kernel diverged from xla"
    assert _trees_equal(mx, ms), "sharded kernel metrics diverged"
    assert int(ms.elections) > 0, "no elections - differential is vacuous"
    assert unsafe_groups(ms) == 0

    gm = kmesh.kglobal_sharded(CFG, leaves, g, mesh)
    assert int(gm.rounds) == int(np.asarray(ms.committed)
                                 .astype(np.int64).sum())
    assert int(gm.elections) == int(ms.elections)
    assert int(gm.max_latency) == int(ms.max_latency)
    assert int(gm.unsafe) == 0
    assert np.array_equal(np.asarray(gm.hist), np.asarray(ms.hist))


def test_sharded_chunk_boundaries_invisible():
    """Two 24-tick sharded launches == one unbroken 48-tick XLA run:
    the widened wire state crosses the shard_map + launch boundary
    intact, and advancing t0 reuses ONE compiled sharded program (the
    property the bench's timed region rides)."""
    st0 = sim.init(CFG)
    stx, mx = run(CFG, st0, 48)
    mesh = parallel.make_mesh(8)
    leaves, g = kmesh.kinit_sharded(CFG, st0, mesh)
    leaves = kmesh.kstep_sharded(CFG, leaves, 0, 24, mesh, interpret=True)
    leaves = kmesh.kstep_sharded(CFG, leaves, 24, 24, mesh, interpret=True)
    sts, ms = pkernel.kfinish(CFG, leaves, g)
    assert _trees_equal(stx, sts)
    assert _trees_equal(mx, ms)


def test_prun_sharded_rejects_over_budget_shapes():
    """prun_sharded refuses a shape whose per-device HBM footprint
    cannot fit, naming the budget — before any device allocation."""
    cfg = RaftConfig(seed=42)
    bpg = 4 * pkernel.wire_words_per_group(cfg)
    too_many = 4 * (pkernel.HBM_LIMIT_BYTES // (2 * bpg))
    mesh = parallel.make_mesh(2)

    class FakeState:   # only .alive_prev.shape[0] is consulted pre-raise
        class alive_prev:
            shape = (too_many, 1)

    with pytest.raises(ValueError, match="HBM"):
        kmesh.prun_sharded(cfg, FakeState(), 1, mesh)