"""The r13 packed-wire layer (DESIGN.md §13): bit-packed bools, delta-
encoded ring terms, input/output aliasing, and the telemetry dials.

The contract under test: every layout dial is WIRE-ONLY. Packing and
unpacking happen at chunk boundaries, so a packed kernel must stay
bit-identical to the XLA path on the full State pytree and (histogram
dial aside) the full Metrics pytree; the modeled single-chip ceiling
must re-derive through all three byte accountings at the packed sizes
(with the 8,308 / 11,056 B/group r12 baselines preserved as the
off-path pins); and checkpoints must be layout-blind in both
directions (a packed run resumes a pre-r13 file and vice versa).
"""

from __future__ import annotations

import dataclasses
import io
import json

import numpy as np
import pytest

import conftest  # noqa: F401  (pins the CPU platform before jax loads)
import jax.numpy as jnp

from raft_tpu.config import LAYOUT_FIELDS, RaftConfig
from raft_tpu.sim import checkpoint, pkernel, state
from raft_tpu.sim.run import metrics_init, run
from raft_tpu.utils.trees import trees_equal, trees_equal_why

# The shared fast-tier differential universe (kmesh.faulted_64_cfg's
# shape): crash + partition + drop churn so restarts, truncations and
# ring churn actually exercise the packed lanes.
FAULTED = RaftConfig(n_groups=64, k=3, seed=23, drop_prob=0.05,
                     crash_prob=0.2, crash_epoch=16, partition_prob=0.2,
                     partition_epoch=16, log_cap=8, compact_every=4)

PACKED = dict(pack_bools=True, pack_ring=True)


def _headline():
    return RaftConfig(seed=42)


def _clients():
    return dataclasses.replace(_headline(), sessions=True, cmds_per_tick=0,
                               client_rate=0.2, client_slots=4,
                               client_retry_backoff=8)


# ------------------------------------------------------------ byte model


def test_packed_wire_models_pinned():
    """The new modeled sizes, pinned EXACTLY (the r13 analogue of the
    8,308/11,056 pins): packing shaves 1,172 B/group at the headline
    config (856 B of bit-packed bools + 316 B of ring deltas) and the
    same 1,172 B on the client universe; the off-path baselines are
    untouched."""
    off, on = _headline(), dataclasses.replace(_headline(), **PACKED)
    assert 4 * pkernel.wire_words_per_group(off) == 8_308
    assert 4 * pkernel.wire_words_per_group(on) == 7_136
    c_off = _clients()
    c_on = dataclasses.replace(c_off, **PACKED)
    assert 4 * pkernel.wire_words_per_group(c_off) == 11_056
    assert 4 * pkernel.wire_words_per_group(c_on) == 9_884
    # Telemetry dials: hist rows −2,048 B, flight ring −1,536 B.
    ceiling_cfg = dataclasses.replace(on, wire_hist=False)
    assert 4 * pkernel.wire_words_per_group(ceiling_cfg) == 7_136 - 2_048
    assert 4 * pkernel.wire_words_per_group(
        ceiling_cfg, with_flight=False) == 7_136 - 2_048 - 1_536


def test_alias_halves_residency_and_ceiling_multiplies():
    """hbm_bytes under alias_wire is exactly half the no-donation
    model, and the full dial stack clears the ISSUE acceptance bar:
    modeled single-chip ceiling >= 2.5x the 1.03M-group r12 baseline
    at the headline config."""
    off = _headline()
    aliased = dataclasses.replace(off, alias_wire=True)
    g = 4 * pkernel.GB
    assert pkernel.hbm_bytes(aliased, g) * 2 == pkernel.hbm_bytes(off, g)
    base_ceiling = pkernel.hbm_ceiling_groups(off)
    assert base_ceiling == 1_033_216   # the DESIGN.md §9 figure
    all_dials = dataclasses.replace(off, alias_wire=True, wire_hist=False,
                                    **PACKED)
    full = pkernel.hbm_ceiling_groups(all_dials, with_flight=False)
    assert full >= 2.5 * base_ceiling
    # Every ceiling stays the exact supported() boundary.
    assert pkernel.supported(all_dials, n_groups=full, with_flight=False)
    assert not pkernel.supported(all_dials, n_groups=full + pkernel.GB,
                                 with_flight=False)


def test_packed_wire_model_matches_real_leaves():
    """The three-accounting reconciliation at the packed sizes: real
    kinit leaf elements == the packed registry == the independently
    derived byte model, flight on and off, for every audited layout."""
    from raft_tpu import sim
    from raft_tpu.analysis import bytemodel
    from raft_tpu.obs import flight_init

    for label, cfg in bytemodel.audit_cfgs():
        for wf in (True, False):
            model = bytemodel.derived_wire_model(cfg, with_flight=wf)
            assert model["problems"] == [], (label, wf, model["problems"])
    cfg = dataclasses.replace(FAULTED, **PACKED)
    st0 = sim.init(cfg, n_groups=64)
    for flight in (None, flight_init(64)):
        leaves, _ = pkernel.kinit(cfg, st0, flight=flight)
        actual = sum(int(np.prod(a.shape)) for a in leaves) // pkernel.GB
        assert actual == pkernel.wire_words_per_group(
            cfg, with_flight=flight is not None)


def test_roofline_tracks_packed_byte_model():
    """Satellite: predicted bytes/tick follows the packed model with no
    second accounting — packing on AND off (the off path IS the 8,308 /
    11,056 pin), and the XLA resident model is layout-blind (packing
    changes the kernel wire, not what the scan keeps resident)."""
    from raft_tpu.obs import roofline

    for cfg, pin in ((_headline(), 8_308), (_clients(), 11_056)):
        packed = dataclasses.replace(cfg, **PACKED)
        r_off = roofline.roofline(cfg, 100_000, "pallas-fused-chunk",
                                  chunk_ticks=200, flops=False)
        r_on = roofline.roofline(packed, 100_000, "pallas-fused-chunk",
                                 chunk_ticks=200, flops=False)
        assert r_off["wire_bytes_per_group"] == pin
        assert r_on["wire_bytes_per_group"] \
            == 4 * pkernel.wire_words_per_group(packed)
        # Traffic model: the wire crosses HBM in AND out once per chunk
        # regardless of aliasing (aliasing halves residency, not moves).
        padded = -(-100_000 // pkernel.GB) * pkernel.GB
        want = 2 * r_on["wire_bytes_per_group"] * padded
        assert abs(r_on["bytes_per_tick_per_chip"] * 200 - want) \
            < 1e-6 * want
        x_off = roofline.roofline(cfg, 100_000, "xla-scan", flops=False)
        x_on = roofline.roofline(packed, 100_000, "xla-scan", flops=False)
        assert x_on["bytes_per_tick_per_chip"] \
            == x_off["bytes_per_tick_per_chip"]


# ------------------------------------------------------- encode/decode


def test_pack_unpack_round_trip_all_features():
    """_pack_wire/_unpack_wire are exact inverses on a synthetic wire
    with every gated feature on (prevote + transfer + reads + clients:
    12 bool mailbox leaves -> 2 shared words per dst at k=3)."""
    from raft_tpu import sim

    cfg = dataclasses.replace(
        FAULTED, prevote=True, transfer_prob=0.5, read_every=4,
        sessions=True, cmds_per_tick=0, client_rate=0.3, client_slots=2,
        **PACKED)
    flat = pkernel._to_kstate(cfg, sim.init(cfg, n_groups=128))
    names = pkernel._unpacked_names(cfg)
    booly = set(pkernel._MB_BOOL) | {"votes", "alive_prev"}
    synth = []
    for i, (n, a) in enumerate(zip(names, flat)):
        v = (np.arange(a.size, dtype=np.int64) * (3 * i + 7)) % 11
        if n in booly:
            v = v % 2
        synth.append(jnp.asarray(v.reshape(a.shape), jnp.int32))
    packed = pkernel._pack_wire(cfg, synth)
    assert len(packed) == pkernel._n_state_leaves(cfg)
    back, aux = pkernel._unpack_wire(cfg, packed)
    assert set(aux) == {"ring_ov"}
    assert int(np.asarray(aux["ring_ov"]).sum()) == 0
    for n, a, b in zip(names, synth, back):
        assert np.array_equal(np.asarray(a), np.asarray(b)), n


def test_kinit_kfinish_round_trip_packed():
    """A mid-run state survives kinit -> kfinish exactly under every
    dial combination (the host-side halves of the chunk boundary)."""
    st0 = state.init(FAULTED)
    st, m = run(FAULTED, st0, 40)
    for knobs in (dict(pack_bools=True), dict(pack_ring=True), PACKED,
                  dict(wire_hist=False, **PACKED)):
        cfg = dataclasses.replace(FAULTED, **knobs)
        leaves, g = pkernel.kinit(cfg, st, m)
        st2, _ = pkernel.kfinish(cfg, leaves, g, m)
        ok, why = trees_equal_why(st, st2)
        assert ok, (knobs, why)


def test_ring_overflow_refused_loudly():
    """A >= 2^16 in-group term spread cannot be 16-bit delta-encoded:
    kfinish must raise naming pack_ring, never return silently wrong
    terms."""
    cfg = dataclasses.replace(FAULTED, pack_ring=True)
    st = state.init(FAULTED)
    lt = np.asarray(st.nodes.log_term).copy()
    lt[0, 0, 0] = 1 << 17          # spread 2^17 vs the zeros elsewhere
    st = st._replace(nodes=st.nodes._replace(log_term=jnp.asarray(lt)))
    leaves, g = pkernel.kinit(cfg, st)
    with pytest.raises(ValueError, match="pack_ring"):
        pkernel.kfinish(cfg, leaves, g)


# ------------------------------------------------- kernel differentials


def test_packed_kernel_bit_identical():
    """THE r13 gate: the packed kernel (bools + ring deltas), chunked
    across two launches so the in-kernel re-encode path runs, stays
    bit-identical to the XLA path on full State AND full Metrics over
    the faulted universe."""
    cfg = dataclasses.replace(FAULTED, **PACKED)
    st0 = state.init(FAULTED)
    stx, mx = run(FAULTED, st0, 48, 0, metrics_init(64))
    leaves, g = pkernel.kinit(cfg, st0)
    leaves = pkernel.kstep(cfg, leaves, 0, 24, interpret=True)
    leaves = pkernel.kstep(cfg, leaves, 24, 24, interpret=True)
    stp, mp = pkernel.kfinish(cfg, leaves, g)
    ok, why = trees_equal_why(stx, stp)
    assert ok, why
    ok, why = trees_equal_why(mx, mp, names=list(type(mx)._fields))
    assert ok, why


def test_alias_wire_flag_bit_identical():
    """cfg.alias_wire routes through the donating jit twin (compiled
    path) and must be a pure layout decision — interpret-mode results
    are bit-identical with the flag on."""
    cfg = dataclasses.replace(FAULTED, alias_wire=True, **PACKED)
    st0 = state.init(FAULTED)
    stx, mx = run(FAULTED, st0, 48, 0, metrics_init(64))
    stp, mp = pkernel.prun(cfg, st0, 48, interpret=True)
    assert trees_equal(stx, stp)
    assert trees_equal(mx, mp)


def test_wire_hist_dial_state_exact_hist_passthrough():
    """wire_hist=False: the State stays bit-identical, every non-row
    metric lane stays bit-identical, and the histogram rows pass
    through untouched (the kernel tracked nothing) — telemetry as a
    dial, with the cost visible only in the byte model."""
    cfg = dataclasses.replace(FAULTED, wire_hist=False)
    st0 = state.init(FAULTED)
    stx, mx = run(FAULTED, st0, 48, 0, metrics_init(64))
    leaves, g = pkernel.kinit(cfg, st0)
    assert len(leaves) == pkernel._n_state_leaves(cfg) \
        + pkernel._n_metric_leaves(cfg)
    assert "hist" not in pkernel._active_metric_leaves(cfg)
    stp, mp = pkernel.prun(cfg, st0, 48, interpret=True)
    assert trees_equal(stx, stp)
    for lane in ("committed", "leaderless", "elections", "max_latency",
                 "safety"):
        assert np.array_equal(np.asarray(getattr(mx, lane)),
                              np.asarray(getattr(mp, lane))), lane
    assert np.all(np.asarray(mp.hist) == 0)   # pass-through of the base


# ------------------------------------------------------------ checkpoint


def test_checkpoint_layout_blind_both_directions():
    """config.LAYOUT_FIELDS never block a resume: a file saved under
    the packed layout loads under the default one and vice versa, and
    a pre-r13 file (embedded cfg has no layout keys at all) loads
    under a packed cfg. Semantic mismatches still refuse."""
    cfg_off = FAULTED
    cfg_on = dataclasses.replace(FAULTED, alias_wire=True,
                                 wire_hist=False, **PACKED)
    st = state.init(cfg_off, n_groups=4)
    met = metrics_init(4)
    for save_cfg, load_cfg in ((cfg_off, cfg_on), (cfg_on, cfg_off)):
        buf = io.BytesIO()
        checkpoint.save(buf, st, 9, metrics=met, cfg=save_cfg)
        buf.seek(0)
        st2, t2, met2 = checkpoint.load(buf, cfg=load_cfg)
        assert t2 == 9 and trees_equal(st, st2) and trees_equal(met, met2)
    # Pre-r13 file: strip the layout keys from the embedded cfg dict.
    buf = io.BytesIO()
    checkpoint.save(buf, st, 9, metrics=met, cfg=cfg_off)
    buf.seek(0)
    with np.load(buf) as z:
        data = {k: z[k] for k in z.files}
    saved = json.loads(bytes(data["__cfg__"]).decode())
    for k in LAYOUT_FIELDS:
        assert k in saved   # the strip below must actually strip
        saved.pop(k)
    data["__cfg__"] = np.bytes_(json.dumps(saved, sort_keys=True))
    buf = io.BytesIO()
    np.savez(buf, **data)
    buf.seek(0)
    st2, t2, _ = checkpoint.load(buf, cfg=cfg_on)
    assert t2 == 9 and trees_equal(st, st2)
    # A SEMANTIC mismatch still refuses, layout knobs notwithstanding.
    buf.seek(0)
    with pytest.raises(ValueError, match="cfg mismatch"):
        checkpoint.load(buf, cfg=dataclasses.replace(cfg_on, seed=99))


def test_engine_hop_packed_wire(tmp_path):
    """XLA -> checkpoint -> PACKED kernel -> checkpoint -> XLA: the
    engines agree across a layout change mid-run (the r13 form of the
    r05 engine-hop test)."""
    cfg_on = dataclasses.replace(FAULTED, **PACKED)
    st0 = state.init(FAULTED)
    stx, _ = run(FAULTED, st0, 32)
    p = tmp_path / "hop.npz"
    checkpoint.save(p, st0, 0, cfg=FAULTED)
    st_loaded, t0, _ = checkpoint.load(p, cfg=cfg_on)
    stp, _ = pkernel.prun(cfg_on, st_loaded, 32, t0=t0, interpret=True)
    assert trees_equal(stx, stp)


# ------------------------------------------------------------- manifests


def test_manifest_packing_keys_present_from_birth_and_backfilled():
    """r13 satellite: every manifest record carries the packing keys
    (null until stamped), history.backfill_record nulls them onto
    pre-r13 records, and the auditor's manifest pass covers both
    directions (it runs inside the clean-tree audit)."""
    from raft_tpu.analysis import contracts
    from raft_tpu.obs import history
    from raft_tpu.obs.manifest import emit_manifest
    from raft_tpu.obs.manifest import PACKING_KEYS as PKEYS

    PACKING_KEYS = PKEYS
    assert tuple(PACKING_KEYS) == tuple(LAYOUT_FIELDS)
    rec = emit_manifest("probe", FAULTED, path="-")
    for k in PACKING_KEYS:
        assert k in rec and rec[k] is None
    old = {k: v for k, v in rec.items() if k not in PACKING_KEYS}
    back = history.backfill_record(old)
    for k in PACKING_KEYS:
        assert k in back and back[k] is None
    assert contracts.manifest_problems() == []
    # Drift detection both directions: an emit side that forgot the
    # keys, and a backfill side that forgot them.

    class _NoPackManifest:
        ROOFLINE_KEYS = ("predicted_rounds_per_sec", "attainment_pct",
                         "bound", "trace_path")
        PACKING_KEYS = PKEYS

        @staticmethod
        def emit_manifest(segment, cfg, device=None, path=None, **fields):
            rec = emit_manifest(segment, cfg, device=device, path="-",
                                **fields)
            return {k: v for k, v in rec.items()
                    if k not in _NoPackManifest.PACKING_KEYS}

    probs = contracts.manifest_problems(manifest_mod=_NoPackManifest)
    assert any("pack_bools" in p for p in probs)

    class _NoPackHistory:
        R12_MANIFEST_KEYS = history.R12_MANIFEST_KEYS
        R13_MANIFEST_KEYS = history.R13_MANIFEST_KEYS

        @staticmethod
        def backfill_record(rec):
            out = dict(rec)
            for k in history.R12_MANIFEST_KEYS:
                out.setdefault(k, None)
            return out   # forgot the r13 keys

    probs = contracts.manifest_problems(history_mod=_NoPackHistory)
    assert any("pack_bools" in p or "backfill" in p for p in probs)


def test_kreads_indexes_by_name_on_packed_wire():
    """The packed layout inserts/removes wire leaves — host-side
    counter readers must index by name (a positional constant would
    read a neighbor)."""
    cfg = dataclasses.replace(FAULTED, read_every=4, **PACKED)
    st0 = state.init(cfg)
    leaves, g = pkernel.kinit(cfg, st0)
    assert pkernel.kreads(cfg, leaves, g) == 0
    assert pkernel._wire_index(cfg, "group_id") \
        == pkernel._n_state_leaves(cfg) - 1
