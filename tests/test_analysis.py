"""Tier-1 coverage for the static engine-contract auditor
(raft_tpu/analysis/ — DESIGN.md §11).

Two halves:

- the auditor runs CLEAN on the current tree, and its derived byte
  model reproduces the pinned wire numbers (8,308 B/group clients-off,
  11,056 B/group clients-on) EXACTLY — the acceptance gate that makes
  the hand model derived-not-pinned;
- synthetic drift is NAMED: a fake State leaf, a dropped checkpoint
  backfill, an untagged jax.random draw, a Python branch on a traced
  value, a lane-coupling op in the workload transition — each must
  surface as a problem string carrying the leaf/file:line and the
  registry that drifted, and the script entry must exit nonzero.
"""

from __future__ import annotations

import os
import subprocess
import sys
import textwrap

import numpy as np

from raft_tpu import analysis
from raft_tpu.analysis import bytemodel, contracts, lint
from raft_tpu.sim import checkpoint
from raft_tpu.sim.state import Mailbox, PerNode

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- clean tree


def test_full_audit_clean():
    """Every pass — contracts, gating, shard rule, checkpoint coverage
    + backfills, byte model, purity lint — holds on the current tree."""
    report = analysis.audit_report(level="full")
    assert report["problems"] == []
    assert report["lint"] == []
    assert report["ok"]


def test_derived_bytes_reproduce_pinned_wire_models():
    """The acceptance pin: bytes/group DERIVED from dtype x shape must
    equal the hand-pinned wire model exactly — 8,308 B (clients off)
    and 11,056 B (clients on), the DESIGN.md §9/§10 headline numbers."""
    m_off = bytemodel.derived_wire_model(bytemodel.headline_cfg())
    assert m_off["problems"] == []
    assert m_off["wire_bytes_derived"] == 8308
    assert m_off["wire_bytes_pinned"] == 8308
    assert m_off["kinit_words_per_group"] * 4 == 8308

    m_on = bytemodel.derived_wire_model(bytemodel.clients_cfg())
    assert m_on["problems"] == []
    assert m_on["wire_bytes_derived"] == 11056
    assert m_on["wire_bytes_pinned"] == 11056
    assert m_on["kinit_words_per_group"] * 4 == 11056
    # The client delta the r09 probe published.
    assert m_on["wire_bytes_derived"] - m_off["wire_bytes_derived"] == 2748


def test_widened_bool_leaves_documented():
    """Satellite: every i32-widened bool leaf is named by the derived
    model, with the waste the r08 probe measured (~700 B/group at the
    headline config: 230 bool words x 3 widening bytes = 690 B)."""
    m = bytemodel.derived_wire_model(bytemodel.headline_cfg())
    widened = set(m["widening"]["leaves"])
    assert widened == {
        "nodes.votes", "alive_prev",
        "mailbox.rv_req_present", "mailbox.rv_resp_present",
        "mailbox.rv_resp_granted", "mailbox.ae_req_present",
        "mailbox.ae_resp_present", "mailbox.ae_resp_success",
        "mailbox.is_req_present", "mailbox.is_resp_present",
    }
    assert m["widening"]["waste_bytes_per_group"] == 690
    # Clients on adds no new bools (session tables are i32).
    m_on = bytemodel.derived_wire_model(bytemodel.clients_cfg())
    assert set(m_on["widening"]["leaves"]) == widened
    # The ceiling the model publishes is the exact supported() boundary.
    assert m["hbm"]["boundary_exact"]


def test_audit_script_exits_zero(tmp_path):
    """scripts/static_audit.py exits 0 on the current tree."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "scripts", "static_audit.py")],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "static audit ok" in proc.stdout
    assert "8308" in proc.stdout and "11056" in proc.stdout


# -------------------------------------------------------- synthetic drift


def test_fake_state_leaf_is_named():
    """Add a fake leaf to a copy of PerNode -> the auditor names it AND
    the registry that missed it."""
    problems = contracts.wire_registry_problems(
        pernode_fields=PerNode._fields + ("ghost_leaf",))
    assert problems, "fake PerNode leaf went undetected"
    assert any("ghost_leaf" in p and "_node_leaves" in p for p in problems)


def test_fake_mailbox_leaf_is_named():
    problems = contracts.wire_registry_problems(
        mailbox_fields=Mailbox._fields + ("xx_req_ghost",))
    assert any("xx_req_ghost" in p and "_mb_fields" in p for p in problems)


def test_fake_presence_leaf_trips_flight_contract():
    """A new *_present mailbox bit missing from PRESENCE_FIELDS would
    silently drop a message type from the flight recorder's volume
    signal — the auditor catches the registry gap."""
    problems = contracts.wire_registry_problems(
        mailbox_fields=Mailbox._fields + ("zz_req_present",))
    assert any("PRESENCE_FIELDS" in p and "zz_req_present" in p
               for p in problems)


def test_audit_script_nonzero_on_injected_drift():
    """End-to-end rc path: the script must exit nonzero, naming the
    injected leaf and the registry."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "scripts", "static_audit.py"),
         "--inject-drift", "ghost_leaf"],
        capture_output=True, text=True)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "ghost_leaf" in proc.stdout
    assert "_node_leaves" in proc.stdout


class _NoBackfillCheckpoint:
    """A drifted checkpoint implementation that forgot the pre-r07/r09
    metric backfills: any file missing a Metrics leaf fails to load,
    exactly what checkpoint.load looked like before the backfill rules
    landed."""

    save = staticmethod(checkpoint.save)

    @staticmethod
    def load(path, cfg=None, sharding=None):
        from raft_tpu.sim.run import Metrics
        with np.load(path) as z:
            if "metrics.committed" in z.files:
                for f in Metrics._fields:
                    key = f"metrics.{f}"
                    if key not in z.files and f not in (
                            "client_acked", "client_retries",
                            "client_hist", "client_max_lat"):
                        raise KeyError(key)
                if ("state.clients.done" in z.files
                        and "metrics.client_acked" not in z.files):
                    raise KeyError("metrics.client_acked")
        path.seek(0)
        return checkpoint.load(path, cfg=cfg, sharding=sharding)


def test_dropped_checkpoint_backfill_detected():
    """Drop the safety / client-lane backfills -> the auditor reports
    the named backfill drift (and the script form would exit nonzero,
    since any problem does)."""
    problems = contracts.checkpoint_problems(
        ckpt_mod=_NoBackfillCheckpoint)
    assert any("pre-r07 backfill drift" in p for p in problems)
    assert any("pre-r09 backfill drift" in p for p in problems)
    # The real implementation passes the same pass cleanly.
    assert contracts.checkpoint_problems() == []


# ------------------------------------------------------------- purity lint


def _lint_fixture(tmp_path, body, name="fixture.py", workload=False):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return lint.lint_file(str(p), workload_rules=workload)


def test_lint_untagged_jax_random_names_file_line(tmp_path):
    findings = _lint_fixture(tmp_path, """
        import jax
        import jax.numpy as jnp

        def draw(key, g):
            return jax.random.uniform(key, (g,))
    """)
    hits = [f for f in findings if f.rule == "untagged-randomness"]
    assert len(hits) == 1
    assert hits[0].line == 6
    assert hits[0].path.endswith("fixture.py")
    assert "jax.random" in hits[0].message


def test_lint_untagged_stdlib_random_import(tmp_path):
    findings = _lint_fixture(tmp_path, """
        import random

        def f():
            return random.random()
    """)
    assert any(f.rule == "untagged-randomness" and f.line == 2
               for f in findings)


def test_lint_traced_branch_named(tmp_path):
    findings = _lint_fixture(tmp_path, """
        import jax.numpy as jnp

        def f(ns: PerNode, cfg):
            if cfg.prevote:          # static gate: legal
                x = jnp.sum(ns.term)
                if x > 0:            # traced branch: illegal
                    return 1
            return 0
    """)
    hits = [f for f in findings if f.rule == "traced-branch"]
    assert len(hits) == 1
    assert hits[0].line == 7
    assert "'x'" in hits[0].message and "f()" in hits[0].message


def test_lint_nonelementwise_workload(tmp_path):
    findings = _lint_fixture(tmp_path, """
        import jax.numpy as jnp

        def client_update(cfg, cs, tmax, g, sid, t):
            acked = jnp.where(tmax >= cs.done, 1, 0)     # legal
            return jnp.sum(acked, axis=1)                # lane-coupling
    """, workload=True)
    hits = [f for f in findings if f.rule == "non-elementwise-workload"]
    assert len(hits) == 1
    assert hits[0].line == 6
    assert "jnp.sum" in hits[0].message


def test_lint_clean_on_real_modules():
    """The three contract-surface modules lint clean — the zero-noise
    property every rule is tuned for."""
    assert lint.lint_default() == []


# ------------------------------------------------------------ parity alias


def test_metric_parity_single_source():
    """The parity script is a thin wrapper over the auditor's pass —
    ONE source of truth (satellite: fold check_metric_parity into the
    auditor)."""
    sys.path.insert(0, os.path.join(_ROOT, "scripts"))
    try:
        import check_metric_parity
    finally:
        sys.path.pop(0)
    assert check_metric_parity.check() == []
    assert check_metric_parity.check.__module__ == "check_metric_parity"
    # Both roads report through the same pass.
    assert contracts.metric_parity_problems() == []
