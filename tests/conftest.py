"""Force tests onto a virtual 8-device CPU platform.

Must run before `import jax` anywhere in the test process: the driver's
multi-chip validation uses the same mechanism
(xla_force_host_platform_device_count), and tests must not depend on real
TPU hardware being attached.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
