"""Force tests onto a virtual 8-device CPU platform.

Two mechanisms, both needed:
- XLA_FLAGS must be set before `import jax` so the host platform splits
  into 8 virtual devices (the driver's multi-chip validation uses the
  same xla_force_host_platform_device_count mechanism).
- The TPU PJRT plugin in this image ignores the JAX_PLATFORMS env var
  (verified: with JAX_PLATFORMS=cpu the default backend stays 'tpu'), so
  the backend must be pinned via jax.config after import. Tests must not
  depend on real TPU hardware being attached; bench.py is the TPU job.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (import must follow the env setup above)

jax.config.update("jax_platforms", "cpu")

# The suite's wall time is XLA compile time, not tick execution (~50s
# compile vs <1s run for a 400-tick differential trace): cache compiled
# executables on disk so only the first-ever run of each (cfg, shape)
# program pays it. The cache dir is gitignored and machine-local; the
# recipe is shared with the dryrun and the multichip sweep so all
# drivers warm the same entries, and enable() exports
# $JAX_COMPILATION_CACHE_DIR so subprocesses the tests spawn (script
# smoke tests, the dryrun hop) hit the same cache instead of paying
# the known test-#33 XLA-compile wall again per child.
from raft_tpu.utils import compile_cache  # noqa: E402

compile_cache.enable()


# Re-exported for the tests (import must follow the jax env setup
# above — raft_tpu.utils.trees imports jax at module level).
from raft_tpu.utils.trees import trees_equal  # noqa: E402, F401
