"""The r19 narrow-native layout gates (DESIGN.md §18).

Three families:
- identity: every narrow dial off is byte-identical to the r18 layout
  (wire pins, config_hash, init bytes), and every dial on is
  VALUE-identical to the wide oracle chain across XLA scan and Pallas
  kernel on the shared faulted universes (the wide XLA path is already
  pinned bit-identical to the CPU oracle by test_differential, so
  values-equal-to-wide-XLA is values-equal-to-the-oracle);
- boundaries: the sticky bit-31 group_id latch fires on overflow, is
  refused loudly at every host boundary (checkpoint.save, the stream
  drivers), and checkpoints hop the narrow axis both ways BY NAME;
- verification: the model-checker kill matrix reproduces at narrow
  widths, and the comparator/lint seams behave.

Narrow dials re-declare RESIDENT dtypes only — the kernel wire and the
compiled programs are dial-invariant, so every kernel test here reuses
the shared-universe compile cache (conftest recipe).
"""

from __future__ import annotations

import dataclasses
import io

import numpy as np
import pytest

import conftest  # noqa: F401  (pins the CPU platform before jax loads)
import jax.numpy as jnp

from raft_tpu.config import NARROW_FIELDS, RaftConfig
from raft_tpu.parallel.kmesh import faulted_64_cfg
from raft_tpu.sim import checkpoint, pkernel, state
from raft_tpu.sim.run import metrics_init, run
from raft_tpu.utils.trees import (trees_equal, trees_equal_values,
                                  trees_equal_why)

ALL_DIALS = {f: True for f in NARROW_FIELDS}
NO_DIALS = {f: False for f in NARROW_FIELDS}


def _narrow_faulted():
    return faulted_64_cfg(**ALL_DIALS)


# ------------------------------------------------------- dials-off = r18


def test_dials_off_byte_identity():
    """Every dial off: empty dtype map, identical init bytes, identical
    wire pins (8,308 / 11,056 / 3,552 B/group), identical config_hash —
    the r18 layout IS the default."""
    from raft_tpu.obs.manifest import config_hash

    cfg = faulted_64_cfg()
    off = faulted_64_cfg(**NO_DIALS)
    assert state.narrow_spec(cfg) == {}
    assert not state.narrow_active(cfg)
    assert trees_equal(state.init(cfg), state.init(off))
    assert config_hash(cfg) == config_hash(faulted_64_cfg(**ALL_DIALS))

    headline = RaftConfig(seed=42)
    clients = dataclasses.replace(headline, sessions=True, cmds_per_tick=0,
                                  client_rate=0.2, client_slots=4,
                                  client_retry_backoff=8)
    packed = dataclasses.replace(headline, pack_bools=True, pack_ring=True,
                                 alias_wire=True, wire_hist=False)
    for base, pin, wf in ((headline, 8308, True), (clients, 11056, True),
                          (packed, 3552, False)):
        narrow = dataclasses.replace(base, **ALL_DIALS)
        assert 4 * pkernel.wire_words_per_group(base, with_flight=wf) == pin
        assert 4 * pkernel.wire_words_per_group(narrow,
                                                with_flight=wf) == pin


def test_resident_pins_and_floor():
    """The four-way reconciled resident model, pinned exactly: headline
    4,034 -> 2,494 B/group (-38.2%), clients 4,734 -> 2,842 (-40.0%),
    both over the >= 35% r19 floor."""
    from raft_tpu.analysis import bytemodel

    assert bytemodel.narrow_model_problems() == []
    m = bytemodel.resident_bytes_model(
        bytemodel.all_dials_cfg(bytemodel.headline_cfg()))
    assert (m["resident_bytes_wide"], m["resident_bytes_narrow"]) \
        == (4034, 2494)
    assert m["reduction_pct"] >= 35.0
    c = bytemodel.resident_bytes_model(
        bytemodel.all_dials_cfg(bytemodel.clients_cfg()))
    assert (c["resident_bytes_wide"], c["resident_bytes_narrow"]) \
        == (4734, 2842)
    assert c["reduction_pct"] >= 35.0


def test_init_dtypes_follow_spec():
    """The real narrow init lands exactly on narrow_spec's dtypes, and
    every unlisted leaf stays wide."""
    from raft_tpu.sim.checkpoint import iter_named_leaves

    cfg = _narrow_faulted()
    spec = state.narrow_spec(cfg)
    assert spec
    st = state.init(cfg)
    wide = state.init(faulted_64_cfg())
    for (name, leaf), (_, wleaf) in zip(iter_named_leaves(st),
                                        iter_named_leaves(wide)):
        want = spec.get(name, wleaf.dtype)
        assert leaf.dtype == want, (name, leaf.dtype, want)


# ----------------------------------------- narrow-on engine value parity


def test_narrow_xla_value_identity_faulted():
    """THE r19 XLA gate: the narrow scan (all dials) stays
    value-identical to the wide run on full State AND full Metrics over
    the faulted universe — and really is narrower (strict compare
    fails on dtype)."""
    ncfg, wcfg = _narrow_faulted(), faulted_64_cfg()
    stw, mw = run(wcfg, state.init(wcfg), 48, 0, metrics_init(64))
    stn, mn = run(ncfg, state.init(ncfg), 48, 0, metrics_init(64))
    ok, why = trees_equal_why(stw, stn, values_only=True)
    assert ok, why
    ok, why = trees_equal_why(mw, mn, values_only=True,
                              names=list(type(mw)._fields))
    assert ok, why
    assert not trees_equal(stw, stn)   # the dtypes really moved


def test_narrow_kernel_value_identity_faulted():
    """THE r19 kernel gate: the fused-chunk kernel under the narrow cfg
    (kinit widens the lanes, the chunk computes wide, kfinish
    re-narrows) stays value-identical to the wide XLA run — across two
    launches so the re-entry boundary runs. The compiled program is
    dial-invariant, so this reuses the shared faulted-universe
    compile."""
    ncfg, wcfg = _narrow_faulted(), faulted_64_cfg()
    stw, mw = run(wcfg, state.init(wcfg), 48, 0, metrics_init(64))
    leaves, g = pkernel.kinit(ncfg, state.init(ncfg))
    leaves = pkernel.kstep(ncfg, leaves, 0, 24, interpret=True)
    leaves = pkernel.kstep(ncfg, leaves, 24, 24, interpret=True)
    stn, mn = pkernel.kfinish(ncfg, leaves, g)
    ok, why = trees_equal_why(stw, stn, values_only=True)
    assert ok, why
    ok, why = trees_equal_why(mw, mn, values_only=True,
                              names=list(type(mw)._fields))
    assert ok, why
    # And the kernel's own narrow round-trip landed on the narrow form.
    spec = state.narrow_spec(ncfg)
    assert str(stn.nodes.term.dtype) == str(np.dtype(spec["nodes.term"]))


@pytest.mark.slow
def test_narrow_clients_value_identity():
    """The clients universe (sessions + dedup tables + ClientState)
    under all dials: value-identical to wide on full State+Metrics."""
    from raft_tpu.clients.workload import clients_64_cfg

    ncfg = clients_64_cfg(**ALL_DIALS)
    wcfg = clients_64_cfg()
    stw, mw = run(wcfg, state.init(wcfg), 48, 0,
                  metrics_init(64, clients=True))
    stn, mn = run(ncfg, state.init(ncfg), 48, 0,
                  metrics_init(64, clients=True))
    ok, why = trees_equal_why(stw, stn, values_only=True)
    assert ok, why
    ok, why = trees_equal_why(mw, mn, values_only=True,
                              names=list(type(mw)._fields))
    assert ok, why
    assert stn.clients.done.dtype == jnp.uint16
    assert stn.clients.last_lat.dtype == jnp.int16


def test_donation_twin_bit_identical():
    """cfg.donate_scan routes through the donating jit twin and must be
    a pure residency decision: bit-identical State+Metrics, on both the
    wide and the narrow layout. Donated operands are stale after the
    call — fresh inits per run, exactly the contract run() documents."""
    wcfg = faulted_64_cfg()
    dcfg = faulted_64_cfg(donate_scan=True)
    stw, mw = run(wcfg, state.init(wcfg), 48, 0, metrics_init(64))
    std, md = run(dcfg, state.init(dcfg), 48, 0, metrics_init(64))
    assert trees_equal(stw, std)
    assert trees_equal(mw, md)
    ncfg = _narrow_faulted()
    ndcfg = faulted_64_cfg(**{**ALL_DIALS, "donate_scan": True})
    stn, mn = run(ncfg, state.init(ncfg), 48, 0, metrics_init(64))
    stnd, mnd = run(ndcfg, state.init(ndcfg), 48, 0, metrics_init(64))
    assert trees_equal(stn, stnd)
    assert trees_equal(mn, mnd)
    # No metrics operand -> nothing to donate; the twin must not engage.
    st2 = run(ndcfg, state.init(ndcfg), 4)[0]
    assert st2.nodes.term.dtype == jnp.uint16


# ------------------------------------------------ overflow latch + hops


def _latched(cfg):
    """A narrow state with group 0's overflow latch forced on."""
    st = state.init(cfg)
    gid = np.asarray(st.group_id).copy()
    gid[0] = np.int32(gid[0] | np.int32(-(2 ** 31)))
    return st._replace(group_id=jnp.asarray(gid))


def test_overflow_latches_sticky_and_refused():
    """A wide value out of its narrow range latches bit 31 of group_id
    for THAT group only; the latch survives widen/narrow round-trips
    and further ticks; checkpoint.save refuses it loudly."""
    cfg = _narrow_faulted()
    wide = state.widen_state(cfg, state.init(cfg))
    term = np.asarray(wide.nodes.term).copy()
    term[3, 0] = 1 << 16                       # over u16, group 3 only
    bad = wide._replace(nodes=wide.nodes._replace(term=jnp.asarray(term)))
    narrowed = state.narrow_state(cfg, bad)
    ov = np.asarray(state.narrow_overflow(narrowed))
    assert ov[3] and not ov[:3].any() and not ov[4:].any()
    with pytest.raises(ValueError, match="narrow-dtype overflow"):
        state.check_narrow_overflow(cfg, narrowed)
    # Sticky through the per-tick boundary and through clean data.
    again = state.narrow_state(cfg, state.widen_state(cfg, narrowed))
    assert np.asarray(state.narrow_overflow(again))[3]
    stepped = run(cfg, narrowed, 2)[0]
    assert np.asarray(state.narrow_overflow(stepped))[3]
    buf = io.BytesIO()
    with pytest.raises(ValueError, match="narrow-dtype overflow"):
        checkpoint.save(buf, narrowed, 7, cfg=cfg)


def test_stream_drivers_refuse_latched_state():
    """The r19 host boundary on the paging drivers: a latched state is
    refused at ENTRY (before any paging or compile), not after n_ticks
    of garbage."""
    from raft_tpu.parallel import cohort, kmesh, make_mesh

    cfg = _narrow_faulted()
    bad = _latched(cfg)
    with pytest.raises(ValueError, match="narrow-dtype overflow"):
        cohort.prun_streamed(cfg, bad, 8)
    mesh = make_mesh(1)
    with pytest.raises(ValueError, match="narrow-dtype overflow"):
        kmesh.prun_sharded(cfg, bad, 8, mesh)
    with pytest.raises(ValueError, match="narrow-dtype overflow"):
        cohort.prun_streamed_sharded(cfg, bad, 8, mesh)


def test_paged_wire_stays_word_sized():
    """The scheduler's staging pool refuses a narrow dtype on the host
    wire — the wire is i32/u32 words by contract, dials or not."""
    from raft_tpu.parallel import stream_sched

    cfg = _narrow_faulted()
    leaves, g = pkernel.kinit(cfg, state.init(cfg))
    host = tuple(np.asarray(a) for a in leaves)
    assert stream_sched.wire_word_problems(host) == []
    bad = (host[0].astype(np.int16),) + host[1:]
    assert stream_sched.wire_word_problems(bad)
    with pytest.raises(ValueError, match="narrow dtype on the paged"):
        stream_sched.StagingPool(bad, pkernel.GB // 128)


def test_checkpoint_hops_narrow_axis_by_name(tmp_path):
    """A checkpoint written under one narrow layout loads under any
    other BY NAME: values exact, dtypes landing on the destination
    cfg's resident form, both directions — and a latched source never
    reaches disk (covered above), while an out-of-range WIDE checkpoint
    refuses at narrow load."""
    ncfg, wcfg = _narrow_faulted(), faulted_64_cfg()
    stw, _ = run(wcfg, state.init(wcfg), 24, 0, metrics_init(64))
    p = tmp_path / "wide.npz"
    checkpoint.save(str(p), stw, 24, cfg=wcfg)
    stn, t, _ = checkpoint.load(str(p), cfg=ncfg)
    assert t == 24
    assert trees_equal_values(stw, stn)
    assert trees_equal(stn, state.narrow_state(ncfg, stw))
    # ... and back: narrow save -> wide load.
    p2 = tmp_path / "narrow.npz"
    checkpoint.save(str(p2), stn, 24, cfg=ncfg)
    stw2, t2, _ = checkpoint.load(str(p2), cfg=wcfg)
    assert t2 == 24
    assert trees_equal(stw2, state.widen_state(ncfg, stn))
    # Resuming the narrow hop continues the SAME universe.
    a = run(wcfg, stw, 8, t0=24)[0]
    b = run(ncfg, stn, 8, t0=24)[0]
    assert trees_equal_values(a, b)
    # A wide checkpoint holding a value past the narrow range refuses
    # the hop instead of wrapping.
    term = np.asarray(stw.nodes.term).copy()
    term[0, 0] = 1 << 16
    stbig = stw._replace(nodes=stw.nodes._replace(term=jnp.asarray(term)))
    p3 = tmp_path / "big.npz"
    checkpoint.save(str(p3), stbig, 24, cfg=wcfg)
    with pytest.raises(ValueError, match="narrow-dtype overflow"):
        checkpoint.load(str(p3), cfg=ncfg)


# ------------------------------------------------- comparator + lint


def test_values_only_comparator():
    """values_only lifts INTEGER/bool dtype mismatches to a common
    width and still catches value drift; strict mode stays byte-strict."""
    a = {"x": jnp.arange(4, dtype=jnp.int32),
         "b": jnp.array([True, False])}
    b = {"x": jnp.arange(4, dtype=jnp.uint16),
         "b": jnp.array([1, 0], dtype=jnp.int8)}
    assert trees_equal_values(a, b)
    assert not trees_equal(a, b)
    c = {"x": jnp.array([0, 1, 2, 4], dtype=jnp.uint16),
         "b": jnp.array([1, 0], dtype=jnp.int8)}
    ok, why = trees_equal_why(a, c, values_only=True)
    assert not ok and "x" in why


def test_lint_flags_untagged_widening(tmp_path):
    """The untagged-widening rule: an astype/jnp.<dtype> cast on a
    traced State leaf chain in a hot-loop file needs `# widen-ok`;
    derived expressions and tagged lines pass. The real hot loops are
    clean (lint_default has no untagged-widening findings)."""
    from raft_tpu.analysis import lint

    fix = tmp_path / "step.py"
    fix.write_text(
        "import jax.numpy as jnp\n"
        "I32 = jnp.int32\n\n\n"
        "def tick(cfg, st, t):\n"
        "    a = st.nodes.term.astype(I32)\n"
        "    b = jnp.int32(st.nodes.commit)\n"
        "    c = st.nodes.applied.astype(I32)   # widen-ok\n"
        "    d = (st.nodes.term == 0).astype(I32)\n"
        "    return a, b, c, d\n")
    found = [f for f in lint.lint_file(str(fix))
             if f.rule == "untagged-widening"]
    assert sorted(f.line for f in found) == [6, 7]
    assert all("widen-ok" in f.message for f in found)
    assert not [f for f in lint.lint_default()
                if f.rule == "untagged-widening"]


# --------------------------------------------- verification at narrow


def test_mcheck_narrow_agreement():
    """Exhaustive small-scope walk: every predicate verdict identical
    at wide and narrow view widths (the _signed lifts hold)."""
    from raft_tpu.verify import mcheck

    assert mcheck.narrow_agreement_problems(ticks=2, max_states=200) == []
    assert mcheck.narrow_agreement_problems(ticks=2, max_states=120,
                                            sessions=True) == []


@pytest.mark.parametrize("name", [
    "accept_stale_append", "minority_quorum",
    "commit_past_match", "truncate_committed"])
def test_mutant_killed_at_narrow_width(name):
    """The kill matrix reproduces with predicates evaluated on
    narrow-native views: the mutant dies with the SAME predicate
    family, and the real oracle survives the same drive, exhaustively.
    (A representative slice per predicate family — the full 14-mutant
    matrix runs wide in test_verify; narrow evaluation only changes
    the view dtypes, so one member per family pins each _signed lift.)
    """
    from raft_tpu.core.node import Node
    from raft_tpu.verify import mcheck
    from raft_tpu.verify.mutants import by_name

    m = by_name(name)
    rm = mcheck.check(m.bounds, m.node_cls, prefix=m.prefix, narrow=True)
    assert not rm.ok, f"{name}: mutant survived at narrow width"
    assert m.expect in rm.violation["predicates"]
    rc = mcheck.check(m.bounds, Node, prefix=m.prefix, narrow=True)
    assert rc.ok and rc.complete, f"{name}: clean oracle tripped narrow"


def test_manifest_narrow_keys_and_segment_fields():
    """NARROW_KEYS ride every record from birth (null), survive caller
    values, backfill onto pre-r19 records, and the roofline producer
    emits exactly the registry."""
    from raft_tpu.analysis import bytemodel
    from raft_tpu.obs import roofline
    from raft_tpu.obs.history import backfill_record
    from raft_tpu.obs.manifest import NARROW_KEYS, emit_manifest

    cfg = RaftConfig(n_groups=2, k=3, seed=3, log_cap=8, compact_every=4)
    rec = emit_manifest("narrow-probe", cfg, path="-")
    assert all(rec[k] is None for k in NARROW_KEYS)
    fields = roofline.narrow_segment_fields(dataclasses.replace(
        cfg, **ALL_DIALS))
    assert set(fields) == set(NARROW_KEYS)
    assert all(fields[f] for f in NARROW_FIELDS)
    assert fields["narrow_resident_bytes_per_group"] == \
        bytemodel.narrow_resident_bytes_per_group(
            dataclasses.replace(cfg, **ALL_DIALS))
    rec2 = emit_manifest("narrow-probe", cfg, path="-", **fields)
    assert all(rec2[k] == fields[k] for k in NARROW_KEYS)
    old = {k: v for k, v in rec.items() if k not in NARROW_KEYS}
    assert all(backfill_record(old)[k] is None for k in NARROW_KEYS)
