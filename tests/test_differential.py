"""The CPU<->TPU differential gate (DESIGN.md §1, SURVEY.md §7 step 3).

Runs the CPU oracle (`core/`) and the batched JAX path (`sim/`) from the
same config+seed and asserts the observable per-node state — (term, role,
voted_for, leader_id, last_index, commit, applied, digest, snap_index,
snap_term, alive) — is bit-identical after every tick, for every node of
every group, with and without each fault class.

The sim side records its whole trace on-device in one scanned program
(`sim.run.trace`); the CPU side ticks normally, collecting
`Cluster.snapshot()` per tick; the two `[T, G, K]` tensors are compared
wholesale. Any semantic drift between `core/node.py` and `sim/step.py`
trips this within a few ticks.
"""

from __future__ import annotations

import numpy as np
import pytest

from raft_tpu import sim
from raft_tpu.config import RaftConfig
from raft_tpu.core.cluster import Cluster
from raft_tpu.obs.triage import oracle_trace
from raft_tpu.sim.run import TRACE_FIELDS, trace

ALL_FIELDS = TRACE_FIELDS + ("alive",)


def cpu_trace(cfg: RaftConfig, n_groups: int, ticks: int):
    """[T, G, K] numpy trace from the CPU oracle, plus the clusters
    (shared harness: obs.triage.oracle_trace)."""
    return oracle_trace(cfg, n_groups, ticks)


def assert_traces_equal(cpu, jx, context=""):
    for f in ALL_FIELDS:
        a = cpu[f]
        b = np.asarray(jx[f]).astype(np.int64)
        if not np.array_equal(a, b):
            t, g, k = np.argwhere(a != b)[0]
            raise AssertionError(
                f"{context} first divergence at t={t} group={g} node={k} "
                f"field={f}: cpu={a[t, g, k]} jax={b[t, g, k]}")


def run_lockstep(cfg: RaftConfig, n_groups: int, ticks: int):
    cpu, clusters = cpu_trace(cfg, n_groups, ticks)
    _, jx = trace(cfg, sim.init(cfg, n_groups=n_groups), ticks)
    assert_traces_equal(cpu, jx, context=f"cfg={cfg}")
    return clusters, jx


def test_differential_no_faults():
    cfg = RaftConfig(seed=7)
    clusters, _ = run_lockstep(cfg, n_groups=3, ticks=400)
    # The run must have actually done consensus work, not idled.
    assert all(c.nodes[0].commit > 100 for c in clusters)


def test_differential_message_drop():
    cfg = RaftConfig(seed=11, drop_prob=0.15)
    clusters, _ = run_lockstep(cfg, n_groups=2, ticks=400)
    assert all(max(n.commit for n in c.nodes) > 20 for c in clusters)


def test_differential_crashes():
    cfg = RaftConfig(seed=13, crash_prob=0.3, crash_epoch=40)
    run_lockstep(cfg, n_groups=2, ticks=600)


def test_differential_partitions():
    cfg = RaftConfig(seed=17, partition_prob=0.5, partition_epoch=50)
    run_lockstep(cfg, n_groups=2, ticks=500)


def test_differential_all_faults():
    """Fast-tier all-faults run: every fault class on, 400 ticks. A
    different seed from the slow 1000-tick gate, so a full run (-m "")
    covers two universes rather than a prefix twice."""
    cfg = RaftConfig(seed=24, drop_prob=0.05, crash_prob=0.2, crash_epoch=48,
                     partition_prob=0.3, partition_epoch=64)
    clusters, _ = run_lockstep(cfg, n_groups=2, ticks=400)
    assert all(max(n.commit for n in c.nodes) > 10 for c in clusters)


@pytest.mark.slow
def test_differential_all_faults_long():
    """The §7-step-3 headline run: >=1K ticks with every fault class on."""
    cfg = RaftConfig(seed=23, drop_prob=0.05, crash_prob=0.2, crash_epoch=48,
                     partition_prob=0.3, partition_epoch=64)
    clusters, _ = run_lockstep(cfg, n_groups=2, ticks=1000)
    # Liveness through faults: groups still commit.
    assert all(max(n.commit for n in c.nodes) > 10 for c in clusters)


def test_differential_small_window():
    """Tight log window + bursty appends exercises flow control, takeover
    re-proposal, compaction, and InstallSnapshot repair."""
    cfg = RaftConfig(seed=29, log_cap=8, compact_every=4, cmds_per_tick=2,
                     max_entries_per_msg=2, crash_prob=0.25, crash_epoch=40)
    run_lockstep(cfg, n_groups=2, ticks=500)


def test_differential_k3():
    cfg = RaftConfig(seed=31, k=3, drop_prob=0.1)
    run_lockstep(cfg, n_groups=2, ticks=400)


def test_differential_reconfig():
    """Membership-change fault class: the scheduled reconfig churns the
    voter set (with crashes forcing re-elections under changed quorums)
    and the two backends must stay bit-identical — including the
    snap_voters surface once compaction folds a config entry."""
    cfg = RaftConfig(seed=37, reconfig_prob=0.9, reconfig_epoch=32,
                     crash_prob=0.2, crash_epoch=48)
    clusters, _ = run_lockstep(cfg, n_groups=3, ticks=600)
    # The schedule must actually have churned membership somewhere.
    full = (1 << cfg.k) - 1
    assert any(n.current_config()[0] != full
               for c in clusters for n in c.nodes) or any(
        n.snap_voters != full for c in clusters for n in c.nodes), (
        "reconfig never fired — differential coverage is vacuous")


def test_differential_prevote():
    """PreVote universe: crashes + partitions force elections that must
    all pass through the pre-ballot; both backends bit-identical,
    including the PRECANDIDATE role values in the trace."""
    cfg = RaftConfig(seed=41, prevote=True, crash_prob=0.25, crash_epoch=48,
                     partition_prob=0.3, partition_epoch=64, drop_prob=0.05)
    clusters, jx = run_lockstep(cfg, n_groups=2, ticks=500)
    # Elections actually happened through the pre-vote path (terms moved)
    # and the groups kept committing.
    assert all(max(n.term for n in c.nodes) > 1 for c in clusters)
    assert all(max(n.commit for n in c.nodes) > 10 for c in clusters)


def test_differential_prevote_reconfig():
    """PreVote x membership change: pre-ballot quorums are voters-aware;
    the combination must stay bit-identical across backends."""
    cfg = RaftConfig(seed=43, prevote=True, reconfig_prob=0.9,
                     reconfig_epoch=32, crash_prob=0.2, crash_epoch=48)
    run_lockstep(cfg, n_groups=2, ticks=500)


def test_differential_scheduled_reads():
    """Batched ReadIndex (DESIGN.md §2c): the scheduled-read machinery
    (ack evidence, registration gate, voters-aware completion quorum,
    abort on leadership loss) must be bit-identical across backends —
    `reads_done` is in the trace surface. Crashes force leader changes
    so the abort paths execute."""
    cfg = RaftConfig(seed=47, read_every=8, crash_prob=0.25, crash_epoch=48,
                     drop_prob=0.05)
    clusters, jx = run_lockstep(cfg, n_groups=2, ticks=500)
    # Reads actually completed somewhere (coverage is not vacuous).
    assert int(np.asarray(jx["reads_done"]).max()) > 0


def test_differential_reads_with_reconfig():
    """ReadIndex x membership change — the round-4 confirmed-violation
    combination — under lockstep: the voters-aware completion quorum
    must match the oracle bit-for-bit while the voter set churns."""
    cfg = RaftConfig(seed=53, read_every=8, reconfig_prob=0.9,
                     reconfig_epoch=32, crash_prob=0.2, crash_epoch=48)
    clusters, jx = run_lockstep(cfg, n_groups=2, ticks=500)
    assert int(np.asarray(jx["reads_done"]).max()) > 0


def test_differential_transfer():
    """Leadership-transfer universe (DESIGN.md §2d): the scheduled
    TimeoutNow handoffs — combined with PreVote, whose lease the
    transfer must bypass — stay bit-identical across backends."""
    cfg = RaftConfig(seed=59, transfer_prob=0.8, transfer_epoch=48,
                     prevote=True, crash_prob=0.15, crash_epoch=64,
                     drop_prob=0.03)
    clusters, _ = run_lockstep(cfg, n_groups=4, ticks=500)
    # Transfers actually moved leadership (terms advanced well past the
    # initial election) and the groups kept committing.
    assert all(max(n.term for n in c.nodes) > 2 for c in clusters)
    assert all(max(n.commit for n in c.nodes) > 10 for c in clusters)


def test_differential_transfer_reconfig():
    """Transfer x membership change: the TimeoutNow voter gate (both
    the sender's target check and the receiver's campaign check) must
    track the churning config identically on both backends."""
    cfg = RaftConfig(seed=61, transfer_prob=0.8, transfer_epoch=48,
                     reconfig_prob=0.8, reconfig_epoch=40,
                     crash_prob=0.15, crash_epoch=64)
    run_lockstep(cfg, n_groups=2, ticks=500)


def test_differential_multi_source_ae_tick():
    """Same-tick AppendEntries from TWO different senders at one
    receiver — a partition-heal window where the deposed leader's
    heartbeat lands alongside the new leader's. Message delivery is
    SEQUENTIAL per inbox: the second AE must observe the first one's
    log writes, which is exactly the cross-sender dependency that
    forbids hoisting receiver-ring reads across senders in the fused
    kernel handler (sim/pkernel.py `_on_ae_req`) — so this universe
    pins the semantics at the step-vs-oracle layer where any wrongly
    "shared" restructure of the entry walk would drift. The probe
    wraps delivery to prove the scenario actually occurs (the seed was
    chosen for it); without it the coverage claim would be vacuous."""
    from raft_tpu.core import rpc
    cfg = RaftConfig(seed=15, k=3, log_cap=8, compact_every=4,
                     crash_prob=0.2, crash_epoch=40,
                     partition_prob=0.6, partition_epoch=40,
                     drop_prob=0.05)
    n_groups, ticks = 2, 400
    multi_ae_ticks = 0
    clusters = []
    for g in range(n_groups):
        c = Cluster(cfg, group=g)
        orig = c.transport.deliver

        def deliver(t, alive, _orig=orig):
            nonlocal multi_ae_ticks
            inboxes = _orig(t, alive)
            for ib in inboxes:
                if len({m.src for m in ib if m.type == rpc.AE_REQ}) >= 2:
                    multi_ae_ticks += 1
            return inboxes

        c.transport.deliver = deliver
        clusters.append(c)
    cpu = {f: np.zeros((ticks, n_groups, cfg.k), np.int64)
           for f in ALL_FIELDS}
    for t in range(ticks):
        for g, c in enumerate(clusters):
            c.tick()
            for k, view in enumerate(c.snapshot()):
                for f in ALL_FIELDS:
                    cpu[f][t, g, k] = getattr(view, f)
    assert multi_ae_ticks >= 1, \
        "no multi-source AE tick occurred - coverage is vacuous"
    _, jx = trace(cfg, sim.init(cfg, n_groups=n_groups), ticks)
    assert_traces_equal(cpu, jx, context="multi-source-AE universe")


def test_comparator_has_teeth():
    """Prove the gate detects a single-field single-node single-tick drift:
    corrupt one sim trace cell by one and require a loud failure."""
    cfg = RaftConfig(seed=7)
    cpu, _ = cpu_trace(cfg, n_groups=1, ticks=60)
    _, jx = trace(cfg, sim.init(cfg, n_groups=1), 60)
    assert_traces_equal(cpu, jx)  # sanity: in sync
    bad = dict(jx)
    bad["commit"] = np.asarray(bad["commit"]).copy()
    bad["commit"][59, 0, 2] += 1
    with pytest.raises(AssertionError, match="field=commit"):
        assert_traces_equal(cpu, bad)
