"""Client API on the CPU oracle (SURVEY.md §2 row 17): propose routed to
the leader, ReadIndex linearizable reads, read-your-writes across leader
changes. Pure-Python — no JAX involvement."""

from __future__ import annotations

from raft_tpu.config import RaftConfig
from raft_tpu.core.cluster import Cluster
from raft_tpu.core.node import Node


def _elect(c: Cluster, max_ticks: int = 100) -> int:
    for _ in range(max_ticks):
        if c.leader() is not None:
            return c.leader()
        c.tick()
    raise AssertionError("no leader elected")


def _commit(c: Cluster, ticket, max_ticks: int = 100):
    for _ in range(max_ticks):
        if c.is_committed(ticket):
            return
        c.tick()
    raise AssertionError(f"ticket {ticket} never committed")


def test_propose_commits_and_applies():
    # cmds_per_tick=0: the only writes are explicit client proposes.
    c = Cluster(RaftConfig(seed=50, cmds_per_tick=0))
    _elect(c)
    t1 = c.propose(111)
    t2 = c.propose(222)
    assert t1 is not None and t2 is not None
    assert t2[0] == t1[0] + 1   # consecutive indices
    _commit(c, t1)
    _commit(c, t2)
    assert c._committed[t1[0]] == 111
    assert c._committed[t2[0]] == 222


def test_propose_without_leader_returns_none():
    c = Cluster(RaftConfig(seed=51, cmds_per_tick=0))
    assert c.leader() is None   # tick 0: nobody elected yet
    assert c.propose(1) is None


def test_propose_flow_control_when_window_full():
    cfg = RaftConfig(seed=52, cmds_per_tick=0, log_cap=8, compact_every=4)
    c = Cluster(cfg)
    _elect(c)
    lead = c.nodes[c.leader()]
    # Fill the leader's window without letting replication advance.
    accepted = 0
    while c.propose(1000 + accepted) is not None:
        accepted += 1
    assert accepted <= cfg.log_cap - (lead.snap_index - lead.snap_index)
    # After ticking (replication + compaction), proposals flow again.
    c.run(20)
    assert c.propose(42) is not None


def test_linearizable_read_basic():
    c = Cluster(RaftConfig(seed=53, cmds_per_tick=0))
    _elect(c)
    t1 = c.propose(777)
    _commit(c, t1)
    r = c.read()
    assert r is not None
    read_index, served_index, digest = r
    assert read_index >= t1[0]
    assert served_index >= read_index
    assert digest == c.expected_digest(served_index)


def test_read_your_writes_across_leader_change():
    """The VERDICT-mandated sequence: propose -> crash the leader ->
    re-election -> read on the new leader sees the write."""
    cfg = RaftConfig(seed=54, cmds_per_tick=0)
    c = Cluster(cfg)
    old = _elect(c)
    ticket = c.propose(31337)
    assert ticket is not None
    _commit(c, ticket)

    # Crash the old leader permanently; everyone else stays up.
    c.alive_fn = lambda t, dead=old: [i != dead for i in range(cfg.k)]
    for _ in range(200):
        c.tick()
        lead = c.leader()
        if lead is not None and lead != old:
            break
    assert c.leader() is not None and c.leader() != old

    r = c.read()
    assert r is not None
    read_index, served_index, digest = r
    # The new leader's read covers the old leader's committed write...
    assert read_index >= ticket[0]
    assert c._committed[ticket[0]] == 31337
    # ...and serves exactly the state machine the commit history implies.
    assert digest == c.expected_digest(served_index)


def test_read_aborts_on_leader_crash():
    cfg = RaftConfig(seed=55, cmds_per_tick=0)
    c = Cluster(cfg)
    old = _elect(c)
    handle = c.read_begin()
    assert handle is not None and handle[0] == old
    c.alive_fn = lambda t, dead=old: [i != dead for i in range(cfg.k)]
    c.tick()
    assert c.read_poll(handle) == Node.READ_ABORTED
    # A fresh read on the new regime still completes.
    assert c.read(max_ticks=300) is not None


def test_read_requires_quorum_roundtrip():
    """A leader cut off from all peers must never serve a ReadIndex read:
    with every link down post-registration, the read stays pending."""
    cfg = RaftConfig(seed=56, cmds_per_tick=0)
    c = Cluster(cfg)
    _elect(c)
    c.run(10)
    handle = c.read_begin()
    assert handle is not None
    # Sever every link from/to the leader from now on.
    lead = handle[0]
    c.transport.link_filter = (
        lambda t, s, d, L=lead: s != L and d != L)
    pend = 0
    for _ in range(cfg.election_min + cfg.election_range + 10):
        r = c.read_poll(handle)
        assert r in (Node.READ_PENDING, Node.READ_ABORTED), (
            f"read served without quorum: {r}")
        pend += r == Node.READ_PENDING
        c.tick()
    assert pend > 0
