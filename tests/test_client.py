"""Client API on the CPU oracle (SURVEY.md §2 row 17): propose routed to
the leader, ReadIndex linearizable reads, read-your-writes across leader
changes. Pure-Python — no JAX involvement."""

from __future__ import annotations

from raft_tpu.config import RaftConfig
from raft_tpu.core.cluster import Cluster
from raft_tpu.core.node import LEADER, Node


def _elect(c: Cluster, max_ticks: int = 100) -> int:
    for _ in range(max_ticks):
        if c.leader() is not None:
            return c.leader()
        c.tick()
    raise AssertionError("no leader elected")


def _commit(c: Cluster, ticket, max_ticks: int = 100):
    for _ in range(max_ticks):
        if c.is_committed(ticket):
            return
        c.tick()
    raise AssertionError(f"ticket {ticket} never committed")


def test_propose_commits_and_applies():
    # cmds_per_tick=0: the only writes are explicit client proposes.
    c = Cluster(RaftConfig(seed=50, cmds_per_tick=0))
    _elect(c)
    t1 = c.propose(111)
    t2 = c.propose(222)
    assert t1 is not None and t2 is not None
    assert t2[0] == t1[0] + 1   # consecutive indices
    _commit(c, t1)
    _commit(c, t2)
    assert c._committed[t1[0]] == 111
    assert c._committed[t2[0]] == 222


def test_propose_without_leader_returns_none():
    c = Cluster(RaftConfig(seed=51, cmds_per_tick=0))
    assert c.leader() is None   # tick 0: nobody elected yet
    assert c.propose(1) is None


def test_propose_flow_control_when_window_full():
    cfg = RaftConfig(seed=52, cmds_per_tick=0, log_cap=8, compact_every=4)
    c = Cluster(cfg)
    _elect(c)
    lead = c.nodes[c.leader()]
    # Fill the leader's window without letting replication advance.
    start_index = lead.last_index
    accepted = 0
    while c.propose(1000 + accepted) is not None:
        accepted += 1
    # Flow control: proposals stop exactly when the bounded window fills.
    assert accepted == cfg.log_cap - (start_index - lead.snap_index)
    # After ticking (replication + compaction), proposals flow again.
    c.run(20)
    assert c.propose(42) is not None


def test_linearizable_read_basic():
    c = Cluster(RaftConfig(seed=53, cmds_per_tick=0))
    _elect(c)
    t1 = c.propose(777)
    _commit(c, t1)
    r = c.read()
    assert r is not None
    read_index, served_index, digest = r
    assert read_index >= t1[0]
    assert served_index >= read_index
    assert digest == c.expected_digest(served_index)


def test_read_your_writes_across_leader_change():
    """The VERDICT-mandated sequence: propose -> crash the leader ->
    re-election -> read on the new leader sees the write."""
    cfg = RaftConfig(seed=54, cmds_per_tick=0)
    c = Cluster(cfg)
    old = _elect(c)
    ticket = c.propose(31337)
    assert ticket is not None
    _commit(c, ticket)

    # Crash the old leader permanently; everyone else stays up.
    c.alive_fn = lambda t, dead=old: [i != dead for i in range(cfg.k)]
    for _ in range(200):
        c.tick()
        lead = c.leader()
        if lead is not None and lead != old:
            break
    assert c.leader() is not None and c.leader() != old

    r = c.read()
    assert r is not None
    read_index, served_index, digest = r
    # The new leader's read covers the old leader's committed write...
    assert read_index >= ticket[0]
    assert c._committed[ticket[0]] == 31337
    # ...and serves exactly the state machine the commit history implies.
    assert digest == c.expected_digest(served_index)


def test_read_aborts_on_leader_crash():
    cfg = RaftConfig(seed=55, cmds_per_tick=0)
    c = Cluster(cfg)
    old = _elect(c)
    handle = c.read_begin()
    assert handle is not None and handle[0] == old
    c.alive_fn = lambda t, dead=old: [i != dead for i in range(cfg.k)]
    c.tick()
    assert c.read_poll(handle) == Node.READ_ABORTED
    # A fresh read on the new regime still completes.
    assert c.read(max_ticks=300) is not None


def test_read_not_served_by_deposed_leader_after_shrink():
    """Round-4 VERDICT confirmed violation, now a regression test: shrink
    k=5 to 3 voters, partition the old leader with the two removed
    learners, let the voter side elect a new leader and commit. The old
    leader keeps collecting the learners' AppendEntries acks, but those
    acks are from no election quorum — its pending read must NEVER be
    served (stale read), only stay pending or abort."""
    cfg = RaftConfig(seed=57, cmds_per_tick=0)
    c = Cluster(cfg)
    old = _elect(c)
    t0 = c.propose(1)
    assert t0 is not None
    _commit(c, t0)

    full = (1 << cfg.k) - 1
    v1, v2 = [i for i in range(cfg.k) if i != old][:2]
    t1 = c.propose_reconfig(full ^ (1 << v1))
    assert t1 is not None
    _commit(c, t1)
    t2 = c.propose_reconfig(full ^ (1 << v1) ^ (1 << v2))
    assert t2 is not None
    _commit(c, t2)
    voters = full ^ (1 << v1) ^ (1 << v2)
    assert c.nodes[old].current_config()[0] == voters

    # Partition: {old leader, both learners} | {the other two voters}.
    side = {old, v1, v2}
    c.transport.link_filter = (
        lambda tk, s, d, side=side: (s in side) == (d in side))
    c.run(2)
    rid = c.nodes[old].read_begin()
    assert rid is not None

    # The voter side (2 of 3 current voters) elects a new leader and
    # commits a write the old leader will never see.
    a, b = [i for i in range(cfg.k) if (voters >> i) & 1 and i != old]
    new_lead = None
    for _ in range(400):
        c.tick()
        r = c.nodes[old].read_poll(rid)
        assert not isinstance(r, tuple), f"stale read served: {r}"
        for i in (a, b):
            if c.nodes[i].role == LEADER:
                new_lead = i
        if new_lead is not None:
            break
    assert new_lead is not None
    idx = c.nodes[new_lead].propose(99)
    assert idx is not None
    for _ in range(100):
        c.tick()
        r = c.nodes[old].read_poll(rid)
        assert not isinstance(r, tuple), f"stale read served: {r}"
    assert c._committed.get(idx) == 99, "voter side never committed"
    # Throughout, the learners' acks kept arriving at the old leader —
    # the voters-aware quorum is what kept the read unserved.
    assert all(c.nodes[old].ack_time[v] >= 0 for v in (v1, v2))


def test_read_completes_in_shrunk_config():
    """Dual of the violation: a healthy 2-of-3-voter regime must be able
    to COMPLETE reads (the old full-k threshold stalled them forever)."""
    cfg = RaftConfig(seed=58, cmds_per_tick=0)
    c = Cluster(cfg)
    old = _elect(c)
    t0 = c.propose(7)
    assert t0 is not None
    _commit(c, t0)
    full = (1 << cfg.k) - 1
    v1, v2 = [i for i in range(cfg.k) if i != old][:2]
    t1 = c.propose_reconfig(full ^ (1 << v1))
    assert t1 is not None
    _commit(c, t1)
    t2 = c.propose_reconfig(full ^ (1 << v1) ^ (1 << v2))
    assert t2 is not None
    _commit(c, t2)
    # Crash both learners AND one voter: 2 of 3 voters remain — a voter
    # majority, but only 2 < 3 = full-k majority of live nodes.
    voters = full ^ (1 << v1) ^ (1 << v2)
    a = next(i for i in range(cfg.k) if (voters >> i) & 1 and i != old)
    dead = {v1, v2, next(i for i in range(cfg.k)
                         if (voters >> i) & 1 and i not in (old, a))}
    c.alive_fn = lambda tk, dead=dead: [i not in dead for i in range(cfg.k)]
    r = c.read(max_ticks=400)
    assert r is not None, "read stalled in a healthy shrunk config"
    read_index, served_index, digest = r
    assert read_index >= t2[0]
    assert digest == c.expected_digest(served_index)


def test_read_requires_quorum_roundtrip():
    """A leader cut off from all peers must never serve a ReadIndex read:
    with every link down post-registration, the read stays pending."""
    cfg = RaftConfig(seed=56, cmds_per_tick=0)
    c = Cluster(cfg)
    _elect(c)
    c.run(10)
    handle = c.read_begin()
    assert handle is not None
    # Sever every link from/to the leader from now on.
    lead = handle[0]
    c.transport.link_filter = (
        lambda t, s, d, L=lead: s != L and d != L)
    pend = 0
    for _ in range(cfg.election_min + cfg.election_range + 10):
        r = c.read_poll(handle)
        assert r in (Node.READ_PENDING, Node.READ_ABORTED), (
            f"read served without quorum: {r}")
        pend += r == Node.READ_PENDING
        c.tick()
    assert pend > 0
