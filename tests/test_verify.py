"""Tier-1 coverage for the r18 verification layer (DESIGN.md §17):
the bounded protocol model checker, the mutation-kill matrix, the
stream-scheduler hazard prover, and the replay/audit plumbing.

Four halves:

- the kill matrix: every catalog mutant is killed at its recorded
  bounds/prefix with the recorded predicate family, AND the unmutated
  oracle survives the exact same waypoint drive (the mutant, not the
  harness, trips the invariant);
- the clean oracle verifies exhaustively at smoke scope (all schedules,
  zero pruning);
- the hazard prover passes the REAL r16/r17 scheduler loops and names
  file:line on each synthetic negative;
- counterexample artifacts round-trip through save/load/replay and the
  nemesis replay door, and the deep-audit CLI exit-code contract holds.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from raft_tpu.core.node import Node
from raft_tpu.verify import hazards, mcheck
from raft_tpu.verify.mutants import MUTANTS, by_name

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------- mutant kill matrix


@pytest.mark.parametrize("name", [m.name for m in MUTANTS])
def test_mutant_killed_and_oracle_clean(name):
    """check() trips the recorded predicate family on the mutant and
    verifies the REAL oracle clean over the same prefix drive — for
    prefix-driven entries the final-tick fan-out must also be
    exhaustive (complete), so the kill bound is a real bound."""
    m = by_name(name)
    rm = mcheck.check(m.bounds, m.node_cls, prefix=m.prefix)
    assert not rm.ok, f"{name}: mutant survived its recorded bounds"
    assert m.expect in rm.violation["predicates"], (
        f"{name}: expected {m.expect}, got {rm.violation['predicates']}")
    rc = mcheck.check(m.bounds, Node, prefix=m.prefix)
    assert rc.ok, f"{name}: REAL oracle tripped on the kill drive"
    assert rc.complete, f"{name}: clean verification was truncated"


def test_prefix_shapes():
    """Catalog prefixes leave exactly one tick for the exhaustive
    fan-out, and every choice is inside the entry's own bounds."""
    for m in MUTANTS:
        if not m.prefix:
            continue
        assert len(m.prefix) == m.bounds.ticks - 1, m.name
        for c in m.prefix:
            assert len(c["alive"]) == m.bounds.k, m.name
            dead = sum(1 for a in c["alive"] if not a)
            assert dead <= m.bounds.max_dead, m.name
            assert len(c["pulse"]) <= m.bounds.max_pulses, m.name
            if c["propose"] is not None:
                assert m.bounds.sessions, m.name


# ------------------------------------------------- exhaustive clean pass


def test_clean_oracle_exhaustive_smoke():
    """The startup-audit smoke: the real oracle over ALL schedules at
    tiny scope, exhaustively (complete=True means zero states were
    pruned by the state cap — the verification actually finished)."""
    rep = mcheck.smoke()
    assert rep.ok, rep.violation
    assert rep.complete
    assert rep.states > 0 and rep.transitions > 0


# -------------------------------------------------------- hazard prover


def test_hazard_prover_real_schedulers():
    """The real r16 (unsharded) and r17 (sharded) paging loops, traced
    at the capture seams over a small config grid: zero hazards."""
    rep = hazards.prove_schedulers(max_cohort_blocks=2, max_devices=2,
                                  max_windows=2)
    assert rep["configs"] > 0 and rep["events"] > 0
    assert rep["hazards"] == [], rep["hazards"]


def test_hazard_prover_negatives_name_file_line():
    """Each synthetic buggy scheduler is caught by its expected rule,
    and the hazard names a file:line inside hazards.py itself (the
    synthetic loops live there)."""
    rep = hazards.prove_negatives()
    assert rep["missed"] == [], rep
    assert rep["caught"] == 3
    for name, site in rep["sites"].items():
        fname, _, line = site.rpartition(":")
        assert os.path.basename(fname) == "hazards.py", (name, site)
        assert line.isdigit(), (name, site)


# ---------------------------------------------- artifact round-trip


def test_reproducer_roundtrip_and_replay(tmp_path):
    m = by_name("commit_off_by_one")
    r = mcheck.check(m.bounds, m.node_cls, prefix=m.prefix)
    assert not r.ok
    art = mcheck.reproducer(r, m.bounds, mutant=m.name)
    path = str(tmp_path / "repro.json")
    mcheck.save_reproducer(art, path)
    art2 = mcheck.load_reproducer(path)
    assert art2["kind"] == mcheck.ARTIFACT_KIND
    assert art2["mutant"] == m.name
    rep = mcheck.replay(art2)          # node_cls resolved from "mutant"
    assert rep["tick"] == art2["violation"]["tick"]
    assert "predicates." + rep["predicates"][0] == \
        art2["violation"]["leaf"]


def test_load_reproducer_rejects_foreign_kind(tmp_path):
    path = str(tmp_path / "bad.json")
    with open(path, "w") as f:
        json.dump({"kind": "nemesis-reproducer"}, f)
    with pytest.raises(ValueError):
        mcheck.load_reproducer(path)


def test_replay_detects_drift(tmp_path):
    """replay() must RAISE when the recorded violation no longer
    reproduces — here, by replaying a mutant's schedule against the
    clean oracle."""
    m = by_name("commit_off_by_one")
    r = mcheck.check(m.bounds, m.node_cls, prefix=m.prefix)
    art = mcheck.reproducer(r, m.bounds, mutant=m.name)
    with pytest.raises(AssertionError):
        mcheck.replay(art, node_cls=Node)


def test_nemesis_replay_door(tmp_path):
    """scripts/nemesis_search.py --replay dispatches on the artifact's
    kind and exits 0 when the counterexample reproduces."""
    m = by_name("commit_off_by_one")
    r = mcheck.check(m.bounds, m.node_cls, prefix=m.prefix)
    art = mcheck.reproducer(r, m.bounds, mutant=m.name)
    path = str(tmp_path / "mcheck_repro.json")
    mcheck.save_reproducer(art, path)
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "scripts",
                                      "nemesis_search.py"),
         "--replay", path],
        cwd=_ROOT, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stdout + out.stderr


# ------------------------------------------------- deep-audit contract


def test_deep_audit_names_verification_failures(monkeypatch):
    """The nonzero half of the rc contract: a failed verification pass
    must flip the deep report to not-ok with a problem string NAMING
    the failing pass (smoke scope / hazard rule), not a bare flag."""
    from raft_tpu import analysis

    bad = mcheck.check(mcheck.Bounds(k=2, ticks=1, max_states=1))
    monkeypatch.setattr(mcheck, "smoke", lambda **kw: bad)
    monkeypatch.setattr(
        hazards, "prove_schedulers",
        lambda **kw: {"configs": 1, "events": 1,
                      "hazards": ["drain-before-sync at cohort.py:1"]})
    report = analysis.audit_report(level="deep")
    assert not report["ok"]
    joined = "\n".join(report["problems"])
    assert "mcheck smoke" in joined
    assert "drain-before-sync" in joined


def test_deep_audit_exit_code():
    """`static_audit.py --level deep` is the pre-push gate: exit 0 on
    the current tree, with both verification passes in its report."""
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "scripts",
                                      "static_audit.py"),
         "--level", "deep"],
        cwd=_ROOT, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stdout + out.stderr
    assert "mcheck smoke" in out.stdout
    assert "hazard prover" in out.stdout
