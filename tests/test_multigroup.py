"""Cross-group seed independence on the CPU oracle (VERDICT round-1
item 8): distinct group ids must yield distinct schedules and payloads
from the same config+seed, with all safety invariants intact — the
oracle's last blind spot before it certifies a 10^5-group sim."""

from __future__ import annotations

from raft_tpu.config import RaftConfig
from raft_tpu.core.cluster import Cluster
from raft_tpu.utils import rng


def test_groups_draw_distinct_schedules():
    cfg = RaftConfig(seed=3)
    deadlines = [
        [rng.election_deadline(cfg.seed, g, i, 0, cfg.election_min,
                               cfg.election_range) for i in range(cfg.k)]
        for g in range(4)]
    # Not a permutation accident: the full per-group vectors must differ.
    assert len({tuple(d) for d in deadlines}) == 4
    payloads = [rng.client_payload(cfg.seed, g, 1, 1) for g in range(4)]
    assert len(set(payloads)) == 4


def test_multi_group_runs_diverge_and_stay_safe():
    cfg = RaftConfig(seed=5, drop_prob=0.1, crash_prob=0.2, crash_epoch=48)
    clusters = [Cluster(cfg, group=g) for g in range(3)]
    for c in clusters:
        c.run(500)  # Cluster.tick raises SafetyViolation on any breach
    digests = [max(n.digest for n in c.nodes) for c in clusters]
    commits = [max(n.commit for n in c.nodes) for c in clusters]
    assert all(x > 0 for x in commits)
    # Groups consumed different payload streams -> different state machines.
    assert len(set(digests)) == 3
    # Fault schedules differ across groups: crash epochs shouldn't align.
    alive_patterns = {
        tuple(rng.node_alive(cfg.seed, g, i, t, cfg.crash_u32,
                             cfg.crash_epoch)
              for i in range(cfg.k) for t in range(0, 480, 48))
        for g in range(3)}
    assert len(alive_patterns) == 3
