"""Seed-sweep robustness: many deterministic universes with randomized
(seed-derived) feature and fault mixes on the CPU oracle. Every tick
runs the live safety checkers (election safety, commit identity); the
digest-agreement and read-quorum machinery are exercised by the feature
mix itself. Pure-Python — wide coverage per second, no XLA compiles."""

from __future__ import annotations

import pytest

from raft_tpu.config import RaftConfig
from raft_tpu.core.cluster import Cluster
from raft_tpu.utils import rng


def _universe(seed: int) -> RaftConfig:
    """A seed-derived feature/fault mix: every universe gets some
    faults; features toggle by hash bits so the sweep covers the
    pairwise combinations (prevote x reconfig x reads x transfer)."""
    h = rng.hash_u32(seed, 0xFEED)
    return RaftConfig(
        seed=seed,
        k=3 + (h & 3) if (h & 3) != 3 else 5,      # k in {3, 4, 5}
        prevote=bool(h & 4),
        read_every=8 if h & 8 else 0,
        reconfig_prob=0.7 if h & 16 else 0.0,
        reconfig_epoch=32,
        transfer_prob=0.7 if h & 32 else 0.0,
        transfer_epoch=48,
        crash_prob=0.15 + ((h >> 6) & 3) * 0.05,
        crash_epoch=48,
        partition_prob=0.2 if h & 256 else 0.0,
        partition_epoch=48,
        drop_prob=0.03,
    )


@pytest.mark.parametrize("seed", range(200, 216))
def test_fuzz_universe_safe_and_live(seed):
    cfg = _universe(seed)
    c = Cluster(cfg)
    c.run(600)   # SafetyViolation raises on any checker trip
    # Liveness: the group committed through the churn.
    assert max(n.commit for n in c.nodes) > 20, (
        f"universe {cfg} made almost no progress")
    # State-machine agreement at equal applied points.
    for a in c.nodes:
        for b in c.nodes:
            if a.applied == b.applied:
                assert a.digest == b.digest, "digest divergence"
