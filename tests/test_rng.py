"""Bit-parity of the Python and JAX counter-based RNGs (DESIGN.md §4).

The JAX side is evaluated on whole coordinate grids in a few calls (the way
the simulator uses it) — per-scalar eager dispatch is orders of magnitude
too slow for a test suite.
"""

import numpy as np

from raft_tpu.utils import rng as pr
from raft_tpu.utils import jrng as jr


def test_mix32_known_values():
    # Self-consistency anchors: if the mixer changes, every trace changes.
    assert pr.mix32(0) == 0
    vals = [pr.mix32(x) for x in (1, 2, 0xDEADBEEF, 0xFFFFFFFF)]
    assert len(set(vals)) == 4
    assert all(0 <= v <= 0xFFFFFFFF for v in vals)


def test_mix32_parity():
    xs = np.array([0, 1, 2, 3, 12345, 0xDEADBEEF, 0xFFFFFFFF], dtype=np.uint32)
    got = np.asarray(jr.mix32(xs))
    want = np.array([pr.mix32(int(x)) for x in xs], dtype=np.uint32)
    np.testing.assert_array_equal(got, want)


def test_hash_u32_parity_grid():
    G, K = 17, 5
    g = np.arange(G, dtype=np.uint32)[:, None]
    n = np.arange(K, dtype=np.uint32)[None, :]
    got = np.asarray(jr.hash_u32(42, 7, g, n))
    want = np.array(
        [[pr.hash_u32(42, 7, gi, ni) for ni in range(K)] for gi in range(G)],
        dtype=np.uint32,
    )
    np.testing.assert_array_equal(got, want)


def test_election_deadline_parity_and_range():
    seed, emin, erange = 3, 10, 10
    G, K, D = 4, 5, 6
    g = np.arange(G, dtype=np.uint32)[:, None, None]
    n = np.arange(K, dtype=np.uint32)[None, :, None]
    d = np.arange(D, dtype=np.uint32)[None, None, :]
    got = np.asarray(jr.election_deadline(seed, g, n, d, emin, erange))
    want = np.array(
        [[[pr.election_deadline(seed, gi, ni, di, emin, erange)
           for di in range(D)] for ni in range(K)] for gi in range(G)],
        dtype=np.int32,
    )
    np.testing.assert_array_equal(got, want)
    assert got.min() >= emin and got.max() < emin + erange


def test_fault_mask_parity():
    seed = 9
    drop_u32 = int(0.3 * 2**32)
    crash_u32 = int(0.2 * 2**32)
    part_u32 = int(0.5 * 2**32)
    G, K, T = 6, 3, 10
    t = np.arange(T, dtype=np.uint32)[:, None, None, None]
    g = np.arange(G, dtype=np.uint32)[None, :, None, None]
    a = np.arange(K, dtype=np.uint32)[None, None, :, None]
    b = np.arange(K, dtype=np.uint32)[None, None, None, :]

    got_alive = np.asarray(jr.node_alive(seed, g, a, t, crash_u32, 4))
    got_drop = np.asarray(jr.link_dropped(seed, g, t, a, b, drop_u32))
    got_part = np.asarray(jr.link_partitioned(seed, g, t, a, b, part_u32, 4))
    for ti in range(T):
        for gi in range(G):
            for ai in range(K):
                assert bool(got_alive[ti, gi, ai, 0]) == pr.node_alive(
                    seed, gi, ai, ti, crash_u32, 4)
                for bi in range(K):
                    assert bool(got_drop[ti, gi, ai, bi]) == pr.link_dropped(
                        seed, gi, ti, ai, bi, drop_u32)
                    assert bool(got_part[ti, gi, ai, bi]) == pr.link_partitioned(
                        seed, gi, ti, ai, bi, part_u32, 4)
    # Disabled faults take the fast path and must be all-clear.
    assert np.asarray(jr.node_alive(seed, g, a, t, 0, 4)).all()
    assert not np.asarray(jr.link_dropped(seed, g, t, a, b, 0)).any()
    assert not np.asarray(jr.link_partitioned(seed, g, t, a, b, 0, 4)).any()


def test_payload_and_digest_parity():
    seed = 1
    idx = np.arange(1, 20, dtype=np.uint32)
    got_p = np.asarray(jr.client_payload(seed, 3, 2, idx))
    want_p = np.array([pr.client_payload(seed, 3, 2, int(i)) for i in idx],
                      dtype=np.int32)
    np.testing.assert_array_equal(got_p, want_p)
    assert (got_p >= 0).all()

    d_py = 0
    d_np = np.uint32(0)
    for i in range(1, 20):
        p = int(want_p[i - 1])
        d_py = pr.digest_update(d_py, i, p)
        d_np = jr.digest_update(d_np, i, p)
    assert d_py == int(np.asarray(d_np))
