"""Client sessions / exactly-once application (dissertation §6.3).

CPU-oracle client feature (`cfg.sessions`): retried proposals commit as
duplicate log entries, but the state machine folds each (sid, seq) into
the digest exactly once on every node — so an ambiguous-failure retry
can never double-apply. The scheduled/batched universes never set
`sessions`, and `test_sessions_off_is_inert` pins that the flag's
absence leaves the digest stream untouched.
"""

from __future__ import annotations

import pytest

from raft_tpu import config as C
from raft_tpu.config import RaftConfig
from raft_tpu.core.cluster import Cluster
from raft_tpu.utils import rng


def _scfg(**kw):
    kw.setdefault("k", 3)
    kw.setdefault("sessions", True)
    kw.setdefault("cmds_per_tick", 0)   # interactive clients only
    return RaftConfig(**kw)


def _settle(c: Cluster, ticket, max_ticks=100):
    for _ in range(max_ticks):
        if ticket is not None and c.is_committed(ticket):
            return True
        c.tick()
    return False


def _expected_digest(entries):
    """Replay the session rule over committed (index, payload) pairs."""
    digest, sessions = 0, {}
    for index, payload in entries:
        if payload & C.SESSION_FLAG and not payload & C.CONFIG_FLAG:
            sid = (payload >> C.SESSION_SID_SHIFT) & C.SESSION_SID_MASK
            if sid == C.SESSION_SID_MASK:
                new_sid = index % C.SESSION_SID_MASK
                if new_sid in sessions:
                    continue
                sessions[new_sid] = -1
            else:
                seq = (payload >> C.SESSION_SEQ_SHIFT) & C.SESSION_SEQ_MASK
                if sid not in sessions or seq <= sessions[sid]:
                    continue
                sessions[sid] = seq
        digest = rng.digest_update(digest, index, payload)
    return digest


def test_duplicate_retry_folds_once():
    """The core exactly-once property: the same (sid, seq) proposed
    twice commits twice but applies once; digest matches a replay that
    skips the duplicate."""
    c = Cluster(_scfg(seed=21))
    c.run(40)
    sid = c.open_session()
    assert sid is not None
    t1 = c.propose_seq(sid, 1, 0x155)
    assert _settle(c, t1)
    t2 = c.propose_seq(sid, 1, 0x155)       # client retry, same command
    assert _settle(c, t2)
    t3 = c.propose_seq(sid, 2, 0x2AA)       # next command still applies
    assert _settle(c, t3)
    c.run(20)
    lead = c.nodes[c.leader()]
    committed = sorted(c._committed.items())
    assert lead.digest == _expected_digest(committed)
    assert lead.sessions[sid] == 2
    # the duplicate entry really is in the committed log (not elided)
    assert sum(1 for _, p in committed if p == t1[1]) == 2


def test_stale_and_unknown_session_skipped():
    c = Cluster(_scfg(seed=22))
    c.run(40)
    sid = c.open_session()
    t = c.propose_seq(sid, 5, 0x0AB)
    assert _settle(c, t)
    lead = c.nodes[c.leader()]
    d0 = lead.digest
    # stale seq: commits, but digest must not move past the replay
    t2 = c.propose_seq(sid, 4, 0x0CD)
    assert _settle(c, t2)
    c.run(5)
    assert c.nodes[c.leader()].digest == d0
    # unknown sid: also a deterministic no-op
    ghost = (sid + 1) % (C.SESSION_SID_MASK - 1)
    t3 = c.propose_seq(ghost, 1, 0x0EF)
    assert _settle(c, t3)
    c.run(5)
    assert c.nodes[c.leader()].digest == d0


def test_retry_across_leader_change():
    """The motivating scenario: propose, depose the leader before the
    client learns the outcome, retry on the new leader — applied once.
    Uses the crash-schedule override to force the leadership change."""
    c = Cluster(_scfg(seed=23, k=3))
    c.run(40)
    sid = c.open_session()
    old = c.leader()
    t1 = c.propose_seq(sid, 1, 0x111)
    assert t1 is not None
    # run just enough for replication, then crash the leader
    c.run(4)
    down_from = c.tick_count
    c.alive_fn = lambda t: [i != old for i in range(3)] \
        if t < down_from + 60 else [True] * 3
    # client never saw the ack: retry on the new leader until committed
    for _ in range(200):
        if c.is_committed(t1):
            break
        t_retry = c.propose_seq(sid, 1, 0x111)
        if t_retry is not None and _settle(c, t_retry, 60):
            break
        c.tick()
    c.alive_fn = None
    c.run(80)   # heal: old leader catches back up
    committed = sorted(c._committed.items())
    for n in c.nodes:
        if n.applied == max(i for i, _ in committed):
            assert n.digest == _expected_digest(committed)
        assert n.sessions.get(sid, 0) == 1 or n.applied < t1[0]


def test_session_table_survives_snapshot_install():
    """Dedup state rides InstallSnapshot: a node that was down across
    the duplicate window is repaired from a snapshot whose table
    already holds the (sid, seq) — the replayed duplicate must not
    fold. compact_every is small so compaction is easy to force."""
    c = Cluster(_scfg(seed=24, k=3, compact_every=4, log_cap=16))
    c.run(40)
    sid = c.open_session()
    t1 = c.propose_seq(sid, 1, 0x3A)
    assert _settle(c, t1)
    victim = (c.leader() + 1) % 3
    down_from = c.tick_count
    c.alive_fn = lambda t: [i != victim for i in range(3)] \
        if t < down_from + 80 else [True] * 3
    # duplicate + enough filler to compact the window past it
    t2 = c.propose_seq(sid, 1, 0x3A)
    assert _settle(c, t2)
    for j in range(20):
        tk = c.propose_seq(sid, 2 + j, j)
        assert _settle(c, tk)
    c.alive_fn = None
    c.run(120)  # victim restarts, gets InstallSnapshot, catches up
    committed = sorted(c._committed.items())
    top = max(i for i, _ in committed)
    want = _expected_digest(committed)
    repaired = c.nodes[victim]
    assert repaired.snap_index > t2[0], "snapshot did not cover the dup"
    assert repaired.applied == top and repaired.digest == want
    assert repaired.sessions[sid] == 21


def test_sessions_off_is_inert_and_guarded():
    """sessions=False: a payload that happens to carry bit 29 folds like
    any other (the scheduled workloads' digest streams are untouched).
    sessions=True: raw propose() with reserved bits is rejected."""
    c = Cluster(RaftConfig(k=3, seed=25, cmds_per_tick=0))
    c.run(40)
    p = C.SESSION_FLAG | 0x123
    t = c.propose(p)
    assert _settle(c, t)
    lead = c.nodes[c.leader()]
    d = 0   # plain fold of every committed entry — no session skipping
    for index, payload in sorted(c._committed.items()):
        d = rng.digest_update(d, index, payload)
    assert lead.digest == d

    cs = Cluster(_scfg(seed=26))
    cs.run(40)
    with pytest.raises(ValueError):
        cs.nodes[cs.leader()].propose(C.SESSION_FLAG | 1)
