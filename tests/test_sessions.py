"""Client sessions / exactly-once application (dissertation §6.3).

CPU-oracle client feature (`cfg.sessions`): retried proposals commit as
duplicate log entries, but the state machine folds each (sid, seq) into
the digest exactly once on every node — so an ambiguous-failure retry
can never double-apply. The scheduled/batched universes never set
`sessions`, and `test_sessions_off_is_inert` pins that the flag's
absence leaves the digest stream untouched.
"""

from __future__ import annotations

import pytest

from raft_tpu import config as C
from raft_tpu.config import RaftConfig
from raft_tpu.core.cluster import Cluster
from raft_tpu.utils import rng


def _scfg(**kw):
    kw.setdefault("k", 3)
    kw.setdefault("sessions", True)
    kw.setdefault("cmds_per_tick", 0)   # interactive clients only
    return RaftConfig(**kw)


def _settle(c: Cluster, ticket, max_ticks=100):
    for _ in range(max_ticks):
        if ticket is not None and c.is_committed(ticket):
            return True
        c.tick()
    return False


def _expected_digest(entries):
    """Replay the session rule over committed (index, payload) pairs."""
    digest, sessions = 0, {}
    for index, payload in entries:
        if payload & C.SESSION_FLAG and not payload & C.CONFIG_FLAG:
            sid = (payload >> C.SESSION_SID_SHIFT) & C.SESSION_SID_MASK
            if sid == C.SESSION_SID_MASK:
                new_sid = index % C.SESSION_SID_MASK
                if new_sid in sessions:
                    continue
                sessions[new_sid] = -1
            else:
                seq = (payload >> C.SESSION_SEQ_SHIFT) & C.SESSION_SEQ_MASK
                if sid not in sessions or seq <= sessions[sid]:
                    continue
                sessions[sid] = seq
        digest = rng.digest_update(digest, index, payload)
    return digest


def test_duplicate_retry_folds_once():
    """The core exactly-once property: the same (sid, seq) proposed
    twice commits twice but applies once; digest matches a replay that
    skips the duplicate."""
    c = Cluster(_scfg(seed=21))
    c.run(40)
    sid = c.open_session()
    assert sid is not None
    t1 = c.propose_seq(sid, 1, 0x155)
    assert _settle(c, t1)
    t2 = c.propose_seq(sid, 1, 0x155)       # client retry, same command
    assert _settle(c, t2)
    t3 = c.propose_seq(sid, 2, 0x2AA)       # next command still applies
    assert _settle(c, t3)
    c.run(20)
    lead = c.nodes[c.leader()]
    committed = sorted(c._committed.items())
    assert lead.digest == _expected_digest(committed)
    assert lead.sessions[sid] == 2
    # the duplicate entry really is in the committed log (not elided)
    assert sum(1 for _, p in committed if p == t1[1]) == 2


def test_stale_and_unknown_session_skipped():
    c = Cluster(_scfg(seed=22))
    c.run(40)
    sid = c.open_session()
    t = c.propose_seq(sid, 5, 0x0AB)
    assert _settle(c, t)
    lead = c.nodes[c.leader()]
    d0 = lead.digest
    # stale seq: commits, but digest must not move past the replay
    t2 = c.propose_seq(sid, 4, 0x0CD)
    assert _settle(c, t2)
    c.run(5)
    assert c.nodes[c.leader()].digest == d0
    # unknown sid: also a deterministic no-op
    ghost = (sid + 1) % (C.SESSION_SID_MASK - 1)
    t3 = c.propose_seq(ghost, 1, 0x0EF)
    assert _settle(c, t3)
    c.run(5)
    assert c.nodes[c.leader()].digest == d0


def test_retry_across_leader_change():
    """The motivating scenario: propose, depose the leader before the
    client learns the outcome, retry on the new leader — applied once.
    Uses the crash-schedule override to force the leadership change."""
    c = Cluster(_scfg(seed=23, k=3))
    c.run(40)
    sid = c.open_session()
    old = c.leader()
    t1 = c.propose_seq(sid, 1, 0x111)
    assert t1 is not None
    # run just enough for replication, then crash the leader
    c.run(4)
    down_from = c.tick_count
    c.alive_fn = lambda t: [i != old for i in range(3)] \
        if t < down_from + 60 else [True] * 3
    # client never saw the ack: retry on the new leader until committed
    for _ in range(200):
        if c.is_committed(t1):
            break
        t_retry = c.propose_seq(sid, 1, 0x111)
        if t_retry is not None and _settle(c, t_retry, 60):
            break
        c.tick()
    c.alive_fn = None
    c.run(80)   # heal: old leader catches back up
    committed = sorted(c._committed.items())
    for n in c.nodes:
        if n.applied == max(i for i, _ in committed):
            assert n.digest == _expected_digest(committed)
        assert n.sessions.get(sid, 0) == 1 or n.applied < t1[0]


def test_session_table_survives_snapshot_install():
    """Dedup state rides InstallSnapshot: a node that was down across
    the duplicate window is repaired from a snapshot whose table
    already holds the (sid, seq) — the replayed duplicate must not
    fold. compact_every is small so compaction is easy to force."""
    c = Cluster(_scfg(seed=24, k=3, compact_every=4, log_cap=16))
    c.run(40)
    sid = c.open_session()
    t1 = c.propose_seq(sid, 1, 0x3A)
    assert _settle(c, t1)
    victim = (c.leader() + 1) % 3
    down_from = c.tick_count
    c.alive_fn = lambda t: [i != victim for i in range(3)] \
        if t < down_from + 80 else [True] * 3
    # duplicate + enough filler to compact the window past it
    t2 = c.propose_seq(sid, 1, 0x3A)
    assert _settle(c, t2)
    for j in range(20):
        tk = c.propose_seq(sid, 2 + j, j)
        assert _settle(c, tk)
    c.alive_fn = None
    c.run(120)  # victim restarts, gets InstallSnapshot, catches up
    committed = sorted(c._committed.items())
    top = max(i for i, _ in committed)
    want = _expected_digest(committed)
    repaired = c.nodes[victim]
    assert repaired.snap_index > t2[0], "snapshot did not cover the dup"
    assert repaired.applied == top and repaired.digest == want
    assert repaired.sessions[sid] == 21


def test_session_payload_range_is_loud():
    """Out-of-range sid/seq raise ValueError (not assert — asserts are
    stripped under `python -O`, and an aliased sid would corrupt the
    exactly-once filter): sid 0x1FF is the reserved REGISTER marker,
    and seq caps at 1023 — the documented session lifetime limit."""
    assert C.session_payload(0, 0, 0) == C.SESSION_FLAG
    ok = C.session_payload(3, C.SESSION_SEQ_MASK, 7)   # last usable seq
    assert (ok >> C.SESSION_SEQ_SHIFT) & C.SESSION_SEQ_MASK == 1023
    with pytest.raises(ValueError, match="sid"):
        C.session_payload(C.SESSION_SID_MASK, 1, 0)    # reserved marker
    with pytest.raises(ValueError, match="sid"):
        C.session_payload(-1, 1, 0)
    with pytest.raises(ValueError, match="lifetime"):
        C.session_payload(0, C.SESSION_SEQ_MASK + 1, 0)
    with pytest.raises(ValueError, match="lifetime"):
        C.session_payload(0, -1, 0)


def test_open_session_reproposes_lost_register_ticket():
    """A REGISTER ticket is lost when it lands on a stale leader at an
    index where the real quorum has ALREADY committed a different
    payload: is_committed(ticket) can then never become true, and the
    old behavior burned the entire tick budget waiting on it.
    open_session must detect the steal via the commit-identity map and
    re-propose.

    Construction: isolate leader A (term 1); B wins term 2 and commits
    a session write S1 at index I; crash B and hand the first
    open_session iteration to still-alive stale A, whose next index is
    exactly I (it never saw S1) — the doomed REGISTER. Then crash A /
    revive B so a healthy term-3 leader exists for the re-proposal."""
    c = Cluster(_scfg(seed=6))
    c.run(40)
    a = c.leader()
    assert a is not None
    sid0 = c.open_session()
    assert sid0 is not None
    c.run(10)                                  # quiesce: all committed
    base_idx = c.nodes[a].last_index
    # Isolate A (it keeps its LEADER role, log frozen at base_idx) and
    # let B win term 2.
    c.transport.link_filter = lambda t, s, d: s != a and d != a
    for _ in range(60):
        if c.leader() not in (None, a):
            break
        c.tick()
    b = c.leader()
    assert b is not None and b != a
    # The competing commit at the doomed index, via the real quorum.
    s1 = c.propose_seq(sid0, 1, 0x31)
    assert s1 is not None and _settle(c, s1)
    doomed_idx = base_idx + 1
    assert s1[0] == doomed_idx and s1[1] != C.SESSION_REGISTER
    # One tick with B down (A still up): leader() now resolves to stale
    # A for open_session's first proposal; from T0 on, A is down and B
    # is back, so a healthy term-3 leader can form for the retry.
    t_bdown = c.tick_count
    c.alive_fn = lambda t, _a=a, _b=b: [
        (t < t_bdown + 1) if i == _a else
        (t >= t_bdown + 1) if i == _b else True
        for i in range(3)]
    c.tick()
    assert c.leader() == a                     # the stale-leader window
    sid = c.open_session(max_ticks=200)
    assert sid is not None, \
        "open_session burned its budget on a lost REGISTER ticket"
    # The re-proposal landed ABOVE the stolen index, on a real leader.
    assert c._committed[doomed_idx] == s1[1]
    assert c._session_owner[sid] > doomed_idx
    # And the session the caller got is live: a write through it folds.
    c.alive_fn = None
    c.transport.link_filter = None
    t1 = c.propose_seq(sid, 1, 0x42)
    assert t1 is not None and _settle(c, t1, 120)


def test_sessions_off_is_inert_and_guarded():
    """sessions=False: a payload that happens to carry bit 29 folds like
    any other (the scheduled workloads' digest streams are untouched).
    sessions=True: raw propose() with reserved bits is rejected."""
    c = Cluster(RaftConfig(k=3, seed=25, cmds_per_tick=0))
    c.run(40)
    p = C.SESSION_FLAG | 0x123
    t = c.propose(p)
    assert _settle(c, t)
    lead = c.nodes[c.leader()]
    d = 0   # plain fold of every committed entry — no session skipping
    for index, payload in sorted(c._committed.items()):
        d = rng.digest_update(d, index, payload)
    assert lead.digest == d

    cs = Cluster(_scfg(seed=26))
    cs.run(40)
    with pytest.raises(ValueError):
        cs.nodes[cs.leader()].propose(C.SESSION_FLAG | 1)
