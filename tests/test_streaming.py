"""The r16 cohort-paging layer (DESIGN.md §15): stream 1024-group
blocks host<->HBM under the unchanged fused-chunk kernel.

The contract under test: the residency knobs (config.STREAM_FIELDS)
are RESIDENCY-ONLY. With stream_groups on, the streamed runner must
stay bit-identical to the resident kernel AND the XLA path on the full
State + Metrics (+ flight ring) — including the multi-cohort shape
where G spans several blocks and each window runs several launches;
with it off, every r14 byte pin (8,308 / 11,056 B/group) and the
static ceiling are untouched. The modeled streamed ceiling must be the
exact supported() boundary against host RAM (>= 10M groups/chip at the
all-dials layout vs 4,836,352 static), checkpoints must load across
residency in both directions, and every manifest record must carry the
STREAM_KEYS from birth.
"""

from __future__ import annotations

import dataclasses
import io
import json

import numpy as np
import pytest

import conftest  # noqa: F401  (pins the CPU platform before jax loads)

from raft_tpu.config import STREAM_FIELDS, RaftConfig
from raft_tpu.parallel import cohort
from raft_tpu.sim import checkpoint, pkernel, state
from raft_tpu.sim.run import metrics_init, run
from raft_tpu.utils.trees import trees_equal, trees_equal_why

# The shared fast-tier differential universe (kmesh.faulted_64_cfg's
# shape): crash + partition + drop churn so restarts, truncations and
# ring churn actually cross the cohort windows.
FAULTED = RaftConfig(n_groups=64, k=3, seed=23, drop_prob=0.05,
                     crash_prob=0.2, crash_epoch=16, partition_prob=0.2,
                     partition_epoch=16, log_cap=8, compact_every=4)

STREAMED = dict(stream_groups=True, cohort_blocks=1)
ALL_DIALS = dict(pack_bools=True, pack_ring=True, alias_wire=True,
                 wire_hist=False)


def _headline():
    return RaftConfig(seed=42)


def _clients():
    return dataclasses.replace(_headline(), sessions=True, cmds_per_tick=0,
                               client_rate=0.2, client_slots=4,
                               client_retry_backoff=8)


# ----------------------------------------------------- residency model


def test_stream_knobs_default_off_and_wire_blind():
    """Default-off is byte-identical r14: stream_groups defaults False,
    and flipping the residency knobs moves ZERO wire bytes — the
    8,308 / 11,056 B/group pins hold with the knobs on, and the static
    resident ceiling keeps its DESIGN.md §9 figure."""
    assert RaftConfig().stream_groups is False
    for cfg, pin in ((_headline(), 8_308), (_clients(), 11_056)):
        on = dataclasses.replace(cfg, stream_groups=True, cohort_blocks=2)
        assert 4 * pkernel.wire_words_per_group(cfg) == pin
        assert 4 * pkernel.wire_words_per_group(on) == pin
        assert pkernel._n_state_leaves(on) == pkernel._n_state_leaves(cfg)
    assert pkernel.hbm_ceiling_groups(_headline()) == 1_033_216
    assert pkernel.hbm_ceiling_groups(
        dataclasses.replace(_headline(), **ALL_DIALS),
        with_flight=False) == 4_836_352


def test_streamed_ceiling_breaks_10m_and_is_exact_boundary():
    """THE r16 acceptance pin: the modeled streamed ceiling clears 10M
    groups/chip at the all-dials layout (vs 4,836,352 static resident),
    it is host-RAM arithmetic in whole blocks, and — like every ceiling
    in this repo — the EXACT supported() boundary: one more block tips
    it."""
    scfg = dataclasses.replace(_headline(), stream_groups=True, **ALL_DIALS)
    ceil = pkernel.streamed_ceiling_groups(scfg, with_flight=False)
    assert ceil >= 10_000_000
    static = pkernel.hbm_ceiling_groups(scfg, with_flight=False)
    assert ceil > 2 * static
    wire = 4 * pkernel.wire_words_per_group(scfg, with_flight=False)
    assert ceil == (pkernel.HOST_RAM_LIMIT_BYTES
                    // (wire * pkernel.GB)) * pkernel.GB
    assert ceil % pkernel.GB == 0
    assert pkernel.supported(scfg, n_groups=ceil, with_flight=False)
    assert not pkernel.supported(scfg, n_groups=ceil + pkernel.GB,
                                 with_flight=False)
    # The cohort window (not the fleet) is what must fit HBM.
    assert pkernel.cohort_hbm_bytes(scfg, with_flight=False) \
        <= pkernel.HBM_LIMIT_BYTES
    assert pkernel._stream_windows(scfg) \
        == 2 + pkernel._residency_buffers(scfg)


def test_streamed_supported_budgets_host_ram_not_hbm():
    """supported() under stream_groups answers for a G the resident
    model refuses: group counts far past the HBM ceiling are fine while
    the host wire fits, and the host budget still refuses somewhere."""
    cfg = _headline()
    scfg = dataclasses.replace(cfg, stream_groups=True)
    g = 4_000_000   # ~4x the static flight-off ceiling
    assert not pkernel.supported(cfg, n_groups=g, with_flight=False)
    assert pkernel.supported(scfg, n_groups=g, with_flight=False)
    too_big = pkernel.streamed_ceiling_groups(
        scfg, with_flight=False) + pkernel.GB
    assert not pkernel.supported(scfg, n_groups=too_big, with_flight=False)


def test_byte_model_reconciles_streamed_ceiling():
    """The engine-contract auditor's derived model agrees: the streamed
    ceiling re-derives from dtype x shape at every audited layout and
    is boundary-exact (the same three-accounting rule as the static
    ceiling)."""
    from raft_tpu.analysis import bytemodel

    for label, cfg in bytemodel.audit_cfgs():
        model = bytemodel.derived_wire_model(cfg)
        assert model["problems"] == [], (label, model["problems"])
        s = model["hbm"]["streamed"]
        assert s["boundary_exact"], label
        assert s["ceiling_groups"] % pkernel.GB == 0, label
        assert s["window_hbm_bytes"] <= pkernel.HBM_LIMIT_BYTES, label


def test_overlap_efficiency_model_and_segment_fields():
    """The overlap model is a sane fraction, the manifest producer
    stamps exactly obs.manifest.STREAM_KEYS, predicted is null on
    resident segments and computed on streamed ones, and a measured
    value passes through."""
    from raft_tpu.obs import roofline
    from raft_tpu.obs.manifest import STREAM_KEYS, STREAM_MESH_KEYS

    scfg = dataclasses.replace(_headline(), stream_groups=True)
    pred = roofline.overlap_efficiency(scfg, chunk_ticks=200)
    assert 0.0 < pred["overlap_efficiency_predicted"] <= 1.0
    assert pred["binding_side"] in ("host-link", "compute")
    # Keeping a window resident longer amortizes its two copies.
    longer = roofline.overlap_efficiency(scfg, chunk_ticks=200,
                                         ticks_per_cohort=2_000)
    assert longer["overlap_efficiency_predicted"] \
        >= pred["overlap_efficiency_predicted"]
    off = roofline.stream_segment_fields(_headline())
    # r17 grew the stamp: the producer now carries the mesh keys too
    # (null on resident segments — tests/test_stream_mesh.py pins the
    # split and the null rule).
    assert set(off) == set(STREAM_KEYS) | set(STREAM_MESH_KEYS)
    assert off["stream_groups"] is False
    assert off["overlap_efficiency_predicted"] is None
    assert off["overlap_efficiency_measured"] is None
    on = roofline.stream_segment_fields(scfg, measured=0.8125,
                                        chunk_ticks=200)
    assert on["stream_groups"] is True
    assert 0.0 < on["overlap_efficiency_predicted"] <= 1.0
    assert on["overlap_efficiency_measured"] == 0.8125


# ------------------------------------------------- engine differentials


def test_streamed_single_cohort_bit_identical():
    """THE r16 fast gate: the streamed runner over one cohort window
    (two launches, so the window re-enters kstep mid-residency) is
    bit-identical to the XLA path on full State AND full Metrics over
    the faulted universe."""
    scfg = dataclasses.replace(FAULTED, **STREAMED)
    st0 = state.init(FAULTED)
    stx, mx = run(FAULTED, st0, 48, 0, metrics_init(64))
    stp, mp = cohort.prun_streamed(scfg, st0, 48, interpret=True,
                                   chunk_ticks=24)
    ok, why = trees_equal_why(stx, stp)
    assert ok, why
    ok, why = trees_equal_why(mx, mp, names=list(type(mx)._fields))
    assert ok, why


@pytest.mark.slow
def test_streamed_multi_cohort_three_way():
    """THE r16 multi-cohort gate (slow tier: two extra interpret
    traces): G spans three blocks, cohort_blocks=1 pages three windows,
    chunk_ticks splits each residency into two launches — and the
    streamed result is bit-identical to the resident kernel (State +
    Metrics + flight ring) AND to the XLA path (State + Metrics)."""
    from raft_tpu.obs import flight_init

    g = 2_500   # pads to 3 x 1024-group blocks
    cfg = dataclasses.replace(FAULTED, n_groups=g)
    scfg = dataclasses.replace(cfg, **STREAMED)
    assert len(cohort.cohort_windows(
        scfg, [np.zeros((3 * pkernel.SUB, pkernel.LANE), np.int32)])) == 3
    st0 = state.init(cfg)
    stx, mx = run(cfg, st0, 24, 0, metrics_init(g))

    leaves, gg = pkernel.kinit(cfg, st0, flight=flight_init(g))
    leaves = pkernel.kstep(cfg, leaves, 0, 12, interpret=True)
    leaves = pkernel.kstep(cfg, leaves, 12, 12, interpret=True)
    stk, mk = pkernel.kfinish(cfg, leaves, gg)
    flk = pkernel.kflight(cfg, leaves, gg)

    stats = {}
    sts, ms, fls = cohort.prun_streamed(
        scfg, st0, 24, interpret=True, flight=flight_init(g),
        chunk_ticks=12, stats=stats)
    assert stats["cohorts"] == 3 and stats["launches"] == 6
    assert 0.0 < stats["overlap_efficiency_measured"] <= 1.0
    for ref_st, ref_m, what in ((stx, mx, "vs-xla"),
                                (stk, mk, "vs-resident-kernel")):
        ok, why = trees_equal_why(ref_st, sts)
        assert ok, (what, why)
        ok, why = trees_equal_why(ref_m, ms, names=list(type(ms)._fields))
        assert ok, (what, why)
    ok, why = trees_equal_why(flk, fls)
    assert ok, ("flight-ring", why)


def test_cohort_paging_is_identity_on_host_wire():
    """Window slicing + writeback round-trips every byte: paging moves
    state, never edits it — across an uneven tail window too."""
    cfg = dataclasses.replace(FAULTED, **STREAMED)
    host, g = cohort.host_wire(cfg, state.init(FAULTED))
    before = [a.copy() for a in host]
    for s0, s1 in cohort.cohort_windows(cfg, host):
        cohort._writeback(host, cohort._window(host, s0, s1), s0, s1)
    for i, (a, b) in enumerate(zip(before, host)):
        assert np.array_equal(a, b), i


def test_streaming_contracts_clean():
    """The auditor's r16 pass holds on the clean tree (knob gating,
    residency model, paging identity, cross-residency checkpoints)."""
    from raft_tpu.analysis import contracts

    assert contracts.streaming_problems() == []


# ------------------------------------------------------------ checkpoint


def test_checkpoint_residency_blind_both_directions():
    """config.STREAM_FIELDS never block a resume: a file saved under
    the streamed residency loads under the resident one and vice versa,
    and a pre-r16 file (embedded cfg has no stream keys at all) loads
    under a streamed cfg. Semantic mismatches still refuse."""
    cfg_off = FAULTED
    cfg_on = dataclasses.replace(FAULTED, **STREAMED)
    st = state.init(cfg_off, n_groups=4)
    met = metrics_init(4)
    for save_cfg, load_cfg in ((cfg_off, cfg_on), (cfg_on, cfg_off)):
        buf = io.BytesIO()
        checkpoint.save(buf, st, 9, metrics=met, cfg=save_cfg)
        buf.seek(0)
        st2, t2, met2 = checkpoint.load(buf, cfg=load_cfg)
        assert t2 == 9 and trees_equal(st, st2) and trees_equal(met, met2)
    # Pre-r16 file: strip the stream keys from the embedded cfg dict.
    buf = io.BytesIO()
    checkpoint.save(buf, st, 9, metrics=met, cfg=cfg_off)
    buf.seek(0)
    with np.load(buf) as z:
        data = {k: z[k] for k in z.files}
    saved = json.loads(bytes(data["__cfg__"]).decode())
    for k in STREAM_FIELDS:
        assert k in saved   # the strip below must actually strip
        saved.pop(k)
    data["__cfg__"] = np.bytes_(json.dumps(saved, sort_keys=True))
    buf = io.BytesIO()
    np.savez(buf, **data)
    buf.seek(0)
    st2, t2, _ = checkpoint.load(buf, cfg=cfg_on)
    assert t2 == 9 and trees_equal(st, st2)
    # A SEMANTIC mismatch still refuses, residency knobs notwithstanding.
    buf.seek(0)
    with pytest.raises(ValueError, match="cfg mismatch"):
        checkpoint.load(buf, cfg=dataclasses.replace(cfg_on, seed=99))


# ------------------------------------------------------------- manifests


def test_manifest_stream_keys_present_from_birth_and_backfilled():
    """r16 satellite: every manifest record carries the stream keys
    (null until stamped), history.backfill_record nulls them onto
    pre-r16 records, and the auditor's manifest pass names a side that
    forgot them — emit and backfill both."""
    from raft_tpu.analysis import contracts
    from raft_tpu.obs import history
    from raft_tpu.obs.manifest import STREAM_KEYS, emit_manifest

    assert tuple(STREAM_KEYS[:len(STREAM_FIELDS)]) == tuple(STREAM_FIELDS)
    assert tuple(history.R16_MANIFEST_KEYS) == tuple(STREAM_KEYS)
    rec = emit_manifest("probe", FAULTED, path="-")
    for k in STREAM_KEYS:
        assert k in rec and rec[k] is None
    old = {k: v for k, v in rec.items() if k not in STREAM_KEYS}
    back = history.backfill_record(old)
    for k in STREAM_KEYS:
        assert k in back and back[k] is None
    assert contracts.manifest_problems() == []
    # Drift detection both directions: an emit side that forgot the
    # keys, and a backfill side that forgot them.

    class _NoStreamManifest:

        @staticmethod
        def emit_manifest(segment, cfg, device=None, path=None, **fields):
            rec = emit_manifest(segment, cfg, device=device, path="-",
                                **fields)
            return {k: v for k, v in rec.items() if k not in STREAM_KEYS}

    probs = contracts.manifest_problems(manifest_mod=_NoStreamManifest)
    assert any("stream_groups" in p for p in probs)

    class _NoStreamHistory:

        @staticmethod
        def backfill_record(rec):
            out = dict(rec)
            for k in (history.R12_MANIFEST_KEYS + history.R13_MANIFEST_KEYS
                      + history.R14_MANIFEST_KEYS):
                out.setdefault(k, None)
            return out   # forgot the r16 keys

    probs = contracts.manifest_problems(history_mod=_NoStreamHistory)
    assert any("stream_groups" in p for p in probs)
