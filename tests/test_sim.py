"""Batched-path behavior tests: the sim on its own terms (invariants,
liveness, metrics, scale) — complementing the lockstep differential gate
with properties at group counts the oracle can't reach."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from conftest import trees_equal as _trees_equal
from raft_tpu import sim
from raft_tpu.config import RaftConfig
from raft_tpu.sim import check
from raft_tpu.sim.run import latency_quantile, unsafe_groups


def test_elects_and_commits_1k_groups():
    cfg = RaftConfig(seed=1)
    st = sim.init(cfg, n_groups=1000)
    st, m = sim.run(cfg, st, 150)
    assert bool(jnp.all(check.all_invariants(st, cfg.log_cap)))
    committed = np.asarray(m.committed)
    # Every group elected a leader and made steady progress.
    assert (committed > 50).all()
    assert int(m.elections) >= 1000


def test_latency_histogram_consistent():
    cfg = RaftConfig(seed=2)
    st = sim.init(cfg, n_groups=256)
    st, m = sim.run(cfg, st, 120)
    hist = np.asarray(m.hist)
    # Every completed election landed in a bucket.
    assert hist.sum() == int(m.elections)
    p50 = latency_quantile(m.hist, 0.5)
    p99 = latency_quantile(m.hist, 0.99)
    # First leaders appear within the first two election windows.
    assert 0 < p50 <= p99 <= 2 * (cfg.election_min + cfg.election_range)


def test_invariants_under_heavy_faults():
    cfg = RaftConfig(seed=3, drop_prob=0.1, crash_prob=0.3, crash_epoch=32,
                     partition_prob=0.4, partition_epoch=48)
    st = sim.init(cfg, n_groups=512)
    st, m = sim.run(cfg, st, 400)
    assert bool(jnp.all(check.all_invariants(st, cfg.log_cap)))
    # The per-tick safety fold held at EVERY tick, not just the endpoint
    # above — 512 groups x 400 ticks x 5 nodes of soak (DESIGN.md §8).
    assert unsafe_groups(m) == 0
    # Liveness in the large: most groups still commit through faults.
    assert (np.asarray(m.committed) > 0).mean() > 0.9


def test_run_is_resumable():
    """run(100) == run(50) twice, continuing from the returned state/t0."""
    cfg = RaftConfig(seed=4, drop_prob=0.05)
    st0 = sim.init(cfg, n_groups=32)
    a, ma = sim.run(cfg, st0, 100)
    b, mb = sim.run(cfg, st0, 50)
    b, mb = sim.run(cfg, b, 50, 50, mb)
    assert _trees_equal(a, b)
    assert np.array_equal(np.asarray(ma.committed), np.asarray(mb.committed))


def test_group_id_defines_universe():
    """Simulating groups [8, 16) standalone must reproduce exactly that
    slice of a 16-group run — the property device sharding relies on."""
    cfg = RaftConfig(seed=5, crash_prob=0.2, crash_epoch=40)
    full = sim.init(cfg, n_groups=16)
    part = jax.tree.map(lambda a: a[8:16], full)
    full, _ = sim.run(cfg, full, 80)
    part, _ = sim.run(cfg, part, 80)
    assert _trees_equal(jax.tree.map(lambda a: a[8:16], full), part)
