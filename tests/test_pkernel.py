"""Differential gate for the Pallas fused-chunk runner (sim/pkernel.py).

The kernel must be BIT-IDENTICAL to the XLA path (sim.run.run), which
the rest of the suite holds bit-identical to the CPU oracle — so these
tests transitively pin the kernel to the oracle. They run in pallas
interpret mode on the CPU test platform (conftest); the real-TPU
compile is exercised by bench.py's runtime self-check, which falls back
to the XLA path on any mismatch.
"""

from __future__ import annotations

import numpy as np
import pytest

from raft_tpu.config import CONFIG_FLAG, RaftConfig
from raft_tpu.sim import pkernel, state
from raft_tpu.sim.run import run
from raft_tpu.utils.trees import trees_equal


def _diff(cfg, n_ticks, chunks=None):
    st0 = state.init(cfg)
    stx, mx = run(cfg, st0, n_ticks)
    if chunks is None:
        stp, mp = pkernel.prun(cfg, st0, n_ticks, interpret=True)
    else:
        leaves, g = pkernel.kinit(cfg, st0)
        at = 0
        for ch in chunks:
            leaves = pkernel.kstep(cfg, leaves, at, ch, interpret=True)
            at += ch
        assert at == n_ticks
        stp, mp = pkernel.kfinish(cfg, leaves, g)
    assert trees_equal(stx, stp), "state diverged from the XLA path"
    assert np.array_equal(np.asarray(mx.committed), np.asarray(mp.committed))
    assert np.array_equal(np.asarray(mx.leaderless),
                          np.asarray(mp.leaderless))
    assert int(mx.elections) == int(mp.elections)
    assert int(mx.max_latency) == int(mp.max_latency)
    # The in-kernel per-group histogram, reduced over groups, must be
    # bit-identical to the XLA path's global scatter-add — this is what
    # lets the fault benches (p50/p99) ride the kernel engine.
    assert np.array_equal(np.asarray(mx.hist), np.asarray(mp.hist)), \
        "election-latency histogram diverged from the XLA path"
    # The in-kernel per-tick safety fold must agree bit-for-bit with the
    # XLA fold (DESIGN.md §8) — every kernel differential doubles as a
    # safety-telemetry parity check.
    assert np.array_equal(np.asarray(mx.safety), np.asarray(mp.safety)), \
        "per-tick safety bit diverged from the XLA path"
    return stp, mp


@pytest.mark.slow
def test_headline_config_small_window():
    """The headline program shape at a small ring (k=5, L=8), incl. the
    pad path (12 groups -> one 1024-group block). The true L=32 program
    is NOT exercised here: its interpret-mode CPU compile exceeds an
    hour (the L-squared apply unroll plus L-wide tree selects), which
    no test tier can carry — instead bench.py runs a strictly stronger
    gate every round: the full-shape (100K-group, L=32) full-State
    differential against the XLA path on the real TPU, which must pass
    before any kernel number is reported. Slow tier (interpret-mode
    compile ~90s — every k=5 interpret compile costs that, which is
    why the fast tier's kernel differentials are all k=3): k=5 and the
    pad path stay covered HERE, in scripts/kernel_sweep.py (universes
    cycle k in {3,4,5}), and by the full-shape k=5 bench gate."""
    _diff(RaftConfig(n_groups=12, seed=42, log_cap=8, compact_every=4), 32)


def test_fault_mix_bit_exact():
    """Crash + partition + drop — every fault class the kernel supports
    — with restarts exercising _apply_restart and mailbox filtering."""
    cfg = RaftConfig(n_groups=16, k=3, seed=7, drop_prob=0.05,
                     crash_prob=0.1, crash_epoch=16,
                     partition_prob=0.2, partition_epoch=16,
                     log_cap=8, compact_every=4)
    _diff(cfg, 56)


@pytest.mark.slow
def test_feature_mix_bit_exact():
    """Everything at once — PreVote x membership change x leadership
    transfer x scheduled reads x crash/drop faults — bit-identical to
    the XLA path. Each feature is also covered alone by the XLA-vs-
    oracle differential suite; this pins the kernel's gating of the
    full combination. Slow tier (~60s+ interpret compile); the fast
    tier keeps per-feature kernel coverage via the fault/reads/chunked
    tests, and scripts/kernel_sweep.py re-runs the full matrix."""
    cfg = RaftConfig(n_groups=6, k=3, seed=47, prevote=True,
                     reconfig_prob=0.8, reconfig_epoch=16,
                     transfer_prob=0.7, transfer_epoch=24,
                     read_every=4, crash_prob=0.15, crash_epoch=24,
                     drop_prob=0.04, log_cap=8, compact_every=4)
    stp, _ = _diff(cfg, 64)
    full = (1 << cfg.k) - 1
    assert ((np.asarray(stp.nodes.snap_voters) != full).any()
            or (np.asarray(stp.nodes.log_payload) & CONFIG_FLAG).any()), \
        "reconfig never fired - combination coverage is vacuous"


def test_scheduled_reads_bit_exact():
    """The ReadIndex pipeline in-kernel: registration (phase C), ack
    stamping (ae/is responses), completion quorum (phase A), and the
    step-down/become-leader read-drops — against the XLA path, with
    drops forcing retries."""
    cfg = RaftConfig(n_groups=12, k=3, seed=13, read_every=4,
                     drop_prob=0.05, log_cap=8, compact_every=4)
    stp, _ = _diff(cfg, 48)
    assert int(np.asarray(stp.nodes.reads_done).sum()) > 0


def test_chunked_resume_matches_single_run():
    """kstep chunk boundaries are invisible: 3 launches == one 48-tick
    run, bit-exact (the carry widens/narrows bools across the fori_loop
    AND the launch boundary — both must round-trip)."""
    cfg = RaftConfig(n_groups=8, k=3, seed=11, drop_prob=0.03,
                     log_cap=8, compact_every=4)
    _diff(cfg, 48, chunks=(16, 16, 16))


def test_fused_ae_smoke():
    """Fast interpret-mode smoke over the fused log-match path: crash
    churn forces re-elections (terms advance past the initial election,
    so stale-leader AppendEntries and the fast-backup/conflict form of
    the packed ring-compare execute) while commits keep flowing, at a
    shape small enough to compile in the fast tier. Histogram asserted
    identical by _diff (elections complete under the crash schedule)."""
    cfg = RaftConfig(n_groups=8, k=3, seed=40, crash_prob=0.5,
                     crash_epoch=8, drop_prob=0.05,
                     log_cap=8, compact_every=4)
    stp, _ = _diff(cfg, 32)
    assert int(np.asarray(stp.nodes.term).max()) > 1, \
        "no leadership churn - fused conflict/backup coverage is vacuous"
    assert int(np.asarray(stp.nodes.commit).max()) > 0, \
        "nothing committed - fused append coverage is vacuous"


def test_every_batched_feature_supported():
    """The kernel is feature-complete with the batched path: every
    schedule combination reports supported (the ValueError path in prun
    stays for out-of-budget shapes)."""
    for cfg in (RaftConfig(prevote=True),
                RaftConfig(reconfig_prob=0.5),
                RaftConfig(transfer_prob=0.5),
                RaftConfig(read_every=4)):
        assert pkernel.supported(cfg)


def test_supported_rejects_oversized_shapes():
    """supported() is a real predicate now: shapes whose per-block VMEM
    footprint cannot fit the compiler budget (or whose voter bitmask
    would overflow an i32 lane) are rejected, and prun refuses them
    loudly instead of dying inside Mosaic."""
    big = RaftConfig(k=25, log_cap=4096, compact_every=64)
    assert pkernel.kernel_vmem_bytes(big) > pkernel.VMEM_LIMIT_BYTES
    assert not pkernel.supported(big)
    with pytest.raises(ValueError, match="unsupported"):
        pkernel.prun(big, state.init(big), 1, interpret=True)
    assert not pkernel.supported(RaftConfig(k=31, election_min=5))
    # The default/headline shape stays comfortably inside the budget.
    assert pkernel.kernel_vmem_bytes(RaftConfig()) \
        < pkernel.VMEM_LIMIT_BYTES // 2


def test_engine_hop_via_checkpoint(tmp_path):
    """Interop: run the first half in the kernel, checkpoint the
    finished State, reload, finish on the XLA path — bit-equal to an
    unbroken XLA run. The kernel is a drop-in engine for the same
    universe, checkpoints included."""
    from raft_tpu.sim import checkpoint
    cfg = RaftConfig(n_groups=8, k=3, seed=17, drop_prob=0.04,
                     log_cap=8, compact_every=4)
    st0 = state.init(cfg)
    stp, mp = pkernel.prun(cfg, st0, 24, interpret=True)
    path = tmp_path / "ckpt.npz"
    checkpoint.save(path, stp, 24, mp, cfg=cfg)
    st1, t1, m1 = checkpoint.load(path, cfg=cfg)
    resumed, mr = run(cfg, st1, 24, t1, m1)
    unbroken, mu = run(cfg, st0, 48)
    assert trees_equal(unbroken, resumed)
    assert np.array_equal(np.asarray(mu.committed), np.asarray(mr.committed))


def test_kstate_round_trip():
    """kinit -> kfinish with zero ticks is the identity on State (and
    on the Flight ring when one rides the wire)."""
    from raft_tpu.obs import flight_init

    cfg = RaftConfig(n_groups=10, k=4, seed=3)
    st0 = state.init(cfg)
    leaves, g = pkernel.kinit(cfg, st0)
    st1, met = pkernel.kfinish(cfg, leaves, g)
    assert trees_equal(st0, st1)
    assert pkernel.kcommitted(cfg, leaves, g) == 0
    assert pkernel.kelections(cfg, leaves, g) == 0
    assert pkernel.kflight(cfg, leaves, g) is None
    fleaves, g = pkernel.kinit(cfg, st0, flight=flight_init(10))
    st2, _ = pkernel.kfinish(cfg, fleaves, g)
    assert trees_equal(st0, st2)
    assert trees_equal(pkernel.kflight(cfg, fleaves, g), flight_init(10))


def test_safety_bit_parity_faulted_64_groups():
    """The per-tick safety fold, XLA vs Pallas on a faulted 64-group
    schedule (crash + partition + drop): the two engines' safety bits
    must be bit-identical (asserted inside _diff), every group must
    have folded a real tick history (elections happened), and the run
    must be clean — 64 groups x 48 ticks x 3 nodes of soak."""
    from raft_tpu.sim.run import unsafe_groups

    cfg = RaftConfig(n_groups=64, k=3, seed=23, drop_prob=0.05,
                     crash_prob=0.2, crash_epoch=16,
                     partition_prob=0.2, partition_epoch=16,
                     log_cap=8, compact_every=4)
    stp, mp = _diff(cfg, 48)
    assert int(mp.elections) > 0, "no elections - safety soak is vacuous"
    assert unsafe_groups(mp) == 0
    assert mp.safety.shape == (64,)


def test_flight_ring_parity_in_kernel():
    """The in-kernel flight-recorder ring (six per-group [RING, 8, 128]
    accumulator leaves) must be bit-identical to the XLA recorder's
    [RING, G] rings at the same tick, crash churn included."""
    from raft_tpu.obs import flight_init, run_recorded

    cfg = RaftConfig(n_groups=8, k=3, seed=40, crash_prob=0.5,
                     crash_epoch=8, drop_prob=0.05,
                     log_cap=8, compact_every=4)
    st0 = state.init(cfg)
    stx, mx, fx = run_recorded(cfg, st0, 32)
    stp, mp, fp = pkernel.prun(cfg, st0, 32, interpret=True,
                               flight=flight_init(8))
    assert trees_equal(stx, stp)
    assert trees_equal(mx, mp)
    assert trees_equal(fx, fp), "flight ring diverged from the XLA path"
    assert int(np.asarray(fp.elections).sum()) == int(mp.elections), \
        "ring elections do not cross-check the metrics fold"
