"""Differential gate for the Pallas fused-chunk runner (sim/pkernel.py).

The kernel must be BIT-IDENTICAL to the XLA path (sim.run.run), which
the rest of the suite holds bit-identical to the CPU oracle — so these
tests transitively pin the kernel to the oracle. They run in pallas
interpret mode on the CPU test platform (conftest); the real-TPU
compile is exercised by bench.py's runtime self-check, which falls back
to the XLA path on any mismatch.
"""

from __future__ import annotations

import numpy as np
import pytest

from raft_tpu.config import CONFIG_FLAG, RaftConfig
from raft_tpu.sim import pkernel, state
from raft_tpu.sim.run import run


def trees_equal(a, b) -> bool:
    """Byte-identical pytree comparison (leaf-count mismatch fails)."""
    import jax
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


def _diff(cfg, n_ticks, chunks=None):
    st0 = state.init(cfg)
    stx, mx = run(cfg, st0, n_ticks)
    if chunks is None:
        stp, mp = pkernel.prun(cfg, st0, n_ticks, interpret=True)
    else:
        leaves, g = pkernel.kinit(cfg, st0)
        at = 0
        for ch in chunks:
            leaves = pkernel.kstep(cfg, leaves, at, ch, interpret=True)
            at += ch
        assert at == n_ticks
        stp, mp = pkernel.kfinish(cfg, leaves, g)
    assert trees_equal(stx, stp), "state diverged from the XLA path"
    assert np.array_equal(np.asarray(mx.committed), np.asarray(mp.committed))
    assert np.array_equal(np.asarray(mx.leaderless),
                          np.asarray(mp.leaderless))
    assert int(mx.elections) == int(mp.elections)
    assert int(mx.max_latency) == int(mp.max_latency)
    return stp


def test_headline_config_small_window():
    """The headline program shape at a small ring (k=5, L=8), incl. the
    pad path (12 groups -> one 1024-group block). The true L=32 program
    is NOT exercised here: its interpret-mode CPU compile exceeds an
    hour (the L-squared apply unroll plus L-wide tree selects), which
    no test tier can carry — instead bench.py runs a strictly stronger
    gate every round: the full-shape (100K-group, L=32) committed-
    vector differential against the XLA path on the real TPU, which
    must pass before any kernel number is reported."""
    _diff(RaftConfig(n_groups=12, seed=42, log_cap=8, compact_every=4), 32)


def test_fault_mix_bit_exact():
    """Crash + partition + drop — every fault class the kernel supports
    — with restarts exercising _apply_restart and mailbox filtering."""
    cfg = RaftConfig(n_groups=16, k=3, seed=7, drop_prob=0.05,
                     crash_prob=0.1, crash_epoch=16,
                     partition_prob=0.2, partition_epoch=16,
                     log_cap=8, compact_every=4)
    _diff(cfg, 56)


def test_feature_mix_bit_exact():
    """Everything at once — PreVote x membership change x leadership
    transfer x scheduled reads x crash/drop faults — bit-identical to
    the XLA path. Each feature is also covered alone by the XLA-vs-
    oracle differential suite; this pins the kernel's gating of the
    full combination."""
    cfg = RaftConfig(n_groups=6, k=3, seed=47, prevote=True,
                     reconfig_prob=0.8, reconfig_epoch=16,
                     transfer_prob=0.7, transfer_epoch=24,
                     read_every=4, crash_prob=0.15, crash_epoch=24,
                     drop_prob=0.04, log_cap=8, compact_every=4)
    stp = _diff(cfg, 64)
    full = (1 << cfg.k) - 1
    assert ((np.asarray(stp.nodes.snap_voters) != full).any()
            or (np.asarray(stp.nodes.log_payload) & CONFIG_FLAG).any()), \
        "reconfig never fired - combination coverage is vacuous"


def test_scheduled_reads_bit_exact():
    """The ReadIndex pipeline in-kernel: registration (phase C), ack
    stamping (ae/is responses), completion quorum (phase A), and the
    step-down/become-leader read-drops — against the XLA path, with
    drops forcing retries."""
    cfg = RaftConfig(n_groups=12, k=3, seed=13, read_every=4,
                     drop_prob=0.05, log_cap=8, compact_every=4)
    stp = _diff(cfg, 48)
    assert int(np.asarray(stp.nodes.reads_done).sum()) > 0


def test_chunked_resume_matches_single_run():
    """kstep chunk boundaries are invisible: 3 launches == one 48-tick
    run, bit-exact (the carry widens/narrows bools across the fori_loop
    AND the launch boundary — both must round-trip)."""
    cfg = RaftConfig(n_groups=8, k=3, seed=11, drop_prob=0.03,
                     log_cap=8, compact_every=4)
    _diff(cfg, 48, chunks=(16, 16, 16))


def test_every_batched_feature_supported():
    """The kernel is feature-complete with the batched path: every
    schedule combination reports supported (the ValueError path in prun
    stays for any future out-of-subset feature)."""
    for cfg in (RaftConfig(prevote=True),
                RaftConfig(reconfig_prob=0.5),
                RaftConfig(transfer_prob=0.5),
                RaftConfig(read_every=4)):
        assert pkernel.supported(cfg)


def test_engine_hop_via_checkpoint(tmp_path):
    """Interop: run the first half in the kernel, checkpoint the
    finished State, reload, finish on the XLA path — bit-equal to an
    unbroken XLA run. The kernel is a drop-in engine for the same
    universe, checkpoints included."""
    from raft_tpu.sim import checkpoint
    cfg = RaftConfig(n_groups=8, k=3, seed=17, drop_prob=0.04,
                     log_cap=8, compact_every=4)
    st0 = state.init(cfg)
    stp, mp = pkernel.prun(cfg, st0, 24, interpret=True)
    path = tmp_path / "ckpt.npz"
    checkpoint.save(path, stp, 24, mp, cfg=cfg)
    st1, t1, m1 = checkpoint.load(path, cfg=cfg)
    resumed, mr = run(cfg, st1, 24, t1, m1)
    unbroken, mu = run(cfg, st0, 48)
    assert trees_equal(unbroken, resumed)
    assert np.array_equal(np.asarray(mu.committed), np.asarray(mr.committed))


def test_kstate_round_trip():
    """kinit -> kfinish with zero ticks is the identity on State."""
    cfg = RaftConfig(n_groups=10, k=4, seed=3)
    st0 = state.init(cfg)
    leaves, g = pkernel.kinit(cfg, st0)
    st1, met = pkernel.kfinish(cfg, leaves, g)
    assert trees_equal(st0, st1)
    assert pkernel.kcommitted(leaves, g) == 0
    assert pkernel.kelections(leaves, g) == 0
