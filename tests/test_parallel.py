"""Multi-device sharding tests on the virtual 8-CPU mesh: the sharded
run must be bit-identical to the unsharded reference, and the psum'd
global metrics must equal the local aggregation (VERDICT round-1
items 4/5 — the in-repo multi-device evidence for dryrun_multichip)."""

from __future__ import annotations

import jax
import numpy as np
import pytest

from raft_tpu import parallel, sim
from raft_tpu.config import RaftConfig
from raft_tpu.sim import check


def test_eight_device_mesh_available():
    assert len(jax.devices()) >= 8, (
        "conftest.py must force an 8-device CPU platform")


def test_sharded_run_matches_unsharded():
    cfg = RaftConfig(seed=9, drop_prob=0.05, crash_prob=0.2, crash_epoch=32)
    n_ticks, n_groups = 120, 64
    ref_st, ref_m = sim.run(cfg, sim.init(cfg, n_groups=n_groups), n_ticks)

    mesh = parallel.make_mesh(8)
    st = parallel.shard_state(sim.init(cfg, n_groups=n_groups), mesh)
    st, gm = parallel.run_sharded(cfg, st, n_ticks, mesh)

    for ref_leaf, leaf in zip(jax.tree.leaves(ref_st), jax.tree.leaves(st)):
        assert np.array_equal(np.asarray(ref_leaf), np.asarray(leaf))
    assert int(gm.rounds) == int(np.asarray(ref_m.committed).sum())
    assert int(gm.elections) == int(ref_m.elections)
    assert np.array_equal(np.asarray(gm.hist), np.asarray(ref_m.hist))
    # The psum'd per-tick safety verdict equals the local fold's.
    assert int(gm.unsafe) == int((np.asarray(ref_m.safety) == 0).sum()) == 0
    assert bool(np.all(np.asarray(check.all_invariants(st, cfg.log_cap))))


def test_sharded_state_actually_sharded():
    mesh = parallel.make_mesh(8)
    st = parallel.shard_state(sim.init(RaftConfig(), n_groups=64), mesh)
    shard_devs = {s.device for s in st.nodes.term.addressable_shards}
    assert len(shard_devs) == 8


def test_make_mesh_refuses_silent_cpu_fallback():
    """Asking for more devices than the platform has must raise unless
    the caller opts into the CPU test vehicle (VERDICT round-4 item 6)."""
    n_too_many = len(jax.devices()) + 1
    with pytest.raises(ValueError):
        parallel.make_mesh(n_too_many)
    # With the flag, the request still raises here (the CPU platform
    # itself has only 8 virtual devices) — but via the same explicit
    # error, not a silent platform swap.
    with pytest.raises(ValueError):
        parallel.make_mesh(len(jax.devices("cpu")) + 1,
                           allow_cpu_fallback=True)
