"""PreVote (Raft dissertation §9.6) on the CPU oracle: pre-ballots
don't bump terms, the lease check protects a healthy leader, and a
rejoining partitioned node cannot inflate terms or depose the regime —
the disruption scenario the feature exists to prevent (VERDICT round-4
item 4). Pure-Python; the batched-path parity is pinned by
tests/test_differential.py::test_differential_prevote*."""

from __future__ import annotations

from raft_tpu.config import RaftConfig
from raft_tpu.core import rpc
from raft_tpu.core.cluster import Cluster
from raft_tpu.core.node import LEADER, PRECANDIDATE


def _elect(c: Cluster, max_ticks: int = 300) -> int:
    for _ in range(max_ticks):
        if c.leader() is not None:
            return c.leader()
        c.tick()
    raise AssertionError("no leader elected")


def test_prevote_elects_and_commits():
    c = Cluster(RaftConfig(seed=80, prevote=True))
    _elect(c)
    before = max(n.commit for n in c.nodes)
    c.run(40)
    assert max(n.commit for n in c.nodes) > before


def test_prevote_reelection_after_leader_crash():
    """Liveness through the lease: after the leader dies, followers'
    lease clocks run out and a pre-ballot quorum forms a new regime."""
    cfg = RaftConfig(seed=81, prevote=True)
    c = Cluster(cfg)
    old = _elect(c)
    c.alive_fn = lambda t, dead=old: [i != dead for i in range(cfg.k)]
    for _ in range(20 * (cfg.election_min + cfg.election_range)):
        c.tick()
        lead = c.leader()
        if lead is not None and lead != old:
            break
    assert c.leader() is not None and c.leader() != old
    before = max(n.commit for n in c.nodes)
    c.run(40)
    assert max(n.commit for n in c.nodes) > before


def test_prevote_prevents_term_inflation_and_disruption():
    """The headline scenario: an isolated node times out over and over
    but never bumps its term (pre-ballots are non-binding), so when the
    partition heals it slots back in as a follower and the leader's
    regime survives untouched."""
    cfg = RaftConfig(seed=82, prevote=True)
    c = Cluster(cfg)
    lead = _elect(c)
    v = (lead + 1) % cfg.k
    c.transport.link_filter = lambda t, s, d, v=v: s != v and d != v
    c.run(200)
    # Isolation: the victim cycled pre-candidacies without a term bump.
    assert c.nodes[v].term == c.nodes[lead].term
    from raft_tpu.core.node import FOLLOWER
    assert c.nodes[v].role in (PRECANDIDATE, FOLLOWER)
    term_before_heal = c.nodes[lead].term
    c.transport.link_filter = None
    c.run(60)
    # No disruption: same leader, same term, victim follows again.
    assert c.leader() == lead
    assert c.nodes[lead].term == term_before_heal
    assert c.nodes[v].leader_id == lead


def test_without_prevote_rejoin_disrupts():
    """Control documenting the problem: with prevote off, the isolated
    node's term inflates with every timeout and the heal deposes the
    healthy leader — the disruption PreVote removes."""
    cfg = RaftConfig(seed=82, prevote=False)   # same seed as above
    c = Cluster(cfg)
    lead = _elect(c)
    v = (lead + 1) % cfg.k
    c.transport.link_filter = lambda t, s, d, v=v: s != v and d != v
    c.run(200)
    assert c.nodes[v].term > c.nodes[lead].term   # inflated
    term_before_heal = c.nodes[lead].term
    c.transport.link_filter = None
    c.run(60)
    assert max(n.term for n in c.nodes) > term_before_heal   # deposed


def test_prevote_lease_denies_near_healthy_leader():
    """A follower in steady heartbeat contact must refuse pre-votes even
    for a perfect log: the lease check is what stops a disruptor that
    somehow reaches a healthy quorum."""
    cfg = RaftConfig(seed=83, prevote=True)
    c = Cluster(cfg)
    lead = _elect(c)
    c.run(10)   # steady heartbeats: lease constantly renewed
    f = (lead + 1) % cfg.k
    n = c.nodes[f]
    assert n.leader_elapsed < cfg.election_min
    n._on_pv_req(rpc.PreVoteReq(
        rpc.PV_REQ, src=(lead + 2) % cfg.k, dst=f,
        term=n.term + 5, last_log_index=10 ** 6, last_log_term=10 ** 6))
    resp = [m for m in c.transport._outbox if m.type == rpc.PV_RESP][-1]
    assert resp.granted is False
    # The same probe is granted once the lease has lapsed.
    n.leader_elapsed = cfg.election_min
    n._on_pv_req(rpc.PreVoteReq(
        rpc.PV_REQ, src=(lead + 2) % cfg.k, dst=f,
        term=n.term + 5, last_log_index=10 ** 6, last_log_term=10 ** 6))
    resp = [m for m in c.transport._outbox if m.type == rpc.PV_RESP][-1]
    assert resp.granted is True
