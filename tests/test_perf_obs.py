"""Performance-observability tests (DESIGN.md §12): the roofline stamp
is pinned at the published wire layouts (8,308 / 11,056 B/group), the
bench-history tracker reads the checked-in BENCH_r* trajectory and
flags the r02->r05 XLA fade at the 0.15 threshold, Chrome trace-event
output schema-validates with distinct compile/warmup/timed + per-chunk
spans, the soak heartbeat emits health records, the segment wall-key
set is normalized through ONE producer, and the static-audit CLI +
bench_history --check both run as fast tier-1 gates."""

from __future__ import annotations

import inspect
import json
import os
import subprocess
import sys

import conftest  # noqa: F401  (pins the CPU platform before jax loads)
from raft_tpu.config import RaftConfig
from raft_tpu.obs import (ROOFLINE_KEYS, Heartbeat, Tracer, history,
                          roofline, set_heartbeat, set_tracer,
                          validate_trace)
from raft_tpu.obs import trace as obs_trace

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)   # for `import bench`

CFG = RaftConfig(n_groups=8, k=3, seed=21, drop_prob=0.05, crash_prob=0.2,
                 crash_epoch=16, log_cap=8, compact_every=4)


# ----------------------------------------------------------- roofline


def test_roofline_pinned_at_published_wire_layouts():
    """The prediction rides the PR-11 reconciled byte model — pinned
    here at the two published layouts so a layout change that forgets
    the roofline shows up as a failed pin, not a silently wrong
    ceiling."""
    from raft_tpu.analysis import bytemodel
    for cfg, pinned in ((bytemodel.headline_cfg(), 8_308),
                        (bytemodel.clients_cfg(), 11_056)):
        r = roofline.roofline(cfg, 100_000, "pallas-fused-chunk",
                              chunk_ticks=200, flops=False)
        assert r["wire_bytes_per_group"] == pinned
        assert r["predicted_ticks_per_sec"] > 0
        assert r["bound"] == "hbm"   # no flops probe -> hbm side binds


def test_engine_class_prefix_not_substring():
    """A fallback engine string names the engine that STOOD — it must
    not price under the kernel's byte model."""
    for mod in (roofline, history):
        assert mod.engine_class("pallas-fused-chunk") == "pallas"
        assert mod.engine_class("pallas-fused-chunk-sharded-8dev") \
            == "pallas"
        assert mod.engine_class("xla-scan (pallas mismatch!)") == "xla"
        assert mod.engine_class("xla-scan (pallas error: XlaRuntimeError)"
                                ) == "xla"
        assert mod.engine_class(None) == "xla"


def test_roofline_attainment_and_bound():
    cfg = RaftConfig(seed=42)
    r = roofline.roofline(cfg, 100_000, "xla-scan",
                          measured_ticks_per_sec=78.0, flops=False)
    # XLA must move at least the resident native state both ways.
    assert r["bytes_per_tick_per_chip"] == \
        2 * r["resident_bytes_per_group"] * 100_000
    assert abs(r["attainment_pct"]
               - 100.0 * 78.0 / r["predicted_ticks_per_sec"]) < 1e-9
    # The kernel moves the wire once per chunk: per-tick traffic is
    # chunk_ticks-fold smaller, so its hbm-side ceiling must dwarf the
    # XLA path's.
    rk = roofline.roofline(cfg, 100_000, "pallas-fused-chunk",
                           chunk_ticks=200, flops=False)
    assert rk["predicted_ticks_per_sec"] > 50 * r["predicted_ticks_per_sec"]
    # rounds/tick basis: headline commits cmds_per_tick per group.
    assert r["rounds_per_tick"] == 100_000 * cfg.cmds_per_tick


def test_roofline_prediction_runs_without_measurement():
    """The CPU-box contract: prediction always runs; attainment is
    null; the three stamp fields are present."""
    f = roofline.segment_fields(RaftConfig(seed=42), 1_000, "xla-scan",
                                ticks=200, timed_wall_s=1.0,
                                measured=False, flops=False)
    assert set(roofline.ROOFLINE_FIELDS) <= set(f)
    assert f["attainment_pct"] is None
    assert f["bound"] == "hbm"
    assert f["predicted_rounds_per_sec"] > 0
    assert f["roofline"]["measured_ticks_per_sec"] is None


def test_roofline_peak_env_override(monkeypatch):
    cfg = RaftConfig(seed=42)
    base = roofline.roofline(cfg, 10_000, "xla-scan", flops=False)
    monkeypatch.setenv(roofline.HBM_ENV,
                       str(2 * roofline.DEFAULT_HBM_GBPS))
    fast = roofline.roofline(cfg, 10_000, "xla-scan", flops=False)
    assert abs(fast["predicted_ticks_per_sec"]
               - 2 * base["predicted_ticks_per_sec"]) < 1e-6


# ------------------------------------------------------- bench history


def test_history_parses_checked_in_trajectory():
    rows = history.load_history(ROOT, manifest="-")
    s = history.series(rows)
    xla = s[("throughput", "xla", "rounds/s")]
    vals = [r["value"] for r in xla]
    # r02 7.18M (parsed), r03 5.71M, r04 5.07M — the fade, in order.
    assert 7182986.4 in vals and 5706722.7 in vals and 5065337.2 in vals
    assert vals.index(7182986.4) < vals.index(5065337.2)
    # The r05 kernel headline lands in its own series.
    pal = s[("throughput", "pallas", "rounds/s")]
    assert any(abs(r["value"] - 29271972.8) < 1 for r in pal)
    table = history.trend_table(rows)
    assert "throughput [xla]" in table and "-29.5% best" in table


def test_history_flags_the_xla_fade_at_015():
    rows = history.load_history(ROOT, manifest="-")
    regs = history.regressions(rows, threshold=0.15)
    hit = [r for r in regs if r["segment"] == "throughput"
           and r["engine"] == "xla"]
    assert len(hit) == 1
    assert hit[0]["drop_pct"] >= 15
    assert hit[0]["best_source"] == "BENCH_r02.json"
    assert hit[0]["latest_source"] == "BENCH_r04.json"
    # And the fade is under 50%, so a loose gate stays quiet.
    assert not [r for r in history.regressions(rows, threshold=0.50)
                if r["segment"] == "throughput"]


def test_history_manifest_backfill_round_trip():
    old = {"schema": 1, "segment": "throughput", "engine": "xla-scan",
           "rounds_per_sec": 5.0}
    back = history.backfill_record(old)
    for k in history.R12_MANIFEST_KEYS:
        assert back[k] is None
    assert back["rounds_per_sec"] == 5.0
    # A stamped record keeps its values through backfill.
    stamped = dict(old, bound="hbm", attainment_pct=10.0)
    assert history.backfill_record(stamped)["bound"] == "hbm"


def test_bench_history_script_table_and_check(tmp_path):
    """The acceptance run: the script on the checked-in JSONs prints
    the full trajectory and exits 0; --check --threshold 0.15 exits
    nonzero flagging the XLA throughput regression."""
    script = os.path.join(ROOT, "scripts", "bench_history.py")
    r = subprocess.run([sys.executable, script, "--root", ROOT,
                        "--manifest", "-"],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "throughput [xla]" in r.stdout
    assert "7,182,986" in r.stdout and "5,065,337" in r.stdout
    r2 = subprocess.run([sys.executable, script, "--root", ROOT,
                         "--manifest", "-", "--check",
                         "--threshold", "0.15"],
                        capture_output=True, text=True, timeout=120)
    assert r2.returncode == 2
    assert "REGRESSION: throughput [xla]" in r2.stderr


def test_audit_cli_static_level():
    """`raft-tpu-audit --level static` (via its script body) as a fast
    tier-1 gate next to the history check — the manifest-coverage pass
    now rides contract_problems, so this also proves the r12 keys."""
    script = os.path.join(ROOT, "scripts", "static_audit.py")
    r = subprocess.run([sys.executable, script, "--level", "static"],
                       capture_output=True, text=True, timeout=300,
                       env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr


def test_manifest_coverage_pass_names_drift():
    """Synthetic drift: a manifest module whose records lack the
    roofline keys, and a history module that forgets the backfill —
    the auditor names both."""
    from raft_tpu.analysis import contracts
    from raft_tpu.obs import manifest as real_manifest
    assert contracts.manifest_problems() == []

    class _BadManifest:
        ROOFLINE_KEYS = real_manifest.ROOFLINE_KEYS

        @staticmethod
        def emit_manifest(segment, cfg, path=None, **fields):
            rec = real_manifest.emit_manifest(segment, cfg, path="-",
                                              **fields)
            for k in real_manifest.ROOFLINE_KEYS:
                rec.pop(k, None)
            rec.update(fields)
            return rec

    probs = contracts.manifest_problems(manifest_mod=_BadManifest)
    assert any("predicted_rounds_per_sec" in p for p in probs)

    class _BadHistory:
        @staticmethod
        def backfill_record(rec):
            return dict(rec)   # forgot the keys

    probs = contracts.manifest_problems(history_mod=_BadHistory)
    assert any("backfill_record" in p for p in probs)


# ------------------------------------------------------ trace + spans


def test_tracer_chrome_schema(tmp_path):
    t = Tracer()
    with t.span("segment a", cat=obs_trace.CAT_SEGMENT):
        with t.span("warmup+compile xla [a]"):
            pass
        with t.span("timed xla [a]"):
            prev = set_tracer(t)
            try:
                # Both engines' chunk spans go through the ONE producer.
                with obs_trace.chunk_span("xla", 0, 8, phase="timed"):
                    pass
                with obs_trace.chunk_span("pallas", 8, 8, phase="timed"):
                    pass
            finally:
                set_tracer(prev)

    @t.traced("decorated")
    def f():
        return 7

    assert f() == 7
    t.instant("marker", note="x")
    path = t.save(str(tmp_path / "trace.json"))
    with open(path) as fh:
        obj = json.load(fh)
    assert validate_trace(obj) == []
    names = [e["name"] for e in obj["traceEvents"]]
    assert "segment a" in names and "decorated" in names
    assert "chunk xla [0,8)" in names and "chunk pallas [8,16)" in names
    cats = {e["name"]: e["cat"] for e in obj["traceEvents"]}
    assert cats["chunk xla [0,8)"] == obs_trace.CAT_CHUNK
    assert cats["segment a"] == obs_trace.CAT_SEGMENT
    # The validator actually rejects malformed events.
    assert validate_trace({"traceEvents": [{"name": "x", "ph": "X",
                                            "ts": 0.0, "pid": 1,
                                            "tid": 1}]}) != []
    assert validate_trace({"x": 1}) != []


def test_bench_timed_chunks_emits_phase_and_chunk_spans(tmp_path):
    """The XLA bench harness under a tracer: distinct warmup/timed
    spans and one chunk span per device call, schema-valid — the
    runtime half of the --trace-dir acceptance (the kernel half shares
    the same chunk_span producer, pinned below)."""
    import bench
    from raft_tpu.sim.run import total_rounds
    t = Tracer()
    hb_path = tmp_path / "hb.jsonl"
    prev = set_tracer(t)
    prev_hb = set_heartbeat(Heartbeat(str(hb_path), every=1))
    try:
        bench._timed_chunks(CFG, 8, 16, lambda st, m: total_rounds(m),
                            label="span-test", chunk=8)
    finally:
        set_tracer(prev)
        set_heartbeat(prev_hb)
    obj = t.to_json()
    assert validate_trace(obj) == []
    names = [e["name"] for e in obj["traceEvents"]]
    assert "warmup+compile xla [span-test]" in names
    assert "timed xla [span-test]" in names
    chunks = [e for e in obj["traceEvents"]
              if e["cat"] == obs_trace.CAT_CHUNK]
    assert len(chunks) == 3          # 1 warmup + 2 timed
    phases = {e["args"]["phase"] for e in chunks}
    assert phases == {"warmup", "timed"}
    # The heartbeat rode the timed loop.
    recs = [json.loads(ln) for ln in hb_path.read_text().splitlines()]
    assert recs and recs[0]["label"] == "span-test"
    for k in ("tick", "rounds_total", "elections_total", "safety_ok",
              "leaderless_groups", "ring_elections", "election_storm",
              "leaderless_stall"):
        assert k in recs[0]


def test_kernel_paths_share_the_chunk_span_producer():
    """Both kernel drivers (bench loops, prun, prun_sharded) emit their
    per-chunk spans through obs.trace.chunk_span — pinned at source
    level because a kernel launch needs a TPU (or a minutes-long
    interpret compile) this tier cannot pay."""
    import bench
    from raft_tpu.parallel import kmesh
    from raft_tpu.sim import pkernel
    for fn in (bench._pallas_segment, bench._pallas_full_run,
               pkernel.prun, kmesh.prun_sharded):
        assert "chunk_span" in inspect.getsource(fn), fn.__name__


def test_heartbeat_wire_beats_on_the_kernel_form(tmp_path):
    """The kernel-engine heartbeat reads health straight off the wire
    tuple (no kernel launch needed: kinit + the counter helpers are
    host-side) — the promoted-engine soak stays observable."""
    from raft_tpu import sim
    from raft_tpu.sim import pkernel
    leaves, g = pkernel.kinit(CFG, sim.init(CFG))
    hb = Heartbeat(str(tmp_path / "hb.jsonl"), every=2)
    rec = hb.beat_wire("pallas:smoke", 200, CFG, leaves, g)
    assert rec is not None and rec["engine"] == "pallas"
    assert rec["tick"] == 200 and rec["safety_ok"]
    assert rec["rounds_total"] == pkernel.kcommitted(CFG, leaves, g)
    assert hb.beat_wire("pallas:smoke", 400, CFG, leaves, g) is None
    assert hb.beat_wire("pallas:smoke", 600, CFG, leaves, g) is not None
    # And the bench kernel loops call it.
    import bench
    for fn in (bench._pallas_segment, bench._pallas_full_run):
        assert "heartbeat_wire" in inspect.getsource(fn), fn.__name__


def test_history_filters_incomparable_rows():
    """CPU / smoke-shape manifest records and discarded-pallas tail
    rates must not join the trajectory (they would always be a
    series' latest point and wreck the gate)."""
    import tempfile
    recs = [
        {"schema": 1, "segment": "throughput", "engine": "xla-scan",
         "rounds_per_sec": 123.0, "device": "cpu:cpu",
         "n_groups": 100_000},                       # CPU box
        {"schema": 1, "segment": "throughput", "engine": "xla-scan",
         "rounds_per_sec": 456.0, "device": "tpu:TPU v5 lite",
         "n_groups": 1_000},                         # --quick shape
        {"schema": 1, "segment": "throughput", "engine": "xla-scan",
         "rounds_per_sec": 5_000_000.0, "device": "tpu:TPU v5 lite",
         "n_groups": 100_000},                       # real point
    ]
    with tempfile.NamedTemporaryFile("w", suffix=".jsonl",
                                     delete=False) as fh:
        fh.write("\n".join(json.dumps(r) for r in recs))
        path = fh.name
    rows = history.parse_manifest_file(path)
    os.unlink(path)
    assert [r["value"] for r in rows] == [5_000_000.0]
    # Tail "[pallas] ... -> N/s" lines are pre-differential and never
    # harvested; the xla line still is.
    doc = {"n": 9, "tail": "  [pallas] 100000 groups x 600 ticks: x in "
           "2s -> 9,999,999 rounds/s\n  [xla] 100000 groups x 600 "
           "ticks: x in 8s -> 7,000,000 rounds/s\n", "parsed": None}
    with tempfile.NamedTemporaryFile("w", suffix="_r09.json",
                                     delete=False) as fh:
        json.dump(doc, fh)
        path = fh.name
    rows = history.parse_bench_file(path)
    os.unlink(path)
    assert [(r["engine"], r["value"]) for r in rows] \
        == [("xla", 7_000_000.0)]


def test_heartbeat_every_n_and_health_fields(tmp_path):
    from raft_tpu.obs import flight_init, run_recorded
    from raft_tpu import sim
    st, m, f = run_recorded(CFG, sim.init(CFG), 40)
    hb = Heartbeat(str(tmp_path / "hb.jsonl"), every=3)
    emitted = [hb.beat("soak", 40 + i, m, f) for i in range(7)]
    assert [e is not None for e in emitted] == [True, False, False,
                                               True, False, False, True]
    rec = emitted[0]
    assert rec["safety_ok"] and rec["unsafe_groups"] == 0
    assert rec["ring_ticks"] == 40   # 40 ticks < RING all recorded
    assert isinstance(rec["election_storm"], bool)
    assert isinstance(rec["leaderless_stall"], bool)


# --------------------------------------------------- wall-key contract


def test_wall_fields_one_producer_and_key_set():
    import bench
    full = bench._wall_fields(1.23456, xla_wall_s=2.0,
                              xla_warmup_wall_s=3.0, kernel_wall_s=4.0,
                              kernel_warmup_wall_s=5.0)
    assert tuple(full) == bench.SEGMENT_WALL_KEYS
    assert full["timed_wall_s"] == 1.235   # ms precision
    sparse = bench._wall_fields(None, xla_wall_s=2.0)
    assert tuple(sparse) == bench.SEGMENT_WALL_KEYS
    assert sparse["kernel_wall_s"] is None
    # Every segment builder routes through the one producer (the
    # runtime path needs device walls this tier cannot pay; source
    # pin keeps a new segment from hand-rolling its own keys).
    for fn in (bench.bench_throughput, bench.bench_fault_latency,
               bench.bench_election_rounds, bench.bench_reads,
               bench.bench_clients):
        src = inspect.getsource(fn)
        assert "_wall_fields(" in src, fn.__name__
        assert "_roofline_fields(" in src, fn.__name__
