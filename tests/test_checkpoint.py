"""Host-side checkpoint/restore (SURVEY.md §5 elastic-recovery row):
run 100 ticks, save, reload — in THIS process and in a FRESH process —
run 100 more, and require bit-equality with an unbroken 200-tick run."""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

from conftest import trees_equal as _trees_equal
from raft_tpu import sim
from raft_tpu.config import RaftConfig
from raft_tpu.sim import checkpoint
from raft_tpu.sim.run import metrics_init

CFG = dict(seed=6, drop_prob=0.05, crash_prob=0.2, crash_epoch=32)


def test_save_load_roundtrip_in_process(tmp_path):
    cfg = RaftConfig(**CFG)
    st = sim.init(cfg, n_groups=16)
    m = metrics_init(16)
    st, m = sim.run(cfg, st, 100, 0, m)
    path = tmp_path / "ckpt.npz"
    checkpoint.save(path, st, 100, m, cfg=cfg)
    st2, t2, m2 = checkpoint.load(path, cfg=cfg)
    assert t2 == 100
    assert _trees_equal(st, st2)
    assert _trees_equal(m, m2)

    # Continue both and compare against an unbroken 200-tick run.
    unbroken, mu = sim.run(cfg, sim.init(cfg, n_groups=16), 100)
    unbroken, mu = sim.run(cfg, unbroken, 100, 100, mu)
    resumed, mr = sim.run(cfg, st2, 100, t2, m2)
    assert _trees_equal(unbroken, resumed)
    assert _trees_equal(mu, mr)


def test_load_rejects_config_mismatch(tmp_path):
    cfg = RaftConfig(**CFG)
    st = sim.init(cfg, n_groups=4)
    path = tmp_path / "ckpt.npz"
    checkpoint.save(path, st, 0, cfg=cfg)
    other = RaftConfig(**{**CFG, "seed": 7})
    with pytest.raises(ValueError, match="cfg mismatch"):
        checkpoint.load(path, cfg=other)
    # Without a cfg to check against, load is permissive by design.
    checkpoint.load(path)


_CHILD = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
import jax
jax.config.update("jax_platforms", "cpu")
from raft_tpu import sim
from raft_tpu.config import RaftConfig
from raft_tpu.sim import checkpoint

cfg = RaftConfig(seed=6, drop_prob=0.05, crash_prob=0.2, crash_epoch=32)
st, t, m = checkpoint.load(sys.argv[1], cfg=cfg)
st, m = sim.run(cfg, st, 100, t, m)
checkpoint.save(sys.argv[2], st, t + 100, m, cfg=cfg)
"""


def test_resume_onto_different_mesh(tmp_path):
    """Elastic recovery across a device-count change: a checkpoint from
    an 8-device sharded run resumes on a 4-device mesh (and unsharded)
    bit-identically — the npz is device-layout-free and group_id travels
    with the shard, so resharding is just a device_put."""
    from raft_tpu import parallel

    cfg = RaftConfig(**CFG)
    n_groups, path = 16, tmp_path / "ckpt.npz"

    mesh8 = parallel.make_mesh(8)
    st = parallel.shard_state(sim.init(cfg, n_groups=n_groups), mesh8)
    st, _ = parallel.run_sharded(cfg, st, 60, mesh8)
    checkpoint.save(path, st, 60, cfg=cfg)

    # Resume on 4 devices, run 60 more, compare with an unbroken run.
    mesh4 = parallel.make_mesh(4)
    st4, t4, _ = checkpoint.load(
        path, cfg=cfg, sharding=parallel.state_sharding(mesh4))
    shard_devs = {s.device for s in st4.nodes.term.addressable_shards}
    assert len(shard_devs) == 4
    st4, _ = parallel.run_sharded(cfg, st4, 60, mesh4, t0=t4)

    unbroken, _ = sim.run(cfg, sim.init(cfg, n_groups=n_groups), 120)
    assert _trees_equal(unbroken, st4)


def test_one_device_checkpoint_onto_eight_device_mesh(tmp_path):
    """The dryrun's elastic-load direction (DESIGN.md §9 satellite): a
    checkpoint written from an UNSHARDED (1-device) state loads straight
    onto an 8-device mesh — metrics' per-group leaves resharding along —
    and the sharded run proceeds bit-identically to sharding directly.
    This is the in-repo assert behind `__graft_entry__`'s checkpoint hop
    preserving the dryrun golden line."""
    from raft_tpu import parallel

    cfg = RaftConfig(**CFG)
    path = tmp_path / "ckpt.npz"
    checkpoint.save(path, sim.init(cfg, n_groups=16), 0, metrics_init(16),
                    cfg=cfg)

    mesh8 = parallel.make_mesh(8)
    st8, t0, m8 = checkpoint.load(
        path, cfg=cfg, sharding=parallel.state_sharding(mesh8))
    assert t0 == 0
    shard_devs = {s.device for s in st8.nodes.term.addressable_shards}
    assert len(shard_devs) == 8
    # Per-group metric leaves follow the state's sharding; the scalars
    # and histogram replicate instead of sharding by accident.
    assert {s.device for s in m8.committed.addressable_shards} == shard_devs
    assert len({s.device for s in m8.hist.addressable_shards}) == 8
    assert all(s.data.shape == m8.hist.shape
               for s in m8.hist.addressable_shards), \
        "histogram must replicate, not shard"

    st8, _ = parallel.run_sharded(cfg, st8, 60, mesh8)
    ref = parallel.shard_state(sim.init(cfg, n_groups=16), mesh8)
    ref, _ = parallel.run_sharded(cfg, ref, 60, mesh8)
    assert _trees_equal(ref, st8)


def test_resume_in_fresh_process(tmp_path):
    cfg = RaftConfig(**CFG)
    st = sim.init(cfg, n_groups=16)
    m = metrics_init(16)
    st, m = sim.run(cfg, st, 100, 0, m)
    p1, p2 = tmp_path / "a.npz", tmp_path / "b.npz"
    checkpoint.save(p1, st, 100, m, cfg=cfg)

    env = dict(os.environ)
    # Share the compile cache so the child doesn't pay a cold compile.
    env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(
        os.path.dirname(__file__), ".jax_cache")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    subprocess.run([sys.executable, "-c", _CHILD, str(p1), str(p2)],
                   env=env, check=True)

    st2, t2, m2 = checkpoint.load(p2)
    assert t2 == 200
    unbroken, mu = sim.run(cfg, sim.init(cfg, n_groups=16), 100)
    unbroken, mu = sim.run(cfg, unbroken, 100, 100, mu)
    assert _trees_equal(unbroken, st2)
    assert _trees_equal(mu, m2)


def test_load_backfills_pre_r09_file(tmp_path):
    """Satellite gate (ISSUE r09): a pre-r09 checkpoint — no session
    leaves, no client metric lanes, an embedded cfg dict that predates
    the client knobs — loads under today's code: State.clients and the
    metric client lanes come back None (clients-off universe), the cfg
    comparison backfills the missing knobs with their defaults, and the
    resumed run is bit-identical. Simulated by re-writing a fresh save
    with every r09 key stripped (a clients-off save is otherwise
    byte-compatible with the pre-r09 format: None subtrees were never
    written)."""
    import json

    import numpy as np

    cfg = RaftConfig(**CFG)
    st = sim.init(cfg, n_groups=8)
    st, m = sim.run(cfg, st, 40)
    path = tmp_path / "new.npz"
    checkpoint.save(path, st, 40, m, cfg=cfg)
    old = tmp_path / "pre_r09.npz"
    with np.load(path) as z:
        data = {k: z[k] for k in z.files}
    # Strip the r09 surface: client cfg knobs from the embedded dict
    # (pre-r09 writers never knew them) — state/metric client keys are
    # already absent from a clients-off save (asserted).
    saved_cfg = json.loads(bytes(data["__cfg__"]).decode())
    for k in ("client_rate", "client_slots", "client_retry_backoff"):
        saved_cfg.pop(k)
    data["__cfg__"] = np.bytes_(json.dumps(saved_cfg, sort_keys=True))
    assert not any("session" in k or "client" in k for k in data)
    np.savez(old, **data)

    st2, t2, m2 = checkpoint.load(old, cfg=cfg)
    assert t2 == 40
    assert st2.clients is None
    assert st2.nodes.session_seq is None
    assert m2.client_acked is None and m2.client_hist is None
    assert _trees_equal(st, st2) and _trees_equal(m, m2)
    a, ma = sim.run(cfg, st, 20, 40, m)
    b, mb = sim.run(cfg, st2, 20, t2, m2)
    assert _trees_equal(a, b) and _trees_equal(ma, mb)


def test_load_backfills_missing_client_metric_lanes(tmp_path):
    """A clients-ON checkpoint whose metrics predate the SLO lanes
    (r07-style partial writer) loads with fresh zeroed lanes — the
    metrics.safety backfill pattern extended to r09."""
    import numpy as np

    from raft_tpu.clients import clients_64_cfg

    ccfg = clients_64_cfg()
    st = sim.init(ccfg)
    st, m = sim.run(ccfg, st, 24)
    path = tmp_path / "full.npz"
    checkpoint.save(path, st, 24, m, cfg=ccfg)
    stripped = tmp_path / "no_lanes.npz"
    with np.load(path) as z:
        data = {k: z[k] for k in z.files
                if not k.startswith("metrics.client_")}
    np.savez(stripped, **data)
    st2, _, m2 = checkpoint.load(stripped, cfg=ccfg)
    assert st2.clients is not None
    assert int(np.asarray(m2.client_acked).sum()) == 0
    assert int(np.asarray(m2.client_hist).sum()) == 0
    assert int(np.asarray(m2.client_max_lat)) == 0
    # acked/retries are idempotent recomputes: the resumed run restores
    # the true totals from the (fully restored) client state. (24-tick
    # chunk: reuses the compiled program from the save above.)
    st3, m3 = sim.run(ccfg, st2, 24, 24, m2)
    assert int(np.asarray(m3.client_acked).sum()) \
        == int(np.asarray(st3.clients.done).sum())
