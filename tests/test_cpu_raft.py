"""CPU reference path scenario tests (SURVEY.md §4's canonical set).

These do not import jax — they are pure-Python and fast. They are the
ground truth the TPU path is differentially tested against.
"""

import pytest

from raft_tpu.config import RaftConfig
from raft_tpu.core.cluster import Cluster, SafetyViolation
from raft_tpu.core.node import FOLLOWER, CANDIDATE, LEADER, Node


def make(seed=0, k=3, ticks=0, **kw):
    cfg = RaftConfig(seed=seed, k=k, **kw)
    c = Cluster(cfg)
    if ticks:
        c.run(ticks)
    return c


def all_digests_consistent(c: Cluster):
    """Nodes with equal applied index must have equal digests."""
    by_applied = {}
    for n in c.nodes:
        if n.applied in by_applied:
            assert by_applied[n.applied] == n.digest, (
                f"digest divergence at applied={n.applied}")
        by_applied[n.applied] = n.digest


# ------------------------------------------------------------------ election

def test_single_group_elects_leader():
    for seed in range(5):
        c = make(seed=seed, k=3, ticks=60)
        assert c.leader() is not None, f"no leader by tick 60 (seed {seed})"


def test_five_node_group_elects_leader():
    for seed in range(5):
        c = make(seed=seed, k=5, ticks=60)
        assert c.leader() is not None


def test_k1_group_is_immediately_leader_and_commits():
    c = make(k=1, ticks=40)
    assert c.leader() == 0
    n = c.nodes[0]
    assert n.commit > 0
    assert n.applied == n.commit


def test_exactly_one_leader_per_term_over_long_run():
    # Safety checker inside Cluster raises on any election-safety violation.
    for seed in range(3):
        make(seed=seed, k=5, ticks=400)


# --------------------------------------------------------------- replication

def test_config1_replicates_1000_entries():
    """Config 1 of BASELINE.json: 3-node group, 1K committed entries."""
    c = make(seed=1, k=3)
    target = 1000
    for _ in range(5000):
        c.tick()
        if min(n.commit for n in c.nodes) >= target:
            break
    assert min(n.commit for n in c.nodes) >= target
    all_digests_consistent(c)
    # Snapshot compaction kept every window bounded.
    for n in c.nodes:
        assert n.last_index - n.snap_index <= c.cfg.log_cap


def test_followers_apply_same_prefix():
    c = make(seed=2, k=5, ticks=300)
    all_digests_consistent(c)
    assert c.total_applies > 0


# -------------------------------------------------------------- leader crash

def test_reelection_after_leader_crash():
    c = make(seed=3, k=3)
    c.run(80)
    first = c.leader()
    assert first is not None
    first_term = c.nodes[first].term
    crash_at = c.tick_count
    c.alive_fn = lambda t: [i != first or t < crash_at for i in range(3)]
    c.run(80)
    new = c.leader()
    assert new is not None and new != first
    assert c.nodes[new].term > first_term
    # Replication continues under the new leader.
    commit_before = max(n.commit for n in c.nodes if n.id != first)
    c.run(40)
    assert max(n.commit for n in c.nodes if n.id != first) > commit_before


def test_crashed_leader_rejoins_and_catches_up():
    c = make(seed=4, k=3)
    c.run(80)
    first = c.leader()
    assert first is not None
    crash_at = c.tick_count
    down_until = crash_at + 120
    c.alive_fn = lambda t: [i != first or not (crash_at <= t < down_until)
                            for i in range(3)]
    c.run(120)          # crash window: others elect + commit a lot
    c.run(200)          # rejoin: must catch up (via AE or InstallSnapshot)
    rejoined = c.nodes[first]
    lead = c.leader()
    assert lead is not None and lead != first
    assert rejoined.role == FOLLOWER
    assert rejoined.commit >= c.nodes[lead].commit - 2 * c.cfg.heartbeat_every * c.cfg.cmds_per_tick - c.cfg.max_entries_per_msg
    all_digests_consistent(c)


def test_snapshot_install_repairs_long_lag():
    # Long outage so the leader compacts far past the dead node's log.
    c = make(seed=5, k=3, compact_every=8, log_cap=16)
    c.run(80)
    first = c.leader()
    assert first is not None
    victim = (first + 1) % 3
    crash_at = c.tick_count
    down_until = crash_at + 400
    c.alive_fn = lambda t: [i != victim or not (crash_at <= t < down_until)
                            for i in range(3)]
    c.run(400)
    lead = c.leader()
    assert lead is not None
    gap = c.nodes[lead].snap_index   # compaction point at rejoin time
    assert gap > c.nodes[victim].last_index, (
        "test premise: leader compacted beyond the victim's log")
    c.run(100)
    # Committing past `gap` is only possible after an InstallSnapshot —
    # the entries below it no longer exist anywhere on the wire.
    assert c.nodes[victim].commit > gap, "victim must have installed a snapshot"
    all_digests_consistent(c)


# ----------------------------------------------------------------- partition

def test_minority_partition_cannot_commit():
    c = make(seed=6, k=5)
    c.run(80)
    lead = c.leader()
    assert lead is not None
    # Isolate the leader with one follower (minority side).
    buddy = (lead + 1) % 5
    side = {lead, buddy}
    part_at = c.tick_count
    c.transport.link_filter = lambda t, s, d: (
        t < part_at or ((s in side) == (d in side)))
    minority_commit = c.nodes[lead].commit
    c.run(150)
    # Old leader may still think it leads but must not advance its commit.
    assert c.nodes[lead].commit == minority_commit, (
        "leader in minority partition advanced commit — split brain")
    # Majority side elected a fresh leader and kept committing.
    maj_leader = c.leader()
    assert maj_leader is not None and maj_leader not in side
    assert c.nodes[maj_leader].commit > minority_commit
    # Heal: old leader steps down, everyone converges.
    c.transport.link_filter = None
    c.run(150)
    assert c.nodes[lead].role != LEADER
    all_digests_consistent(c)


def test_partition_heal_discards_uncommitted_minority_entries():
    c = make(seed=7, k=5)
    c.run(80)
    lead = c.leader()
    assert lead is not None
    buddy = (lead + 1) % 5
    side = {lead, buddy}
    part_at = c.tick_count
    c.transport.link_filter = lambda t, s, d: (
        t < part_at or ((s in side) == (d in side)))
    c.run(120)
    stale_last = c.nodes[lead].last_index   # uncommitted minority appends
    assert stale_last > c.nodes[lead].commit
    c.transport.link_filter = None
    c.run(200)
    # The minority suffix was overwritten by the majority leader's log.
    all_digests_consistent(c)
    new_lead = c.leader()
    assert new_lead is not None
    lo = min(n.commit for n in c.nodes)
    assert lo > 0


# ------------------------------------------------------- figure-8 / §5.4.2

def test_commit_restriction_prior_term_not_counted():
    """Raft §5.4.2 (figure 8): a leader never commits a prior-term entry by
    counting replicas; it may only commit it below a current-term entry."""
    cfg = RaftConfig(k=5, cmds_per_tick=0)
    c = Cluster(cfg)
    n = c.nodes[0]
    # Hand-craft: node 0 is leader of term 4; log has entries of terms [2, 2, 4].
    n.term = 4
    n.role = LEADER
    n.leader_id = 0
    n.log = [(2, 11), (2, 12), (4, 13)]
    # A majority replicated index 2 (a term-2 entry) but not index 3.
    n.match_index = [0, 2, 2, 0, 0]
    n.phase_a()
    assert n.commit == 0, "must NOT commit prior-term entry by counting"
    # Once a CURRENT-term entry reaches a majority, everything below commits.
    n.match_index = [0, 3, 3, 0, 0]
    n.phase_a()
    assert n.commit == 3


def test_vote_up_to_date_check():
    cfg = RaftConfig(k=3)
    c = Cluster(cfg)
    n = c.nodes[0]
    n.term = 5
    n.log = [(1, 1), (5, 2)]   # last term 5, last index 2
    from raft_tpu.core import rpc
    # Candidate with shorter log of same last term: reject.
    n._on_rv_req(rpc.RequestVoteReq(rpc.RV_REQ, 1, 0, term=5,
                                    last_log_index=1, last_log_term=5))
    assert n.voted_for == -1
    # Candidate with longer log, lower last term: reject.
    n._on_rv_req(rpc.RequestVoteReq(rpc.RV_REQ, 1, 0, term=5,
                                    last_log_index=9, last_log_term=4))
    assert n.voted_for == -1
    # Up-to-date candidate: grant.
    n._on_rv_req(rpc.RequestVoteReq(rpc.RV_REQ, 2, 0, term=5,
                                    last_log_index=2, last_log_term=5))
    assert n.voted_for == 2
    # Already voted this term for 2: reject 1 even if up-to-date.
    n._on_rv_req(rpc.RequestVoteReq(rpc.RV_REQ, 1, 0, term=5,
                                    last_log_index=3, last_log_term=5))
    assert n.voted_for == 2


def test_conflict_fast_backup_hint():
    cfg = RaftConfig(k=3, cmds_per_tick=0)
    c = Cluster(cfg)
    n = c.nodes[0]
    from raft_tpu.core import rpc
    n.term = 3
    n.log = [(1, 1), (2, 2), (2, 3), (2, 4)]   # terms 1,2,2,2 at idx 1..4
    n._on_ae_req(rpc.AppendEntriesReq(
        rpc.AE_REQ, 1, 0, term=3, prev_index=4, prev_term=3,
        entries=(), leader_commit=0))
    # Conflicting term at prev=4 is 2; first index of term 2 is 2.
    resp = [m for m in c.transport._outbox if m.type == rpc.AE_RESP][-1]
    assert resp.success is False and resp.match == 2


def test_window_flow_control_never_overflows():
    c = make(seed=8, k=3, log_cap=12, compact_every=4, cmds_per_tick=3,
             ticks=300)
    for n in c.nodes:
        assert n.last_index - n.snap_index <= 12
    all_digests_consistent(c)
    assert min(n.commit for n in c.nodes) > 0


# -------------------------------------------- takeover with a full window

def test_takeover_with_full_window_stays_live():
    """Regression: a new leader whose window is FULL of prior-term entries
    must still make progress. With the naive append-a-no-op takeover this
    wedges forever (no room for a current-term entry, §5.4.2 blocks commit,
    no commit → no compaction → no room). Term re-proposal (DESIGN.md §2a)
    rewrites the suffix in place instead."""
    c = make(seed=9, k=5, log_cap=12, compact_every=4)
    c.run(80)
    lead = c.leader()
    assert lead is not None
    buddy = [i for i in range(5) if i != lead][0]
    # Only the buddy's acks reach the leader: entries replicate to the buddy
    # (its next_index advances) but 2 < majority(3), so nothing commits and
    # the leader appends until its window is full — mirrored by the buddy.
    cut_at = c.tick_count
    c.transport.link_filter = lambda t, s, d: (
        t < cut_at or d != lead or s in (lead, buddy))
    c.run(200)
    stuck = c.nodes[lead]
    assert stuck.last_index - stuck.snap_index == c.cfg.log_cap, (
        "test premise: leader filled its window with uncommitted entries")
    assert c.nodes[buddy].last_index == stuck.last_index, (
        "test premise: buddy mirrors the full window")
    assert c.nodes[buddy].commit == stuck.commit
    # Kill the old leader (and one short-log follower, so that the remaining
    # quorum {buddy, f2, f3} can only elect the buddy: the short-log
    # followers can never gather 3 votes past the buddy's up-to-date check).
    # The buddy must win and commit through its FULL inherited window.
    others = [i for i in range(5) if i not in (lead, buddy)]
    dead = {lead, others[0]}
    dead_at = c.tick_count
    c.transport.link_filter = None
    c.alive_fn = lambda t: [i not in dead or t < dead_at for i in range(5)]
    c.run(200)
    new = c.leader()
    assert new == buddy, "staging: only the buddy should be electable"
    assert c.nodes[new].commit > stuck.last_index, (
        "new leader wedged: could not commit past the inherited window")
    all_digests_consistent(c)


def test_takeover_reproposal_preserves_payloads():
    """Re-proposal changes terms, never (index, payload): digests of the
    survivors must match the payloads the old leader appended."""
    c = make(seed=10, k=5)
    c.run(80)
    lead = c.leader()
    assert lead is not None
    buddy = [i for i in range(5) if i != lead][0]
    cut_at = c.tick_count
    c.transport.link_filter = lambda t, s, d: (
        t < cut_at or d != lead or s in (lead, buddy))
    c.run(60)
    # Snapshot the uncommitted suffix payloads the buddy replicated.
    f = c.nodes[buddy]
    suffix = {i: f.payload_at(i) for i in range(f.commit + 1, f.last_index + 1)}
    assert suffix, "test premise: some uncommitted replicated entries exist"
    # Same staging as above: only the buddy is electable in the new quorum.
    others = [i for i in range(5) if i not in (lead, buddy)]
    dead = {lead, others[0]}
    dead_at = c.tick_count
    c.transport.link_filter = None
    c.alive_fn = lambda t: [i not in dead or t < dead_at for i in range(5)]
    c.run(200)
    new = c.leader()
    assert new == buddy, "staging: only the buddy should be electable"
    n = c.nodes[new]
    for idx, payload in suffix.items():
        assert idx <= n.commit, f"inherited entry {idx} never committed"
        assert c._committed[idx] == payload, (
            "re-proposal changed a payload — safety violation")
    all_digests_consistent(c)


# ------------------------------------------------------------ fault schedule

def test_hash_fault_schedule_run_is_safe():
    """Config-4 style run on CPU: random crashes via the hash schedule."""
    cfg = RaftConfig(seed=11, k=5, crash_prob=0.2, crash_epoch=32)
    c = Cluster(cfg)
    c.run(600)   # SafetyViolation would raise from the checker
    all_digests_consistent(c)


def test_hash_partition_and_drop_run_is_safe():
    cfg = RaftConfig(seed=12, k=5, partition_prob=0.3, partition_epoch=40,
                     drop_prob=0.05)
    c = Cluster(cfg)
    c.run(600)
    all_digests_consistent(c)
