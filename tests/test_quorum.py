"""Property tests for ops/quorum.py against the CPU oracle's phase_a
computation (node.py:359-367), per VERDICT round-1 item 6: >=10^4 random
states, exact agreement."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from raft_tpu.ops import quorum


def cpu_commit_candidate(match_index, last_index, node_id, k, majority):
    """Verbatim re-statement of node.py:361-365."""
    matches = sorted((match_index[p] for p in range(k) if p != node_id),
                     reverse=True)
    matches.insert(0, last_index)
    return matches[majority - 1]


@pytest.mark.parametrize("k", [1, 3, 5, 7])
def test_commit_candidate_matches_oracle(k):
    majority = k // 2 + 1
    rng = np.random.default_rng(1234 + k)
    n = 4000
    match = rng.integers(0, 60, size=(n, k)).astype(np.int32)
    last = rng.integers(0, 60, size=n).astype(np.int32)
    node = rng.integers(0, k, size=n).astype(np.int32)

    got = jax.vmap(
        lambda m, l, i: quorum.commit_candidate(m, l, i, k, majority))(
            jnp.asarray(match), jnp.asarray(last), jnp.asarray(node))
    got = np.asarray(got)
    for idx in range(n):
        want = cpu_commit_candidate(match[idx], int(last[idx]),
                                    int(node[idx]), k, majority)
        assert got[idx] == want, (
            f"k={k} case={idx}: match={match[idx]} last={last[idx]} "
            f"node={node[idx]}: got {got[idx]}, oracle {want}")


def test_vote_count():
    rng = np.random.default_rng(99)
    votes = rng.random((1000, 5)) < 0.5
    got = np.asarray(quorum.vote_count(jnp.asarray(votes)))
    assert np.array_equal(got, votes.sum(axis=1))
