"""Client traffic subsystem (DESIGN.md §10): the oracle-vs-batched
session differential, host/jax workload parity, the kernel bit-parity
gate over the session-table + client-state leaves, the exactly-once
invariant, and the checkpoint round trip.

Every JAX test here simulates `clients.clients_64_cfg()` at 48 or 120
ticks — ONE clients-on tick/kernel program per shape, shared with the
compile cache (tests/conftest.py) like kmesh.faulted_64_cfg's family.
"""

from __future__ import annotations

import numpy as np

from conftest import trees_equal as _trees_equal
from raft_tpu import config as C
from raft_tpu import sim
from raft_tpu.clients import (HostClients, clients_64_cfg,
                              exactly_once_report, workload_params)
from raft_tpu.core.cluster import Cluster
from raft_tpu.sim import check
from raft_tpu.sim.run import (metrics_init, run, total_client_ops,
                              total_client_retries, unsafe_groups)
from raft_tpu.utils import rng

CFG = clients_64_cfg()
TICKS = 120


def _run_cfg(ticks=TICKS):
    return run(CFG, sim.init(CFG), ticks)


def test_oracle_vs_batched_session_differential():
    """THE satellite gate (ISSUE r09): the CPU-oracle session machinery
    (core/cluster.py + HostClients) and the batched dedup fold run the
    SAME retrying open-loop schedule on the faulted 64-group universe
    and must agree on every dedup decision — per-node (sid -> seq)
    tables, digests (which fold only effective ops), applied counts —
    and on the client-side state (done/backlog/retries/inflight).
    Asserts the differential is not vacuous: duplicates were actually
    submitted and deduped."""
    st, m = _run_cfg()
    table = np.asarray(st.nodes.session_seq)       # [G, K, S]
    digest = np.asarray(st.nodes.digest)
    applied = np.asarray(st.nodes.applied)
    cl = st.clients
    for g in range(CFG.n_groups):
        c = Cluster(CFG, group=g)
        c.run(TICKS)
        for i, n in enumerate(c.nodes):
            want = [n.sessions.get(s, -1) for s in range(CFG.client_slots)]
            assert list(table[g, i]) == want, (g, i)
            assert int(digest[g, i]) == n.digest, (g, i)
            assert int(applied[g, i]) == n.applied, (g, i)
        hc = c.clients
        assert list(np.asarray(cl.done)[g]) == hc.done, g
        assert list(np.asarray(cl.backlog)[g]) == hc.backlog, g
        assert list(np.asarray(cl.retries)[g]) == hc.retries, g
        assert list(np.asarray(cl.inflight)[g]) == hc.inflight, g
    # Not vacuous: the fault mix forced ambiguous-failure retries, ops
    # completed, and the per-tick exactly-once fold stayed clean.
    assert total_client_retries(m) > 0
    assert total_client_ops(m) > 0
    assert unsafe_groups(m) == 0
    ok, why = exactly_once_report(CFG, st, m)
    assert ok, why


def test_client_kernel_bit_identical():
    """The Pallas engine with the full client subsystem — session
    tables in k-state, IS session payload on the wire, in-kernel
    client transition, SLO metric lanes, exactly-once safety fold —
    ends bit-identical to the XLA path on full State AND full Metrics
    (interpret mode; same 48-tick shape as the kmesh family)."""
    from raft_tpu.sim import pkernel

    st0 = sim.init(CFG)
    stx, mx = run(CFG, st0, 48)
    stp, mp = pkernel.prun(CFG, st0, 48, interpret=True)
    assert _trees_equal(stx, stp), "kernel State diverged (client leaves?)"
    assert _trees_equal(mx, mp), "kernel Metrics diverged (client lanes?)"
    assert total_client_ops(mx) > 0, "no acked ops - differential vacuous"
    # The wire-lane readers bench drives are pinned to the XLA totals
    # (kinit loads the SLO lanes pass-through; a wire-order drift here
    # would feed bench a wrong counter).
    leaves, g = pkernel.kinit(CFG, stx, mx)
    assert pkernel.kacked(CFG, leaves, g) == total_client_ops(mx)
    assert pkernel.kretries(CFG, leaves, g) == total_client_retries(mx)


def test_client_wire_model_pins_exact():
    """The HBM byte model counts the client wire leaves (session
    tables, IS mailbox payload, client state, SLO lanes + second
    histogram) EXACTLY — the r08 pin extended over the r09 leaves."""
    from raft_tpu.obs import flight_init
    from raft_tpu.sim import pkernel

    st0 = sim.init(CFG)
    for flight in (None, flight_init(CFG.n_groups)):
        leaves, _ = pkernel.kinit(CFG, st0, flight=flight)
        actual = sum(int(np.prod(a.shape)) for a in leaves) // pkernel.GB
        model = pkernel.wire_words_per_group(
            CFG, with_flight=flight is not None)
        assert actual == model, (
            f"wire model {model} words/group != real leaves {actual} "
            f"(flight={'on' if flight is not None else 'off'})")
    # And the clients-on wire strictly exceeds the clients-off wire of
    # the same shape (the documented bytes/group delta is real).
    import dataclasses
    off = dataclasses.replace(CFG, client_rate=0.0, sessions=False)
    assert pkernel.wire_words_per_group(CFG) \
        > pkernel.wire_words_per_group(off)


import pytest


@pytest.mark.parametrize("queue_cap", [0, 2])
def test_host_workload_mirror_is_exact(queue_cap):
    """HostClients (the oracle driver) mirrors the jnp transition bit
    for bit through an adversarial synthetic table-witness schedule —
    acks, arrivals, retry backoff, backlog, latency events, and (r20,
    cap > 0) the bounded-admission shed ledger."""
    import dataclasses

    import jax.numpy as jnp
    from raft_tpu.clients import client_update, clients_init, \
        submit_payloads

    cfg = dataclasses.replace(CFG, client_queue_cap=queue_cap)
    g = 0
    cs = clients_init(cfg, 1)
    host = HostClients(cfg, g)
    tmax_host = [-1] * cfg.client_slots
    gcol = jnp.asarray([[g]], jnp.int32)
    scol = jnp.arange(cfg.client_slots, dtype=jnp.int32)[None, :]
    for t in range(160):
        # Adversarial witness: acks arrive only when the hash says so,
        # so ops straddle several backoff windows and retry.
        for s in range(cfg.client_slots):
            if host.inflight[s] and rng.hash_u32(7, g, s, t) % 5 == 0:
                tmax_host[s] = max(tmax_host[s], host.done[s])
        tm = jnp.asarray([tmax_host], jnp.int32)
        cs = client_update(cfg, cs, tm, gcol, scol, t)
        host.observe(tmax_host, t)
        for f in cs._fields:
            leaf = getattr(cs, f)
            if leaf is None:   # admission-gated shed leaf, cap off
                assert f == "shed" and queue_cap == 0, (f, t)
                continue
            assert list(np.asarray(leaf)[0]) \
                == list(getattr(host, f)), (f, t)
        sub, pay = submit_payloads(cfg, cs, gcol, scol)
        assert list(np.asarray(sub)[0]) == host.submit, t
        want = []
        for s in range(cfg.client_slots):
            want.append(C.session_payload(
                s, host.done[s], rng.client_val(cfg.seed, g, s,
                                                host.done[s])))
        assert list(np.asarray(pay)[0]) == want, t
    assert sum(host.retries) > 0 and sum(host.done) > 0
    if queue_cap:
        # Not vacuous: load (0.3/tick) outruns the hash-gated ack rate
        # (~0.2/tick), so the bounded queue genuinely rejected work.
        assert sum(host.shed) > 0


def test_client_safety_latches_double_apply():
    """The exactly-once safety clause trips on synthetic corruption:
    a table seq above the issued frontier (phantom apply) and a
    divergent dedup decision between equally-applied nodes both drop
    the per-tick bit, and the AND latches."""
    from raft_tpu.sim.run import metrics_update

    st, m = _run_cfg(48)
    assert unsafe_groups(m) == 0
    # Phantom apply: node 0's sid-0 entry jumps past done.
    bad = st._replace(nodes=st.nodes._replace(
        session_seq=st.nodes.session_seq.at[:, 0, 0].set(
            st.clients.done[:, 0] + 7)))
    m2 = metrics_update(m, bad, CFG.log_cap)
    assert unsafe_groups(m2) == CFG.n_groups
    assert not bool(np.all(np.asarray(check.client_safety(bad))))
    # Divergent dedup decision: two nodes with forced-equal applied
    # prefixes disagree on a table entry.
    nodes = st.nodes._replace(
        applied=st.nodes.applied.at[:, 1].set(st.nodes.applied[:, 0]),
        commit=st.nodes.commit.at[:, 1].set(st.nodes.applied[:, 0]),
        session_seq=st.nodes.session_seq.at[:, 1, 0].set(
            st.nodes.session_seq[:, 0, 0] - 1))
    m3 = metrics_update(m, st._replace(nodes=nodes), CFG.log_cap)
    assert unsafe_groups(m3) == CFG.n_groups
    # The AND latches: a later clean tick cannot clear it.
    m4 = metrics_update(m2, st, CFG.log_cap)
    assert unsafe_groups(m4) == CFG.n_groups


def test_client_chunk_boundaries_invisible():
    """Two chunked runs == one unbroken run on state AND client metric
    lanes (idempotent acked/retry recompute; event-folded histogram).
    24-tick chunks share the checkpoint test's compiled program."""
    st0 = sim.init(CFG)
    st_a, m_a = run(CFG, st0, 48)
    st_b, m_b = run(CFG, st0, 24)
    st_b, m_b = run(CFG, st_b, 24, 24, m_b)
    assert _trees_equal(st_a, st_b)
    assert _trees_equal(m_a, m_b)


def test_checkpoint_roundtrip_with_clients(tmp_path):
    """A clients-on checkpoint round-trips exactly (session tables,
    client state, SLO lanes) and the resumed run continues
    bit-identically."""
    from raft_tpu.sim import checkpoint

    st, m = _run_cfg(24)
    path = tmp_path / "clients.npz"
    checkpoint.save(path, st, 24, m, cfg=CFG)
    st2, t2, m2 = checkpoint.load(path, cfg=CFG)
    assert t2 == 24
    assert _trees_equal(st, st2) and _trees_equal(m, m2)
    a, ma = run(CFG, st, 24, 24, m)
    b, mb = run(CFG, st2, 24, t2, m2)
    assert _trees_equal(a, b) and _trees_equal(ma, mb)


def test_checkpoint_admission_roundtrip_and_pre_r20_backfill(tmp_path):
    """r20 checkpoint seams: (a) an admission-on checkpoint round-trips
    the shed ledger exactly and resumes bit-identically; (b) a
    synthesized pre-r20 file (no `state.clients.shed` key, no
    `client_queue_cap` in its config dict) loads under a cap-OFF cfg
    with shed backfilled to None and the knob backfilled to its
    default; (c) the same file REFUSES to resume under a cap-ON cfg —
    admission control changes the trajectory, so silently resuming
    would splice two different universes."""
    import dataclasses

    import pytest
    from raft_tpu.sim import checkpoint

    cfg = dataclasses.replace(CFG, client_queue_cap=2)
    st, m = run(cfg, sim.init(cfg), 24)
    assert int(np.asarray(st.clients.shed).sum()) > 0  # non-vacuous
    path = tmp_path / "admission.npz"
    checkpoint.save(path, st, 24, m, cfg=cfg)
    st2, t2, m2 = checkpoint.load(path, cfg=cfg)
    assert _trees_equal(st, st2) and _trees_equal(m, m2)
    a, ma = run(cfg, st, 24, 24, m)
    b, mb = run(cfg, st2, 24, t2, m2)
    assert _trees_equal(a, b) and _trees_equal(ma, mb)
    # Synthesize the pre-r20 file: strip the shed leaf and the cfg knob.
    import json

    with np.load(path) as z:
        data = {k: z[k] for k in z.files}
    del data["state.clients.shed"]
    meta = json.loads(bytes(data["__cfg__"]).decode())
    del meta["client_queue_cap"]
    data["__cfg__"] = np.bytes_(json.dumps(meta, sort_keys=True))
    old = tmp_path / "pre_r20.npz"
    np.savez(old, **data)
    st3, _, _ = checkpoint.load(old, cfg=CFG)   # cap-off: backfills
    assert st3.clients.shed is None
    with pytest.raises(ValueError):             # cap-on: refuses
        checkpoint.load(old, cfg=cfg)


def test_workload_params_cover_the_knobs():
    p = workload_params(CFG)
    assert p["rate"] == CFG.client_rate
    assert p["slots"] == CFG.client_slots
    assert p["retry_backoff"] == CFG.client_retry_backoff
    assert p["seed"] == CFG.seed and "retry_policy" in p
