"""Declarative gray-failure programs (DESIGN.md §14).

A nemesis *program* is a tuple of composable clauses — slow-but-alive
followers, asymmetric flaky links, WAN-style heterogeneous delivery,
timeout/clock skew, crash-recovery storms, correlated partition waves —
each with a tick span and a per-group participation probability. The
builders here quantize every probability to a u32 threshold at
construction (the same `config._prob_to_u32` rule every fault knob
uses), so a program is nothing but ints: `RaftConfig(nemesis=prog)`
carries it as a static, hashable, JSON-round-trippable part of the
semantic config, and the compiled form (`utils.rng.nem_*` and its
bit-identical `utils.jrng` twins) evaluates it as pure
`(seed, TAG_NEM_*, cid, coords)` hashes on all three engines.

Clause identity: every clause owns a `cid` that domain-separates all of
its hash draws. `program()` assigns cids positionally ONCE; the
shrinker (`nemesis.search`) then drops/narrows clauses WITHOUT
renumbering, so a surviving clause's schedule is bit-identical in the
shrunk program — minimization is behavior-preserving per clause.
"""

from __future__ import annotations

from typing import NamedTuple

from raft_tpu.config import _prob_to_u32
from raft_tpu.utils import rng as _r

# JSON names of the clause kinds (stable; the artifact format and the
# manifest clause list both use them).
KIND_NAMES = {
    _r.NEM_SLOW: "slow_follower",
    _r.NEM_FLAKY: "flaky_link",
    _r.NEM_WAN: "wan_delay",
    _r.NEM_SKEW: "clock_skew",
    _r.NEM_STORM: "crash_storm",
    _r.NEM_WAVE: "partition_wave",
    _r.NEM_DISK: "disk_full_follower",
    _r.NEM_COMPACT: "compaction_pressure",
}
KIND_IDS = {v: k for k, v in KIND_NAMES.items()}

FIELDS = ("kind", "t0", "t1", "group_u32", "p_u32", "a", "b", "cid")

_UNASSIGNED = -1


class Clause(NamedTuple):
    """One gray-failure clause — the 8-int wire layout utils.rng
    destructures. Field meaning per kind: see utils/rng.py's nemesis
    block (the one semantics definition site)."""
    kind: int
    t0: int
    t1: int
    group_u32: int
    p_u32: int
    a: int = 0
    b: int = 0
    cid: int = _UNASSIGNED


def _clause(kind, t0, t1, groups, p, a=0, b=0):
    if not 0 <= t0 <= t1:
        raise ValueError(f"clause span [{t0}, {t1}) invalid")
    return Clause(kind=kind, t0=int(t0), t1=int(t1),
                  group_u32=_prob_to_u32(groups), p_u32=_prob_to_u32(p),
                  a=int(a), b=int(b))


def slow_follower(t0, t1, p=0.8, direction=3, groups=1.0):
    """Slow-but-alive follower: one hash-chosen node per participating
    group keeps ticking but its links drop w.p. `p` per tick.
    `direction`: 1 = messages FROM it, 2 = TO it, 3 = both."""
    if direction not in (1, 2, 3):
        raise ValueError(f"direction {direction} not in (1, 2, 3)")
    return _clause(_r.NEM_SLOW, t0, t1, groups, p, a=direction)


def flaky_link(t0, t1, p=0.9, burst_epoch=8, burst_p=0.5, groups=1.0):
    """Asymmetric flaky link: ONE hash-chosen ordered (src -> dst) pair
    drops w.p. `p`, only inside bursts — `burst_epoch`-tick sub-epochs
    firing w.p. `burst_p`. The reverse direction is untouched."""
    if burst_epoch < 1:
        raise ValueError("burst_epoch must be >= 1")
    return _clause(_r.NEM_FLAKY, t0, t1, groups, p, a=burst_epoch,
                   b=_prob_to_u32(burst_p))


def wan_delay(t0, t1, sites=3, p=0.5, groups=1.0):
    """Heterogeneous WAN delivery: nodes hash onto `sites` sites;
    cross-site links drop w.p. `p` per tick. In the tick-synchronous
    model (heartbeat-driven retransmission) this IS added latency: a
    link losing each delivery w.p. p delays its information by a
    geometric number of resend rounds."""
    if sites < 2:
        raise ValueError("sites must be >= 2")
    return _clause(_r.NEM_WAN, t0, t1, groups, p, a=sites)


def clock_skew(t0, t1, amount=8, node_p=0.5, groups=1.0):
    """Timeout/clock skew: nodes selected w.p. `node_p` add the SIGNED
    `amount` ticks to every election-deadline draw made during the
    span (negative = a fast clock that times out early and campaigns
    aggressively; the skewed deadline clamps at 1)."""
    return _clause(_r.NEM_SKEW, t0, t1, groups, node_p, a=amount)


def crash_storm(t0, t1, p=0.4, epoch=4, groups=1.0):
    """Crash-recovery storm: a second, faster crash schedule — per
    node per `epoch`-tick sub-epoch, down w.p. `p` — ANDed into the
    base crash mask for the span."""
    if epoch < 1:
        raise ValueError("epoch must be >= 1")
    return _clause(_r.NEM_STORM, t0, t1, groups, p, a=epoch)


def partition_wave(t0, t1, period=32, width=12, leak_p=1.0, groups=1.0):
    """Correlated partition wave: a `width`-tick partition window
    sweeps the fleet with `period` (group g enters it g ticks after
    g-1 — correlated across the fleet, unlike the epoch-hash base
    schedule). Sides re-draw each period; cross-side links drop w.p.
    `leak_p` (below 1.0 = a gray, leaky partition)."""
    if period < 1 or width < 0:
        raise ValueError("period must be >= 1 and width >= 0")
    return _clause(_r.NEM_WAVE, t0, t1, groups, leak_p, a=period, b=width)


def disk_full_follower(t0, t1, p=0.8, epoch=8, groups=1.0):
    """Disk-full follower (r20, DESIGN.md §19): ONE hash-chosen node
    per participating group exhausts its persistence budget during
    `epoch`-tick sub-epochs firing w.p. `p` — every local append on it
    fails while full, so entries are not durable and are never acked
    (the AE reply stops at the durable prefix and the leader's
    retransmission loop is the NACK/throttle path)."""
    if epoch < 1:
        raise ValueError("epoch must be >= 1")
    return _clause(_r.NEM_DISK, t0, t1, groups, p, a=epoch)


def compaction_pressure(t0, t1, p=0.5, epoch=8, groups=1.0):
    """Compaction pressure (r20, DESIGN.md §19): per node per
    `epoch`-tick sub-epoch, w.p. `p`, the phase-A snapshot/compaction
    step is delayed — the log_cap ring genuinely fills and the window
    invariant becomes a runtime backpressure path that throttles
    replication instead of deadlocking."""
    if epoch < 1:
        raise ValueError("epoch must be >= 1")
    return _clause(_r.NEM_COMPACT, t0, t1, groups, p, a=epoch)


def program(*clauses) -> tuple:
    """Assemble clauses into a program: assign fresh cids to builder
    output (positional), keep explicit cids (a shrunk program re-built
    through here keeps its surviving clauses' schedules bit-identical),
    and reject duplicates."""
    taken = {c[7] for c in clauses if c[7] != _UNASSIGNED}
    out, nxt = [], 0
    for c in clauses:
        c = Clause(*(int(x) for x in c))
        if c.cid == _UNASSIGNED:
            while nxt in taken:
                nxt += 1
            c = c._replace(cid=nxt)
            taken.add(nxt)
        out.append(c)
    if len({c.cid for c in out}) != len(out):
        raise ValueError("duplicate clause cids")
    return tuple(out)


def gray_mix(n_ticks: int, t0: int = 0) -> tuple:
    """THE canonical gray-failure program (slow-follower + flaky-link
    mix): the acceptance-gate universe shared by tests/test_nemesis.py,
    `kernel_sweep.py --nemesis`, and bench.py's nemesis segment —
    defined once so the three drivers exercise the same program."""
    return program(
        slow_follower(t0, t0 + n_ticks, p=0.7, direction=3),
        flaky_link(t0, t0 + n_ticks, p=0.9, burst_epoch=8, burst_p=0.6),
    )


def pressure_mix(n_ticks: int, t0: int = 0) -> tuple:
    """THE canonical storage-pressure program (disk-full follower +
    compaction pressure; r20, DESIGN.md §19): the graceful-degradation
    universe shared by tests/test_nemesis.py, `kernel_sweep.py
    --nemesis`'s pressure cells, and bench.py's knee sweep — defined
    once so the three drivers exercise the same adversary (and the
    manifest's `pressure_program_hash` means one thing)."""
    return program(
        disk_full_follower(t0, t0 + n_ticks, p=0.8, epoch=8),
        compaction_pressure(t0, t0 + n_ticks, p=0.5, epoch=8),
    )


def to_json(prog) -> list:
    """JSON form: one dict per clause, kinds by name — the manifest's
    `nemesis_clauses` list and the reproducer artifact's `program`."""
    return [{**dict(zip(FIELDS, c)), "kind": KIND_NAMES[c[0]]}
            for c in prog]


def from_json(doc) -> tuple:
    """Inverse of `to_json` (also accepts numeric kinds and bare
    8-lists, so a program pasted from a manifest config dict loads)."""
    out = []
    for c in doc:
        if isinstance(c, dict):
            kind = c["kind"]
            kind = KIND_IDS[kind] if isinstance(kind, str) else int(kind)
            out.append(Clause(kind, *(int(c[f]) for f in FIELDS[1:])))
        else:
            out.append(Clause(*(int(x) for x in c)))
    return tuple(out)


def program_hash(prog) -> str:
    """Stable 8-hex-digit identity of a program — hashed through the
    repo's own mixer over the flat clause ints (so it is reproducible
    from the manifest's clause list alone, no JSON canonicalization)."""
    flat = [len(prog)]
    for c in prog:
        flat.extend(int(x) for x in c)
    return format(_r.hash_u32(*flat), "08x")


def describe(prog) -> str:
    """One human line per clause (search/shrink logs)."""
    lines = []
    for c in prog:
        kind, t0, t1, group_u32, p_u32, a, b, cid = c
        lines.append(
            f"#{cid} {KIND_NAMES[kind]} [{t0},{t1}) "
            f"groups={group_u32 / 2**32:.2f} p={p_u32 / 2**32:.2f} "
            f"a={a} b={b}")
    return "; ".join(lines) or "<empty>"
