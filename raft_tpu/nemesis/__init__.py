"""Nemesis scenario compiler (DESIGN.md §14): declarative gray-failure
programs compiled to the hashed elementwise schedule form all three
engines share, plus the coverage-guided adversarial search and the
auto-shrinking minimal-reproducer machinery.

- ``program`` — the clause builders / JSON / hashing (no jax; safe to
  import from anywhere, including the engines' static gates).
- ``search`` — scoring, deterministic mutation, shrinking, artifacts
  (imports the engines; NOT imported here at module level so
  ``sim.step -> nemesis.program`` can never become a cycle).
"""

from raft_tpu.nemesis.program import (Clause, clock_skew,
                                      compaction_pressure, crash_storm,
                                      describe, disk_full_follower,
                                      flaky_link, from_json, gray_mix,
                                      partition_wave, pressure_mix,
                                      program, program_hash, slow_follower,
                                      to_json, wan_delay)

__all__ = ["Clause", "clock_skew", "compaction_pressure", "crash_storm",
           "describe", "disk_full_follower", "flaky_link", "from_json",
           "gray_mix", "partition_wave", "pressure_mix", "program",
           "program_hash", "slow_follower", "to_json", "wan_delay"]
