"""Coverage-guided adversarial search + auto-shrinking reproducers
(DESIGN.md §14).

The searcher mutates gray-failure programs and scores each candidate
run by safety-fold NEAR-MISSES and flight-ring health — election
storms, leaderless stalls, dual-leader coexistence (distinct terms;
same-term would be a violation), term inflation, commit stalls — the
signals real fleets page on. A candidate that lights up a new coverage
signature joins the corpus; a candidate that actually drops the
per-tick safety bit is a VIOLATION and goes to the shrinker.

Everything here is deterministic: mutation choices are
`utils.rng.hash_u32` draws keyed on (search seed, step) — the repo's
"all randomness is a pure function of (seed, tag, coords)" rule applied
to the search itself, so a hunt (and a shrink) replays exactly from its
seed. No `random`, ever — the analysis linter enforces it over this
package like it does over the tick modules.

Shrinking: greedy clause-drops then span-halvings, re-checking the
caller's `repro(program) -> report | None` after each candidate edit,
until no single edit still reproduces. Clause cids are never
renumbered (see nemesis/program.py), so a surviving clause's compiled
schedule is bit-identical in the minimal program — the reason a shrunk
reproducer replays to the SAME tick and leaf. Reports come from
`obs.triage.bisect_divergence` (engine-vs-engine divergence) or from
`first_unsafe_tick` (single-engine safety-fold violations, named per
predicate via `check.predicate_report`); a minimal reproducer is
serialized as a self-contained JSON artifact.
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np

from raft_tpu.config import RaftConfig
from raft_tpu.nemesis.program import (clock_skew, compaction_pressure,
                                      crash_storm, describe,
                                      disk_full_follower, flaky_link,
                                      from_json, gray_mix,
                                      partition_wave, program,
                                      program_hash, slow_follower,
                                      to_json, wan_delay)
from raft_tpu.utils import rng

_SEARCH_TAG = 0x4E454D53   # "NEMS": domain-separates search draws


def _draw(seed: int, step: int, i: int) -> int:
    return rng.hash_u32(_SEARCH_TAG, seed, step, i)


def _pick(seed, step, i, menu):
    return menu[_draw(seed, step, i) % len(menu)]


# ------------------------------------------------------------- scoring


def run_signals(cfg: RaftConfig, n_groups: int, n_ticks: int) -> dict:
    """One scored run on the XLA engine (the searcher's engine: cheap,
    reference-grade): host-int health signals from the metrics fold,
    the endpoint state, and the flight ring."""
    from raft_tpu import sim
    from raft_tpu.obs.recorder import flight_rows, run_recorded

    fin, met, ring = run_recorded(cfg, sim.init(cfg, n_groups=n_groups),
                                  n_ticks)
    safety = np.asarray(met.safety)
    committed = np.asarray(met.committed)
    term = np.asarray(fin.nodes.term)
    # Transient dual-leader windows out of the flight ring's per-tick
    # per-group alive-leader counts (last RING recorded ticks), not
    # just the endpoint state — a program that provokes the window
    # mid-run and converges by the end must still score.
    ring_leaders = np.asarray(ring.leaders)
    recorded = np.asarray(ring.tick) >= 0
    dual = ((ring_leaders >= 2) & recorded).any(axis=0)
    rows = flight_rows(ring)
    return {
        "unsafe_groups": int((safety == 0).sum()),
        "elections": int(np.asarray(met.elections)),
        "max_leaderless": int(np.asarray(met.max_latency)),
        "committed": int(committed.astype(np.int64).sum()),
        "stalled_groups": int((committed == 0).sum()),
        # Near-miss: >= 2 alive leaders in one group at ANY recorded
        # tick (necessarily in DISTINCT terms, or the safety bit would
        # have latched) — the state one message away from split-brain.
        "dual_leader_groups": int(dual.sum()),
        "term_spread": int((term.max(axis=1) - term.min(axis=1)).max()),
        # Flight-ring health (the r12 heartbeat's storm signal): ticks
        # whose fleet-wide election completions exceed half the fleet.
        "storm_ticks": sum(1 for r in rows
                           if r["elections"] > n_groups // 2),
    }


def near_miss_score(sig: dict) -> float:
    """Higher = closer to the edge. An actual violation dominates
    everything (the searcher still shrinks it, not just ranks it)."""
    return (1000.0 * sig["unsafe_groups"]
            + 8.0 * sig["dual_leader_groups"]
            + 2.0 * sig["storm_ticks"]
            + 1.0 * sig["max_leaderless"]
            + 1.0 * sig["term_spread"]
            + 0.5 * sig["stalled_groups"]
            + 0.05 * sig["elections"])


def coverage_key(sig: dict) -> tuple:
    """Quantized signature: a candidate joins the corpus iff its key is
    new (log2 buckets keep the key space small but direction-sensitive)."""
    def b(x):
        return int(x).bit_length()
    return (min(sig["unsafe_groups"], 1), sig["dual_leader_groups"],
            b(sig["storm_ticks"]), b(sig["max_leaderless"]),
            b(sig["term_spread"]), b(sig["stalled_groups"]),
            b(sig["elections"]))


# ------------------------------------------------------------ mutation

# Per-kind parameter menus the deterministic mutator draws from.
_MENUS = {
    "slow": dict(p=(0.5, 0.7, 0.9), direction=(1, 2, 3)),
    "flaky": dict(p=(0.7, 0.9, 1.0), burst_epoch=(4, 8, 16),
                  burst_p=(0.3, 0.6, 1.0)),
    "wan": dict(sites=(2, 3), p=(0.3, 0.5, 0.8)),
    "skew": dict(amount=(-6, -3, 4, 8, 16), node_p=(0.3, 0.6, 1.0)),
    "storm": dict(p=(0.2, 0.4, 0.6), epoch=(2, 4, 8)),
    "wave": dict(period=(8, 16, 32), width_frac=(0.25, 0.5, 0.75),
                 leak_p=(0.6, 1.0)),
    # r20 storage-pressure kinds (DESIGN.md §19): the searcher mutates
    # over the durability seam too — disk-full windows that park a
    # node at its durable prefix and compaction stalls that fill the
    # log_cap ring compose with the delivery/timer kinds above into
    # exactly the mixed programs the hand-written tests never try.
    "disk": dict(p=(0.5, 0.8, 1.0), epoch=(4, 8, 16)),
    "compact": dict(p=(0.3, 0.5, 0.8), epoch=(4, 8, 16)),
}


def _new_clause(horizon: int, seed: int, step: int):
    """A fresh hash-drawn clause spanning a random sub-window of
    [0, horizon)."""
    t0 = _draw(seed, step, 10) % max(1, horizon - 8)
    t1 = t0 + 8 + _draw(seed, step, 11) % max(1, horizon - t0 - 7)
    groups = _pick(seed, step, 12, (1.0, 1.0, 0.5))
    which = _pick(seed, step, 13, tuple(_MENUS))
    menu = _MENUS[which]
    if which == "slow":
        return slow_follower(t0, t1, p=_pick(seed, step, 14, menu["p"]),
                             direction=_pick(seed, step, 15,
                                             menu["direction"]),
                             groups=groups)
    if which == "flaky":
        return flaky_link(t0, t1, p=_pick(seed, step, 14, menu["p"]),
                          burst_epoch=_pick(seed, step, 15,
                                            menu["burst_epoch"]),
                          burst_p=_pick(seed, step, 16, menu["burst_p"]),
                          groups=groups)
    if which == "wan":
        return wan_delay(t0, t1,
                         sites=_pick(seed, step, 14, menu["sites"]),
                         p=_pick(seed, step, 15, menu["p"]), groups=groups)
    if which == "skew":
        return clock_skew(t0, t1,
                          amount=_pick(seed, step, 14, menu["amount"]),
                          node_p=_pick(seed, step, 15, menu["node_p"]),
                          groups=groups)
    if which == "storm":
        return crash_storm(t0, t1, p=_pick(seed, step, 14, menu["p"]),
                           epoch=_pick(seed, step, 15, menu["epoch"]),
                           groups=groups)
    if which == "disk":
        return disk_full_follower(t0, t1,
                                  p=_pick(seed, step, 14, menu["p"]),
                                  epoch=_pick(seed, step, 15,
                                              menu["epoch"]),
                                  groups=groups)
    if which == "compact":
        return compaction_pressure(t0, t1,
                                   p=_pick(seed, step, 14, menu["p"]),
                                   epoch=_pick(seed, step, 15,
                                               menu["epoch"]),
                                   groups=groups)
    period = _pick(seed, step, 14, menu["period"])
    width = max(1, int(period * _pick(seed, step, 15, menu["width_frac"])))
    return partition_wave(t0, t1, period=period, width=width,
                          leak_p=_pick(seed, step, 16, menu["leak_p"]),
                          groups=groups)


def mutate(prog: tuple, horizon: int, seed: int, step: int) -> tuple:
    """One deterministic mutation: add / drop / narrow-a-span / flip an
    intensity. Surviving clauses keep their cids (and hence their exact
    compiled schedules)."""
    op = _draw(seed, step, 0) % 4
    if op == 1 and len(prog) > 1:
        i = _draw(seed, step, 1) % len(prog)
        return prog[:i] + prog[i + 1:]
    if op == 2 and prog:
        i = _draw(seed, step, 1) % len(prog)
        c = tuple(prog[i])
        if c[2] - c[1] >= 2:
            mid = (c[1] + c[2]) // 2
            half = ((c[1], mid) if _draw(seed, step, 2) & 1
                    else (mid, c[2]))
            return prog[:i] + (c[:1] + half + c[3:],) + prog[i + 1:]
    if op == 3 and prog:
        i = _draw(seed, step, 1) % len(prog)
        c = tuple(prog[i])
        p = (min(0xFFFFFFFF, c[4] * 2 + 1) if _draw(seed, step, 2) & 1
             else c[4] // 2)
        return prog[:i] + (c[:4] + (p,) + c[5:],) + prog[i + 1:]
    return program(*prog, _new_clause(horizon, seed, step))


# -------------------------------------------------------------- search


def search(base_cfg: RaftConfig, n_groups: int, n_ticks: int,
           budget: int, seed: int = 0, start: tuple | None = None,
           log=None, seed_corpus: list | None = None) -> dict:
    """The coverage-guided loop: `budget` mutate-run-score steps from a
    seed corpus. Returns {corpus, coverage, best, best_score,
    violations} — `violations` are (program, signals) pairs whose runs
    dropped the per-tick safety bit (shrink them with `shrink`).
    Deterministic in (base_cfg, n_groups, n_ticks, budget, seed,
    start, seed_corpus). NOTE each distinct program is a distinct
    static config: a step costs one XLA compile of the tick program —
    size the shapes like a test, not like a bench.

    `seed_corpus`: programs from a PERSISTED corpus (`load_corpus`) to
    seed the mutation pool — a resumed hunt starts from every
    coverage-novel program earlier hunts found instead of the canonical
    gray mix. Seeded programs are mutation parents only (not re-run, so
    resuming costs no extra compiles until mutation reaches them)."""
    corpus = (list(seed_corpus) if seed_corpus
              else [start if start is not None else gray_mix(n_ticks)])
    coverage: dict = {}
    violations: list = []
    best, best_score = corpus[0], float("-inf")
    for step in range(budget):
        parent = corpus[_draw(seed, step, 99) % len(corpus)]
        cand = mutate(parent, n_ticks, seed, step)
        cfg = dataclasses.replace(base_cfg, nemesis=cand)
        sig = run_signals(cfg, n_groups, n_ticks)
        key = coverage_key(sig)
        score = near_miss_score(sig)
        fresh = key not in coverage
        if fresh:
            coverage[key] = score
            corpus.append(cand)
        if score > best_score:
            best, best_score = cand, score
        if sig["unsafe_groups"] > 0:
            violations.append((cand, sig))
        if log is not None:
            log(f"[{step:3d}] score={score:8.1f} "
                f"{'NEW-COVERAGE ' if fresh else ''}"
                f"{'VIOLATION ' if sig['unsafe_groups'] else ''}"
                f"{describe(cand)}")
    return {"corpus": corpus, "coverage": coverage, "best": best,
            "best_score": best_score, "violations": violations}


# ---------------------------------------------------- violation triage


def first_unsafe_tick(cfg: RaftConfig, n_groups: int, n_ticks: int,
                      chunk: int = 16):
    """First tick whose post-state violates `check.tick_safety`, with
    the violated predicate(s) named (`check.predicate_report`) — the
    single-engine analogue of `obs.triage.bisect_divergence`, sharing
    its report shape so reproducer artifacts are schema-identical.
    Returns None when the whole run is clean."""
    from raft_tpu import sim
    from raft_tpu.sim import check
    from raft_tpu.sim.run import metrics_init, run

    cur = sim.init(cfg, n_groups=n_groups)
    curm = metrics_init(n_groups, clients=cfg.clients_u32 != 0)
    t, end = 0, n_ticks
    while t < end:
        n = min(chunk, end - t)
        nxt, nxtm = run(cfg, cur, n, t, curm)
        if int((np.asarray(nxtm.safety) == 0).sum()) == 0:
            cur, curm, t = nxt, nxtm, t + n
            continue
        for dt in range(n):
            cur, curm = run(cfg, cur, 1, t + dt, curm)
            rep = {name: np.asarray(v) for name, v in
                   check.predicate_report(cur, cfg.log_cap).items()}
            names = [name for name, v in rep.items() if not v.all()]
            if names:
                grp = int(np.argwhere(~rep[names[0]])[0][0])
                return {"tick": t + dt,
                        "leaf_report": f"safety predicate "
                                       f"{'+'.join(names)} violated "
                                       f"(first group {grp})",
                        "leaf": names[0], "predicates": names,
                        "boundary": (t, t + n)}
        raise AssertionError(
            "safety bit latched over the chunk but no tick-by-tick "
            "re-execution violated a predicate — the engine is not "
            "deterministic in (state, t0)")
    return None


def _leaf_of(report: dict) -> str:
    """The divergent-leaf path out of a triage report (its own `leaf`
    key, else parsed from trees_equal_why's message)."""
    if "leaf" in report:
        return report["leaf"]
    why = report["leaf_report"]
    if "first divergent leaf: " in why:
        return why.split("first divergent leaf: ")[1].split(" — ")[0]
    return why


def divergence_repro(base_cfg: RaftConfig, engine_pair, n_groups: int,
                     n_ticks: int, chunk: int = 16):
    """repro builder over `obs.triage.bisect_divergence`:
    `engine_pair(cfg) -> (engine_a, engine_b)`, each an
    `(state, n, t) -> state` runner (e.g. the XLA scan vs the Pallas
    kernel, or a clean engine vs a corruption-injecting wrapper)."""
    from raft_tpu import sim
    from raft_tpu.obs.triage import bisect_divergence

    def repro(prog):
        cfg = dataclasses.replace(base_cfg, nemesis=tuple(prog))
        ea, eb = engine_pair(cfg)
        rep = bisect_divergence(ea, eb, sim.init(cfg, n_groups=n_groups),
                                n_ticks, chunk=chunk)
        if rep is not None:
            rep = {**rep, "leaf": _leaf_of(rep)}
        return rep
    return repro


def safety_repro(base_cfg: RaftConfig, n_groups: int, n_ticks: int,
                 chunk: int = 16):
    """repro builder over `first_unsafe_tick` (single-engine safety
    violations — what the search loop feeds the shrinker)."""
    def repro(prog):
        cfg = dataclasses.replace(base_cfg, nemesis=tuple(prog))
        return first_unsafe_tick(cfg, n_groups, n_ticks, chunk=chunk)
    return repro


def term_corruption_pair(tick: int, group: int = 0, node: int = 1,
                         bump: int = 4, only_under_nemesis: bool = True):
    """The SEEDED safety violation (tests/test_nemesis.py,
    `nemesis_search.py --seed-violation`): an `engine_pair` whose
    second engine is the clean XLA scan plus one injected fault —
    `nodes.term[group, node] += bump` as the run crosses `tick` — so
    `divergence_repro`'s bisect must name exactly that tick and the
    `.nodes.term` leaf. `bump` defaults comfortably above 1: terms are
    monotone under message exchange, so a +1 flip can be ABSORBED
    within the very tick it lands (a higher-term message heals it and
    the run never diverges). With `only_under_nemesis` (the default)
    the fault arms only while SOME clause's span covers the tick, the
    shape of a real gray-failure-triggered bug: the shrinker then
    converges to the one narrowed clause that keeps the bug alive
    instead of the empty program."""
    def pair(cfg):
        from raft_tpu.sim.run import run
        armed = (not only_under_nemesis) \
            or any(c[1] <= tick < c[2] for c in cfg.nemesis)

        def clean(s, n, t):
            return run(cfg, s, n, t)[0]

        def corrupt(s, n, t):
            if not armed or not t <= tick < t + n:
                return run(cfg, s, n, t)[0]
            # Tick-by-tick through the window holding the injection:
            # reuses the n=1 program the bisect compiles anyway, so a
            # shrink candidate costs ONE fresh XLA compile, not three
            # (each candidate program is a distinct static config).
            for tt in range(t, t + n):
                if tt == tick:
                    s = s._replace(nodes=s.nodes._replace(
                        term=s.nodes.term.at[group, node].add(bump)))
                s = run(cfg, s, 1, tt)[0]
            return s
        return clean, corrupt
    return pair


# ------------------------------------------------------------ shrinker


def shrink(prog: tuple, repro, log=None):
    """Greedy minimization: repeatedly try dropping a clause, then
    halving a clause's span, keeping any edit after which
    `repro(program)` still returns a report — to a fixpoint where no
    single edit reproduces. Deterministic (fixed edit order, no draws);
    cids survive edits, so the minimal program's surviving schedules
    are bit-identical to the original's. Returns (minimal_program,
    final_report)."""
    prog = tuple(tuple(c) for c in prog)
    report = repro(prog)
    if report is None:
        raise ValueError("shrink: the starting program does not reproduce")
    changed = True
    while changed:
        changed = False
        for i in range(len(prog)):
            cand = prog[:i] + prog[i + 1:]
            rep = repro(cand)
            if rep is not None:
                if log is not None:
                    log(f"shrink: dropped clause cid={prog[i][7]} -> "
                        f"{len(cand)} clause(s), still reproduces at "
                        f"tick {rep['tick']}")
                prog, report, changed = cand, rep, True
                break
        if changed:
            continue
        for i, c in enumerate(prog):
            if c[2] - c[1] < 2:
                continue
            mid = (c[1] + c[2]) // 2
            for half in ((c[1], mid), (mid, c[2])):
                cand = prog[:i] + (c[:1] + half + c[3:],) + prog[i + 1:]
                rep = repro(cand)
                if rep is not None:
                    if log is not None:
                        log(f"shrink: narrowed clause cid={c[7]} span to "
                            f"[{half[0]}, {half[1]}), still reproduces "
                            f"at tick {rep['tick']}")
                    prog, report, changed = cand, rep, True
                    break
            if changed:
                break
    return prog, report


# ---------------------------------------------------- corpus persistence


def save_corpus(dirpath: str, corpus) -> int:
    """Persist a search corpus (r18: `--corpus DIR`): one JSON file per
    coverage-novel program, named by program hash — idempotent across
    runs (re-saving a program overwrites identical bytes), so repeated
    hunts into the same DIR accumulate coverage monotonically."""
    import os
    os.makedirs(dirpath, exist_ok=True)
    for prog in corpus:
        h = program_hash(prog)
        with open(os.path.join(dirpath, f"corpus_{h}.json"), "w") as fh:
            json.dump({"schema": ARTIFACT_SCHEMA,
                       "kind": "nemesis-corpus-entry",
                       "program": to_json(prog),
                       "program_hash": h}, fh, indent=1, sort_keys=True)
            fh.write("\n")
    return len(corpus)


def load_corpus(dirpath: str) -> list:
    """Reload a persisted corpus (sorted by filename, so the seeded
    mutation pool is deterministic); [] when DIR is absent or holds no
    entries. Entries failing the hash self-check are skipped loudly
    rather than poisoning a deterministic hunt."""
    import glob
    import os
    import sys
    progs = []
    for path in sorted(glob.glob(os.path.join(dirpath, "corpus_*.json"))):
        with open(path) as fh:
            entry = json.load(fh)
        if entry.get("kind") != "nemesis-corpus-entry":
            continue
        prog = from_json(entry["program"])
        if program_hash(prog) != entry.get("program_hash"):
            print(f"[corpus] {path}: hash mismatch, skipping",
                  file=sys.stderr)
            continue
        progs.append(prog)
    return progs


# ----------------------------------------------------------- artifacts

ARTIFACT_SCHEMA = 1


def reproducer(cfg: RaftConfig, n_ticks: int, report: dict,
               engines: str, note: str = "",
               inject: dict | None = None,
               n_groups: int | None = None) -> dict:
    """The minimal-reproducer JSON artifact: self-contained (full
    config incl. the program, both hashed), replayable, and diffable —
    the thing a violation checks in next to its fix. `inject` records
    a SEEDED fault's parameters (`term_corruption_pair`) so a replayer
    can rebuild the corrupting engine; None = the violation was real.
    `n_groups` is the RUN's group count (the violating group must
    exist in the replay universe — `RaftConfig.n_groups` is the
    oracle's per-Cluster default, not the batched run shape)."""
    from raft_tpu.obs.manifest import config_hash
    return {
        "schema": ARTIFACT_SCHEMA, "kind": "nemesis-reproducer",
        "config": dataclasses.asdict(cfg),
        "config_hash": config_hash(cfg),
        "program": to_json(cfg.nemesis),
        "program_hash": program_hash(cfg.nemesis),
        "n_ticks": int(n_ticks),
        "n_groups": None if n_groups is None else int(n_groups),
        "engines": engines,
        "inject": inject,
        "violation": {"tick": int(report["tick"]),
                      "leaf": _leaf_of(report),
                      "leaf_report": report["leaf_report"],
                      "boundary": list(report["boundary"])},
        "note": note,
    }


def save_reproducer(path: str, artifact: dict) -> str:
    with open(path, "w") as fh:
        json.dump(artifact, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path


def load_reproducer(path: str):
    """(cfg, artifact) from a saved reproducer. The program rides
    inside the config dict (normalized by RaftConfig.__post_init__);
    the separate `program` list is checked against it."""
    with open(path) as fh:
        artifact = json.load(fh)
    if artifact.get("schema") != ARTIFACT_SCHEMA:
        raise ValueError(f"unknown reproducer schema "
                         f"{artifact.get('schema')!r}")
    cfg = RaftConfig(**artifact["config"])
    if cfg.nemesis != from_json(artifact["program"]):
        raise ValueError("reproducer program list disagrees with the "
                         "embedded config's nemesis field")
    if artifact["program_hash"] != program_hash(cfg.nemesis):
        raise ValueError("reproducer program_hash does not match its "
                         "program")
    return cfg, artifact


def verify_reproducer(artifact: dict, repro) -> dict:
    """Replay: run the caller's repro on the artifact's program and
    require the SAME violation tick and leaf. Returns the fresh report
    (raises on silence or drift — a reproducer that stopped reproducing
    is itself a finding)."""
    cfg = RaftConfig(**artifact["config"])
    rep = repro(cfg.nemesis)
    if rep is None:
        raise AssertionError("reproducer no longer reproduces (clean run)")
    want = artifact["violation"]
    if rep["tick"] != want["tick"] or _leaf_of(rep) != want["leaf"]:
        raise AssertionError(
            f"reproducer drifted: replay names tick {rep['tick']} leaf "
            f"{_leaf_of(rep)!r}, artifact recorded tick {want['tick']} "
            f"leaf {want['leaf']!r}")
    return rep
