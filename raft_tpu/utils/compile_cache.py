"""One shared recipe for the on-disk XLA compile cache.

Compiles on the CPU build box are the wall (minutes per program, 20+
in its slow mode), so every driver that can reuse the test suite's
cache must point at the SAME directory with the SAME threshold —
tests/conftest.py, the dryrun subprocess, and scripts/multichip_sweep
all do. This helper is the single copy of that recipe; a second
hand-rolled copy that drifts silently turns the shared-warm-compile
design (e.g. parallel/kmesh.faulted_64_cfg) back into cold compiles.
"""

from __future__ import annotations

import os

# tests/.jax_cache at the repo root — machine-local, gitignored.
DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "tests", ".jax_cache")


def enable(cache_dir: str | None = None,
           min_compile_secs: float = 1.0) -> str:
    """Point jax's persistent compilation cache at the repo's shared
    directory (or `cache_dir`). Call AFTER `import jax` and any
    platform pinning; returns the directory used.

    Also exports $JAX_COMPILATION_CACHE_DIR so every CHILD process
    inherits the same cache — the test suite shells out (static-audit /
    bench-history / sweep subprocess tests, the dryrun hop), and before
    this export each of those children recompiled from scratch inside
    the tier-1 budget while the parent's warm cache sat unused."""
    import jax

    cache_dir = cache_dir or DEFAULT_DIR
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_secs)
    os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir
    return cache_dir
