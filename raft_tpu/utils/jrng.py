"""JAX uint32 implementation of utils.rng — bit-identical by construction.

All functions accept and return uint32 (or bool) arrays and broadcast like
ordinary jnp ops, so they can be evaluated for whole [G], [G, K] or
[G, K, K] coordinate grids at once on device.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from raft_tpu.utils import rng as _r

# np (not jnp) scalars: identical u32 arithmetic, but they inline as
# literals wherever they are traced — a module-level jnp scalar is a
# device array, which a pallas kernel body cannot close over.
_GOLD = np.uint32(_r.GOLD)
_SEED0 = np.uint32(0x243F6A88)
_C1 = np.uint32(0x7FEB352D)
_C2 = np.uint32(0x846CA68B)


def _u32(x):
    return jnp.asarray(x).astype(jnp.uint32)


def mix32(x):
    x = _u32(x)
    x = x ^ (x >> 16)
    x = x * _C1
    x = x ^ (x >> 15)
    x = x * _C2
    x = x ^ (x >> 16)
    return x


def hash_u32(*vals):
    h = _u32(_SEED0)   # trace-time jnp scalar: inlines as a literal
    for v in vals:
        h = mix32(h * _GOLD + _u32(v))
    return h


def election_deadline(seed, g, node, draws, election_min, election_range):
    r = hash_u32(seed, _r.TAG_TIMEOUT, g, node, draws) % jnp.uint32(election_range)
    return (jnp.uint32(election_min) + r).astype(jnp.int32)


def _full_shape(*coords):
    return jnp.broadcast_shapes(*(jnp.shape(c) for c in coords))


def link_dropped(seed, g, tick, src, dst, drop_u32: int):
    # drop_u32 is a compile-time config constant <= 0xFFFFFFFF
    # (config._prob_to_u32); the fast path must keep the full broadcast
    # shape so faults-off and faults-on programs have identical signatures.
    if drop_u32 == 0:
        return jnp.zeros(_full_shape(g, tick, src, dst), jnp.bool_)
    return hash_u32(seed, _r.TAG_DROP, g, tick, src, dst) < jnp.uint32(drop_u32)


def node_alive(seed, g, node, tick, crash_u32: int, crash_epoch: int):
    if crash_u32 == 0:
        return jnp.ones(_full_shape(g, node, tick), jnp.bool_)
    epoch = _u32(tick) // jnp.uint32(crash_epoch)
    return hash_u32(seed, _r.TAG_CRASH, g, node, epoch) >= jnp.uint32(crash_u32)


def link_partitioned(seed, g, tick, src, dst, partition_u32: int, partition_epoch: int):
    if partition_u32 == 0:
        return jnp.zeros(_full_shape(g, tick, src, dst), jnp.bool_)
    epoch = _u32(tick) // jnp.uint32(partition_epoch)
    active = hash_u32(seed, _r.TAG_PART, g, epoch) < jnp.uint32(partition_u32)
    side_src = hash_u32(seed, _r.TAG_PART_SIDE, g, epoch, src) & jnp.uint32(1)
    side_dst = hash_u32(seed, _r.TAG_PART_SIDE, g, epoch, dst) & jnp.uint32(1)
    return active & (side_src != side_dst)


def client_payload(seed, g, term, index):
    # 30-bit: the CONFIG_FLAG bit must stay clear (see utils/rng.py).
    return (hash_u32(seed, _r.TAG_CMD, g, term, index) & jnp.uint32(0x3FFFFFFF)).astype(jnp.int32)


def reconfig_fires(seed, g, epoch, reconfig_u32: int):
    if reconfig_u32 == 0:
        return jnp.zeros(_full_shape(g, epoch), jnp.bool_)
    return hash_u32(seed, _r.TAG_RECONFIG, g, epoch) < jnp.uint32(reconfig_u32)


def reconfig_target(seed, g, epoch, k: int):
    return (hash_u32(seed, _r.TAG_RECONFIG_NODE, g, epoch)
            % jnp.uint32(k)).astype(jnp.int32)


def transfer_fires(seed, g, epoch, transfer_u32: int):
    if transfer_u32 == 0:
        return jnp.zeros(_full_shape(g, epoch), jnp.bool_)
    return hash_u32(seed, _r.TAG_TRANSFER, g, epoch) < jnp.uint32(transfer_u32)


def transfer_target(seed, g, epoch, k: int):
    return (hash_u32(seed, _r.TAG_TRANSFER_NODE, g, epoch)
            % jnp.uint32(k)).astype(jnp.int32)


def client_arrives(seed, g, sid, tick, clients_u32: int):
    if clients_u32 == 0:
        return jnp.zeros(_full_shape(g, sid, tick), jnp.bool_)
    return hash_u32(seed, _r.TAG_CLIENT_ARRIVAL, g, sid, tick) \
        < jnp.uint32(clients_u32)


def client_val(seed, g, sid, seq):
    return (hash_u32(seed, _r.TAG_CLIENT_VAL, g, sid, seq)
            & jnp.uint32(0x3FF)).astype(jnp.int32)


def digest_update(digest, index, payload):
    return mix32(_u32(digest) * _GOLD + mix32(_u32(index) * _GOLD + _u32(payload)))


# ------------------------------------------- compiled nemesis evaluators
# u32-lane twins of utils.rng's nemesis evaluators (DESIGN.md §14),
# bit-identical by construction and pinned by tests/test_nemesis.py.
# `prog` is a STATIC tuple of 8-int clauses (the python loop unrolls at
# trace time, exactly like the K-unrolled handlers); every per-lane
# value derives from hash compares on runtime coordinates, so the masks
# are Mosaic-legal inside the Pallas kernel (no i1 constants). The
# bodies are elementwise-only — one implementation serves the XLA
# [G, ...] layouts and the kernel [.., 8, 128] tiles, enforced by the
# analysis linter's elementwise rule over these functions.


def _nem_active(seed, c, g, t):
    """One clause's span ∧ per-group participation gate (broadcast)."""
    _, t0, t1, group_u32, _, _, _, cid = c
    span = (jnp.asarray(t) >= t0) & (jnp.asarray(t) < t1)
    return span & (hash_u32(seed, _r.TAG_NEM_GROUP, cid, g)
                   < jnp.uint32(group_u32))


def nem_link_ok(seed, prog, g, t, src, dst, k: int):
    relevant = False
    blocked = None
    for c in prog:
        kind, t0, t1, group_u32, p_u32, a, b, cid = c
        if kind not in _r.NEM_LINK_KINDS:
            continue
        # Relevance is established BEFORE the static per-kind no-op
        # skips below, so a link program whose clauses are all no-ops
        # (e.g. a flaky link in a k=1 group) stays legal on every
        # engine, exactly like utils.rng's host evaluator.
        relevant = True
        if kind == _r.NEM_SLOW:
            target = hash_u32(seed, _r.TAG_NEM_NODE, cid, g) % jnp.uint32(k)
            hit = None
            if a & 1:
                hit = _u32(src) == target
            if a & 2:
                h2 = _u32(dst) == target
                hit = h2 if hit is None else hit | h2
            if hit is None:
                continue   # direction mask 0: statically a no-op
        elif kind == _r.NEM_FLAKY:
            if k < 2:
                continue   # a 1-node group has no links
            s = hash_u32(seed, _r.TAG_NEM_NODE, cid, g, 0) % jnp.uint32(k)
            d = (s + jnp.uint32(1)
                 + hash_u32(seed, _r.TAG_NEM_NODE, cid, g, 1)
                 % jnp.uint32(k - 1)) % jnp.uint32(k)
            burst = hash_u32(seed, _r.TAG_NEM_BURST, cid, g,
                             _u32(t) // jnp.uint32(a)) < jnp.uint32(b)
            hit = (_u32(src) == s) & (_u32(dst) == d) & burst
        elif kind == _r.NEM_WAN:
            hit = (hash_u32(seed, _r.TAG_NEM_NODE, cid, g, src)
                   % jnp.uint32(a)
                   != hash_u32(seed, _r.TAG_NEM_NODE, cid, g, dst)
                   % jnp.uint32(a))
        else:   # NEM_WAVE
            wave = ((_u32(t) + _u32(g)) % jnp.uint32(a)) < jnp.uint32(b)
            ep = _u32(t) // jnp.uint32(a)
            hit = wave & (
                (hash_u32(seed, _r.TAG_NEM_SIDE, cid, g, ep, src)
                 & jnp.uint32(1))
                != (hash_u32(seed, _r.TAG_NEM_SIDE, cid, g, ep, dst)
                    & jnp.uint32(1)))
        drop = (_nem_active(seed, c, g, t) & hit
                & (hash_u32(seed, _r.TAG_NEM_LINK, cid, g, t, src, dst)
                   < jnp.uint32(p_u32)))
        blocked = drop if blocked is None else blocked | drop
    if not relevant:
        raise ValueError("nem_link_ok: no link clause in the program — "
                         "gate the call on cfg.nem_link")
    if blocked is None:
        return jnp.bool_(True)   # every link clause statically a no-op
    return jnp.logical_not(blocked)


def nem_alive(seed, prog, g, i, t):
    dead = None
    for c in prog:
        kind, t0, t1, group_u32, p_u32, a, b, cid = c
        if kind not in _r.NEM_CRASH_KINDS:
            continue
        down = (_nem_active(seed, c, g, t)
                & (hash_u32(seed, _r.TAG_NEM_CRASH, cid, g, i,
                            _u32(t) // jnp.uint32(a)) < jnp.uint32(p_u32)))
        dead = down if dead is None else dead | down
    if dead is None:
        raise ValueError("nem_alive: no crash clause in the program — "
                         "gate the call on cfg.nem_crash")
    return jnp.logical_not(dead)


def nem_deadline_extra(seed, prog, g, i, t):
    extra = None
    for c in prog:
        kind, t0, t1, group_u32, p_u32, a, b, cid = c
        if kind not in _r.NEM_TIMING_KINDS:
            continue
        act = (_nem_active(seed, c, g, t)
               & (hash_u32(seed, _r.TAG_NEM_NODE, cid, g, i)
                  < jnp.uint32(p_u32)))
        term = jnp.where(act, jnp.int32(a), jnp.int32(0))
        extra = term if extra is None else extra + term
    if extra is None:
        raise ValueError("nem_deadline_extra: no timing clause in the "
                         "program — gate the call on cfg.nem_skew")
    return extra


def nem_disk_full(seed, prog, g, i, t, k: int):
    """u32-lane twin of utils.rng.nem_disk_full (r20, DESIGN.md §19)."""
    full = None
    for c in prog:
        kind, t0, t1, group_u32, p_u32, a, b, cid = c
        if kind not in _r.NEM_DISK_KINDS:
            continue
        target = hash_u32(seed, _r.TAG_NEM_NODE, cid, g) % jnp.uint32(k)
        hit = (_nem_active(seed, c, g, t)
               & (_u32(i) == target)
               & (hash_u32(seed, _r.TAG_NEM_DISK, cid, g,
                           _u32(t) // jnp.uint32(a)) < jnp.uint32(p_u32)))
        full = hit if full is None else full | hit
    if full is None:
        raise ValueError("nem_disk_full: no disk clause in the program — "
                         "gate the call on cfg.nem_disk")
    return full


def nem_compact_block(seed, prog, g, i, t):
    """u32-lane twin of utils.rng.nem_compact_block (r20)."""
    blocked = None
    for c in prog:
        kind, t0, t1, group_u32, p_u32, a, b, cid = c
        if kind not in _r.NEM_COMPACT_KINDS:
            continue
        hit = (_nem_active(seed, c, g, t)
               & (hash_u32(seed, _r.TAG_NEM_COMPACT, cid, g, i,
                           _u32(t) // jnp.uint32(a)) < jnp.uint32(p_u32)))
        blocked = hit if blocked is None else blocked | hit
    if blocked is None:
        raise ValueError("nem_compact_block: no compaction clause in the "
                         "program — gate the call on cfg.nem_compact")
    return blocked
