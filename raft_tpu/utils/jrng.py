"""JAX uint32 implementation of utils.rng — bit-identical by construction.

All functions accept and return uint32 (or bool) arrays and broadcast like
ordinary jnp ops, so they can be evaluated for whole [G], [G, K] or
[G, K, K] coordinate grids at once on device.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from raft_tpu.utils import rng as _r

# np (not jnp) scalars: identical u32 arithmetic, but they inline as
# literals wherever they are traced — a module-level jnp scalar is a
# device array, which a pallas kernel body cannot close over.
_GOLD = np.uint32(_r.GOLD)
_SEED0 = np.uint32(0x243F6A88)
_C1 = np.uint32(0x7FEB352D)
_C2 = np.uint32(0x846CA68B)


def _u32(x):
    return jnp.asarray(x).astype(jnp.uint32)


def mix32(x):
    x = _u32(x)
    x = x ^ (x >> 16)
    x = x * _C1
    x = x ^ (x >> 15)
    x = x * _C2
    x = x ^ (x >> 16)
    return x


def hash_u32(*vals):
    h = _u32(_SEED0)   # trace-time jnp scalar: inlines as a literal
    for v in vals:
        h = mix32(h * _GOLD + _u32(v))
    return h


def election_deadline(seed, g, node, draws, election_min, election_range):
    r = hash_u32(seed, _r.TAG_TIMEOUT, g, node, draws) % jnp.uint32(election_range)
    return (jnp.uint32(election_min) + r).astype(jnp.int32)


def _full_shape(*coords):
    return jnp.broadcast_shapes(*(jnp.shape(c) for c in coords))


def link_dropped(seed, g, tick, src, dst, drop_u32: int):
    # drop_u32 is a compile-time config constant <= 0xFFFFFFFF
    # (config._prob_to_u32); the fast path must keep the full broadcast
    # shape so faults-off and faults-on programs have identical signatures.
    if drop_u32 == 0:
        return jnp.zeros(_full_shape(g, tick, src, dst), jnp.bool_)
    return hash_u32(seed, _r.TAG_DROP, g, tick, src, dst) < jnp.uint32(drop_u32)


def node_alive(seed, g, node, tick, crash_u32: int, crash_epoch: int):
    if crash_u32 == 0:
        return jnp.ones(_full_shape(g, node, tick), jnp.bool_)
    epoch = _u32(tick) // jnp.uint32(crash_epoch)
    return hash_u32(seed, _r.TAG_CRASH, g, node, epoch) >= jnp.uint32(crash_u32)


def link_partitioned(seed, g, tick, src, dst, partition_u32: int, partition_epoch: int):
    if partition_u32 == 0:
        return jnp.zeros(_full_shape(g, tick, src, dst), jnp.bool_)
    epoch = _u32(tick) // jnp.uint32(partition_epoch)
    active = hash_u32(seed, _r.TAG_PART, g, epoch) < jnp.uint32(partition_u32)
    side_src = hash_u32(seed, _r.TAG_PART_SIDE, g, epoch, src) & jnp.uint32(1)
    side_dst = hash_u32(seed, _r.TAG_PART_SIDE, g, epoch, dst) & jnp.uint32(1)
    return active & (side_src != side_dst)


def client_payload(seed, g, term, index):
    # 30-bit: the CONFIG_FLAG bit must stay clear (see utils/rng.py).
    return (hash_u32(seed, _r.TAG_CMD, g, term, index) & jnp.uint32(0x3FFFFFFF)).astype(jnp.int32)


def reconfig_fires(seed, g, epoch, reconfig_u32: int):
    if reconfig_u32 == 0:
        return jnp.zeros(_full_shape(g, epoch), jnp.bool_)
    return hash_u32(seed, _r.TAG_RECONFIG, g, epoch) < jnp.uint32(reconfig_u32)


def reconfig_target(seed, g, epoch, k: int):
    return (hash_u32(seed, _r.TAG_RECONFIG_NODE, g, epoch)
            % jnp.uint32(k)).astype(jnp.int32)


def transfer_fires(seed, g, epoch, transfer_u32: int):
    if transfer_u32 == 0:
        return jnp.zeros(_full_shape(g, epoch), jnp.bool_)
    return hash_u32(seed, _r.TAG_TRANSFER, g, epoch) < jnp.uint32(transfer_u32)


def transfer_target(seed, g, epoch, k: int):
    return (hash_u32(seed, _r.TAG_TRANSFER_NODE, g, epoch)
            % jnp.uint32(k)).astype(jnp.int32)


def client_arrives(seed, g, sid, tick, clients_u32: int):
    if clients_u32 == 0:
        return jnp.zeros(_full_shape(g, sid, tick), jnp.bool_)
    return hash_u32(seed, _r.TAG_CLIENT_ARRIVAL, g, sid, tick) \
        < jnp.uint32(clients_u32)


def client_val(seed, g, sid, seq):
    return (hash_u32(seed, _r.TAG_CLIENT_VAL, g, sid, seq)
            & jnp.uint32(0x3FF)).astype(jnp.int32)


def digest_update(digest, index, payload):
    return mix32(_u32(digest) * _GOLD + mix32(_u32(index) * _GOLD + _u32(payload)))
