"""Counter-based deterministic randomness shared by both backends.

Everything stochastic in the simulator — election timeouts, message drops,
crash/partition schedules, client payloads — is a pure function of
``(seed, tag, coordinates...)`` through a 32-bit hash. There is no stateful
RNG anywhere: the CPU reference path calls the Python implementation with
plain ints, the TPU path calls the JAX implementation on uint32 lanes, and
the two are bit-identical by construction (``tests/test_rng.py``).

The mixer is the public-domain "lowbias32" finalizer (a Murmur3-style
avalanche); the fold is a multiply-accumulate by the 32-bit golden ratio.
"""

from __future__ import annotations

_U32 = 0xFFFFFFFF
GOLD = 0x9E3779B9
_SEED0 = 0x243F6A88  # pi fraction, arbitrary non-zero start

# Domain-separation tags.
TAG_TIMEOUT = 1   # election deadline draws
TAG_DROP = 2      # per-link per-tick message loss
TAG_CRASH = 3     # per-node per-epoch crash schedule
TAG_PART = 4      # per-group per-epoch partition active?
TAG_PART_SIDE = 5  # per-node partition side assignment
TAG_CMD = 6       # client command payloads
TAG_RECONFIG = 7       # per-group per-epoch membership-change proposal?
TAG_RECONFIG_NODE = 8  # which node's membership the proposal toggles
TAG_TRANSFER = 9       # per-group per-epoch leadership-transfer attempt?
TAG_TRANSFER_NODE = 10  # which node the transfer hands leadership to
TAG_CLIENT_ARRIVAL = 11  # per-(group, sid) per-tick client-op arrival?
TAG_CLIENT_VAL = 12      # the 10-bit value hash of client op (sid, seq)


def mix32(x: int) -> int:
    """32-bit avalanche (lowbias32). Pure-Python reference implementation."""
    x &= _U32
    x ^= x >> 16
    x = (x * 0x7FEB352D) & _U32
    x ^= x >> 15
    x = (x * 0x846CA68B) & _U32
    x ^= x >> 16
    return x


def hash_u32(*vals: int) -> int:
    """Fold arbitrarily many int coordinates into one uint32."""
    h = _SEED0
    for v in vals:
        h = mix32((h * GOLD + (v & _U32)) & _U32)
    return h


def election_deadline(seed: int, g: int, node: int, draws: int,
                      election_min: int, election_range: int) -> int:
    """The `draws`-th randomized election deadline for (group, node)."""
    return election_min + hash_u32(seed, TAG_TIMEOUT, g, node, draws) % election_range


def link_dropped(seed: int, g: int, tick: int, src: int, dst: int,
                 drop_u32: int) -> bool:
    return hash_u32(seed, TAG_DROP, g, tick, src, dst) < drop_u32


def node_alive(seed: int, g: int, node: int, tick: int,
               crash_u32: int, crash_epoch: int) -> bool:
    return hash_u32(seed, TAG_CRASH, g, node, tick // crash_epoch) >= crash_u32


def link_partitioned(seed: int, g: int, tick: int, src: int, dst: int,
                     partition_u32: int, partition_epoch: int) -> bool:
    epoch = tick // partition_epoch
    if hash_u32(seed, TAG_PART, g, epoch) >= partition_u32:
        return False
    side_src = hash_u32(seed, TAG_PART_SIDE, g, epoch, src) & 1
    side_dst = hash_u32(seed, TAG_PART_SIDE, g, epoch, dst) & 1
    return side_src != side_dst


def client_payload(seed: int, g: int, term: int, index: int) -> int:
    """Deterministic opaque payload for the entry at (group, term, index).

    30-bit so the CONFIG_FLAG bit (config.py) stays clear: a client
    payload can never be mistaken for a membership-change entry.
    """
    return hash_u32(seed, TAG_CMD, g, term, index) & 0x3FFFFFFF


def reconfig_fires(seed: int, g: int, epoch: int, reconfig_u32: int) -> bool:
    """Does the membership-change schedule propose at this epoch?"""
    return hash_u32(seed, TAG_RECONFIG, g, epoch) < reconfig_u32


def reconfig_target(seed: int, g: int, epoch: int, k: int) -> int:
    """Which node's membership the epoch's proposal toggles."""
    return hash_u32(seed, TAG_RECONFIG_NODE, g, epoch) % k


def transfer_fires(seed: int, g: int, epoch: int, transfer_u32: int) -> bool:
    """Does the leadership-transfer schedule attempt at this epoch?"""
    return hash_u32(seed, TAG_TRANSFER, g, epoch) < transfer_u32


def transfer_target(seed: int, g: int, epoch: int, k: int) -> int:
    """Which node the epoch's transfer attempt hands leadership to."""
    return hash_u32(seed, TAG_TRANSFER_NODE, g, epoch) % k


def client_arrives(seed: int, g: int, sid: int, tick: int,
                   clients_u32: int) -> bool:
    """Does a new op arrive at (group, sid)'s open-loop client this tick?"""
    return hash_u32(seed, TAG_CLIENT_ARRIVAL, g, sid, tick) < clients_u32


def client_val(seed: int, g: int, sid: int, seq: int) -> int:
    """10-bit value hash of client op (sid, seq) — a pure function of
    the op identity, so a RETRY submits the byte-identical payload."""
    return hash_u32(seed, TAG_CLIENT_VAL, g, sid, seq) & 0x3FF


def digest_update(digest: int, index: int, payload: int) -> int:
    """State-machine hash chain: apply entry `index` with `payload`."""
    return mix32((digest * GOLD + mix32((index * GOLD + payload) & _U32)) & _U32)
