"""Counter-based deterministic randomness shared by both backends.

Everything stochastic in the simulator — election timeouts, message drops,
crash/partition schedules, client payloads — is a pure function of
``(seed, tag, coordinates...)`` through a 32-bit hash. There is no stateful
RNG anywhere: the CPU reference path calls the Python implementation with
plain ints, the TPU path calls the JAX implementation on uint32 lanes, and
the two are bit-identical by construction (``tests/test_rng.py``).

The mixer is the public-domain "lowbias32" finalizer (a Murmur3-style
avalanche); the fold is a multiply-accumulate by the 32-bit golden ratio.
"""

from __future__ import annotations

_U32 = 0xFFFFFFFF
GOLD = 0x9E3779B9
_SEED0 = 0x243F6A88  # pi fraction, arbitrary non-zero start

# Domain-separation tags.
TAG_TIMEOUT = 1   # election deadline draws
TAG_DROP = 2      # per-link per-tick message loss
TAG_CRASH = 3     # per-node per-epoch crash schedule
TAG_PART = 4      # per-group per-epoch partition active?
TAG_PART_SIDE = 5  # per-node partition side assignment
TAG_CMD = 6       # client command payloads
TAG_RECONFIG = 7       # per-group per-epoch membership-change proposal?
TAG_RECONFIG_NODE = 8  # which node's membership the proposal toggles
TAG_TRANSFER = 9       # per-group per-epoch leadership-transfer attempt?
TAG_TRANSFER_NODE = 10  # which node the transfer hands leadership to
TAG_CLIENT_ARRIVAL = 11  # per-(group, sid) per-tick client-op arrival?
TAG_CLIENT_VAL = 12      # the 10-bit value hash of client op (sid, seq)
# Nemesis scenario compiler (DESIGN.md §14): every gray-failure clause
# compiles to draws under these tags, domain-separated per clause by
# its cid so dropping one clause never reshuffles another's schedule
# (the property the auto-shrinker's monotone minimization rests on).
TAG_NEM_GROUP = 13   # per-clause per-group participation
TAG_NEM_NODE = 14    # per-clause node / link-endpoint / site selection
TAG_NEM_LINK = 15    # per-clause per-link per-tick delivery draw
TAG_NEM_CRASH = 16   # crash-storm epoch draws
TAG_NEM_SIDE = 17    # partition-wave side assignment (per period)
TAG_NEM_BURST = 18   # flaky-link burst-epoch draws
# Storage-pressure seam (r20, DESIGN.md §19): the two clause kinds the
# r14 compiler could not express because the tick had no storage seam.
TAG_NEM_DISK = 19     # disk-full-follower sub-epoch draws
TAG_NEM_COMPACT = 20  # compaction-pressure sub-epoch draws


# ------------------------------------------------------ nemesis programs
# A nemesis program is a static tuple of 8-int clauses
#     (kind, t0, t1, group_u32, p_u32, a, b, cid)
# built by raft_tpu/nemesis/program.py and carried in
# RaftConfig.nemesis. The clause kinds and their compiled elementwise
# semantics live HERE (with bit-identical jrng twins) because this
# module is the repo's one source of schedule randomness: a clause is
# nothing but a pure (seed, TAG_NEM_*, cid, coords) hash family gating
# the same three seams the config-4/5 fault mix already uses — the
# delivery filter, the aliveness mask, and the election-deadline draw.
#
# Kind-specific meaning of (p_u32, a, b):
#   NEM_SLOW   slow-but-alive follower: links touching the hash-chosen
#              target node drop w.p. p_u32 per tick; a = direction mask
#              (1 = from the target, 2 = to it, 3 = both); b unused.
#   NEM_FLAKY  asymmetric flaky link: ONE hash-chosen ordered pair
#              (s -> d) drops w.p. p_u32, but only inside bursts —
#              sub-epochs of a ticks firing w.p. b (a u32 threshold).
#   NEM_WAN    heterogeneous WAN delivery: nodes hash onto a sites;
#              cross-site links drop w.p. p_u32 per tick (in a
#              tick-synchronous world with heartbeat retransmission, a
#              d-tick link delay IS a geometric redelivery — loss with
#              retry — which is how latency compiles to this form).
#   NEM_SKEW   timeout/clock skew: nodes selected w.p. p_u32 add the
#              SIGNED a to every election-deadline draw made during
#              the span (deadline clamps at 1); b unused.
#   NEM_STORM  crash-recovery storm: per (node, sub-epoch of a ticks)
#              the node is down w.p. p_u32 — a second, faster crash
#              schedule ANDed into the base one; b unused.
#   NEM_WAVE   correlated partition wave: a partition window of b
#              ticks sweeps the fleet with period a (group g enters it
#              g ticks after g-1); inside the window cross-side links
#              (sides re-drawn each period) drop w.p. p_u32 — p_u32
#              below 1.0 is a leaky, gray partition.
#   NEM_DISK   disk-full follower (r20, DESIGN.md §19): the hash-chosen
#              target node's persistence budget is exhausted during
#              sub-epochs of a ticks firing w.p. p_u32 — every local
#              append fails (the entry is NOT durable, so it is never
#              acked; the leader's retransmission loop is the
#              backpressure). b unused.
#   NEM_COMPACT compaction pressure (r20): each node independently has
#              its snapshot/compaction step blocked during sub-epochs
#              of a ticks firing w.p. p_u32 — the log_cap ring
#              genuinely fills and the window invariant becomes a
#              runtime backpressure path. b unused.
NEM_SLOW = 1
NEM_FLAKY = 2
NEM_WAN = 3
NEM_SKEW = 4
NEM_STORM = 5
NEM_WAVE = 6
NEM_DISK = 7
NEM_COMPACT = 8
NEM_KINDS = (NEM_SLOW, NEM_FLAKY, NEM_WAN, NEM_SKEW, NEM_STORM, NEM_WAVE,
             NEM_DISK, NEM_COMPACT)
# Which seam each kind compiles onto — RaftConfig.nem_link / nem_crash
# / nem_skew / nem_disk / nem_compact filter by these, and the engines
# statically gate each seam on its filtered subprogram being non-empty.
# Every kind MUST appear in exactly one tuple
# (analysis.contracts.nemesis_problems proves the partition, so a new
# kind cannot be silently ignored by every seam).
NEM_LINK_KINDS = (NEM_SLOW, NEM_FLAKY, NEM_WAN, NEM_WAVE)
NEM_CRASH_KINDS = (NEM_STORM,)
NEM_TIMING_KINDS = (NEM_SKEW,)
NEM_DISK_KINDS = (NEM_DISK,)
NEM_COMPACT_KINDS = (NEM_COMPACT,)


def mix32(x: int) -> int:
    """32-bit avalanche (lowbias32). Pure-Python reference implementation."""
    x &= _U32
    x ^= x >> 16
    x = (x * 0x7FEB352D) & _U32
    x ^= x >> 15
    x = (x * 0x846CA68B) & _U32
    x ^= x >> 16
    return x


def hash_u32(*vals: int) -> int:
    """Fold arbitrarily many int coordinates into one uint32."""
    h = _SEED0
    for v in vals:
        h = mix32((h * GOLD + (v & _U32)) & _U32)
    return h


def election_deadline(seed: int, g: int, node: int, draws: int,
                      election_min: int, election_range: int) -> int:
    """The `draws`-th randomized election deadline for (group, node)."""
    return election_min + hash_u32(seed, TAG_TIMEOUT, g, node, draws) % election_range


def link_dropped(seed: int, g: int, tick: int, src: int, dst: int,
                 drop_u32: int) -> bool:
    return hash_u32(seed, TAG_DROP, g, tick, src, dst) < drop_u32


def node_alive(seed: int, g: int, node: int, tick: int,
               crash_u32: int, crash_epoch: int) -> bool:
    return hash_u32(seed, TAG_CRASH, g, node, tick // crash_epoch) >= crash_u32


def link_partitioned(seed: int, g: int, tick: int, src: int, dst: int,
                     partition_u32: int, partition_epoch: int) -> bool:
    epoch = tick // partition_epoch
    if hash_u32(seed, TAG_PART, g, epoch) >= partition_u32:
        return False
    side_src = hash_u32(seed, TAG_PART_SIDE, g, epoch, src) & 1
    side_dst = hash_u32(seed, TAG_PART_SIDE, g, epoch, dst) & 1
    return side_src != side_dst


def client_payload(seed: int, g: int, term: int, index: int) -> int:
    """Deterministic opaque payload for the entry at (group, term, index).

    30-bit so the CONFIG_FLAG bit (config.py) stays clear: a client
    payload can never be mistaken for a membership-change entry.
    """
    return hash_u32(seed, TAG_CMD, g, term, index) & 0x3FFFFFFF


def reconfig_fires(seed: int, g: int, epoch: int, reconfig_u32: int) -> bool:
    """Does the membership-change schedule propose at this epoch?"""
    return hash_u32(seed, TAG_RECONFIG, g, epoch) < reconfig_u32


def reconfig_target(seed: int, g: int, epoch: int, k: int) -> int:
    """Which node's membership the epoch's proposal toggles."""
    return hash_u32(seed, TAG_RECONFIG_NODE, g, epoch) % k


def transfer_fires(seed: int, g: int, epoch: int, transfer_u32: int) -> bool:
    """Does the leadership-transfer schedule attempt at this epoch?"""
    return hash_u32(seed, TAG_TRANSFER, g, epoch) < transfer_u32


def transfer_target(seed: int, g: int, epoch: int, k: int) -> int:
    """Which node the epoch's transfer attempt hands leadership to."""
    return hash_u32(seed, TAG_TRANSFER_NODE, g, epoch) % k


def client_arrives(seed: int, g: int, sid: int, tick: int,
                   clients_u32: int) -> bool:
    """Does a new op arrive at (group, sid)'s open-loop client this tick?"""
    return hash_u32(seed, TAG_CLIENT_ARRIVAL, g, sid, tick) < clients_u32


def client_val(seed: int, g: int, sid: int, seq: int) -> int:
    """10-bit value hash of client op (sid, seq) — a pure function of
    the op identity, so a RETRY submits the byte-identical payload."""
    return hash_u32(seed, TAG_CLIENT_VAL, g, sid, seq) & 0x3FF


def digest_update(digest: int, index: int, payload: int) -> int:
    """State-machine hash chain: apply entry `index` with `payload`."""
    return mix32((digest * GOLD + mix32((index * GOLD + payload) & _U32)) & _U32)


# ------------------------------------------- compiled nemesis evaluators
# Host-int reference implementations; utils/jrng.py carries the
# bit-identical u32-lane twins (tests/test_nemesis.py pins the parity
# on coordinate grids, like every other schedule pair). Callers pass
# the kind-FILTERED subprogram (RaftConfig.nem_link / nem_crash /
# nem_skew) and statically gate the call on it being non-empty — an
# evaluator that finds no relevant clause raises, so a mis-filtered
# program fails at trace/build time, never as a silent no-op.


def _nem_active(seed: int, c: tuple, g: int, t: int) -> bool:
    """One clause's span ∧ per-group participation gate."""
    _, t0, t1, group_u32, _, _, _, cid = c
    return (t0 <= t < t1
            and hash_u32(seed, TAG_NEM_GROUP, cid, g) < group_u32)


def nem_link_ok(seed, prog, g, t, src, dst, k):
    """True iff no active link clause blocks delivery on (src -> dst)
    at tick t — ANDed into the same delivery filter as drop/partition."""
    relevant = False
    ok = True
    for c in prog:
        kind, t0, t1, group_u32, p_u32, a, b, cid = c
        if kind not in NEM_LINK_KINDS:
            continue
        relevant = True
        if not _nem_active(seed, c, g, t):
            continue
        if kind == NEM_SLOW:
            target = hash_u32(seed, TAG_NEM_NODE, cid, g) % k
            hit = (((a & 1) != 0 and src == target)
                   or ((a & 2) != 0 and dst == target))
        elif kind == NEM_FLAKY:
            if k < 2:
                continue   # a 1-node group has no links
            s = hash_u32(seed, TAG_NEM_NODE, cid, g, 0) % k
            d = (s + 1 + hash_u32(seed, TAG_NEM_NODE, cid, g, 1)
                 % (k - 1)) % k
            hit = (src == s and dst == d
                   and hash_u32(seed, TAG_NEM_BURST, cid, g, t // a) < b)
        elif kind == NEM_WAN:
            hit = (hash_u32(seed, TAG_NEM_NODE, cid, g, src) % a
                   != hash_u32(seed, TAG_NEM_NODE, cid, g, dst) % a)
        else:   # NEM_WAVE
            wave = ((t + g) % a) < b
            hit = (wave
                   and (hash_u32(seed, TAG_NEM_SIDE, cid, g, t // a, src) & 1)
                   != (hash_u32(seed, TAG_NEM_SIDE, cid, g, t // a, dst) & 1))
        if hit and hash_u32(seed, TAG_NEM_LINK, cid, g, t, src, dst) < p_u32:
            ok = False
    if not relevant:
        raise ValueError("nem_link_ok: no link clause in the program — "
                         "gate the call on cfg.nem_link")
    return ok


def nem_alive(seed, prog, g, i, t):
    """True iff no active crash-storm clause holds node i down at tick
    t — ANDed into the base TAG_CRASH aliveness mask."""
    relevant = False
    alive = True
    for c in prog:
        kind, t0, t1, group_u32, p_u32, a, b, cid = c
        if kind not in NEM_CRASH_KINDS:
            continue
        relevant = True
        if (_nem_active(seed, c, g, t)
                and hash_u32(seed, TAG_NEM_CRASH, cid, g, i, t // a) < p_u32):
            alive = False
    if not relevant:
        raise ValueError("nem_alive: no crash clause in the program — "
                         "gate the call on cfg.nem_crash")
    return alive


def nem_deadline_extra(seed, prog, g, i, t):
    """Signed tick skew added to the election-deadline draw node i
    makes at tick t (callers clamp the skewed deadline at 1)."""
    relevant = False
    extra = 0
    for c in prog:
        kind, t0, t1, group_u32, p_u32, a, b, cid = c
        if kind not in NEM_TIMING_KINDS:
            continue
        relevant = True
        if (_nem_active(seed, c, g, t)
                and hash_u32(seed, TAG_NEM_NODE, cid, g, i) < p_u32):
            extra += a
    if not relevant:
        raise ValueError("nem_deadline_extra: no timing clause in the "
                         "program — gate the call on cfg.nem_skew")
    return extra


def nem_disk_full(seed, prog, g, i, t, k):
    """True iff an active disk-full clause exhausts node i's
    persistence budget at tick t (r20, DESIGN.md §19). The target node
    is hash-chosen per (clause, group) like NEM_SLOW's, so a quorum of
    healthy disks usually survives; fullness fires per sub-epoch of a
    ticks w.p. p_u32. A full disk fails every local append — the entry
    is not durable and must never be acked."""
    relevant = False
    full = False
    for c in prog:
        kind, t0, t1, group_u32, p_u32, a, b, cid = c
        if kind not in NEM_DISK_KINDS:
            continue
        relevant = True
        if not _nem_active(seed, c, g, t):
            continue
        target = hash_u32(seed, TAG_NEM_NODE, cid, g) % k
        if (i == target
                and hash_u32(seed, TAG_NEM_DISK, cid, g, t // a) < p_u32):
            full = True
    if not relevant:
        raise ValueError("nem_disk_full: no disk clause in the program — "
                         "gate the call on cfg.nem_disk")
    return full


def nem_compact_block(seed, prog, g, i, t):
    """True iff an active compaction-pressure clause blocks node i's
    snapshot/compaction step at tick t (r20, DESIGN.md §19): per-node
    per-sub-epoch-of-a-ticks draws under p_u32, so the log_cap ring
    genuinely fills while the clause holds."""
    relevant = False
    blocked = False
    for c in prog:
        kind, t0, t1, group_u32, p_u32, a, b, cid = c
        if kind not in NEM_COMPACT_KINDS:
            continue
        relevant = True
        if (_nem_active(seed, c, g, t)
                and hash_u32(seed, TAG_NEM_COMPACT, cid, g, i,
                             t // a) < p_u32):
            blocked = True
    if not relevant:
        raise ValueError("nem_compact_block: no compaction clause in the "
                         "program — gate the call on cfg.nem_compact")
    return blocked
