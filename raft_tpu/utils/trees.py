"""Byte-identical pytree comparison — the ONE definition behind every
engine-differential gate (test suite, bench promotion, kernel sweep).
Semantic changes here (dtype sensitivity, NaN handling) propagate to
all gates at once instead of drifting between hand-rolled copies."""

from __future__ import annotations

import jax
import numpy as np


def trees_equal(a, b) -> bool:
    """True iff the two pytrees have the same leaf count and every leaf
    pair is byte-identical (np.array_equal)."""
    ok, _ = trees_equal_why(a, b)
    return ok


def trees_equal_why(a, b, names=None):
    """(equal, why) — like `trees_equal`, but `why` names the first
    divergent leaf (via `names`, e.g. a NamedTuple's `_fields`) or the
    leaf-count mismatch, for diagnostics."""
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    if len(la) != len(lb):
        return False, f"leaf count {len(la)} != {len(lb)}"
    for n, (x, y) in enumerate(zip(la, lb)):
        if not np.array_equal(np.asarray(x), np.asarray(y)):
            label = names[n] if names and n < len(names) else f"leaf {n}"
            return False, f"first divergent leaf: {label}"
    return True, ""
