"""Byte-identical pytree comparison — the ONE definition behind every
engine-differential gate (test suite, bench promotion, kernel sweep) —
plus the leaf-level divergence report that feeds triage
(raft_tpu/obs/triage.py). Semantic changes here (dtype sensitivity, NaN
handling) propagate to all gates at once instead of drifting between
hand-rolled copies."""

from __future__ import annotations

import jax
import numpy as np


def trees_equal(a, b) -> bool:
    """True iff the two pytrees have the same leaf count and every leaf
    pair is byte-identical (np.array_equal semantics: NaN != NaN)."""
    ok, _ = trees_equal_why(a, b)
    return ok


def trees_equal_values(a, b) -> bool:
    """The values-only form: dtype-blind across integer/bool widths.
    See trees_equal_why(values_only=True)."""
    ok, _ = trees_equal_why(a, b, values_only=True)
    return ok


def leaf_mismatch(x, y, values_only: bool = False) -> str | None:
    """None when the two arrays are byte-identical; otherwise a one-line
    description carrying dtype, shape, the differing-element count, and
    the first differing index with both values — enough to aim a triage
    bisection without re-running anything.

    `values_only=True` is the narrow-dtype comparator mode (DESIGN.md
    §18): integer/bool leaves compare by VALUE through an exact int64
    lift, so a u16/i8 narrow-native leaf can be pinned against its
    wide i32 oracle twin. Shape mismatches still fail, and a dtype
    mismatch that is not an exact integer lift (e.g. float vs int)
    still fails — the mode relaxes width, never meaning."""
    x, y = np.asarray(x), np.asarray(y)
    meta_x = f"{x.dtype}{list(x.shape)}"
    meta_y = f"{y.dtype}{list(y.shape)}"
    if x.shape != y.shape:
        return f"shape mismatch: {meta_x} vs {meta_y}"
    if x.dtype != y.dtype:
        int_like = all(np.issubdtype(d, np.integer)
                       or np.issubdtype(d, np.bool_)
                       for d in (x.dtype, y.dtype))
        if not (values_only and int_like):
            return f"dtype mismatch: {meta_x} vs {meta_y}"
        # int64 holds every integer dtype in the repo exactly (widest
        # lane is u32), so the lift never aliases two distinct values.
        x, y = x.astype(np.int64), y.astype(np.int64)
    neq = x != y   # NaN != NaN — matches np.array_equal's default
    n_bad = int(np.count_nonzero(neq))
    if n_bad == 0:
        return None
    if neq.ndim == 0:
        return f"{meta_x}: {x!r} != {y!r}"
    first = np.unravel_index(int(np.argmax(neq)), neq.shape)
    idx = ",".join(str(int(i)) for i in first)
    return (f"{meta_x}: {n_bad}/{x.size} elements differ, first at "
            f"[{idx}]: {x[first]!r} != {y[first]!r}")


def _label(path, n, names):
    if names and n < len(names):
        return names[n]
    label = jax.tree_util.keystr(path) if path else ""
    return label or f"leaf {n}"


def trees_equal_why(a, b, names=None, values_only: bool = False):
    """(equal, why) — like `trees_equal`, but `why` names the FIRST
    divergent leaf by its pytree path (e.g. `.nodes.log_term` for a
    `State`) with its dtype/shape and first differing element, or the
    leaf-count mismatch. `names` (e.g. a NamedTuple's `_fields`)
    overrides the path labels when given — kept for callers that compare
    bare leaf tuples with their own naming. `values_only=True` relaxes
    integer/bool WIDTH only (the narrow-native differential mode, see
    leaf_mismatch) — engine-to-engine gates at matching cfg keep the
    default byte-strict mode."""
    pa, _ = jax.tree_util.tree_flatten_with_path(a)
    pb, _ = jax.tree_util.tree_flatten_with_path(b)
    if len(pa) != len(pb):
        return False, f"leaf count {len(pa)} != {len(pb)}"
    for n, ((path_x, x), (_, y)) in enumerate(zip(pa, pb)):
        why = leaf_mismatch(x, y, values_only=values_only)
        if why is not None:
            return False, (f"first divergent leaf: "
                           f"{_label(path_x, n, names)} — {why}")
    return True, ""
