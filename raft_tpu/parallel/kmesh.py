"""Data-parallel driver for the Pallas fused-chunk kernel: the wire
form sharded over the 1-D group mesh (DESIGN.md §9).

Raft groups never talk to each other, so multi-chip for the kernel is
the same story `mesh.run_sharded` tells for the XLA path: shard the
groups axis, run the UNCHANGED single-chip program per device, reduce
metrics at the boundary. Here the shard is of the kernel's wire form —
every leaf carries the folded group axis at dim -2 ([..., GS, LANE]),
so one PartitionSpec rule (`kleaf_spec`) shards all of them — and the
per-device program is the same `pallas_call` grid `kstep` launches,
over the device's own blocks. A chunk launch is communication-free:
no collective appears anywhere inside `kstep_sharded`, so ticks/s
scales with devices until per-chip HBM, not ICI, is the wall.

Layout contract: `kinit(..., pad_to=n_devices * GB)` pads the group
axis so each device holds whole 1024-group blocks; pad groups carry
global group ids past `g` (their seed streams are junk but harmless —
groups are independent and `kfinish` slices them off) and their metric
lanes are masked by group id in `kglobal_sharded`'s psum. State
correctness under sharding rides on `State.group_id` traveling with the
shard, exactly like the XLA path (sim/state.py).

The psum'd boundary (`kglobal_sharded`) exists for drivers that want
global verdict counters without gathering per-group arrays — the
dryrun and the multichip sweep. Differential gates keep using
`kfinish`/`kflight` on the (global, sharded) leaves: outside the
shard_map those are ordinary global arrays, so the full-pytree
comparators work unchanged, and `tests/test_kmesh.py` pins the
8-way-sharded kernel bit-identical to the unsharded kernel and the
XLA path on a faulted universe.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.config import RaftConfig
from raft_tpu.obs.recorder import Flight
from raft_tpu.parallel.mesh import AXIS, _shard_map
from raft_tpu.sim import pkernel
from raft_tpu.sim.run import HIST_SIZE, Metrics
from raft_tpu.sim.state import I32, State


def faulted_64_cfg(**overrides) -> RaftConfig:
    """THE shared sharded-differential universe: 64 faulted k=3/L=8
    groups (crash + partition + drop). tests/test_kmesh.py, the
    dryrun's `dryrun_pallas_mesh` segment, and multichip_sweep's
    CPU dryrun cells + interpret gate all simulate exactly this config
    so ONE interpret-mode kernel compile (minutes on the CPU box)
    serves every driver — defined once here so a drift in any driver
    cannot silently turn the others back into cold compiles.
    `overrides` layers dials on top of the pinned universe — the r19
    narrow tests pass `narrow_scalars=True, ...`, which is free here:
    the narrow dials re-declare RESIDENT dtypes only, the kernel wire
    and compiled program are dial-invariant, so the shared interpret
    compile still serves every variant."""
    import dataclasses
    cfg = RaftConfig(n_groups=64, k=3, seed=23, drop_prob=0.05,
                     crash_prob=0.2, crash_epoch=16,
                     partition_prob=0.2, partition_epoch=16,
                     log_cap=8, compact_every=4)
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def kleaf_spec(a) -> P:
    """PartitionSpec sharding a wire leaf's folded GS axis (dim -2 of
    every leaf — [K, GS, 128], [K, L, GS, 128], [H, GS, 128], ...)."""
    return P(*([None] * (a.ndim - 2) + [AXIS, None]))


def shard_kleaves(leaves, mesh: Mesh):
    """Place a wire tuple onto `mesh`, GS axis sharded. The leaves must
    have come from `kinit(..., pad_to=mesh.size * GB)` so each device
    shard is a whole number of kernel blocks."""
    return tuple(jax.device_put(a, NamedSharding(mesh, kleaf_spec(a)))
                 for a in leaves)


def kinit_sharded(cfg: RaftConfig, st: State, mesh: Mesh,
                  metrics: Metrics | None = None,
                  flight: Flight | None = None):
    """`pkernel.kinit` padded for and placed onto `mesh`. Same
    (leaves, g) contract; call once around a chunk loop."""
    leaves, g = pkernel.kinit(cfg, st, metrics, flight,
                              pad_to=mesh.size * pkernel.GB)
    return shard_kleaves(leaves, mesh), g


def _kstep_sharded_impl(cfg, mesh, t0, leaves, n_ticks, interpret):
    specs = tuple(kleaf_spec(a) for a in leaves)

    def local(t0s, *lvs):
        return pkernel._prun_padded_impl(cfg, tuple(lvs), t0s, n_ticks,
                                         interpret=interpret)

    f = _shard_map(local, mesh=mesh, in_specs=(P(),) + specs,
                   out_specs=specs)
    return f(t0, *leaves)


_STEP_STATICS = ("cfg", "n_ticks", "mesh", "interpret")
_kstep_sharded = jax.jit(_kstep_sharded_impl,
                         static_argnames=_STEP_STATICS)
# Donating twin for cfg.alias_wire (DESIGN.md §13): the wire operands'
# buffers are released to the sharded launch — together with the
# pallas_call's input_output_aliases inside, one wire copy is resident
# per device instead of in+out. Same consumed-operand contract as
# pkernel.kstep.
_kstep_sharded_donate = jax.jit(_kstep_sharded_impl,
                                static_argnames=_STEP_STATICS,
                                donate_argnums=(3,))


def kstep_sharded(cfg: RaftConfig, leaves, t0: int, n_ticks: int,
                  mesh: Mesh, interpret: bool = False):
    """`pkernel.kstep` with the launch shard_map'd over `mesh`: each
    device runs the kernel grid over its own blocks, no collectives.
    `t0` stays traced, so chunked calls at advancing t0 reuse ONE
    compiled sharded program — the property the bench's timed region
    depends on. Under `cfg.alias_wire` (compiled path) the input
    leaves are donated — stale after the call, the way every chunk
    loop already treats them."""
    fn = _kstep_sharded_donate if (cfg.alias_wire and not interpret) \
        else _kstep_sharded
    return tuple(fn(cfg, mesh, jnp.asarray(int(t0), I32),
                    tuple(leaves), int(n_ticks), bool(interpret)))


class GlobalKMetrics(NamedTuple):
    """Mesh-reduced verdict counters off the kernel wire — the sharded
    kernel's analogue of mesh.GlobalMetrics. i32 on-device (x64 is
    off); for promoted throughput numbers use the int64 host-side
    counters (`pkernel.kcommitted`) instead."""
    rounds: jnp.ndarray      # i32 — committed entries, psum over mesh
    elections: jnp.ndarray   # i32 — completed elections, psum
    hist: jnp.ndarray        # i32[H] — election-latency histogram, psum
    max_latency: jnp.ndarray  # i32 — longest completed streak, pmax
    unsafe: jnp.ndarray      # i32 — groups whose per-tick safety bit
    # dropped (psum); 0 = the whole sharded run was a clean soak


@functools.partial(jax.jit, static_argnames=("g", "mesh", "with_hist"))
def _kglobal_sharded(mesh, g, with_hist, gid, mc, me, mx, ms, mh=None):
    operands = (gid, mc, me, mx, ms) + ((mh,) if with_hist else ())
    specs = tuple(kleaf_spec(a) for a in operands)

    def local(gid, mc, me, mx, ms, mh=None):
        real = gid < g

        def tot(a):
            return jax.lax.psum(jnp.sum(jnp.where(real, a, 0)), AXIS)

        return GlobalKMetrics(
            rounds=tot(mc),
            elections=tot(me),
            # Under the wire_hist dial no [H] rows exist on the wire —
            # the reduced histogram is the same all-zeros row khist
            # would be summing (a ceiling run trades percentiles away;
            # the scalar counters stay exact).
            hist=(jax.lax.psum(
                jnp.sum(jnp.where(real[None], mh, 0), axis=(1, 2)), AXIS)
                if with_hist else
                jax.lax.psum(jnp.zeros((HIST_SIZE,), I32), AXIS)),
            max_latency=jax.lax.pmax(
                jnp.max(jnp.where(real, mx, 0)), AXIS),
            unsafe=tot(1 - ms),
        )

    f = _shard_map(local, mesh=mesh, in_specs=specs,
                   out_specs=GlobalKMetrics(P(), P(), P(), P(), P()))
    return f(*operands)


def kglobal_sharded(cfg: RaftConfig, leaves, g: int, mesh: Mesh
                    ) -> GlobalKMetrics:
    """Reduce the wire's metric tail with psum/pmax at the mesh
    boundary — group state never leaves its device; five scalars and
    one [H] row do. Pad groups (group id >= g) are masked out on-device
    before the reduction, so the counters equal the host-side
    `kcommitted`/`kelections`/`khist` values exactly (i32 adds
    reassociate). Module-level jit (like `_kstep_sharded`): repeated
    calls at one (g, mesh, shape) reuse a single compiled reduction.
    Follows the cfg layout dials: with `wire_hist` off the histogram
    row comes back all-zeros (nothing was tracked)."""
    gid = leaves[pkernel._n_state_leaves(cfg) - 1]
    tail = [pkernel._mleaf(cfg, leaves, n)
            for n in ("committed", "elections", "max_latency", "safety")]
    if cfg.wire_hist:
        tail.append(pkernel._mleaf(cfg, leaves, "hist"))
    return _kglobal_sharded(mesh, int(g), bool(cfg.wire_hist), gid, *tail)


def prun_sharded(cfg: RaftConfig, st: State, n_ticks: int, mesh: Mesh,
                 t0: int = 0, metrics: Metrics | None = None,
                 interpret: bool = False, flight: Flight | None = None):
    """Drop-in for `pkernel.prun` with the groups axis data-parallel
    over `mesh`: same (State, Metrics[, Flight]) out, same bits —
    sharding must be invisible in every leaf. Raises ValueError when
    the shape is unsupported for this device count (per-device VMEM or
    HBM budget)."""
    g = st.alive_prev.shape[0]
    wf = flight is not None
    # r19 host boundary: a latched narrow state must refuse here, not
    # compute garbage for n_ticks and refuse at kfinish.
    from raft_tpu.sim import state as state_mod
    state_mod.check_narrow_overflow(cfg, st)
    if not pkernel.supported(cfg, n_groups=g, n_devices=mesh.size,
                             with_flight=wf):
        raise ValueError(
            f"pkernel: shape unsupported on {mesh.size} device(s) "
            f"(k > 30, VMEM footprint {pkernel.kernel_vmem_bytes(cfg)} B "
            f"> {pkernel.VMEM_LIMIT_BYTES} B, or per-device HBM "
            f"{pkernel.hbm_bytes(cfg, g, mesh.size, with_flight=wf)} B "
            f"> {pkernel.HBM_LIMIT_BYTES} B) — use the XLA path")
    leaves, g = kinit_sharded(cfg, st, mesh, metrics, flight)
    # Same chunk-boundary span as pkernel.prun, on the sharded engine's
    # lane (no-op without a tracer installed).
    from raft_tpu.obs import trace as _trace
    with _trace.chunk_span(f"pallas-sharded-{mesh.size}dev", int(t0),
                           int(n_ticks), interpret=bool(interpret)):
        leaves = kstep_sharded(cfg, leaves, t0, n_ticks, mesh,
                               interpret=interpret)
    if flight is None:
        return pkernel.kfinish(cfg, leaves, g, metrics)
    st2, met = pkernel.kfinish(cfg, leaves, g, metrics)
    return st2, met, pkernel.kflight(cfg, leaves, g)
