"""Shard-aware cohort window scheduler: the copy path of the r17
sharded streaming pipeline (DESIGN.md §16).

`parallel/cohort.py` owns the pipeline's control flow (which window is
resident, when to prefetch, when to drain); this module owns how one
window's bytes actually cross the host<->device boundary when the
window is SPLIT over the r08 device mesh:

- `window_sharding`/`device_slices`: the placement rule. Every wire
  leaf carries the folded group axis at dim -2, so `kmesh.kleaf_spec`
  is the one PartitionSpec for streamed windows too, and the
  per-device index map is asked FROM the sharding
  (`addressable_devices_indices_map`) rather than re-derived — a mesh
  ordering the slicer assumed but the sharding disagreed with would
  scatter blocks to the wrong devices silently.
- `StagingPool` + `put_window`: the h2d commit point. The naive path
  (`staged=False`) hands `jax.device_put` one strided host view per
  leaf and lets jax allocate + linearize a transfer buffer per window
  — allocator churn on every prefetch. The staged path copies the
  window into REUSABLE preallocated contiguous buffers (two
  parity-alternated slots, the double-buffered pipeline's depth), then
  issues one per-device `jax.device_put(slice, device)` per leaf —
  all N dispatches in flight before any is awaited, so the N h2d
  streams never serialize — and commits them as ONE global sharded
  array via `jax.make_array_from_single_device_arrays` (the
  `dma_start`-style commit: the assembled array is a handle over
  transfers already in flight, not a barrier). `staging_ablation`
  measures the two paths against each other; DESIGN.md §16 records the
  protocol and the driver's TPU column.
- `drain_window`: the d2h twin. One `np.asarray` per addressable
  shard, written straight into the host store at the shard's own
  index (offset into the window) — per-device drains, each blocking
  only on its own device's launches, with per-device wall captured so
  a soak can name the slow device (`stats`/heartbeat lanes in
  cohort.stream_ticks_sharded).

Slot-reuse safety: `put_window` for window i+1 reuses the parity slot
window i-1 staged into. By then window i-1's `device_put`s have long
returned (jax copies the host buffer into its transfer staging before
returning) AND window i-1's launches were synced by the pipeline
(`jax.block_until_ready` at the end of its residency), so no transfer
still reads the slot. Depth-2 is exactly the pipeline's lookahead; a
deeper prefetch would need more slots.
"""

from __future__ import annotations

import time

import numpy as np

from raft_tpu.sim.pkernel import GB, SUB


def window_sharding(mesh, leaf):
    """The NamedSharding a streamed window leaf pages in under: the
    r08 `kleaf_spec` rule (folded GS axis at dim -2) on `mesh` — the
    SAME sharding `kmesh.kstep_sharded`'s shard_map uses, so a paged-in
    window launches with zero resharding."""
    from jax.sharding import NamedSharding

    from raft_tpu.parallel.kmesh import kleaf_spec
    return NamedSharding(mesh, kleaf_spec(leaf))


def device_slices(mesh, leaf, s0: int, s1: int):
    """[(device, (lo, hi)), ...] — each device's sublane range of the
    window [s0, s1), in the sharding's own addressable-device order,
    RELATIVE to the window (add s0 for host-store coordinates). Asks
    the sharding for its index map instead of assuming one; every
    slice must be whole 1024-group blocks (contracts.streaming_problems
    audits this via the public seam)."""
    shape = leaf.shape[:-2] + (s1 - s0,) + leaf.shape[-1:]
    sharding = window_sharding(mesh, leaf)
    out = []
    for dev, idx in sharding.addressable_devices_indices_map(shape).items():
        lo, hi, _ = idx[-2].indices(s1 - s0)
        out.append((dev, (lo, hi)))
    return out


def wire_word_problems(host_leaves) -> list[str]:
    """Leaves of the paged wire that are NOT 4-byte words. The narrow
    dials (config.NARROW_FIELDS, r19) re-declare RESIDENT dtypes only;
    the wire the scheduler stages, pages, and budgets is i32/u32 words
    by contract — the staging-pool slot arithmetic, `device_slices`'s
    whole-block math and the hazard prover's window-byte model all
    assume it. A narrow dtype leaking onto the host wire means kinit
    skipped a widen; refuse loudly here instead of paging a corrupted
    window."""
    return [f"wire leaf #{i} is {a.dtype}, not a 4-byte word lane"
            for i, a in enumerate(host_leaves)
            if np.dtype(a.dtype).itemsize != 4]


class StagingPool:
    """Reusable preallocated contiguous host staging buffers for the
    h2d path: one buffer per wire leaf per parity slot, sized for the
    FULL window shape (tail windows use a leading view). Kills the
    per-window allocate-and-linearize cost of the naive `device_put`
    path; see the module docstring for the depth-2 reuse argument."""

    SLOTS = 2

    def __init__(self, host_leaves, window_sublanes: int):
        bad = wire_word_problems(host_leaves)
        if bad:
            raise ValueError("stream_sched: narrow dtype on the paged "
                             "wire — " + "; ".join(bad))
        self._bufs = [
            tuple(np.empty(a.shape[:-2] + (window_sublanes,)
                           + a.shape[-1:], a.dtype)
                  for a in host_leaves)
            for _ in range(self.SLOTS)]

    def stage(self, host_leaves, s0: int, s1: int, slot: int):
        """Copy the window [s0, s1) into parity slot `slot % SLOTS`;
        returns contiguous views (the transfer sources)."""
        views = []
        for host, buf in zip(host_leaves, self._bufs[slot % self.SLOTS]):
            dst = buf[..., : s1 - s0, :]
            np.copyto(dst, host[..., s0:s1, :])
            views.append(dst)
        return tuple(views)


def put_window(host_leaves, s0: int, s1: int, mesh, pool=None,
               slot: int = 0, per_device=None):
    """h2d of one cohort window onto `mesh`, every leaf sharded by the
    kleaf rule. With `pool` (a StagingPool) the staged commit path runs
    — per-device `device_put`s off the contiguous slot, assembled with
    `make_array_from_single_device_arrays`; without, the naive path
    (one sharded `device_put` per strided leaf view). Both return the
    same tuple of global sharded arrays; both only DISPATCH (nothing
    here blocks on the transfer). `per_device`, when a dict,
    accumulates per-device h2d dispatch seconds keyed by device id."""
    import jax

    if pool is None:
        return tuple(
            jax.device_put(np.ascontiguousarray(leaf[..., s0:s1, :]),
                           window_sharding(mesh, leaf))
            for leaf in host_leaves)
    staged = pool.stage(host_leaves, s0, s1, slot)
    out = []
    for leaf, src in zip(host_leaves, staged):
        sharding = window_sharding(mesh, leaf)
        shape = src.shape
        shards = []
        for dev, idx in sharding.addressable_devices_indices_map(
                shape).items():
            tic = time.perf_counter()
            shards.append(jax.device_put(src[idx], dev))
            if per_device is not None:
                key = getattr(dev, "id", dev)
                per_device[key] = (per_device.get(key, 0.0)
                                   + time.perf_counter() - tic)
        out.append(jax.make_array_from_single_device_arrays(
            shape, sharding, shards))
    return tuple(out)


def drain_window(host_leaves, window_leaves, s0: int, s1: int,
                 per_device=None):
    """d2h of one evolved sharded window back into the host store:
    one `np.asarray` per addressable shard, each blocking only on its
    OWN device's launches + transfer, written at the shard's index
    offset by `s0`. `per_device`, when a dict, accumulates per-device
    drain seconds keyed by device id — the slow-device instrument."""
    for host, dev_leaf in zip(host_leaves, window_leaves):
        shards = getattr(dev_leaf, "addressable_shards", None)
        if not shards:   # unsharded (1-device) window: plain writeback
            host[..., s0:s1, :] = np.asarray(dev_leaf)
            continue
        for shard in shards:
            lo, hi, _ = shard.index[-2].indices(s1 - s0)
            tic = time.perf_counter()
            host[..., s0 + lo:s0 + hi, :] = np.asarray(shard.data)
            if per_device is not None:
                key = getattr(shard.device, "id", shard.device)
                per_device[key] = (per_device.get(key, 0.0)
                                   + time.perf_counter() - tic)


def staging_ablation(cfg, mesh, n_windows: int = 4,
                     repeats: int = 3) -> dict:
    """Measure the staged commit path against the naive `device_put`
    loop (DESIGN.md §16's copy-path measurement protocol): page
    `n_windows` full cohort windows h2d through each path, block until
    delivered, take the best of `repeats` passes. Pure copy-path
    probe — no kernel launches, so it runs anywhere the mesh exists
    (virtual CPU devices included; only the TPU column is a bandwidth
    claim). Returns wall seconds + MiB/s per path and the ratio."""
    import jax

    from raft_tpu import sim
    from raft_tpu.parallel import cohort
    from raft_tpu.sim import pkernel

    nd = mesh.size
    bpd = pkernel.stream_blocks_per_device(cfg, nd)
    win = bpd * nd * SUB
    g = min(n_windows, 4) * bpd * nd * GB
    host, _ = cohort.host_wire(cfg, sim.init(cfg, n_groups=g),
                               pad_to=nd * GB)
    wins = [(s0, min(s0 + win, host[0].shape[-2]))
            for s0 in range(0, host[0].shape[-2], win)]
    window_bytes = sum(a.dtype.itemsize * a[..., :win, :].size
                       for a in host)
    pool = StagingPool(host, win)
    walls = {}
    for label, use_pool in (("staged", True), ("naive", False)):
        best = None
        for _ in range(repeats):
            tic = time.perf_counter()
            for i, (s0, s1) in enumerate(wins):
                dev = put_window(host, s0, s1, mesh,
                                 pool=pool if use_pool else None, slot=i)
                jax.block_until_ready(dev)
            wall = time.perf_counter() - tic
            best = wall if best is None else min(best, wall)
        walls[label] = best
    moved = len(wins) * window_bytes
    return {
        "n_devices": nd, "windows": len(wins),
        "window_bytes": window_bytes,
        "staged_wall_s": round(walls["staged"], 6),
        "naive_wall_s": round(walls["naive"], 6),
        "staged_mib_s": round(moved / walls["staged"] / 2**20, 1),
        "naive_mib_s": round(moved / walls["naive"] / 2**20, 1),
        "staged_over_naive": round(walls["naive"] / walls["staged"], 3),
    }
