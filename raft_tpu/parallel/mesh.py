"""1-D mesh over the groups axis: shard_map + psum'd metrics.

Groups are embarrassingly parallel (no cross-group messages), so the G
axis shards over a 1-D `jax.sharding.Mesh` and the ONLY cross-device
traffic is the psum of metric aggregates at the end of a run — riding
ICI on a real slice, DCN across hosts (SURVEY.md §5: config 5's
"sharded over ICI" is data-parallel group sharding, not intra-group RPC).

Correct sharding depends on `State.group_id` traveling with the shard:
each device simulates its own global group indices' seed streams (see
sim/state.py). `tests/test_parallel.py` pins bit-identity between an
8-device sharded run and the unsharded reference.
"""

from __future__ import annotations

import sys
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.config import RaftConfig
from raft_tpu.sim.run import metrics_init, run
from raft_tpu.sim.state import State

AXIS = "g"


def _pvary(x, axis):
    """Mark `x` as varying over `axis` (API name moved across jax
    versions: prefer the current `pcast`; `pvary` is the deprecated
    spelling). On jax builds with NEITHER (0.4.x), `_shard_map` below
    disables the replication checker entirely (check_rep=False — the
    varying/replicated distinction does not exist yet), so marking is
    unnecessary and this is the identity."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, (axis,), to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, (axis,))
    return x


def _shard_map(f, mesh, in_specs, out_specs):
    """`jax.shard_map` for every jax this repo meets: top-level on
    current jax, `jax.experimental.shard_map` on 0.4.x — where
    check_rep must be False (its replication checker predates
    pcast/pvary and rejects the metrics carry `run_sharded` marks
    varying by hand; pallas_call under shard_map also requires it)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def make_mesh(n_devices: int | None = None, devices=None,
              allow_cpu_fallback: bool = False) -> Mesh:
    """1-D mesh over the first `n_devices` of `devices`.

    When the default platform has too few devices (the TPU plugin in
    this image exposes a single chip), the caller must OPT IN to the
    virtual-CPU fallback with `allow_cpu_fallback=True` — silently
    swapping platforms would let a benchmark measure the wrong hardware.
    Without the flag, asking for more devices than exist raises."""
    if devices is None:
        devices = jax.devices()
        if (n_devices is not None and len(devices) < n_devices
                and allow_cpu_fallback):
            print(f"make_mesh: default platform has {len(devices)} "
                  f"device(s) < {n_devices}; falling back to the virtual "
                  f"CPU platform", file=sys.stderr)
            devices = jax.devices("cpu")
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)} "
                f"(pass allow_cpu_fallback=True for the CPU test vehicle)")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (AXIS,))


def state_sharding(mesh: Mesh) -> NamedSharding:
    """Every State leaf shards its leading (G) axis; the rest replicate."""
    return NamedSharding(mesh, P(AXIS))


def shard_state(st: State, mesh: Mesh) -> State:
    return jax.device_put(st, state_sharding(mesh))


class GlobalMetrics(NamedTuple):
    rounds: jnp.ndarray      # i32 — total committed entries, psum over mesh
    elections: jnp.ndarray   # i32 — completed leader acquisitions, psum
    hist: jnp.ndarray        # i32[H] — election-latency histogram, psum
    max_latency: jnp.ndarray  # i32 — longest completed streak, pmax
    unsafe: jnp.ndarray      # i32 — groups whose per-tick safety bit
    # dropped during the run (run.Metrics.safety), psum; 0 = clean soak


def run_sharded(cfg: RaftConfig, st: State, n_ticks: int, mesh: Mesh,
                t0: int = 0):
    """Run `n_ticks` with the G axis sharded over `mesh`.

    Returns (state, GlobalMetrics): state stays sharded (leading axis
    over the mesh); metrics are psum-reduced and replicated.
    """

    def local(st_local):
        # The zero-valued initial metrics are constants inside the shard —
        # unvarying over the mesh axis — while the updated metrics coming
        # out of the scan body vary per shard; mark them varying up front
        # or the scan carry types mismatch under shard_map.
        m0 = jax.tree.map(lambda a: _pvary(a, AXIS),
                          metrics_init(st_local.alive_prev.shape[0],
                                       clients=st_local.clients is not None))
        s, m = run(cfg, st_local, n_ticks, t0, m0)
        return s, GlobalMetrics(
            rounds=jax.lax.psum(jnp.sum(m.committed), AXIS),
            elections=jax.lax.psum(m.elections, AXIS),
            hist=jax.lax.psum(m.hist, AXIS),
            max_latency=jax.lax.pmax(m.max_latency, AXIS),
            unsafe=jax.lax.psum(jnp.sum(1 - m.safety), AXIS),
        )

    f = _shard_map(local, mesh=mesh, in_specs=(P(AXIS),),
                   out_specs=(P(AXIS), P()))
    return jax.jit(f)(st)
