"""Cohort paging: stream 1024-group blocks host<->HBM under the
unchanged fused-chunk kernel (DESIGN.md §15).

DESIGN.md §9 proved the single-chip group ceiling is an artifact of
whole-fleet HBM residency, not of the protocol: the kernel's grid cuts
independent SUB-sublane slices with zero collectives per chunk, so no
block ever needs another block resident. This module exploits exactly
that property. The full fleet's wire form (pkernel.kinit's leaves)
lives in host RAM as per-block numpy arrays; a double-buffered pipeline
pages `cfg.cohort_blocks`-block windows through HBM:

      host RAM  [b0 b1 b2 b3 b4 b5 ...]          one wire copy of G
                    |        ^
              h2d copy of    |  d2h copy of
              window i+1     |  window i-1
                    v        |
      HBM       [ prev | current | next ]        O(cohort_blocks)
                          |
                  unchanged pallas_call(s)       chunk ticks each

While the kernel runs window i, the host->HBM copy of window i+1 and
the HBM->host copy of window i-1 are in flight (JAX async dispatch:
`jax.device_put` and the launches return immediately; only the
`np.asarray` readback blocks). HBM holds at most `_stream_windows(cfg)`
windows instead of the whole fleet, so the group ceiling becomes
host-RAM-bound (`pkernel.streamed_ceiling_groups`, $RAFT_TPU_HOST_RAM_
BYTES) instead of HBM-bound.

Bit-identity is free by construction: paging happens only at chunk
boundaries — where `_pack_wire`/`_unpack_wire` already run — and every
window's launch is the same `pallas_call` over the same folded
[..., GS, LANE] leaves (`group_id` rides the wire, so the seed streams
of a block are identical wherever it is resident). The fori-loop and
every bit-identity gate stay layout-blind; `prun_streamed` is pinned
bit-identical to `pkernel.prun` AND the XLA path by
tests/test_streaming.py and the multichip sweep's three-way gate.

Gated behind `cfg.stream_groups` / `cfg.cohort_blocks`
(config.STREAM_FIELDS — residency-class knobs, default off, excluded
from the checkpoint semantic match like LAYOUT_FIELDS).

r17 composes this pipeline with the r08 device mesh (DESIGN.md §16):
`prun_streamed_sharded` keeps the ONE writable host wire but splits
every window into whole per-device block slices (`stream_sched` owns
the copy path — staged per-device `device_put`s committed as one
sharded array), launches `kmesh.kstep_sharded` instead of
`pkernel.kstep`, and drains per-shard — all N devices page, compute,
and drain concurrently, so the modeled ceiling becomes
`pkernel.streamed_ceiling_groups(cfg, n_devices)` = N x the per-device
host-RAM bound, and copy bandwidth scales with the N independent
host<->device links.
"""

from __future__ import annotations

import contextlib
import time

import numpy as np

from raft_tpu.config import RaftConfig
from raft_tpu.obs.recorder import Flight
from raft_tpu.sim import pkernel
from raft_tpu.sim.pkernel import GB, LANE, SUB
from raft_tpu.sim.run import Metrics
from raft_tpu.sim.state import State

# Engine string of the streamed runner. obs.roofline.engine_class
# prefix-matches "pallas" (same residency byte model per launch); the
# sweeps' verdict columns and chunk spans carry it verbatim.
ENGINE = "pallas-streamed"


def _host_device():
    """The host CPU jax device, or None when no CPU backend exists —
    kinit/kfinish (the one-time whole-fleet conversions) are pinned to
    it so the full wire never materializes in HBM even on a TPU box."""
    import jax
    try:
        return jax.local_devices(backend="cpu")[0]
    except Exception:
        return None


def _on_host():
    import jax
    dev = _host_device()
    return jax.default_device(dev) if dev is not None \
        else contextlib.nullcontext()


def host_wire(cfg: RaftConfig, st: State, metrics: Metrics | None = None,
              flight: Flight | None = None, pad_to: int | None = None):
    """(host_leaves, g): the fleet's full wire form as HOST numpy
    arrays — `pkernel.kinit` run on the host backend, each leaf pulled
    out of jax. This is the pinned store the pipeline pages from; it is
    mutated in place by `stream_ticks`. `pad_to` passes through to
    kinit — the sharded pipeline pads to `mesh.size * GB` so the total
    block count divides the mesh and EVERY window (the tail included)
    splits into whole equal per-device block slices."""
    with _on_host():
        leaves, g = pkernel.kinit(cfg, st, metrics, flight,
                                  pad_to=pad_to or GB)
    # np.array, not np.asarray: jax buffers surface as READ-ONLY views
    # and the store must accept _writeback's in-place window drains.
    return [np.array(leaf) for leaf in leaves], g


def cohort_windows(cfg: RaftConfig, host_leaves,
                   n_devices: int = 1) -> list:
    """[(s0, s1), ...] sublane windows of the folded group axis
    (dim -2, the axis `kleaf_spec` shards): `cohort_blocks` whole
    SUB-sublane blocks each, the last window taking the remainder. At
    `n_devices > 1` the step is the GLOBAL sharded window —
    `stream_blocks_per_device(cfg, N) * N` blocks, so each device's
    slice of every window is whole blocks — and the leaves must carry
    a multiple of N*SUB sublanes (host_wire `pad_to=N*GB`), which
    keeps the tail window equally divisible too."""
    gs = host_leaves[0].shape[-2]
    if gs % SUB:
        raise ValueError(f"wire leaves carry {gs} sublanes — not whole "
                         f"{SUB}-sublane blocks; host_wire pads to {GB}")
    if gs % (n_devices * SUB):
        raise ValueError(
            f"wire leaves carry {gs} sublanes — not divisible into "
            f"whole blocks over {n_devices} devices; host_wire with "
            f"pad_to={n_devices}*{GB} makes every window slice whole")
    step = (pkernel.stream_blocks_per_device(cfg, n_devices)
            * n_devices * SUB)
    return [(s0, min(s0 + step, gs)) for s0 in range(0, gs, step)]


def _window(host_leaves, s0: int, s1: int):
    """Device-put one cohort window (h2d of every leaf's [s0:s1)
    sublanes — async dispatch; nothing blocks here)."""
    import jax
    return tuple(jax.device_put(np.ascontiguousarray(
        leaf[..., s0:s1, :])) for leaf in host_leaves)


def stream_ticks(cfg: RaftConfig, host_leaves, g: int, t0: int,
                 n_ticks: int, interpret: bool = False,
                 chunk_ticks: int | None = None,
                 stats: dict | None = None):
    """Advance the WHOLE host-resident fleet by `n_ticks` ticks (from
    absolute tick `t0`), paging one cohort window at a time: window i+1
    is prefetched (h2d) and window i-1 drained (d2h) while window i's
    launches run — the double-buffered pipeline of DESIGN.md §15.
    Mutates `host_leaves` in place and returns it.

    Each window runs ceil(n_ticks / chunk_ticks) launches of the
    unchanged `pkernel.kstep` (one compiled program reused across
    windows — every window is the same leaf shapes except possibly a
    smaller last one). `chunk_ticks=None` means one launch per window.
    With a tracer installed every launch leaves one span on the
    "pallas-streamed" lane (cohort + block window attached), and the
    soak heartbeat snapshots the streamed wire lanes after each
    window's last launch (obs.trace.heartbeat_wire), so a 10M-group
    soak is observable mid-flight.

    `stats`, when passed, accumulates the measured pipeline split:
    h2d_s / compute_s / d2h_s / wall_s / launches / cohorts and
    `overlap_efficiency_measured` = compute_s / wall_s (1.0 == copies
    fully hidden behind compute; obs.roofline.overlap_efficiency is the
    predicted twin)."""
    import jax

    from raft_tpu.obs import trace as obs_trace

    if n_ticks <= 0:
        return host_leaves
    chunk = chunk_ticks or n_ticks
    wins = cohort_windows(cfg, host_leaves)
    t_h2d = t_compute = t_d2h = 0.0
    launches = 0
    wall0 = time.perf_counter()
    tic = time.perf_counter()
    nxt = _window(host_leaves, *wins[0])
    t_h2d += time.perf_counter() - tic
    pending = None   # (evolved_leaves, s0, s1) of window i-1, d2h owed
    for ci, (s0, s1) in enumerate(wins):
        cur = nxt
        if ci + 1 < len(wins):
            tic = time.perf_counter()
            nxt = _window(host_leaves, *wins[ci + 1])   # prefetch i+1
            t_h2d += time.perf_counter() - tic
        g_win = min(g - s0 * LANE, (s1 - s0) * LANE)
        at = t0
        while at < t0 + n_ticks:
            n = min(chunk, t0 + n_ticks - at)
            with obs_trace.chunk_span(ENGINE, at, n, cohort=ci,
                                      blocks=(s1 - s0) // SUB,
                                      interpret=bool(interpret)):
                cur = pkernel.kstep(cfg, cur, at, n, interpret=interpret)
            launches += 1
            at += n
        obs_trace.heartbeat_wire(f"{ENGINE}:c{ci}", t0 + n_ticks, cfg,
                                 cur, g_win)
        if pending is not None:
            tic = time.perf_counter()
            _writeback(host_leaves, *pending)   # d2h of i-1 overlaps i
            t_d2h += time.perf_counter() - tic
        tic = time.perf_counter()
        jax.block_until_ready(cur)
        t_compute += time.perf_counter() - tic
        pending = (cur, s0, s1)
    tic = time.perf_counter()
    _writeback(host_leaves, *pending)
    t_d2h += time.perf_counter() - tic
    wall = time.perf_counter() - wall0
    if stats is not None:
        stats["cohorts"] = stats.get("cohorts", 0) + len(wins)
        stats["launches"] = stats.get("launches", 0) + launches
        stats["h2d_s"] = stats.get("h2d_s", 0.0) + t_h2d
        stats["compute_s"] = stats.get("compute_s", 0.0) + t_compute
        stats["d2h_s"] = stats.get("d2h_s", 0.0) + t_d2h
        stats["wall_s"] = stats.get("wall_s", 0.0) + wall
        stats["overlap_efficiency_measured"] = (
            stats["compute_s"] / stats["wall_s"] if stats["wall_s"] > 0
            else None)
    return host_leaves


def _writeback(host_leaves, window_leaves, s0: int, s1: int):
    """d2h: drain one evolved window back into the host store (the
    np.asarray blocks on the window's launches + transfer)."""
    for host, dev in zip(host_leaves, window_leaves):
        host[..., s0:s1, :] = np.asarray(dev)


def prun_streamed(cfg: RaftConfig, st: State, n_ticks: int, t0: int = 0,
                  metrics: Metrics | None = None, interpret: bool = False,
                  flight: Flight | None = None,
                  chunk_ticks: int | None = None,
                  stats: dict | None = None):
    """Drop-in for `pkernel.prun` / `kmesh.prun_sharded` on streamed
    configs: same (State, Metrics[, Flight]) out, same bits — the
    cohort pipeline between the same kinit/kfinish conversions. Raises
    ValueError on unsupported shapes (supported() under
    cfg.stream_groups budgets host RAM for G and HBM only for the
    cohort window). Pass `stats` (a dict) to receive the measured
    pipeline split, `chunk_ticks` to split each window's residency
    into multiple launches (bench cadence)."""
    g = st.alive_prev.shape[0]
    wf = flight is not None
    # r19 host boundary: refuse a latched narrow state before paging
    # (the sticky latch would ride the whole stream otherwise).
    from raft_tpu.sim import state as state_mod
    state_mod.check_narrow_overflow(cfg, st)
    scfg = cfg if cfg.stream_groups else None
    if scfg is None:
        import dataclasses
        scfg = dataclasses.replace(cfg, stream_groups=True)
    if not pkernel.supported(scfg, n_groups=g, with_flight=wf):
        raise ValueError(
            "cohort: shape unsupported (k > 30, VMEM footprint "
            f"{pkernel.kernel_vmem_bytes(cfg)} B > "
            f"{pkernel.VMEM_LIMIT_BYTES} B, cohort window "
            f"{pkernel.cohort_hbm_bytes(cfg, wf)} B > "
            f"{pkernel.HBM_LIMIT_BYTES} B HBM, or host wire "
            f"{pkernel.host_bytes(cfg, g, wf)} B > "
            f"{pkernel.HOST_RAM_LIMIT_BYTES} B host RAM)")
    host_leaves, g = host_wire(cfg, st, metrics, flight)
    stream_ticks(cfg, host_leaves, g, t0, n_ticks, interpret=interpret,
                 chunk_ticks=chunk_ticks, stats=stats)
    with _on_host():
        leaves = tuple(map(np.asarray, host_leaves))
        if flight is None:
            return pkernel.kfinish(cfg, leaves, g, metrics)
        st2, met2 = pkernel.kfinish(cfg, leaves, g, metrics)
        return st2, met2, pkernel.kflight(cfg, leaves, g)


# ---------------------------------------------------- sharded pipeline


def sharded_engine(n_devices: int) -> str:
    """Engine string of the sharded streamed runner — prefix `ENGINE`
    plus the device count, so `obs.roofline.engine_class` classifies it
    "pallas" (same per-launch byte model) and history's regression gate
    compares like against like."""
    return f"{ENGINE}-sharded-{n_devices}dev"


def _heartbeat_sharded(eng: str, ci: int, tick_at: int, cfg: RaftConfig,
                       window_leaves, g: int, s0: int, s1: int):
    """Per-device heartbeat lanes (ISSUE r17 satellite): one beat_wire
    per mesh device off its OWN shards of the just-finished window,
    labeled `{eng}:c{ci}:d{device_id}` — so a multi-chip soak's
    heartbeat JSONL names the slow or unsafe device mid-flight. No-op
    without an installed heartbeat (the shard walk costs nothing
    then); NOTE the beat's readback syncs that device's launches, the
    standard beat_wire caveat."""
    from raft_tpu.obs import trace as obs_trace
    if obs_trace._HEARTBEAT is None:
        return
    per_leaves: dict = {}
    bounds: dict = {}
    for leaf in window_leaves:
        shards = getattr(leaf, "addressable_shards", None)
        if not shards:
            return
        for shard in shards:
            key = getattr(shard.device, "id", shard.device)
            per_leaves.setdefault(key, []).append(shard.data)
            bounds[key] = shard.index[-2].indices(s1 - s0)[:2]
    for key in sorted(per_leaves):
        lo, hi = bounds[key]
        g_dev = min(max(g - (s0 + lo) * LANE, 0), (hi - lo) * LANE)
        if g_dev > 0:
            obs_trace.heartbeat_wire(f"{eng}:c{ci}:d{key}", tick_at,
                                     cfg, tuple(per_leaves[key]), g_dev)


def stream_ticks_sharded(cfg: RaftConfig, host_leaves, g: int, t0: int,
                         n_ticks: int, mesh, interpret: bool = False,
                         chunk_ticks: int | None = None,
                         stats: dict | None = None,
                         staging: bool = True):
    """`stream_ticks` with every window SPLIT over `mesh`: the same
    double-buffered prefetch/launch/drain pipeline, but h2d goes
    through `stream_sched.put_window` (staged per-device device_puts
    committed as one kleaf-sharded array), the launch is
    `kmesh.kstep_sharded` (each device runs the unchanged kernel grid
    over its own blocks, zero collectives), and d2h drains per
    addressable shard — N h2d streams, N kernel programs, and N d2h
    streams in flight concurrently. Mutates `host_leaves` (which must
    come from `host_wire(..., pad_to=mesh.size*GB)`) in place.

    `staging=False` drops to the naive whole-window `device_put` path
    (the ablation baseline; `stream_sched.staging_ablation` measures
    the two against each other). `stats` additionally accumulates the
    per-device copy split: `per_device` rows (h2d_s/d2h_s/copy_s per
    device id), `slowest_device` (max copy_s — the device that owns
    the window wall), and `overlap_efficiency_per_device_measured`
    (compute_s / max(compute_s, that device's copy_s); the pipeline's
    overall measured efficiency is bounded by the minimum entry)."""
    import jax

    from raft_tpu.obs import trace as obs_trace
    from raft_tpu.parallel import stream_sched
    from raft_tpu.parallel.kmesh import kstep_sharded

    if n_ticks <= 0:
        return host_leaves
    nd = mesh.size
    eng = sharded_engine(nd)
    chunk = chunk_ticks or n_ticks
    wins = cohort_windows(cfg, host_leaves, n_devices=nd)
    pool = stream_sched.StagingPool(host_leaves, wins[0][1] - wins[0][0]) \
        if staging else None
    h2d_dev: dict = {}
    d2h_dev: dict = {}
    t_h2d = t_compute = t_d2h = 0.0
    launches = 0
    wall0 = time.perf_counter()
    tic = time.perf_counter()
    nxt = stream_sched.put_window(host_leaves, *wins[0], mesh, pool=pool,
                                  slot=0, per_device=h2d_dev)
    t_h2d += time.perf_counter() - tic
    pending = None   # (evolved_leaves, s0, s1) of window i-1, d2h owed
    for ci, (s0, s1) in enumerate(wins):
        cur = nxt
        if ci + 1 < len(wins):
            tic = time.perf_counter()
            nxt = stream_sched.put_window(host_leaves, *wins[ci + 1],
                                          mesh, pool=pool, slot=ci + 1,
                                          per_device=h2d_dev)
            t_h2d += time.perf_counter() - tic
        at = t0
        while at < t0 + n_ticks:
            n = min(chunk, t0 + n_ticks - at)
            with obs_trace.chunk_span(eng, at, n, cohort=ci,
                                      blocks=(s1 - s0) // SUB,
                                      devices=nd,
                                      interpret=bool(interpret)):
                cur = kstep_sharded(cfg, cur, at, n, mesh,
                                    interpret=interpret)
            launches += 1
            at += n
        _heartbeat_sharded(eng, ci, t0 + n_ticks, cfg, cur, g, s0, s1)
        if pending is not None:
            tic = time.perf_counter()
            stream_sched.drain_window(host_leaves, *pending,
                                      per_device=d2h_dev)
            t_d2h += time.perf_counter() - tic
        tic = time.perf_counter()
        jax.block_until_ready(cur)
        t_compute += time.perf_counter() - tic
        pending = (cur, s0, s1)
    tic = time.perf_counter()
    stream_sched.drain_window(host_leaves, *pending, per_device=d2h_dev)
    t_d2h += time.perf_counter() - tic
    wall = time.perf_counter() - wall0
    if stats is not None:
        stats["cohorts"] = stats.get("cohorts", 0) + len(wins)
        stats["launches"] = stats.get("launches", 0) + launches
        stats["h2d_s"] = stats.get("h2d_s", 0.0) + t_h2d
        stats["compute_s"] = stats.get("compute_s", 0.0) + t_compute
        stats["d2h_s"] = stats.get("d2h_s", 0.0) + t_d2h
        stats["wall_s"] = stats.get("wall_s", 0.0) + wall
        stats["overlap_efficiency_measured"] = (
            stats["compute_s"] / stats["wall_s"] if stats["wall_s"] > 0
            else None)
        stats["n_devices"] = nd
        stats["staging"] = bool(staging)
        acc = stats.setdefault("_per_device_s", {})
        for k in set(h2d_dev) | set(d2h_dev):
            rec = acc.setdefault(k, {"h2d_s": 0.0, "d2h_s": 0.0})
            rec["h2d_s"] += h2d_dev.get(k, 0.0)
            rec["d2h_s"] += d2h_dev.get(k, 0.0)
        comp = stats["compute_s"]
        per = [{"device": k,
                "h2d_s": round(v["h2d_s"], 6),
                "d2h_s": round(v["d2h_s"], 6),
                "copy_s": round(v["h2d_s"] + v["d2h_s"], 6)}
               for k, v in sorted(acc.items())]
        stats["per_device"] = per
        if per:
            stats["slowest_device"] = max(
                per, key=lambda r: r["copy_s"])["device"]
            stats["overlap_efficiency_per_device_measured"] = [
                (round(comp / max(comp, r["copy_s"]), 4)
                 if comp > 0 else None) for r in per]
    return host_leaves


def prun_streamed_sharded(cfg: RaftConfig, st: State, n_ticks: int,
                          mesh, t0: int = 0,
                          metrics: Metrics | None = None,
                          interpret: bool = False,
                          flight: Flight | None = None,
                          chunk_ticks: int | None = None,
                          stats: dict | None = None,
                          staging: bool = True):
    """Drop-in for `kmesh.prun_sharded` on streamed configs — the r17
    tentpole: same (State, Metrics[, Flight]) out, same bits, but the
    fleet lives in host RAM and every double-buffered window pages
    through ALL of `mesh`'s devices concurrently (DESIGN.md §16).
    Raises ValueError on unsupported shapes (`supported()` at
    `n_devices=mesh.size` budgets the per-device host-RAM share for G
    and per-device HBM only for the window slice). `stats` receives
    the measured split including the per-device copy lanes;
    `staging=False` selects the naive `device_put` copy path."""
    g = st.alive_prev.shape[0]
    wf = flight is not None
    nd = mesh.size
    # r19 host boundary, same refusal as prun_streamed.
    from raft_tpu.sim import state as state_mod
    state_mod.check_narrow_overflow(cfg, st)
    scfg = cfg if cfg.stream_groups else None
    if scfg is None:
        import dataclasses
        scfg = dataclasses.replace(cfg, stream_groups=True)
    if not pkernel.supported(scfg, n_groups=g, n_devices=nd,
                             with_flight=wf):
        raise ValueError(
            f"cohort: shape unsupported on {nd} device(s) (k > 30, "
            f"VMEM footprint {pkernel.kernel_vmem_bytes(cfg)} B > "
            f"{pkernel.VMEM_LIMIT_BYTES} B, per-device cohort window "
            f"{pkernel.cohort_hbm_bytes(cfg, wf, nd)} B > "
            f"{pkernel.HBM_LIMIT_BYTES} B HBM, or per-device host "
            f"wire share {pkernel.host_bytes(scfg, -(-g // nd), wf)} B "
            f"> {pkernel.HOST_RAM_LIMIT_BYTES} B host RAM)")
    host_leaves, g = host_wire(cfg, st, metrics, flight,
                               pad_to=nd * GB)
    stream_ticks_sharded(cfg, host_leaves, g, t0, n_ticks, mesh,
                         interpret=interpret, chunk_ticks=chunk_ticks,
                         stats=stats, staging=staging)
    with _on_host():
        leaves = tuple(map(np.asarray, host_leaves))
        if flight is None:
            return pkernel.kfinish(cfg, leaves, g, metrics)
        st2, met2 = pkernel.kfinish(cfg, leaves, g, metrics)
        return st2, met2, pkernel.kflight(cfg, leaves, g)
