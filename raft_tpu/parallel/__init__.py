"""Device-mesh sharding of the groups axis (DESIGN.md §5, config 5;
§9 for the kernel wire form — raft_tpu.parallel.kmesh)."""

from raft_tpu.parallel.mesh import (AXIS, make_mesh, run_sharded,
                                    shard_state, state_sharding)

__all__ = ["AXIS", "make_mesh", "run_sharded", "shard_state",
           "state_sharding"]
