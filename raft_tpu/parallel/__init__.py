"""Device-mesh sharding of the groups axis (DESIGN.md §5, config 5;
§9 for the kernel wire form — raft_tpu.parallel.kmesh; §15 for the
host<->HBM cohort paging path — raft_tpu.parallel.cohort; §16 for the
two composed — raft_tpu.parallel.stream_sched + prun_streamed_sharded)."""

from raft_tpu.parallel.cohort import prun_streamed, prun_streamed_sharded
from raft_tpu.parallel.mesh import (AXIS, make_mesh, run_sharded,
                                    shard_state, state_sharding)

__all__ = ["AXIS", "make_mesh", "prun_streamed", "prun_streamed_sharded",
           "run_sharded", "shard_state", "state_sharding"]
