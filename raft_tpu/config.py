"""Configuration shared by the CPU reference path and the TPU batched path.

Every knob that affects semantics lives here so the two backends cannot
drift. Probabilities are expressed as floats in [0, 1] and converted to
uint32 thresholds (`*_u32`) so that the CPU path (python ints) and the TPU
path (uint32 lanes) make bit-identical decisions.
"""

from __future__ import annotations

import dataclasses

# Clause-kind constants of the nemesis scenario compiler (DESIGN.md
# §14). utils/rng.py is the layering bottom (it imports nothing from
# the repo), so the kind registry lives there and both this module's
# seam filters and raft_tpu/nemesis/program.py's builders import it.
from raft_tpu.utils import rng as _nem

_U32 = 0xFFFFFFFF

# Log-entry payload encoding. Client payloads are 30-bit hashes; a set
# CONFIG_FLAG bit marks a membership-change entry whose low k bits are
# the new voter bitmask (single-server delta from the previous config).
# Both backends share these constants so the encodings cannot drift.
CONFIG_FLAG = 1 << 30
PAYLOAD_MASK = CONFIG_FLAG - 1

# Client-session encoding (exactly-once application, dissertation §6.3;
# active only when `RaftConfig.sessions`). A set SESSION_FLAG bit (below
# CONFIG_FLAG) marks a session command: sid in bits 20-28 (sid 0x1FF
# reserved = session REGISTER), client sequence number in bits 10-19,
# 10-bit value hash in bits 0-9. The state machine applies a (sid, seq)
# at most once — retried proposals commit as duplicate log entries but
# fold into the digest exactly once on every node.
SESSION_FLAG = 1 << 29
SESSION_SID_SHIFT, SESSION_SID_MASK = 20, 0x1FF
SESSION_SEQ_SHIFT, SESSION_SEQ_MASK = 10, 0x3FF
SESSION_VAL_MASK = 0x3FF
SESSION_REGISTER = SESSION_FLAG | (SESSION_SID_MASK << SESSION_SID_SHIFT)


def session_payload(sid: int, seq: int, val: int) -> int:
    """Encode an exactly-once session command.

    Raises ValueError (not assert — asserts vanish under `python -O`,
    and an out-of-range sid/seq would silently alias ANOTHER session's
    slot, corrupting the exactly-once filter) on sid outside
    [0, SESSION_SID_MASK) or seq outside [0, SESSION_SEQ_MASK].

    Lifetime limit: seq is a 10-bit field, so a session can issue at
    most SESSION_SEQ_MASK + 1 = 1024 commands (seq 0..1023) before the
    client must register a fresh session — the filter keeps only the
    highest applied seq per sid, so a wrapped seq would be dropped as a
    duplicate, never double-applied.
    """
    if not 0 <= sid < SESSION_SID_MASK:
        raise ValueError(
            f"session sid {sid} outside [0, {SESSION_SID_MASK}) "
            f"(sid {SESSION_SID_MASK:#x} is the reserved REGISTER marker)")
    if not 0 <= seq <= SESSION_SEQ_MASK:
        raise ValueError(
            f"session seq {seq} outside [0, {SESSION_SEQ_MASK}] — a "
            f"session's lifetime is {SESSION_SEQ_MASK + 1} commands; "
            f"open a new session instead of wrapping")
    return (SESSION_FLAG | (sid << SESSION_SID_SHIFT)
            | (seq << SESSION_SEQ_SHIFT) | (val & SESSION_VAL_MASK))


# Kernel wire-LAYOUT knobs: fields of RaftConfig that change how the
# Pallas kernel lays state out in HBM (packing, buffer donation,
# telemetry rows) but never what any engine computes per tick. One
# registry, consumed by checkpoint.load (configs match modulo these —
# a packed run may resume an unpacked file and vice versa), by the
# bench/sweep manifests (recorded per segment), and by the contract
# auditor (flipping one must change zero State pytree leaves).
LAYOUT_FIELDS = ("pack_bools", "pack_ring", "alias_wire", "wire_hist")

# Kernel RESIDENCY knobs (r16, DESIGN.md §15): fields of RaftConfig
# that change where the wire form LIVES between chunk launches (host
# RAM vs HBM) but never what any engine computes per tick — the same
# layout-class contract as LAYOUT_FIELDS, kept as a separate registry
# because the r13 manifest/backfill key lists (PACKING_KEYS ==
# LAYOUT_FIELDS) are pinned four-wide by the contract auditor. One
# registry, consumed by checkpoint.load (configs match modulo these —
# a streamed run may resume a resident-layout file and vice versa), by
# the bench/sweep manifests (obs.manifest.STREAM_KEYS lead with these
# names), and by the contract auditor's streaming pass (flipping one
# must change zero State pytree leaves and zero wire lanes).
STREAM_FIELDS = ("stream_groups", "cohort_blocks")

# Narrow-native dtype dials (r19, DESIGN.md §18): fields of RaftConfig
# that change the NATIVE dtype the resident State/Mailbox/ClientState
# leaves are carried at between ticks (u16 terms/indices, i8 roles,
# real bools instead of i32-widened lanes) and whether the XLA scan
# donates its carry buffers — but never what any engine computes per
# tick: the tick body widens on entry and re-narrows on exit, so every
# arithmetic op still runs at the audited i32/u32 widths and the
# narrow form is value-identical to the wide one by construction
# (overflow latches loudly, sim/state.narrow_state). Same layout-class
# contract as LAYOUT_FIELDS/STREAM_FIELDS, kept as a third registry
# because the earlier manifest/backfill key lists are pinned at their
# widths by the contract auditor. One registry, consumed by
# checkpoint.load (configs match modulo these — a narrow run may
# resume a wide file and vice versa, widened/narrowed by leaf NAME on
# load), by obs.manifest.config_hash (excluded), by the bench/sweep
# manifests (obs.manifest.NARROW_KEYS lead with these names), and by
# the contract auditor's narrowing pass (flipping one must change zero
# State pytree leaves and zero wire lanes).
NARROW_FIELDS = ("narrow_scalars", "narrow_ring", "narrow_mailbox",
                 "narrow_clients", "donate_scan")


def _prob_to_u32(p: float) -> int:
    """Map a probability to a uint32 threshold: event iff hash < threshold.

    Probabilities are quantized to k/2**32 with k <= 2**32 - 1, so p=1.0
    means 1 - 2**-32 — the threshold must itself fit in a uint32 lane or the
    CPU and TPU paths could disagree on hash == 0xFFFFFFFF.
    """
    if p <= 0.0:
        return 0
    return min(int(p * 4294967296.0), _U32)


@dataclasses.dataclass(frozen=True)
class RaftConfig:
    """Semantic parameters of the simulated Raft universe (see DESIGN.md §2)."""

    n_groups: int = 1          # G — independent Raft groups (batch axis)
    k: int = 5                 # K — replicas per group
    log_cap: int = 32          # L — ring window: last_index - snap_index <= L
    max_entries_per_msg: int = 4   # E — entries carried per AppendEntries
    heartbeat_every: int = 2   # leader AE cadence, in ticks
    election_min: int = 10     # randomized election timeout in
    election_range: int = 10   # [election_min, election_min + election_range)
    compact_every: int = 8     # snapshot when commit - snap_index >= this
    cmds_per_tick: int = 1     # client commands the leader appends per tick
    # Client sessions (exactly-once application, dissertation §6.3) —
    # the session bit-fields above become meaningful to the state
    # machine only when True. Interactive `propose` payloads must then
    # keep bit 29 clear (asserted); scheduled fire-hose payloads hash
    # the full 30-bit space, so sessions=True requires cmds_per_tick=0.
    # Two client modes ride this flag: interactive oracle clients
    # (Cluster.propose_seq / open_session) and, when client_rate > 0,
    # the scheduled open-loop traffic below — on BOTH engines.
    sessions: bool = False
    seed: int = 0

    # Scheduled client traffic (open-loop, exactly-once — DESIGN.md
    # §10). When client_rate > 0 every group carries `client_slots`
    # pre-registered sessions (sid 0..client_slots-1); each session is
    # an independent open-loop client whose ops arrive w.p. client_rate
    # per tick (Bernoulli — the discrete-tick Poisson limit), queue in
    # a backlog, and are submitted to whichever node(s) claim
    # leadership. A client that sees no ack within
    # client_retry_backoff ticks RE-SUBMITS the same (sid, seq) — the
    # ambiguous-failure retry after a leader crash — and the per-group
    # (sid, seq) dedup table in the replicated state machine folds the
    # duplicate exactly once. Requires sessions=True (the state machine
    # must interpret bit 29) and hence cmds_per_tick=0.
    client_rate: float = 0.0
    client_slots: int = 4
    client_retry_backoff: int = 8

    # Bounded-queue admission control (r20, DESIGN.md §19): when > 0,
    # a scheduled arrival that would push a session's backlog to
    # client_queue_cap or beyond is SHED — a definitive reject, counted
    # in ClientState.shed, never issued a seq, never retried (no
    # ambiguity: the op provably never entered the replicated log, the
    # exactly-once ledger in clients.workload.exactly_once_report
    # accounts arrivals = issued + shed). SEMANTIC knob (config_hash,
    # checkpoint match); 0 = off, the shed leaf and every admission
    # compare are statically absent and the wire is byte-identical to
    # r19. Requires the scheduled client subsystem (client_rate > 0).
    client_queue_cap: int = 0

    # Fault injection (DESIGN.md §4). All off by default.
    drop_prob: float = 0.0       # per-link per-tick message loss
    crash_prob: float = 0.0      # per-node per-epoch crash probability
    crash_epoch: int = 64        # ticks per crash epoch
    partition_prob: float = 0.0  # per-group per-epoch partition probability
    partition_epoch: int = 64    # ticks per partition epoch

    # Membership-change schedule (DESIGN.md §2b). Off by default. At the
    # first tick of each reconfig epoch, w.p. reconfig_prob the leader
    # proposes toggling one hash-chosen node's membership — subject to
    # the single-server gating rules and to the resulting config keeping
    # at least min_voters voters (0 = k//2 + 1, keeping quorums live
    # under the crash schedule).
    reconfig_prob: float = 0.0
    reconfig_epoch: int = 64
    min_voters: int = 0

    # Leadership-transfer schedule (DESIGN.md §2d): at the first tick of
    # each transfer epoch, w.p. transfer_prob, the leader hands
    # leadership to a hash-chosen fully-caught-up voter by sending
    # TimeoutNow (dissertation §3.10); the target campaigns immediately,
    # bypassing PreVote. Off by default (statically absent).
    transfer_prob: float = 0.0
    transfer_epoch: int = 64

    # Scheduled linearizable reads (DESIGN.md §2c): every `read_every`
    # ticks the leader registers a ReadIndex read (dissertation §6.4) at
    # the start of phase C; it completes in a later tick's phase A once
    # a CURRENT-config voter majority has acked at ticks >= reg + 2 and
    # the state machine has applied through the read point, incrementing
    # the node's `reads_done` counter (part of the differential trace
    # surface). 0 = off (statically absent from both backends' programs).
    read_every: int = 0

    # PreVote (Raft dissertation §9.6): before bumping its term, a
    # timed-out node runs a non-binding pre-ballot at term+1; peers grant
    # only if the log is up-to-date AND they have not heard from a leader
    # within election_min ticks (the lease check). Prevents a rejoining
    # partitioned node from inflating terms and deposing a healthy
    # leader. Static flag: when False, the pre-vote machinery is absent
    # from both backends' programs (no new messages, identical traces).
    prevote: bool = False

    # Kernel wire-layout dials (DESIGN.md §13). LAYOUT-ONLY knobs: none
    # of them changes tick semantics — the CPU oracle and the XLA scan
    # ignore them entirely, and the kernel packs/unpacks only at chunk
    # boundaries so per-tick state stays bit-identical across engines.
    # All default off/on such that the default wire, checkpoints, and
    # compiled programs are byte-identical to pre-r13 builds
    # (LAYOUT_FIELDS below; checkpoint.load matches configs modulo
    # these fields for the same reason).
    #
    # pack_bools: bit-pack the i32-widened bool wire leaves — the
    #   [K, K] mailbox presence/grant/success masks share i32 lanes
    #   (bit = field x src), votes packs its peer axis, alive_prev its
    #   node axis (−856 B/group at the headline config).
    # pack_ring: delta-encode the log_term ring against a per-chunk
    #   per-group base in 16-bit half-lanes (2 slots/word, −316 B/group
    #   at headline; requires an even log_cap). Lossless while the
    #   in-group term spread stays under 2^16; overflow latches a
    #   sticky bit that kfinish refuses loudly (never silent corruption).
    # alias_wire: input/output-alias the fused-chunk pallas_call (and
    #   donate the wire operands through jit/shard_map), so ONE copy of
    #   the wire is resident instead of in+out — halves the HBM
    #   residency model behind supported()/hbm_ceiling_groups.
    # wire_hist: carry the in-kernel per-group [H]-row histogram(s) on
    #   the wire (2,048 B/group each). False is the ceiling-run dial:
    #   the kernel stops tracking election/ack latency histograms
    #   (Metrics.hist passes through unchanged) — telemetry as a dial,
    #   not a tax (DESIGN.md §9 "next levers").
    pack_bools: bool = False
    pack_ring: bool = False
    alias_wire: bool = False
    wire_hist: bool = True

    # Cohort-paging residency dials (DESIGN.md §15). RESIDENCY-ONLY
    # knobs (STREAM_FIELDS below): none of them changes tick semantics
    # — the CPU oracle and the XLA scan ignore them entirely, and the
    # cohort scheduler (parallel/cohort.py) pages whole group blocks
    # host<->HBM only at chunk boundaries, where the wire is already
    # packed/unpacked, so per-tick state stays bit-identical across
    # engines. Both default off/neutral so the default wire,
    # checkpoints, and compiled programs are byte-identical to r14.
    #
    # stream_groups: hold the full fleet's wire form in host RAM and
    #   stream cohort_blocks-sized windows of 1024-group blocks through
    #   HBM under the unchanged fused-chunk kernel — the group ceiling
    #   becomes host-RAM-bound (pkernel.streamed_ceiling_groups)
    #   instead of HBM-bound (pkernel.hbm_ceiling_groups).
    # cohort_blocks: 1024-group blocks resident per cohort window. The
    #   double-buffered pipeline holds up to prev + current (x residency
    #   buffers) + next windows in HBM at once — bigger windows amortize
    #   launch overhead, smaller ones shrink the HBM footprint.
    stream_groups: bool = False
    cohort_blocks: int = 4

    # Narrow-native dtype dials (r19, DESIGN.md §18). LAYOUT-class
    # knobs (NARROW_FIELDS above): none of them changes tick semantics
    # — the CPU oracle ignores them entirely, the XLA scan carries the
    # narrow form between ticks but computes every tick at the audited
    # wide widths (sim/step.py widen-on-entry / narrow-on-exit), and
    # the kernel wire form is untouched (its i32-word registries and
    # every byte pin stay exactly r18's; the kernel widens at kinit and
    # re-narrows at kfinish). All default off so the default pytrees,
    # checkpoints, and compiled programs are byte-identical to r18.
    #
    # narrow_scalars: PerNode term/index/clock scalars drop to
    #   u16/i16/i8 per the audited range proofs in sim/state.narrow_spec
    #   (value-range table in DESIGN.md §18); out-of-range values latch
    #   sticky bit 31 of group_id and the next host boundary refuses
    #   loudly (never silent truncation).
    # narrow_ring: the log_term ring rides u16 natively (terms are
    #   u16-range in every benched universe; same latch on overflow) —
    #   the resident twin of the pack_ring WIRE dial.
    # narrow_mailbox: mailbox term/index/count payload lanes drop to
    #   u16/i8; presence/grant/success bits stay real bools (they
    #   already are — the i32 widening only ever existed on the wire).
    # narrow_clients: ClientState + session dedup tables at
    #   u16/i16/i8 (session seqs are 10-bit by construction,
    #   config.SESSION_SEQ_MASK).
    # donate_scan: donate the (state, metrics) carry into the jitted
    #   XLA scan (donate_argnums twins of sim/run.run — the scan-path
    #   analogue of alias_wire's kernel donation): one resident carry
    #   copy instead of in+out.
    narrow_scalars: bool = False
    narrow_ring: bool = False
    narrow_mailbox: bool = False
    narrow_clients: bool = False
    donate_scan: bool = False

    # Nemesis gray-failure program (DESIGN.md §14): a tuple of 8-int
    # clauses (kind, t0, t1, group_u32, p_u32, a, b, cid) built by
    # raft_tpu/nemesis/program.py. SEMANTIC (part of the universe
    # schedule, included in config_hash and the checkpoint match) and
    # static: each clause compiles to pure (seed, TAG_NEM_*, cid,
    # coords) hashes evaluated identically by all three engines at the
    # existing fault seams — no new state, no new wire lanes, and the
    # default () leaves every compiled program byte-identical to
    # pre-r14. Normalized in __post_init__ to plain int tuples so a
    # config rebuilt from JSON (checkpoint/manifest dicts) stays
    # hashable and equal to the original.
    nemesis: tuple = ()

    def __post_init__(self):
        norm = []
        for c in self.nemesis:
            c = tuple(int(x) for x in c)
            if len(c) != 8:
                raise ValueError(
                    f"nemesis clause {c} must have 8 fields "
                    f"(kind, t0, t1, group_u32, p_u32, a, b, cid)")
            kind, t0, t1, group_u32, p_u32, a, b, cid = c
            if kind not in _nem.NEM_KINDS:
                raise ValueError(f"nemesis clause kind {kind} unknown "
                                 f"(known: {_nem.NEM_KINDS})")
            if not 0 <= t0 <= t1:
                raise ValueError(f"nemesis clause span [{t0}, {t1}) invalid")
            if not (0 <= group_u32 <= _U32 and 0 <= p_u32 <= _U32):
                raise ValueError(
                    f"nemesis clause thresholds ({group_u32}, {p_u32}) "
                    f"outside u32")
            # a/b range: the jnp twins cast them to u32 lanes (i32 for
            # the signed skew amount) — an out-of-range value from a
            # hand-edited artifact would be a silent no-op on the host
            # evaluator but an OverflowError (or worse, a wrapped,
            # DIFFERENT schedule) at trace time on the engines.
            if kind == _nem.NEM_SKEW:
                if not -2**31 <= a < 2**31:
                    raise ValueError(f"nemesis skew amount {a} outside "
                                     f"i32")
            elif not 0 <= a <= _U32:
                raise ValueError(f"nemesis clause a={a} outside u32")
            if not 0 <= b <= _U32:
                raise ValueError(f"nemesis clause b={b} outside u32")
            if kind in (_nem.NEM_FLAKY, _nem.NEM_STORM, _nem.NEM_WAVE,
                        _nem.NEM_DISK, _nem.NEM_COMPACT) \
                    and a < 1:
                raise ValueError(f"nemesis clause kind {kind} needs its "
                                 f"epoch/period a >= 1, got {a}")
            if kind == _nem.NEM_SLOW and a not in (1, 2, 3):
                # A 0/out-of-range direction mask would be a silent
                # no-op on the oracle and a misleading "no link clause"
                # trace error on the jnp engines — refuse it at the
                # boundary every hand-edited artifact/manifest dict
                # crosses.
                raise ValueError(f"nemesis slow-follower clause needs "
                                 f"direction a in (1, 2, 3), got {a}")
            if kind == _nem.NEM_WAN and a < 2:
                raise ValueError(f"nemesis WAN clause needs >= 2 sites, "
                                 f"got {a}")
            if cid < 0:
                raise ValueError(
                    f"nemesis clause cid {cid} unassigned — build "
                    f"programs via raft_tpu.nemesis.program()")
            norm.append(c)
        if len({c[7] for c in norm}) != len(norm):
            raise ValueError("nemesis clause cids must be unique — a "
                             "duplicate cid aliases two clauses' draws")
        object.__setattr__(self, "nemesis", tuple(norm))
        assert not self.sessions or self.cmds_per_tick == 0, (
            "sessions=True needs cmds_per_tick=0: scheduled payloads hash "
            "the full 30-bit space, so bit 29 would be misread as session "
            "commands (see the sessions field comment)")
        if self.client_rate > 0.0:
            assert self.sessions, (
                "client_rate > 0 needs sessions=True: scheduled client "
                "traffic is session commands, and the state machine only "
                "interprets bit 29 under the sessions flag")
            # The subsystem gates on the QUANTIZED threshold everywhere;
            # a rate below 2^-32 would pass the float test yet build a
            # clients-off universe — reject it here, loudly.
            assert self.clients_u32 > 0, (
                f"client_rate {self.client_rate} quantizes to a zero "
                f"uint32 arrival threshold (< 2**-32): the client "
                f"subsystem would be statically absent")
            # sid 0..client_slots-1 must stay clear of the reserved
            # REGISTER marker, and both engines statically unroll the
            # slot axis — keep it register-sized.
            assert 1 <= self.client_slots <= 16, (
                "client_slots must be in [1, 16]")
            assert self.client_retry_backoff >= 1
        assert self.client_queue_cap >= 0, (
            "client_queue_cap must be >= 0 (0 = admission control off)")
        if self.client_queue_cap > 0:
            assert self.client_rate > 0.0, (
                "client_queue_cap > 0 needs client_rate > 0: admission "
                "control bounds the scheduled clients' backlog queues, "
                "which only exist under the scheduled-traffic subsystem")
        assert self.cohort_blocks >= 1, (
            "cohort_blocks must be >= 1: the cohort scheduler pages "
            "whole 1024-group blocks and an empty window pages nothing")
        assert self.k >= 1
        assert self.election_range >= 1
        assert self.heartbeat_every >= 1
        assert self.max_entries_per_msg >= 1
        # The batched AE entry walk (sim/step.py) relies on one message's
        # E consecutive indices occupying pairwise-distinct ring slots.
        assert self.max_entries_per_msg <= self.log_cap, (
            "max_entries_per_msg must not exceed log_cap"
        )
        # The window must fit a burst of appends plus compaction slack.
        assert self.log_cap >= self.compact_every + self.cmds_per_tick + 1, (
            "log_cap must cover compact_every + cmds_per_tick + 1 or the "
            "window can deadlock before compaction frees space"
        )
        assert self.election_min > 2 * self.heartbeat_every, (
            "election timeout must comfortably exceed the heartbeat cadence "
            "or steady-state leadership is impossible"
        )
        assert not self.pack_ring or self.log_cap % 2 == 0, (
            "pack_ring packs two ring-term deltas per i32 word, so "
            "log_cap must be even"
        )

    @property
    def majority(self) -> int:
        """Majority of the FULL k-node set — the initial config. Live
        quorum decisions use the majority of the active voter mask
        (`voter_majority`), which equals this until a membership change
        commits."""
        return self.k // 2 + 1

    @property
    def full_mask(self) -> int:
        return (1 << self.k) - 1

    @property
    def effective_min_voters(self) -> int:
        return self.min_voters if self.min_voters > 0 else self.k // 2 + 1

    @property
    def clients_u32(self) -> int:
        """uint32 arrival threshold of the scheduled client traffic —
        the ONE static gate for the whole subsystem on both engines
        (0 = every client structure is absent from the programs)."""
        return _prob_to_u32(self.client_rate)

    @property
    def reconfig_u32(self) -> int:
        return _prob_to_u32(self.reconfig_prob)

    @property
    def transfer_u32(self) -> int:
        return _prob_to_u32(self.transfer_prob)

    @property
    def drop_u32(self) -> int:
        return _prob_to_u32(self.drop_prob)

    @property
    def crash_u32(self) -> int:
        return _prob_to_u32(self.crash_prob)

    @property
    def partition_u32(self) -> int:
        return _prob_to_u32(self.partition_prob)

    # Nemesis seam filters (DESIGN.md §14): the kind-partitioned
    # subprograms each engine seam statically gates on — link clauses
    # into the delivery filter, storm clauses into the aliveness mask,
    # skew clauses into the deadline draw. The partition is proven
    # total by analysis.contracts.nemesis_problems (a kind filtered by
    # no seam would be a silently-ignored clause).

    @property
    def nem_link(self) -> tuple:
        return tuple(c for c in self.nemesis
                     if c[0] in _nem.NEM_LINK_KINDS)

    @property
    def nem_crash(self) -> tuple:
        return tuple(c for c in self.nemesis
                     if c[0] in _nem.NEM_CRASH_KINDS)

    @property
    def nem_skew(self) -> tuple:
        return tuple(c for c in self.nemesis
                     if c[0] in _nem.NEM_TIMING_KINDS)

    @property
    def nem_disk(self) -> tuple:
        """Disk-full-follower clauses → the append/persistence seam
        (r20, DESIGN.md §19): every local append on the hash-chosen
        target node fails while the clause holds, so entries are never
        durable and must never be acked."""
        return tuple(c for c in self.nemesis
                     if c[0] in _nem.NEM_DISK_KINDS)

    @property
    def nem_compact(self) -> tuple:
        """Compaction-pressure clauses → the snapshot/compaction seam
        (r20, DESIGN.md §19): a blocked node's phase-A compaction is
        delayed, the log_cap ring genuinely fills, and the window
        invariant becomes a runtime backpressure path."""
        return tuple(c for c in self.nemesis
                     if c[0] in _nem.NEM_COMPACT_KINDS)
