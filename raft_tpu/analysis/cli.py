"""`raft-tpu-audit` console entry / scripts/static_audit.py body.

Exit status IS the verdict: 0 = every contract holds, nonzero = drift
(each problem printed, naming the leaf and the registry). `--json`
emits the full machine-readable report (byte model included) for
tooling; `--inject-drift LEAF` is the self-test hook the synthetic-
drift tests use to prove the nonzero path end-to-end.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    # Static analysis — never let the import initialize a real
    # accelerator (same guard as the old check_metric_parity.py).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    ap = argparse.ArgumentParser(
        prog="raft-tpu-audit",
        description="Static engine-contract auditor: pytrees vs kernel "
                    "wire registries vs shard rule vs checkpoint format, "
                    "derived byte model, and the purity lint "
                    "(DESIGN.md §11). rc != 0 on any drift.")
    ap.add_argument("--json", action="store_true",
                    help="print the full machine-readable report")
    ap.add_argument("--level", choices=("static", "full", "deep"),
                    default="full",
                    help="'static' skips the behavioral checkpoint "
                         "round-trips (the bench startup form); 'deep' "
                         "adds the r18 verification passes — model-"
                         "checker smoke + scheduler hazard prover "
                         "(still chip-free, fits the pre-push gate)")
    ap.add_argument("--deep", action="store_true",
                    help="alias for --level deep")
    ap.add_argument("--bytes", action="store_true",
                    help="also print the per-leaf derived byte table")
    ap.add_argument("--inject-drift", metavar="LEAF", default=None,
                    help="self-test: audit against a PerNode copy that "
                         "grew this fake leaf (must exit nonzero naming "
                         "it)")
    args = ap.parse_args(argv)
    if args.deep:
        args.level = "deep"

    import jax
    jax.config.update("jax_platforms", "cpu")

    from raft_tpu import analysis
    from raft_tpu.analysis import contracts, lint

    if args.inject_drift:
        from raft_tpu.sim.state import PerNode
        problems = contracts.wire_registry_problems(
            pernode_fields=PerNode._fields + (args.inject_drift,))
        for p in problems:
            print(f"CONTRACT DRIFT: {p}")
        if not problems:
            print("SELF-TEST FAILED: injected drift went undetected")
            return 2
        # Second synthetic-drift leg (r17): a history module whose
        # backfill drops the streamed-mesh keys must be caught by the
        # manifest pass — proves the STREAM_MESH_KEYS coverage check
        # end-to-end, not just the PerNode registry one.
        import types

        from raft_tpu.obs import history as _hist
        from raft_tpu.obs import manifest as _man

        def _drifted_backfill(rec):
            out = _hist.backfill_record(rec)
            for k in _man.STREAM_MESH_KEYS:
                out.pop(k, None)
            return out

        stub = types.SimpleNamespace(**{
            **{k: getattr(_hist, k) for k in dir(_hist)
               if not k.startswith("_")},
            "backfill_record": _drifted_backfill})
        mesh_problems = [p for p in contracts.manifest_problems(
            history_mod=stub) if "stream_slowest_device" in p]
        for p in mesh_problems:
            print(f"CONTRACT DRIFT (synthetic r17): {p}")
        if not mesh_problems:
            print("SELF-TEST FAILED: dropped STREAM_MESH_KEYS backfill "
                  "went undetected by manifest_problems")
            return 2
        return 1

    report = analysis.audit_report(level=args.level)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for p in report["problems"]:
            print(f"CONTRACT DRIFT: {p}")
        for f in report["lint"]:
            print(f"LINT: {lint.Finding(**f)}")
        if args.bytes:
            for label, model in report["byte_model"].items():
                print(f"derived wire model [{label}]: "
                      f"{model['wire_bytes_derived']} B/group "
                      f"(pinned {model['wire_bytes_pinned']})")
                for row in sorted(model["leaves"],
                                  key=lambda r: -r["wire_words"]):
                    star = " *widened bool" if row["widened_bool"] else ""
                    print(f"  {4 * row['wire_words']:6d} B  "
                          f"{row['name']:34s} {row['dtype']}{star}")
                w = model["widening"]
                print(f"  widening waste: {w['waste_bytes_per_group']} "
                      f"B/group over {len(w['leaves'])} bool leaves")
        if report["ok"]:
            hb = report["byte_model"]["headline"]["wire_bytes_derived"]
            cb = report["byte_model"]["clients"]["wire_bytes_derived"]
            print(f"static audit ok ({args.level}): contracts + shard rule "
                  f"+ checkpoint coverage + byte model (headline {hb} "
                  f"B/group, clients {cb} B/group, derived == pinned) + "
                  f"purity lint all clean")
            if "verify" in report:
                v = report["verify"]
                print(f"verification ok (deep): mcheck smoke "
                      f"[{v['mcheck_smoke']}] + hazard prover "
                      f"({v['hazard_configs']} scheduler configs, "
                      f"{v['hazard_events']} events, 0 hazards, "
                      f"{v['negatives_caught']}/3 negatives caught)")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
