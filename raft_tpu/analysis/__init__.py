"""Static engine-contract auditor (DESIGN.md §11).

Proves — without a TPU and without executing a tick — that the three
engines (CPU oracle, XLA scan, Pallas kernel), the kernel wire model,
and the checkpoint format agree:

- `contracts` — leaf-contract passes: pytree definitions vs the wire
  registries, the `kleaf_spec` shard rule, checkpoint coverage +
  pre-r07/r09 backfills, the cfg-gating table, rng/jrng parity.
- `bytemodel` — bytes/group DERIVED from dtype x shape (eval_shape),
  reconciled exactly against the hand-pinned wire model
  (`pkernel.wire_words_per_group`: 8,308 B clients-off / 11,056 B
  clients-on), with the i32-widened-bool waste named per leaf.
- `lint` — AST purity/determinism rules over sim/step.py,
  sim/pkernel.py, clients/workload.py (tagged randomness only, no
  Python branching on traced values, elementwise-only workload
  transition).

Entry points: `audit_report()` (machine-readable dict),
`audit_problems()` (flat strings), `startup_audit()` (raise on drift —
bench.py / kernel_sweep.py call it so no number is ever published off
a drifted layout), and the `raft-tpu-audit` console script /
`scripts/static_audit.py` (rc != 0 on any drift).
"""

from __future__ import annotations

from raft_tpu.analysis import bytemodel, contracts, lint

__all__ = ["audit_report", "audit_problems", "startup_audit",
           "bytemodel", "contracts", "lint"]


def audit_report(level: str = "full") -> dict:
    """Run every pass; return the full machine-readable report.

    `level="static"` skips the behavioral checkpoint round-trips (the
    only pass that materializes concrete host arrays) — the cheap
    import-time form bench/kernel_sweep gate their startup on;
    `level="full"` is the CI/script form; `level="deep"` (r18) is full
    plus the verification passes — a depth-limited model-checker smoke
    (exhaustive clean oracle at tiny scope + a seeded-mutant canary
    kill, verify/mcheck.py) and the scheduler hazard prover over its
    whole bound grid plus its synthetic negatives (verify/hazards.py).
    Deep stays chip-free and fits the pre-push gate
    (scripts/ci_static.sh).
    """
    if level not in ("static", "full", "deep"):
        raise ValueError(f"unknown audit level {level!r}")
    problems = contracts.contract_problems(
        include_behavioral=(level in ("full", "deep")))
    # One derivation per (config, flight) point — the flight-on models
    # double as the report's byte_model block (each derivation is
    # several eval_shape traces; don't pay them twice per startup).
    # audit_cfgs covers the r12 baselines AND the r13 packed/dialed
    # layouts, so no number is published off a drifted PACKED wire
    # either.
    byte_models = {}
    for label, cfg in bytemodel.audit_cfgs():
        for wf in (True, False):
            model = bytemodel.derived_wire_model(cfg, with_flight=wf)
            problems += [
                f"byte model [{label}, flight={'on' if wf else 'off'}]: {p}"
                for p in model["problems"]]
            if wf:
                byte_models[label] = model
    findings = lint.lint_default()
    verify_block = None
    if level == "deep":
        from raft_tpu.verify import hazards, mcheck
        smoke = mcheck.smoke()
        if not (smoke.ok and smoke.complete):
            problems.append(
                "mcheck smoke: clean oracle not exhaustively verified "
                f"at smoke scope ({smoke.summary()})")
        haz = hazards.prove_schedulers()
        problems += [f"scheduler hazard: {h}" for h in haz["hazards"]]
        neg = hazards.prove_negatives()
        if neg["missed"]:
            problems.append(
                "hazard prover failed to catch synthetic negatives: "
                + ", ".join(neg["missed"]))
        verify_block = {
            "mcheck_smoke": smoke.summary(),
            "hazard_configs": haz["configs"],
            "hazard_events": haz["events"],
            "negatives_caught": neg["caught"],
        }
    report = {
        "level": level,
        "ok": not problems and not findings,
        "problems": problems,
        "lint": [f.as_dict() for f in findings],
        "byte_model": byte_models,
    }
    if verify_block is not None:
        report["verify"] = verify_block
    return report


def audit_problems(level: str = "full") -> list[str]:
    """Every problem as one flat list of strings (lint findings
    rendered file:line)."""
    rep = audit_report(level=level)
    return rep["problems"] + [str(lint.Finding(**f)) for f in rep["lint"]]


def startup_audit(level: str = "static", log=None) -> None:
    """The cheap pre-flight gate for benchmark drivers: raise
    RuntimeError listing every contract drift, so no benchmark number
    is ever published off a drifted layout. Call before the first
    timed segment; costs a few eval_shape traces and three AST parses
    (no device programs, no compiles)."""
    probs = audit_problems(level=level)
    if probs:
        raise RuntimeError(
            "static engine-contract audit failed — refusing to run on a "
            "drifted layout (scripts/static_audit.py for the report):\n  "
            + "\n  ".join(probs))
    if log is not None:
        log(f"static audit ok ({level}): contracts, byte model, and "
            f"purity lint all clean")
