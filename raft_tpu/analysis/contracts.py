"""Leaf-contract auditor: proves the pytree definitions, the kernel
wire registries, the shard rule, and the checkpoint format agree —
statically, before anything runs (DESIGN.md §11).

The repo's cross-engine contracts are REGISTRIES — tuples of leaf
names whose order IS the wire order — plus a handful of derived rules
(the `kleaf_spec` "shard dim -2" rule, `checkpoint._optional_fields`,
the cfg-gating "clients-off means the leaf is absent on all three
engines" table). Every pass here compares one registry against the
ground truth it mirrors, derived from the NamedTuple definitions and
`jax.eval_shape` traces (no device, no tick), and returns problem
strings naming the leaf AND the registry that drifted.

Every pass takes its inputs as parameters with the real definitions as
defaults, so the synthetic-drift tests (tests/test_analysis.py) can
hand in a State copy with a fake leaf — or a checkpoint module that
forgot a backfill — and assert the auditor names it.
"""

from __future__ import annotations

import dataclasses
import io
import json

from raft_tpu.config import RaftConfig

# The statically-gated leaf table: gate name -> (mailbox fields,
# PerNode fields, State fields) that must exist IFF the gate is on —
# on the XLA pytree (None otherwise), in the kernel registries, and
# (sessions) on the CPU oracle. This is the one hand-written table the
# auditor itself carries; everything else is derived. A new gated
# feature adds a row here and the gating pass then enforces it across
# all three engines and the checkpoint optional-field set.
GATED_LEAVES = {
    "prevote": (("pv_req_present", "pv_req_term", "pv_req_lli",
                 "pv_req_llt", "pv_resp_present", "pv_resp_term",
                 "pv_resp_req_term", "pv_resp_granted"), (), ()),
    "transfer": (("tn_present", "tn_term"), (), ()),
    "clients": (("is_req_snap_sessions",),
                ("session_seq", "snap_session_seq"),
                ("clients",)),
    # Bounded admission control (r20, DESIGN.md §19) gates exactly one
    # leaf INSIDE the clients subtree: the shed reject ledger. State
    # entries with a dot are literal leaf dot-paths; the gate stacks on
    # "clients" (its baseline below is the clients-on universe).
    "admission": ((), (), ("clients.shed",)),
    # The nemesis scenario compiler (DESIGN.md §14) gates NOTHING: a
    # compiled program is pure hash masks over existing schedules —
    # zero new State leaves, zero new wire lanes. The empty row is the
    # contract (like read_every), enforced by the gating pass AND by
    # nemesis_problems below.
    "nemesis": ((), (), ()),
    # Cohort streaming (DESIGN.md §15) gates NOTHING either: the
    # residency knobs (config.STREAM_FIELDS) only move where the wire
    # LIVES between chunk launches — zero new State leaves, zero new
    # wire lanes. The empty row is the contract (like read_every and
    # nemesis), enforced by the gating pass AND by streaming_problems
    # below.
    "streaming": ((), (), ()),
}


def _nemesis_probe_program() -> tuple:
    """A program exercising every clause kind — the gating/nemesis
    passes' probe (built inline; analysis must not import the nemesis
    package at module level)."""
    from raft_tpu.nemesis.program import (clock_skew, compaction_pressure,
                                          crash_storm, disk_full_follower,
                                          flaky_link, partition_wave,
                                          program, slow_follower,
                                          wan_delay)
    return program(slow_follower(0, 64), flaky_link(0, 64),
                   wan_delay(0, 64), clock_skew(0, 64),
                   crash_storm(0, 64), partition_wave(0, 64),
                   disk_full_follower(0, 64), compaction_pressure(0, 64))


def _base_cfg() -> RaftConfig:
    return RaftConfig(n_groups=2, k=3, seed=3, log_cap=8, compact_every=4)


def _gate_cfgs() -> dict:
    """gate name -> the config that turns exactly that gate on."""
    base = _base_cfg()
    return {
        "prevote": dataclasses.replace(base, prevote=True),
        "transfer": dataclasses.replace(base, transfer_prob=0.5),
        "clients": dataclasses.replace(base, sessions=True,
                                       cmds_per_tick=0, client_rate=0.3,
                                       client_slots=2),
        "admission": dataclasses.replace(base, sessions=True,
                                         cmds_per_tick=0, client_rate=0.3,
                                         client_slots=2,
                                         client_queue_cap=4),
        "nemesis": dataclasses.replace(base,
                                       nemesis=_nemesis_probe_program()),
        "streaming": dataclasses.replace(base, stream_groups=True,
                                         cohort_blocks=1),
    }


def _leaf_names(cfg: RaftConfig) -> set:
    """Dot-path names of the non-None State leaves under `cfg`
    (eval_shape — abstract, device-free)."""
    import jax

    from raft_tpu import sim
    from raft_tpu.analysis.bytemodel import iter_named_leaves
    st = jax.eval_shape(lambda: sim.init(cfg, n_groups=2))
    return {name for name, _ in iter_named_leaves(st)}


# --------------------------------------------------- metric-surface parity


def metric_parity_problems() -> list[str]:
    """The static Metrics == KMetrics == METRIC_LEAVES / Flight /
    ClientState parity check — the former scripts/check_metric_parity.py
    body, now one pass of the auditor (the script is a thin wrapper)."""
    import jax.numpy as jnp

    from raft_tpu.clients.state import (ADMISSION_LEAVES, CLIENT_LEAVES,
                                        ClientState, active_client_leaves,
                                        clients_init)
    from raft_tpu.obs.recorder import (FLIGHT_LEAVES, RING, Flight,
                                       flight_init)
    from raft_tpu.sim.pkernel import (CLIENT_METRIC_LEAVES, KMetrics,
                                      METRIC_LEAVES, N_METRIC_LEAVES,
                                      _active_metric_leaves)
    from raft_tpu.sim.run import HIST_SIZE, Metrics, metrics_init

    problems = []
    if KMetrics._fields != METRIC_LEAVES:
        problems.append(f"KMetrics fields {KMetrics._fields} != wire order "
                        f"METRIC_LEAVES {METRIC_LEAVES}")
    if set(Metrics._fields) != set(METRIC_LEAVES):
        problems.append(f"Metrics fields {sorted(Metrics._fields)} != "
                        f"METRIC_LEAVES names {sorted(METRIC_LEAVES)}")
    if N_METRIC_LEAVES != len(METRIC_LEAVES):
        problems.append("N_METRIC_LEAVES out of sync with METRIC_LEAVES")
    if Flight._fields != FLIGHT_LEAVES:
        problems.append(f"Flight fields {Flight._fields} != wire order "
                        f"FLIGHT_LEAVES {FLIGHT_LEAVES}")
    if ClientState._fields != CLIENT_LEAVES + ADMISSION_LEAVES:
        problems.append(f"ClientState fields {ClientState._fields} != wire "
                        f"order CLIENT_LEAVES {CLIENT_LEAVES} + admission "
                        f"leaves {ADMISSION_LEAVES}")

    # The active wire subset must drop EXACTLY the client lanes when
    # clients are off, and be the full tuple when on.
    cfg_off = RaftConfig(seed=1)
    cfg_on = RaftConfig(seed=1, sessions=True, cmds_per_tick=0,
                        client_rate=0.2, client_slots=3)
    if _active_metric_leaves(cfg_on) != METRIC_LEAVES:
        problems.append("clients-on active metric leaves != METRIC_LEAVES")
    want_off = tuple(n for n in METRIC_LEAVES
                     if n not in CLIENT_METRIC_LEAVES)
    if _active_metric_leaves(cfg_off) != want_off:
        problems.append(f"clients-off active metric leaves "
                        f"{_active_metric_leaves(cfg_off)} != {want_off}")

    g = 4
    # The kernel wire is i32 lanes: every metric leaf must be i32, with
    # the shapes kinit folds ([G] per-group, scalar, or [H] histogram);
    # client lanes None with clients off, concrete with clients on.
    want_shape = {"committed": (g,), "leaderless": (g,), "elections": (),
                  "hist": (HIST_SIZE,), "max_latency": (), "safety": (g,),
                  "client_acked": (g,), "client_retries": (g,),
                  "client_hist": (HIST_SIZE,), "client_max_lat": ()}
    for clients in (False, True):
        m = metrics_init(g, clients=clients)
        for name in Metrics._fields:
            leaf = getattr(m, name)
            if leaf is None:
                if clients or name not in CLIENT_METRIC_LEAVES:
                    problems.append(f"Metrics.{name} unexpectedly None "
                                    f"(clients={clients})")
                continue
            if not clients and name in CLIENT_METRIC_LEAVES:
                problems.append(f"Metrics.{name} present with clients off")
            if leaf.dtype != jnp.int32:
                problems.append(f"Metrics.{name} dtype {leaf.dtype} != "
                                f"int32 (kernel wire lanes are i32)")
            if leaf.shape != want_shape[name]:
                problems.append(f"Metrics.{name} shape {leaf.shape} != "
                                f"{want_shape[name]}")
    cfg_adm = dataclasses.replace(cfg_on, client_queue_cap=4)
    for label, c in (("cap-off", cfg_on), ("cap-on", cfg_adm)):
        cs = clients_init(c, g)
        active = active_client_leaves(c)
        for name in ClientState._fields:
            leaf = getattr(cs, name)
            if name not in active:
                if leaf is not None:
                    problems.append(f"[{label}] ClientState.{name} present "
                                    f"with its admission gate off")
                continue
            if leaf is None:
                problems.append(f"[{label}] ClientState.{name} is None but "
                                f"active_client_leaves lists it")
                continue
            if leaf.dtype != jnp.int32:
                problems.append(f"[{label}] ClientState.{name} dtype "
                                f"{leaf.dtype} != i32")
            if leaf.shape != (g, c.client_slots):
                problems.append(f"[{label}] ClientState.{name} shape "
                                f"{leaf.shape} != {(g, c.client_slots)}")
    f = flight_init(g)
    for name in Flight._fields:
        leaf = getattr(f, name)
        if leaf.dtype != jnp.int32:
            problems.append(f"Flight.{name} dtype {leaf.dtype} != int32")
        if leaf.shape != (RING, g):
            problems.append(f"Flight.{name} shape {leaf.shape} != "
                            f"{(RING, g)}")
    return problems


# ----------------------------------------------------- wire registries


def wire_registry_problems(pernode_fields: tuple | None = None,
                           mailbox_fields: tuple | None = None,
                           client_fields: tuple | None = None) -> list[str]:
    """The kernel wire registries (`_node_leaves` / `_mb_fields` /
    `CLIENT_LEAVES` / `_MB_BOOL` / `_n_state_leaves` /
    `PRESENCE_FIELDS`) against the pytree definitions. Pass a drifted
    field tuple (e.g. PerNode._fields + ('ghost',)) to prove the
    auditor names the leaf — the synthetic-drift hook."""
    import jax
    import numpy as np

    from raft_tpu import sim
    from raft_tpu.clients.state import (ADMISSION_LEAVES, CLIENT_LEAVES,
                                        ClientState)
    from raft_tpu.obs.recorder import PRESENCE_FIELDS
    from raft_tpu.sim import pkernel
    from raft_tpu.sim.state import Mailbox, PerNode

    pernode_fields = PerNode._fields if pernode_fields is None \
        else tuple(pernode_fields)
    mailbox_fields = Mailbox._fields if mailbox_fields is None \
        else tuple(mailbox_fields)
    client_fields = ClientState._fields if client_fields is None \
        else tuple(client_fields)

    problems = []
    sess_fields = ("session_seq", "snap_session_seq")
    cfgs = {"clients-off": _base_cfg(), "clients-on": _gate_cfgs()["clients"],
            "clients-admission": _gate_cfgs()["admission"]}
    all_on = dataclasses.replace(
        _gate_cfgs()["clients"], prevote=True, transfer_prob=0.5,
        read_every=4)

    for label, cfg in cfgs.items():
        clients = cfg.clients_u32 != 0
        reg = [f for f, _ in pkernel._node_leaves(cfg)]
        want = [f for f in pernode_fields
                if clients or f not in sess_fields]
        if reg != want:
            missing = [f for f in want if f not in reg]
            extra = [f for f in reg if f not in want]
            problems.append(
                f"[{label}] pkernel._node_leaves {'misses ' + str(missing) if missing else ''}"
                f"{' carries stale ' + str(extra) if extra else ''}"
                f"{' (order drift)' if not missing and not extra else ''} "
                f"vs PerNode._fields")
        reg_mb = pkernel._mb_fields(cfg)
        gated_mb = set()
        for gate, (mb, _, _) in GATED_LEAVES.items():
            on = {"prevote": cfg.prevote,
                  "transfer": cfg.transfer_u32 != 0,
                  "clients": clients,
                  "admission": cfg.client_queue_cap > 0,
                  "nemesis": bool(cfg.nemesis),
                  "streaming": cfg.stream_groups}[gate]
            if not on:
                gated_mb.update(mb)
        want_mb = [f for f in mailbox_fields if f not in gated_mb]
        if reg_mb != want_mb:
            missing = [f for f in want_mb if f not in reg_mb]
            extra = [f for f in reg_mb if f not in want_mb]
            problems.append(
                f"[{label}] pkernel._mb_fields misses {missing} / carries "
                f"stale {extra} vs Mailbox._fields under this cfg")
        # Leaf count promised to the kernel launch vs the registries
        # (the admission-gated shed leaf rides the wire only cap-on).
        n_cl = len(client_fields) if clients else 0
        if clients and cfg.client_queue_cap == 0:
            n_cl -= len(ADMISSION_LEAVES)
        n = len(reg) + len(reg_mb) + 2 + n_cl
        if pkernel._n_state_leaves(cfg) != n:
            problems.append(
                f"[{label}] pkernel._n_state_leaves {pkernel._n_state_leaves(cfg)} "
                f"!= node {len(reg)} + mailbox {len(reg_mb)} + client "
                f"{n_cl} + alive_prev + group_id = {n}")

        # Kind table vs the real per-leaf shapes (eval_shape).
        st = jax.eval_shape(lambda c=cfg: sim.init(c, n_groups=2))
        kind_shape = {"scalar": (cfg.k,), "peer": (cfg.k, cfg.k),
                      "ring": (cfg.k, cfg.log_cap),
                      "sess": (cfg.k, cfg.client_slots)}
        for f, kind in pkernel._node_leaves(cfg):
            leaf = getattr(st.nodes, f, None)
            if leaf is None:
                problems.append(f"[{label}] pkernel._node_leaves lists "
                                f"{f!r} but PerNode has no such leaf under "
                                f"this cfg")
                continue
            if tuple(leaf.shape[1:]) != kind_shape[kind]:
                problems.append(
                    f"[{label}] pkernel._node_leaves files {f!r} as "
                    f"{kind!r} ({kind_shape[kind]}) but its shape is "
                    f"{tuple(leaf.shape[1:])}")

    # Bool / u32 casting tables, derived from the all-features-on dtypes.
    st_on = jax.eval_shape(lambda: sim.init(all_on, n_groups=2))
    mb_bool = tuple(f for f in mailbox_fields
                    if getattr(st_on.mailbox, f, None) is not None
                    and np.dtype(getattr(st_on.mailbox, f).dtype)
                    == np.bool_)
    if set(mb_bool) != set(pkernel._MB_BOOL):
        problems.append(
            f"pkernel._MB_BOOL {sorted(pkernel._MB_BOOL)} != the bool "
            f"Mailbox leaves {sorted(mb_bool)} — kfinish would narrow the "
            f"wrong set")
    presence = tuple(f for f in mailbox_fields if f.endswith("_present")
                     or f == "tn_present")
    if set(presence) != set(PRESENCE_FIELDS):
        problems.append(
            f"obs.recorder.PRESENCE_FIELDS {sorted(PRESENCE_FIELDS)} != the "
            f"mailbox occupancy leaves {sorted(presence)} — the flight "
            f"recorder's message-volume signal would miss a message type")
    if client_fields != CLIENT_LEAVES + ADMISSION_LEAVES:
        problems.append(f"CLIENT_LEAVES {CLIENT_LEAVES} + admission leaves "
                        f"{ADMISSION_LEAVES} != ClientState fields "
                        f"{client_fields}")
    return problems


# ------------------------------------------------------------ cfg gating


def gating_problems() -> list[str]:
    """Clients-off (and prevote-/transfer-off) must mean THE LEAF IS
    ABSENT on all three engines: None in the XLA pytree, missing from
    the kernel wire registries, empty on the CPU oracle — and flipping
    one gate must change EXACTLY its gated leaves, nothing else."""
    from raft_tpu.core.cluster import Cluster
    from raft_tpu.sim import pkernel

    problems = []
    base = _base_cfg()
    base_names = _leaf_names(base)
    # Gates that stack on another gate compare against THAT gate's
    # universe, not the all-off base (admission requires clients on).
    gate_base = {"admission": "clients"}
    for gate, cfg_on in _gate_cfgs().items():
        mb, nd, st_fields = GATED_LEAVES[gate]
        expect_new = {f"mailbox.{f}" for f in mb}
        expect_new |= {f"nodes.{f}" for f in nd}
        for f in st_fields:
            if f == "clients":
                from raft_tpu.clients.state import CLIENT_LEAVES
                expect_new |= {f"clients.{x}" for x in CLIENT_LEAVES}
            else:
                expect_new.add(f)   # literal leaf dot-path (clients.shed)
        ref_names = base_names if gate not in gate_base \
            else _leaf_names(_gate_cfgs()[gate_base[gate]])
        on_names = _leaf_names(cfg_on)
        got_new = on_names - ref_names
        if got_new != expect_new:
            problems.append(
                f"gate {gate!r}: turning it on adds leaves "
                f"{sorted(got_new)} but the gating table promises "
                f"{sorted(expect_new)}")
        if ref_names - on_names:
            problems.append(f"gate {gate!r}: turning it on REMOVES leaves "
                            f"{sorted(ref_names - on_names)}")
        # Kernel registries mirror the same gate.
        for f in mb:
            if f in pkernel._mb_fields(base):
                problems.append(f"gate {gate!r}: mailbox leaf {f} on the "
                                f"kernel wire with the gate off")
            if f not in pkernel._mb_fields(cfg_on):
                problems.append(f"gate {gate!r}: mailbox leaf {f} missing "
                                f"from the kernel wire with the gate on")
        node_off = [f for f, _ in pkernel._node_leaves(base)]
        node_on = [f for f, _ in pkernel._node_leaves(cfg_on)]
        for f in nd:
            if f in node_off:
                problems.append(f"gate {gate!r}: node leaf {f} on the "
                                f"kernel wire with the gate off")
            if f not in node_on:
                problems.append(f"gate {gate!r}: node leaf {f} missing "
                                f"from the kernel wire with the gate on")
    # read_every is deliberately NOT gated (stable trace surface) — a
    # leaf appearing under it would silently break pre-r05 programs.
    reads_on = dataclasses.replace(base, read_every=4)
    if _leaf_names(reads_on) != base_names:
        problems.append("read_every gates State leaves — the scheduled-"
                        "read state is contractually always-present")
    # Metric client lanes follow the clients gate (checked shape-level
    # by metric_parity_problems; membership here).
    if set(pkernel._active_metric_leaves(base)) \
            & set(pkernel.CLIENT_METRIC_LEAVES):
        problems.append("client metric lanes on the wire with clients off")
    missing = set(pkernel.CLIENT_METRIC_LEAVES) \
        - set(pkernel._active_metric_leaves(_gate_cfgs()["clients"]))
    if missing:
        problems.append(f"client metric lanes {sorted(missing)} missing "
                        f"from the wire with clients on")
    # CPU oracle: the session tables exist (pre-registered) iff the
    # scheduled-client gate is on.
    c_off = Cluster(base)
    c_on = Cluster(_gate_cfgs()["clients"])
    if c_off.nodes[0].sessions or c_off.nodes[0].snap_sessions:
        problems.append("oracle Node carries session tables with the "
                        "clients gate off")
    s = _gate_cfgs()["clients"].client_slots
    want_tab = {i: -1 for i in range(s)}
    if c_on.nodes[0].sessions != want_tab \
            or c_on.nodes[0].snap_sessions != want_tab:
        problems.append(
            f"oracle Node pre-registered tables {c_on.nodes[0].sessions} != "
            f"the batched init's slots 0..{s - 1} at -1")
    return problems


# ------------------------------------------------------------ shard rule


def shard_rule_problems() -> list[str]:
    """parallel.kmesh.kleaf_spec must place EVERY wire leaf: each leaf
    of the real kinit output (eval_shape) must carry the folded
    [..., GS, LANE] layout, and the spec must shard exactly dim -2 on
    the group axis."""
    import jax

    from raft_tpu import sim
    from raft_tpu.obs.recorder import flight_init
    from raft_tpu.parallel.kmesh import kleaf_spec
    from raft_tpu.parallel.mesh import AXIS
    from raft_tpu.sim import pkernel

    problems = []
    for label, cfg in (("clients-off", _base_cfg()),
                       ("clients-on", _gate_cfgs()["clients"])):
        st = jax.eval_shape(lambda c=cfg: sim.init(c, n_groups=2))
        fl = jax.eval_shape(lambda: flight_init(2))
        leaves = jax.eval_shape(
            lambda s, f, c=cfg: pkernel.kinit(c, s, None, f)[0], st, fl)
        for i, leaf in enumerate(leaves):
            shape = tuple(leaf.shape)
            if len(shape) < 2 or shape[-1] != pkernel.LANE \
                    or shape[-2] % pkernel.SUB:
                problems.append(
                    f"[{label}] wire leaf #{i}: shape {shape} is not the "
                    f"folded [..., GS, {pkernel.LANE}] layout kleaf_spec "
                    f"shards")
                continue
            spec = tuple(kleaf_spec(leaf))
            want = tuple([None] * (len(shape) - 2) + [AXIS, None])
            if spec != want:
                problems.append(
                    f"[{label}] wire leaf #{i}: kleaf_spec {spec} does not "
                    f"place the folded GS axis (want {want})")
    return problems


# ------------------------------------------------------------ checkpoint


def checkpoint_problems(ckpt_mod=None,
                        include_behavioral: bool = True) -> list[str]:
    """checkpoint.save/load coverage: the optional-field sets must be
    exactly the statically-gated leaves; behaviorally (tiny G, host
    npz in memory), a round trip must be exact, pre-r07/r09 files must
    backfill (safety -> ones, client lanes -> zeros, missing cfg knobs
    -> defaults), and a missing REQUIRED leaf must raise naming the
    field. Pass `ckpt_mod` (a save/load namespace) to audit a drifted
    implementation — the synthetic-drift hook."""
    import numpy as np

    from raft_tpu.clients.state import ClientState
    from raft_tpu.sim import checkpoint as real_ckpt
    from raft_tpu.sim.state import Mailbox, PerNode

    ckpt = real_ckpt if ckpt_mod is None else ckpt_mod
    problems = []

    # Static: optional == statically-gated, per class.
    gated_mb, gated_nd = set(), set()
    for mb, nd, _ in GATED_LEAVES.values():
        gated_mb.update(mb)
        gated_nd.update(nd)
    if real_ckpt._optional_fields(Mailbox) != frozenset(gated_mb):
        problems.append(
            f"checkpoint._optional_fields(Mailbox) "
            f"{sorted(real_ckpt._optional_fields(Mailbox))} != the "
            f"statically-gated mailbox leaves {sorted(gated_mb)}")
    if real_ckpt._optional_fields(PerNode) != frozenset(gated_nd):
        problems.append(
            f"checkpoint._optional_fields(PerNode) "
            f"{sorted(real_ckpt._optional_fields(PerNode))} != the "
            f"statically-gated node leaves {sorted(gated_nd)}")
    gated_cl = {f.split(".", 1)[1] for _, _, stf in GATED_LEAVES.values()
                for f in stf if f.startswith("clients.")}
    if real_ckpt._optional_fields(ClientState) != frozenset(gated_cl):
        problems.append(
            f"checkpoint._optional_fields(ClientState) "
            f"{sorted(real_ckpt._optional_fields(ClientState))} != the "
            f"statically-gated client leaves {sorted(gated_cl)} — the "
            f"clients subtree is otherwise all-or-nothing (a spurious "
            f"optional leaf would load as None and crash the workload "
            f"transition)")
    if not include_behavioral:
        return problems

    from raft_tpu import sim
    from raft_tpu.analysis.bytemodel import iter_named_leaves
    from raft_tpu.sim.run import metrics_init

    def roundtrip(cfg, strip=(), patch_cfg=None, expect_raise=None,
                  load_cfg="same"):
        """save -> optionally strip npz keys -> load. Returns
        (state, tick, metrics) or the raised exception."""
        st = sim.init(cfg, n_groups=2)
        met = metrics_init(2, clients=cfg.clients_u32 != 0)
        buf = io.BytesIO()
        ckpt.save(buf, st, 7, metrics=met, cfg=cfg)
        buf.seek(0)
        if strip or patch_cfg:
            with np.load(buf) as z:
                ghost = [k for k in strip if k not in z.files]
                if ghost:
                    # A rename in checkpoint._flatten's key scheme would
                    # otherwise turn the backfill checks vacuous: the
                    # strip removes nothing, load sees a complete file,
                    # and the pass reports clean without exercising the
                    # backfill at all.
                    problems.append(
                        f"backfill check could not strip {ghost} — the "
                        f"checkpoint key naming moved and the auditor's "
                        f"strip targets went stale")
                data = {k: z[k] for k in z.files if k not in strip}
            if patch_cfg:
                saved = json.loads(bytes(data["__cfg__"]).decode())
                for k in patch_cfg:
                    saved.pop(k, None)
                data["__cfg__"] = np.bytes_(json.dumps(saved,
                                                       sort_keys=True))
            buf = io.BytesIO()
            np.savez(buf, **data)
            buf.seek(0)
        try:
            out = ckpt.load(buf, cfg=cfg if load_cfg == "same" else load_cfg)
        except Exception as e:  # noqa: BLE001 — audited, not handled
            if expect_raise and isinstance(e, expect_raise):
                return e
            problems.append(f"checkpoint load raised {type(e).__name__}: "
                            f"{e} (cfg={cfg_label}, strip={sorted(strip)})")
            return None
        if expect_raise:
            problems.append(
                f"checkpoint load SUCCEEDED where it must refuse "
                f"(cfg={cfg_label}, strip={sorted(strip)}) — a corrupt or "
                f"mismatched file would resume silently")
        return (st, met, out)

    all_on = dataclasses.replace(
        _gate_cfgs()["clients"], prevote=True, transfer_prob=0.5,
        read_every=4)
    for cfg_label, cfg in (("base", _base_cfg()), ("all-on", all_on)):
        r = roundtrip(cfg)
        if r is None:
            continue
        st, met, (st2, t2, met2) = r
        if t2 != 7:
            problems.append(f"[{cfg_label}] round-trip lost the tick "
                            f"counter ({t2} != 7)")
        for (name, a), (_, b) in zip(iter_named_leaves(st),
                                     iter_named_leaves(st2)):
            if not np.array_equal(np.asarray(a), np.asarray(b)):
                problems.append(f"[{cfg_label}] round-trip changed state "
                                f"leaf {name}")
        for (name, a), (_, b) in zip(iter_named_leaves(met),
                                     iter_named_leaves(met2)):
            if not np.array_equal(np.asarray(a), np.asarray(b)):
                problems.append(f"[{cfg_label}] round-trip changed metric "
                                f"leaf {name}")

    # Pre-r07 backfill: a file without metrics.safety resumes with a
    # clean (all-ones) safety fold.
    cfg_label = "base"
    r = roundtrip(_base_cfg(), strip=("metrics.safety",))
    ok = False
    if r is not None and not isinstance(r, Exception):
        _, _, (_, _, met2) = r
        ok = met2 is not None and np.all(np.asarray(met2.safety) == 1)
    if not ok:
        problems.append("pre-r07 backfill drift: loading a checkpoint "
                        "without metrics.safety must fill ones "
                        "(registry: checkpoint.load safety backfill)")

    # Pre-r09 backfill: a client universe whose file predates the SLO
    # lanes resumes with zeroed lanes.
    cfg_label = "all-on"
    client_lanes = ("metrics.client_acked", "metrics.client_retries",
                    "metrics.client_hist", "metrics.client_max_lat")
    r = roundtrip(all_on, strip=client_lanes)
    ok = False
    if r is not None and not isinstance(r, Exception):
        _, _, (_, _, met2) = r
        ok = (met2 is not None
              and met2.client_acked is not None
              and np.all(np.asarray(met2.client_acked) == 0)
              and met2.client_hist is not None
              and np.all(np.asarray(met2.client_hist) == 0))
    if not ok:
        problems.append("pre-r09 backfill drift: loading a client "
                        "checkpoint without the client metric lanes "
                        "must fill zeros (registry: checkpoint.load "
                        "client-lane backfill)")

    # Pre-r09/r14 cfg backfill: a saved cfg dict missing a later-added
    # knob (client knobs; the r14 nemesis program) loads against that
    # knob's default.
    cfg_label = "base"
    r = roundtrip(_base_cfg(), patch_cfg=("client_rate", "client_slots",
                                          "nemesis"))
    if r is None or isinstance(r, Exception):
        problems.append("cfg-default backfill drift: a checkpoint whose "
                        "embedded cfg predates a knob must load against "
                        "the knob's default (registry: checkpoint.load "
                        "cfg setdefault)")
    # ...and the converse must REFUSE: a nemesis-on run resuming a
    # pre-r14 file (whose embedded cfg backfills to nemesis=[]) would
    # silently continue a DIFFERENT universe schedule.
    roundtrip(_base_cfg(), patch_cfg=("nemesis",),
              load_cfg=dataclasses.replace(
                  _base_cfg(), nemesis=_nemesis_probe_program()),
              expect_raise=(ValueError,))

    # r20 admission: an admission-on universe round-trips its shed
    # ledger exactly...
    cfg_label = "admission"
    adm = _gate_cfgs()["admission"]
    r = roundtrip(adm)
    if r is not None and not isinstance(r, Exception):
        st, _, (st2, _, _) = r
        if st2.clients.shed is None or not np.array_equal(
                np.asarray(st.clients.shed), np.asarray(st2.clients.shed)):
            problems.append("admission round trip lost or changed the "
                            "clients.shed ledger")
    # ...a pre-r20 file (no shed leaf, no client_queue_cap knob —
    # synthesized by stripping both from a cap-on save, so the strip
    # guard proves the key names are live) loads under a cap-off cfg
    # with the knob backfilled to its default...
    cfg_label = "admission"
    r = roundtrip(adm, strip=("state.clients.shed",),
                  patch_cfg=("client_queue_cap",),
                  load_cfg=_gate_cfgs()["clients"])
    if r is None or isinstance(r, Exception):
        problems.append("pre-r20 backfill drift: a client checkpoint "
                        "predating admission control must load under a "
                        "cap-off cfg (registry: checkpoint.load cfg "
                        "setdefault + ClientState optional shed)")
    else:
        _, _, (st2, _, _) = r
        if st2.clients.shed is not None:
            problems.append("pre-r20 file loaded a phantom clients.shed "
                            "leaf under a cap-off cfg")
    # ...and REFUSES under a cap-on cfg: admission changes what the
    # transition computes, so the semantics differ.
    roundtrip(adm, strip=("state.clients.shed",),
              patch_cfg=("client_queue_cap",),
              load_cfg=adm, expect_raise=(ValueError,))

    # Strictness: a missing REQUIRED leaf must raise, naming the field.
    r = roundtrip(_base_cfg(), strip=("state.nodes.term",),
                  expect_raise=(KeyError,))
    if isinstance(r, Exception) and "state.nodes.term" not in str(r):
        problems.append(f"missing-leaf error does not name the field: {r}")
    # A mismatched semantic cfg must refuse to resume.
    roundtrip(_base_cfg(), load_cfg=dataclasses.replace(_base_cfg(),
                                                        seed=99),
              expect_raise=(ValueError,))
    return problems


# ------------------------------------------------------ packed wire layout


def _packed_cfgs() -> dict:
    """label -> a config exercising each r13 layout dial combination
    the packing pass audits (built on the small `_base_cfg` universe
    so every derived check stays eval_shape-cheap)."""
    base = _base_cfg()
    return {
        "pack_bools": dataclasses.replace(base, pack_bools=True),
        "pack_ring": dataclasses.replace(base, pack_ring=True),
        "packed": dataclasses.replace(base, pack_bools=True,
                                      pack_ring=True),
        "ceiling": dataclasses.replace(base, pack_bools=True,
                                       pack_ring=True, alias_wire=True,
                                       wire_hist=False),
        "packed-clients": dataclasses.replace(
            _gate_cfgs()["clients"], pack_bools=True, pack_ring=True),
    }


def packing_problems(include_behavioral: bool = True) -> list[str]:
    """The r13 packed-wire contracts (DESIGN.md §13):

    - layout dials are LAYOUT-ONLY — flipping any of them changes zero
      State pytree leaves (the XLA/oracle programs cannot see them);
    - the packed wire registry's leaf count matches independent
      arithmetic (mailbox bools collapse to ONE shared lane, pack_ring
      adds exactly the base lane) and the real kinit output under
      eval_shape emits exactly that many leaves, every one in the
      folded [..., GS, LANE] layout `kleaf_spec` shards;
    - the wire_hist dial drops exactly the [H]-row metric leaves;
    - (behavioral) `_pack_wire`/`_unpack_wire` round-trip a synthetic
      non-trivial wire EXACTLY, and a checkpoint written under one
      layout loads under any other (config.LAYOUT_FIELDS are excluded
      from the semantic match).
    """
    import jax
    import numpy as np

    from raft_tpu import sim
    from raft_tpu.clients.state import active_client_leaves
    from raft_tpu.obs.recorder import flight_init
    from raft_tpu.sim import pkernel
    from raft_tpu.sim.pkernel import LANE, ROW_METRIC_LEAVES

    problems = []
    base = _base_cfg()
    base_names = _leaf_names(base)
    for label, cfg in _packed_cfgs().items():
        # Dials never touch the State pytree (clients gate aside —
        # compare against the matching packing-off config).
        off = dataclasses.replace(
            cfg, pack_bools=False, pack_ring=False, alias_wire=False,
            wire_hist=True)
        ref_names = base_names if off == base else _leaf_names(off)
        if _leaf_names(cfg) != ref_names:
            problems.append(
                f"[{label}] layout dials changed State pytree leaves — "
                f"they must be invisible to the XLA/oracle engines")
        # Independent leaf-count arithmetic vs the packed registry.
        n_mb_bools = len([f for f in pkernel._mb_fields(cfg)
                          if f in pkernel._MB_BOOL])
        expect = (len(pkernel._node_leaves(cfg))
                  + len(pkernel._mb_fields(cfg)) + 2
                  + (len(active_client_leaves(cfg))
                     if cfg.clients_u32 else 0))
        if cfg.pack_bools:
            expect -= n_mb_bools - 1     # bools collapse to ONE lane leaf
        if cfg.pack_ring:
            expect += 1                  # the base/overflow lane
        if pkernel._n_state_leaves(cfg) != expect:
            problems.append(
                f"[{label}] packed wire registry has "
                f"{pkernel._n_state_leaves(cfg)} state leaves; independent "
                f"arithmetic expects {expect}")
        # Real kinit output: count AND folded layout (the shard rule).
        st = jax.eval_shape(lambda c=cfg: sim.init(c, n_groups=2))
        fl = jax.eval_shape(lambda: flight_init(2))
        leaves = jax.eval_shape(
            lambda s, f, c=cfg: pkernel.kinit(c, s, None, f)[0], st, fl)
        want_n = (pkernel._n_state_leaves(cfg) + 6
                  + pkernel._n_metric_leaves(cfg))
        if len(leaves) != want_n:
            problems.append(
                f"[{label}] kinit emitted {len(leaves)} wire leaves; the "
                f"packed registries promise {want_n}")
        for i, leaf in enumerate(leaves):
            shape = tuple(leaf.shape)
            if len(shape) < 2 or shape[-1] != LANE \
                    or shape[-2] % pkernel.SUB:
                problems.append(
                    f"[{label}] wire leaf #{i}: shape {shape} is not the "
                    f"folded [..., GS, {LANE}] layout kleaf_spec shards")
        # wire_hist drops exactly the row leaves.
        no_hist = dataclasses.replace(cfg, wire_hist=False)
        want_active = tuple(n for n in pkernel._active_metric_leaves(cfg)
                            if n not in ROW_METRIC_LEAVES)
        if pkernel._active_metric_leaves(no_hist) != want_active:
            problems.append(
                f"[{label}] wire_hist=False active metric leaves "
                f"{pkernel._active_metric_leaves(no_hist)} != "
                f"{want_active} (must drop exactly the [H]-row leaves)")

    # Behavioral: exact pack/unpack round trip on a synthetic wire
    # whose every lane is distinct-ish (zeros would round-trip through
    # a BROKEN encode too), and the cross-layout checkpoint load.
    if not include_behavioral:
        return problems
    import jax.numpy as jnp

    for label in ("packed", "packed-clients"):
        cfg = _packed_cfgs()[label]
        st = sim.init(cfg, n_groups=LANE)
        flat = pkernel._to_kstate(cfg, st)
        # Fill every lane with a distinct deterministic pattern; bool
        # wire lanes (the bit-pack inputs) clamp to {0, 1}.
        names = pkernel._unpacked_names(cfg)
        booly = set(pkernel._MB_BOOL) | {"votes", "alive_prev"}
        synth = []
        for i, (n, a) in enumerate(zip(names, flat)):
            v = (np.arange(a.size, dtype=np.int64) * (2 * i + 3)) % 5
            if n in booly:
                v = v % 2
            synth.append(jnp.asarray(v.reshape(a.shape), jnp.int32))
        back, _ = pkernel._unpack_wire(cfg, pkernel._pack_wire(cfg, synth))
        for n, a, b in zip(names, synth, back):
            if not np.array_equal(np.asarray(a), np.asarray(b)):
                problems.append(
                    f"[{label}] pack/unpack round trip changed wire leaf "
                    f"{n!r} — the encode is not lossless")
    # A checkpoint saved under one layout loads under another (and a
    # pre-r13 file — no layout keys at all — loads under a packed cfg).
    from raft_tpu.sim import checkpoint as ckpt
    cfg_off = _base_cfg()
    cfg_on = _packed_cfgs()["packed"]
    st = sim.init(cfg_off, n_groups=2)
    buf = io.BytesIO()
    ckpt.save(buf, st, 3, cfg=cfg_off)
    buf.seek(0)
    try:
        ckpt.load(buf, cfg=cfg_on)
    except Exception as e:  # noqa: BLE001 — audited, not handled
        problems.append(
            f"cross-layout checkpoint load raised {type(e).__name__}: {e} "
            f"— config.LAYOUT_FIELDS must be excluded from the semantic "
            f"match (a packed run could never resume a pre-r13 file)")
    return problems


# ------------------------------------------------- narrow-native layout


def _narrow_cfgs() -> dict:
    """label -> config with that narrow dial set on (r19)."""
    base = _base_cfg()
    every = dict(narrow_scalars=True, narrow_ring=True,
                 narrow_mailbox=True, narrow_clients=True)
    return {
        "narrow_scalars": dataclasses.replace(base, narrow_scalars=True),
        "narrow_ring": dataclasses.replace(base, narrow_ring=True),
        "narrow_mailbox": dataclasses.replace(base, narrow_mailbox=True),
        "donate_scan": dataclasses.replace(base, donate_scan=True),
        "narrow-all": dataclasses.replace(base, **every),
        "narrow-clients": dataclasses.replace(_gate_cfgs()["clients"],
                                              **every),
    }


def narrowing_problems(include_behavioral: bool = True) -> list[str]:
    """The r19 narrow-native layout contracts (DESIGN.md §18):

    - the dials are LAYOUT-ONLY in structure: flipping any of them
      changes zero State leaf NAMES or shapes (only resident dtypes),
      and with every dial off `narrow_spec` is empty — the resident
      form is byte-identical to r18;
    - `config_hash` is dial-invariant (a narrow-vs-wide ablation pair
      for one universe must be pairable), and every NARROW_FIELDS dial
      defaults to False;
    - `narrow_spec` agrees with the real narrow init's dtypes leaf by
      leaf and names only leaves that exist (the byte model's four-way
      resident reconciliation, delegated to
      `bytemodel.narrow_model_problems`);
    - the kernel wire is dial-invariant and every wire leaf under a
      narrow cfg still lands in the folded [..., GS, LANE] layout
      `kmesh.kleaf_spec` shards;
    - (behavioral) the overflow latch fires for EVERY narrowed leaf —
      an out-of-range wide value must latch bit 31 of group_id, make
      `check_narrow_overflow` refuse, and stay sticky; and a
      checkpoint hops the narrow axis both ways by NAME, values
      preserved exactly.
    """
    import jax
    import numpy as np

    from raft_tpu import sim
    from raft_tpu.config import NARROW_FIELDS, RaftConfig
    from raft_tpu.obs.manifest import config_hash
    from raft_tpu.sim import pkernel
    from raft_tpu.sim import state as state_mod

    problems = []
    for f in NARROW_FIELDS:
        if getattr(RaftConfig(), f) is not False:
            problems.append(f"narrow dial {f!r} does not default to False "
                            f"— the r18 layout must be the default")
    base = _base_cfg()
    if state_mod.narrow_spec(base) or state_mod.narrow_active(base):
        problems.append("narrow_spec is non-empty with every dial off — "
                        "the wide layout must be exactly r18's")

    def shapes(cfg):
        st = jax.eval_shape(lambda: sim.init(cfg, n_groups=2))
        from raft_tpu.analysis.bytemodel import iter_named_leaves
        return {name: tuple(leaf.shape)
                for name, leaf in iter_named_leaves(st)}

    for label, cfg in _narrow_cfgs().items():
        off = dataclasses.replace(cfg,
                                  **{f: False for f in NARROW_FIELDS})
        if shapes(cfg) != shapes(off):
            problems.append(
                f"[{label}] narrow dials changed State leaf names/shapes "
                f"— they may only re-declare dtypes")
        if config_hash(cfg) != config_hash(off):
            problems.append(
                f"[{label}] config_hash moved under the narrow dials — "
                f"ablation pairs for one universe must hash equal")
        # Wire invariance + shard-rule coverage of the narrow cfg: the
        # kernel computes wide inside the chunk, so kinit's wire leaves
        # must be untouched by the dials and stay kleaf_spec-shardable.
        from raft_tpu.obs.recorder import flight_init
        from raft_tpu.parallel.kmesh import AXIS, kleaf_spec

        def kspecs(c):
            st = jax.eval_shape(lambda: sim.init(c, n_groups=2))
            fl = jax.eval_shape(lambda: flight_init(2))
            return jax.eval_shape(
                lambda s, f: pkernel.kinit(c, s, None, f)[0], st, fl)
        on_leaves, off_leaves = kspecs(cfg), kspecs(off)
        if [(tuple(a.shape), str(a.dtype)) for a in on_leaves] \
                != [(tuple(a.shape), str(a.dtype)) for a in off_leaves]:
            problems.append(
                f"[{label}] kinit's wire leaves moved under the narrow "
                f"dials — the wire is a layout the dials must not touch")
        for i, leaf in enumerate(on_leaves):
            spec = kleaf_spec(leaf)
            if len(spec) != leaf.ndim or spec[-2] != AXIS \
                    or spec[-1] is not None:
                problems.append(
                    f"[{label}] kleaf_spec does not shard wire leaf #{i} "
                    f"(shape {tuple(leaf.shape)}) on the [..., GS, LANE] "
                    f"group axis under the narrow cfg")

    # The four-way resident byte reconciliation (derived / spec-priced /
    # wide-minus-deltas / pinned) + the >= 35% floor + wire-ceiling
    # invariance, at the published configs.
    from raft_tpu.analysis import bytemodel
    problems += bytemodel.narrow_model_problems()

    if not include_behavioral:
        return problems
    import jax.numpy as jnp

    from raft_tpu.utils.trees import trees_equal_values, trees_equal_why

    # Latch coverage: EVERY narrowed leaf, driven out of range, must
    # latch (group 0 only), refuse the host boundary, and stay sticky
    # across a clean re-narrow.
    ncfg = _narrow_cfgs()["narrow-clients"]
    spec = state_mod.narrow_spec(ncfg)
    wide0 = state_mod.widen_state(ncfg, sim.init(ncfg, n_groups=2))
    over = {state_mod.U16: 1 << 16, state_mod.I16: 1 << 15,
            state_mod.I8: 1 << 10}
    for name, dt in sorted(spec.items()):
        def poke(path, leaf, name=name):
            if path != name:
                return leaf
            flat = np.asarray(leaf).copy().reshape(leaf.shape[0], -1)
            flat[0, 0] = over[dt]
            return jnp.asarray(flat.reshape(leaf.shape))
        bad = state_mod._map_named(wide0, "", poke)
        bad = bad._replace(group_id=wide0.group_id)
        narrowed = state_mod.narrow_state(ncfg, bad)
        ov = np.asarray(state_mod.narrow_overflow(narrowed))
        if not (ov[0] and not ov[1:].any()):
            problems.append(
                f"overflow latch missed narrowed leaf {name!r} "
                f"(latched groups: {np.flatnonzero(ov).tolist()})")
            continue
        try:
            state_mod.check_narrow_overflow(ncfg, narrowed)
            problems.append(f"check_narrow_overflow accepted a state "
                            f"latched via {name!r}")
        except ValueError:
            pass
        again = state_mod.narrow_state(
            ncfg, state_mod.widen_state(ncfg, narrowed))
        if not np.asarray(state_mod.narrow_overflow(again))[0]:
            problems.append(f"overflow latch for {name!r} is not sticky "
                            f"across widen/narrow")

    # Narrow init is value-identical to wide init (values-only
    # comparator), and strictly different (the dtypes really moved).
    wide_init = sim.init(dataclasses.replace(
        ncfg, **{f: False for f in NARROW_FIELDS}), n_groups=2)
    narrow_init = sim.init(ncfg, n_groups=2)
    ok, why = trees_equal_why(wide_init, narrow_init, values_only=True)
    if not ok:
        problems.append(f"narrow init diverges from wide init in VALUES: "
                        f"{why}")
    if trees_equal_why(wide_init, narrow_init)[0]:
        problems.append("narrow init is byte-identical to wide init — "
                        "the dials narrowed nothing")

    # Checkpoint narrow-axis hop, both directions, values exact.
    from raft_tpu.sim import checkpoint as ckpt
    for src_cfg, dst_cfg, way in ((ncfg, None, "narrow->wide"),
                                  (None, ncfg, "wide->narrow")):
        wide_cfg = dataclasses.replace(ncfg,
                                       **{f: False for f in NARROW_FIELDS})
        s_cfg = src_cfg or wide_cfg
        d_cfg = dst_cfg or wide_cfg
        st = sim.init(s_cfg, n_groups=2)
        buf = io.BytesIO()
        ckpt.save(buf, st, 5, cfg=s_cfg)
        buf.seek(0)
        try:
            loaded, t, _ = ckpt.load(buf, cfg=d_cfg)
        except Exception as e:  # noqa: BLE001 — audited, not handled
            problems.append(f"checkpoint {way} hop raised "
                            f"{type(e).__name__}: {e}")
            continue
        if t != 5:
            problems.append(f"checkpoint {way} hop lost the tick counter")
        if not trees_equal_values(st, loaded):
            problems.append(f"checkpoint {way} hop changed State VALUES")
        want = sim.init(d_cfg, n_groups=2)
        if not trees_equal_why(want, loaded)[0]:
            problems.append(
                f"checkpoint {way} hop did not land on the destination "
                f"cfg's resident dtypes")
    return problems


# ---------------------------------------------------- nemesis compiler


def nemesis_problems(kinds: tuple | None = None,
                     link_kinds: tuple | None = None,
                     crash_kinds: tuple | None = None,
                     timing_kinds: tuple | None = None,
                     disk_kinds: tuple | None = None,
                     compact_kinds: tuple | None = None) -> list[str]:
    """The nemesis scenario compiler's contracts (DESIGN.md §14):

    - compiled programs add ZERO leaves — GATED_LEAVES carries the
      empty 'nemesis' row, a kinds-complete program changes neither the
      State pytree nor any kernel wire registry nor the byte model
      (kleaf_spec has nothing new to cover, proven by the counts);
    - the seam partition is TOTAL: every clause kind is routed to
      exactly one engine seam (link / crash / timing filter, or the
      r20 storage seams — the per-append disk budget and the phase-A
      compaction gate) — a kind in none would be a silently-ignored
      clause, a kind in two would double-apply;
    - the program builders cover every kind and `RaftConfig` normalizes
      a JSON-round-tripped program back to the identical hashable form;
    - utils.rng / utils.jrng evaluator parity rides the existing
      rng_parity pass (same exports, same signatures).

    Pass drifted kind tuples to prove the auditor names the drift —
    the synthetic-drift hook (tests/test_analysis.py pattern)."""
    import jax

    from raft_tpu import sim
    from raft_tpu.nemesis.program import KIND_NAMES, from_json, to_json
    from raft_tpu.sim import pkernel
    from raft_tpu.utils import rng as _r

    kinds = _r.NEM_KINDS if kinds is None else tuple(kinds)
    link_kinds = _r.NEM_LINK_KINDS if link_kinds is None \
        else tuple(link_kinds)
    crash_kinds = _r.NEM_CRASH_KINDS if crash_kinds is None \
        else tuple(crash_kinds)
    timing_kinds = _r.NEM_TIMING_KINDS if timing_kinds is None \
        else tuple(timing_kinds)
    disk_kinds = _r.NEM_DISK_KINDS if disk_kinds is None \
        else tuple(disk_kinds)
    compact_kinds = _r.NEM_COMPACT_KINDS if compact_kinds is None \
        else tuple(compact_kinds)

    problems = []
    # Seam partition: every kind on exactly one seam.
    routed = (list(link_kinds) + list(crash_kinds) + list(timing_kinds)
              + list(disk_kinds) + list(compact_kinds))
    unrouted = [k for k in kinds if k not in routed]
    if unrouted:
        problems.append(
            f"nemesis kinds {unrouted} routed to NO engine seam "
            f"(NEM_LINK/CRASH/TIMING/DISK/COMPACT_KINDS) — their clauses "
            f"would be silently ignored by every engine")
    if len(routed) != len(set(routed)):
        dup = sorted({k for k in routed if routed.count(k) > 1})
        problems.append(f"nemesis kinds {dup} routed to MORE than one "
                        f"seam — their clauses would double-apply")
    ghost = [k for k in routed if k not in kinds]
    if ghost:
        problems.append(f"seam filters route unknown nemesis kinds "
                        f"{ghost} (not in NEM_KINDS)")
    # Builder coverage: every kind constructible through the DSL.
    built = {c[0] for c in _nemesis_probe_program()}
    missing = [k for k in kinds if k not in built]
    if missing:
        problems.append(
            f"nemesis kinds {missing} have no program.py builder "
            f"(KIND_NAMES knows {sorted(KIND_NAMES)}) — a kind the "
            f"DSL cannot express cannot be searched or shrunk")

    # Zero extra leaves, zero wire drift, zero byte-model drift.
    base = _base_cfg()
    on = dataclasses.replace(base, nemesis=_nemesis_probe_program())
    if _leaf_names(on) != _leaf_names(base):
        problems.append(
            "a compiled nemesis program changed the State pytree leaves "
            "— the compiler's whole contract is hash masks over "
            "EXISTING schedules (GATED_LEAVES 'nemesis' row is empty)")
    for fn in (pkernel._mb_fields, pkernel._n_state_leaves,
               pkernel._active_metric_leaves, pkernel.wire_words_per_group):
        if fn(on) != fn(base):
            problems.append(
                f"a compiled nemesis program changed pkernel.{fn.__name__} "
                f"— no new wire lanes are allowed (kleaf_spec would not "
                f"cover them)")
    if [f for f, _ in pkernel._node_leaves(on)] \
            != [f for f, _ in pkernel._node_leaves(base)]:
        problems.append("a compiled nemesis program changed "
                        "pkernel._node_leaves")
    # kinit emits the identical wire-leaf set (eval_shape, no device).
    st_b = jax.eval_shape(lambda: sim.init(base, n_groups=2))
    st_o = jax.eval_shape(lambda: sim.init(on, n_groups=2))
    lv_b = jax.eval_shape(lambda s: pkernel.kinit(base, s)[0], st_b)
    lv_o = jax.eval_shape(lambda s: pkernel.kinit(on, s)[0], st_o)
    if [(tuple(a.shape), str(a.dtype)) for a in lv_b] \
            != [(tuple(a.shape), str(a.dtype)) for a in lv_o]:
        problems.append("a compiled nemesis program changed the kinit "
                        "wire leaves (shape/dtype drift)")

    # JSON round trip: RaftConfig normalization keeps the program
    # hashable and equal through a manifest/checkpoint config dict.
    d = json.loads(json.dumps(dataclasses.asdict(on)))
    if RaftConfig(**d) != on or hash(RaftConfig(**d)) != hash(on):
        problems.append(
            "RaftConfig.nemesis does not survive a JSON round trip as "
            "an equal, hashable static config — jit caching and the "
            "checkpoint cfg match would both break")
    if from_json(to_json(on.nemesis)) != on.nemesis:
        problems.append("nemesis program to_json/from_json round trip "
                        "is not the identity")
    return problems


# --------------------------------------------------- cohort streaming


def _streamed_cfgs() -> dict:
    """label -> a config exercising each r16 residency-knob combination
    the streaming pass audits (built on the small `_base_cfg` universe
    so every derived check stays eval_shape-cheap)."""
    base = _base_cfg()
    return {
        "streamed": dataclasses.replace(base, stream_groups=True),
        "streamed-1blk": dataclasses.replace(base, stream_groups=True,
                                             cohort_blocks=1),
        "streamed-dials": dataclasses.replace(
            base, stream_groups=True, cohort_blocks=2, pack_bools=True,
            pack_ring=True, alias_wire=True, wire_hist=False),
        "streamed-clients": dataclasses.replace(
            _gate_cfgs()["clients"], stream_groups=True, cohort_blocks=2),
    }


def streaming_problems(include_behavioral: bool = True) -> list[str]:
    """The r16 cohort-paging contracts (DESIGN.md §15):

    - the residency knobs (config.STREAM_FIELDS) are RESIDENCY-ONLY —
      flipping them changes zero State pytree leaves, zero kernel wire
      registries, zero wire words (GATED_LEAVES carries the empty
      'streaming' row, like read_every and nemesis), and the real kinit
      output under eval_shape is shape/dtype-identical;
    - the streamed residency model is self-consistent: the cohort
      window fits HBM at every audited layout, and
      `pkernel.streamed_ceiling_groups` is the EXACT `supported()`
      boundary (whole blocks; one more block must tip it — the same
      no-over-promise rule as hbm_ceiling_groups);
    - (behavioral) the cohort scheduler's window slicing + writeback is
      the identity on the host wire (paging moves bytes, never edits
      them), and a checkpoint written under one residency loads under
      the other (config.STREAM_FIELDS are excluded from the semantic
      match, so a streamed run can resume every pre-r16 file).
    """
    import jax

    from raft_tpu import sim
    from raft_tpu.config import STREAM_FIELDS
    from raft_tpu.sim import pkernel

    problems = []
    defaults = RaftConfig()
    for f in STREAM_FIELDS:
        if not hasattr(defaults, f):
            problems.append(f"config.STREAM_FIELDS names {f!r} but "
                            f"RaftConfig has no such field")
            return problems
    if defaults.stream_groups:
        problems.append("cfg.stream_groups defaults ON — the default "
                        "wire/programs/checkpoints must stay byte-"
                        "identical to r14 (stream knobs are opt-in)")
    if GATED_LEAVES.get("streaming") != ((), (), ()):
        problems.append("GATED_LEAVES 'streaming' row is not empty — the "
                        "residency knobs must gate no leaves")
    for label, cfg in _streamed_cfgs().items():
        off = dataclasses.replace(cfg, stream_groups=False,
                                  cohort_blocks=defaults.cohort_blocks)
        if _leaf_names(cfg) != _leaf_names(off):
            problems.append(
                f"[{label}] residency knobs changed State pytree leaves — "
                f"they must be invisible to the XLA/oracle engines")
        for fn in (pkernel._mb_fields, pkernel._n_state_leaves,
                   pkernel._active_metric_leaves,
                   pkernel.wire_words_per_group):
            if fn(cfg) != fn(off):
                problems.append(
                    f"[{label}] residency knobs changed pkernel."
                    f"{fn.__name__} — streaming must add no wire lanes "
                    f"(kleaf_spec would not cover them)")
        st_on = jax.eval_shape(lambda c=cfg: sim.init(c, n_groups=2))
        st_off = jax.eval_shape(lambda c=off: sim.init(c, n_groups=2))
        lv_on = jax.eval_shape(
            lambda s, c=cfg: pkernel.kinit(c, s)[0], st_on)
        lv_off = jax.eval_shape(
            lambda s, c=off: pkernel.kinit(c, s)[0], st_off)
        if [(tuple(a.shape), str(a.dtype)) for a in lv_on] \
                != [(tuple(a.shape), str(a.dtype)) for a in lv_off]:
            problems.append(f"[{label}] residency knobs changed the kinit "
                            f"wire leaves (shape/dtype drift)")
        # Residency model: window fits HBM, ceiling is the exact
        # supported() boundary under the streamed branch.
        if pkernel.cohort_hbm_bytes(cfg) > pkernel.HBM_LIMIT_BYTES:
            problems.append(
                f"[{label}] cohort window ({cfg.cohort_blocks} blocks, "
                f"{pkernel.cohort_hbm_bytes(cfg)} B) does not fit the "
                f"{pkernel.HBM_LIMIT_BYTES} B HBM budget")
            continue
        ceiling = pkernel.streamed_ceiling_groups(cfg)
        if not (pkernel.supported(cfg, n_groups=ceiling)
                and not pkernel.supported(cfg,
                                          n_groups=ceiling + pkernel.GB)):
            problems.append(
                f"[{label}] streamed_ceiling_groups {ceiling} is not the "
                f"exact supported() boundary under stream_groups")
        # r17 mesh axis: at every device count the PER-DEVICE window
        # slice fits HBM and the sharded-streamed ceiling stays the
        # exact supported() boundary (one more block over-promises).
        for nd in (2, 8):
            if pkernel.cohort_hbm_bytes(cfg, True, nd) \
                    > pkernel.HBM_LIMIT_BYTES:
                problems.append(
                    f"[{label}] per-device cohort window at {nd} devices "
                    f"({pkernel.cohort_hbm_bytes(cfg, True, nd)} B) does "
                    f"not fit the {pkernel.HBM_LIMIT_BYTES} B HBM budget")
                continue
            nceil = pkernel.streamed_ceiling_groups(cfg, n_devices=nd)
            if not (pkernel.supported(cfg, n_groups=nceil, n_devices=nd)
                    and not pkernel.supported(
                        cfg, n_groups=nceil + pkernel.GB, n_devices=nd)):
                problems.append(
                    f"[{label}] sharded streamed_ceiling_groups {nceil} at "
                    f"{nd} devices is not the exact supported() boundary")
            if nceil < pkernel.streamed_ceiling_groups(cfg):
                problems.append(
                    f"[{label}] sharded streamed ceiling at {nd} devices "
                    f"({nceil}) fell below the 1-device ceiling — adding "
                    f"devices must never shrink the admitted fleet")

    if not include_behavioral:
        return problems
    import numpy as np

    from raft_tpu.parallel import cohort

    # Paging is the identity: page a real host wire through every
    # window (h2d + d2h, zero ticks of kernel in between) and the bytes
    # must come back exact — a lossy slice/reassembly would corrupt
    # state silently under real runs.
    cfg = _streamed_cfgs()["streamed-1blk"]
    host_leaves, g = cohort.host_wire(cfg, sim.init(cfg, n_groups=2))
    before = [a.copy() for a in host_leaves]
    for s0, s1 in cohort.cohort_windows(cfg, host_leaves):
        cohort._writeback(host_leaves, cohort._window(host_leaves, s0, s1),
                          s0, s1)
    for i, (a, b) in enumerate(zip(before, host_leaves)):
        if not np.array_equal(a, b):
            problems.append(
                f"cohort paging round trip changed wire leaf #{i} — "
                f"window slicing/writeback must be the identity")
    # r17 sharded paging: on a mesh (2 devices when the box has them,
    # else the 1-device degenerate mesh — the code path is identical),
    # every per-device window slice is whole 1024-group blocks, every
    # paged-in leaf carries the r08 kleaf_spec sharding, and the staged
    # put/drain round trip is the identity on the host wire.
    from raft_tpu.parallel import stream_sched
    from raft_tpu.parallel.kmesh import kleaf_spec
    from raft_tpu.parallel.mesh import make_mesh
    nd = 2 if len(jax.local_devices()) >= 2 else 1
    mesh = make_mesh(nd, allow_cpu_fallback=True)
    cfg = _streamed_cfgs()["streamed-1blk"]
    host_leaves, g = cohort.host_wire(cfg, sim.init(cfg, n_groups=2),
                                      pad_to=nd * pkernel.GB)
    before = [a.copy() for a in host_leaves]
    pool = stream_sched.StagingPool(
        host_leaves, pkernel.stream_blocks_per_device(cfg, nd) * nd
        * pkernel.SUB)
    for i, (s0, s1) in enumerate(
            cohort.cohort_windows(cfg, host_leaves, n_devices=nd)):
        for dev, (lo, hi) in stream_sched.device_slices(
                mesh, host_leaves[0], s0, s1):
            if (hi - lo) % pkernel.SUB:
                problems.append(
                    f"sharded window [{s0},{s1}) slice on {dev} covers "
                    f"sublanes [{lo},{hi}) — not whole 1024-group blocks")
        window = stream_sched.put_window(host_leaves, s0, s1, mesh,
                                         pool=pool, slot=i)
        for j, leaf in enumerate(window):
            if leaf.sharding.spec != kleaf_spec(leaf):
                problems.append(
                    f"sharded window leaf #{j} paged in under "
                    f"{leaf.sharding.spec}, not the r08 kleaf_spec "
                    f"{kleaf_spec(leaf)} — kstep_sharded would reshard")
        stream_sched.drain_window(host_leaves, window, s0, s1)
    for i, (a, b) in enumerate(zip(before, host_leaves)):
        if not np.array_equal(a, b):
            problems.append(
                f"sharded cohort paging round trip changed wire leaf "
                f"#{i} — per-device slicing/drain must be the identity")
    # A checkpoint saved under one residency loads under the other (and
    # a pre-r16 file — no stream keys at all — loads under a streamed
    # cfg: the same backfill rule, exercised via the defaults table).
    from raft_tpu.sim import checkpoint as ckpt
    cfg_off = _base_cfg()
    cfg_on = _streamed_cfgs()["streamed"]
    for src, dst, what in ((cfg_off, cfg_on, "resident->streamed"),
                           (cfg_on, cfg_off, "streamed->resident")):
        stx = sim.init(src, n_groups=2)
        buf = io.BytesIO()
        ckpt.save(buf, stx, 3, cfg=src)
        buf.seek(0)
        try:
            ckpt.load(buf, cfg=dst)
        except Exception as e:  # noqa: BLE001 — audited, not handled
            problems.append(
                f"cross-residency checkpoint load ({what}) raised "
                f"{type(e).__name__}: {e} — config.STREAM_FIELDS must be "
                f"excluded from the semantic match (a streamed run could "
                f"never resume a pre-r16 file)")
    return problems


# ------------------------------------------------------- manifest schema


def manifest_problems(manifest_mod=None, history_mod=None) -> list[str]:
    """Manifest-record coverage for the r12 observability keys
    (DESIGN.md §12): every record `emit_manifest` writes must carry the
    roofline/trace keys from birth (null until a caller fills them,
    like the r08 mesh keys), `obs.history.backfill_record` must add
    exactly those keys as null onto a pre-r12 record, and caller-filled
    values must survive emission and backfill untouched. Pass a drifted
    module to prove the auditor names it — the synthetic-drift hook."""
    from raft_tpu.obs import history as real_history
    from raft_tpu.obs import manifest as real_manifest

    man = real_manifest if manifest_mod is None else manifest_mod
    hist = real_history if history_mod is None else history_mod
    problems = []
    keys = (real_manifest.ROOFLINE_KEYS + real_manifest.PACKING_KEYS
            + real_manifest.NEMESIS_KEYS + real_manifest.STREAM_KEYS
            + real_manifest.STREAM_MESH_KEYS + real_manifest.NARROW_KEYS
            + real_manifest.PRESSURE_KEYS)
    if tuple(real_history.R20_MANIFEST_KEYS) \
            != tuple(real_manifest.PRESSURE_KEYS):
        problems.append(
            f"obs.history.R20_MANIFEST_KEYS {real_history.R20_MANIFEST_KEYS}"
            f" != obs.manifest.PRESSURE_KEYS "
            f"{real_manifest.PRESSURE_KEYS} — the emit-side and "
            f"backfill-side key lists drifted")
    if tuple(real_history.R19_MANIFEST_KEYS) \
            != tuple(real_manifest.NARROW_KEYS):
        problems.append(
            f"obs.history.R19_MANIFEST_KEYS {real_history.R19_MANIFEST_KEYS}"
            f" != obs.manifest.NARROW_KEYS "
            f"{real_manifest.NARROW_KEYS} — the emit-side and "
            f"backfill-side key lists drifted")
    if tuple(real_history.R17_MANIFEST_KEYS) \
            != tuple(real_manifest.STREAM_MESH_KEYS):
        problems.append(
            f"obs.history.R17_MANIFEST_KEYS {real_history.R17_MANIFEST_KEYS}"
            f" != obs.manifest.STREAM_MESH_KEYS "
            f"{real_manifest.STREAM_MESH_KEYS} — the emit-side and "
            f"backfill-side key lists drifted")
    if tuple(real_history.R16_MANIFEST_KEYS) \
            != tuple(real_manifest.STREAM_KEYS):
        problems.append(
            f"obs.history.R16_MANIFEST_KEYS {real_history.R16_MANIFEST_KEYS}"
            f" != obs.manifest.STREAM_KEYS "
            f"{real_manifest.STREAM_KEYS} — the emit-side and "
            f"backfill-side key lists drifted")
    if tuple(real_history.R14_MANIFEST_KEYS) \
            != tuple(real_manifest.NEMESIS_KEYS):
        problems.append(
            f"obs.history.R14_MANIFEST_KEYS {real_history.R14_MANIFEST_KEYS}"
            f" != obs.manifest.NEMESIS_KEYS "
            f"{real_manifest.NEMESIS_KEYS} — the emit-side and "
            f"backfill-side key lists drifted")
    if tuple(real_history.R12_MANIFEST_KEYS) \
            != tuple(real_manifest.ROOFLINE_KEYS):
        problems.append(
            f"obs.history.R12_MANIFEST_KEYS {real_history.R12_MANIFEST_KEYS}"
            f" != obs.manifest.ROOFLINE_KEYS "
            f"{real_manifest.ROOFLINE_KEYS} — the emit-side and "
            f"backfill-side key lists drifted")
    if tuple(real_history.R13_MANIFEST_KEYS) \
            != tuple(real_manifest.PACKING_KEYS):
        problems.append(
            f"obs.history.R13_MANIFEST_KEYS {real_history.R13_MANIFEST_KEYS}"
            f" != obs.manifest.PACKING_KEYS "
            f"{real_manifest.PACKING_KEYS} — the emit-side and "
            f"backfill-side key lists drifted")
    from raft_tpu.config import LAYOUT_FIELDS, NARROW_FIELDS, STREAM_FIELDS
    if tuple(real_manifest.NARROW_KEYS[:len(NARROW_FIELDS)]) \
            != tuple(NARROW_FIELDS):
        problems.append(
            f"obs.manifest.NARROW_KEYS {real_manifest.NARROW_KEYS} does "
            f"not lead with config.NARROW_FIELDS {NARROW_FIELDS} — a "
            f"narrow dial exists that manifests would not record")
    if tuple(real_manifest.PACKING_KEYS) != tuple(LAYOUT_FIELDS):
        problems.append(
            f"obs.manifest.PACKING_KEYS {real_manifest.PACKING_KEYS} != "
            f"config.LAYOUT_FIELDS {LAYOUT_FIELDS} — a layout dial exists "
            f"that manifests would not record")
    if tuple(real_manifest.STREAM_KEYS[:len(STREAM_FIELDS)]) \
            != tuple(STREAM_FIELDS):
        problems.append(
            f"obs.manifest.STREAM_KEYS {real_manifest.STREAM_KEYS} does "
            f"not lead with config.STREAM_FIELDS {STREAM_FIELDS} — a "
            f"residency knob exists that manifests would not record")
    rec = man.emit_manifest("audit-probe", _base_cfg(), path="-")
    for k in keys + ("mesh_shape", "groups_per_device"):
        if k not in rec:
            problems.append(
                f"manifest record missing default key {k!r} — a reader "
                f"cannot distinguish 'unstamped' from 'pre-r12 schema'")
        elif rec[k] is not None:
            problems.append(
                f"manifest default for {k!r} is {rec[k]!r}, not null — "
                f"an unstamped record would claim a value")
    # Caller-filled roofline AND wire-layout values must survive
    # emission.
    rec2 = man.emit_manifest("audit-probe", _base_cfg(), path="-",
                             bound="hbm", attainment_pct=12.5,
                             predicted_rounds_per_sec=1.0,
                             pack_bools=True, wire_hist=False,
                             stream_groups=True, cohort_blocks=2,
                             overlap_efficiency_predicted=0.75,
                             stream_devices=8, stream_blocks_per_device=1,
                             stream_slowest_device=3,
                             narrow_scalars=True,
                             narrow_resident_bytes_per_group=2494,
                             knee_ops_per_sec=1.5e6,
                             shed_rate_at_knee=0.02,
                             pressure_program_hash="deadbeef")
    for k, want in (("bound", "hbm"), ("attainment_pct", 12.5),
                    ("predicted_rounds_per_sec", 1.0),
                    ("pack_bools", True), ("wire_hist", False),
                    ("stream_groups", True), ("cohort_blocks", 2),
                    ("overlap_efficiency_predicted", 0.75),
                    ("stream_devices", 8), ("stream_blocks_per_device", 1),
                    ("stream_slowest_device", 3),
                    ("narrow_scalars", True),
                    ("narrow_resident_bytes_per_group", 2494),
                    ("knee_ops_per_sec", 1.5e6),
                    ("shed_rate_at_knee", 0.02),
                    ("pressure_program_hash", "deadbeef")):
        if rec2.get(k) != want:
            problems.append(f"manifest dropped the caller's {k!r} value "
                            f"({rec2.get(k)!r} != {want!r})")
    # Pre-r12 backfill: strip the keys, the history reader re-adds them
    # present-but-null without touching anything else.
    old = {k: v for k, v in rec.items() if k not in keys}
    back = hist.backfill_record(old)
    for k in keys:
        if k not in back:
            problems.append(f"history.backfill_record leaves a pre-r12 "
                            f"record without {k!r}")
        elif back[k] is not None:
            problems.append(f"history.backfill_record invents a value for "
                            f"{k!r} ({back[k]!r}) on a pre-r12 record")
    changed = {k for k in old if back.get(k) != old[k]}
    if changed:
        problems.append(f"history.backfill_record rewrote pre-existing "
                        f"manifest fields {sorted(changed)}")
    return problems


# ------------------------------------------------------------- rng parity


def rng_parity_problems() -> list[str]:
    """utils.rng (host ints) and utils.jrng (u32 lanes) must export the
    same schedule surface — a draw added to one side only is exactly
    the untagged-randomness drift the linter hunts dynamically."""
    import inspect

    from raft_tpu.utils import jrng, rng

    def public_fns(mod):
        return {n for n, v in vars(mod).items()
                if callable(v) and not n.startswith("_")
                and getattr(v, "__module__", None) == mod.__name__}

    problems = []
    only_rng = public_fns(rng) - public_fns(jrng)
    only_jrng = public_fns(jrng) - public_fns(rng)
    if only_rng:
        problems.append(f"rng functions missing a jrng twin: "
                        f"{sorted(only_rng)}")
    if only_jrng:
        problems.append(f"jrng functions missing an rng twin: "
                        f"{sorted(only_jrng)}")
    # Same coordinate signature, so call sites cannot transpose args.
    for n in public_fns(rng) & public_fns(jrng):
        a = list(inspect.signature(getattr(rng, n)).parameters)
        b = list(inspect.signature(getattr(jrng, n)).parameters)
        if a != b:
            problems.append(f"rng.{n}{a} and jrng.{n}{b} disagree on "
                            f"parameter names/order")
    return problems


def contract_problems(include_behavioral: bool = True) -> list[str]:
    """All contract passes, concatenated."""
    out = []
    out += metric_parity_problems()
    out += wire_registry_problems()
    out += gating_problems()
    out += shard_rule_problems()
    out += packing_problems(include_behavioral=include_behavioral)
    out += narrowing_problems(include_behavioral=include_behavioral)
    out += checkpoint_problems(include_behavioral=include_behavioral)
    out += nemesis_problems()
    out += streaming_problems(include_behavioral=include_behavioral)
    out += manifest_problems()
    out += rng_parity_problems()
    return out
