"""AST purity/determinism linter over the tick implementations
(DESIGN.md §11).

The whole repo's bit-identity story rests on three source-level
properties of `sim/step.py`, `sim/pkernel.py`, and
`clients/workload.py`:

1. **Tagged randomness only** — every stochastic draw routes through
   the counter-based `utils.rng`/`utils.jrng` TAG_* hashes. A stray
   `jax.random` / `random` / `np.random` / `secrets` / `uuid` call is
   hidden state: it breaks oracle/XLA/kernel tri-identity and makes
   checkpoints non-resumable.
2. **No Python-level branching on traced values** — an `if`/`while`
   whose test depends on a traced array either crashes under jit
   (ConcretizationTypeError) or, worse, silently bakes one branch into
   the compiled program. Static branching on `cfg` knobs is the
   codebase's whole gating idiom and stays legal.
3. **The client workload transition is purely elementwise** — ONE jnp
   implementation serves [G, S] XLA leaves and [S, 8, 128] kernel
   tiles ONLY because `client_update`/`submit_payloads` never use an
   op that couples lanes (reductions, reshapes, gathers).

This is a lint, not a proof: traced-ness is propagated by a small
forward dataflow (annotation-seeded + jnp/jrng-call-seeded + a short
conventional-name list), which can miss a branch on an unannotated
parameter — but every rule is tuned to be zero-noise on the real
modules (enforced in tier-1), so a finding is always worth reading.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterable

# Default lint surface: the modules whose purity the engines'
# bit-identity contract depends on. r14 adds the nemesis compiler
# (utils/jrng.py hosts the compiled-program evaluators — its nem_*
# bodies must stay elementwise so one implementation serves the XLA
# layouts and the kernel tiles — and the nemesis package must stay
# free of untagged randomness: the SEARCH itself draws only hash_u32).
# r16 adds the cohort scheduler: its host-side orchestration may
# branch only on shapes/knobs, never on traced lane VALUES — a
# value-dependent paging decision would make the streamed engine's
# schedule diverge from the resident kernel it must stay bit-identical
# to. r17 adds the shard-aware scheduler (parallel/stream_sched.py):
# per-device slicing and staging decisions are schedule, so the same
# shapes-and-knobs-only rule applies. r18 closes the remaining gap in
# the multi-device surface: parallel/kmesh.py (the shard_map launch
# wrapper — sharding and resharding decisions must be shape/knob
# static) and ops/quorum.py (popcount/majority lane math used by every
# engine's vote and commit paths — a hidden draw or traced branch
# there skews all three engines at once).
DEFAULT_TARGETS = ("sim/step.py", "sim/pkernel.py", "clients/workload.py",
                   "utils/jrng.py", "nemesis/program.py",
                   "nemesis/search.py", "parallel/cohort.py",
                   "parallel/stream_sched.py", "parallel/kmesh.py",
                   "ops/quorum.py")

# The jrng functions the elementwise rule covers (the compiled nemesis
# evaluators — DESIGN.md §14; the rest of jrng predates the rule and is
# already pinned elementwise by its kernel use).
NEM_EVAL_FNS = ("nem_link_ok", "nem_alive", "nem_deadline_extra",
                "_nem_active")

# Pytree / array annotations that seed traced-ness for parameters.
ARRAY_TYPES = {"PerNode", "Mailbox", "State", "ClientState", "Metrics",
               "KMetrics", "Flight", "ndarray", "Array"}

# Conventional traced-value parameter names in the tick modules —
# belt-and-braces seeding for unannotated handler signatures.
TRACED_PARAM_NAMES = {"ns", "st", "nodes", "mailbox", "inbox", "outbox",
                      "ib", "out", "cl", "cs", "met", "fl", "m", "state",
                      "clients", "alive_prev", "alive_now", "carry"}

# Attribute reads that are static at trace time even on traced values.
STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "_fields"}

# Call roots whose results are traced arrays.
_TRACED_CALL_ROOTS = ("jnp", "jrng")
_TRACED_CALL_PREFIXES = (("jax", "lax"), ("jax", "numpy"), ("jax", "nn"),
                         ("jax", "vmap"), ("jax", "tree"), ("jax", "jit"))

# Modules whose mere use is nondeterminism in the tick surface.
FORBIDDEN_MODULES = {"random", "secrets", "uuid"}
FORBIDDEN_ATTR_CHAINS = (("jax", "random"), ("np", "random"),
                         ("numpy", "random"), ("os", "urandom"))

# jnp ops that are elementwise (lane-local) — the ONLY jnp calls the
# client workload transition may make. Reducers/reshapers couple lanes
# and break the one-implementation-two-layouts contract.
ELEMENTWISE_JNP = {
    "where", "minimum", "maximum", "abs", "clip", "sign", "mod",
    "equal", "not_equal", "greater", "less", "greater_equal",
    "less_equal", "logical_and", "logical_or", "logical_not",
    "logical_xor", "bitwise_and", "bitwise_or", "bitwise_xor",
    "invert", "left_shift", "right_shift", "add", "subtract",
    "multiply", "floor_divide", "remainder", "negative",
    "zeros_like", "ones_like", "full_like", "asarray",
    "int32", "uint32", "bool_", "float32",
}
ELEMENTWISE_METHODS = {"astype"}
WORKLOAD_FNS = ("client_update", "submit_payloads")

# r19 untagged-widening rule (DESIGN.md §18): the hot-loop modules
# whose State-leaf dtypes are a CONTRACT under the narrow-native dials.
# A bare `ns.term.astype(I32)` (or `jnp.int32(st.nodes.commit)`) inside
# the tick silently re-declares a resident leaf wide, undoing the
# narrow layout's byte win — every deliberate leaf cast must carry a
# `# widen-ok` tag on its line (the annotation-allowlist idiom of the
# elementwise rule). Casts of derived predicates/locals
# (`cond.astype(I32)`) are not leaf re-declarations and pass untagged.
WIDEN_TAG = "widen-ok"
WIDENING_TARGETS = ("step.py", "pkernel.py", "workload.py")
_DTYPE_CTORS = {"int8", "int16", "int32", "uint16", "uint32",
                "float32", "bool_"}


@dataclasses.dataclass
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def _attr_chain(node) -> tuple:
    """('jax', 'random', 'split') for jax.random.split; () if the
    expression is not a plain dotted name."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return ()


def _is_traced_call(node: ast.Call) -> bool:
    chain = _attr_chain(node.func)
    if not chain:
        return False
    if chain[0] in _TRACED_CALL_ROOTS:
        return True
    return any(chain[:len(p)] == p for p in _TRACED_CALL_PREFIXES)


class _TracedScope:
    """Forward dataflow of traced-ness through one function body."""

    def __init__(self, fn: ast.FunctionDef, inherited: set):
        self.traced = set(inherited)
        for arg in list(fn.args.args) + list(fn.args.kwonlyargs):
            ann = arg.annotation
            names = set()
            if isinstance(ann, (ast.Name, ast.Attribute)):
                chain = _attr_chain(ann)
                if chain:
                    names.add(chain[-1])
            elif isinstance(ann, ast.Constant) and isinstance(ann.value,
                                                              str):
                names.update(ann.value.replace("|", " ").split())
            if names & ARRAY_TYPES or arg.arg in TRACED_PARAM_NAMES:
                self.traced.add(arg.arg)

    def expr_is_traced(self, node) -> bool:
        """Does `node` (an expression) depend on a traced value, after
        the static exemptions (`.shape`/`.dtype`/..., `is` compares,
        len/isinstance calls, constants)?"""
        if node is None or isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in self.traced
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.expr_is_traced(node.value)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            return (self.expr_is_traced(node.left)
                    or any(self.expr_is_traced(c) for c in node.comparators))
        if isinstance(node, ast.Call):
            if _is_traced_call(node):
                return True
            # A call yields a traced value iff its CALLEE is traced (a
            # method on a traced array: ns._replace, arr.at[i].set) or
            # rooted at jnp/jrng/jax (above). Argument traced-ness does
            # NOT propagate through unknown callees — host helpers
            # routinely take pytrees and return host ints/np arrays,
            # and flagging those drowns the signal (the cost: a branch
            # on a local helper's traced result is missed — a lint,
            # not a proof).
            return self.expr_is_traced(node.func)
        if isinstance(node, ast.Subscript):
            return (self.expr_is_traced(node.value)
                    or self.expr_is_traced(node.slice))
        if isinstance(node, (ast.BoolOp,)):
            return any(self.expr_is_traced(v) for v in node.values)
        if isinstance(node, ast.BinOp):
            return (self.expr_is_traced(node.left)
                    or self.expr_is_traced(node.right))
        if isinstance(node, ast.UnaryOp):
            return self.expr_is_traced(node.operand)
        if isinstance(node, ast.IfExp):
            # Only the TEST branches at Python level; the arms are data.
            return (self.expr_is_traced(node.test)
                    or self.expr_is_traced(node.body)
                    or self.expr_is_traced(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.expr_is_traced(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self.expr_is_traced(v) for v in node.values
                       if v is not None)
        if isinstance(node, ast.Starred):
            return self.expr_is_traced(node.value)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            # Comprehensions over traced iterables yield traced elements.
            return any(self.expr_is_traced(g.iter)
                       for g in node.generators)
        return False

    def _mark_targets(self, target):
        if isinstance(target, ast.Name):
            self.traced.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._mark_targets(e)
        elif isinstance(target, ast.Starred):
            self._mark_targets(target.value)

    def propagate(self, body: Iterable[ast.stmt]):
        """Two passes so a use-before-later-assign inside a loop body
        still converges for this flat propagation."""
        for _ in range(2):
            for stmt in ast.walk(ast.Module(body=list(body),
                                            type_ignores=[])):
                if isinstance(stmt, ast.Assign):
                    if self.expr_is_traced(stmt.value):
                        for t in stmt.targets:
                            self._mark_targets(t)
                elif isinstance(stmt, ast.AugAssign):
                    if (self.expr_is_traced(stmt.value)
                            or self.expr_is_traced(stmt.target)):
                        self._mark_targets(stmt.target)
                elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                    if self.expr_is_traced(stmt.value):
                        self._mark_targets(stmt.target)
                elif isinstance(stmt, ast.For):
                    if self.expr_is_traced(stmt.iter):
                        self._mark_targets(stmt.target)


def _lint_randomness(tree: ast.AST, path: str) -> list[Finding]:
    out = []
    seen = set()   # (lineno, chain) — jax.random.X also matches at its
    # nested jax.random node; report each draw once
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                root = alias.name.split(".")[0]
                if root in FORBIDDEN_MODULES:
                    out.append(Finding(path, node.lineno,
                                       "untagged-randomness",
                                       f"import of {alias.name!r} — all "
                                       f"draws must route through the "
                                       f"utils.rng/jrng TAG_* hashes"))
                if alias.name in ("jax.random", "numpy.random"):
                    out.append(Finding(path, node.lineno,
                                       "untagged-randomness",
                                       f"import of {alias.name} — "
                                       f"stateful/seeded PRNGs break "
                                       f"tri-engine bit-identity"))
        elif isinstance(node, ast.ImportFrom):
            mod = (node.module or "").split(".")[0]
            if mod in FORBIDDEN_MODULES:
                out.append(Finding(path, node.lineno, "untagged-randomness",
                                   f"import from {node.module!r}"))
            if node.module in ("jax", "numpy") and any(
                    a.name == "random" for a in node.names):
                out.append(Finding(path, node.lineno, "untagged-randomness",
                                   f"from {node.module} import random"))
            if node.module in ("jax.random", "numpy.random"):
                out.append(Finding(path, node.lineno, "untagged-randomness",
                                   f"import from {node.module}"))
        elif isinstance(node, ast.Attribute):
            chain = _attr_chain(node)
            for bad in FORBIDDEN_ATTR_CHAINS:
                if chain[:len(bad)] == bad:
                    if (node.lineno, bad) not in seen:
                        seen.add((node.lineno, bad))
                        out.append(Finding(
                            path, node.lineno, "untagged-randomness",
                            f"use of {'.'.join(chain)} — every draw must "
                            f"be a pure (seed, TAG_*, coords) hash via "
                            f"utils.rng/jrng"))
                    break
    return out


def _lint_traced_branches(tree: ast.AST, path: str) -> list[Finding]:
    out = []

    def visit_fn(fn: ast.FunctionDef, inherited: set):
        scope = _TracedScope(fn, inherited)
        scope.propagate(fn.body)

        # Walk fn's OWN statements only — nested function bodies are
        # visited once, below, with this scope inherited (walking them
        # here too would double-report their findings under the wrong
        # scope).
        own, nested, stack = [], [], list(fn.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested.append(node)
                continue
            own.append(node)
            stack.extend(ast.iter_child_nodes(node))

        for node in own:
            test = None
            kind = None
            if isinstance(node, (ast.If, ast.While)):
                test, kind = node.test, type(node).__name__.lower()
            elif isinstance(node, ast.IfExp):
                test, kind = node.test, "conditional expression"
            elif isinstance(node, ast.Assert):
                test, kind = node.test, "assert"
            if test is not None and scope.expr_is_traced(test):
                names = sorted({n.id for n in ast.walk(test)
                                if isinstance(n, ast.Name)
                                and n.id in scope.traced})
                out.append(Finding(
                    path, node.lineno, "traced-branch",
                    f"Python-level {kind} on traced value(s) "
                    f"{names or '<expr>'} in {fn.name}() — branch with "
                    f"jnp.where / static cfg gates instead"))
            if isinstance(node, ast.Call):
                chain = _attr_chain(node.func)
                if chain and chain[-1] in ("bool", "int", "float") \
                        and len(chain) == 1 \
                        and any(scope.expr_is_traced(a)
                                for a in node.args):
                    out.append(Finding(
                        path, node.lineno, "traced-branch",
                        f"host {chain[-1]}() coercion of a traced value "
                        f"in {fn.name}() — forces a device sync / "
                        f"concretization"))

        for sub in nested:
            visit_fn(sub, scope.traced)

    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, ast.FunctionDef):
            visit_fn(node, set())
        elif isinstance(node, ast.ClassDef):
            # Host-side classes (HostClients, the oracle mirror) are
            # exempt from the traced-branch rule: they ARE the python
            # reference. Randomness rules still apply (walked above).
            continue
    return out


def _lint_workload_elementwise(tree: ast.AST, path: str,
                               fns: tuple = WORKLOAD_FNS) -> list[Finding]:
    out = []
    for node in (tree.body if isinstance(tree, ast.Module) else []):
        if not (isinstance(node, ast.FunctionDef) and node.name in fns):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            chain = _attr_chain(sub.func)
            if not chain:
                continue
            bad = None
            if chain[0] == "jnp" and len(chain) == 2 \
                    and chain[1] not in ELEMENTWISE_JNP:
                bad = (f"jnp.{chain[1]} is not in the elementwise "
                       f"allowlist")
            elif chain[0] == "jax":
                bad = f"{'.'.join(chain)} call"
            elif len(chain) >= 2 and chain[-1] not in ELEMENTWISE_METHODS \
                    and chain[-1] in ("sum", "max", "min", "mean", "prod",
                                      "reshape", "transpose", "ravel",
                                      "flatten", "dot", "sort", "argsort",
                                      "argmax", "argmin", "cumsum", "take"):
                bad = f"method .{chain[-1]}() couples lanes"
            if bad is None and any(k.arg == "axis" for k in sub.keywords):
                bad = f"{'.'.join(chain)} with an axis= argument reduces " \
                      f"over an axis"
            if bad:
                out.append(Finding(
                    path, sub.lineno, "non-elementwise-workload",
                    f"{bad} inside {node.name}() — the client transition "
                    f"must stay purely elementwise so one implementation "
                    f"serves the [G, S] and [S, 8, 128] layouts"))
    return out


def _is_leaf_chain(scope: _TracedScope, node) -> bool:
    """Syntactic pytree-leaf read: an Attribute/Subscript chain (at
    least one link, none of the static attrs) rooted at a traced Name —
    `ns.term`, `st.nodes.commit`, `nd["votes"]`. Calls/operators in the
    chain break it: their result is a derived value, not a leaf."""
    links = 0
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if isinstance(node, ast.Attribute) and node.attr in STATIC_ATTRS:
            return False
        links += 1
        node = node.value
    return (links > 0 and isinstance(node, ast.Name)
            and node.id in scope.traced)


def _lint_untagged_widening(tree: ast.AST, path: str,
                            src_lines: list[str]) -> list[Finding]:
    """Flag `<leaf>.astype(...)` and `jnp.<dtype>(<leaf>)` casts of
    State leaves in the hot-loop modules unless the line carries the
    `# widen-ok` tag — see WIDENING_TARGETS above."""
    out = []

    def tagged(lineno: int) -> bool:
        return (0 < lineno <= len(src_lines)
                and WIDEN_TAG in src_lines[lineno - 1])

    def visit_fn(fn: ast.FunctionDef, inherited: set):
        scope = _TracedScope(fn, inherited)
        scope.propagate(fn.body)
        own, nested, stack = [], [], list(fn.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested.append(node)
                continue
            own.append(node)
            stack.extend(ast.iter_child_nodes(node))
        for node in own:
            if not isinstance(node, ast.Call):
                continue
            leaf = None
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype"
                    and _is_leaf_chain(scope, node.func.value)):
                leaf = ast.unparse(node.func.value)
                how = f"{leaf}.astype(...)"
            else:
                chain = _attr_chain(node.func)
                if (len(chain) == 2 and chain[0] == "jnp"
                        and chain[1] in _DTYPE_CTORS
                        and any(_is_leaf_chain(scope, a)
                                for a in node.args)):
                    leaf = next(ast.unparse(a) for a in node.args
                                if _is_leaf_chain(scope, a))
                    how = f"jnp.{chain[1]}({leaf})"
            if leaf is not None and not tagged(node.lineno):
                out.append(Finding(
                    path, node.lineno, "untagged-widening",
                    f"{how} in {fn.name}() re-declares a State leaf's "
                    f"dtype in a hot loop — under the narrow-native "
                    f"dials (config.NARROW_FIELDS) leaf dtypes are a "
                    f"layout contract; tag the line `# {WIDEN_TAG}` if "
                    f"the cast is a deliberate boundary"))
        for sub in nested:
            visit_fn(sub, scope.traced)

    for node in tree.body if isinstance(tree, ast.Module) else []:
        if isinstance(node, ast.FunctionDef):
            visit_fn(node, set())
    return out


def lint_file(path: str, *, workload_rules: bool | None = None
              ) -> list[Finding]:
    """All rules over one file. `workload_rules` defaults to "is this
    clients/workload.py" and forces the elementwise pass on fixture
    files when True."""
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    tree = ast.parse(src, filename=path)
    if workload_rules is None:
        workload_rules = os.path.basename(path) == "workload.py"
    out = _lint_randomness(tree, path)
    out += _lint_traced_branches(tree, path)
    if os.path.basename(path) in WIDENING_TARGETS:
        out += _lint_untagged_widening(tree, path, src.splitlines())
    if workload_rules:
        out += _lint_workload_elementwise(tree, path)
    if os.path.basename(path) == "jrng.py":
        # The compiled nemesis evaluators share the workload rule's
        # contract: purely elementwise, so the one jnp implementation
        # serves both engine layouts (and Mosaic can lower it).
        out += _lint_workload_elementwise(tree, path, fns=NEM_EVAL_FNS)
    return out


def lint_default() -> list[Finding]:
    """Lint the contract surface (`DEFAULT_TARGETS`: the engine tick
    modules, the client workload, the jrng evaluators, and the nemesis
    package, resolved relative to the installed package)."""
    import raft_tpu
    root = os.path.dirname(os.path.abspath(raft_tpu.__file__))
    out = []
    for rel in DEFAULT_TARGETS:
        out += lint_file(os.path.join(root, rel))
    return out
