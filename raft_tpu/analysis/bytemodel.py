"""Derived wire-byte model: bytes/group computed from real dtypes and
shapes, reconciled against the hand-pinned model (DESIGN.md §11).

Every number here is DERIVED, never pinned: the State / Metrics /
Flight pytrees are traced with `jax.eval_shape` (no device buffers, no
tick executed — the whole pass runs on a box with no accelerator), each
leaf's wire contribution is computed from its dtype x shape, and the
totals are reconciled against THREE independent accountings:

1. the per-leaf walk over the State pytree + metric lanes + flight
   rings (this module's own sum);
2. the real `pkernel.kinit` output leaves, again under `eval_shape`
   (each wire leaf's element count divided by the padded group count);
3. the hand-maintained `pkernel.wire_words_per_group` model that
   `supported()` / `hbm_bytes` / the multichip sweep budget against.

Any disagreement is contract drift and fails the audit — this is the
machine that would have caught r08's alive_prev k-words bug (8,308 vs
8,292 B/group) before a reviewer did.

The model also names every i32-WIDENED bool leaf: a State bool costs
1 byte on the XLA path but rides the kernel wire as a 4-byte i32 lane
(Mosaic cannot transport i1 vectors — sim/pkernel.py module
docstring), so each bool word carries 3 bytes of pure widening waste
(~690 B/group at the headline config, the "~700 B" of the r08 probe).
Since r13 that waste is a DIAL, not a structure: under the
`pack_bools` / `pack_ring` layout knobs (DESIGN.md §13) this module
derives the PACKED arithmetic independently — bit lanes for the bool
leaves, half-lane ring deltas plus a base lane — and the three-way
reconciliation holds at every audited layout (`audit_cfgs`: the 8,308 /
11,056 B/group r12 baselines exactly preserved as the off-path pins,
7,136 / 9,884 packed, 3,552 with every dial at the headline config).
"""

from __future__ import annotations

import dataclasses

from raft_tpu.config import RaftConfig

# Group count used for every eval_shape trace. Must differ from
# HIST_SIZE and from every per-node axis so shapes discriminate leaf
# roles by value, and must be >= 2 so a [G] lane cannot be mistaken
# for a scalar.
_G0 = 2


def headline_cfg() -> RaftConfig:
    """The bench headline universe (k=5, L=32, clients off) whose wire
    model is pinned at 8,308 B/group (DESIGN.md §9)."""
    return RaftConfig(seed=42)


def clients_cfg() -> RaftConfig:
    """The bench client-SLO universe (headline + 4 retrying sessions)
    whose wire model is pinned at 11,056 B/group (DESIGN.md §10)."""
    return dataclasses.replace(headline_cfg(), sessions=True,
                               cmds_per_tick=0, client_rate=0.2,
                               client_slots=4, client_retry_backoff=8)


# THE pytree-walk/key-naming rule is checkpoint's (its npz keys are
# one of the audited surfaces) — re-exported here so every auditor
# pass names leaves identically to the checkpoint format by
# construction, not by parallel implementation.
from raft_tpu.sim.checkpoint import iter_named_leaves  # noqa: F401,E402


def _specs(cfg: RaftConfig, with_flight: bool):
    """(state, metrics, flight, kinit-leaves) as ShapeDtypeStruct
    pytrees — pure abstract tracing, zero device buffers."""
    import jax

    from raft_tpu import sim
    from raft_tpu.obs.recorder import flight_init
    from raft_tpu.sim import pkernel
    from raft_tpu.sim.run import metrics_init

    st = jax.eval_shape(lambda: sim.init(cfg, n_groups=_G0))
    met = jax.eval_shape(
        lambda: metrics_init(_G0, clients=cfg.clients_u32 != 0))
    fl = jax.eval_shape(lambda: flight_init(_G0)) if with_flight else None
    if with_flight:
        kleaves = jax.eval_shape(
            lambda s, f: pkernel.kinit(cfg, s, None, f)[0], st, fl)
    else:
        kleaves = jax.eval_shape(
            lambda s: pkernel.kinit(cfg, s, None, None)[0], st)
    return st, met, fl, kleaves


def derived_wire_model(cfg: RaftConfig, with_flight: bool = True) -> dict:
    """The machine-readable bytes/group report. Keys:

    - ``leaves``: one row per wire contribution (name, kind, dtype,
      per-group shape, wire words, wire bytes, native bytes, widened);
    - ``wire_bytes_derived`` / ``wire_bytes_pinned`` and the two
      cross-check sums (`state_words_*`, `kinit_words_per_group`);
    - ``widening``: the i32-widened bool leaves and their waste;
    - ``hbm``: the ceiling implied by the derived bytes, plus the
      supported()-boundary consistency bits;
    - ``problems``: every reconciliation failure, as strings (empty ==
      the derived and pinned models agree exactly).
    """
    import numpy as np

    from raft_tpu.obs.recorder import FLIGHT_LEAVES, RING
    from raft_tpu.sim import pkernel

    problems: list[str] = []
    st, met, fl, kleaves = _specs(cfg, with_flight)

    rows = []
    state_words = 0
    # The packed-layout arithmetic (DESIGN.md §13), derived here
    # INDEPENDENTLY from the leaf dtypes/shapes so a drifted encode in
    # pkernel's registry cannot agree with itself: under pack_bools a
    # bool leaf's wire words come from its own trailing axis packed
    # into bit lanes (votes: k per-node lanes; alive_prev: 1; the
    # mailbox bools: ONE shared-lane leaf of ceil(n_bool x k / 32)
    # words per dst, emitted after the walk); under pack_ring the
    # log_term ring carries two 16-bit deltas per word plus a one-word
    # base/overflow lane.
    n_mb_bools = 0
    for name, leaf in iter_named_leaves(st):
        shape = tuple(leaf.shape)
        if not shape or shape[0] != _G0:
            problems.append(
                f"state leaf {name}: shape {shape} does not lead with the "
                f"group axis (G={_G0}) — the wire fold and kleaf_spec both "
                f"assume it does")
            continue
        per_group = shape[1:]
        words = int(np.prod(per_group, dtype=np.int64)) if per_group else 1
        itemsize = np.dtype(leaf.dtype).itemsize
        is_bool = np.dtype(leaf.dtype) == np.bool_
        if np.dtype(leaf.dtype).itemsize > 4:
            problems.append(
                f"state leaf {name}: dtype {leaf.dtype} is wider than the "
                f"32-bit wire lane — kinit would silently truncate it")
        wire_words, packed = words, False
        if cfg.pack_bools and is_bool:
            packed = True
            if name.startswith("mailbox."):
                n_mb_bools += 1     # shared-lane leaf emitted below
                wire_words = 0
            elif name == "nodes.votes":
                wire_words = int(per_group[0])   # k per-node bit lanes
            elif name == "alive_prev":
                wire_words = 1
            else:
                problems.append(
                    f"state leaf {name}: bool leaf with no packed-layout "
                    f"rule — the pack_bools encode would drop it")
        if cfg.pack_ring and name == "nodes.log_term":
            if words % 2:
                problems.append(f"state leaf {name}: odd ring cannot pack "
                                f"two 16-bit deltas per word")
            wire_words, packed = words // 2, True
        rows.append({
            "name": name, "kind": "state", "dtype": str(np.dtype(leaf.dtype)),
            "shape_per_group": list(per_group),
            "wire_words": wire_words, "wire_bytes": 4 * wire_words,
            "native_bytes": itemsize * words,
            "widened_bool": bool(is_bool and not packed),
            "packed": packed,
        })
        state_words += wire_words
    if cfg.pack_bools:
        from raft_tpu.sim.pkernel import MB_BOOLS_PACKED
        mb_words = -(-n_mb_bools * cfg.k // 32) * cfg.k
        rows.append({
            "name": MB_BOOLS_PACKED, "kind": "state-packed",
            "dtype": "int32", "shape_per_group": [cfg.k],
            "wire_words": mb_words, "wire_bytes": 4 * mb_words,
            "native_bytes": 0, "widened_bool": False, "packed": True,
        })
        state_words += mb_words
    if cfg.pack_ring:
        from raft_tpu.sim.pkernel import RING_BASE
        rows.append({
            "name": RING_BASE, "kind": "state-packed", "dtype": "int32",
            "shape_per_group": [], "wire_words": 1, "wire_bytes": 4,
            "native_bytes": 0, "widened_bool": False, "packed": True,
        })
        state_words += 1

    # Metric tail: every active non-row leaf is ONE per-group lane on
    # the wire (scalars like `elections` accumulate per group in-kernel
    # and reduce at kfinish); row leaves are per-group [H] histogram
    # rows. Derived from the Metrics leaf shapes, not from the kind
    # tables, so a new metric lane cannot be silently mis-filed.
    metric_words = 0
    per_group_metrics = set()
    for name in pkernel._active_metric_leaves(cfg):
        leaf = getattr(met, name)
        if leaf is None:
            problems.append(f"metric leaf {name}: active on the wire under "
                            f"this cfg but None in metrics_init")
            continue
        shape = tuple(leaf.shape)
        if name in pkernel.ROW_METRIC_LEAVES:
            words = int(shape[0])
            kind = "metric-row"
        elif shape == (_G0,):
            words, kind = 1, "metric-lane"
            per_group_metrics.add(name)
        elif shape == ():
            words, kind = 1, "metric-lane"
        else:
            problems.append(f"metric leaf {name}: unclassifiable shape "
                            f"{shape} (not [G], scalar, or a row leaf)")
            continue
        rows.append({
            "name": f"metrics.{name}", "kind": kind,
            "dtype": str(np.dtype(leaf.dtype)), "shape_per_group": [],
            "wire_words": words, "wire_bytes": 4 * words,
            "native_bytes": 4 * words, "widened_bool": False,
            "packed": False,
        })
        metric_words += words

    flight_words = 0
    if with_flight:
        for name in FLIGHT_LEAVES:
            leaf = getattr(fl, name)
            if tuple(leaf.shape) != (RING, _G0):
                problems.append(f"flight leaf {name}: shape "
                                f"{tuple(leaf.shape)} != ({RING}, G)")
                continue
            rows.append({
                "name": f"flight.{name}", "kind": "flight-ring",
                "dtype": str(np.dtype(leaf.dtype)), "shape_per_group": [],
                "wire_words": RING, "wire_bytes": 4 * RING,
                "native_bytes": 4 * RING, "widened_bool": False,
                "packed": False,
            })
            flight_words += RING

    derived_words = state_words + metric_words + flight_words

    # Cross-check 2: the real kinit output, element-counted. Every wire
    # leaf is [..., GS, LANE] with GS * LANE == the padded group count.
    padded = -(-_G0 // pkernel.GB) * pkernel.GB
    kinit_words = 0
    for i, leaf in enumerate(kleaves):
        n = int(np.prod(leaf.shape, dtype=np.int64))
        if n % padded:
            problems.append(f"kinit leaf #{i}: element count {n} is not a "
                            f"multiple of the padded group count {padded}")
        kinit_words += n // padded
    n_expected = (pkernel._n_state_leaves(cfg)
                  + (len(FLIGHT_LEAVES) if with_flight else 0)
                  + pkernel._n_metric_leaves(cfg))
    if len(kleaves) != n_expected:
        problems.append(f"kinit emitted {len(kleaves)} wire leaves; the "
                        f"registries (_n_state_leaves + flight + "
                        f"_n_metric_leaves) promise {n_expected}")

    # Cross-check 3: the hand-pinned model supported()/hbm_bytes use.
    pinned_state = pkernel._state_words_per_group(cfg)
    pinned_wire = pkernel.wire_words_per_group(cfg, with_flight=with_flight)
    # state_words here includes only State-pytree leaves; the pinned
    # _state_words_per_group additionally counts the non-row metric
    # LANES (its "scalar_lanes" tail) — align the two accountings.
    lane_words = sum(r["wire_words"] for r in rows
                     if r["kind"] == "metric-lane")
    if state_words + lane_words != pinned_state:
        problems.append(
            f"derived state words/group {state_words} + {lane_words} metric "
            f"lanes != pinned pkernel._state_words_per_group {pinned_state}")
    if derived_words != pinned_wire:
        problems.append(
            f"derived wire words/group {derived_words} != pinned "
            f"pkernel.wire_words_per_group {pinned_wire} "
            f"(with_flight={with_flight})")
    if kinit_words != pinned_wire:
        problems.append(
            f"real kinit wire words/group {kinit_words} != pinned "
            f"pkernel.wire_words_per_group {pinned_wire} "
            f"(with_flight={with_flight})")

    # Checkpoint's name-based resharding rule must cover exactly the
    # per-group metric lanes (a [G] lane missing from the tuple loads
    # replicated — wrong under a mesh; a scalar listed there would
    # shard a replicated value).
    from raft_tpu.sim.checkpoint import _PER_GROUP_METRICS
    active_pg = {n for n in per_group_metrics}
    listed = set(_PER_GROUP_METRICS) & set(pkernel._active_metric_leaves(cfg))
    if active_pg != listed:
        problems.append(
            f"checkpoint._PER_GROUP_METRICS covers {sorted(listed)} of the "
            f"active metric leaves but the [G]-shaped ones are "
            f"{sorted(active_pg)}")

    widened = [r for r in rows if r["widened_bool"]]
    waste = sum(3 * r["wire_words"] for r in widened)

    # HBM-boundary consistency: the published ceiling must be the exact
    # supported() boundary (whole blocks; one more block must tip it).
    rcfg = dataclasses.replace(cfg, stream_groups=False)
    ceiling = pkernel.hbm_ceiling_groups(rcfg, with_flight=with_flight)
    hbm_ok = (pkernel.supported(rcfg, n_groups=ceiling,
                                with_flight=with_flight)
              and not pkernel.supported(rcfg, n_groups=ceiling + pkernel.GB,
                                        with_flight=with_flight))
    if not hbm_ok:
        problems.append(
            f"hbm_ceiling_groups {ceiling} is not the exact supported() "
            f"boundary (with_flight={with_flight})")

    # Streamed residency (r16, DESIGN.md §15): under cfg.stream_groups
    # the fleet's ONE wire copy lives in host RAM and only the cohort
    # window is HBM-resident — reconcile the published streamed ceiling
    # against THIS module's independently derived wire bytes (not
    # pkernel's own model) and pin the exact supported() boundary of
    # the streamed branch, the same no-over-promise rule as the static
    # ceiling above.
    scfg = dataclasses.replace(cfg, stream_groups=True)
    streamed_ceiling = pkernel.streamed_ceiling_groups(
        scfg, with_flight=with_flight)
    window_hbm = pkernel.cohort_hbm_bytes(scfg, with_flight=with_flight)
    streamed_ok = (
        window_hbm <= pkernel.HBM_LIMIT_BYTES
        and pkernel.supported(scfg, n_groups=streamed_ceiling,
                              with_flight=with_flight)
        and not pkernel.supported(scfg,
                                  n_groups=streamed_ceiling + pkernel.GB,
                                  with_flight=with_flight))
    if not streamed_ok:
        problems.append(
            f"streamed_ceiling_groups {streamed_ceiling} is not the exact "
            f"supported() boundary under stream_groups "
            f"(with_flight={with_flight})")
    expect_streamed = (pkernel.HOST_RAM_LIMIT_BYTES
                       // (4 * derived_words * pkernel.GB)) * pkernel.GB
    if streamed_ceiling != expect_streamed:
        problems.append(
            f"streamed ceiling {streamed_ceiling} != "
            f"{expect_streamed} implied by the derived wire bytes "
            f"(4 x {derived_words} words/group, whole blocks, "
            f"{pkernel.HOST_RAM_LIMIT_BYTES} B host RAM) — the streamed "
            f"residency model drifted from the derived byte model")

    # r17 sharded streaming (DESIGN.md §16): with each of N devices
    # paging its own window slice off its own host-RAM allocation, the
    # ceiling is N x the per-device host bound — re-derive it from THIS
    # module's wire bytes (N x expect_streamed, the multi-host/pod
    # allocation model supported() budgets), pin the exact boundary at
    # 8 devices, and hold the ISSUE r17 acceptance floor: >= 4x the
    # 1-device streamed ceiling at 8 devices.
    ND_SHARDED = 8
    sharded_ceiling = pkernel.streamed_ceiling_groups(
        scfg, n_devices=ND_SHARDED, with_flight=with_flight)
    window_hbm_sharded = pkernel.cohort_hbm_bytes(
        scfg, with_flight=with_flight, n_devices=ND_SHARDED)
    sharded_ok = (
        window_hbm_sharded <= pkernel.HBM_LIMIT_BYTES
        and pkernel.supported(scfg, n_groups=sharded_ceiling,
                              n_devices=ND_SHARDED,
                              with_flight=with_flight)
        and not pkernel.supported(scfg,
                                  n_groups=sharded_ceiling + pkernel.GB,
                                  n_devices=ND_SHARDED,
                                  with_flight=with_flight))
    if not sharded_ok:
        problems.append(
            f"sharded streamed ceiling {sharded_ceiling} at {ND_SHARDED} "
            f"devices is not the exact supported() boundary "
            f"(with_flight={with_flight})")
    if sharded_ceiling != ND_SHARDED * expect_streamed:
        problems.append(
            f"sharded streamed ceiling {sharded_ceiling} != "
            f"{ND_SHARDED} x {expect_streamed} implied by the derived "
            f"wire bytes over {ND_SHARDED} per-device host-RAM "
            f"allocations — the sharded residency model drifted from "
            f"the derived byte model")
    if streamed_ceiling and sharded_ceiling < 4 * streamed_ceiling:
        problems.append(
            f"sharded streamed ceiling {sharded_ceiling} at {ND_SHARDED} "
            f"devices is under 4x the 1-device ceiling "
            f"{streamed_ceiling} — the r17 scaling floor")

    return {
        "config": {"k": cfg.k, "log_cap": cfg.log_cap,
                   "max_entries_per_msg": cfg.max_entries_per_msg,
                   "clients": cfg.clients_u32 != 0,
                   "client_slots": (cfg.client_slots
                                    if cfg.clients_u32 else 0),
                   "prevote": cfg.prevote,
                   "transfer": cfg.transfer_u32 != 0,
                   "with_flight": with_flight},
        "leaves": rows,
        "state_words_derived": state_words,
        "kinit_words_per_group": kinit_words,
        "wire_words_derived": derived_words,
        "wire_words_pinned": pinned_wire,
        "wire_bytes_derived": 4 * derived_words,
        "wire_bytes_pinned": 4 * pinned_wire,
        "widening": {
            "leaves": [r["name"] for r in widened],
            "wire_bytes": sum(4 * r["wire_words"] for r in widened),
            "native_bytes": sum(r["native_bytes"] for r in widened),
            "waste_bytes_per_group": waste,
        },
        "hbm": {"ceiling_groups": ceiling,
                "boundary_exact": bool(hbm_ok),
                "limit_bytes": pkernel.HBM_LIMIT_BYTES,
                # 2 = in+out buffers live across a launch; 1 under the
                # alias_wire dial (input/output aliasing + donation).
                "residency_buffers": pkernel._residency_buffers(cfg),
                # r16 cohort streaming: with the fleet paged from host
                # RAM the ceiling is host-bound — only the
                # stream-window blocks (prev awaiting d2h + current x
                # residency + next prefetched) are HBM-resident.
                "streamed": {
                    "ceiling_groups": streamed_ceiling,
                    "boundary_exact": bool(streamed_ok),
                    "host_limit_bytes": pkernel.HOST_RAM_LIMIT_BYTES,
                    "cohort_blocks": scfg.cohort_blocks,
                    "stream_windows": pkernel._stream_windows(scfg),
                    "window_hbm_bytes": window_hbm,
                    # r17: the device axis — per-device host-RAM
                    # allocations (multi-host/pod model), whole-block
                    # per-device window slices.
                    "sharded": {
                        "n_devices": ND_SHARDED,
                        "ceiling_groups": sharded_ceiling,
                        "boundary_exact": bool(sharded_ok),
                        "speedup_vs_1dev": (
                            round(sharded_ceiling / streamed_ceiling, 2)
                            if streamed_ceiling else None),
                        "blocks_per_device":
                            pkernel.stream_blocks_per_device(
                                scfg, ND_SHARDED),
                        "window_hbm_bytes_per_device": window_hbm_sharded,
                    },
                }},
        "problems": problems,
    }


# Hand-pinned RESIDENT bytes/group (flight off) for the audited narrow
# layouts: {label: (wide, narrow-all-dials)}. These are the §18
# headline claims — the four derived accountings in
# `resident_bytes_model` must land on them EXACTLY, so a dtype-map edit
# that moves the resident footprint cannot ship without re-pinning here
# (the same no-silent-drift rule as the 8,308 / 11,056 / 3,552 wire
# pins). The reduction floor is the r19 acceptance bar.
_RESIDENT_PINS = {"headline": (4034, 2494), "clients": (4734, 2842)}
_NARROW_REDUCTION_FLOOR_PCT = 35.0


def resident_bytes_model(cfg: RaftConfig, with_flight: bool = False
                         ) -> dict:
    """The r19 narrow-native RESIDENT byte model (DESIGN.md §18): what
    one group keeps in HBM across the XLA scan carry, derived FOUR
    independent ways and reconciled exactly:

    1. the real `sim.init` output under `cfg`'s narrow dials, traced
       with `eval_shape` (what the engine actually keeps resident);
    2. the wide leaf shapes priced at `sim.state.narrow_spec`'s dtypes
       (the dtype map applied arithmetically, no narrowing code run);
    3. the wide total minus the per-leaf narrowing deltas
       (wide-minus-deltas — a different summation order, so a leaf the
       spec names but `narrow_state` misses cannot self-agree);
    4. the hand-pinned `_RESIDENT_PINS` constants (audited labels only).

    Metric [G]/scalar lanes and the flight rings are deliberately NOT
    narrowed (the fold arithmetic is audited at i32 — run._run_impl)
    and are priced identically on both sides. Wire invariance — the
    kernel wire, `supported()` and both streamed ceilings must not move
    under the dials — is asserted by `byte_model_problems`, which runs
    `derived_wire_model` on narrow/wide twins and compares."""
    import numpy as np

    from raft_tpu.config import NARROW_FIELDS
    from raft_tpu.obs.recorder import FLIGHT_LEAVES, RING
    from raft_tpu.sim import pkernel
    from raft_tpu.sim import state as state_mod

    problems: list[str] = []
    wide_cfg = dataclasses.replace(cfg, **{f: False for f in NARROW_FIELDS})
    st_n, _, _, _ = _specs(cfg, with_flight=False)
    st_w, _, _, _ = _specs(wide_cfg, with_flight=False)
    spec = state_mod.narrow_spec(cfg)

    rows = []
    seen = set()
    state_wide = state_narrow_real = state_narrow_spec = delta = 0
    for (name_w, lw), (name_n, ln) in zip(iter_named_leaves(st_w),
                                          iter_named_leaves(st_n)):
        if name_w != name_n:
            problems.append(f"narrow/wide leaf walks diverged: "
                            f"{name_n!r} vs {name_w!r}")
            continue
        seen.add(name_w)
        per_group = tuple(lw.shape)[1:]
        words = int(np.prod(per_group, dtype=np.int64)) if per_group else 1
        it_w = np.dtype(lw.dtype).itemsize
        dt_spec = spec.get(name_w)
        it_spec = np.dtype(dt_spec).itemsize if dt_spec is not None else it_w
        # The real narrow leaf's dtype must BE the spec's (or the wide
        # one when unlisted) — a narrow_state that skips a spec'd leaf
        # or narrows an unlisted one fails here, not silently.
        want = np.dtype(dt_spec) if dt_spec is not None else np.dtype(lw.dtype)
        if np.dtype(ln.dtype) != want:
            problems.append(
                f"state leaf {name_w}: narrow init dtype {ln.dtype} != "
                f"{want} promised by narrow_spec")
        state_wide += it_w * words
        state_narrow_real += np.dtype(ln.dtype).itemsize * words
        state_narrow_spec += it_spec * words
        delta += (it_w - it_spec) * words
        rows.append({
            "name": name_w, "dtype_wide": str(np.dtype(lw.dtype)),
            "dtype_narrow": str(np.dtype(ln.dtype)),
            "shape_per_group": list(per_group),
            "bytes_wide": it_w * words,
            "bytes_narrow": it_spec * words,
            "narrowed": dt_spec is not None,
        })
    for name in spec:
        if name not in seen:
            problems.append(f"narrow_spec names {name!r} but no such leaf "
                            f"exists under this cfg — a dead dtype-map "
                            f"entry (or a walk that skipped it)")

    # Metric lanes ride wide on both sides — one 4-byte lane per active
    # non-row leaf, the same lane convention as _state_words_per_group's
    # scalar tail (scalars accumulate per group in-kernel).
    lane_bytes = 4 * sum(1 for n in pkernel._active_metric_leaves(cfg)
                         if n not in pkernel.ROW_METRIC_LEAVES)
    flight_bytes = 4 * RING * len(FLIGHT_LEAVES) if with_flight else 0
    tail = lane_bytes + flight_bytes

    wide_total = state_wide + tail
    narrow_real = state_narrow_real + tail
    narrow_spec_total = state_narrow_spec + tail
    narrow_delta = wide_total - delta

    if not (narrow_real == narrow_spec_total == narrow_delta):
        problems.append(
            f"narrow resident accountings disagree: real-init "
            f"{narrow_real} vs spec-priced {narrow_spec_total} vs "
            f"wide-minus-deltas {narrow_delta} B/group "
            f"(with_flight={with_flight})")
    reduction_pct = (100.0 * (wide_total - narrow_real) / wide_total
                     if wide_total else 0.0)
    return {
        "leaves": rows,
        "resident_bytes_wide": wide_total,
        "resident_bytes_narrow": narrow_real,
        "resident_bytes_narrow_spec": narrow_spec_total,
        "resident_bytes_narrow_delta": narrow_delta,
        "metric_lane_bytes": lane_bytes,
        "flight_bytes": flight_bytes,
        "reduction_pct": round(reduction_pct, 2),
        "problems": problems,
    }


def narrow_resident_bytes_per_group(cfg: RaftConfig) -> int:
    """The manifest figure (obs.manifest.NARROW_KEYS): resident
    bytes/group under `cfg`'s narrow dials, flight off."""
    return int(resident_bytes_model(cfg)["resident_bytes_narrow"])


def all_dials_cfg(cfg: RaftConfig) -> RaftConfig:
    """`cfg` with every narrow dial on (donation included — it changes
    residency multiples, not the byte model)."""
    from raft_tpu.config import NARROW_FIELDS
    return dataclasses.replace(cfg, **{f: True for f in NARROW_FIELDS})


def narrow_model_problems() -> list[str]:
    """The r19 audit entry point: reconcile the four resident
    accountings on the audited labels, pin the headline/clients
    wide->narrow byte pairs exactly, hold the >= 35% all-dials
    reduction floor, and prove WIRE invariance — the derived wire
    model, `supported()` ceiling and both streamed ceilings must be
    byte-identical between every narrow cfg and its all-dials-off
    twin (the dials re-declare resident dtypes; the kernel wire
    computes wide inside the chunk and never moves)."""
    from raft_tpu.config import NARROW_FIELDS

    out: list[str] = []
    for label, base in (("headline", headline_cfg()),
                        ("clients", clients_cfg())):
        ncfg = all_dials_cfg(base)
        model = resident_bytes_model(ncfg)
        out.extend(f"narrow model [{label}]: {p}"
                   for p in model["problems"])
        pin_wide, pin_narrow = _RESIDENT_PINS[label]
        if model["resident_bytes_wide"] != pin_wide:
            out.append(f"narrow model [{label}]: derived wide resident "
                       f"{model['resident_bytes_wide']} B/group != pinned "
                       f"{pin_wide}")
        if model["resident_bytes_narrow"] != pin_narrow:
            out.append(f"narrow model [{label}]: derived narrow resident "
                       f"{model['resident_bytes_narrow']} B/group != "
                       f"pinned {pin_narrow}")
        if model["reduction_pct"] < _NARROW_REDUCTION_FLOOR_PCT:
            out.append(
                f"narrow model [{label}]: all-dials reduction "
                f"{model['reduction_pct']}% is under the "
                f"{_NARROW_REDUCTION_FLOOR_PCT}% r19 floor")
        # Wire invariance: every wire figure a ceiling/budget reads must
        # be identical across the dial flip.
        for wf in (True, False):
            wn = derived_wire_model(ncfg, with_flight=wf)
            ww = derived_wire_model(base, with_flight=wf)
            for key in ("wire_words_derived", "wire_words_pinned",
                        "kinit_words_per_group"):
                if wn[key] != ww[key]:
                    out.append(
                        f"narrow model [{label}, flight="
                        f"{'on' if wf else 'off'}]: {key} moved under the "
                        f"narrow dials ({ww[key]} -> {wn[key]}) — the wire "
                        f"must be layout-invariant")
            hn, hw = wn["hbm"], ww["hbm"]
            if (hn["ceiling_groups"], hn["streamed"]["ceiling_groups"],
                hn["streamed"]["sharded"]["ceiling_groups"]) != \
               (hw["ceiling_groups"], hw["streamed"]["ceiling_groups"],
                    hw["streamed"]["sharded"]["ceiling_groups"]):
                out.append(
                    f"narrow model [{label}, flight="
                    f"{'on' if wf else 'off'}]: an HBM/streamed ceiling "
                    f"moved under the narrow dials")
        # Dials-off is the identity: the narrow model of the WIDE cfg
        # must report zero reduction and an empty dtype map.
        wmodel = resident_bytes_model(base)
        if (wmodel["resident_bytes_narrow"]
                != wmodel["resident_bytes_wide"]):
            out.append(f"narrow model [{label}]: dials-off cfg reports a "
                       f"nonzero reduction — narrowing leaked past its "
                       f"dials")
    # A lone donate_scan dial changes residency multiples, never the
    # byte model or any leaf dtype.
    dcfg = dataclasses.replace(headline_cfg(), donate_scan=True)
    dmodel = resident_bytes_model(dcfg)
    if dmodel["resident_bytes_narrow"] != dmodel["resident_bytes_wide"]:
        out.append("narrow model: a lone donate_scan dial changed the "
                   "resident byte model — donation must not touch dtypes")
    assert NARROW_FIELDS  # the registry the dials-off twin is built from
    return out


def audit_cfgs() -> list:
    """(label, cfg) pairs every audit derives and reconciles: the two
    published baselines (8,308 B/group headline, 11,056 B/group client
    universe — the r13 off-path pins) plus their packed/dialed variants
    (7,136 / 9,884 B/group packed; the all-dials ceiling-run layout) —
    one list, shared by `byte_model_problems` and
    `analysis.audit_report` so the packed layouts are audited wherever
    the baselines are."""
    packed = dict(pack_bools=True, pack_ring=True)
    return [
        ("headline", headline_cfg()),
        ("clients", clients_cfg()),
        ("headline-packed", dataclasses.replace(headline_cfg(), **packed)),
        ("clients-packed", dataclasses.replace(clients_cfg(), **packed)),
        ("headline-ceiling", dataclasses.replace(
            headline_cfg(), alias_wire=True, wire_hist=False, **packed)),
        # r19: the narrow-native layouts — the WIRE model must reconcile
        # under the dials too (it is dial-invariant; the resident-side
        # arithmetic is narrow_model_problems' job).
        ("headline-narrow", all_dials_cfg(headline_cfg())),
        ("clients-narrow", all_dials_cfg(clients_cfg())),
    ]


def byte_model_problems() -> list[str]:
    """The audit entry point: derive + reconcile every config a
    published wire number rides on — the r12 baselines AND the r13
    packed/dialed layouts (`audit_cfgs`), flight on and off."""
    out = []
    for label, cfg in audit_cfgs():
        for wf in (True, False):
            model = derived_wire_model(cfg, with_flight=wf)
            out.extend(f"byte model [{label}, flight={'on' if wf else 'off'}]"
                       f": {p}" for p in model["problems"])
    out.extend(narrow_model_problems())
    return out
