"""Quorum reductions over the K (replica) axis, batched over groups.

The north star maps vote counting to a masked popcount and commit advance
to a k-th order statistic of ``match_index`` ("segment-reduce /
prefix-scan"). Both are written for ONE node (vectors of length K) and
lifted over `[G, K]` with `vmap` by the caller — K is a tiny compile-time
constant (typically 5), so a full sort is a handful of vectorized
compare-exchanges; the batch axis G is where the parallelism lives.

Semantics are pinned to the CPU oracle, `core/node.py`:

- `vote_count` == ``sum(self.votes)`` in `node.py` `_on_rv_resp`.
- `commit_candidate` == the `matches[majority - 1]` computation in
  `node.py` `phase_a`: peer match indices sorted descending, with
  ``last_index`` prepended as the leader's own (always-largest-ranked)
  entry — NOT mixed into the sort. `tests/test_quorum.py` property-tests
  this equivalence on random states.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def vote_count(votes):
    """Number of granted votes. ``votes``: bool[K] (or any trailing shape)."""
    return jnp.sum(votes.astype(jnp.int32), axis=-1)


def popcount(mask):
    """Set bits of an i32/u32 bitmask."""
    return jax.lax.population_count(
        jnp.asarray(mask).astype(jnp.uint32)).astype(jnp.int32)


def voter_majority(voters):
    """Majority size of a voter bitmask (node.py `majority_of`)."""
    return popcount(voters) // 2 + 1


def voter_bits(voters, k: int):
    """bool[K]: lane p is a member of the voter bitmask."""
    return ((voters >> jnp.arange(k, dtype=jnp.int32)) & 1) == 1


def vote_won(votes, voters, k: int):
    """`Node._vote_quorum`: granted votes from CURRENT-config voters
    reach that config's majority. ``votes``: bool[K]; ``voters``: i32."""
    granted = jnp.sum((votes & voter_bits(voters, k)).astype(jnp.int32), -1)
    return granted >= voter_majority(voters)


def commit_candidate_voters(match_index, last_index, node_id, voters, k: int):
    """Voters-aware commit tally (node.py phase_a): the majority(voters)-th
    largest replication index among voters, where the leader contributes
    `last_index` for itself iff it is a voter. Returns -1 when no voters
    exist (callers mask). Matches the CPU sort exactly: non-voters are
    forced to -1 (real indices are >= 0) and the k-lane descending sort's
    element at majority-1 is selected by one-hot."""
    lanes = jnp.arange(k, dtype=jnp.int32)
    own = lanes == node_id
    vals = jnp.where(voter_bits(voters, k),
                     jnp.where(own, last_index, match_index),
                     jnp.int32(-1))
    desc = jnp.sort(vals)[::-1]
    pick = voter_majority(voters) - 1
    return jnp.sum(jnp.where(lanes == pick, desc, 0), -1)


def commit_candidate(match_index, last_index, node_id, k: int, majority: int):
    """The highest index N replicated on a majority, per `node.py` phase_a.

    Args:
      match_index: int32[K] — the leader's view of peer replication.
      last_index: int32 scalar — the leader's own last log index.
      node_id: int32 scalar — the leader's id (its own match slot is
        excluded from the sort; the leader "matches itself" at
        ``last_index``, ranked first regardless of value).
      k, majority: static config constants.

    Returns int32 scalar: the candidate commit index (still subject to the
    §5.4.2 current-term check, done by the caller).
    """
    if majority == 1:
        return last_index
    # Exclude the self slot by forcing it below any real match index
    # (match_index >= 0 always), then take the (majority-1)-th largest of
    # the K-1 peer values == index majority-2 of the descending sort.
    peers = jnp.where(jnp.arange(k) == node_id, jnp.int32(-1), match_index)
    desc = jnp.sort(peers)[::-1]
    return desc[majority - 2]
