"""Quorum reductions over the K (replica) axis, batched over groups.

The north star maps vote counting to a masked popcount and commit advance
to a k-th order statistic of ``match_index`` ("segment-reduce /
prefix-scan"). Both are written for ONE node (vectors of length K) and
lifted over `[G, K]` with `vmap` by the caller — K is a tiny compile-time
constant (typically 5), so a full sort is a handful of vectorized
compare-exchanges; the batch axis G is where the parallelism lives.

Semantics are pinned to the CPU oracle, `core/node.py`:

- `vote_count` == ``sum(self.votes)`` in `node.py` `_on_rv_resp`.
- `commit_candidate` == the `matches[majority - 1]` computation in
  `node.py` `phase_a`: peer match indices sorted descending, with
  ``last_index`` prepended as the leader's own (always-largest-ranked)
  entry — NOT mixed into the sort. `tests/test_quorum.py` property-tests
  this equivalence on random states.
"""

from __future__ import annotations

import jax.numpy as jnp


def vote_count(votes):
    """Number of granted votes. ``votes``: bool[K] (or any trailing shape)."""
    return jnp.sum(votes.astype(jnp.int32), axis=-1)


def commit_candidate(match_index, last_index, node_id, k: int, majority: int):
    """The highest index N replicated on a majority, per `node.py` phase_a.

    Args:
      match_index: int32[K] — the leader's view of peer replication.
      last_index: int32 scalar — the leader's own last log index.
      node_id: int32 scalar — the leader's id (its own match slot is
        excluded from the sort; the leader "matches itself" at
        ``last_index``, ranked first regardless of value).
      k, majority: static config constants.

    Returns int32 scalar: the candidate commit index (still subject to the
    §5.4.2 current-term check, done by the caller).
    """
    if majority == 1:
        return last_index
    # Exclude the self slot by forcing it below any real match index
    # (match_index >= 0 always), then take the (majority-1)-th largest of
    # the K-1 peer values == index majority-2 of the descending sort.
    peers = jnp.where(jnp.arange(k) == node_id, jnp.int32(-1), match_index)
    desc = jnp.sort(peers)[::-1]
    return desc[majority - 2]
