"""Batched per-group reduction ops for the TPU path (DESIGN.md §5)."""
