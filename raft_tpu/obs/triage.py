"""Divergence triage: bisect two engine trajectories to the first
divergent tick, then name the first divergent leaf (DESIGN.md §8).

A bare `state_identical: false` names neither WHERE nor WHEN the two
engines parted. Triage exploits the property the whole repo is built
on — an engine is a deterministic pure function of (state, n_ticks,
t0), pinned by the checkpoint/resume tests — to re-execute cheaply:
compare at chunk boundaries until the first unequal boundary, then
re-run BOTH engines tick-by-tick from the last boundary where they were
still byte-identical (one shared state, so re-execution is exact), and
report the first tick whose post-states differ plus the first
divergent leaf path (utils.trees.trees_equal_why).
"""

from __future__ import annotations

from raft_tpu.utils.trees import trees_equal_why


def bisect_divergence(engine_a, engine_b, st0, n_ticks: int, t0: int = 0,
                      chunk: int = 16):
    """First divergent (tick, leaf) between two engine trajectories.

    `engine_x(st, n, t)` runs n ticks from absolute tick t and returns
    the evolved state (e.g. ``lambda st, n, t: run(cfg, st, n, t)[0]``;
    a pkernel wrapper works the same). Both engines start from `st0` at
    `t0`. Returns None when every chunk boundary over [t0, t0+n_ticks)
    is byte-identical, else::

        {"tick": first tick t whose post-tick states differ,
         "leaf_report": first divergent leaf path + dtype/shape + first
                        differing element (trees_equal_why),
         "boundary": the (start, end) chunk the bisection narrowed}

    Cost: one pass at `chunk` granularity plus at most `chunk` single-
    tick re-executions — two compiled programs per engine (n=chunk,
    n=1), not one per tick.
    """
    sa = sb = st0
    t, end = t0, t0 + n_ticks
    while t < end:
        n = min(chunk, end - t)
        na = engine_a(sa, n, t)
        nb = engine_b(sb, n, t)
        ok, _ = trees_equal_why(na, nb)
        if ok:
            sa, sb, t = na, nb, t + n
            continue
        for dt in range(n):
            sa = engine_a(sa, 1, t + dt)
            sb = engine_b(sb, 1, t + dt)
            ok, why = trees_equal_why(sa, sb)
            if not ok:
                return {"tick": t + dt, "leaf_report": why,
                        "boundary": (t, t + n)}
        raise AssertionError(
            "chunk diverged but its tick-by-tick re-execution did not — "
            "an engine is not a deterministic function of (state, t0)")
    return None


# ------------------------------------------------- oracle lockstep leg


def oracle_trace(cfg, n_groups: int, n_ticks: int):
    """[T, G, K] int64 numpy trace of the CPU oracle (one `Cluster`
    per group, ticked in lockstep, `snapshot()` per tick) over
    `sim.run`'s trace surface plus the aliveness bit — THE oracle-side
    harness every oracle-vs-batched differential shares
    (tests/test_differential.py, tests/test_nemesis.py,
    `kernel_sweep.py --nemesis`), so a change to the trace surface or
    the snapshot timing convention lands in one place. Returns
    (field -> array, live clusters)."""
    import numpy as np

    from raft_tpu.core.cluster import Cluster
    from raft_tpu.sim.run import TRACE_FIELDS

    fields = TRACE_FIELDS + ("alive",)
    clusters = [Cluster(cfg, group=g) for g in range(n_groups)]
    out = {f: np.zeros((n_ticks, n_groups, cfg.k), np.int64)
           for f in fields}
    for t in range(n_ticks):
        for g, c in enumerate(clusters):
            c.tick()
            for k, view in enumerate(c.snapshot()):
                for f in fields:
                    out[f][t, g, k] = getattr(view, f)
    return out, clusters


def oracle_divergence(cfg, n_groups: int, n_ticks: int,
                      oracle_groups: int | None = None):
    """First divergence between the CPU oracle and the XLA scan on the
    per-node trace surface, or None when lockstep holds. The batched
    side runs the FULL `n_groups`; the oracle runs the first
    `oracle_groups` (groups are independent and their identity is the
    global group id, so the slice is exact). Returns
    {tick, group, node, field, cpu, jax} on divergence."""
    import numpy as np

    from raft_tpu import sim
    from raft_tpu.sim.run import trace

    g_oracle = n_groups if oracle_groups is None \
        else min(oracle_groups, n_groups)
    cpu, _ = oracle_trace(cfg, g_oracle, n_ticks)
    _, jx = trace(cfg, sim.init(cfg, n_groups=n_groups), n_ticks)
    for f, a in cpu.items():
        b = np.asarray(jx[f]).astype(np.int64)[:, :g_oracle]
        if not np.array_equal(a, b):
            t, g, k = (int(x) for x in np.argwhere(a != b)[0])
            return {"tick": t, "group": g, "node": k, "field": f,
                    "cpu": int(a[t, g, k]), "jax": int(b[t, g, k])}
    return None
