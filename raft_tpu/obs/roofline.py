"""Roofline attainment: the HBM/FLOP-bound rounds/s ceiling every bench
number is measured against (DESIGN.md §12).

DESIGN.md §7 established the tick is an IO problem (17.8 GB accessed vs
13.2 GFLOP at 100K groups), echoing the hardware-consensus literature's
claim that consensus is data movement, not arithmetic (PAPERS.md,
arXiv:1605.05619). This module turns that observation into a per-
segment instrument: for each (cfg, G, engine) it derives

- **bytes moved per tick** from the PR-11 auditor's reconciled byte
  model (`analysis.bytemodel.derived_wire_model`) — the ONE byte
  accounting in the repo; no second hand-pinned copy here. The XLA
  scan must carry the resident per-group state (native dtypes) through
  HBM every tick; the fused-chunk kernel moves the full wire form
  (i32 lanes, histograms + flight rings included) once per CHUNK-tick
  launch. Both are *floors*: the minimum traffic the engine's
  residency scheme permits, so the predicted ceiling is an upper bound
  and attainment (measured/predicted) is an honest efficiency figure.
- **FLOPs per tick** from `jax.jit(tick).lower(...).compile()
  .cost_analysis()` at a small probe shape, scaled linearly in G (the
  tick is elementwise over groups; per-group cost is G-independent).
  The same compile also reports XLA's *actual* scheduled traffic
  (``bytes accessed``) — recorded next to the floor so the
  materialized-intermediates blowup (~22x at the headline shape) is a
  published number, not DESIGN.md lore.

The ceiling: predicted ticks/s = 1 / max(bytes/tick / HBM peak,
FLOPs/tick / VPU peak), `bound` names the binding resource, and
predicted rounds/s = predicted ticks/s x steady-state commits/tick
(G x (cmds_per_tick + client_rate); 0 for the election-only config-2
shape, whose workload commits nothing by construction — attainment is
still defined there via ticks/s).

Peaks default to the TPU v5 lite the bench history was measured on and
follow env overrides on other parts: $RAFT_TPU_HBM_GBPS (819 GB/s
default, the figure DESIGN.md §7 used) and $RAFT_TPU_VPU_GFLOPS
(14,300 — back-derived from §7's "13.2 GFLOP is ~6% of the VPU budget
at 65 ticks/s" calibration). On a CPU box the prediction side still
runs (eval_shape + one tiny probe compile, no accelerator needed) with
``measured_ticks_per_sec=None`` — the model is testable everywhere,
and the bench stamps attainment only for real TPU walls.
"""

from __future__ import annotations

import dataclasses
import json
import os

# v5e HBM peak the DESIGN.md §7 arithmetic used.
DEFAULT_HBM_GBPS = 819.0
# v5e VPU peak back-derived from §7 ("13.2 GFLOP ~ 6% of the VPU
# budget at the measured 65 ticks/s" => ~14.3 TFLOP/s). An estimate —
# the hbm/flops classification is insensitive to 2x error here because
# the two candidate times differ by orders of magnitude on both
# engines; override with $RAFT_TPU_VPU_GFLOPS for other parts.
DEFAULT_VPU_GFLOPS = 14_300.0

# Host<->HBM link peak for the r16 cohort-paging overlap model
# (DESIGN.md §15): the PCIe path `jax.device_put` / host readback
# rides. Defaults to a PCIe gen4 x16-class 32 GB/s; override with
# $RAFT_TPU_HOST_GBPS on other hosts (gen3 x16: ~16, gen5: ~64).
DEFAULT_HOST_GBPS = 32.0

HBM_ENV = "RAFT_TPU_HBM_GBPS"
VPU_ENV = "RAFT_TPU_VPU_GFLOPS"
HOST_ENV = "RAFT_TPU_HOST_GBPS"

# Ticks per kernel launch assumed when the caller does not say —
# bench.py's CHUNK (its chunk loops pass the real value through).
DEFAULT_CHUNK_TICKS = 200

# Probe group count for the FLOPs compile: one kernel block. Small
# enough that the probe compile is cheap on any box, large enough that
# per-group costs dominate the fixed overhead the linear scaling
# ignores.
FLOPS_PROBE_GROUPS = 1024

# The manifest/segment stamp every published number must carry
# (ISSUE r12 acceptance; obs.manifest defaults them to null).
ROOFLINE_FIELDS = ("predicted_rounds_per_sec", "attainment_pct", "bound")


def peak_hbm_gbps() -> float:
    return float(os.environ.get(HBM_ENV, DEFAULT_HBM_GBPS))


def peak_vpu_gflops() -> float:
    return float(os.environ.get(VPU_ENV, DEFAULT_VPU_GFLOPS))


def peak_host_gbps() -> float:
    return float(os.environ.get(HOST_ENV, DEFAULT_HOST_GBPS))


def engine_class(engine: str | None) -> str:
    """"pallas" for any fused-chunk kernel engine string (sharded or
    not), else "xla" — the residency scheme, which is what the byte
    model depends on. Prefix match, NOT substring: a fallback string
    like "xla-scan (pallas mismatch!)" names the engine that STOOD
    (the XLA scan), and pricing it with the kernel's byte model would
    overstate its ceiling ~200-fold."""
    return "pallas" if engine and engine.startswith("pallas") else "xla"


# ------------------------------------------------------------ byte model


def _derived_model(cfg, with_flight: bool) -> dict:
    from raft_tpu.analysis import bytemodel
    model = bytemodel.derived_wire_model(cfg, with_flight=with_flight)
    if model["problems"]:
        # Refuse to predict off a drifted layout — same contract as
        # analysis.startup_audit, reachable even when a caller skipped
        # the audit.
        raise RuntimeError(
            "roofline: byte model reconciliation failed:\n  "
            + "\n  ".join(model["problems"]))
    return model


def tick_byte_model(cfg, n_groups: int, engine: str | None,
                    nd: int = 1, chunk_ticks: int | None = None,
                    with_flight: bool = True) -> dict:
    """Minimum HBM bytes one tick moves PER CHIP under `engine`'s
    residency scheme, derived from the reconciled byte model.

    - xla: read + write the resident per-group bytes every tick —
      native-dtype State leaves, the per-group metric lanes, and the
      flight ring (the global [H] histograms are G-independent and
      excluded).
    - pallas: the full i32 wire form (histogram rows + flight rings
      included) crosses HBM once per `chunk_ticks`-tick launch, in and
      out, at the per-device padded group count.
    """
    from raft_tpu.sim import pkernel

    cls = engine_class(engine)
    model = _derived_model(cfg, with_flight)
    wire = model["wire_bytes_derived"]
    resident = sum(r["native_bytes"] for r in model["leaves"]
                   if r["kind"] in ("state", "metric-lane")
                   or (with_flight and r["kind"] == "flight-ring"))
    if cls == "pallas":
        chunk = chunk_ticks or DEFAULT_CHUNK_TICKS
        padded_per_dev = -(-n_groups // (nd * pkernel.GB)) * pkernel.GB
        per_tick = 2 * wire * padded_per_dev / chunk
    else:
        chunk = None
        per_tick = 2 * resident * (-(-n_groups // nd))
    # Scan-carry residency multiple (r19, DESIGN.md §18): donation
    # (cfg.donate_scan) lets XLA write the carry in place, halving PEAK
    # residency from in+out copies to one — but the read+write traffic
    # FLOOR per tick is unchanged (per_tick above stays 2x resident),
    # so donation moves the residency ceiling, never this prediction.
    # Honest by construction: a donated run that got faster than the
    # 2x-traffic ceiling would be a model bug, not a win.
    scan_buffers = 1 if (cls == "xla" and cfg.donate_scan) else 2
    return {"engine_class": cls, "wire_bytes_per_group": wire,
            "resident_bytes_per_group": resident,
            "scan_residency_buffers": scan_buffers,
            "bytes_per_tick_per_chip": per_tick,
            "chunk_ticks": chunk}


# ----------------------------------------------------------- FLOPs probe

_FLOPS_CACHE: dict = {}


def _flops_key(cfg, g: int) -> str:
    d = dataclasses.asdict(cfg)
    d.pop("seed", None)   # seed changes constants, never the program
    return json.dumps(d, sort_keys=True) + f"@{g}"


def tick_cost_analysis(cfg, probe_groups: int = FLOPS_PROBE_GROUPS) -> (
        dict | None):
    """`cost_analysis()` of ONE compiled XLA tick at the probe shape:
    {"flops": ..., "bytes_accessed": ...} per tick at `probe_groups`
    groups, or None when the backend cannot report it. Memoized per
    (cfg-minus-seed, probe shape) — the fault knobs change the traced
    program, the seed does not. Abstract lowering (eval_shape inputs),
    so no device buffers move; the compile itself is the only cost."""
    key = _flops_key(cfg, probe_groups)
    if key in _FLOPS_CACHE:
        return _FLOPS_CACHE[key]
    out = None
    try:
        import jax
        import jax.numpy as jnp

        from raft_tpu import sim
        from raft_tpu.sim.step import tick as _tick

        st = jax.eval_shape(lambda: sim.init(cfg, n_groups=probe_groups))
        lowered = jax.jit(lambda s, t: _tick(cfg, s, t)).lower(
            st, jax.ShapeDtypeStruct((), jnp.int32))
        ca = lowered.compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca and ca.get("flops") is not None:
            out = {"flops": float(ca["flops"]),
                   "bytes_accessed": (float(ca["bytes accessed"])
                                      if ca.get("bytes accessed")
                                      is not None else None)}
    except Exception:   # no backend / cost model: prediction degrades
        out = None      # to hbm-only, it must never fail a bench
    _FLOPS_CACHE[key] = out
    return out


def tick_flops(cfg, n_groups: int,
               probe_groups: int = FLOPS_PROBE_GROUPS) -> dict | None:
    """FLOPs (and XLA's scheduled bytes) per tick at `n_groups`,
    linearly scaled from the probe shape."""
    probe = min(probe_groups, n_groups)
    ca = tick_cost_analysis(cfg, probe_groups=probe)
    if ca is None:
        return None
    scale = n_groups / probe
    return {"flops_per_tick": ca["flops"] * scale,
            "xla_bytes_accessed_per_tick":
                (ca["bytes_accessed"] * scale
                 if ca["bytes_accessed"] is not None else None),
            "flops_probe_groups": probe}


# -------------------------------------------------------------- roofline


def roofline(cfg, n_groups: int, engine: str | None, nd: int = 1,
             chunk_ticks: int | None = None, with_flight: bool = True,
             measured_ticks_per_sec: float | None = None,
             flops: bool = True) -> dict:
    """The full roofline record for one (cfg, G, engine) point.

    `measured_ticks_per_sec=None` (a CPU box, or an unsupported-engine
    segment) leaves ``attainment_pct`` null — prediction always runs.
    `flops=False` skips the probe compile (hbm-only bound) for callers
    that cannot afford any compile at all."""
    bm = tick_byte_model(cfg, n_groups, engine, nd=nd,
                         chunk_ticks=chunk_ticks, with_flight=with_flight)
    fm = tick_flops(cfg, n_groups) if flops else None
    hbm_gbps, vpu_gflops = peak_hbm_gbps(), peak_vpu_gflops()
    hbm_s = bm["bytes_per_tick_per_chip"] / (hbm_gbps * 1e9)
    flops_per_chip = (fm["flops_per_tick"] / nd) if fm else None
    vpu_s = (flops_per_chip / (vpu_gflops * 1e9)
             if flops_per_chip is not None else 0.0)
    bound = "hbm" if hbm_s >= vpu_s else "flops"
    predicted_tps = 1.0 / max(hbm_s, vpu_s)
    # Steady-state committed entries per tick: the scheduled fire-hose
    # appends cmds_per_tick per group; with clients on, each of the
    # client_slots open-loop sessions submits w.p. client_rate per tick
    # (config.py §10 knobs), and every accepted op commits exactly once.
    rounds_per_tick = n_groups * (cfg.cmds_per_tick
                                  + cfg.client_slots * cfg.client_rate)
    attainment = (None if measured_ticks_per_sec is None
                  else 100.0 * measured_ticks_per_sec / predicted_tps)
    return {
        **bm,
        "n_groups": n_groups, "nd": nd,
        "flops_per_tick": fm["flops_per_tick"] if fm else None,
        "xla_bytes_accessed_per_tick":
            fm["xla_bytes_accessed_per_tick"] if fm else None,
        "peak_hbm_gbps": hbm_gbps, "peak_vpu_gflops": vpu_gflops,
        "hbm_s_per_tick": hbm_s,
        "vpu_s_per_tick": vpu_s if fm else None,
        "bound": bound,
        "predicted_ticks_per_sec": predicted_tps,
        "rounds_per_tick": rounds_per_tick,
        "predicted_rounds_per_sec": predicted_tps * rounds_per_tick,
        "measured_ticks_per_sec": measured_ticks_per_sec,
        "attainment_pct": attainment,
    }


def segment_fields(cfg, n_groups: int, engine: str | None,
                   ticks: int | None = None,
                   timed_wall_s: float | None = None, nd: int = 1,
                   chunk_ticks: int | None = None,
                   with_flight: bool = True,
                   measured: bool = True, flops: bool = True) -> dict:
    """The dict every bench segment (and its manifest record) is
    stamped with: the three contract fields (`ROOFLINE_FIELDS`) plus
    the full derivation under ``"roofline"``. `measured=False` (CPU
    box) nulls the measured side while the prediction still stands;
    `flops=False` skips the probe compile (slow-compile boxes)."""
    mtps = None
    if measured and ticks and timed_wall_s:
        mtps = ticks / timed_wall_s
    try:
        r = roofline(cfg, n_groups, engine, nd=nd, chunk_ticks=chunk_ticks,
                     with_flight=with_flight, measured_ticks_per_sec=mtps,
                     flops=flops)
    except RuntimeError:
        # A drifted byte model already failed the startup audit for
        # drivers that gate on it; a caller that didn't still gets the
        # contract keys, null.
        return {"predicted_rounds_per_sec": None, "attainment_pct": None,
                "bound": None, "roofline": None}
    return {
        "predicted_rounds_per_sec": round(r["predicted_rounds_per_sec"], 1),
        "attainment_pct": (round(r["attainment_pct"], 2)
                           if r["attainment_pct"] is not None else None),
        "bound": r["bound"],
        "roofline": {k: (round(v, 6) if isinstance(v, float) else v)
                     for k, v in r.items()},
    }


# --------------------------------------------- cohort-paging overlap model


def overlap_efficiency(cfg, chunk_ticks: int | None = None,
                       ticks_per_cohort: int | None = None,
                       with_flight: bool = True,
                       flops: bool = False,
                       n_devices: int = 1) -> dict:
    """Predicted overlap efficiency of the r16 cohort pipeline
    (DESIGN.md §15; §16 for the sharded axis): the fraction of
    steady-state pipeline time the kernel (not the host link) owns the
    critical path,

        efficiency = t_compute / max(t_compute, t_copy)

    per cohort-window residency. `t_copy` is the window's wire crossing
    the host link twice (h2d in, d2h out); `t_compute` is
    `ticks_per_cohort` ticks of the §12 per-tick kernel time at the
    window's group count — the HBM side from the reconciled byte model
    always, the VPU side only when `flops=True` buys the probe compile
    (off-TPU boxes skip it; the copy-vs-HBM comparison already bounds
    the answer from below). `ticks_per_cohort` defaults to one
    `chunk_ticks` launch per residency — the conservative cadence; a
    soak that keeps each window resident for many launches amortizes
    the copies linearly (the derivation the returned dict spells out).
    1.0 == copies fully hidden; parallel/cohort.py's `stats` measures
    the real twin (`overlap_efficiency_measured`).

    At `n_devices > 1` (the r17 sharded pipeline) every quantity is
    PER DEVICE: each device pages and computes its own
    `stream_blocks_per_device` slice of the window over its own
    host link, so both t_copy and t_compute shrink N-fold and the
    efficiency — a ratio — is unchanged for divisible windows. The
    model is symmetric (identical devices), so the per-device
    predicted split is N equal entries; the pipeline's window wall is
    the SLOWEST device's wall, which is what the measured split in
    `cohort.stream_ticks_sharded`'s stats exists to catch deviating."""
    from raft_tpu.sim import pkernel

    chunk = chunk_ticks or DEFAULT_CHUNK_TICKS
    resident_ticks = ticks_per_cohort or chunk
    bpd = pkernel.stream_blocks_per_device(cfg, n_devices)
    window_groups = bpd * n_devices * pkernel.GB
    dev_groups = bpd * pkernel.GB
    model = _derived_model(cfg, with_flight)
    wire = model["wire_bytes_derived"]
    window_bytes = wire * dev_groups
    copy_s = 2.0 * window_bytes / (peak_host_gbps() * 1e9)
    # Per-tick kernel time at the per-device window shape (§12 byte
    # model: the wire crosses HBM once in and once out per chunk-tick
    # launch).
    hbm_s = (2.0 * window_bytes / chunk) / (peak_hbm_gbps() * 1e9)
    fm = tick_flops(cfg, dev_groups) if flops else None
    vpu_s = (fm["flops_per_tick"] / (peak_vpu_gflops() * 1e9)
             if fm else 0.0)
    compute_s = resident_ticks * max(hbm_s, vpu_s)
    eff = compute_s / max(compute_s, copy_s) if copy_s > 0 else 1.0
    return {
        "overlap_efficiency_predicted": eff,
        "overlap_efficiency_per_device_predicted":
            [round(eff, 6)] * n_devices,
        "n_devices": n_devices,
        "blocks_per_device": bpd,
        "window_groups": window_groups,
        "window_groups_per_device": dev_groups,
        "window_wire_bytes_per_device": window_bytes,
        "copy_s_per_window": copy_s,
        "compute_s_per_window": compute_s,
        "ticks_per_cohort": resident_ticks,
        "chunk_ticks": chunk,
        "peak_host_gbps": peak_host_gbps(),
        "binding_side": "host-link" if copy_s > compute_s else "compute",
        "flops_side_included": fm is not None,
    }


def stream_segment_fields(cfg, measured: float | None = None,
                          chunk_ticks: int | None = None,
                          ticks_per_cohort: int | None = None,
                          with_flight: bool = True,
                          flops: bool = False,
                          n_devices: int = 1,
                          per_device_measured: list | None = None,
                          slowest_device=None) -> dict:
    """The r16 manifest stamp every segment carries
    (obs.manifest.STREAM_KEYS + r17's STREAM_MESH_KEYS, null-by-default
    in every record until stamped here): the residency knobs the
    segment's kernel engine ran with, the predicted overlap efficiency
    (meaningful — and computed — only under cfg.stream_groups) with its
    per-device split, and the measured values when the cohort runner's
    `stats` produced them (null on CPU boxes / non-streamed engines,
    same rule as attainment_pct). `per_device_measured` /
    `slowest_device` come straight from `stream_ticks_sharded`'s stats
    (the slowest device owns every window wall). Derived against the
    key registry so a manifest-side rename cannot drift past this
    producer."""
    from raft_tpu.config import STREAM_FIELDS
    from raft_tpu.obs.manifest import STREAM_KEYS, STREAM_MESH_KEYS
    from raft_tpu.sim import pkernel

    vals = {k: getattr(cfg, k) for k in STREAM_FIELDS}
    pred = None
    per_dev_pred = None
    if cfg.stream_groups:
        ov = overlap_efficiency(
            cfg, chunk_ticks=chunk_ticks, ticks_per_cohort=ticks_per_cohort,
            with_flight=with_flight, flops=flops, n_devices=n_devices)
        pred = round(ov["overlap_efficiency_predicted"], 6)
        per_dev_pred = ov["overlap_efficiency_per_device_predicted"]
    vals["overlap_efficiency_predicted"] = pred
    vals["overlap_efficiency_measured"] = (round(measured, 6)
                                           if measured is not None else None)
    # The mesh keys are null on resident engines (same rule as the
    # overlap efficiencies): stream_devices answers "how many devices
    # PAGED", which a resident segment must not claim.
    vals["stream_devices"] = n_devices if cfg.stream_groups else None
    vals["stream_blocks_per_device"] = (
        pkernel.stream_blocks_per_device(cfg, n_devices)
        if cfg.stream_groups else None)
    vals["overlap_efficiency_per_device_predicted"] = per_dev_pred
    vals["overlap_efficiency_per_device_measured"] = (
        list(per_device_measured) if per_device_measured is not None
        else None)
    vals["stream_slowest_device"] = slowest_device
    if set(vals) != set(STREAM_KEYS) | set(STREAM_MESH_KEYS):
        raise RuntimeError(
            f"obs.manifest STREAM_KEYS+STREAM_MESH_KEYS "
            f"{set(STREAM_KEYS) | set(STREAM_MESH_KEYS)} drifted from "
            f"the roofline producer {set(vals)}")
    return vals


def narrow_segment_fields(cfg) -> dict:
    """The r19 manifest stamp (obs.manifest.NARROW_KEYS, null-by-default
    in every record until stamped here): which narrow-native dials
    (config.NARROW_FIELDS) the segment ran with, plus the dial-set's
    resident bytes/group from the reconciled §18 byte model — so a
    reader pricing a rate against the narrow layout never digs through
    the config dict. Derived against the key registry so a
    manifest-side rename cannot drift past this producer (the same
    check as the stream stamp above)."""
    from raft_tpu.analysis import bytemodel
    from raft_tpu.config import NARROW_FIELDS
    from raft_tpu.obs.manifest import NARROW_KEYS

    vals = {k: getattr(cfg, k) for k in NARROW_FIELDS}
    vals["narrow_resident_bytes_per_group"] = (
        bytemodel.narrow_resident_bytes_per_group(cfg))
    if set(vals) != set(NARROW_KEYS):
        raise RuntimeError(
            f"obs.manifest NARROW_KEYS {set(NARROW_KEYS)} drifted from "
            f"the roofline producer {set(vals)}")
    return vals
