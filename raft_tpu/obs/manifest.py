"""Structured run manifests: one JSONL record per bench segment, so
BENCH_r0N numbers carry their own provenance instead of relying on the
session log that produced them (DESIGN.md §8).

Schema (one JSON object per line; `schema` bumps on breaking change):

    schema        1
    segment       segment name, e.g. "throughput" / "config4-faults"
    unix_time     emission time (host clock, seconds)
    config_hash   first 12 hex chars of sha256 over the canonical
                  (sort_keys) JSON of the RaftConfig dataclass
    config        the full RaftConfig dict the hash covers
    jax, jaxlib   library versions
    device        "platform:device_kind" of jax.devices()[0]
    mesh_shape    device-mesh shape the segment's engine ran on, e.g.
                  [8] for an 8-way group-sharded run; [1] single-chip;
                  null when the caller did not say (DESIGN.md §9 — a
                  rounds/s number without its device count is not a
                  per-chip claim)
    groups_per_device
                  G / mesh size (ceil), same null rule
    predicted_rounds_per_sec, attainment_pct, bound
                  roofline stamp (DESIGN.md §12): the HBM/FLOP-bound
                  ceiling the segment's engine was predicted to hit,
                  how much of it the measured rate attained, and which
                  resource binds ("hbm"/"flops"); null = unstamped
                  (pre-r12 records — obs.history.backfill_record adds
                  the keys as null on read, proven by the auditor's
                  manifest pass)
    trace_path    the Chrome trace-event file a --trace-dir run wrote
                  for this segment's process, same null rule
    ...           caller fields: engine, warmup_wall_s / timed_wall_s
                  (the compile-vs-run split), rates, state_identical /
                  metrics_identical / flight_identical verdicts,
                  safety_ok + unsafe_groups, counters

Destination: $RAFT_TPU_MANIFEST if set, else ./bench_manifest.jsonl,
appended — a bench run leaves one record per segment beside its JSON
line. Pass path="-" to skip the write (the record is still returned).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time

MANIFEST_ENV = "RAFT_TPU_MANIFEST"
DEFAULT_PATH = "bench_manifest.jsonl"

# r12 observability keys: in EVERY record from emission (null until the
# caller fills them), and backfilled as null onto pre-r12 records by
# obs.history.backfill_record — one list, imported by both sides and by
# the analysis auditor's manifest pass so the two rules cannot drift.
ROOFLINE_KEYS = ("predicted_rounds_per_sec", "attainment_pct", "bound",
                 "trace_path")

# r13 wire-layout keys (config.LAYOUT_FIELDS by name): which packing /
# aliasing / telemetry dials the segment's KERNEL engine ran with —
# top-level so a reader pricing a rate against a byte model never digs
# through the config dict (and a pre-r13 record, which could only have
# run the unpacked wire, reads as null = "pre-dial schema", same rule
# as the r8 mesh keys and the r12 roofline keys; obs.history backfills
# them on read, proven both directions by the auditor's manifest pass).
PACKING_KEYS = ("pack_bools", "pack_ring", "alias_wire", "wire_hist")

# r14 nemesis keys: which gray-failure program (DESIGN.md §14) the
# segment's universe ran under — the program's stable hash plus its
# human/JSON clause list (nemesis.program.to_json), top-level so a
# reader pairing numbers across fault scenarios never digs through the
# config dict. Present-but-null from birth (a null = "no nemesis
# program", which every pre-r14 record trivially satisfies — the same
# rule as the mesh/roofline/packing keys); obs.history backfills them
# on read, proven both directions by the auditor's manifest pass.
NEMESIS_KEYS = ("nemesis_program_hash", "nemesis_clauses")

# r16 cohort-streaming keys: the residency knobs (config.STREAM_FIELDS
# by name, leading) the segment's KERNEL engine ran with, plus the
# predicted/measured overlap efficiency of the host<->HBM paging
# pipeline (DESIGN.md §15) — top-level so a reader grading a streamed
# rate against the §12 overlap model never digs through the config
# dict. Present-but-null from birth (a null = "pre-streaming schema or
# resident engine", which every pre-r16 record trivially satisfies —
# the same rule as the mesh/roofline/packing/nemesis keys);
# obs.history backfills them on read, proven both directions by the
# auditor's manifest pass. Producer: obs.roofline.stream_segment_fields.
STREAM_KEYS = ("stream_groups", "cohort_blocks",
               "overlap_efficiency_predicted",
               "overlap_efficiency_measured")

# r17 sharded-streaming keys: the device axis of the cohort pipeline
# (DESIGN.md §16) — how many devices paged concurrently, the whole-
# block per-device window share, and the per-device predicted/measured
# overlap split (slowest device owns every window wall; the measured
# list and `stream_slowest_device` name it). Present-but-null from
# birth, backfilled on read, proven both directions by the auditor's
# manifest pass — the same lifecycle as every registry above.
# Producer: obs.roofline.stream_segment_fields.
STREAM_MESH_KEYS = ("stream_devices", "stream_blocks_per_device",
                    "overlap_efficiency_per_device_predicted",
                    "overlap_efficiency_per_device_measured",
                    "stream_slowest_device")

# r19 narrow-native keys: the resident-dtype dials (config.NARROW_FIELDS
# by name, leading) the segment ran with, plus the dial-set's resident
# bytes/group so a reader pricing a rate against the §18 byte model
# never digs through the config dict. Present-but-null from birth (a
# null = "pre-narrow schema or wide layout", which every pre-r19 record
# trivially satisfies — the same rule as every registry above);
# obs.history backfills them on read, proven both directions by the
# auditor's manifest pass.
NARROW_KEYS = ("narrow_scalars", "narrow_ring", "narrow_mailbox",
               "narrow_clients", "donate_scan",
               "narrow_resident_bytes_per_group")

# r20 storage-pressure keys (DESIGN.md §19): the graceful-degradation
# headline of the bench_pressure knee protocol — the max offered load
# (ops/s) meeting the p99 ack SLO under the disk-pressure nemesis, the
# shed rate the admission queue sustained there, and the hash of the
# pressure program the sweep ran under (pairs the knee with its exact
# adversary like NEMESIS_KEYS pairs rates). Present-but-null from
# birth (a null = "no pressure sweep", which every pre-r20 record
# trivially satisfies); obs.history backfills them on read, proven
# both directions by the auditor's manifest pass.
PRESSURE_KEYS = ("knee_ops_per_sec", "shed_rate_at_knee",
                 "pressure_program_hash")


def config_hash(cfg) -> str:
    """Stable short hash of the SEMANTIC config — two runs with equal
    hashes simulated the same universe schedule (same seed included).
    The r13 wire-layout dials (config.LAYOUT_FIELDS) are excluded:
    they never change what any engine computes, and the packed-vs-
    unpacked ablation pair for one universe must hash equal to be
    pairable (the dials themselves are recorded via PACKING_KEYS).
    The r16 residency knobs (config.STREAM_FIELDS) follow the same
    rule: a streamed-vs-resident pair for one universe hashes equal
    (the knobs themselves are recorded via STREAM_KEYS). The r19
    narrow-native dials (config.NARROW_FIELDS) follow it too: the
    narrow layout is a value-preserving re-declaration of the same
    State, so a narrow-vs-wide ablation pair for one universe hashes
    equal (the dials themselves are recorded via NARROW_KEYS)."""
    from raft_tpu.config import LAYOUT_FIELDS, NARROW_FIELDS, STREAM_FIELDS
    d = dataclasses.asdict(cfg)
    for k in LAYOUT_FIELDS + STREAM_FIELDS + NARROW_FIELDS:
        d.pop(k, None)
    blob = json.dumps(d, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def _versions():
    try:
        import jax
        jv = jax.__version__
    except Exception:
        jv = None
    try:
        import jaxlib
        jlv = jaxlib.__version__
    except Exception:
        jlv = None
    return jv, jlv


def emit_manifest(segment: str, cfg, device: str | None = None,
                  path: str | None = None, **fields) -> dict:
    """Append one manifest record for `segment` under `cfg`; returns the
    record. Caller passes `device` (emit never probes jax.devices()
    itself — probing can initialize a backend the caller deliberately
    avoided) and any extra fields."""
    jv, jlv = _versions()
    rec = {"schema": 1, "segment": segment,
           "unix_time": round(time.time(), 3),
           "config_hash": config_hash(cfg),
           "config": dataclasses.asdict(cfg),
           "jax": jv, "jaxlib": jlv, "device": device,
           # Mesh provenance keys exist in EVERY record (null until the
           # caller fills them) so a reader can always distinguish "ran
           # on one chip" from "device count unrecorded". The r12
           # roofline/trace keys follow the same rule.
           "mesh_shape": None, "groups_per_device": None,
           **{k: None for k in ROOFLINE_KEYS + PACKING_KEYS
              + NEMESIS_KEYS + STREAM_KEYS + STREAM_MESH_KEYS
              + NARROW_KEYS + PRESSURE_KEYS}}
    rec.update(fields)
    path = path or os.environ.get(MANIFEST_ENV) or DEFAULT_PATH
    if path != "-":
        with open(path, "a") as fh:
            fh.write(json.dumps(rec, sort_keys=True) + "\n")
    return rec
