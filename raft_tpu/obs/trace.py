"""Timeline tracing + soak heartbeat: span-based wall-clock traces in
Chrome trace-event JSON (loadable in Perfetto / chrome://tracing), plus
a periodic JSONL health snapshot for long soaks (DESIGN.md §12).

Two complementary instruments:

- **Tracer** — host-side spans (context manager or decorator) emitted
  as Chrome trace-event ``"ph": "X"`` complete events. bench.py wraps
  every segment's compile/warmup/timed regions and every timed chunk
  in spans, and `pkernel.prun` / `kmesh.prun_sharded` mark their
  launch boundaries, so a `--trace-dir` bench run yields one
  ``trace_<label>.json`` per run showing exactly where the wall went.
  Device-side detail is the profiler's job: pass ``--jax-profile`` to
  bench.py and each segment is additionally wrapped in
  ``jax.profiler.trace`` (TensorBoard/Perfetto-loadable, opt-in
  because captures are large).
- **Heartbeat** — during a long chunked run (the 60M-node-tick soak),
  a JSONL line every N chunks with the counters and flight-ring-derived
  health signals (election storms, leaderless stalls, the safety bit),
  so a soak is observable mid-flight instead of only post-mortem.

The module-level tracer slot (`set_tracer` / `span`) exists so deep
callees (pkernel.prun, kmesh.prun_sharded, bench chunk loops) can emit
spans without threading a tracer through every signature; with no
tracer installed every hook is a no-op costing one attribute read.
"""

from __future__ import annotations

import contextlib
import functools
import json
import os
import sys
import threading
import time

# ---------------------------------------------------------------- tracer

# Span categories, fixed so trace consumers (and the schema validator)
# can rely on them: segment-level phases vs per-chunk launches.
CAT_PHASE = "phase"      # compile / warmup / timed regions of a segment
CAT_CHUNK = "chunk"      # one device launch inside a timed/warmup loop
CAT_SEGMENT = "segment"  # a whole bench segment


class Tracer:
    """Collects Chrome trace-event complete spans ("ph": "X", ts/dur in
    microseconds since the tracer's epoch). Thread-safe appends; one
    process = one pid lane, host threads = tid lanes."""

    def __init__(self):
        self._epoch = time.perf_counter()
        self._lock = threading.Lock()
        self.events: list[dict] = []

    def _now_us(self) -> float:
        return (time.perf_counter() - self._epoch) * 1e6

    @contextlib.contextmanager
    def span(self, name: str, cat: str = CAT_PHASE, **args):
        """Context manager recording one complete event around the
        body. `args` land in the event's ``args`` dict (Perfetto shows
        them in the selection panel)."""
        t0 = self._now_us()
        try:
            yield self
        finally:
            ev = {"name": name, "cat": cat, "ph": "X", "ts": t0,
                  "dur": self._now_us() - t0, "pid": os.getpid(),
                  "tid": threading.get_ident() & 0x7FFFFFFF}
            if args:
                ev["args"] = args
            with self._lock:
                self.events.append(ev)

    def traced(self, name: str | None = None, cat: str = CAT_PHASE):
        """Decorator form of `span`."""
        def deco(fn):
            @functools.wraps(fn)
            def wrapper(*a, **kw):
                with self.span(name or fn.__qualname__, cat=cat):
                    return fn(*a, **kw)
            return wrapper
        return deco

    def instant(self, name: str, cat: str = CAT_PHASE, **args):
        """One instant event ("ph": "i") — markers like 'gate failed'."""
        ev = {"name": name, "cat": cat, "ph": "i", "s": "p",
              "ts": self._now_us(), "pid": os.getpid(),
              "tid": threading.get_ident() & 0x7FFFFFFF}
        if args:
            ev["args"] = args
        with self._lock:
            self.events.append(ev)

    def to_json(self) -> dict:
        """The Chrome trace-event container object."""
        with self._lock:
            return {"traceEvents": list(self.events),
                    "displayTimeUnit": "ms"}

    def save(self, path: str) -> str:
        with open(path, "w") as fh:
            json.dump(self.to_json(), fh)
        return path


def validate_trace(obj) -> list[str]:
    """Schema problems of a Chrome trace-event container (empty list ==
    valid). The subset both chrome://tracing and Perfetto require:
    a ``traceEvents`` list whose events carry name/ph/ts/pid/tid, with
    a numeric ``dur`` on every complete ("X") event. Tests and any
    manifest-attaching caller share this one validator."""
    problems = []
    if not isinstance(obj, dict) or not isinstance(
            obj.get("traceEvents"), list):
        return ["trace container is not {'traceEvents': [...]}"]
    for i, ev in enumerate(obj["traceEvents"]):
        if not isinstance(ev, dict):
            problems.append(f"event #{i} is not an object")
            continue
        for k in ("name", "ph", "ts", "pid", "tid"):
            if k not in ev:
                problems.append(f"event #{i} ({ev.get('name')!r}) missing "
                                f"required key {k!r}")
        if ev.get("ph") == "X" and not isinstance(
                ev.get("dur"), (int, float)):
            problems.append(f"event #{i} ({ev.get('name')!r}) is a "
                            f"complete span without a numeric 'dur'")
        for k in ("ts", "dur"):
            if k in ev and not isinstance(ev[k], (int, float)):
                problems.append(f"event #{i}: {k} is not numeric")
    return problems


# Module-level tracer slot: None = tracing off, every hook a no-op.
_TRACER: Tracer | None = None


def set_tracer(tracer: Tracer | None) -> Tracer | None:
    """Install (or clear, with None) the process tracer; returns the
    previous one so tests can restore it."""
    global _TRACER
    prev, _TRACER = _TRACER, tracer
    return prev


def get_tracer() -> Tracer | None:
    return _TRACER


def span(name: str, cat: str = CAT_PHASE, **args):
    """`tracer.span(...)` against the installed tracer, or a null
    context when tracing is off — the form deep callees use."""
    t = _TRACER
    if t is None:
        return contextlib.nullcontext()
    return t.span(name, cat=cat, **args)


def chunk_span(engine: str, t0: int, n_ticks: int, **args):
    """The per-chunk span BOTH engines' chunk loops emit — one shared
    producer so the XLA and kernel lanes of a trace are named
    identically (``chunk xla [t0,t0+n)`` / ``chunk pallas [...)``) and
    a trace consumer can diff the two engines' chunk cadence."""
    return span(f"chunk {engine} [{t0},{t0 + n_ticks})", cat=CAT_CHUNK,
                engine=engine, t0=int(t0), n_ticks=int(n_ticks), **args)


# ------------------------------------------------------------- heartbeat


class Heartbeat:
    """Periodic JSONL health snapshot for long chunked runs.

    Call `beat(label, tick_at, metrics, flight)` after every chunk; one
    record is appended every `every` chunks (and always on the first
    beat of a label, so even a run killed in its first minutes leaves a
    record). Health signals are derived from the same surfaces the gate
    machinery uses — GlobalMetrics-style counters from `Metrics`, storm
    /stall detection from the flight-recorder ring:

    - ``election_storm``: more completed elections in the last RING
      ticks than half the fleet — the fleet is thrashing leaders, not
      replicating (the config-2 crash-churn shape trips this by
      design; a throughput segment must not).
    - ``leaderless_stall``: some group's CURRENT leaderless streak
      exceeds the flight ring — it has been electing for > RING ticks,
      longer than the recorder can even see.
    - ``safety_ok``: the per-tick safety fold has not latched a
      violation anywhere.
    """

    def __init__(self, path: str, every: int = 10):
        if every < 1:
            raise ValueError(f"heartbeat every={every} must be >= 1")
        self.path = path
        self.every = every
        self._beats: dict[str, int] = {}

    def _due(self, label: str) -> bool:
        """Cadence: true on the first beat of a label and every
        `every`-th thereafter."""
        n = self._beats.get(label, 0)
        self._beats[label] = n + 1
        return n % self.every == 0

    def beat(self, label: str, tick_at: int, metrics, flight=None) -> (
            dict | None):
        """Maybe-append one record; returns it (or None when skipped —
        not this label's Nth chunk)."""
        if not self._due(label):
            return None
        import numpy as np

        from raft_tpu.sim.run import total_rounds, unsafe_groups
        leaderless = np.asarray(metrics.leaderless)
        rec = {
            "label": label,
            "unix_time": round(time.time(), 3),
            "tick": int(tick_at),
            "rounds_total": total_rounds(metrics),
            "elections_total": int(metrics.elections),
            "unsafe_groups": unsafe_groups(metrics),
            "safety_ok": unsafe_groups(metrics) == 0,
            "leaderless_groups": int((leaderless > 0).sum()),
            "max_leaderless_streak": int(leaderless.max(initial=0)),
        }
        if metrics.client_acked is not None:
            from raft_tpu.sim.run import (total_client_ops,
                                          total_client_retries)
            rec["client_acked_total"] = total_client_ops(metrics)
            rec["client_retries_total"] = total_client_retries(metrics)
        if flight is not None:
            from raft_tpu.obs.recorder import RING, flight_rows
            rows = flight_rows(flight)
            ring_elections = sum(r["elections"] for r in rows)
            n_groups = int(leaderless.shape[0])
            rec.update(
                ring_ticks=len(rows),
                ring_elections=ring_elections,
                ring_msgs=sum(r["msgs"] for r in rows),
                election_storm=ring_elections > n_groups // 2,
                leaderless_stall=rec["max_leaderless_streak"] > RING,
            )
        with open(self.path, "a") as fh:
            fh.write(json.dumps(rec, sort_keys=True) + "\n")
        return rec

    def beat_wire(self, label: str, tick_at: int, cfg, leaves,
                  g: int) -> dict | None:
        """The kernel-engine beat: health straight off the wire tuple
        between chunk launches — the long soak DESIGN.md §12b promises
        to make observable mid-flight runs on the PROMOTED (kernel)
        engine, so a heartbeat that only rode the XLA loops would go
        silent during exactly that window. Reads the metric lanes via
        the pkernel counter helpers (a few [GS, 128] lanes to host,
        cheap next to a chunk); flight-ring-derived keys are omitted —
        unfolding six [RING, GS, 128] rings per beat is not (kflight
        is the gate/dump path). NOTE: the readback forces the
        dispatched chunk to complete, so timed walls measured with a
        heartbeat installed include that sync (same caveat as `beat`)."""
        if not self._due(label):
            return None
        import numpy as np

        from raft_tpu.sim import pkernel
        lane = {n: np.asarray(pkernel._unfold_g(
                    pkernel._mleaf(cfg, leaves, n)))[:g]
                for n in ("leaderless", "safety")}
        unsafe = int((lane["safety"] == 0).sum())
        rec = {
            "label": label, "engine": "pallas",
            "unix_time": round(time.time(), 3),
            "tick": int(tick_at),
            "rounds_total": pkernel.kcommitted(cfg, leaves, g),
            "elections_total": pkernel.kelections(cfg, leaves, g),
            "unsafe_groups": unsafe, "safety_ok": unsafe == 0,
            "leaderless_groups": int((lane["leaderless"] > 0).sum()),
            "max_leaderless_streak": int(lane["leaderless"]
                                         .max(initial=0)),
        }
        if cfg.clients_u32:
            rec["client_acked_total"] = pkernel.kacked(cfg, leaves, g)
            rec["client_retries_total"] = pkernel.kretries(cfg, leaves, g)
        with open(self.path, "a") as fh:
            fh.write(json.dumps(rec, sort_keys=True) + "\n")
        return rec


# Module-level heartbeat slot, same pattern as the tracer.
_HEARTBEAT: Heartbeat | None = None


def set_heartbeat(hb: Heartbeat | None) -> Heartbeat | None:
    global _HEARTBEAT
    prev, _HEARTBEAT = _HEARTBEAT, hb
    return prev


def heartbeat(label: str, tick_at: int, metrics, flight=None):
    """Module-level `Heartbeat.beat` against the installed heartbeat
    (no-op when none) — what the XLA chunk loops call."""
    hb = _HEARTBEAT
    if hb is None:
        return None
    try:
        return hb.beat(label, tick_at, metrics, flight)
    except OSError as e:   # a full disk must not kill a 60M-tick soak
        print(f"[heartbeat] write failed ({e}); continuing",
              file=sys.stderr, flush=True)
        return None


def heartbeat_wire(label: str, tick_at: int, cfg, leaves, g: int):
    """Module-level `Heartbeat.beat_wire` (no-op when none) — what the
    kernel chunk loops call between launches."""
    hb = _HEARTBEAT
    if hb is None:
        return None
    try:
        return hb.beat_wire(label, tick_at, cfg, leaves, g)
    except OSError as e:
        print(f"[heartbeat] write failed ({e}); continuing",
              file=sys.stderr, flush=True)
        return None
