"""Flight recorder: a fixed-size on-device ring of per-tick per-group
aggregates, captured by BOTH engines and dumped host-side on any gate
failure (DESIGN.md §8).

Between bench boundaries the fleet used to be a black box: a failed
`state_identical` gate said nothing about WHEN behavior went strange.
The ring keeps the last `RING` ticks of six aggregate signals per
group — absolute tick, alive-leader count, election-completion bit,
max commit index, message volume, and the per-tick safety bit — so a
failure report comes with the recent aggregate history attached.

Capture is per-GROUP (no cross-group reduction on device): slot
`t % RING` of each `[RING, G]` ring is overwritten every tick. The
Pallas kernel writes the identical values into `[RING, GS, 128]` lanes
(sim/pkernel.py `_metrics_tick`), so the two engines' rings are
bit-comparable like every other gate surface; reduction over groups
happens host-side at dump time (i32 sums — exact in any order).
"""

from __future__ import annotations

import functools
import sys
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.config import RaftConfig
from raft_tpu.core.node import LEADER
from raft_tpu.sim import check
from raft_tpu.sim.run import Metrics, metrics_init, metrics_update
from raft_tpu.sim.state import I32, State, widen_state
from raft_tpu.sim.step import tick

RING = 64   # ticks of history; slot t % RING holds tick t

# Field order of `Flight` — the kernel wire appends these leaves in this
# exact order (scripts/check_metric_parity.py pins the two).
FLIGHT_LEAVES = ("tick", "leaders", "elections", "commit", "msgs", "safety")

# Mailbox occupancy fields, in the order both engines sum them for the
# message-volume signal (i32 adds are exact in any order; fixing the
# order keeps the two folds textually parallel). PreVote/TimeoutNow
# slots are skipped when their schedules are off (leaf is None / absent).
PRESENCE_FIELDS = ("rv_req_present", "rv_resp_present", "ae_req_present",
                   "ae_resp_present", "is_req_present", "is_resp_present",
                   "pv_req_present", "pv_resp_present", "tn_present")


class Flight(NamedTuple):
    """Per-group ring buffers, i32[RING, G] each ([RING, GS, 128] on the
    kernel wire). Slot s holds the most recent tick t with t % RING == s."""

    tick: jnp.ndarray       # absolute tick recorded in the slot; -1 = never
    leaders: jnp.ndarray    # alive leaders in the group that tick
    elections: jnp.ndarray  # 1 iff the group completed an election that tick
    commit: jnp.ndarray     # max commit index over the group's nodes
    msgs: jnp.ndarray       # messages in flight out of that tick
    safety: jnp.ndarray     # that tick's safety bit (1 = invariants held)


def flight_init(n_groups: int, ring: int = RING) -> Flight:
    z = jnp.zeros((ring, n_groups), I32)
    return Flight(tick=jnp.full((ring, n_groups), -1, I32), leaders=z,
                  elections=z, commit=z, msgs=z, safety=z)


def message_volume(st: State):
    """i32[G]: occupied mailbox slots after the tick — this tick's sends,
    post dead-sender erasure. The kernel mirrors this field order."""
    total = None
    for f in PRESENCE_FIELDS:
        p = getattr(st.mailbox, f)
        if p is None:
            continue
        v = jnp.sum(jnp.sum(p.astype(I32), axis=-1), axis=-1)
        total = v if total is None else total + v
    return total


def flight_update(cfg: RaftConfig, f: Flight, st: State, m_prev: Metrics,
                  t) -> Flight:
    """Record tick `t`'s aggregates into ring slot t % RING (overwrite).
    `m_prev` is the metrics BEFORE this tick's fold — the election event
    bit is derived from the previous leaderless streak, exactly as
    `metrics_update` derives it."""
    nodes = st.nodes
    ring = f.tick.shape[0]
    on = (jnp.arange(ring, dtype=I32)[:, None] == t % ring)   # [RING, 1]

    leaders = jnp.sum(((nodes.role == LEADER) & st.alive_prev).astype(I32),
                      axis=1)
    done = ((leaders > 0) & (m_prev.leaderless > 0)).astype(I32)
    commit = jnp.max(nodes.commit, axis=1)
    msgs = message_volume(st)
    safe = check.tick_safety(st, cfg.log_cap).astype(I32)

    def w(r, val):
        return jnp.where(on, val[None, :], r)

    return Flight(tick=jnp.where(on, t, f.tick),
                  leaders=w(f.leaders, leaders),
                  elections=w(f.elections, done),
                  commit=w(f.commit, commit),
                  msgs=w(f.msgs, msgs),
                  safety=w(f.safety, safe))


@functools.partial(jax.jit, static_argnums=(0, 2))
def run_recorded(cfg: RaftConfig, st: State, n_ticks: int, t0=0,
                 metrics: Metrics | None = None,
                 flight: Flight | None = None):
    """`sim.run.run` with the flight recorder riding the scan: returns
    (state, metrics, flight). The state/metrics bits are identical to
    run.run's — the ring fold only READS the post-tick state, never
    feeds back. Chunked drivers pass the returned metrics/flight back
    in to continue the same recording."""
    if metrics is None:
        metrics = metrics_init(st.alive_prev.shape[0],
                               clients=st.clients is not None)
    if flight is None:
        flight = flight_init(st.alive_prev.shape[0])

    def body(carry, t):
        s, m, f = carry
        s = tick(cfg, s, t)
        # Ring + metrics folds read the WIDE view (same convention as
        # run._run_impl): the i32 ring values stay identical under the
        # narrow dials while the scan carry stays narrow.
        sw = widen_state(cfg, s)
        f = flight_update(cfg, f, sw, m, t)
        m = metrics_update(m, sw, cfg.log_cap)
        return (s, m, f), None

    (st, metrics, flight), _ = jax.lax.scan(
        body, (st, metrics, flight), t0 + jnp.arange(n_ticks, dtype=I32))
    return st, metrics, flight


def flight_rows(f: Flight, g: int | None = None) -> list[dict]:
    """Reduce the per-group rings over groups into one dict per recorded
    tick, oldest first. `g` slices off kernel pad groups."""
    leaves = {k: np.asarray(v) for k, v in zip(Flight._fields, f)}
    if g is not None:
        leaves = {k: v[:, :g] for k, v in leaves.items()}
    ticks = leaves["tick"].max(axis=1)   # same value in every group lane
    rows = []
    for s in np.argsort(ticks, kind="stable"):
        if ticks[s] < 0:
            continue   # slot never written
        rows.append({
            "tick": int(ticks[s]),
            "leaders": int(leaves["leaders"][s].astype(np.int64).sum()),
            "elections": int(leaves["elections"][s].astype(np.int64).sum()),
            "commit_total": int(leaves["commit"][s].astype(np.int64).sum()),
            "msgs": int(leaves["msgs"][s].astype(np.int64).sum()),
            "unsafe_groups": int((leaves["safety"][s] == 0).sum()),
        })
    return rows


def dump_flight(f: Flight, g: int | None = None, label: str = "flight",
                log=None) -> list[dict]:
    """Print the ring, one line per recorded tick — called on any gate
    failure so the last RING ticks of aggregate behavior land next to
    the failure report. Returns the rows for callers that also want to
    attach them to a manifest."""
    if log is None:
        def log(s):
            print(s, file=sys.stderr, flush=True)
    rows = flight_rows(f, g)
    log(f"[{label}] flight recorder: {len(rows)} tick(s) recorded")
    for r in rows:
        log(f"[{label}]   tick {r['tick']:>6}: leaders={r['leaders']} "
            f"elections={r['elections']} commit_total={r['commit_total']} "
            f"msgs={r['msgs']} unsafe_groups={r['unsafe_groups']}")
    return rows
