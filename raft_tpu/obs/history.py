"""Bench-history normalization + regression tracking: every BENCH_r*,
MULTICHIP_* and bench_manifest.jsonl record parsed into ONE trajectory,
with a per-segment trend table and a threshold gate (DESIGN.md §12).

The repo has carried five BENCH_r0N.json snapshots recording a
7.2M -> 5.1M rounds/s XLA fade (r02 -> r04) that nothing read: the
trajectory existed on disk but was invisible. This module is the
reader. It normalizes three source shapes into one row schema::

    {"source": file, "round": N or None, "segment": str,
     "engine": "xla" | "pallas", "unit": "rounds/s" | ...,
     "value": float, "n_groups": int | None, "extra": {...}}

- **BENCH_rNN.json** driver snapshots: the ``parsed`` bench JSON line
  (headline + per-segment rates) PLUS the stderr ``tail`` — the tail
  carries the per-engine ``[xla] ... -> N rounds/s`` lines, which is
  the only place the XLA rate survives once the kernel takes the
  headline (r05+), so both are parsed and tail rows fill engines the
  JSON no longer exposes.
- **MULTICHIP_*.json** sweep grids: only ``promoted`` cells are
  throughput claims (CPU dryrun/interpret cells are correctness-only
  by construction — their wall times are compile-bound); unpromoted
  cells are counted, not trended.
- **bench_manifest.jsonl** provenance records: one row per rate-
  bearing segment record. Pre-r12 records predate the roofline/trace
  keys; `backfill_record` makes them present-but-null so every
  consumer sees one schema (the analysis auditor proves this backfill
  and the emit-side default agree).

Series identity is (segment, engine, unit): the headline shape moved
50K -> 100K groups at r03, and rounds/s is a per-chip figure both
shapes saturate, so group count is REPORTED per row but does not split
the series — exactly the comparison the ISSUE's r02->r05 XLA fade
needs. The gate: for each series, the LATEST value against the best
ancestor; a drop beyond ``threshold`` is a regression (latency-like
units invert: a rise is the regression).
"""

from __future__ import annotations

import glob
import json
import os
import re

# One engine-classification rule with the roofline model — a string
# handled by one consumer but not the other would misfile a series
# here while mis-pricing its ceiling there.
from raft_tpu.obs.roofline import engine_class  # noqa: F401  (re-export)

# Manifest keys added by the r12 observability layer — present (null
# until filled) on every record emit_manifest writes from r12 on, and
# backfilled as null onto older records by `backfill_record`. Declared
# as this module's own literal (the repo's registry idiom) and proven
# equal to the emit side's obs.manifest.ROOFLINE_KEYS by the auditor's
# manifest pass (analysis/contracts.py).
R12_MANIFEST_KEYS = ("predicted_rounds_per_sec", "attainment_pct",
                     "bound", "trace_path")

# Manifest keys added by the r13 packed-wire layer (the kernel layout
# dials a segment ran with) — same present-from-birth / backfilled-as-
# null contract as the r12 keys. Its own literal (the registry idiom),
# proven equal to obs.manifest.PACKING_KEYS by the auditor.
R13_MANIFEST_KEYS = ("pack_bools", "pack_ring", "alias_wire", "wire_hist")

# Manifest keys added by the r14 nemesis scenario compiler (the
# gray-failure program a segment's universe ran under: program hash +
# clause list) — same present-from-birth / backfilled-as-null contract.
# Its own literal (the registry idiom), proven equal to
# obs.manifest.NEMESIS_KEYS by the auditor.
R14_MANIFEST_KEYS = ("nemesis_program_hash", "nemesis_clauses")

# Manifest keys added by the r16 cohort-paging layer (the residency
# knobs a segment's kernel engine ran with + the predicted/measured
# overlap efficiency of the host<->HBM pipeline, DESIGN.md §15) — same
# present-from-birth / backfilled-as-null contract. Its own literal
# (the registry idiom), proven equal to obs.manifest.STREAM_KEYS by
# the auditor.
R16_MANIFEST_KEYS = ("stream_groups", "cohort_blocks",
                     "overlap_efficiency_predicted",
                     "overlap_efficiency_measured")

# Manifest keys added by the r17 sharded-streaming layer (the device
# axis of the cohort pipeline: device count, per-device window blocks,
# per-device predicted/measured overlap split, slowest device —
# DESIGN.md §16) — same present-from-birth / backfilled-as-null
# contract. Its own literal (the registry idiom), proven equal to
# obs.manifest.STREAM_MESH_KEYS by the auditor. The engine strings
# these records carry ("pallas-streamed-sharded-Ndev") classify as
# "pallas" via `engine_class`'s prefix rule, so the regression gate
# files them with the other kernel-residency series.
R17_MANIFEST_KEYS = ("stream_devices", "stream_blocks_per_device",
                     "overlap_efficiency_per_device_predicted",
                     "overlap_efficiency_per_device_measured",
                     "stream_slowest_device")

# Manifest keys added by the r19 narrow-native layer (the resident-
# dtype dials a segment ran with + the dial-set's resident bytes/group
# from the §18 byte model) — same present-from-birth / backfilled-as-
# null contract. Its own literal (the registry idiom), proven equal to
# obs.manifest.NARROW_KEYS by the auditor.
R19_MANIFEST_KEYS = ("narrow_scalars", "narrow_ring", "narrow_mailbox",
                     "narrow_clients", "donate_scan",
                     "narrow_resident_bytes_per_group")

# Manifest keys added by the r20 storage-pressure layer (the
# bench_pressure knee protocol: max offered load meeting the p99 ack
# SLO under the disk-pressure nemesis, the shed rate sustained there,
# and the pressure program's hash — DESIGN.md §19) — same
# present-from-birth / backfilled-as-null contract. Its own literal
# (the registry idiom), proven equal to obs.manifest.PRESSURE_KEYS by
# the auditor.
R20_MANIFEST_KEYS = ("knee_ops_per_sec", "shed_rate_at_knee",
                     "pressure_program_hash")

# Manifest records below this group count are smoke/--quick shapes:
# correctness drives, not trajectory points — a 1K-group quick run's
# rate joining the 100K series would trip (or mask) the regression
# gate on every segment. The smallest real headline shape in the
# checked-in history is the 10K-group config-2 segment.
QUICK_GROUP_FLOOR = 10_000

# parsed-JSON rate keys -> (segment, engine-key, n_groups-key, unit)
_PARSED_RATES = (
    ("value", "throughput", "engine", "n_groups", "rounds/s"),
    ("faulted_rounds_per_sec", "config5-faults", "config5_fault_engine",
     "config5_fault_n_groups", "rounds/s"),
    ("elections_per_sec", "config2-elections", "config2_engine", None,
     "elections/s"),
    ("linearizable_reads_per_sec", "reads", "reads_engine", None,
     "reads/s"),
    ("client_ops_per_sec", "client-slo", "client_engine", None, "ops/s"),
)

# manifest segment-name -> (rate key, unit)
_MANIFEST_RATES = {
    "throughput": ("rounds_per_sec", "rounds/s"),
    "config-4 fault run": ("rounds_per_sec", "rounds/s"),
    "config-5 fault mix": ("rounds_per_sec", "rounds/s"),
    "election-rounds": ("elections_per_sec", "elections/s"),
    "reads": ("reads_per_sec", "reads/s"),
    "client-slo fault mix": ("client_ops_per_sec", "ops/s"),
}

# One stderr tail line with a measured rate, either engine-tagged
# ("[xla] 100000 groups x 600 ticks: ... -> 7,802,521 rounds/s") or
# untagged pre-r05 ("  50000 groups x 600 ticks: ... -> 7,182,986
# rounds/s", engine implicitly the XLA scan).
_TAIL_RE = re.compile(
    r"(?:\[(?P<eng>xla|pallas)[^\]]*\]\s*)?"
    r"(?:election rounds |linearizable reads )?"
    r"(?P<groups>\d[\d,]*) groups x (?P<ticks>\d+) ticks[^\n>]*"
    r"-> (?P<rate>[\d,]+) (?P<unit>rounds|elections|reads|ops)/s")

_UNIT_SEGMENT = {"rounds": "throughput", "elections": "config2-elections",
                 "reads": "reads", "ops": "client-slo"}


def _round_of(path: str) -> int | None:
    m = re.search(r"_r(\d+)\.json", os.path.basename(path))
    return int(m.group(1)) if m else None


def backfill_record(rec: dict) -> dict:
    """A manifest record normalized to the current schema: the r12
    roofline/trace keys, the r13 wire-layout keys, the r14 nemesis
    keys, the r16 streaming keys, the r17 sharded-streaming keys, the
    r19 narrow-native keys, AND the r20 storage-pressure keys
    present-but-null when the record predates them (same rule as the
    mesh keys at r08). Returns a new dict."""
    out = dict(rec)
    for k in (R12_MANIFEST_KEYS + R13_MANIFEST_KEYS + R14_MANIFEST_KEYS
              + R16_MANIFEST_KEYS + R17_MANIFEST_KEYS
              + R19_MANIFEST_KEYS + R20_MANIFEST_KEYS):
        out.setdefault(k, None)
    return out


def _row(source, rnd, segment, engine, unit, value, n_groups,
         **extra) -> dict:
    return {"source": os.path.basename(str(source)), "round": rnd,
            "segment": segment, "engine": engine_class(engine),
            "unit": unit, "value": float(value),
            "n_groups": int(n_groups) if n_groups is not None else None,
            "extra": extra}


def parse_bench_file(path: str) -> list[dict]:
    """Rows from one BENCH_rNN.json driver snapshot (parsed JSON line +
    stderr tail; tail rows only fill (segment, engine) points the
    parsed line does not already cover)."""
    with open(path) as fh:
        doc = json.load(fh)
    rnd = _round_of(path) or doc.get("n")
    rows: list[dict] = []
    parsed = doc.get("parsed") or {}
    for key, segment, eng_key, g_key, unit in _PARSED_RATES:
        if parsed.get(key) is None:
            continue
        engine = parsed.get(eng_key) if eng_key else None
        n_groups = parsed.get(g_key) if g_key else None
        rows.append(_row(path, rnd, segment, engine, unit, parsed[key],
                         n_groups, from_="parsed"))
    seen = {(r["segment"], r["engine"]) for r in rows}
    for m in _TAIL_RE.finditer(doc.get("tail") or ""):
        segment = _UNIT_SEGMENT[m.group("unit")]
        engine = m.group("eng") or "xla"
        # XLA tail lines only: a "[pallas] ... -> N/s" line is logged
        # BEFORE the promotion differential, so on a mismatch the tail
        # carries the very rate the bench refused to publish; promoted
        # kernel numbers always reach the parsed JSON (value/engine +
        # the per-segment rate keys), so nothing real is lost.
        if engine_class(engine) == "pallas":
            continue
        if (segment, engine_class(engine)) in seen:
            continue
        seen.add((segment, engine_class(engine)))
        rows.append(_row(path, rnd, segment, engine,
                         m.group("unit") + "/s",
                         float(m.group("rate").replace(",", "")),
                         int(m.group("groups").replace(",", "")),
                         from_="tail"))
    return rows


def parse_multichip_file(path: str) -> list[dict]:
    """Rows from a MULTICHIP_*.json sweep: promoted cells only (the
    rest are correctness gates, not rates); unpromoted counts ride in
    a zero-row summary extra for the table footer."""
    with open(path) as fh:
        doc = json.load(fh)
    rnd = _round_of(path)
    rows = []
    for cell in doc.get("grid", []):
        if not cell.get("promoted"):
            continue
        wall = cell.get("wall_s")
        rounds = cell.get("rounds")
        if not wall or rounds is None:
            continue
        rows.append(_row(
            path, rnd, f"multichip-{cell['devices']}dev",
            cell.get("run", {}).get("engine", "pallas"), "rounds/s",
            rounds / wall, cell.get("groups"), devices=cell["devices"]))
    return rows


def parse_manifest_file(path: str) -> list[dict]:
    """Rows from a bench_manifest.jsonl: one per rate-bearing segment
    record, ordered (and "round"-less — unix_time is the axis), each
    record backfilled to the r12 key schema first.

    Comparability filter: only TPU records at real shapes join the
    trajectory. A CPU dev-box run or a --quick smoke
    (n_groups < QUICK_GROUP_FLOOR) appends manifest records too — by
    the sort rule those would always become a series' LATEST point and
    trip the regression gate with a ~99% "drop" against the TPU best
    (or, worse, mask a real one). Skips are announced on stderr, never
    silent — a reader must know the trajectory excluded records."""
    rows = []
    skipped = 0
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = backfill_record(json.loads(line))
            except json.JSONDecodeError:
                continue   # a torn append must not kill the reader
            seg = rec.get("segment")
            rate = _MANIFEST_RATES.get(seg)
            if rate is None or rec.get(rate[0]) is None:
                continue
            dev = rec.get("device") or ""
            g = rec.get("n_groups")
            if not dev.startswith("tpu") or (g is not None
                                             and g < QUICK_GROUP_FLOOR):
                skipped += 1
                continue
            rows.append(_row(path, None, seg, rec.get("engine"), rate[1],
                             rec[rate[0]], g,
                             unix_time=rec.get("unix_time"),
                             attainment_pct=rec.get("attainment_pct"),
                             bound=rec.get("bound")))
    if skipped:
        import sys
        print(f"[bench-history] {os.path.basename(str(path))}: skipped "
              f"{skipped} non-TPU/smoke-shape record(s) — not trajectory "
              f"points", file=sys.stderr)
    return rows


def load_history(root: str = ".", manifest: str | None = None
                 ) -> list[dict]:
    """Every row from `root`'s BENCH_r*.json + MULTICHIP_*.json plus
    the manifest JSONL ($RAFT_TPU_MANIFEST / bench_manifest.jsonl /
    explicit path), sorted by (segment, engine, round)."""
    rows: list[dict] = []
    for p in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        rows += parse_bench_file(p)
    for p in sorted(glob.glob(os.path.join(root, "MULTICHIP_*.json"))):
        rows += parse_multichip_file(p)
    mpath = manifest or os.environ.get("RAFT_TPU_MANIFEST") \
        or os.path.join(root, "bench_manifest.jsonl")
    if mpath != "-" and os.path.exists(mpath):
        rows += parse_manifest_file(mpath)
    rows.sort(key=lambda r: (r["segment"], r["engine"],
                             r["round"] if r["round"] is not None else 1e9,
                             r["extra"].get("unix_time") or 0))
    return rows


def series(rows: list[dict]) -> dict:
    """rows grouped by series identity (segment, engine, unit), order
    preserved."""
    out: dict = {}
    for r in rows:
        out.setdefault((r["segment"], r["engine"], r["unit"]),
                       []).append(r)
    return out


def trend_table(rows: list[dict]) -> str:
    """The human trajectory: one block per series, one line per point,
    with delta vs the previous point and vs the best ancestor — the
    r01->r05 XLA fade becomes visible output."""
    lines = []
    for (segment, engine, unit), pts in sorted(series(rows).items()):
        lines.append(f"{segment} [{engine}] ({unit})")
        best = None
        for i, r in enumerate(pts):
            rnd = (f"r{r['round']:02d}" if r["round"] is not None
                   else "manif")
            d_prev = d_best = ""
            if best is not None:
                prev = pts[i - 1]["value"]
                d_prev = f"{100 * (r['value'] - prev) / prev:+7.1f}% prev"
                d_best = f"{100 * (r['value'] - best) / best:+7.1f}% best"
            g = f"{r['n_groups']:>7}" if r["n_groups"] else "      ?"
            lines.append(f"  {rnd}  {g} groups  {r['value']:>14,.1f}  "
                         f"{d_prev:>14}  {d_best:>14}")
            best = r["value"] if best is None else max(best, r["value"])
        lines.append("")
    return "\n".join(lines)


def regressions(rows: list[dict], threshold: float = 0.15) -> list[dict]:
    """Series whose LATEST point dropped more than `threshold` below
    its best ancestor. Rates regress downward; a series whose unit ends
    in "ticks" (latency) would regress upward — none are trended today,
    the guard documents the rule for whoever adds one."""
    out = []
    for (segment, engine, unit), pts in sorted(series(rows).items()):
        if len(pts) < 2:
            continue
        latest = pts[-1]
        best = max(pts[:-1], key=lambda r: r["value"])
        if unit.endswith("ticks"):
            continue   # latency trending needs an inverted rule
        drop = (best["value"] - latest["value"]) / best["value"]
        if drop > threshold:
            out.append({
                "segment": segment, "engine": engine, "unit": unit,
                "latest": latest["value"], "latest_source":
                    latest["source"], "best": best["value"],
                "best_source": best["source"],
                "drop_pct": round(100 * drop, 1),
                "threshold_pct": round(100 * threshold, 1),
            })
    return out
