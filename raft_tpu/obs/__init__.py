"""Observability layer over both engines (DESIGN.md §8).

Four pieces, one evidence chain:

- **Per-tick safety fold** — `check.tick_safety` ANDed into
  `Metrics.safety` every tick by `run.metrics_update` and, on the
  Pallas path, in-kernel by `pkernel._safety_tick` (a host readback
  would dominate the tick; the in-kernel fold is a few vreg compares).
- **Flight recorder** (`obs.recorder`) — a fixed-size on-device ring of
  per-tick per-group aggregates captured by both engines and dumped
  host-side on any gate failure.
- **Divergence triage** (`obs.triage`) — chunk-boundary re-execution
  that bisects two engine trajectories to the first divergent tick,
  then names the first divergent leaf (utils.trees).
- **Run manifests** (`obs.manifest`) — every bench segment appends one
  JSONL provenance record (config hash, versions, device, compile-vs-
  run wall split, safety/identity verdicts).
"""

from raft_tpu.obs.manifest import config_hash, emit_manifest
from raft_tpu.obs.recorder import (FLIGHT_LEAVES, RING, Flight, dump_flight,
                                   flight_init, flight_rows, flight_update,
                                   run_recorded)
from raft_tpu.obs.triage import bisect_divergence

__all__ = [
    "FLIGHT_LEAVES", "RING", "Flight", "bisect_divergence", "config_hash",
    "dump_flight", "emit_manifest", "flight_init", "flight_rows",
    "flight_update", "run_recorded",
]
