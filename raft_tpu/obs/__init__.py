"""Observability layer over both engines (DESIGN.md §8, §12).

Seven pieces, one evidence chain:

- **Per-tick safety fold** — `check.tick_safety` ANDed into
  `Metrics.safety` every tick by `run.metrics_update` and, on the
  Pallas path, in-kernel by `pkernel._safety_tick` (a host readback
  would dominate the tick; the in-kernel fold is a few vreg compares).
- **Flight recorder** (`obs.recorder`) — a fixed-size on-device ring of
  per-tick per-group aggregates captured by both engines and dumped
  host-side on any gate failure.
- **Divergence triage** (`obs.triage`) — chunk-boundary re-execution
  that bisects two engine trajectories to the first divergent tick,
  then names the first divergent leaf (utils.trees).
- **Run manifests** (`obs.manifest`) — every bench segment appends one
  JSONL provenance record (config hash, versions, device, compile-vs-
  run wall split, safety/identity verdicts, roofline stamp).
- **Roofline model** (`obs.roofline`, §12) — the HBM/FLOP-bound
  rounds/s ceiling per (cfg, G, engine), derived from the auditor's
  reconciled byte model + `cost_analysis()`; every published number
  carries `predicted_rounds_per_sec` / `attainment_pct` / `bound`.
- **Timeline tracer + soak heartbeat** (`obs.trace`, §12) — Chrome
  trace-event spans over segments/warmups/chunks (Perfetto-loadable),
  plus a JSONL health snapshot every N chunks during long soaks.
- **Bench history** (`obs.history`, §12) — every BENCH_r*/MULTICHIP_*/
  manifest record normalized into one trajectory with a regression
  gate (`scripts/bench_history.py`).
"""

from raft_tpu.obs import history, roofline, trace
from raft_tpu.obs.manifest import ROOFLINE_KEYS, config_hash, emit_manifest
from raft_tpu.obs.recorder import (FLIGHT_LEAVES, RING, Flight, dump_flight,
                                   flight_init, flight_rows, flight_update,
                                   run_recorded)
from raft_tpu.obs.trace import (Heartbeat, Tracer, chunk_span, heartbeat,
                                heartbeat_wire, set_heartbeat, set_tracer,
                                span, validate_trace)
from raft_tpu.obs.triage import bisect_divergence

__all__ = [
    "FLIGHT_LEAVES", "RING", "ROOFLINE_KEYS", "Flight", "Heartbeat",
    "Tracer", "bisect_divergence", "chunk_span", "config_hash",
    "dump_flight", "emit_manifest", "flight_init", "flight_rows",
    "flight_update", "heartbeat", "heartbeat_wire", "history", "roofline",
    "run_recorded", "set_heartbeat", "set_tracer", "span", "trace",
    "validate_trace",
]
