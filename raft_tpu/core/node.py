"""A single Raft replica — the CPU reference implementation.

Implements the tick contract of DESIGN.md §2 exactly: phase D (process
inbox in canonical order), phase T (timers/roles), phase C (client
appends), phase A (commit advance / apply / compact). The TPU path
(raft_tpu/sim/step.py, built against this oracle) mirrors every branch in
here; any semantic change must be made in both backends together, and the
differential suite comparing their traces must stay green.

Log model (DESIGN.md §3): `self.log` holds entries for absolute indices
(snap_index, last_index], window-bounded by `log_cap`; the prefix up to
snap_index lives only as (snap_index, snap_term, snap_digest).
"""

from __future__ import annotations

from typing import Callable, List, Optional

from raft_tpu import config
from raft_tpu.config import CONFIG_FLAG, RaftConfig
from raft_tpu.core import rpc
from raft_tpu.utils import rng

FOLLOWER, CANDIDATE, LEADER, PRECANDIDATE = 0, 1, 2, 3
NO_VOTE = -1


def majority_of(voters: int) -> int:
    """Majority size of a voter bitmask."""
    return voters.bit_count() // 2 + 1


class Node:
    def __init__(self, cfg: RaftConfig, group: int, node_id: int, transport,
                 on_apply: Optional[Callable[[int, int, int, int], None]] = None):
        self.cfg = cfg
        self.g = group
        self.id = node_id
        self.transport = transport
        self.on_apply = on_apply  # (node_id, index, term, payload)

        # Durable state (survives crash/restart).
        self.term = 0
        self.voted_for = NO_VOTE
        self.log: List[tuple] = []   # [(term, payload)] for (snap_index, last_index]
        self.snap_index = 0
        self.snap_term = 0
        self.snap_digest = 0
        self.snap_voters = cfg.full_mask  # voter mask as of the snapshot prefix
        # Session table as of the snapshot prefix (cfg.sessions only):
        # sid -> last applied client seq. Durable with the snapshot.
        # Scheduled client traffic (cfg.client_rate > 0, DESIGN.md §10)
        # pre-registers slots 0..client_slots-1 with no applied
        # commands — bit-matching the batched path's session_seq init.
        self.snap_sessions: dict = (
            {s: -1 for s in range(cfg.client_slots)}
            if cfg.clients_u32 else {})
        self.rng_draws = 0           # monotone deadline-draw counter

        # Volatile state (reset on restart).
        self.role = FOLLOWER
        self.leader_id = NO_VOTE
        self.commit = 0
        self.applied = 0
        self.digest = 0
        # Live session table (exactly-once, dissertation §6.3): pure
        # state-machine state — rebuilt from snap_sessions + re-apply
        # on restart, exactly like `digest`.
        self.sessions: dict = dict(self.snap_sessions)
        self.votes = [False] * cfg.k
        self.next_index = [1] * cfg.k
        self.match_index = [0] * cfg.k
        self.election_elapsed = 0
        self.heartbeat_elapsed = 0
        self.deadline = 0
        # Ticks since last authoritative leader contact (valid AE/IS) —
        # the PreVote lease clock (dissertation §9.6): pre-votes are
        # granted only when this reaches election_min. Distinct from
        # election_elapsed, which resets on vote grants and pre-ballots;
        # resetting the lease there too would let dueling pre-candidates
        # deny each other forever.
        self.leader_elapsed = 0
        # Client-facing state (volatile, leader-only): `now` is the
        # current tick (set by the harness before phases), `ack_time[p]`
        # the last tick a current-term AppendEntries response arrived
        # from peer p (any such response proves p's deference at the
        # time it was sent), `pending_reads` the ReadIndex protocol
        # state: rid -> (read_index, registration tick).
        self.now = 0
        self.ack_time = [-1] * cfg.k
        self.pending_reads: dict = {}
        self._next_read_id = 0
        # Scheduled-read state (DESIGN.md §2c): at most one in flight,
        # as (read_index, registration tick); `reads_done` counts
        # completions and is part of the differential trace surface.
        self.sched_read = None
        self.reads_done = 0
        # Storage-pressure override (r20, DESIGN.md §19): the bounded
        # model checker forces THIS node's disk full for the current
        # tick by setting these before the phases — an adversarial
        # over-approximation of the hashed nemesis schedule, same
        # soundness argument as mcheck's adversarial crashes. The
        # harness never sets them; production pressure comes from
        # cfg.nem_disk / cfg.nem_compact.
        self.disk_override = False
        self.compact_override = False
        self._reset_election_timer()

    # ------------------------------------------------------------- log helpers

    @property
    def last_index(self) -> int:
        return self.snap_index + len(self.log)

    def term_at(self, idx: int) -> int:
        if idx == self.snap_index:
            return self.snap_term
        assert self.snap_index < idx <= self.last_index, (idx, self.snap_index)
        return self.log[idx - self.snap_index - 1][0]

    def payload_at(self, idx: int) -> int:
        assert self.snap_index < idx <= self.last_index
        return self.log[idx - self.snap_index - 1][1]

    def last_log_term(self) -> int:
        return self.term_at(self.last_index)

    # ----------------------------------------------------- membership config

    def current_config(self):
        """(voters_mask, cfg_index): the latest membership-change entry in
        the log — committed or not, per the dissertation's §4.1 rule — or
        the snapshot's config if the window holds none. Derived, never
        stored: truncation of a config entry reverts the config with no
        bookkeeping."""
        for j in range(len(self.log) - 1, -1, -1):
            _, payload = self.log[j]
            if payload & CONFIG_FLAG:
                return payload & self.cfg.full_mask, self.snap_index + 1 + j
        return self.snap_voters, self.snap_index

    def committed_config(self) -> int:
        """Voter mask implied by the committed prefix (<= commit) — what
        compaction folds into `snap_voters`, and the authority for the
        'removed leader steps down' rule."""
        hi = min(self.commit, self.last_index) - self.snap_index
        for j in range(hi - 1, -1, -1):
            _, payload = self.log[j]
            if payload & CONFIG_FLAG:
                return payload & self.cfg.full_mask
        return self.snap_voters

    def is_voter(self, node_id: Optional[int] = None) -> bool:
        i = self.id if node_id is None else node_id
        voters, _ = self.current_config()
        return bool((voters >> i) & 1)

    def _window_has_room(self, n: int = 1) -> bool:
        return self.last_index + n - self.snap_index <= self.cfg.log_cap

    def _disk_full(self) -> bool:
        """Persistence budget exhausted at the current tick (r20,
        DESIGN.md §19): every local append fails — an entry that is
        not durable must never be acked, so the AE entry walk stops
        here and the follower's partial-prefix reply (match=hi) is the
        NACK that makes the leader retransmit. In-place term rewrites
        and snapshot installs are NOT appends and stay live."""
        if self.disk_override:
            return True
        nem_disk = self.cfg.nem_disk
        return bool(nem_disk and rng.nem_disk_full(
            self.cfg.seed, nem_disk, self.g, self.id, self.now,
            self.cfg.k))

    def _compact_blocked(self) -> bool:
        """Compaction pressure at the current tick (r20, DESIGN.md
        §19): phase A's snapshot step is delayed, the log_cap ring
        genuinely fills, and `_append`'s window check becomes the
        runtime backpressure path that throttles replication."""
        if self.compact_override:
            return True
        nem_compact = self.cfg.nem_compact
        return bool(nem_compact and rng.nem_compact_block(
            self.cfg.seed, nem_compact, self.g, self.id, self.now))

    def _append(self, term: int, payload: int) -> bool:
        if not self._window_has_room(1):
            return False
        if self._disk_full():
            return False
        self.log.append((term, payload))
        return True

    # ------------------------------------------------------------ transitions

    def _reset_election_timer(self):
        self.election_elapsed = 0
        deadline = rng.election_deadline(
            self.cfg.seed, self.g, self.id, self.rng_draws,
            self.cfg.election_min, self.cfg.election_range)
        nem_skew = self.cfg.nem_skew
        if nem_skew:
            # Nemesis clock-skew clauses (DESIGN.md §14): the draw made
            # at tick `now` is skewed while a span covers it, clamped
            # at 1 — the batched `_reset_timer` mirrors this exactly.
            deadline = max(1, deadline + rng.nem_deadline_extra(
                self.cfg.seed, nem_skew, self.g, self.id, self.now))
        self.deadline = deadline
        self.rng_draws += 1

    def _step_down(self, new_term: int):
        """Observed a higher term: adopt it, become follower. No timer reset."""
        self.term = new_term
        self.role = FOLLOWER
        self.voted_for = NO_VOTE
        self.leader_id = NO_VOTE
        self.votes = [False] * self.cfg.k
        self._drop_client_state()

    def _drop_client_state(self):
        """Leadership (or the term it was held under) is gone: pending
        reads abort, deference evidence is stale."""
        self.ack_time = [-1] * self.cfg.k
        self.pending_reads = {}
        self.sched_read = None

    def _become_leader(self):
        self.role = LEADER
        self.leader_id = self.id
        self.next_index = [self.last_index + 1] * self.cfg.k
        self.match_index = [0] * self.cfg.k
        self._drop_client_state()
        # Fire the initial heartbeat in phase T of this same tick.
        self.heartbeat_elapsed = self.cfg.heartbeat_every
        # Paxos-style takeover (DESIGN.md §2a): re-propose the TOP entry —
        # and only the top — under the new term, in place. Like the common
        # "append a no-op" idiom this creates a current-term entry whose
        # replication commits the whole inherited suffix (§5.4.2), but it
        # cannot grow the log, so takeover stays live when the bounded
        # window is full of uncommitted prior-term entries. Restricting the
        # rewrite to last_index is what keeps elections safe: current-term
        # entries then exist only at-or-above every committed index, so a
        # log whose last term is T' provably extends the T'-leader's log
        # and hence holds every committed entry (the round-1 variant that
        # re-termed the whole suffix created new-term entries BELOW the
        # committed frontier and broke Leader Completeness — see §2a).
        if self.last_index > self.commit:
            pos = self.last_index - self.snap_index - 1
            self.log[pos] = (self.term, self.log[pos][1])

    def _vote_quorum(self) -> bool:
        """Votes granted by members of the CURRENT config reach its
        majority (a vote from a non-voter — e.g. a peer the latest config
        entry removed — is received but never counted)."""
        voters, _ = self.current_config()
        granted = sum(1 for p in range(self.cfg.k)
                      if self.votes[p] and (voters >> p) & 1)
        return granted >= majority_of(voters)

    def _start_election(self):
        self.term += 1
        self.role = CANDIDATE
        self.voted_for = self.id
        self.leader_id = NO_VOTE
        self.votes = [i == self.id for i in range(self.cfg.k)]
        self._reset_election_timer()
        if self._vote_quorum():   # single-voter config: instant leader
            self._become_leader()
            return
        for p in range(self.cfg.k):
            if p != self.id:
                self.transport.send(rpc.RequestVoteReq(
                    rpc.RV_REQ, self.id, p, term=self.term,
                    last_log_index=self.last_index,
                    last_log_term=self.last_log_term()))

    def _start_prevote(self):
        """Timeout with cfg.prevote: run a non-binding pre-ballot at
        term+1 instead of bumping the term (dissertation §9.6). Term and
        voted_for are untouched; a pre-vote quorum triggers the real
        election."""
        self.role = PRECANDIDATE
        self.leader_id = NO_VOTE
        self.votes = [i == self.id for i in range(self.cfg.k)]
        self._reset_election_timer()
        if self._vote_quorum():   # single-voter config: skip the pre-ballot
            self._start_election()
            return
        for p in range(self.cfg.k):
            if p != self.id:
                self.transport.send(rpc.PreVoteReq(
                    rpc.PV_REQ, self.id, p, term=self.term + 1,
                    last_log_index=self.last_index,
                    last_log_term=self.last_log_term()))

    def restart(self):
        """Dead→alive edge: durable state survives, volatile state resets."""
        self.role = FOLLOWER
        self.leader_id = NO_VOTE
        self.commit = self.snap_index
        self.applied = self.snap_index
        self.digest = self.snap_digest
        self.sessions = dict(self.snap_sessions)
        self.votes = [False] * self.cfg.k
        self.next_index = [1] * self.cfg.k
        self.match_index = [0] * self.cfg.k
        self.heartbeat_elapsed = 0
        self.leader_elapsed = 0   # fresh lease clock: deny pre-votes until
        #                           election_min ticks of observed silence
        self.reads_done = 0       # volatile counter
        self._drop_client_state()
        self._reset_election_timer()

    # ---------------------------------------------------------------- phase D

    def phase_d(self, inbox: List[rpc.Msg]):
        for m in rpc.sort_inbox(inbox):
            if m.type == rpc.RV_REQ:
                self._on_rv_req(m)
            elif m.type == rpc.RV_RESP:
                self._on_rv_resp(m)
            elif m.type == rpc.AE_REQ:
                self._on_ae_req(m)
            elif m.type == rpc.AE_RESP:
                self._on_ae_resp(m)
            elif m.type == rpc.IS_REQ:
                self._on_is_req(m)
            elif m.type == rpc.IS_RESP:
                self._on_is_resp(m)
            elif m.type == rpc.PV_REQ:
                self._on_pv_req(m)
            elif m.type == rpc.PV_RESP:
                self._on_pv_resp(m)
            elif m.type == rpc.TN_REQ:
                self._on_tn_req(m)

    def _on_rv_req(self, m: rpc.RequestVoteReq):
        if m.term > self.term:
            self._step_down(m.term)
        log_ok = (m.last_log_term > self.last_log_term()
                  or (m.last_log_term == self.last_log_term()
                      and m.last_log_index >= self.last_index))
        grant = (m.term == self.term
                 and self.voted_for in (NO_VOTE, m.src)
                 and log_ok)
        if grant:
            self.voted_for = m.src
            self._reset_election_timer()
        self.transport.send(rpc.RequestVoteResp(
            rpc.RV_RESP, self.id, m.src, term=self.term, granted=grant))

    def _on_rv_resp(self, m: rpc.RequestVoteResp):
        if m.term > self.term:
            self._step_down(m.term)
            return
        if self.role != CANDIDATE or m.term != self.term or not m.granted:
            return
        self.votes[m.src] = True
        if self._vote_quorum():
            self._become_leader()

    def _accept_leader(self, m):
        """Common prelude of AE/IS from the current-term leader."""
        self.role = FOLLOWER
        self.leader_id = m.src
        self.votes = [False] * self.cfg.k
        self.leader_elapsed = 0   # authoritative leader contact: lease renews
        self._reset_election_timer()

    def _on_ae_req(self, m: rpc.AppendEntriesReq):
        if m.term > self.term:
            self._step_down(m.term)
        if m.term < self.term:
            self.transport.send(rpc.AppendEntriesResp(
                rpc.AE_RESP, self.id, m.src, term=self.term,
                success=False, match=0))
            return
        self._accept_leader(m)

        prev = m.prev_index
        if prev > self.last_index:
            # Past our end: tell the leader where our log actually ends.
            self.transport.send(rpc.AppendEntriesResp(
                rpc.AE_RESP, self.id, m.src, term=self.term,
                success=False, match=self.last_index + 1))
            return
        if prev >= self.snap_index and self.term_at(prev) != m.prev_term:
            # Conflict fast-backup: first index of the conflicting term.
            ct = self.term_at(prev)
            ci = prev
            while ci - 1 > self.snap_index and self.term_at(ci - 1) == ct:
                ci -= 1
            self.transport.send(rpc.AppendEntriesResp(
                rpc.AE_RESP, self.id, m.src, term=self.term,
                success=False, match=ci))
            return

        # Entries with index <= snap_index are committed here, hence match by
        # the Log Matching property — skip them.
        j0 = max(0, self.snap_index - prev)
        hi = prev + j0
        for j in range(j0, len(m.entries)):
            idx = prev + 1 + j
            et, ep = m.entries[j]
            if idx <= self.last_index:
                if self.term_at(idx) == et:
                    hi = idx
                    continue
                if self.payload_at(idx) == ep:
                    # Same entry re-proposed under a newer term (leader
                    # takeover, DESIGN.md §2a): overwrite the term in place
                    # and keep the tail. Needs no window room — this is what
                    # keeps takeover live when the window is full.
                    self.log[idx - self.snap_index - 1] = (et, ep)
                    hi = idx
                    continue
                # Divergent suffix: truncate it (never reaches committed
                # entries: a committed entry's payload is what the leader
                # itself holds at that index, so a differing payload proves
                # the entry was never committed).
                assert idx > self.commit, "refusing to truncate committed entries"
                del self.log[idx - self.snap_index - 1:]
            if not self._append(et, ep):
                break  # window full — flow control; leader will resend
            hi = idx
        if m.leader_commit > self.commit:
            # Only up to `hi`: beyond it our suffix is not known to match.
            self.commit = max(self.commit, min(m.leader_commit, hi))
        self.transport.send(rpc.AppendEntriesResp(
            rpc.AE_RESP, self.id, m.src, term=self.term, success=True, match=hi))

    def _on_ae_resp(self, m: rpc.AppendEntriesResp):
        if m.term > self.term:
            self._step_down(m.term)
            return
        if self.role != LEADER or m.term != self.term:
            return
        # Any current-term response (success or not) proves the sender
        # deferred to this leader when it replied — ReadIndex evidence.
        self.ack_time[m.src] = self.now
        if m.success:
            self.match_index[m.src] = max(self.match_index[m.src], m.match)
            self.next_index[m.src] = self.match_index[m.src] + 1
        else:
            self.next_index[m.src] = max(1, min(self.next_index[m.src] - 1, m.match))

    def _on_is_req(self, m: rpc.InstallSnapshotReq):
        if m.term > self.term:
            self._step_down(m.term)
        if m.term < self.term:
            self.transport.send(rpc.InstallSnapshotResp(
                rpc.IS_RESP, self.id, m.src, term=self.term, match=0))
            return
        self._accept_leader(m)
        if m.snap_index <= self.commit:
            # Already have everything the snapshot covers.
            self.transport.send(rpc.InstallSnapshotResp(
                rpc.IS_RESP, self.id, m.src, term=self.term, match=self.commit))
            return
        if (m.snap_index <= self.last_index
                and self.term_at(max(m.snap_index, self.snap_index)) == m.snap_term
                and m.snap_index >= self.snap_index):
            # Snapshot point exists in our log with the same term: keep the
            # suffix after it (Raft §7), drop the prefix.
            self.log = self.log[m.snap_index - self.snap_index:]
        else:
            self.log = []
        self.snap_index = m.snap_index
        self.snap_term = m.snap_term
        self.snap_digest = m.snap_digest
        self.snap_voters = m.snap_voters
        self.snap_sessions = dict(m.snap_sessions or ())
        self.commit = m.snap_index
        self.applied = m.snap_index
        self.digest = m.snap_digest
        self.sessions = dict(self.snap_sessions)
        self.transport.send(rpc.InstallSnapshotResp(
            rpc.IS_RESP, self.id, m.src, term=self.term, match=m.snap_index))

    def _on_is_resp(self, m: rpc.InstallSnapshotResp):
        if m.term > self.term:
            self._step_down(m.term)
            return
        if self.role != LEADER or m.term != self.term:
            return
        self.ack_time[m.src] = self.now
        self.match_index[m.src] = max(self.match_index[m.src], m.match)
        self.next_index[m.src] = self.match_index[m.src] + 1

    def _on_pv_req(self, m: rpc.PreVoteReq):
        """Pre-vote grant rule (dissertation §9.6): the proposed term is
        ahead of ours, the candidate's log is up-to-date, we are not the
        leader, and we have not heard from one within election_min ticks
        (the lease check — what stops a healthy regime's followers from
        helping a rejoined partitioned node depose the leader). A
        pre-vote is non-binding: no term adoption, no voted_for record,
        no timer reset — any number may be granted per term."""
        log_ok = (m.last_log_term > self.last_log_term()
                  or (m.last_log_term == self.last_log_term()
                      and m.last_log_index >= self.last_index))
        grant = (m.term > self.term
                 and log_ok
                 and self.role != LEADER
                 and self.leader_elapsed >= self.cfg.election_min)
        self.transport.send(rpc.PreVoteResp(
            rpc.PV_RESP, self.id, m.src, term=self.term,
            req_term=m.term, granted=grant))

    def _on_pv_resp(self, m: rpc.PreVoteResp):
        if m.term > self.term:
            self._step_down(m.term)
            return
        if (self.role != PRECANDIDATE or m.req_term != self.term + 1
                or not m.granted):
            return
        self.votes[m.src] = True
        if self._vote_quorum():
            self._start_election()   # quorum would vote for us: go real

    def _on_tn_req(self, m: rpc.TimeoutNow):
        """Leadership transfer (dissertation §3.10): campaign NOW —
        deliberately bypassing PreVote (the sender is the current
        leader handing off; a pre-ballot would be refused under the
        lease check everyone still holds for that leader).

        Honored only as FOLLOWER or PRECANDIDATE: a CANDIDATE already
        started an election — possibly THIS tick (a pre-ballot quorum in
        phase D, processed before TN in the canonical order) — and a
        second `_start_election` would emit two RequestVotes per
        destination in one tick, violating the one-message-per-
        (type, src, dst) contract the dense TPU mailbox relies on."""
        if m.term > self.term:
            self._step_down(m.term)
        if (m.term < self.term or self.role in (LEADER, CANDIDATE)
                or not self.is_voter()):
            return
        self._start_election()

    # ------------------------------------------------------------- client API

    def _session_effective(self, index: int, payload: int) -> bool:
        """Exactly-once filter (dissertation §6.3), applied at digest-fold
        time so every node makes the identical decision from the same
        committed prefix. Returns False iff the entry is a session
        command whose effect must be skipped: a duplicate (sid, seq)
        retry, a command on an unregistered session, or a REGISTER whose
        index-derived sid is already taken. With cfg.sessions off (every
        scheduled universe), every entry is effective — bit-identical to
        the pre-session digest stream."""
        if not self.cfg.sessions:
            return True
        if payload & config.CONFIG_FLAG or not payload & config.SESSION_FLAG:
            return True
        sid = (payload >> config.SESSION_SID_SHIFT) & config.SESSION_SID_MASK
        if sid == config.SESSION_SID_MASK:          # REGISTER
            new_sid = index % config.SESSION_SID_MASK
            if new_sid in self.sessions:
                return False
            self.sessions[new_sid] = -1
            return True
        seq = (payload >> config.SESSION_SEQ_SHIFT) & config.SESSION_SEQ_MASK
        if sid not in self.sessions or seq <= self.sessions[sid]:
            return False
        self.sessions[sid] = seq
        return True

    def propose(self, payload: int):
        """Client write: append `payload` under the current term.

        Returns the assigned absolute index, or None if this node is not
        the leader or the log window is full (flow control — retry after
        compaction frees space). The entry is durably committed once some
        node applies (index, payload); the ticket for that check is the
        (index, payload) pair — terms are ballot numbers and may be
        rewritten in place by a takeover re-proposal (DESIGN.md §2a).
        """
        if self.role != LEADER:
            return None
        if self.cfg.sessions:
            # Bits 29-30 are protocol-reserved when sessions are on: a
            # raw payload carrying them would be (mis)read by the state
            # machine as a session/config command. Session commands go
            # through `propose_seq`.
            if payload & (CONFIG_FLAG | config.SESSION_FLAG):
                raise ValueError("payload uses reserved session/config bits; "
                                 "use propose_seq/propose_config")
        if not self._append(self.term, payload):
            return None
        return self.last_index

    def propose_register(self):
        """Propose a session REGISTER entry (cfg.sessions). On apply,
        the state machine allocates sid = index % SESSION_SID_MASK (a
        taken sid makes the registration a deterministic no-op — the
        client retries). Returns the index or None."""
        if self.role != LEADER or not self.cfg.sessions:
            return None
        if not self._append(self.term, config.SESSION_REGISTER):
            return None
        return self.last_index

    def propose_seq(self, sid: int, seq: int, val: int):
        """Client write with exactly-once semantics (cfg.sessions): the
        state machine applies (sid, seq) at most once, so a client that
        RETRIES after an ambiguous failure (leader deposed with the
        ticket unresolved) cannot double-apply. Returns the index or
        None (not leader / window full). `sid` comes from a committed
        REGISTER entry (Cluster.open_session)."""
        if self.role != LEADER or not self.cfg.sessions:
            return None
        if not self._append(self.term, config.session_payload(sid, seq, val)):
            return None
        return self.last_index

    def admit_and_propose(self, sid: int, seq: int, val: int, shed: bool):
        """Admission seam of the bounded client queue (r20, DESIGN.md
        §19). A shed arrival gets a DEFINITIVE reject: the op never
        enters the log, its seq is never consumed, and the client must
        not retry it — so an admission layer that says "rejected" yet
        still proposes is a safety bug, not a liveness one. The mutant
        harness overrides exactly this method (shed_then_apply); the
        applied-seq frontier then outruns the issued frontier and
        invariants.client_safety kills it."""
        if shed:
            return None
        return self.propose_seq(sid, seq, val)

    def read_begin(self):
        """Begin a linearizable ReadIndex read (Raft dissertation §6.4).

        Records the current commit index and the registration tick;
        returns a read id, or None if not leader. The read completes
        once (a) a majority of peers have sent this leader a current-term
        response at a tick >= registration + 2 — in the lockstep tick
        model a response received at tick t was emitted at t-1 reacting
        to authority this leader held at t-2, so t >= reg + 2 proves the
        peer still deferred to this leader strictly after the read was
        registered (no newer leader could have been elected before reg
        without this majority having refused us) — and (b) the state
        machine has applied through the recorded read index.

        A freshly elected leader must not serve reads yet: its commit
        index can lag entries committed by prior leaders (dissertation
        §6.4 step 1). Serving is safe once (a) the entry at `commit`
        carries the current term — the takeover re-proposal (DESIGN.md
        §2a) guarantees a current-term entry at the takeover
        `last_index`, which is >= every previously committed index, so
        committing it pulls `commit` past all prior commits — or (b)
        `commit == last_index`, in which case Leader Completeness bounds
        every committed entry by `last_index` directly. Until then:
        return None, client retries.
        """
        if self.role != LEADER:
            return None
        if not (self.commit == self.last_index
                or self.term_at(self.commit) == self.term):
            return None
        rid = self._next_read_id
        self._next_read_id += 1
        self.pending_reads[rid] = (self.commit, self.now)
        return rid

    READ_PENDING = "pending"
    READ_ABORTED = "aborted"

    def _read_quorum_met(self, reg_tick: int) -> bool:
        """ReadIndex leadership confirmation: acks from CURRENT-config
        voters at ticks >= reg + 2 reach the voter majority (the leader
        counts itself iff it is a voter). Acks from non-voter learners
        prove nothing — they are in no election quorum (round-4 VERDICT
        confirmed violation). Shared by the interactive `read_poll` and
        the scheduled-read completion in `phase_a`."""
        voters, _ = self.current_config()
        acks = sum(1 for p in range(self.cfg.k)
                   if p != self.id and (voters >> p) & 1
                   and self.ack_time[p] >= reg_tick + 2)
        return acks + ((voters >> self.id) & 1) >= majority_of(voters)

    def read_poll(self, rid: int):
        """Poll a pending read: READ_ABORTED (leadership lost — retry on
        the new leader), READ_PENDING, or (read_index, served_index,
        digest) once the quorum round-trip confirmed leadership and the
        state machine caught up. The digest is the machine state after
        applying exactly `served_index` entries (served_index >=
        read_index), which includes every write committed before the
        read began — serving a later applied state is still
        linearizable because that state is current at completion."""
        if rid not in self.pending_reads:
            return self.READ_ABORTED
        read_index, reg_tick = self.pending_reads[rid]
        if not self._read_quorum_met(reg_tick):
            return self.READ_PENDING
        if self.applied < read_index:
            return self.READ_PENDING
        del self.pending_reads[rid]
        return (read_index, self.applied, self.digest)

    # ---------------------------------------------------------------- phase T

    def phase_t(self):
        if self.role == LEADER:
            self.leader_elapsed = 0   # a leader is its own lease authority
            self.heartbeat_elapsed += 1
            if self.heartbeat_elapsed >= self.cfg.heartbeat_every:
                self.heartbeat_elapsed = 0
                self._broadcast_append()
            self._maybe_transfer()
        else:
            self.leader_elapsed += 1
            self.election_elapsed += 1
            # Non-voters (servers the latest config removed) never start
            # elections — they keep replicating as learners and keep
            # granting votes, but cannot disrupt the voters' regime.
            if self.election_elapsed >= self.deadline and self.is_voter():
                if self.cfg.prevote:
                    self._start_prevote()
                else:
                    self._start_election()

    def _send_timeout_now(self, target: int):
        """Transfer gate: the target must be a CURRENT-config voter, not
        self, hold every committed entry, and be the most-caught-up
        peer (the dissertation's §3.10 "catch the target up first"
        precondition, adapted to continuous appends — strict equality
        with last_index can never hold while in-flight entries lead the
        acks, so the gate asks for the best log a follower can have)."""
        if target == self.id or not self.is_voter(target):
            return None
        mt = self.match_index[target]
        if mt < self.commit or mt != max(self.match_index):
            return None
        self.transport.send(rpc.TimeoutNow(
            rpc.TN_REQ, self.id, target, term=self.term))
        return True

    def transfer_leadership(self, target: int):
        """Client API: hand leadership to `target` (dissertation §3.10).
        Returns True if TimeoutNow was sent, None if not leader or the
        gate refused (non-voter, self, or not caught up)."""
        if self.role != LEADER:
            return None
        return self._send_timeout_now(target)

    def _maybe_transfer(self):
        """The deterministic transfer schedule (DESIGN.md §2d): at the
        first tick of each transfer epoch, w.p. transfer_prob, hand
        leadership to a hash-chosen peer — if the gate clears."""
        cfg = self.cfg
        if cfg.transfer_u32 == 0 or self.now % cfg.transfer_epoch != 0:
            return
        epoch = self.now // cfg.transfer_epoch
        if not rng.transfer_fires(cfg.seed, self.g, epoch, cfg.transfer_u32):
            return
        self._send_timeout_now(
            rng.transfer_target(cfg.seed, self.g, epoch, cfg.k))

    def _broadcast_append(self):
        for p in range(self.cfg.k):
            if p == self.id:
                continue
            if self.next_index[p] <= self.snap_index:
                self.transport.send(rpc.InstallSnapshotReq(
                    rpc.IS_REQ, self.id, p, term=self.term,
                    snap_index=self.snap_index, snap_term=self.snap_term,
                    snap_digest=self.snap_digest,
                    snap_voters=self.snap_voters,
                    snap_sessions=(tuple(sorted(self.snap_sessions.items()))
                                   if self.cfg.sessions else None)))
            else:
                prev = self.next_index[p] - 1
                n = min(self.cfg.max_entries_per_msg, self.last_index - prev)
                lo = prev - self.snap_index
                entries = tuple(self.log[lo:lo + n])
                self.transport.send(rpc.AppendEntriesReq(
                    rpc.AE_REQ, self.id, p, term=self.term,
                    prev_index=prev, prev_term=self.term_at(prev),
                    entries=entries, leader_commit=self.commit))

    # ---------------------------------------------------------------- phase C

    def _reconfig_gate(self, new_mask: int):
        """Single-server change preconditions (dissertation §4.1 + the
        2015 single-server bugfix): the previous config entry must be
        committed, and this leader must have committed an entry of its
        own term. Returns the (voters, cfg_index) pair if clear."""
        voters, cfg_index = self.current_config()
        if cfg_index > self.commit:
            return None
        if self.term_at(self.commit) != self.term:
            return None
        if (new_mask ^ voters).bit_count() != 1:
            return None   # not a single-server delta
        if new_mask.bit_count() == 0:
            return None   # an empty voter set can never commit or elect
        return voters, cfg_index

    def _maybe_propose_reconfig(self):
        """The deterministic membership-change schedule (DESIGN.md §2b):
        at the first tick of each reconfig epoch, w.p. reconfig_prob,
        toggle one hash-chosen node — if the gate clears and the result
        keeps at least min_voters voters."""
        cfg = self.cfg
        if cfg.reconfig_u32 == 0 or self.now % cfg.reconfig_epoch != 0:
            return
        epoch = self.now // cfg.reconfig_epoch
        if not rng.reconfig_fires(cfg.seed, self.g, epoch, cfg.reconfig_u32):
            return
        target = rng.reconfig_target(cfg.seed, self.g, epoch, cfg.k)
        voters, _ = self.current_config()
        new_mask = voters ^ (1 << target)
        if new_mask.bit_count() < cfg.effective_min_voters:
            return
        if self._reconfig_gate(new_mask) is None:
            return
        self._append(self.term, CONFIG_FLAG | new_mask)

    def propose_config(self, new_mask: int):
        """Client API: propose a single-server membership change. Returns
        the assigned index or None (not leader / gate closed / window
        full). `new_mask` must differ from the current config by exactly
        one member."""
        if self.role != LEADER:
            return None
        if self._reconfig_gate(new_mask) is None:
            return None
        if not self._append(self.term, CONFIG_FLAG | new_mask):
            return None
        return self.last_index

    def _maybe_schedule_read(self):
        """DESIGN.md §2c: at the first tick of each read epoch a leader
        with no read in flight registers a ReadIndex read at the START
        of phase C (so the read point is the pre-append commit index),
        subject to `read_begin`'s serving gate."""
        cfg = self.cfg
        if cfg.read_every == 0 or self.now % cfg.read_every != 0:
            return
        if self.sched_read is not None:
            return
        if not (self.commit == self.last_index
                or self.term_at(self.commit) == self.term):
            return
        self.sched_read = (self.commit, self.now)

    def phase_c(self, client_cmds=None):
        """`client_cmds`: the scheduled open-loop clients' pulsed
        session payloads for this tick (DESIGN.md §10), in slot order —
        every node that believes itself leader appends them (duplicate
        appends by transient dual leaders are exactly what the
        exactly-once fold dedups), stopping at window-full like the
        batched path's stopped latch."""
        if self.role != LEADER:
            return
        self._maybe_schedule_read()
        self._maybe_propose_reconfig()
        if client_cmds:
            for payload in client_cmds:
                if not self._append(self.term, payload):
                    break
        for _ in range(self.cfg.cmds_per_tick):
            payload = rng.client_payload(
                self.cfg.seed, self.g, self.term, self.last_index + 1)
            if not self._append(self.term, payload):
                break

    # ---------------------------------------------------------------- phase A

    def phase_a(self):
        if self.role == LEADER:
            voters, _ = self.current_config()
            # Replication tally over CURRENT voters only; the leader
            # counts itself (at last_index) iff it is still a voter.
            vals = sorted(
                (self.last_index if p == self.id else self.match_index[p]
                 for p in range(self.cfg.k) if (voters >> p) & 1),
                reverse=True)
            if vals:
                n = vals[majority_of(voters) - 1]
                # §5.4.2: only entries of the current term commit by counting.
                if n > self.commit and self.term_at(n) == self.term:
                    self.commit = n
            # A removed leader steps down once its removal is committed
            # (latest config entry committed and it is not in it).
            voters, cfg_index = self.current_config()
            if cfg_index <= self.commit and not (voters >> self.id) & 1:
                self.role = FOLLOWER
                self.leader_id = NO_VOTE
                self.votes = [False] * self.cfg.k
                self._drop_client_state()
        while self.applied < self.commit:
            self.applied += 1
            t, p = self.log[self.applied - self.snap_index - 1]
            if self._session_effective(self.applied, p):
                self.digest = rng.digest_update(self.digest, self.applied, p)
            if self.on_apply is not None:
                self.on_apply(self.id, self.applied, t, p)
        if (self.commit - self.snap_index >= self.cfg.compact_every
                and not self._compact_blocked()):
            self.snap_voters = self.committed_config()
            self.snap_sessions = dict(self.sessions)
            self.snap_term = self.term_at(self.commit)
            self.log = self.log[self.commit - self.snap_index:]
            self.snap_index = self.commit
            self.snap_digest = self.digest
        # Scheduled-read completion (DESIGN.md §2c), end of phase A: the
        # same voters-aware quorum as `read_poll` — a step-down or
        # demotion earlier this tick already cleared `sched_read`.
        if self.sched_read is not None:
            read_index, reg = self.sched_read
            if self._read_quorum_met(reg) and self.applied >= read_index:
                self.reads_done += 1
                self.sched_read = None
