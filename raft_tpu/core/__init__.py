"""CPU reference path: classical Node / Transport / Cluster Raft.

This is the ground-truth oracle (SURVEY.md §7 step 1): a readable,
object-style single-group-at-a-time Raft implementation whose per-tick
semantics are specified in DESIGN.md §2 and mirrored bit-for-bit by the
batched TPU path in raft_tpu.sim.
"""

from raft_tpu.core.node import Node
from raft_tpu.core.transport import Transport
from raft_tpu.core.cluster import Cluster

__all__ = ["Node", "Transport", "Cluster"]
