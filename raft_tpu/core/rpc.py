"""Raft RPC message types and the canonical per-tick processing order.

At most one message of each (type, src, dst) exists per tick by
construction (DESIGN.md §2), so the canonical inbox order — type first,
then sender id — fully determinizes phase D.
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

# Canonical type order for phase D. The TPU path unrolls its handler loop in
# exactly this order. Pre-vote types come last so that enabling
# `cfg.prevote` leaves the processing order of the original six
# unchanged (prevote-off traces are bit-identical to older builds).
(RV_REQ, RV_RESP, AE_REQ, AE_RESP, IS_REQ, IS_RESP, PV_REQ, PV_RESP,
 TN_REQ) = range(9)


@dataclasses.dataclass(frozen=True)
class Msg:
    type: int
    src: int
    dst: int


@dataclasses.dataclass(frozen=True)
class RequestVoteReq(Msg):
    term: int = 0
    last_log_index: int = 0
    last_log_term: int = 0


@dataclasses.dataclass(frozen=True)
class RequestVoteResp(Msg):
    term: int = 0
    granted: bool = False


@dataclasses.dataclass(frozen=True)
class AppendEntriesReq(Msg):
    term: int = 0
    prev_index: int = 0
    prev_term: int = 0
    entries: Tuple[Tuple[int, int], ...] = ()   # ((term, payload), ...)
    leader_commit: int = 0


@dataclasses.dataclass(frozen=True)
class AppendEntriesResp(Msg):
    term: int = 0
    success: bool = False
    # On success: highest index known replicated (prev + len(entries)).
    # On failure: conflict fast-backup hint for the leader's next_index.
    match: int = 0


@dataclasses.dataclass(frozen=True)
class InstallSnapshotReq(Msg):
    term: int = 0
    snap_index: int = 0
    snap_term: int = 0
    snap_digest: int = 0
    snap_voters: int = 0   # voter bitmask as of the snapshot prefix
    # Session table as of the snapshot prefix (sid -> last applied seq);
    # None unless cfg.sessions (the batched path never carries it).
    snap_sessions: tuple = None


@dataclasses.dataclass(frozen=True)
class InstallSnapshotResp(Msg):
    term: int = 0
    match: int = 0


@dataclasses.dataclass(frozen=True)
class PreVoteReq(Msg):
    """Non-binding pre-ballot probe (dissertation §9.6): `term` is the
    PROPOSED next term (sender's term + 1); the sender has not bumped its
    own term. Receivers never adopt this term."""
    term: int = 0
    last_log_index: int = 0
    last_log_term: int = 0


@dataclasses.dataclass(frozen=True)
class PreVoteResp(Msg):
    """`term` is the responder's CURRENT term (authoritative — a higher
    one steps the pre-candidate down); `req_term` echoes the proposed
    term so a grant can be matched to the pre-ballot that asked."""
    term: int = 0
    req_term: int = 0
    granted: bool = False


@dataclasses.dataclass(frozen=True)
class TimeoutNow(Msg):
    """Leadership transfer (dissertation §3.10): the leader tells a
    fully-caught-up voter to campaign immediately — bypassing PreVote,
    since the handoff is deliberate. `term` is the sender's term."""
    term: int = 0


def inbox_sort_key(m: Msg):
    return (m.type, m.src)


def sort_inbox(msgs: List[Msg]) -> List[Msg]:
    return sorted(msgs, key=inbox_sort_key)
