"""In-memory Transport: the message bus between a group's replicas.

Messages sent during tick ``t`` are delivered at tick ``t+1``, filtered at
delivery time by the fault model (DESIGN.md §4): dead destinations lose
their mail, partitioned or dropped links deliver nothing. In-flight mail
survives a *sender* crash — it already left the node.

This is the seam the TPU backend replaces with a dense device-resident
mailbox (DESIGN.md §5).
"""

from __future__ import annotations

from typing import List

from raft_tpu.config import RaftConfig
from raft_tpu.core import rpc
from raft_tpu.utils import rng


class Transport:
    def __init__(self, cfg: RaftConfig, group: int):
        self.cfg = cfg
        self.g = group
        self._outbox: List[rpc.Msg] = []      # sent this tick, in flight
        # Test hook: extra delivery predicate (tick, src, dst) -> bool.
        # Production faults use the hash-based model below; scenario tests
        # (staged partitions, targeted drops) use this.
        self.link_filter = None

    def send(self, msg: rpc.Msg):
        self._outbox.append(msg)

    def deliver(self, tick: int, alive_now: List[bool]) -> List[List[rpc.Msg]]:
        """Return per-destination inboxes for this tick and rotate buffers.

        Called at the start of tick ``tick``, before any phase runs, so
        ``_outbox`` holds exactly the messages sent during tick
        ``tick - 1`` — the t+1 delivery the tick contract specifies.
        """
        cfg = self.cfg
        inboxes: List[List[rpc.Msg]] = [[] for _ in range(cfg.k)]
        nem_link = cfg.nem_link   # one program filter per tick, not
        for m in self._outbox:    # one per in-flight message
            if not alive_now[m.dst]:
                continue
            if self.link_filter is not None and not self.link_filter(
                    tick, m.src, m.dst):
                continue
            if rng.link_partitioned(cfg.seed, self.g, tick, m.src, m.dst,
                                    cfg.partition_u32, cfg.partition_epoch):
                continue
            if rng.link_dropped(cfg.seed, self.g, tick, m.src, m.dst,
                                cfg.drop_u32):
                continue
            if nem_link and not rng.nem_link_ok(
                    cfg.seed, nem_link, self.g, tick, m.src, m.dst,
                    cfg.k):
                continue   # nemesis link clause blocked it (DESIGN.md §14)
            inboxes[m.dst].append(m)
        self._outbox = []
        return inboxes
