"""Cluster harness: builds one Raft group, drives ticks, checks invariants.

Also the trace source for the CPU↔TPU differential test: `snapshot()`
captures exactly the per-node fields the batched state carries.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from raft_tpu import config as _c
from raft_tpu.config import RaftConfig
from raft_tpu.core.node import Node, LEADER
from raft_tpu.core.transport import Transport
from raft_tpu.utils import rng


@dataclasses.dataclass
class NodeView:
    """Per-node observable state after phase A of a tick."""
    term: int
    role: int
    voted_for: int
    leader_id: int
    last_index: int
    commit: int
    applied: int
    digest: int
    snap_index: int
    snap_term: int
    snap_voters: int
    reads_done: int
    alive: bool


class SafetyViolation(AssertionError):
    pass


class Cluster:
    def __init__(self, cfg: RaftConfig, group: int = 0,
                 check_invariants: bool = True):
        self.cfg = cfg
        self.g = group
        self.check = check_invariants
        self.transport = Transport(cfg, group)
        self.nodes = [Node(cfg, group, i, self.transport, self._on_apply)
                      for i in range(cfg.k)]
        self.tick_count = 0
        self.alive_prev = [True] * cfg.k
        # Test hook: (tick) -> List[bool] overriding the hash-based crash
        # schedule. Instance attribute (like Transport.link_filter) so one
        # test's schedule can never leak into another cluster.
        self.alive_fn = None
        # Safety bookkeeping.
        self._leaders_by_term: Dict[int, int] = {}
        # index -> payload. Identity of a committed entry is (index, payload):
        # the term of an entry may legitimately be rewritten by a leader
        # takeover re-proposal (DESIGN.md §2a) without changing the entry.
        self._committed: Dict[int, int] = {}
        # Shadow of the state machine's session-allocation rule (first
        # REGISTER to claim an sid owns it), maintained from the same
        # first-application events as the commit-identity map — lets
        # `open_session` tell a successful registration from a no-op
        # collision without peeking at server state mid-protocol.
        self._session_owner: Dict[int, int] = {}
        self.total_applies = 0
        # Scheduled open-loop client traffic (DESIGN.md §10): the
        # host-side mirror of the batched client transition — pulses
        # feed phase C, the post-tick dedup-table witness feeds back.
        if cfg.clients_u32:
            from raft_tpu.clients.workload import HostClients
            self.clients = HostClients(cfg, group)
        else:
            self.clients = None

    # ---------------------------------------------------------------- faults

    def alive(self, tick: int) -> List[bool]:
        if self.alive_fn is not None:
            return list(self.alive_fn(tick))
        cfg = self.cfg
        out = [rng.node_alive(cfg.seed, self.g, i, tick,
                              cfg.crash_u32, cfg.crash_epoch)
               for i in range(cfg.k)]
        nem_crash = cfg.nem_crash   # one program filter per call
        if nem_crash:
            # Nemesis crash-storm clauses AND into the base schedule
            # (DESIGN.md §14) — the batched tick applies the same mask.
            out = [a and rng.nem_alive(cfg.seed, nem_crash, self.g,
                                       i, tick)
                   for i, a in enumerate(out)]
        return out

    # ------------------------------------------------------------ invariants

    def _on_apply(self, node_id: int, index: int, term: int, payload: int):
        self.total_applies += 1
        if not self.check:
            return
        prev = self._committed.get(index)
        if prev is None:
            self._committed[index] = payload
            if self.cfg.sessions:
                if payload == _c.SESSION_REGISTER:
                    self._session_owner.setdefault(
                        index % _c.SESSION_SID_MASK, index)
        elif prev != payload:
            raise SafetyViolation(
                f"group {self.g}: node {node_id} applied payload {payload} at "
                f"index {index}, but {prev} was already applied there")

    def _check_election_safety(self):
        # Scans ALL nodes, crashed included: a crashed leader still "holds"
        # its term — no other leader may ever exist for it.
        for n in self.nodes:
            if n.role == LEADER:
                prev = self._leaders_by_term.get(n.term)
                if prev is None:
                    self._leaders_by_term[n.term] = n.id
                elif prev != n.id:
                    raise SafetyViolation(
                        f"group {self.g}: two leaders in term {n.term}: "
                        f"{prev} and {n.id}")

    # ------------------------------------------------------------------ tick

    def tick(self):
        t = self.tick_count
        alive_now = self.alive(t)
        for n in self.nodes:
            n.now = t   # client-API clock (ReadIndex ack timestamps)
        for i, n in enumerate(self.nodes):
            if alive_now[i] and not self.alive_prev[i]:
                n.restart()
        inboxes = self.transport.deliver(t, alive_now)
        # Pulses raised by the previous tick's client transition — read
        # BEFORE the phases (the batched path snapshots them the same
        # way: submit_payloads on the start-of-tick state).
        client_cmds = (self.clients.pending_cmds()
                       if self.clients is not None else None)
        for i, n in enumerate(self.nodes):
            if alive_now[i]:
                n.phase_d(inboxes[i])
        for i, n in enumerate(self.nodes):
            if alive_now[i]:
                n.phase_t()
        for i, n in enumerate(self.nodes):
            if alive_now[i]:
                n.phase_c(client_cmds)
        for i, n in enumerate(self.nodes):
            if alive_now[i]:
                n.phase_a()
        # Crashed nodes sent nothing; anything they had queued pre-crash was
        # already in flight and still delivers.
        if self.clients is not None:
            # Post-tick client transition: the durable-commit witness is
            # the max applied seq per sid over ALL nodes (a crashed
            # node's frozen table still witnesses committed applies),
            # exactly the batched table_max.
            self.clients.observe(
                [max(n.sessions.get(s, -1) for n in self.nodes)
                 for s in range(self.cfg.client_slots)], t)
        if self.check:
            self._check_election_safety()
        self.alive_prev = alive_now
        self.tick_count += 1

    def run(self, ticks: int):
        for _ in range(ticks):
            self.tick()

    # ------------------------------------------------------------ client API

    def propose(self, payload: int):
        """Route a client write to the current leader. Returns a
        (index, payload) ticket or None (no leader / window full —
        retry). Committed iff `is_committed(ticket)` ever holds; a
        ticket can also be lost (leader deposed before replication), in
        which case it never commits and the client re-proposes."""
        lead = self.leader()
        if lead is None:
            return None
        idx = self.nodes[lead].propose(payload)
        if idx is None:
            return None
        return (idx, payload)

    def is_committed(self, ticket) -> bool:
        """True iff the proposed (index, payload) has been applied by
        some node — the commit-identity map is the authority."""
        idx, payload = ticket
        return self._committed.get(idx) == payload

    def open_session(self, max_ticks: int = 200):
        """Register a client session (dissertation §6.3): propose the
        REGISTER entry, tick until it commits, and return the
        index-derived session id (or None if nothing commits within the
        budget). cfg.sessions only.

        A ticket is LOST when its leader is deposed before replication
        and a later leader commits a different payload at that index —
        detected below via the commit-identity map, which resets the
        ticket so the loop re-proposes immediately instead of burning
        the remaining tick budget waiting on an index that can never
        hold a REGISTER again."""
        ticket = None
        for _ in range(max_ticks):
            if ticket is None:
                lead = self.leader()
                if lead is not None:
                    idx = self.nodes[lead].propose_register()
                    if idx is not None:
                        ticket = (idx, _c.SESSION_REGISTER)
            if ticket is not None and self.is_committed(ticket):
                sid = ticket[0] % _c.SESSION_SID_MASK
                if self._session_owner.get(sid) == ticket[0]:
                    return sid
                ticket = None            # collision no-op: re-register
            elif (ticket is not None
                  and self._committed.get(ticket[0]) is not None):
                ticket = None            # lost ticket: index taken by
            self.tick()                  # another payload — re-propose
        return None

    def propose_seq(self, sid: int, seq: int, val: int):
        """Route an exactly-once session write to the current leader.
        Returns the (index, payload) ticket or None (retry — safely:
        duplicates fold once)."""
        lead = self.leader()
        if lead is None:
            return None
        idx = self.nodes[lead].propose_seq(sid, seq, val)
        if idx is None:
            return None
        return (idx, self.nodes[lead].payload_at(idx))

    def propose_reconfig(self, new_mask: int):
        """Route a single-server membership change to the current leader.
        Returns the (index, payload) ticket or None."""
        lead = self.leader()
        if lead is None:
            return None
        idx = self.nodes[lead].propose_config(new_mask)
        if idx is None:
            return None
        return (idx, self.nodes[lead].payload_at(idx))

    def read_begin(self):
        """Begin a linearizable read on the current leader. Returns
        (leader_id, rid) or None if no leader."""
        lead = self.leader()
        if lead is None:
            return None
        rid = self.nodes[lead].read_begin()
        if rid is None:
            return None
        return (lead, rid)

    def read_poll(self, handle):
        """Poll a read begun with `read_begin`: Node.READ_ABORTED,
        Node.READ_PENDING, or (read_index, served_index, digest)."""
        lead, rid = handle
        n = self.nodes[lead]
        if not self.alive_prev[lead]:
            return Node.READ_ABORTED
        return n.read_poll(rid)

    def read(self, max_ticks: int = 200):
        """Convenience: begin a read (retrying while leaderless) and tick
        until it completes. Returns (read_index, served_index, digest)
        or None if no read completed within `max_ticks`."""
        handle = None
        for _ in range(max_ticks):
            if handle is None:
                handle = self.read_begin()
            if handle is not None:
                r = self.read_poll(handle)
                if r == Node.READ_ABORTED:
                    handle = None
                elif r != Node.READ_PENDING:
                    return r
            self.tick()
        return None

    def expected_digest(self, through_index: int) -> int:
        """Replay the commit-identity map's hash chain through
        `through_index` — the value any node's digest must hold after
        applying exactly that prefix (read-your-writes checker). With
        cfg.sessions, the replay applies the same exactly-once filter
        as `Node._session_effective` (tests/test_sessions.py carries an
        independent re-implementation as the oracle-of-this-oracle)."""
        d = 0
        sessions: Dict[int, int] = {}
        for i in range(1, through_index + 1):
            p = self._committed[i]
            if (self.cfg.sessions and p & _c.SESSION_FLAG
                    and not p & _c.CONFIG_FLAG):
                sid = (p >> _c.SESSION_SID_SHIFT) & _c.SESSION_SID_MASK
                if sid == _c.SESSION_SID_MASK:
                    new_sid = i % _c.SESSION_SID_MASK
                    if new_sid in sessions:
                        continue
                    sessions[new_sid] = -1
                else:
                    seq = (p >> _c.SESSION_SEQ_SHIFT) & _c.SESSION_SEQ_MASK
                    if sid not in sessions or seq <= sessions[sid]:
                        continue
                    sessions[sid] = seq
            d = rng.digest_update(d, i, p)
        return d

    # ------------------------------------------------------------- observers

    def leader(self) -> Optional[int]:
        """Current unique alive leader of the highest term, if any."""
        best = None
        for i, n in enumerate(self.nodes):
            if n.role == LEADER and self.alive_prev[i]:
                if best is None or n.term > self.nodes[best].term:
                    best = i
        return best

    def snapshot(self) -> List[NodeView]:
        return [NodeView(term=n.term, role=n.role, voted_for=n.voted_for,
                         leader_id=n.leader_id, last_index=n.last_index,
                         commit=n.commit, applied=n.applied, digest=n.digest,
                         snap_index=n.snap_index, snap_term=n.snap_term,
                         snap_voters=n.snap_voters, reads_done=n.reads_done,
                         alive=self.alive_prev[i])
                for i, n in enumerate(self.nodes)]
