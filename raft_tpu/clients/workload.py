"""Deterministic open-loop client workload (DESIGN.md §10).

Traffic model, per (group, sid) slot:

- **Arrival** (open loop): a new op arrives w.p. `cfg.client_rate` each
  tick (Bernoulli per tick — the discrete-tick Poisson limit), hashed
  from `(seed, TAG_CLIENT_ARRIVAL, g, sid, t)` like every other
  schedule, and joins the slot's backlog. Arrivals never wait for acks
  — the generator keeps offering load while the group is leaderless,
  which is what makes the measurement open-loop.
- **Submission**: an idle client with backlog starts its next op
  (seq = `done`) and raises a one-tick `submit` pulse; EVERY node that
  believes itself leader appends the op in the NEXT tick's phase C
  (a real client broadcasts to whoever claims leadership — two
  transient leaders produce duplicate log entries, which is exactly
  what the dedup table is for).
- **Ack**: the op is client-visibly committed once ANY node's applied
  dedup table holds `seq >= done` — table entries only advance at
  apply time (applied <= commit), so a table witness IS a durable
  commit witness. Ack latency = `t_ack - t_start` (service latency;
  backlog depth is reported separately — queueing delay of ops still
  in the backlog is deliberately not folded into the histogram).
- **Retry with backoff** (the ambiguous-failure path): no ack within
  `cfg.client_retry_backoff` ticks of the last submission → re-submit
  the SAME `(sid, seq, val)` payload (`client_val` hashes the op
  identity, so the retry is byte-identical). A leader crash between
  append and ack makes the outcome ambiguous; the retry may commit a
  duplicate entry, and the exactly-once fold applies it once.

Sequence-space bound: seq is the 10-bit session field, so arrivals are
gated on `done + backlog + inflight <= SESSION_SEQ_MASK` — a slot
saturates at 1024 lifetime ops (config.py's documented session
lifetime) instead of wrapping, which would alias the dedup filter.

One transition, two engines, one oracle: `client_update` /
`submit_payloads` are written purely elementwise so the SAME jnp code
runs on `[G, S]` leaves (sim/step.py) and `[S, 8, 128]` kernel tiles
(sim/pkernel.py); `HostClients` is the pure-Python mirror driving the
CPU oracle `Cluster`, bit-identical by the shared utils/rng hashes
(pinned by tests/test_clients.py).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from raft_tpu import config as _c
from raft_tpu.config import RaftConfig
from raft_tpu.clients.state import (ADMISSION_LEAVES, CLIENT_LEAVES,
                                    ClientState, active_client_leaves,
                                    clients_init)
from raft_tpu.utils import jrng, rng

__all__ = ["ADMISSION_LEAVES", "CLIENT_LEAVES", "ClientState",
           "active_client_leaves", "clients_init", "client_update",
           "submit_payloads", "HostClients", "table_max",
           "exactly_once_report", "clients_64_cfg", "workload_params"]

I32 = jnp.int32


def clients_64_cfg(**overrides) -> RaftConfig:
    """THE shared client-differential universe: 64 faulted k=3/L=8
    groups (kmesh.faulted_64_cfg's fault mix) carrying 3 retrying
    open-loop sessions per group. tests/test_clients.py's oracle
    differential, its kernel bit-parity test, and the checkpoint
    round-trip all simulate exactly this config so the clients-on tick
    compiles ONCE per machine (tests/conftest.py compile-cache
    recipe). `overrides` layers dials on the pinned universe — the r19
    narrow tests add `narrow_*` flags, which change resident dtypes
    but not the compiled kernel program, so the shared compile still
    serves."""
    import dataclasses
    cfg = RaftConfig(n_groups=64, k=3, seed=29, log_cap=8, compact_every=4,
                     sessions=True, cmds_per_tick=0,
                     client_rate=0.3, client_slots=3,
                     client_retry_backoff=5,
                     drop_prob=0.05, crash_prob=0.2, crash_epoch=16,
                     partition_prob=0.2, partition_epoch=16)
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def workload_params(cfg: RaftConfig) -> dict:
    """The client-workload provenance block every bench manifest and
    client segment records (ISSUE r09: a client-SLO number without its
    workload parameters is not reproducible)."""
    return {"rate": cfg.client_rate, "slots": cfg.client_slots,
            "retry_backoff": cfg.client_retry_backoff,
            "retry_policy": "fixed-interval-resubmit",
            "queue_cap": cfg.client_queue_cap,
            "seed": cfg.seed}


def table_max(session_seq, node_axis: int):
    """Group-level durable-commit witness: the max applied seq per sid
    over the group's nodes. `node_axis` is the K axis of the layout
    ([G, K, S] batched -> 1; [K, S, 8, 128] kernel -> 0)."""
    return jnp.max(session_seq, axis=node_axis)


def client_update(cfg: RaftConfig, cs: ClientState, tmax, g, sid, t
                  ) -> ClientState:
    """One client transition, evaluated on the POST-tick state. Purely
    elementwise over broadcastable coordinate grids `g`/`sid` and the
    per-slot table witness `tmax` — layout-agnostic (module docstring).
    `HostClients._update` mirrors this line for line."""
    acked = (cs.inflight != 0) & (tmax >= cs.done)
    last_lat = jnp.where(acked, t - cs.t_start, -1)
    done = cs.done + acked.astype(I32)
    inflight = jnp.where(acked, 0, cs.inflight)
    # Open-loop arrival, gated on the 10-bit lifetime bound.
    room = (done + cs.backlog + inflight) <= _c.SESSION_SEQ_MASK
    arrive = jrng.client_arrives(cfg.seed, g, sid, t, cfg.clients_u32) & room
    shed = cs.shed
    if cfg.client_queue_cap > 0:
        # Bounded admission (r20, DESIGN.md §19): an arrival that would
        # push the backlog past the cap is SHED — a definitive reject,
        # never issued a seq, never retried. The static gate keeps the
        # cap-off transition byte-identical to r19.
        admit = cs.backlog < cfg.client_queue_cap
        shed = shed + (arrive & ~admit).astype(I32)
        arrive = arrive & admit
    backlog = cs.backlog + arrive.astype(I32)
    # Retry BEFORE start: only an op that stayed in flight re-submits.
    retry = (inflight != 0) & ((t - cs.t_sub) >= cfg.client_retry_backoff)
    start = (inflight == 0) & (backlog > 0)
    submit = (start | retry).astype(I32)
    return ClientState(
        done=done,
        backlog=backlog - start.astype(I32),
        inflight=jnp.where(start, 1, inflight),
        t_start=jnp.where(start, t, cs.t_start),
        t_sub=jnp.where(start | retry, t, cs.t_sub),
        submit=submit,
        retries=cs.retries + retry.astype(I32),
        last_lat=last_lat,
        shed=shed,
    )


def submit_payloads(cfg: RaftConfig, cs: ClientState, g, sid):
    """(submit, payload): the one-tick pulses phase C consumes and the
    full 30-bit session payloads they carry (seq = the slot's `done`,
    val hashed from the op identity so retries are byte-identical).
    Elementwise like `client_update` — both engines call it."""
    val = jrng.client_val(cfg.seed, g, sid, cs.done)
    payload = (jnp.int32(_c.SESSION_FLAG)
               | (sid << _c.SESSION_SID_SHIFT)
               | (cs.done << _c.SESSION_SEQ_SHIFT) | val)
    return cs.submit, payload   # i32 pulses: kernel-safe (no i1 vectors)


# ----------------------------------------------------------- CPU oracle side


class HostClients:
    """Pure-Python mirror of `client_update`/`submit_payloads` for ONE
    group, driving the CPU oracle `Cluster` (core/cluster.py wires it
    in when cfg.client_rate > 0). Every branch matches the jnp
    transition term for term; the differential in tests/test_clients.py
    holds the two bit-identical through the full retrying schedule."""

    def __init__(self, cfg: RaftConfig, group: int):
        self.cfg = cfg
        self.g = group
        s = cfg.client_slots
        self.done = [0] * s
        self.backlog = [0] * s
        self.inflight = [0] * s
        self.t_start = [0] * s
        self.t_sub = [0] * s
        self.submit = [0] * s
        self.retries = [0] * s
        self.last_lat = [-1] * s
        self.shed = [0] * s      # admission rejects (cap > 0 only)
        # Host-side SLO tally (the oracle's analogue of the client
        # metric lanes): completed-op ack latencies, in ticks.
        self.latencies: list[int] = []

    def pending_cmds(self) -> list[int]:
        """The payloads phase C appends THIS tick, in slot order — the
        pulses raised by the previous tick's `observe`."""
        out = []
        for s in range(self.cfg.client_slots):
            if self.submit[s]:
                out.append(_c.session_payload(
                    s, self.done[s],
                    rng.client_val(self.cfg.seed, self.g, s, self.done[s])))
        return out

    def observe(self, tmax: list[int], t: int) -> None:
        """`client_update` on host ints: fold the post-tick table
        witness `tmax` (max applied seq per sid over the group's
        nodes) and raise next tick's pulses."""
        cfg = self.cfg
        for s in range(cfg.client_slots):
            acked = bool(self.inflight[s]) and tmax[s] >= self.done[s]
            self.last_lat[s] = t - self.t_start[s] if acked else -1
            if acked:
                self.latencies.append(t - self.t_start[s])
                self.done[s] += 1
                self.inflight[s] = 0
            room = (self.done[s] + self.backlog[s] + self.inflight[s]
                    <= _c.SESSION_SEQ_MASK)
            if room and rng.client_arrives(cfg.seed, self.g, s, t,
                                           cfg.clients_u32):
                if (cfg.client_queue_cap > 0
                        and self.backlog[s] >= cfg.client_queue_cap):
                    self.shed[s] += 1   # definitive reject (no seq, no retry)
                else:
                    self.backlog[s] += 1
            retry = (self.inflight[s]
                     and t - self.t_sub[s] >= cfg.client_retry_backoff)
            start = not self.inflight[s] and self.backlog[s] > 0
            if start:
                self.backlog[s] -= 1
                self.inflight[s] = 1
                self.t_start[s] = t
            if start or retry:
                self.t_sub[s] = t
            self.submit[s] = 1 if (start or retry) else 0
            if retry:
                self.retries[s] += 1


# --------------------------------------------------------- exactly-once gate


def exactly_once_report(cfg: RaftConfig, st, metrics=None):
    """(ok, detail): host-side exactly-once accounting over a FINAL
    state — the endpoint complement of the per-tick client-safety fold
    (sim/check.py `client_safety`). Checks, per group:

    - dedup-table agreement: nodes with the same applied prefix hold
      identical (sid -> seq) tables (a divergent dedup DECISION);
    - no phantom apply: no node's table holds a seq above the slot's
      issued frontier (`done`);
    - every fully-applied node agrees: nodes whose applied index
      reaches the group max hold the group-max table per sid (the
      crash-stable form of "every ack is table-backed" — a
      mid-recovery node legitimately lags, a caught-up one cannot);
    - metric accounting (when `metrics` carries client lanes):
      `client_acked[g] == sum_s done[g, s]` exactly;
    - admission accounting (cfg.client_queue_cap > 0; r20): the shed
      ledger exists exactly when the cap is on, no backlog ever
      exceeds the cap (the admission gate is the ONLY producer), and
      shed counts are nonnegative — a shed arrival was a definitive
      reject that provably never entered seq space, so it can appear
      in no dedup table (already covered by the frontier check: shed
      never advances `done`).
    """
    nodes = st.nodes
    cl = st.clients
    if cl is None or nodes.session_seq is None:
        return False, "state carries no client subsystem"
    table = np.asarray(nodes.session_seq)          # [G, K, S]
    applied = np.asarray(nodes.applied)            # [G, K]
    done = np.asarray(cl.done)                     # [G, S]
    g, k, s = table.shape
    problems = []
    for a in range(k):
        for b in range(a + 1, k):
            bad = (applied[:, a] == applied[:, b]) \
                & (table[:, a] != table[:, b]).any(axis=-1)
            if bad.any():
                problems.append(
                    f"nodes {a}/{b}: {int(bad.sum())} group(s) with equal "
                    f"applied prefix but divergent dedup tables")
    over = table > done[:, None, :]
    if over.any():
        problems.append(f"{int(over.any(axis=(1, 2)).sum())} group(s) hold "
                        f"a table seq above the issued frontier")
    # Tables are monotone in the applied prefix, so the most-applied
    # node must hold the group's pointwise-max table.
    top = np.take_along_axis(
        table, applied.argmax(axis=1)[:, None, None], axis=1)[:, 0, :]
    lag = top < table.max(axis=1)
    if lag.any():
        problems.append(f"{int(lag.any(axis=1).sum())} group(s): a node "
                        f"with a shorter applied prefix holds a HIGHER "
                        f"dedup seq than the most-applied node")
    if metrics is not None and metrics.client_acked is not None:
        acked = np.asarray(metrics.client_acked)
        if not np.array_equal(acked, done.sum(axis=1)):
            problems.append("client_acked metric != sum of per-slot done")
    cap = cfg.client_queue_cap
    if (cl.shed is None) != (cap == 0):
        problems.append(
            f"ClientState.shed {'absent' if cl.shed is None else 'present'} "
            f"but cfg.client_queue_cap == {cap} — the shed ledger must "
            f"exist exactly when admission control is on")
    n_shed = 0
    if cap > 0 and cl.shed is not None:
        shed = np.asarray(cl.shed)
        n_shed = int(shed.sum())
        if (shed < 0).any():
            problems.append("negative shed count — the reject ledger "
                            "only ever increments")
        over_cap = np.asarray(cl.backlog) > cap
        if over_cap.any():
            problems.append(
                f"{int(over_cap.any(axis=1).sum())} group(s) hold a "
                f"backlog above client_queue_cap={cap} — an arrival "
                f"bypassed the admission gate")
    return (not problems,
            "; ".join(problems) if problems else
            f"exactly-once ok over {g} group(s) x {s} slot(s): "
            f"{int(done.sum())} acked op(s)"
            + (f", {n_shed} shed" if cap > 0 else "")
            + ", tables consistent")
