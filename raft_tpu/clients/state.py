"""Per-group open-loop client state for the scheduled traffic model
(DESIGN.md §10).

Every leaf is i32 with leading dims `[G, S]` (S = cfg.client_slots) on
the batched XLA path and `[S, 8, 128]` tiles on the Pallas kernel wire
— the transition in `clients/workload.py` is written purely
elementwise so ONE implementation serves both layouts, exactly like
utils/jrng serves both engines.

This is CLIENT-side (environment) state, not replicated state: it
rides `State.clients` so the scan carry / kernel wire / checkpoints
all transport it, but no node ever reads another group's client state
and the protocol tick only sees it through phase C's submit pulses.
The replicated dedup table lives in `PerNode.session_seq`.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

I32 = jnp.int32

# Wire/leaf order of the BASE client state — the unconditional
# clients-on wire (scripts/check_metric_parity.py pins dtype/shape).
# `ClientState._fields` is this tuple plus the statically-gated
# admission leaves below; `active_client_leaves(cfg)` is the per-cfg
# wire order every engine iterates.
CLIENT_LEAVES = ("done", "backlog", "inflight", "t_start", "t_sub",
                 "submit", "retries", "last_lat")

# Leaves that exist IFF bounded admission control is on
# (cfg.client_queue_cap > 0; r20, DESIGN.md §19) — optional NamedTuple
# fields (default None) so a cap-off universe's wire, checkpoint key
# set, and pytree are byte-identical to r19.
ADMISSION_LEAVES = ("shed",)


def active_client_leaves(cfg) -> tuple:
    """The cfg's client wire order: the base leaves, plus the admission
    leaves when the bounded queue is on. THE iteration rule for every
    client-leaf consumer (kernel wire pack/unpack, narrow specs, byte
    models) — a gated leaf must never ride the wire gate-off."""
    return CLIENT_LEAVES + (ADMISSION_LEAVES
                            if cfg.client_queue_cap > 0 else ())

# Narrow RESIDENT dtypes under cfg.narrow_clients (r19, DESIGN.md §18
# range table) — the authority `sim.state.narrow_spec` prices
# `clients.*` from, kept next to the NamedTuple so a new leaf cannot
# ship without a dtype decision. Ranges: op counters / tick stamps fit
# u16 under the <= 65,535-tick audited horizon (the sticky group_id
# latch refuses past it); 0/1 pulses fit i8; last_lat needs a signed
# lane for its -1 idle sentinel. The KERNEL wire stays i32 words
# regardless (kinit widens, kfinish re-narrows).
NARROW_CLIENT_SPEC = {
    "done": jnp.uint16, "backlog": jnp.uint16, "t_start": jnp.uint16,
    "t_sub": jnp.uint16, "retries": jnp.uint16,
    "inflight": jnp.int8, "submit": jnp.int8,
    "last_lat": jnp.int16,
    # shed counts rejected arrivals — at most one per tick, so it fits
    # u16 under the same <= 65,535-tick audited horizon as done.
    "shed": jnp.uint16,
}


class ClientState(NamedTuple):
    """One open-loop exactly-once client per (group, sid) slot."""

    done: jnp.ndarray      # ops fully acked == seq of the NEXT op
    backlog: jnp.ndarray   # arrived-but-not-started ops (open-loop queue)
    inflight: jnp.ndarray  # 0/1: an op (seq == done) is being processed
    t_start: jnp.ndarray   # tick the in-flight op was first submitted
    t_sub: jnp.ndarray     # tick of the LAST submission (retry clock)
    submit: jnp.ndarray    # 0/1 pulse: leaders append this op next tick
    retries: jnp.ndarray   # re-submissions to date (potential duplicates)
    last_lat: jnp.ndarray  # ack latency of an op acked THIS tick; -1 none
    # Admission control (cfg.client_queue_cap > 0; None otherwise):
    shed: jnp.ndarray = None   # arrivals definitively rejected at the cap


def clients_init(cfg, n_groups: int) -> ClientState:
    """Fresh clients: idle, empty backlogs, no events."""
    z = jnp.zeros((n_groups, cfg.client_slots), I32)
    return ClientState(done=z, backlog=z, inflight=z, t_start=z, t_sub=z,
                       submit=z, retries=z,
                       last_lat=jnp.full((n_groups, cfg.client_slots),
                                         -1, I32),
                       shed=z if cfg.client_queue_cap > 0 else None)
