"""Client traffic subsystem (DESIGN.md §10): deterministic open-loop
exactly-once sessions on BOTH engines.

`state.py` carries the per-(group, sid) client state that rides
`State.clients`; `workload.py` is the one elementwise transition both
engines evaluate (plus its pure-Python oracle mirror `HostClients` and
the endpoint `exactly_once_report` gate). The replicated `(sid, seq)`
dedup tables live in the protocol state (`sim/state.py
PerNode.session_seq`); the per-tick exactly-once invariant in
`sim/check.py client_safety`; the client-visible SLO lanes in
`sim/run.py Metrics` / `sim/pkernel.py KMetrics`.
"""

from raft_tpu.clients.state import (ADMISSION_LEAVES, CLIENT_LEAVES,
                                    ClientState, active_client_leaves,
                                    clients_init)
from raft_tpu.clients.workload import (HostClients, client_update,
                                       clients_64_cfg, exactly_once_report,
                                       submit_payloads, table_max,
                                       workload_params)

__all__ = [
    "ADMISSION_LEAVES", "CLIENT_LEAVES", "ClientState", "HostClients",
    "active_client_leaves", "client_update", "clients_64_cfg",
    "clients_init", "exactly_once_report", "submit_payloads", "table_max",
    "workload_params",
]
