"""Static happens-before hazard prover for the cohort paging pipeline
(DESIGN.md §17): prove `parallel/cohort.py` + `parallel/stream_sched.py`
schedule their put / launch / drain / staging-reuse operations safely —
for every (cohort_blocks, n_devices, n_windows) within bounds — without
running a chip.

How it works: the pipeline's device-touching primitives are narrow and
module-seamed (`cohort._window`, `stream_sched.put_window`,
`pkernel.kstep`, `kmesh.kstep_sharded`, `cohort._writeback`,
`stream_sched.drain_window`, `jax.block_until_ready`). `capture()`
monkeypatches that seam table with recording stubs and runs the REAL
scheduler loop (`cohort.stream_ticks` / `stream_ticks_sharded`) over
synthetic host leaves — the control flow under audit is the shipped
code, byte for byte; only the copies and launches are replaced by event
emission. The result is a total-order event trace in program order,
each event stamped with the scheduler call site (`file.py:line`).

`check_trace` then replays the trace against the dependency rules the
module docstrings promise (stream_sched.py "Slot-reuse safety",
cohort.py's pipeline contract):

- **drain-before-sync** — a window's d2h drain must happen-after
  completion evidence for its launches (`block_until_ready`). The real
  np.asarray would block anyway, but THAT is the engine saving the
  scheduler; the contract is that the pipeline never *relies* on it —
  a drain of an in-flight window serializes d2h behind compute on the
  device queue and voids the overlap model (DESIGN.md §15/§16).
- **staging-overwrite-in-flight** — a StagingPool slot may be
  overwritten only after the window previously staged there has
  completion evidence (its `device_put`s are long returned by then —
  the depth-2 reuse argument, stream_sched.py:37-43).
- **double-drain** — each resident window drains exactly once (a
  second drain would overwrite host rows a later window already
  evolved).
- **drain-coverage** — per pass over the store, the drained [s0, s1)
  ranges must tile [0, GS) exactly: every wire offset written exactly
  once per pass, no gap, no overlap. Put ranges must tile identically
  (nothing computed but never persisted, nothing persisted twice).

A violated rule is reported as a `Hazard` naming the rule, the window,
and the scheduler source line that issued the offending operation —
`prove_schedulers()` must return zero hazards over the whole bound grid
(the r16/r17 pipelines), and the synthetic negative schedulers below
(`synthetic_use_after_free`, `synthetic_double_drain`,
`synthetic_slot_overwrite`) must each be caught with their own
file:line. Wired into `scripts/static_audit.py --level deep`.

Soundness/limits: the proof is over the scheduler's *program order* at
one (cohort_blocks, n_devices, n_windows) point per run — a data-
dependent schedule would need per-point re-proof, which is what the
grid sweep is. Python program order is the happens-before order here
(single host thread issues every operation; device-side reordering is
exactly what the completion-evidence rules guard). `device_put`'s
return is NOT taken as copy-completion evidence — only
`block_until_ready` is — so the rules are conservative with respect to
a fully async transfer engine."""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import traceback
from typing import Callable, List, Optional, Tuple

import numpy as np

from raft_tpu.config import RaftConfig
from raft_tpu.sim.pkernel import GB, LANE, SUB

_THIS_FILE = __file__


# ------------------------------------------------------------- the trace


@dataclasses.dataclass(frozen=True)
class Event:
    """One pipeline operation, in issue (program) order."""
    kind: str                      # put | stage | launch | sync | drain
    token: int                     # resident-window instance id
    win: Tuple[int, int]           # (s0, s1) sublane range in the store
    slot: Optional[int]            # staging slot (stage events only)
    site: str                      # "file.py:NN" — the scheduler line


@dataclasses.dataclass(frozen=True)
class Hazard:
    """One dependency-rule violation, named with the scheduler source
    line that issued the unsafe operation."""
    rule: str
    site: str
    detail: str

    def __str__(self):
        return f"{self.rule} at {self.site}: {self.detail}"


class _Tok:
    """Opaque stand-in for a resident device window (the tuple of
    sharded arrays in the real pipeline). The scheduler only threads it
    through kstep/block_until_ready/writeback, so an attribute bag is
    enough."""
    _next = itertools.count()

    def __init__(self, win):
        self.tid = next(_Tok._next)
        self.win = win


def _site() -> str:
    """file.py:line of the innermost non-stub caller — the scheduler
    statement that issued the operation under capture."""
    for fr in reversed(traceback.extract_stack()):
        if fr.filename == _THIS_FILE and (
                fr.name.startswith("_stub") or fr.name in
                ("_site", "stage")):
            continue
        base = fr.filename.rsplit("/", 1)[-1]
        return f"{base}:{fr.lineno}"
    return "<unknown>"


# -------------------------------------------------------------- capture


@contextlib.contextmanager
def capture(events: List[Event]):
    """Patch the pipeline's device-seam table with recording stubs;
    restore on exit. Inside the context, running any scheduler built on
    the seams (the real `cohort.stream_ticks`/`stream_ticks_sharded`,
    or the synthetic negatives below) appends its operation trace to
    `events` without touching a device."""
    import jax

    from raft_tpu.parallel import cohort, stream_sched
    from raft_tpu.sim import pkernel

    def _stub_window(host_leaves, s0, s1):
        t = _Tok((s0, s1))
        events.append(Event("put", t.tid, (s0, s1), None, _site()))
        return t

    def _stub_put_window(host_leaves, s0, s1, mesh, pool=None, slot=0,
                         per_device=None):
        t = _Tok((s0, s1))
        if pool is not None:
            # The staged path copies into the parity slot BEFORE the
            # device_puts read it — keep the real copy (it validates
            # shapes) and record the reuse event.
            pool.stage(host_leaves, s0, s1, slot)
            events.append(Event("stage", t.tid, (s0, s1),
                                slot % stream_sched.StagingPool.SLOTS,
                                _site()))
        events.append(Event("put", t.tid, (s0, s1), None, _site()))
        return t

    def _stub_kstep(cfg, leaves, t0, n_ticks, interpret=False, **kw):
        events.append(Event("launch", leaves.tid, leaves.win, None,
                            _site()))
        return leaves

    def _stub_kstep_sharded(cfg, leaves, t0, n_ticks, mesh,
                            interpret=False, **kw):
        events.append(Event("launch", leaves.tid, leaves.win, None,
                            _site()))
        return leaves

    def _stub_block(x, *a, **kw):
        if isinstance(x, _Tok):
            events.append(Event("sync", x.tid, x.win, None, _site()))
            return x
        return _real_block(x, *a, **kw)

    def _stub_writeback(host_leaves, window_leaves, s0, s1):
        events.append(Event("drain", window_leaves.tid, (s0, s1), None,
                            _site()))

    def _stub_drain_window(host_leaves, window_leaves, s0, s1,
                           per_device=None):
        events.append(Event("drain", window_leaves.tid, (s0, s1), None,
                            _site()))

    try:
        from raft_tpu.parallel import kmesh
    except Exception:                             # pragma: no cover
        kmesh = None
    saved = [(cohort, "_window", cohort._window),
             (cohort, "_writeback", cohort._writeback),
             (stream_sched, "put_window", stream_sched.put_window),
             (stream_sched, "drain_window", stream_sched.drain_window),
             (pkernel, "kstep", pkernel.kstep),
             (jax, "block_until_ready", jax.block_until_ready)]
    if kmesh is not None:
        saved.append((kmesh, "kstep_sharded", kmesh.kstep_sharded))
    _real_block = jax.block_until_ready
    cohort._window = _stub_window
    cohort._writeback = _stub_writeback
    stream_sched.put_window = _stub_put_window
    stream_sched.drain_window = _stub_drain_window
    pkernel.kstep = _stub_kstep
    jax.block_until_ready = _stub_block
    if kmesh is not None:
        kmesh.kstep_sharded = _stub_kstep_sharded
    try:
        yield events
    finally:
        for mod, name, fn in saved:
            setattr(mod, name, fn)


class _FakeMesh:
    """mesh.size is all the captured scheduler needs (put/drain/launch
    are stubbed; _heartbeat_sharded no-ops without a heartbeat)."""

    def __init__(self, size):
        self.size = size


def _fake_leaves(gs: int, n_leaves: int = 2):
    """Tiny host-store stand-ins: real numpy arrays (StagingPool's real
    allocation + copy run against them) with `gs` sublanes of `LANE`
    lanes — the only geometry the scheduler reads."""
    return [np.zeros((gs, LANE), dtype=np.uint32)
            for _ in range(n_leaves)]


# ----------------------------------------------------------- the prover


def check_trace(events: List[Event], gs: int,
                passes: int = 1) -> List[Hazard]:
    """Replay an event trace against the dependency rules; returns the
    hazards found (empty == proven safe for this schedule). `gs` is the
    store's sublane extent; `passes` how many full store sweeps the
    trace is expected to make (stream_ticks makes one per call)."""
    hazards = []
    synced: set = set()
    drained_tokens: set = set()
    slot_owner: dict = {}
    put_ranges: List[Tuple[int, int]] = []
    drain_ranges: List[Tuple[int, int]] = []
    for ev in events:
        if ev.kind == "stage":
            prev = slot_owner.get(ev.slot)
            if prev is not None and prev not in synced:
                hazards.append(Hazard(
                    "staging-overwrite-in-flight", ev.site,
                    f"slot {ev.slot} restaged for window {ev.win} while "
                    f"the window previously staged there has no "
                    f"completion evidence"))
            slot_owner[ev.slot] = ev.token
        elif ev.kind == "put":
            put_ranges.append(ev.win)
        elif ev.kind == "sync":
            synced.add(ev.token)
        elif ev.kind == "drain":
            if ev.token in drained_tokens:
                hazards.append(Hazard(
                    "double-drain", ev.site,
                    f"window {ev.win} drained twice"))
            drained_tokens.add(ev.token)
            if ev.token not in synced:
                hazards.append(Hazard(
                    "drain-before-sync", ev.site,
                    f"window {ev.win} drained without completion "
                    f"evidence for its launches"))
            drain_ranges.append(ev.win)
    for label, ranges in (("put", put_ranges), ("drain", drain_ranges)):
        cover = sorted(ranges)
        expect = passes * _tile(gs, cover)
        if cover != sorted(expect):
            hazards.append(Hazard(
                "drain-coverage", "<whole-trace>",
                f"{label} ranges {cover} do not tile [0, {gs}) exactly "
                f"{passes}x"))
    return hazards


def _tile(gs: int, cover: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    """The expected one-pass tiling of [0, gs): infer the window step
    from the trace's first range (bounds geometry), fall back to one
    whole-store window."""
    step = (cover[0][1] - cover[0][0]) if cover else gs
    step = step or gs
    return [(s0, min(s0 + step, gs)) for s0 in range(0, gs, step)]


# ------------------------------------------------- real-scheduler proofs


def _run_real(cfg: RaftConfig, gs: int, n_devices: int,
              staging: bool = True, n_ticks: int = 2,
              chunk_ticks: int = 1) -> List[Event]:
    """Run the SHIPPED pipeline loop over a synthetic store under
    capture; returns its event trace."""
    from raft_tpu.parallel import cohort
    leaves = _fake_leaves(gs)
    events: List[Event] = []
    with capture(events):
        if n_devices == 1:
            cohort.stream_ticks(cfg, leaves, gs * LANE, 0, n_ticks,
                                chunk_ticks=chunk_ticks)
        else:
            cohort.stream_ticks_sharded(
                cfg, leaves, gs * LANE, 0, n_ticks, _FakeMesh(n_devices),
                chunk_ticks=chunk_ticks, staging=staging)
    return events


def prove_schedulers(max_cohort_blocks: int = 3, max_devices: int = 4,
                     max_windows: int = 4,
                     log: Callable = None) -> dict:
    """The r18 hazard proof: for every (cohort_blocks, n_devices,
    n_windows, staging) within bounds, run the real r16 unsharded and
    r17 sharded pipeline loops under capture and check every trace.
    Returns {"configs": n, "events": n, "hazards": [str, ...]} —
    hazards must be empty; static_audit --level deep asserts so."""
    from raft_tpu.sim import pkernel as pk

    n_cfg = n_ev = 0
    hazards: List[Hazard] = []
    for cb, nd in itertools.product(range(1, max_cohort_blocks + 1),
                                    (1, 2, max_devices)):
        cfg = RaftConfig(seed=0, k=3, stream_groups=True,
                         cohort_blocks=cb)
        step = pk.stream_blocks_per_device(cfg, nd) * nd * SUB
        for nw in range(1, max_windows + 1):
            gs = step * nw
            for staging in ((True, False) if nd > 1 else (True,)):
                ev = _run_real(cfg, gs, nd, staging=staging)
                n_cfg += 1
                n_ev += len(ev)
                found = check_trace(ev, gs)
                hazards += found
                if log and found:
                    log(f"hazards at cb={cb} nd={nd} nw={nw} "
                        f"staging={staging}: {[str(h) for h in found]}")
    return {"configs": n_cfg, "events": n_ev,
            "hazards": [str(h) for h in hazards]}


# ------------------------------------------- synthetic negative fixtures
#
# Buggy scheduler loops written against the SAME seams, so the prover's
# detection (and its file:line naming) is itself tested — each of these
# must be caught, at a line inside this file. They mirror the shape of
# cohort.stream_ticks with one dependency edge removed.


def synthetic_use_after_free(cfg: RaftConfig, gs: int) -> List[Event]:
    """BUG: drains window i right after its launches DISPATCH — before
    any completion evidence — modeling a d2h racing the compute that
    still owns the buffers."""
    from raft_tpu.parallel import cohort
    from raft_tpu.sim import pkernel
    leaves = _fake_leaves(gs)
    events: List[Event] = []
    step = pkernel.stream_blocks_per_device(cfg, 1) * SUB
    wins = [(s0, min(s0 + step, gs)) for s0 in range(0, gs, step)]
    with capture(events):
        for s0, s1 in wins:
            cur = cohort._window(leaves, s0, s1)
            cur = pkernel.kstep(cfg, cur, 0, 1)
            cohort._writeback(leaves, cur, s0, s1)   # <- no sync first
    return events


def synthetic_double_drain(cfg: RaftConfig, gs: int) -> List[Event]:
    """BUG: drains the final window twice (a stale `pending` not
    cleared after the epilogue drain) — the second drain overwrites
    host rows with the same bytes today, and with ANOTHER window's
    evolution the day the loop is reordered."""
    import jax

    from raft_tpu.parallel import cohort
    from raft_tpu.sim import pkernel
    leaves = _fake_leaves(gs)
    events: List[Event] = []
    with capture(events):
        cur = cohort._window(leaves, 0, gs)
        cur = pkernel.kstep(cfg, cur, 0, 1)
        jax.block_until_ready(cur)
        cohort._writeback(leaves, cur, 0, gs)
        cohort._writeback(leaves, cur, 0, gs)        # <- stale pending
    return events


def synthetic_slot_overwrite(cfg: RaftConfig, gs: int) -> List[Event]:
    """BUG: a depth-3 prefetch over the depth-2 StagingPool — window
    i+2 restages the slot window i staged while window i still has no
    completion evidence (exactly the "deeper prefetch would need more
    slots" caveat, stream_sched.py:42-43)."""
    import jax

    from raft_tpu.parallel import stream_sched
    from raft_tpu.sim import pkernel
    step = pkernel.stream_blocks_per_device(cfg, 2) * 2 * SUB
    gs = max(gs, 3 * step)
    gs -= gs % step
    leaves = _fake_leaves(gs)
    events: List[Event] = []
    mesh = _FakeMesh(2)
    wins = [(s0, min(s0 + step, gs)) for s0 in range(0, gs, step)]
    with capture(events):
        pool = stream_sched.StagingPool(leaves, step)
        resident = [stream_sched.put_window(leaves, *wins[i], mesh,
                                            pool=pool, slot=i)
                    for i in range(3)]               # <- depth-3 lookahead
        for tok, (s0, s1) in zip(resident, wins[:3]):
            from raft_tpu.parallel import kmesh
            tok = kmesh.kstep_sharded(cfg, tok, 0, 1, mesh)
            jax.block_until_ready(tok)
            stream_sched.drain_window(leaves, tok, s0, s1)
    return events


def prove_negatives(log: Callable = None) -> dict:
    """Run the synthetic buggy schedulers; each must be CAUGHT with the
    expected rule (the prover's own mutation test). Returns
    {"caught": n, "missed": [name, ...], "sites": {name: site}}."""
    cfg = RaftConfig(seed=0, k=3, stream_groups=True, cohort_blocks=1)
    gs2 = pkernel_step(cfg, 1) * 2
    cases = (
        ("use_after_free", synthetic_use_after_free(cfg, gs2),
         "drain-before-sync", gs2),
        ("double_drain", synthetic_double_drain(cfg, pkernel_step(cfg, 1)),
         "double-drain", pkernel_step(cfg, 1)),
        ("slot_overwrite", synthetic_slot_overwrite(
            cfg, 3 * pkernel_step(cfg, 2)),
         "staging-overwrite-in-flight", 3 * pkernel_step(cfg, 2)),
    )
    missed, sites = [], {}
    for name, events, rule, gs in cases:
        found = [h for h in check_trace(events, gs) if h.rule == rule]
        if not found:
            missed.append(name)
        else:
            sites[name] = found[0].site
            if log:
                log(f"negative {name}: caught at {found[0].site}")
    return {"caught": len(cases) - len(missed), "missed": missed,
            "sites": sites}


def pkernel_step(cfg: RaftConfig, nd: int) -> int:
    """Sublane step of one global window at `nd` devices (the
    cohort_windows geometry, exposed for the fixtures)."""
    from raft_tpu.sim import pkernel
    return pkernel.stream_blocks_per_device(cfg, nd) * nd * SUB
