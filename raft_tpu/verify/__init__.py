"""Exhaustive verification without a chip (DESIGN.md §17).

Three instruments over the SAME semantics the engines execute:

- `invariants`: the one source of the safety predicates — array-level,
  generic over numpy/jax.numpy. `sim/check.py`'s per-tick fold and the
  bounded model checker both evaluate these exact functions, so the
  runtime safety bit is by construction a spot-check of what the
  checker proves exhaustively at small scope.
- `mcheck`: bounded exhaustive model checker — BFS over canonicalized
  states of the REAL CPU oracle (`core/node.py`) under all delivery /
  drop / crash / timeout schedules within bounds, with node-permutation
  symmetry reduction; counterexamples emit as nemesis-format
  reproducer artifacts that replay through `scripts/nemesis_search.py`.
- `hazards`: static happens-before prover for the r16/r17 streaming
  pipeline — records the put/launch/drain/staging event order the real
  scheduler code dispatches (patched copy/launch seams, no chip) and
  proves the ordering invariants over a (cohort_blocks, n_devices, G)
  grid.

`mutants` seeds ~12 semantic bugs into the oracle step; the checker
must kill every one (tests/test_verify.py's kill matrix) — the proof
the verifier has teeth.
"""
