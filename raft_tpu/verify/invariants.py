"""THE invariant source: Raft's safety properties as array predicates,
generic over the array namespace (DESIGN.md §17).

Every predicate takes raw per-node leaves plus `xp` — `numpy` when the
bounded model checker (`verify/mcheck.py`) evaluates it on views of the
CPU oracle's state, `jax.numpy` when `sim/check.py`'s per-tick fold
evaluates it on `[G, K]` State leaves. One definition site means the
runtime safety bit folded into `Metrics.safety` every tick is a
spot-check of the SAME predicates the checker proves exhaustively at
small scope — they cannot drift. (`pkernel._safety_tick` mirrors these
on k-state tiles, statically unrolled; pinned by the kernel
differential + scripts/check_metric_parity.py, the established kernel
mirror rule.)

Axis convention: the node axis is LAST for scalar leaves (`[..., K]`),
second-to-last for ring leaves (`[..., K, L]`); leading batch axes
broadcast through (check.py: `[G, K]`, mcheck: `[1, K]`). Predicates
return `bool[...]` — one bit per group.

This module is also the spec seam ROADMAP item 3 needs: a MultiPaxos
engine sharing State/Mailbox checks against these exact predicates
(election safety becomes per-slot ballot safety; log matching and
leader completeness are the properties arXiv:2004.05074 shows are the
only real deltas).
"""

from __future__ import annotations

import numpy as np

from raft_tpu.core.node import LEADER


def _signed(a, xp=np):
    """`a` lifted to a signed >= 32-bit lane when it is a narrow or
    unsigned integer (r19, DESIGN.md §18): predicate arithmetic — the
    ring-slot subtraction below, the window differences — must run at
    the audited width regardless of the caller's resident dtype. At
    u16, `s - snap` wraps to the 65-thousands and the `off >= 0` branch
    is vacuously true, silently blessing a broken window. Bools and
    already-wide signed lanes pass through untouched, so the wide path
    is byte-for-byte the pre-r19 one; int64 under numpy (the model
    checker's native view width), int32 under jax (x64 is off)."""
    dt = np.dtype(a.dtype)
    if dt == np.bool_ or (dt.kind == "i" and dt.itemsize >= 4):
        return a
    return a.astype(np.int64 if xp is np else np.int32)


def slot_abs_index(snap_index, log_cap: int, xp=np):
    """`[..., L]` absolute index assigned to each ring slot: entry at
    absolute index i lives in slot (i-1) % L on EVERY node, so slot s
    under window (snap, snap+L] holds snap + 1 + ((s - snap) mod L) —
    the same formula as `step._abs_index` / `pkernel._abs_index`,
    written without a negative-operand mod."""
    snap_index = _signed(snap_index, xp)
    s = xp.arange(log_cap, dtype=snap_index.dtype)
    off = s - snap_index[..., None] % log_cap
    return snap_index[..., None] + 1 + xp.where(off >= 0, off,
                                                off + log_cap)


def election_safety(role, term, xp=np):
    """No two current leaders share a term (point-in-time form of
    cluster._check_election_safety; crashed leaders hold their term)."""
    k = role.shape[-1]
    ok = xp.ones(role.shape[:-1], dtype=bool)
    for a in range(k):
        for b in range(a + 1, k):
            clash = ((role[..., a] == LEADER) & (role[..., b] == LEADER)
                     & (term[..., a] == term[..., b]))
            ok = ok & ~clash
    return ok


def digest_agreement(applied, digest, xp=np):
    """State-machine safety witness: nodes that applied the same prefix
    hold the same state-machine digest (cluster._on_apply's commit-
    identity invariant, collapsed to the digest chain)."""
    k = applied.shape[-1]
    ok = xp.ones(applied.shape[:-1], dtype=bool)
    for a in range(k):
        for b in range(a + 1, k):
            clash = ((applied[..., a] == applied[..., b])
                     & (digest[..., a] != digest[..., b]))
            ok = ok & ~clash
    return ok


def window_bounds(applied, commit, snap_index, last_index, log_cap: int,
                  xp=np):
    """Per-node structural sanity: applied == commit (phase A drains),
    snap <= commit <= last, window within the ring capacity."""
    applied, commit, snap_index, last_index = (
        _signed(a, xp) for a in (applied, commit, snap_index, last_index))
    ok = ((applied == commit)
          & (snap_index <= commit) & (commit <= last_index)
          & (last_index - snap_index <= log_cap))
    return xp.all(ok, axis=-1)


def log_matching(last_index, snap_index, log_term, log_payload,
                 log_cap: int, xp=np):
    """If two logs hold an entry with the same index and term, the
    entries carry the same payload (Raft's Log Matching property,
    point-in-time, per overlapping ring lane). Slot identity makes the
    pairwise compare elementwise: slot s holds the same absolute index
    on both nodes exactly when their computed slot indices agree."""
    last_index = _signed(last_index, xp)
    k = last_index.shape[-1]
    ok = xp.ones(last_index.shape[:-1], dtype=bool)
    absidx = slot_abs_index(snap_index, log_cap, xp)      # [..., K, L]
    for a in range(k):
        for b in range(a + 1, k):
            live = ((absidx[..., a, :] == absidx[..., b, :])
                    & (absidx[..., a, :] <= last_index[..., a, None])
                    & (absidx[..., b, :] <= last_index[..., b, None]))
            m = live & (log_term[..., a, :] == log_term[..., b, :])
            agree = xp.all(
                xp.where(m, log_payload[..., a, :] == log_payload[..., b, :],
                         True), axis=-1)
            ok = ok & agree
    return ok


def leader_completeness(role, term, commit, last_index, snap_index,
                        log_payload, log_cap: int, xp=np):
    """A current leader holds every entry any node has committed up to
    its own term (Raft Figure 3's Leader Completeness, point-in-time):
    for each ordered pair (a, b) with role_a == LEADER and
    term_a >= term_b, (1) commit_b <= last_index_a, and (2) on every
    ring lane where both nodes' slots map to the same absolute index
    within b's committed prefix and a's log, the payloads agree.

    Why sound: every entry in b's committed prefix was committed under
    a leader of term <= term_b <= term_a (accepting a commit index
    raises b's term to at least the committing leader's); by quorum
    intersection + the §5.4.2 current-term commit rule, the leader of
    term_a holds all of them, and leaders never truncate their own
    log. Payloads (not terms) are compared because takeover re-terms
    the top entry in place — commit identity is (index, payload).
    Entries below a's snap_index are excluded structurally (slot
    indices live in (snap_a, snap_a + L]); b's restart rewind only
    shrinks commit_b, weakening nothing."""
    commit = _signed(commit, xp)
    last_index = _signed(last_index, xp)
    k = role.shape[-1]
    ok = xp.ones(role.shape[:-1], dtype=bool)
    absidx = slot_abs_index(snap_index, log_cap, xp)      # [..., K, L]
    for a in range(k):
        for b in range(k):
            if a == b:
                continue
            cond = (role[..., a] == LEADER) & (term[..., a] >= term[..., b])
            holds = commit[..., b] <= last_index[..., a]
            lim = xp.minimum(commit[..., b], last_index[..., a])
            m = ((absidx[..., a, :] == absidx[..., b, :])
                 & (absidx[..., a, :] <= lim[..., None]))
            agree = xp.all(
                xp.where(m, log_payload[..., a, :] == log_payload[..., b, :],
                         True), axis=-1)
            ok = ok & (~cond | (holds & agree))
    return ok


def commit_durability(commit, last_index, snap_index, log_payload,
                      log_cap: int, xp=np):
    """The commit rule checked against lossy persistence (r20,
    DESIGN.md §19): every index in any node's committed prefix that is
    still visible in that node's window is HELD by at least a majority
    of the k nodes. Node `a` holds absolute index i when either

    - i <= snap_index_a: a's snapshot folded it (snapshots cover only
      committed prefixes, and commit identity pins one payload per
      index, so a compacted copy is a durable copy of THE entry), or
    - i sits live in a's window on the same ring lane (slot identity:
      i lives at slot (i-1) % L on every node) with the SAME payload —
      a conflicting uncommitted entry at i does not count.

    Why sound point-in-time: an index commits only after a majority
    durably acked it (under storage pressure a disk-full follower's
    AE reply stops at its durable prefix — entries that did not
    persist are never acked); each acker's term is >= the committing
    term from that point on, so no stale leader can make it truncate,
    and any leader of a later term holds the committed prefix (Leader
    Completeness) so conflict resolution never deletes it; restart
    keeps the durable log; compaction converts holding-in-window to
    holding-in-snapshot. Indices below the observing node's OWN
    snap_index are structurally out of view (they were checked while
    live). Payloads (not terms) compare because takeover re-terms the
    top entry in place.

    This is exactly what ack-without-persist breaks: a follower that
    acks entries its storage rejected lets the leader's match tally
    commit an index held by fewer than a majority. Majority is over
    the FULL k membership, which is exact in the model checker's
    reconfig-off scope (verify/mcheck.py's modeled universe — this
    predicate is checker-side, like log_matching, NOT folded into the
    runtime safety bit: under joint-consensus reconfig a commit
    quorum is a majority of the live voter set, which k-majority
    over-approximates)."""
    commit = _signed(commit, xp)
    last_index = _signed(last_index, xp)
    k = commit.shape[-1]
    majority = k // 2 + 1
    absidx = slot_abs_index(snap_index, log_cap, xp)      # [..., K, L]
    snap = _signed(snap_index, xp)
    ok = xp.ones(commit.shape[:-1], dtype=bool)
    for b in range(k):
        idx_b = absidx[..., b, :]                         # [..., L]
        live = idx_b <= commit[..., b, None]   # committed, in b's window
        cnt = xp.zeros(idx_b.shape, dtype=np.int32)
        for a in range(k):
            held = ((idx_b <= snap[..., a, None])
                    | ((absidx[..., a, :] == idx_b)
                       & (idx_b <= last_index[..., a, None])
                       & (log_payload[..., a, :] == log_payload[..., b, :])))
            cnt = cnt + held.astype(np.int32)
        ok = ok & xp.all(xp.where(live, cnt >= majority, True), axis=-1)
    return ok


def client_safety(applied, session_seq, done, xp=np):
    """The r09 exactly-once invariant (DESIGN.md §10): nodes with the
    same applied prefix hold element-identical (sid -> seq) dedup
    tables, and no table entry exceeds the slot's issued frontier.
    `session_seq` is `[..., K, S]`, `done` is `[..., S]`."""
    session_seq = _signed(session_seq, xp)
    done = _signed(done, xp)
    k = session_seq.shape[-2]
    ok = xp.all(session_seq <= done[..., None, :], axis=(-2, -1))
    for a in range(k):
        for b in range(a + 1, k):
            clash = ((applied[..., a] == applied[..., b])
                     & xp.any(session_seq[..., a, :] != session_seq[..., b, :],
                              axis=-1))
            ok = ok & ~clash
    return ok
