"""Seeded semantic mutants of the oracle step (DESIGN.md §17).

Each mutant is a `Node` subclass overriding ONE handler with a
copied-but-bugged body — a change a refactor could plausibly introduce,
at protocol level (not a typo a linter would catch). The bounded model
checker must KILL every one: find a schedule within `KILL_BOUNDS`
where a shared predicate (or a history ghost) goes false, and emit it
as a replayable artifact. `tests/test_verify.py` runs the full kill
matrix; `mcheck.smoke` uses `reterm_whole_suffix` as its canary.

Every mutant names its `mirror` — the sim/step.py site computing the
same clause for the batched engines — because the differential suite
pins step.py/pkernel.py to THIS oracle: a bug class killed here is a
bug class the differential would catch if introduced there instead.

Killing bounds are per-mutant (smallest universe that exposes the
bug): most die at k=2 within a few ticks; quorum-arithmetic bugs that
need a 2-of-3 split die at k=3; the dedup mutant needs the sessions
universe. `expect` names the predicate expected in the counterexample
(checked loosely — any violation kills, the name documents WHY the
mutant is unsafe).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

from raft_tpu import config
from raft_tpu.core import rpc
from raft_tpu.utils import rng
from raft_tpu.core.node import (CANDIDATE, FOLLOWER, LEADER, NO_VOTE, Node,
                                majority_of)
from raft_tpu.verify.mcheck import Bounds


@dataclasses.dataclass(frozen=True)
class Mutant:
    name: str
    node_cls: type
    mirror: str          # the sim/step.py site computing the same clause
    expect: str          # predicate the counterexample should trip
    bounds: Bounds       # smallest universe known to kill it
    doc: str
    # Waypoint drive (mcheck.check's `prefix`): fixed scheduler choices
    # reaching the deep protocol region where the bug is expressible;
    # the BFS fans out exhaustively from there. () = blind search.
    prefix: tuple = ()


def _sched(k: int, *ticks: str) -> tuple:
    """Compact scheduler-trace literal for catalog prefixes. One string
    per tick, space-separated tokens: `xN` node N down, `pN` pulse N's
    election timer, `bSD` block link S->D, `dN` node N disk-full (r20),
    `nN`/`uN`/`sN` propose new/dup/shed on N (sessions universes; shed
    needs Bounds.admission). '' is the quiet tick. These are the shrunk
    counterexample schedules the hunts/hand analysis found, frozen so
    the kill matrix replays them in milliseconds."""
    out = []
    for spec in ticks:
        c = {"alive": [True] * k, "blocked": (), "pulse": (),
             "disk": (), "propose": None}
        for tok in spec.split():
            if tok[0] == "x":
                c["alive"][int(tok[1])] = False
            elif tok[0] == "p":
                c["pulse"] += (int(tok[1]),)
            elif tok[0] == "b":
                c["blocked"] += ((int(tok[1]), int(tok[2])),)
            elif tok[0] == "d":
                c["disk"] += (int(tok[1]),)
            elif tok[0] == "n":
                c["propose"] = (int(tok[1]), "new")
            elif tok[0] == "u":
                c["propose"] = (int(tok[1]), "dup")
            elif tok[0] == "s":
                c["propose"] = (int(tok[1]), "shed")
            else:
                raise ValueError(f"bad sched token {tok!r}")
        c["alive"] = tuple(c["alive"])
        out.append(c)
    return tuple(out)


# -------------------------------------------------- vote-path mutants


class AcceptStaleAppend(Node):
    """_on_ae_req drops the m.term < self.term stale-leader reject: a
    deposed leader's AppendEntries still installs entries and advances
    commit on followers that have moved to a newer term — two leaders
    replicate concurrently into the same logs. (The RV-side analog —
    granting a stale-term vote — is NOT observable in this universe:
    in-flight mail lives exactly one tick, so a stale RequestVote can
    only arrive via same-inbox term-raise reordering, and every such
    path is blocked by the voted_for dedup; the AE-side slip is the
    stale-term-check bug a bounded schedule can actually reach.)"""
    def _on_ae_req(self, m: rpc.AppendEntriesReq):
        if m.term > self.term:
            self._step_down(m.term)
        # BUG: `if m.term < self.term: reject` dropped.
        self._accept_leader(m)
        prev = m.prev_index
        if prev > self.last_index:
            self.transport.send(rpc.AppendEntriesResp(
                rpc.AE_RESP, self.id, m.src, term=self.term,
                success=False, match=self.last_index + 1))
            return
        if prev >= self.snap_index and self.term_at(prev) != m.prev_term:
            ct = self.term_at(prev)
            ci = prev
            while ci - 1 > self.snap_index and self.term_at(ci - 1) == ct:
                ci -= 1
            self.transport.send(rpc.AppendEntriesResp(
                rpc.AE_RESP, self.id, m.src, term=self.term,
                success=False, match=ci))
            return
        j0 = max(0, self.snap_index - prev)
        hi = prev + j0
        for j in range(j0, len(m.entries)):
            idx = prev + 1 + j
            et, ep = m.entries[j]
            if idx <= self.last_index:
                if self.term_at(idx) == et:
                    hi = idx
                    continue
                if self.payload_at(idx) == ep:
                    self.log[idx - self.snap_index - 1] = (et, ep)
                    hi = idx
                    continue
                if idx <= self.commit:
                    break   # surface as divergence, not a harness crash
                del self.log[idx - self.snap_index - 1:]
            if not self._append(et, ep):
                break
            hi = idx
        if m.leader_commit > self.commit:
            self.commit = max(self.commit, min(m.leader_commit, hi))
        self.transport.send(rpc.AppendEntriesResp(
            rpc.AE_RESP, self.id, m.src, term=self.term, success=True,
            match=hi))


class SkipVoteDedup(Node):
    """_on_rv_req skips the voted_for dedup: one follower grants two
    candidates in the same term — double vote."""
    def _on_rv_req(self, m: rpc.RequestVoteReq):
        if m.term > self.term:
            self._step_down(m.term)
        log_ok = (m.last_log_term > self.last_log_term()
                  or (m.last_log_term == self.last_log_term()
                      and m.last_log_index >= self.last_index))
        grant = m.term == self.term and log_ok   # BUG: no voted_for check
        if grant:
            self.voted_for = m.src
            self._reset_election_timer()
        self.transport.send(rpc.RequestVoteResp(
            rpc.RV_RESP, self.id, m.src, term=self.term, granted=grant))


class IndexOnlyLogOk(Node):
    """_on_rv_req compares log recency by index alone, ignoring the
    last log TERM: a long stale-term log outranks a short current-term
    one, electing a leader missing committed entries."""
    def _on_rv_req(self, m: rpc.RequestVoteReq):
        if m.term > self.term:
            self._step_down(m.term)
        log_ok = m.last_log_index >= self.last_index   # BUG: term ignored
        grant = (m.term == self.term
                 and self.voted_for in (NO_VOTE, m.src)
                 and log_ok)
        if grant:
            self.voted_for = m.src
            self._reset_election_timer()
        self.transport.send(rpc.RequestVoteResp(
            rpc.RV_RESP, self.id, m.src, term=self.term, granted=grant))


class CountStaleVoteResp(Node):
    """_on_rv_resp drops the m.term == self.term guard: grants from a
    previous failed candidacy count toward the current one."""
    def _on_rv_resp(self, m: rpc.RequestVoteResp):
        if m.term > self.term:
            self._step_down(m.term)
            return
        if self.role != CANDIDATE or not m.granted:   # BUG: no term check
            return
        self.votes[m.src] = True
        if self._vote_quorum():
            self._become_leader()


class MinorityQuorum(Node):
    """_vote_quorum off-by-one (bit_count // 2, no +1): k // 2 votes
    win an election — two disjoint 'majorities' can coexist."""
    def _vote_quorum(self) -> bool:
        voters, _ = self.current_config()
        granted = sum(1 for p in range(self.cfg.k)
                      if self.votes[p] and (voters >> p) & 1)
        return granted >= majority_of(voters) - 1   # BUG: minority wins


class VolatileTerm(Node):
    """restart() resets the durable term to 0: a crash-recovered voter
    re-campaigns AT a term it already voted in (the fresh election
    bumps its zeroed term back to an old value with voted_for = self),
    so a second leader wins a term that already has one. (The sibling
    slip — dropping only voted_for — is NOT observable in this
    universe: in-flight mail lives exactly one tick, so no same-term
    vote request can arrive after a crash-revive; a fresh candidacy
    always bumps the term. Dropping the term is the restart-durability
    bug a bounded schedule can actually reach.)"""
    def restart(self):
        super().restart()
        self.term = 0   # BUG: durable term reset


# --------------------------------------------------- commit-path mutants


class CommitOffByOne(Node):
    """phase_a reads the replication tally one rank too low
    (majority_of - 2): an index replicated on a minority commits."""
    def phase_a(self):
        if self.role == LEADER:
            voters, _ = self.current_config()
            vals = sorted(
                (self.last_index if p == self.id else self.match_index[p]
                 for p in range(self.cfg.k) if (voters >> p) & 1),
                reverse=True)
            if vals:
                n = vals[max(0, majority_of(voters) - 2)]   # BUG: rank - 1
                if n > self.commit and self.term_at(n) == self.term:
                    self.commit = n
        self._phase_a_tail()

    def _phase_a_tail(self):
        """phase_a after the commit tally, verbatim (reads/reconfig are
        statically off in every mcheck universe, so the removed-leader
        step-down and sched_read completion are dead code here)."""
        while self.applied < self.commit:
            self.applied += 1
            t, p = self.log[self.applied - self.snap_index - 1]
            if self._session_effective(self.applied, p):
                self.digest = rng.digest_update(self.digest, self.applied, p)
            if self.on_apply is not None:
                self.on_apply(self.id, self.applied, t, p)
        if self.commit - self.snap_index >= self.cfg.compact_every:
            self.snap_voters = self.committed_config()
            self.snap_sessions = dict(self.sessions)
            self.snap_term = self.term_at(self.commit)
            self.log = self.log[self.commit - self.snap_index:]
            self.snap_index = self.commit
            self.snap_digest = self.digest


class CommitStaleTerm(CommitOffByOne):
    """phase_a drops the §5.4.2 current-term guard: a prior-term entry
    commits by counting — the Figure 8 scenario."""
    def phase_a(self):
        if self.role == LEADER:
            voters, _ = self.current_config()
            vals = sorted(
                (self.last_index if p == self.id else self.match_index[p]
                 for p in range(self.cfg.k) if (voters >> p) & 1),
                reverse=True)
            if vals:
                n = vals[majority_of(voters) - 1]
                if n > self.commit:   # BUG: term_at(n) == self.term dropped
                    self.commit = n
        self._phase_a_tail()


class AckBeyondSent(Node):
    """_on_ae_resp credits a success ack one entry past what the
    follower actually matched — the classic fencepost between
    match_index (last replicated) and next_index (first to send): the
    commit tally counts an entry the follower does not hold, so the
    leader commits under-replicated entries. (The textbook neighbor —
    counting acks from a STALE term — is not observable in this
    universe: mail lives exactly one tick, a leader's term cannot
    change while it stays leader within that tick, and any AE_RESP is
    a reply to this leader's own current-term AE, so m.term <
    self.term can never reach a standing leader; the fencepost is the
    tally bug a bounded schedule can actually reach.)"""
    def _on_ae_resp(self, m: rpc.AppendEntriesResp):
        if m.term > self.term:
            self._step_down(m.term)
            return
        if self.role != LEADER or m.term != self.term:
            return
        self.ack_time[m.src] = self.now
        if m.success:
            # BUG: m.match + 1 — one past the acked prefix.
            self.match_index[m.src] = max(self.match_index[m.src],
                                          m.match + 1)
            self.next_index[m.src] = self.match_index[m.src] + 1
        else:
            self.next_index[m.src] = max(
                1, min(self.next_index[m.src] - 1, m.match))


class AckWithoutPersist(Node):
    """_on_ae_req acks entries its storage rejected (r20, DESIGN.md
    §19): when `_append` fails — window full OR the disk-full budget
    exhausted — the reply still advances `match` over the entry, so
    the leader's commit tally counts a copy that does not exist. The
    real oracle's NACK rule stops `hi` at the durable prefix (the
    partial ack IS the NACK); this mutant is the classic
    fsync-skipped durability bug, and `commit_durability` kills it:
    the leader commits an index held by fewer than a majority."""
    def _on_ae_req(self, m: rpc.AppendEntriesReq):
        if m.term > self.term:
            self._step_down(m.term)
        if m.term < self.term:
            self.transport.send(rpc.AppendEntriesResp(
                rpc.AE_RESP, self.id, m.src, term=self.term,
                success=False, match=0))
            return
        self._accept_leader(m)
        prev = m.prev_index
        if prev > self.last_index:
            self.transport.send(rpc.AppendEntriesResp(
                rpc.AE_RESP, self.id, m.src, term=self.term,
                success=False, match=self.last_index + 1))
            return
        if prev >= self.snap_index and self.term_at(prev) != m.prev_term:
            ct = self.term_at(prev)
            ci = prev
            while ci - 1 > self.snap_index and self.term_at(ci - 1) == ct:
                ci -= 1
            self.transport.send(rpc.AppendEntriesResp(
                rpc.AE_RESP, self.id, m.src, term=self.term,
                success=False, match=ci))
            return
        j0 = max(0, self.snap_index - prev)
        hi = prev + j0
        for j in range(j0, len(m.entries)):
            idx = prev + 1 + j
            et, ep = m.entries[j]
            if idx <= self.last_index:
                if self.term_at(idx) == et:
                    hi = idx
                    continue
                if self.payload_at(idx) == ep:
                    self.log[idx - self.snap_index - 1] = (et, ep)
                    hi = idx
                    continue
                if idx <= self.commit:
                    break   # surface as divergence, not a harness crash
                del self.log[idx - self.snap_index - 1:]
            if not self._append(et, ep):
                hi = idx   # BUG: acked without persisting
                break
            hi = idx
        if m.leader_commit > self.commit:
            # Clamped to last_index so the window stays structurally
            # traversable; the durability bug is in the inflated ack.
            self.commit = max(self.commit,
                              min(m.leader_commit, hi, self.last_index))
        self.transport.send(rpc.AppendEntriesResp(
            rpc.AE_RESP, self.id, m.src, term=self.term, success=True,
            match=hi))


class CommitPastDurable(CommitOffByOne):
    """phase_a tallies the optimistic SEND pointer (next_index)
    instead of the durable-acked pointer (match_index): entries the
    leader has merely queued for a peer count as replicated, so an
    index commits before any follower durably holds it — the
    send/ack confusion a pipelined replication refactor could
    introduce. commit_durability kills it the tick the leader
    commits its own un-acked append."""
    def phase_a(self):
        if self.role == LEADER:
            voters, _ = self.current_config()
            vals = sorted(
                (self.last_index if p == self.id else self.next_index[p]
                 for p in range(self.cfg.k) if (voters >> p) & 1),
                reverse=True)   # BUG: next_index, not match_index
            if vals:
                n = vals[majority_of(voters) - 1]
                n = min(n, self.last_index)
                if n > self.commit and self.term_at(n) == self.term:
                    self.commit = n
        self._phase_a_tail()


# ------------------------------------------------------ log-path mutants


class SkipPrevTermCheck(Node):
    """_on_ae_req skips the (prev_index, prev_term) consistency check:
    entries append after a hole/conflict — Log Matching breaks."""
    def _on_ae_req(self, m: rpc.AppendEntriesReq):
        if m.term > self.term:
            self._step_down(m.term)
        if m.term < self.term:
            self.transport.send(rpc.AppendEntriesResp(
                rpc.AE_RESP, self.id, m.src, term=self.term,
                success=False, match=0))
            return
        self._accept_leader(m)
        prev = m.prev_index
        if prev > self.last_index:
            self.transport.send(rpc.AppendEntriesResp(
                rpc.AE_RESP, self.id, m.src, term=self.term,
                success=False, match=self.last_index + 1))
            return
        # BUG: term_at(prev) != m.prev_term conflict check dropped — a
        # divergent suffix is extended instead of truncated.
        self._install_entries(m, prev)

    def _install_entries(self, m, prev):
        j0 = max(0, self.snap_index - prev)
        hi = prev + j0
        for j in range(j0, len(m.entries)):
            idx = prev + 1 + j
            et, ep = m.entries[j]
            if idx <= self.last_index:
                if self.term_at(idx) == et:
                    hi = idx
                    continue
                if self.payload_at(idx) == ep:
                    self.log[idx - self.snap_index - 1] = (et, ep)
                    hi = idx
                    continue
                if idx <= self.commit:
                    break   # keep the oracle's guard as flow, not assert
                del self.log[idx - self.snap_index - 1:]
            if not self._append(et, ep):
                break
            hi = idx
        if m.leader_commit > self.commit:
            self.commit = max(self.commit, min(m.leader_commit, hi))
        self.transport.send(rpc.AppendEntriesResp(
            rpc.AE_RESP, self.id, m.src, term=self.term, success=True,
            match=hi))


class CommitPastMatch(Node):
    """_on_ae_req advances commit to leader_commit without clamping to
    `hi`: a follower commits indices its own suffix never matched."""
    def _on_ae_req(self, m: rpc.AppendEntriesReq):
        if m.term > self.term:
            self._step_down(m.term)
        if m.term < self.term:
            self.transport.send(rpc.AppendEntriesResp(
                rpc.AE_RESP, self.id, m.src, term=self.term,
                success=False, match=0))
            return
        self._accept_leader(m)
        prev = m.prev_index
        if prev > self.last_index:
            self.transport.send(rpc.AppendEntriesResp(
                rpc.AE_RESP, self.id, m.src, term=self.term,
                success=False, match=self.last_index + 1))
            return
        if prev >= self.snap_index and self.term_at(prev) != m.prev_term:
            ct = self.term_at(prev)
            ci = prev
            while ci - 1 > self.snap_index and self.term_at(ci - 1) == ct:
                ci -= 1
            self.transport.send(rpc.AppendEntriesResp(
                rpc.AE_RESP, self.id, m.src, term=self.term,
                success=False, match=ci))
            return
        j0 = max(0, self.snap_index - prev)
        hi = prev + j0
        for j in range(j0, len(m.entries)):
            idx = prev + 1 + j
            et, ep = m.entries[j]
            if idx <= self.last_index:
                if self.term_at(idx) == et:
                    hi = idx
                    continue
                if self.payload_at(idx) == ep:
                    self.log[idx - self.snap_index - 1] = (et, ep)
                    hi = idx
                    continue
                if idx <= self.commit:
                    break
                del self.log[idx - self.snap_index - 1:]
            if not self._append(et, ep):
                break
            hi = idx
        if m.leader_commit > self.commit:
            # BUG: min(m.leader_commit, hi) dropped — commit outruns the
            # verified-matching prefix (clamped to last_index so the
            # window stays structurally valid; the SAFETY bug remains).
            self.commit = max(self.commit,
                              min(m.leader_commit, self.last_index))
        self.transport.send(rpc.AppendEntriesResp(
            rpc.AE_RESP, self.id, m.src, term=self.term, success=True,
            match=hi))


class TruncateCommitted(Node):
    """_on_ae_req truncates on a TERM conflict without the payload
    re-term escape or the committed-entry guard: an in-place takeover
    re-proposal wipes a committed suffix instead of re-terming it.
    (commit/applied are rewound alongside so the harness state stays
    structurally traversable — the durability bug remains: a wiped
    committed entry re-applies, double-folding the digest against the
    reference, or re-commits with a different payload.)"""
    def _on_ae_req(self, m: rpc.AppendEntriesReq):
        if m.term > self.term:
            self._step_down(m.term)
        if m.term < self.term:
            self.transport.send(rpc.AppendEntriesResp(
                rpc.AE_RESP, self.id, m.src, term=self.term,
                success=False, match=0))
            return
        self._accept_leader(m)
        prev = m.prev_index
        if prev > self.last_index:
            self.transport.send(rpc.AppendEntriesResp(
                rpc.AE_RESP, self.id, m.src, term=self.term,
                success=False, match=self.last_index + 1))
            return
        if prev >= self.snap_index and self.term_at(prev) != m.prev_term:
            ct = self.term_at(prev)
            ci = prev
            while ci - 1 > self.snap_index and self.term_at(ci - 1) == ct:
                ci -= 1
            self.transport.send(rpc.AppendEntriesResp(
                rpc.AE_RESP, self.id, m.src, term=self.term,
                success=False, match=ci))
            return
        j0 = max(0, self.snap_index - prev)
        hi = prev + j0
        for j in range(j0, len(m.entries)):
            idx = prev + 1 + j
            et, ep = m.entries[j]
            if idx <= self.last_index:
                if self.term_at(idx) == et:
                    hi = idx
                    continue
                # BUG: the payload-match re-term escape and the
                # committed-entry guard are both gone — ANY term
                # conflict truncates, committed entries included.
                del self.log[idx - self.snap_index - 1:]
                self.commit = min(self.commit, self.last_index)
                self.applied = min(self.applied, self.commit)
            if not self._append(et, ep):
                break
            hi = idx
        if m.leader_commit > self.commit:
            self.commit = max(self.commit, min(m.leader_commit, hi))
        self.transport.send(rpc.AppendEntriesResp(
            rpc.AE_RESP, self.id, m.src, term=self.term, success=True,
            match=hi))


class RetermWholeSuffix(Node):
    """_become_leader re-terms the WHOLE uncommitted suffix instead of
    only the top entry — the documented round-1 takeover bug
    (node.py §2a comment): current-term entries appear BELOW the
    committed frontier of OTHER nodes, because the re-term range is
    keyed on the new leader's LOCAL commit, which can trail the global
    frontier. The kill is a recency-poisoning chain the sticky hunts
    never found: A@1 commits idx 1-2 with B's acks but B never learns
    (leader_commit blocked); B wins term 2 with commit=0 and re-terms
    the GLOBALLY COMMITTED idx 1 to term 2; a dark node C catching up
    gets just [x1@2] — whose last-log term now BEATS A's genuine
    4-entry term-1 log, so C wins term 3 lacking committed idx 2 and
    replicates over it (state_machine_safety). The top-only oracle
    hands C [x1@1] and A's log-recency vote denies the takeover."""
    def _become_leader(self):
        self.role = LEADER
        self.leader_id = self.id
        self.next_index = [self.last_index + 1] * self.cfg.k
        self.match_index = [0] * self.cfg.k
        self._drop_client_state()
        self.heartbeat_elapsed = self.cfg.heartbeat_every
        # BUG: the round-1 variant — every uncommitted entry re-termed.
        for idx in range(self.commit + 1, self.last_index + 1):
            pos = idx - self.snap_index - 1
            self.log[pos] = (self.term, self.log[pos][1])


class AlwaysEffective(Node):
    """_session_effective drops the duplicate-seq skip: a retried
    (sid, seq) folds into the digest AGAIN on every node — broken
    identically everywhere, so cross-node digest agreement still
    holds; only the reference-digest ghost (an independent recompute
    of the exactly-once fold) catches it."""
    def _session_effective(self, index: int, payload: int) -> bool:
        if not self.cfg.sessions:
            return True
        if payload & config.CONFIG_FLAG or not payload & config.SESSION_FLAG:
            return True
        sid = (payload >> config.SESSION_SID_SHIFT) & config.SESSION_SID_MASK
        if sid == config.SESSION_SID_MASK:          # REGISTER
            new_sid = index % config.SESSION_SID_MASK
            if new_sid in self.sessions:
                return False
            self.sessions[new_sid] = -1
            return True
        seq = (payload >> config.SESSION_SEQ_SHIFT) & config.SESSION_SEQ_MASK
        if sid not in self.sessions:
            return False
        # BUG: `seq <= self.sessions[sid]` duplicate skip dropped.
        self.sessions[sid] = max(self.sessions[sid], seq)
        return True


class ShedThenApply(Node):
    """admit_and_propose ignores the shed verdict (r20, DESIGN.md §19):
    an arrival the admission queue rejected — whose client got a
    DEFINITIVE reject and will re-issue under a fresh seq, never retry
    this one — is proposed anyway. The command commits and applies, so
    a node's dedup table runs ahead of the issued frontier and
    `client_safety`'s no-phantom-apply clause kills it. This is the
    bug the definitive-reject contract exists to exclude: shed must
    mean NOT IN THE LOG, or exactly-once accounting is fiction."""
    def admit_and_propose(self, sid: int, seq: int, val: int, shed: bool):
        # BUG: `if shed: return None` dropped — the reject is ignored.
        return self.propose_seq(sid, seq, val)


# ------------------------------------------------------------ the catalog


def _b(**kw) -> Bounds:
    base = dict(k=2, ticks=6, max_states=40_000, max_term=3, max_index=4,
                max_dead=1, max_pulses=1)
    base.update(kw)
    return Bounds(**base)


# Every entry is a VERIFIED kill: `check(bounds, node_cls, prefix)`
# trips `expect` on the final tick's exhaustive fan-out, and
# `check(bounds, Node, prefix)` — the unmutated oracle on the same
# waypoint drive — completes clean. Prefixes are the shrunk schedules
# the sticky hunts found (or hand-derived choreography where the random
# walk structurally can't reach the bug — see each docstring); bounds
# carry the trace's actual term/index envelope, so replay never exits
# via in_bounds() early.
MUTANTS: Tuple[Mutant, ...] = (
    Mutant("accept_stale_append", AcceptStaleAppend,
           "sim/step.py phase_d AE_REQ stale-term reject clause",
           # r20: commit_durability (the stronger commit-rule clause)
           # catches the deposed leader's divergent install SHALLOWER
           # than leader_completeness does — BFS reports the first
           # violation, so the expectation follows the new frontier.
           "commit_durability",
           _b(k=3, ticks=14, log_cap=4, compact_every=2, max_index=5,
              max_dead=0, adversary="isolate"),
           "deposed leader's AE still installs entries",
           _sched(3, "p0", "", "", "p2",
                  "b01 b02 b10 b20", "b01 b02 b10 b20",
                  "b01 b02 b10 b20", "b01 b02 b10 b20",
                  "b01 b02 b10 b20 b21", "b01 b02 b10 b20 b21",
                  "b02 b10 b20 b21", "b02 b20 b21 p0", "b02 b20 b21")),
    Mutant("skip_vote_dedup", SkipVoteDedup,
           "sim/step.py phase_d RV_REQ grant clause (voted_for dedup)",
           "election_safety_history", _b(max_pulses=2),
           "voted_for dedup dropped — double vote per term"),
    Mutant("index_only_log_ok", IndexOnlyLogOk,
           "sim/step.py phase_d RV_REQ log-recency clause",
           "state_machine_safety",
           _b(k=3, ticks=25, max_term=4, max_pulses=2),
           "log recency by index alone — stale log electable",
           _sched(3, "", "p0", "", "", "p2", "", "", "p0",
                  "b01 b02", "b01 b02", "b01 b21", "", "", "", "", "",
                  "", "", "p0", "", "", "", "", "")),
    Mutant("count_stale_vote_resp", CountStaleVoteResp,
           "sim/step.py phase_d RV_RESP tally guard",
           "log_matching", _b(k=3, ticks=14, max_term=4, max_pulses=2),
           "grants from a dead candidacy tallied",
           _sched(3, "", "", "", "p2", "", "", "", "", "p1", "p0",
                  "p0", "b01 b02 p2", "b01 b02 b20")),
    Mutant("minority_quorum", MinorityQuorum,
           "sim/step.py vote-quorum popcount threshold",
           "election_safety_history", _b(max_pulses=2),
           "k//2 votes win — disjoint majorities"),
    Mutant("volatile_term", VolatileTerm,
           "sim/step.py restart mask (term is durable)",
           "election_safety_history",
           _b(k=3, ticks=22, max_index=10, adversary="isolate"),
           "crash drops the durable term — re-win a used term",
           _sched(3, "p0", "", "", "", "", "", "", "", "", "", "", "",
                  "", "", "p2", "x2", "x0 b02 b12", "", "x1", "p2",
                  "")),
    Mutant("commit_off_by_one", CommitOffByOne,
           "sim/step.py phase_a sorted-match commit rank",
           "state_machine_safety", _b(k=3, ticks=6, max_pulses=2),
           "minority replication commits",
           _sched(3, "p1", "", "", "p2", "")),
    Mutant("commit_stale_term", CommitStaleTerm,
           "sim/step.py phase_a §5.4.2 current-term commit guard",
           "state_machine_safety",
           _b(k=3, ticks=23, max_states=120_000, max_term=5,
              max_entries=1, max_pulses=2),
           "prior-term entry commits by counting (Figure 8)",
           _sched(3, "", "", "p0", "b01 b21", "", "p2", "", "p1", "",
                  "", "p0", "b02 b12", "", "b02 b12", "", "", "p2",
                  "", "", "", "", "")),
    Mutant("ack_beyond_sent", AckBeyondSent,
           "sim/step.py phase_d AE_RESP match-index credit",
           "state_machine_safety", _b(k=3, ticks=10),
           "success acks credit one entry past the matched prefix",
           _sched(3, "p0", "", "", "", "", "b01 b02 p1", "", "", "")),
    Mutant("skip_prev_term_check", SkipPrevTermCheck,
           "sim/step.py phase_d AE_REQ prev-term conflict clause",
           "state_machine_safety",
           _b(k=3, ticks=17, max_term=4, max_index=5, max_pulses=2),
           "append after divergence — Log Matching breaks",
           _sched(3, "", "", "p0", "", "", "", "", "p2", "", "p0",
                  "p2", "", "", "", "", "")),
    Mutant("commit_past_match", CommitPastMatch,
           "sim/step.py phase_d AE_REQ commit clamp (min with hi)",
           "state_machine_safety",
           _b(k=3, ticks=16, log_cap=7, compact_every=5, max_entries=1,
              max_term=4, max_index=7),
           "follower commit outruns its matched prefix",
           _sched(3, "p1", "", "", "", "", "p2",
                  "b10 b12 b01 b21", "", "b10 b12 b01 b21", "", "",
                  "b10 b12 b01 b21", "b10 b12 b01 b21",
                  "b10 b12 b01 b21", "")),
    Mutant("truncate_committed", TruncateCommitted,
           "sim/step.py phase_d AE_REQ committed-prefix truncate guard",
           "state_machine_digest",
           _b(k=3, ticks=20, log_cap=4, compact_every=2, max_index=5,
              max_pulses=2),
           "conflict resolution deletes below the commit frontier",
           _sched(3, "", "", "", "", "", "", "", "", "", "p0", "", "",
                  "", "x1", "b01 b02 p2", "", "", "", "")),
    Mutant("reterm_whole_suffix", RetermWholeSuffix,
           "sim/step.py become-leader takeover re-term (top entry only)",
           "state_machine_safety",
           _b(k=3, ticks=19, log_cap=4, compact_every=2, max_entries=1,
              max_index=5, max_dead=0),
           "whole-suffix re-term — the documented round-1 bug",
           _sched(3, "p0", "", "", "", "b02 b20", "b02 b20", "b02 b20",
                  "b02 b20 b01", "b02 b20 b01 p1", "", "", "", "", "",
                  "p2", "", "", "")),
    Mutant("ack_without_persist", AckWithoutPersist,
           "sim/step.py phase_d AE_REQ entry-walk room clause (~df fold)",
           "commit_durability",
           _b(ticks=6, max_dead=0, max_disk=1, log_cap=4,
              compact_every=2, max_index=5),
           "entries storage rejected are acked — fsync skipped",
           _sched(2, "p0", "", "", "", "d1")),
    Mutant("commit_past_durable", CommitPastDurable,
           "sim/step.py phase_a commit tally (match_index, not next_index)",
           "commit_durability",
           _b(ticks=3, max_dead=0, log_cap=4, compact_every=2,
              max_index=5),
           "send pointer tallied as replicated — commit precedes acks",
           _sched(2, "p0", "")),
    Mutant("shed_then_apply", ShedThenApply,
           "clients/workload.py admission shed gate (definitive reject)",
           "client_safety",
           _b(sessions=True, admission=True, ticks=6, max_dead=0),
           "shed arrival proposed anyway — reject was not definitive",
           _sched(2, "p0", "", "s0", "", "")),
    Mutant("always_effective", AlwaysEffective,
           "sim/step.py session dedup fold (seq <= table entry skip)",
           "state_machine_digest",
           _b(sessions=True, ticks=14, max_pulses=2),
           "duplicate retry re-applies — exactly-once breaks",
           _sched(2, "", "", "", "", "", "p0", "", "", "", "n0", "u0",
                  "", "")),
)


def by_name(name: str) -> Mutant:
    for m in MUTANTS:
        if m.name == name:
            return m
    raise KeyError(name)
