"""Bounded exhaustive model checker for the tick semantics
(DESIGN.md §17).

Enumerates ALL reachable states of the REAL CPU oracle step
(`core/node.py` `Node` objects on a `core/transport.Transport`, driven
by a tick loop mirroring `Cluster.tick`) for small-scope universes —
k in {2, 3}, log cap <= 3, bounded term/index — under every delivery,
drop, crash, and timeout schedule within `Bounds`, via BFS over
canonicalized states with node-permutation symmetry reduction. At
every state it evaluates the SAME predicates the runtime fold spot-
checks (`verify/invariants.py`, shared with `sim/check.py`) plus two
history-ghost invariants a point-in-time predicate cannot see
(per-term leader uniqueness across time; commit identity of every
(index, payload) ever applied). A violation emits a nemesis-format
reproducer artifact whose explicit schedule replays deterministically
(`replay`), and which `scripts/nemesis_search.py --replay` accepts.

Soundness of the abstractions (each an OVER-approximation — the
checker explores a superset of the behaviors the hashed production
schedules can produce, so "clean here" implies "clean there"):

- Adversarial timers: every node's election deadline is pinned
  unreachably high and the SCHEDULER chooses which nodes time out each
  tick (a pulse sets `election_elapsed = deadline - 1` so phase T
  fires). Any hash-drawn timeout pattern is one pulse schedule among
  those enumerated; `rng_draws`/`deadline`/`election_elapsed` leave
  the canonical key. This is also what makes node-permutation symmetry
  exact: the only id-dependent inputs (the per-id deadline hashes) are
  replaced by the adversary.
- Adversarial delivery: per tick the scheduler picks any subset of the
  links currently carrying in-flight mail to BLOCK (via the
  `Transport.link_filter` seam — dead-destination loss stays in the
  real `deliver`). Hash-driven drops/partitions/nemesis clauses are
  link subsets, so all are covered.
- Adversarial crashes: any alive-vector per tick with at most
  `max_dead` nodes down (restart-on-revive through the real
  `Node.restart`).
- Adversarial storage pressure (r20): with `max_disk > 0` the
  scheduler forces any subset of at most `max_disk` nodes'
  persistence budgets empty for the tick (`Node.disk_override` — the
  seam `_append` consults before the hashed disk_full_follower
  schedule), so every hash-drawn disk-full pattern is one override
  schedule among those enumerated, and `commit_durability` is checked
  against the full adversarial lossy-persistence space.
- Time-homogeneous scope: reconfig/reads/transfer/nemesis are off and
  fault hashes are scheduler-replaced, so transitions do not depend on
  the absolute tick — state dedup across depths is sound, and
  `ack_time`/read state (which only feed the disabled machinery) leave
  the key. `leader_elapsed` is capped at `election_min` in the key
  (the PreVote lease only tests `>= election_min`).
- The batched engines are NOT re-modeled: sim/step.py and the Pallas
  kernel are pinned bit-identical to this oracle by the differential
  suite, so the verdict transfers to all three engines (DESIGN.md §17
  spells out the argument and its limits).

The exactly-once client universe (`Bounds.sessions=True`) drives
`propose_seq` adversarially: each tick the scheduler may hand any
self-believed leader a fresh command or a duplicate retry of the last
issued seq — the dual-leader double-append scenarios the r09 dedup
exists for — and `client_safety` is checked against the ghost issued
frontier. With `Bounds.admission=True` it may also hand a leader a
SHED arrival (`Node.admit_and_propose` with shed=True, r20): the
definitive-reject contract means the seq is never issued, so a node
that applies it anyway trips `client_safety`'s issued-frontier clause.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
from typing import Callable, List, Optional

import numpy as np

from raft_tpu import config as cfgmod
from raft_tpu.config import RaftConfig
from raft_tpu.core import rpc
from raft_tpu.core.node import LEADER, NO_VOTE, Node
from raft_tpu.core.transport import Transport
from raft_tpu.utils import rng
from raft_tpu.verify import invariants as inv

#: Unreachably-high election deadline: the adversary owns timeouts.
HUGE_DEADLINE = 1 << 30

ARTIFACT_KIND = "mcheck-reproducer"
ARTIFACT_ENGINES = "oracle-mcheck"

#: The r19 narrow-native dtype map over the checker's view names
#: (DESIGN.md §18) — the mcheck twin of sim/state.narrow_spec's
#: nodes.* entries, used by `predicate_report(narrow=True)` to prove
#: the shared predicates are width-robust over the exhaustive small
#: scope. Payload/digest lanes stay wide, exactly as the spec keeps
#: them resident-wide.
_NARROW_VIEW_DTYPES = {"role": np.int8, "term": np.uint16,
                       "commit": np.uint16, "applied": np.uint16,
                       "snap_index": np.uint16, "last_index": np.uint16,
                       "log_term": np.uint16}


@dataclasses.dataclass(frozen=True)
class Bounds:
    """The small-scope universe: every knob both caps the state space
    and names exactly what the verdict covers."""
    k: int = 2                # replicas (2 or 3; symmetry reduces k!)
    log_cap: int = 3          # ring window (>= compact_every + cmds + 1)
    ticks: int = 6            # schedule depth (BFS levels)
    max_states: int = 50_000  # canonical-state budget (complete=False past it)
    max_term: int = 3         # prune states whose any term exceeds this
    max_index: int = 4        # prune states whose any last_index exceeds this
    max_dead: int = 1         # simultaneously-crashed cap per tick
    max_pulses: int = 1       # nodes the timeout adversary fires per tick
    max_disk: int = 0         # simultaneously disk-full cap per tick (r20)
    sessions: bool = False    # exactly-once client universe (cmds off)
    admission: bool = False   # shed arrivals in the propose menu (r20)
    prevote: bool = False
    # compact_every=1 snapshots every committed entry immediately (the
    # smallest window state space). Some bug classes live in the gap
    # between commit and compaction — e.g. truncating a committed entry
    # still in the window — and need compact_every >= 2 (with log_cap
    # respecting cfg's `log_cap >= compact_every + cmds + 1` floor).
    compact_every: int = 1
    max_entries: int = 2      # cfg.max_entries_per_msg (1 = one-entry AEs)
    # Narrow the delivery adversary from arbitrary per-link subsets
    # ("links": 2^active_links options) to directional single-node
    # isolation ("isolate": none, or one node's inbound / outbound /
    # both links cut). Kill runs use "isolate" to tame the branch
    # factor — any schedule found is still a real schedule, so a kill
    # stands; CLEAN exhaustive runs keep the full per-link adversary
    # (asymmetric loss included).
    adversary: str = "links"


def bounds_config(b: Bounds) -> RaftConfig:
    """The RaftConfig of a small-scope universe. Faults are OFF — the
    scheduler owns them — and reconfig/reads/transfer are outside the
    modeled scope (documented above)."""
    return RaftConfig(
        seed=0, k=b.k, log_cap=b.log_cap,
        max_entries_per_msg=min(b.max_entries, b.log_cap),
        heartbeat_every=1, election_min=3, election_range=1,
        compact_every=b.compact_every,
        cmds_per_tick=0 if b.sessions else 1,
        sessions=b.sessions,
        client_rate=0.5 if b.sessions else 0.0,  # pre-registers slot 0
        client_slots=1 if b.sessions else 4,
        prevote=b.prevote)


# ------------------------------------------------------------ the universe


def _adversarial_reset(n: Node):
    """Instance-patched `_reset_election_timer`: no hash draw, no
    reachable deadline — timeouts happen only when pulsed."""
    n.election_elapsed = 0
    n.deadline = HUGE_DEADLINE
    n.rng_draws += 1


class Universe:
    """k real `Node`s + a real `Transport` under scheduler control,
    with freeze/restore so BFS can fan out from any state."""

    def __init__(self, bounds: Bounds, node_cls=Node,
                 narrow: bool = False):
        self.bounds = bounds
        # narrow=True evaluates every predicate on narrow-native views
        # (_NARROW_VIEW_DTYPES) — the r19 kill-matrix re-run mode: a
        # mutant must die, and the clean oracle must survive, at BOTH
        # widths or the _signed lifts are wrong.
        self.narrow = narrow
        self.cfg = bounds_config(bounds)
        self.transport = Transport(self.cfg, 0)
        self.nodes = [node_cls(self.cfg, 0, i, self.transport,
                               on_apply=self._on_apply)
                      for i in range(self.cfg.k)]
        for n in self.nodes:
            n._reset_election_timer = (lambda n=n: _adversarial_reset(n))
            n.deadline = HUGE_DEADLINE
        self.alive_prev = [True] * self.cfg.k
        # History ghosts (part of the frozen state): term -> leader id,
        # applied index -> payload, exactly-once issued frontier.
        self.ghost_leaders: dict = {}
        self.ghost_committed: dict = {}
        self.issued = -1
        self._sm_violation = False
        # Reference session table at index 0 (clients_u32 pre-registers
        # the slots) — seed of the reference-digest recompute.
        self._initial_sessions = dict(self.nodes[0].snap_sessions)

    def _on_apply(self, node_id: int, index: int, term: int, payload: int):
        """State-machine safety ghost (cluster._on_apply's commit-
        identity check): every apply of index i must carry the payload
        the first apply of i carried, on every node, forever."""
        prev = self.ghost_committed.setdefault(index, payload)
        if prev != payload:
            self._sm_violation = True

    # -------------------------------------------------- freeze / restore

    def freeze(self) -> tuple:
        b, cfg = self.bounds, self.cfg
        nodes = []
        for n in self.nodes:
            nodes.append((
                n.term, n.voted_for, tuple(n.log), n.snap_index,
                n.snap_term, n.snap_digest, n.snap_voters,
                tuple(sorted(n.snap_sessions.items())),
                n.role, n.leader_id, n.commit, n.applied, n.digest,
                tuple(sorted(n.sessions.items())),
                tuple(n.votes), tuple(n.next_index), tuple(n.match_index),
                n.heartbeat_elapsed,
                min(n.leader_elapsed, cfg.election_min) if b.prevote else 0,
            ))
        msgs = tuple(sorted(dataclasses.astuple(m)
                            for m in self.transport._outbox))
        return (tuple(nodes), msgs, tuple(self.alive_prev),
                tuple(sorted(self.ghost_leaders.items())),
                tuple(sorted(self.ghost_committed.items())),
                self.issued)

    def restore(self, raw: tuple):
        nodes, msgs, alive_prev, gl, gc, issued = raw
        for n, s in zip(self.nodes, nodes):
            (n.term, n.voted_for, log, n.snap_index, n.snap_term,
             n.snap_digest, n.snap_voters, snap_sessions, n.role,
             n.leader_id, n.commit, n.applied, n.digest, sessions,
             votes, next_index, match_index, n.heartbeat_elapsed,
             n.leader_elapsed) = s
            n.log = list(log)
            n.snap_sessions = dict(snap_sessions)
            n.sessions = dict(sessions)
            n.votes = list(votes)
            n.next_index = list(next_index)
            n.match_index = list(match_index)
            n.election_elapsed = 0
            n.deadline = HUGE_DEADLINE
            n.ack_time = [-1] * self.cfg.k
            n.pending_reads = {}
            n.sched_read = None
        self.transport._outbox = [_msg_from_tuple(m) for m in msgs]
        self.alive_prev = list(alive_prev)
        self.ghost_leaders = dict(gl)
        self.ghost_committed = dict(gc)
        self.issued = issued
        self._sm_violation = False

    # ------------------------------------------------------- one tick

    def tick(self, t: int, choice: dict) -> List[str]:
        """Run ONE tick under `choice` (mirrors Cluster.tick with the
        scheduler owning alive/links/timeouts/proposes); returns the
        violated predicate names (empty = safe)."""
        cfg = self.cfg
        alive_now = list(choice["alive"])
        blocked = {tuple(l) for l in choice["blocked"]}
        disk = set(choice.get("disk", ()))
        for i, n in enumerate(self.nodes):
            n.now = t
            # Adversarial storage pressure: the override seam every
            # `_append` consults (set for ALL nodes every tick, so no
            # stale override survives a restore).
            n.disk_override = i in disk
        for i, n in enumerate(self.nodes):
            if alive_now[i] and not self.alive_prev[i]:
                n.restart()
        self.transport.link_filter = (
            lambda tick, s, d: (s, d) not in blocked)
        inboxes = self.transport.deliver(t, alive_now)
        for i, n in enumerate(self.nodes):
            if alive_now[i]:
                n.phase_d(inboxes[i])
        for i, n in enumerate(self.nodes):
            if alive_now[i]:
                if i in choice["pulse"]:
                    n.election_elapsed = n.deadline - 1
                n.phase_t()
                n.election_elapsed = 0   # excluded from the key; keep flat
        # Adversarial client (sessions universe): a fresh command or a
        # duplicate retry lands on a self-believed leader at phase C's
        # position — the real client seam (node.phase_c appends client
        # payloads after phase D/T, so a leader deposed THIS tick no
        # longer appends, while a not-yet-informed dual leader does).
        prop = choice.get("propose")
        if prop is not None:
            i, kind = prop
            n = self.nodes[i]
            # "new"/"shed" arrive with the next unissued seq; "dup"
            # retries the last issued one. Routed through the r20
            # admission seam: a shed arrival is a definitive reject, so
            # `issued` NEVER advances for it (only an accepted "new"
            # does) — a node that applies a shed command runs ahead of
            # the ghost frontier and client_safety kills it.
            seq = self.issued if kind == "dup" else self.issued + 1
            if seq >= 0 and n.role == LEADER and alive_now[i]:
                r = n.admit_and_propose(0, seq, seq, shed=(kind == "shed"))
                if r is not None and kind == "new":
                    self.issued = seq
        for i, n in enumerate(self.nodes):
            if alive_now[i]:
                n.phase_c(None)
        for i, n in enumerate(self.nodes):
            if alive_now[i]:
                n.phase_a()
        self.alive_prev = alive_now
        # History ghosts.
        for n in self.nodes:
            if n.role == LEADER:
                if self.ghost_leaders.setdefault(n.term, n.id) != n.id:
                    return ["election_safety_history"]
        if self._sm_violation:
            return ["state_machine_safety"]
        if not self._digests_match_reference():
            return ["state_machine_digest"]
        return self.violations()

    def _digests_match_reference(self) -> bool:
        """Reference-semantics ghost: every node's digest must equal the
        fold of the committed payload sequence (ghost_committed, which
        state-machine safety pins to one payload per index) through the
        REFERENCE exactly-once filter, up to that node's applied point.
        Catches bugs the cross-node predicates cannot: a dedup filter
        broken IDENTICALLY on every node double-applies everywhere, so
        digests still agree with each other — only a recompute against
        independent reference semantics notices."""
        for n in self.nodes:
            if n.digest != self._reference_digest(n.applied):
                return False
        return True

    def _reference_digest(self, upto: int) -> int:
        d = 0
        table = dict(self._initial_sessions)
        for i in range(1, upto + 1):
            p = self.ghost_committed[i]
            if self._ref_effective(table, i, p):
                d = rng.digest_update(d, i, p)
        return d

    def _ref_effective(self, table: dict, index: int, payload: int) -> bool:
        """`Node._session_effective` re-derived over a local table — an
        independent transcription of the spec, NOT a call into the
        (possibly mutated) node under test."""
        if not self.cfg.sessions:
            return True
        if (payload & cfgmod.CONFIG_FLAG
                or not payload & cfgmod.SESSION_FLAG):
            return True
        sid = ((payload >> cfgmod.SESSION_SID_SHIFT)
               & cfgmod.SESSION_SID_MASK)
        if sid == cfgmod.SESSION_SID_MASK:          # REGISTER
            new_sid = index % cfgmod.SESSION_SID_MASK
            if new_sid in table:
                return False
            table[new_sid] = -1
            return True
        seq = ((payload >> cfgmod.SESSION_SEQ_SHIFT)
               & cfgmod.SESSION_SEQ_MASK)
        if sid not in table or seq <= table[sid]:
            return False
        table[sid] = seq
        return True

    # --------------------------------------------------- shared predicates

    def views(self):
        """numpy `[1, K]` / `[1, K, L]` views of the oracle state, built
        by the ring slot rule ((i-1) % L) — the exact leaf layout the
        batched State carries, so the SHARED predicates see the oracle
        through the same lens the runtime fold sees the engines."""
        cfg = self.cfg
        k, L = cfg.k, cfg.log_cap
        f = lambda attr: np.array([[getattr(n, attr) for n in self.nodes]])
        v = {name: f(name) for name in
             ("role", "term", "commit", "applied", "digest", "snap_index")}
        v["last_index"] = np.array([[n.last_index for n in self.nodes]])
        lt = np.zeros((1, k, L), np.int64)
        lp = np.zeros((1, k, L), np.int64)
        for i, n in enumerate(self.nodes):
            for idx in range(n.snap_index + 1, n.last_index + 1):
                et, ep = n.log[idx - n.snap_index - 1]
                lt[0, i, (idx - 1) % L] = et
                lp[0, i, (idx - 1) % L] = ep
        v["log_term"], v["log_payload"] = lt, lp
        return v

    def predicate_report(self, narrow: bool = False) -> dict:
        """name -> bool: the verify/invariants predicates (the clause
        registry sim/check.py folds, plus log_matching which the
        runtime approximates via digest agreement) on this state.

        `narrow=True` evaluates the SAME predicates on views cast to
        the r19 narrow-native dtypes (sim/state.narrow_spec's map —
        u16 terms/indices, i8 roles, i16 session tables; DESIGN.md
        §18): at bounded-model scope every value fits, so the two
        reports must be identical — `narrow_agreement_problems` walks
        the small universe asserting exactly that, which is how the
        width-robustness of verify/invariants (its `_signed` lifts) is
        proven against the exhaustive state space rather than one
        hand-picked example."""
        cfg, v = self.cfg, self.views()
        if narrow:
            v = {name: a.astype(_NARROW_VIEW_DTYPES.get(name, a.dtype))
                 for name, a in v.items()}
        rep = {
            "election_safety": inv.election_safety(v["role"], v["term"]),
            "digest_agreement": inv.digest_agreement(v["applied"],
                                                     v["digest"]),
            "window_bounds": inv.window_bounds(
                v["applied"], v["commit"], v["snap_index"],
                v["last_index"], cfg.log_cap),
            "log_matching": inv.log_matching(
                v["last_index"], v["snap_index"], v["log_term"],
                v["log_payload"], cfg.log_cap),
            "leader_completeness": inv.leader_completeness(
                v["role"], v["term"], v["commit"], v["last_index"],
                v["snap_index"], v["log_payload"], cfg.log_cap),
            # Checker-side like log_matching (not in the runtime fold):
            # the commit rule vs lossy persistence (r20) — every
            # committed index still in view is held by a k-majority.
            "commit_durability": inv.commit_durability(
                v["commit"], v["last_index"], v["snap_index"],
                v["log_payload"], cfg.log_cap),
        }
        if self.bounds.sessions:
            table = np.array([[[n.sessions.get(0, -1)]
                               for n in self.nodes]])      # [1, K, 1]
            done = np.array([[self.issued]])               # [1, 1]
            if narrow:
                # i16 both: the spec's table dtype, and — for `done` —
                # the sign-preserving width, because the mcheck frontier
                # uses a -1 "nothing issued" sentinel the resident u16
                # lane never stores (ClientState.done is a count).
                table, done = table.astype(np.int16), done.astype(np.int16)
            rep["client_safety"] = inv.client_safety(
                v["applied"], table, done)
        return {name: bool(np.all(ok)) for name, ok in rep.items()}

    def violations(self) -> List[str]:
        return [name for name, ok
                in self.predicate_report(narrow=self.narrow).items()
                if not ok]

    def in_bounds(self) -> bool:
        b = self.bounds
        return all(n.term <= b.max_term and n.last_index <= b.max_index
                   for n in self.nodes)

    # ------------------------------------------------------ choice menu

    def choices(self):
        """Every scheduler choice from the CURRENT state: alive vectors
        (<= max_dead down), blocked-link subsets over links actually
        carrying in-flight mail, timeout pulses (<= max_pulses alive
        voters), and (sessions) propose actions on self-believed
        leaders. Restore the state before calling."""
        b, k = self.bounds, self.cfg.k
        alive_opts = []
        for dead in range(b.max_dead + 1):
            for down in itertools.combinations(range(k), dead):
                alive_opts.append(tuple(i not in down for i in range(k)))
        active = sorted({(m.src, m.dst) for m in self.transport._outbox})
        if b.adversary == "isolate":
            # Directional single-node isolation: nothing blocked, or one
            # node's inbound / outbound / both directions cut (deduped —
            # isolating a node with no mail changes nothing).
            subsets = {()}
            for i in range(k):
                subsets.add(tuple(sorted(l for l in active if l[1] == i)))
                subsets.add(tuple(sorted(l for l in active if l[0] == i)))
                subsets.add(tuple(sorted(l for l in active if i in l)))
            blocked_opts = sorted(subsets)
        else:
            blocked_opts = []
            for r in range(len(active) + 1):
                for sub in itertools.combinations(active, r):
                    blocked_opts.append(sub)
        pulse_opts = [()]
        for r in range(1, b.max_pulses + 1):
            pulse_opts.extend(itertools.combinations(range(k), r))
        disk_opts = [()]
        for r in range(1, b.max_disk + 1):
            disk_opts.extend(itertools.combinations(range(k), r))
        prop_opts: list = [None]
        if b.sessions:
            for i, n in enumerate(self.nodes):
                if n.role == LEADER:
                    prop_opts.append((i, "new"))
                    if self.issued >= 0:
                        prop_opts.append((i, "dup"))
                    if b.admission:
                        prop_opts.append((i, "shed"))
        for alive in alive_opts:
            for blocked in blocked_opts:
                for pulse in pulse_opts:
                    if any(not alive[i] for i in pulse):
                        continue   # a dead node cannot time out
                    for disk in disk_opts:
                        for prop in prop_opts:
                            yield {"alive": alive, "blocked": blocked,
                                   "pulse": pulse, "disk": disk,
                                   "propose": prop}


def _msg_from_tuple(t: tuple):
    """Invert dataclasses.astuple for the 9 frozen RPC dataclasses
    (astuple of a flat dataclass is positional-field order)."""
    cls = _MSG_CLS[t[0]]
    vals = list(t)
    # astuple recursed into the entries tuple-of-tuples already; the
    # field wants tuples back (astuple yields tuples here, not lists).
    return cls(*vals)


_MSG_CLS = {
    rpc.RV_REQ: rpc.RequestVoteReq, rpc.RV_RESP: rpc.RequestVoteResp,
    rpc.AE_REQ: rpc.AppendEntriesReq, rpc.AE_RESP: rpc.AppendEntriesResp,
    rpc.IS_REQ: rpc.InstallSnapshotReq, rpc.IS_RESP: rpc.InstallSnapshotResp,
    rpc.PV_REQ: rpc.PreVoteReq, rpc.PV_RESP: rpc.PreVoteResp,
    rpc.TN_REQ: rpc.TimeoutNow,
}


# -------------------------------------------------- symmetry + canonical


def _permute_raw(raw: tuple, perm: tuple, k: int) -> tuple:
    """The frozen state under node relabeling i -> perm[i]: node order,
    every id-valued field (voted_for/leader_id/ghost leaders), every
    peer-indexed vector (votes/next/match), voter bitmasks, and message
    endpoints. Valid because the adversarial-timer regime removed the
    only id-dependent inputs (module docstring)."""
    nodes, msgs, alive_prev, gl, gc, issued = raw
    invp = [0] * k
    for i, p in enumerate(perm):
        invp[p] = i

    def rid(x):
        return perm[x] if 0 <= x < k else x

    def rmask(m):
        out = 0
        for i in range(k):
            if (m >> i) & 1:
                out |= 1 << perm[i]
        return out

    new_nodes = []
    for j in range(k):
        (term, voted_for, log, snap_index, snap_term, snap_digest,
         snap_voters, snap_sessions, role, leader_id, commit, applied,
         digest, sessions, votes, next_index, match_index, hb,
         le) = nodes[invp[j]]
        new_nodes.append((
            term, rid(voted_for), log, snap_index, snap_term, snap_digest,
            rmask(snap_voters), snap_sessions, role, rid(leader_id),
            commit, applied, digest, sessions,
            tuple(votes[invp[i]] for i in range(k)),
            tuple(next_index[invp[i]] for i in range(k)),
            tuple(match_index[invp[i]] for i in range(k)), hb, le))
    new_msgs = tuple(sorted(
        _permute_msg(m, perm, rmask) for m in msgs))
    return (tuple(new_nodes), new_msgs,
            tuple(alive_prev[invp[j]] for j in range(k)),
            tuple(sorted((t, perm[i]) for t, i in gl)), gc, issued)


def _permute_msg(m: tuple, perm: tuple, rmask) -> tuple:
    out = list(m)
    out[1], out[2] = perm[m[1]], perm[m[2]]
    if m[0] == rpc.IS_REQ:
        # snap_voters rides InstallSnapshot (field 6 after type/src/dst/
        # term/snap_index/snap_term... positional: type,src,dst,term,
        # snap_index,snap_term,snap_digest,snap_voters,snap_sessions).
        out[7] = rmask(m[7])
    return tuple(out)


def canonical(raw: tuple, k: int) -> tuple:
    """Minimum over all k! node relabelings — the symmetry quotient."""
    return min(_permute_raw(raw, perm, k)
               for perm in itertools.permutations(range(k)))


# --------------------------------------------------------------- the BFS


@dataclasses.dataclass
class Result:
    ok: bool
    states: int                 # canonical states reached
    transitions: int            # ticks executed
    depth: int                  # BFS levels completed
    complete: bool              # True iff no budget cap was hit
    pruned: int                 # states past max_term/max_index
    violation: Optional[dict] = None   # tick / predicates / schedule

    def summary(self) -> str:
        if not self.ok:
            v = self.violation
            return (f"VIOLATION {v['predicates']} at tick {v['tick']} "
                    f"({self.states} states)")
        tag = "exhaustive" if self.complete else "budget-capped"
        return (f"clean: {self.states} canonical states, "
                f"{self.transitions} transitions, depth {self.depth} "
                f"({tag}, {self.pruned} pruned at scope bound)")


def check(bounds: Bounds, node_cls=Node, log: Callable = None,
          prefix: tuple = (), narrow: bool = False) -> Result:
    """BFS over the canonicalized reachable states. Every state at
    every depth is checked against the shared predicates + history
    ghosts; the first violation wins and carries its full scheduler
    trace (root -> violation), already minimal in DEPTH because BFS
    reaches shallow states first.

    `prefix`: fixed scheduler choices for the first len(prefix) ticks —
    a waypoint drive into a deep protocol region, after which the BFS
    fans out exhaustively for the remaining `bounds.ticks - len(prefix)`
    levels (guided model checking). The emitted counterexample contains
    the prefix, so the artifact is still one complete, replayable
    schedule; clean-verification runs use no prefix. `narrow=True`
    evaluates the predicates on narrow-native views (r19, DESIGN.md
    §18) — the kill matrix must reproduce at both widths."""
    uni = Universe(bounds, node_cls, narrow=narrow)
    root = uni.freeze()
    seen = {canonical(root, bounds.k)}
    frontier = [(root, ())]     # (raw state, schedule that reached it)
    states = transitions = pruned = 0
    capped = False
    for depth in range(bounds.ticks):
        nxt = []
        for raw, sched in frontier:
            if depth < len(prefix):
                menu = [prefix[depth]]
            else:
                uni.restore(raw)
                menu = list(uni.choices())
            for choice in menu:
                uni.restore(raw)
                try:
                    viol = uni.tick(depth, choice)
                except AssertionError:
                    # A step-internal assert (e.g. "refusing to truncate
                    # committed entries") firing IS a safety finding —
                    # the oracle's own last-line guard tripped.
                    viol = ["oracle_assertion"]
                transitions += 1
                if viol:
                    try:
                        report = uni.predicate_report(narrow=uni.narrow)
                    except Exception:
                        report = {}   # mid-assert state may not view
                    return Result(
                        ok=False, states=len(seen),
                        transitions=transitions, depth=depth + 1,
                        complete=False, pruned=pruned,
                        violation={
                            "tick": depth,
                            "predicates": viol,
                            "schedule": list(sched) + [choice],
                            "report": report,
                        })
                if not uni.in_bounds():
                    pruned += 1
                    continue
                new_raw = uni.freeze()
                ck = canonical(new_raw, bounds.k)
                if depth < len(prefix):
                    # Prefix drive, not exploration: a waypoint tick may
                    # be a canonical no-op (e.g. a quiet tick before any
                    # mail is in flight) — dedup must not prune the ride.
                    seen.add(ck)
                    nxt.append((new_raw, sched + (choice,)))
                    continue
                if ck in seen:
                    continue
                if len(seen) >= bounds.max_states:
                    capped = True
                    continue
                seen.add(ck)
                nxt.append((new_raw, sched + (choice,)))
        frontier = nxt
        if log:
            log(f"mcheck depth {depth + 1}: {len(seen)} states, "
                f"{transitions} transitions")
        if not frontier:
            break
    return Result(ok=True, states=len(seen), transitions=transitions,
                  depth=depth + 1, complete=not capped, pruned=pruned)


# ----------------------------------------------- hunt (guided search)


def _quiet(choice_alive_k: int) -> dict:
    return {"alive": tuple([True] * choice_alive_k), "blocked": (),
            "pulse": (), "disk": (), "propose": None}


def hunt(bounds: Bounds, node_cls=Node, episodes: int = 2000,
         horizon: int = 20, seed: int = 0, log: Callable = None):
    """Biased random-walk search for deep counterexamples — the
    simulation mode every bounded checker grows once exhaustive depth
    runs out (TLC's -simulate). Episodes sample schedules that LOOK
    like fault traces — STICKY faults: a crashed node stays down and a
    blocked direction stays blocked across consecutive ticks with high
    probability, the way real gray failures persist, plus occasional
    pulses. The deep counterexamples (Figure 8, deposed-leader
    replication) all need a fault HELD across 5-10 ticks, which
    independent per-tick sampling essentially never produces. A hit is
    shrunk (`shrink_schedule`) and returned as (schedule, predicates).
    Deterministic under `seed` — the kill matrix pins its seeds.
    Returns None if no violation within the budget."""
    import random
    r = random.Random(seed)
    uni = Universe(bounds, node_cls)
    root = uni.freeze()
    k = bounds.k
    links = [(a, b) for a in range(k) for b in range(k) if a != b]
    for ep in range(episodes):
        uni.restore(root)
        sched = []
        down = None          # sticky crash
        blocked = ()         # sticky directional block
        full = None          # sticky disk-full node (r20)
        for t in range(horizon):
            c = dict(_quiet(k))
            if down is not None and r.random() < 0.65:
                pass                            # stays down
            elif bounds.max_dead and r.random() < 0.15:
                down = r.randrange(k)
            else:
                down = None
            if down is not None:
                c["alive"] = tuple(i != down for i in range(k))
            if blocked and r.random() < 0.70:
                pass                            # stays blocked
            elif r.random() < 0.35:
                i = r.randrange(k)
                dirn = r.random()
                if dirn < 0.4:
                    blocked = tuple(l for l in links if l[0] == i)
                elif dirn < 0.8:
                    blocked = tuple(l for l in links if l[1] == i)
                else:
                    blocked = tuple(l for l in links if i in l)
            else:
                blocked = ()
            c["blocked"] = blocked
            # Sticky disk pressure: a full disk stays full across ticks
            # with high probability, like the crash/block faults — the
            # durability bugs need the budget held across an AE round
            # trip, which per-tick sampling essentially never produces.
            if full is not None and r.random() < 0.70:
                pass                            # stays full
            elif bounds.max_disk and r.random() < 0.30:
                full = r.randrange(k)
            else:
                full = None
            if full is not None:
                c["disk"] = (full,)
            if r.random() < 0.45:
                c["pulse"] = (r.randrange(k),)
            if bounds.sessions and r.random() < 0.5:
                lead = [i for i, n in enumerate(uni.nodes)
                        if n.role == LEADER]
                if lead:
                    kind = "dup" if (uni.issued >= 0
                                     and r.random() < 0.5) else "new"
                    if bounds.admission and r.random() < 0.35:
                        kind = "shed"
                    c["propose"] = (r.choice(lead), kind)
            sched.append(c)
            try:
                viol = uni.tick(t, c)
            except AssertionError:
                viol = ["oracle_assertion"]
            if viol:
                if log:
                    log(f"hunt: hit {viol} at tick {t}, episode {ep}")
                return shrink_schedule(bounds, node_cls, sched), viol
    return None


def run_schedule(bounds: Bounds, node_cls, sched):
    """Run a fixed schedule from the initial state; returns (tick,
    predicates) of the first violation or None."""
    uni = Universe(bounds, node_cls)
    for t, c in enumerate(sched):
        try:
            viol = uni.tick(t, c)
        except AssertionError:
            viol = ["oracle_assertion"]
        if viol:
            return t, viol
    return None


def shrink_schedule(bounds: Bounds, node_cls, sched):
    """Greedy counterexample minimization (the nemesis searcher's
    auto-shrink, on scheduler traces): truncate to the violating tick,
    then try simplifying each tick's choice one field at a time toward
    the quiet choice (everyone alive, nothing blocked, no pulse, no
    propose), keeping a change only if SOME violation still occurs."""
    hit = run_schedule(bounds, node_cls, sched)
    assert hit is not None, "shrink called on a non-violating schedule"
    sched = list(sched[:hit[0] + 1])
    quiet = _quiet(bounds.k)
    for t in range(len(sched)):
        for field in ("alive", "blocked", "pulse", "disk", "propose"):
            if sched[t].get(field, quiet[field]) == quiet[field]:
                continue
            trial = [dict(c) for c in sched]
            trial[t][field] = quiet[field]
            if run_schedule(bounds, node_cls, trial) is not None:
                sched = trial
    hit = run_schedule(bounds, node_cls, sched)
    return list(sched[:hit[0] + 1])


# ------------------------------------------- nemesis-format reproducers


def _choice_json(c: dict) -> dict:
    return {"alive": list(c["alive"]),
            "blocked": [list(l) for l in c["blocked"]],
            "pulse": list(c["pulse"]),
            "disk": list(c.get("disk", ())),
            "propose": list(c.get("propose")) if c.get("propose") else None}


def reproducer(result: Result, bounds: Bounds,
               mutant: str = None) -> dict:
    """A model-checker counterexample as a nemesis-format artifact
    (nemesis/search.py ARTIFACT_SCHEMA): same schema/violation shape so
    the triage tooling reads it, with kind/engines marking it an oracle
    schedule and the explicit per-tick scheduler trace replacing the
    hashed nemesis program. `scripts/nemesis_search.py --replay`
    dispatches on `kind` to `replay` below. `mutant` names the seeded
    mutant the schedule kills (verify/mutants.py) — None means the
    counterexample is against the REAL oracle step (which would be a
    genuine protocol bug, not a harness artifact)."""
    from raft_tpu.nemesis import search as nsearch
    assert not result.ok and result.violation is not None
    v = result.violation
    cfg = bounds_config(bounds)
    return {
        "schema": nsearch.ARTIFACT_SCHEMA,
        "kind": ARTIFACT_KIND,
        "engines": ARTIFACT_ENGINES,
        "mutant": mutant,
        "config": {"k": cfg.k, "log_cap": cfg.log_cap,
                   "sessions": cfg.sessions, "prevote": cfg.prevote},
        "bounds": dataclasses.asdict(bounds),
        "program": None,
        "inject": None,
        "n_ticks": len(v["schedule"]),
        "n_groups": 1,
        "schedule": [_choice_json(c) for c in v["schedule"]],
        "violation": {"tick": v["tick"],
                      "leaf": "predicates." + v["predicates"][0],
                      "leaf_report": {k_: bool(ok)
                                      for k_, ok in v["report"].items()},
                      "boundary": None},
        "note": ("bounded model-checker counterexample: explicit "
                 "scheduler trace (alive/blocked/pulse/propose per "
                 "tick) on the CPU oracle at small scope"),
    }


def save_reproducer(art: dict, path: str):
    with open(path, "w") as f:
        json.dump(art, f, indent=2, sort_keys=True)


def load_reproducer(path: str) -> dict:
    with open(path) as f:
        art = json.load(f)
    if art.get("kind") != ARTIFACT_KIND:
        raise ValueError(f"not an mcheck artifact: kind={art.get('kind')}")
    return art


def replay(art: dict, node_cls=None) -> dict:
    """Re-run an artifact's schedule on a fresh universe; returns the
    violation report and raises if the violation does not reproduce at
    the recorded tick (nsearch.verify_reproducer's contract). With
    `node_cls=None` the artifact's own `mutant` field picks the node
    class (the real oracle when it is None/absent)."""
    if node_cls is None:
        name = art.get("mutant")
        if name:
            from raft_tpu.verify import mutants
            node_cls = mutants.by_name(name).node_cls
        else:
            node_cls = Node
    bounds = Bounds(**art["bounds"])
    uni = Universe(bounds, node_cls)
    for t, c in enumerate(art["schedule"]):
        choice = {"alive": tuple(c["alive"]),
                  "blocked": tuple(tuple(l) for l in c["blocked"]),
                  "pulse": tuple(c["pulse"]),
                  "disk": tuple(c.get("disk", ())),
                  "propose": (tuple(c["propose"])
                              if c.get("propose") else None)}
        try:
            viol = uni.tick(t, choice)
        except AssertionError:
            viol = ["oracle_assertion"]
        if viol:
            want = art["violation"]
            if t != want["tick"]:
                raise AssertionError(
                    f"violation moved: tick {t} != {want['tick']}")
            leaf = "predicates." + viol[0]
            if leaf != want["leaf"]:
                raise AssertionError(
                    f"violation leaf moved: {leaf} != {want['leaf']}")
            return {"tick": t, "predicates": viol}
    raise AssertionError("schedule replayed clean — violation did not "
                         "reproduce")


# ----------------------------------------------- narrow-width agreement


def narrow_agreement_problems(ticks: int = 2, max_states: int = 250,
                              sessions: bool = False) -> list[str]:
    """Walk the k=2 small-scope universe (depth `ticks`, up to
    `max_states` states) asserting `predicate_report()` and
    `predicate_report(narrow=True)` return IDENTICAL verdicts at every
    visited state — the r19 proof that verify/invariants' predicates
    hold at the narrow-native widths (their `_signed` lifts work) over
    an exhaustive state space, not one example. Returns problem
    strings (empty = agreement everywhere); wired into `smoke` and the
    auditor's narrowing pass."""
    b = Bounds(k=2, ticks=ticks, max_states=max_states, sessions=sessions)
    uni = Universe(b)
    problems: list[str] = []
    seen = 0

    def walk(depth: int, t: int):
        nonlocal seen
        if problems or seen >= max_states:
            return
        seen += 1
        wide = uni.predicate_report()
        narrow = uni.predicate_report(narrow=True)
        if wide != narrow:
            diff = {k: (wide[k], narrow[k]) for k in wide
                    if wide[k] != narrow.get(k)}
            problems.append(
                f"narrow-width predicate disagreement at depth "
                f"{ticks - depth}: wide vs narrow {diff}")
            return
        if depth == 0:
            return
        frozen = uni.freeze()
        for choice in list(uni.choices()):
            uni.restore(frozen)
            try:
                uni.tick(t, choice)
            except AssertionError:
                continue   # pruned oracle path; agreement is the question
            walk(depth - 1, t + 1)
            if problems or seen >= max_states:
                break
        uni.restore(frozen)

    walk(ticks, 0)
    return problems


# ------------------------------------------------------------- the smoke


def smoke(ticks: int = 3, max_states: int = 1500) -> Result:
    """The depth-limited audit smoke (scripts/ci_static.sh,
    `startup_audit --level deep`): the k=2 universe explored a few
    levels deep must verify clean, AND a canary mutant (the documented
    round-1 takeover bug) must be killed — proof the checker both
    passes the real step and still has teeth, in seconds."""
    b = Bounds(k=2, ticks=ticks, max_states=max_states)
    res = check(b)
    if not res.ok:
        return res
    # r19: the shared predicates must report identically on wide and
    # narrow-native views over the explored scope (DESIGN.md §18).
    nw = narrow_agreement_problems(ticks=2, max_states=250)
    if nw:
        return Result(ok=False, states=res.states,
                      transitions=res.transitions, depth=res.depth,
                      complete=res.complete, pruned=res.pruned,
                      violation={"tick": -1,
                                 "predicates": ["narrow_disagreement"],
                                 "schedule": [],
                                 "report": {"narrow": nw[:4]}})
    from raft_tpu.verify import mutants
    canary = mutants.by_name("minority_quorum")
    kill = check(Bounds(k=2, ticks=2, max_states=max_states,
                        max_pulses=2), node_cls=canary.node_cls)
    if kill.ok:
        return Result(ok=False, states=kill.states,
                      transitions=kill.transitions, depth=kill.depth,
                      complete=kill.complete, pruned=kill.pruned,
                      violation={"tick": -1,
                                 "predicates": ["mutant_survived"],
                                 "schedule": [],
                                 "report": {"canary": False}})
    return res
