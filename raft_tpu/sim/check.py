"""Batched invariant checks over a `State` — the `Cluster` safety
checkers (cluster.py:73-96) lifted to `[G, K]` arrays.

Used two ways (DESIGN.md §8):

- Point-in-time: tests and `__graft_entry__.dryrun_multichip` call
  `all_invariants` on an endpoint state — cheap gross-violation catch
  at 10^5-group scale where lockstep comparison is impractical.
- Per-tick: `tick_safety` is folded into `Metrics.safety` EVERY tick by
  `run.metrics_update` (and its k-state port `pkernel._safety_tick`),
  turning every bench run into a continuous runtime-verification soak —
  a violation that exists for a single tick between check boundaries
  can no longer hide.

The predicate BODIES live in `verify/invariants.py` (r18): one
invariant source shared with the bounded model checker, evaluated here
with `xp=jnp` over State leaves — the runtime fold is a spot-check of
the exact predicates `verify.mcheck` proves exhaustively at small
scope. The differential suite remains the strong correctness gate;
these are the cheap always-on safety net.
"""

from __future__ import annotations

import jax.numpy as jnp

from raft_tpu.sim.state import State
from raft_tpu.verify import invariants as inv


def election_safety(st: State):
    """bool[G]: no two current leaders share a term (point-in-time form of
    cluster._check_election_safety; crashed leaders still hold their term)."""
    return inv.election_safety(st.nodes.role, st.nodes.term, xp=jnp)


def digest_agreement(st: State):
    """bool[G]: nodes that applied the same prefix hold the same state-
    machine digest (commit-identity, cluster._on_apply's invariant)."""
    return inv.digest_agreement(st.nodes.applied, st.nodes.digest, xp=jnp)


def window_bounds(st: State, log_cap: int):
    """bool[G]: per-node structural sanity — applied == commit (phase A
    drains), snap <= commit <= last, window within the ring capacity."""
    n = st.nodes
    return inv.window_bounds(n.applied, n.commit, n.snap_index,
                             n.last_index, log_cap, xp=jnp)


def leader_completeness(st: State, log_cap: int):
    """bool[G]: a current leader's log covers every node's committed
    prefix — commit_b <= last_index_a plus per-ring-lane payload
    agreement on the committed overlap, for every ordered pair with
    role_a == LEADER and term_a >= term_b (the r18 clause; soundness
    argument in verify/invariants.py). Payload-based, so takeover's
    in-place re-term never trips it."""
    n = st.nodes
    return inv.leader_completeness(n.role, n.term, n.commit, n.last_index,
                                   n.snap_index, n.log_payload, log_cap,
                                   xp=jnp)


def client_safety(st: State):
    """bool[G]: the exactly-once invariant (DESIGN.md §10), checked
    every tick when the scheduled client traffic is on. Two clauses:

    - dedup-decision agreement: nodes with the SAME applied prefix hold
      element-identical (sid -> seq) tables — a divergent dedup
      decision (one node skipping a duplicate another folded) trips
      this even if the digests happen to collide;
    - no phantom apply: no node's table entry exceeds the slot's issued
      frontier (`clients.done` — the client never issued a higher seq,
      and dedup-table entries only ever come from applied commands).

    A duplicate retry that double-applied would desynchronize either
    the tables (clause 1) or the digest chain (digest_agreement); the
    pair is what lets the bench assert "a duplicate never
    double-applies" per segment instead of per run. (A table LOWER
    bound is deliberately absent: restart rewinds a node's live table
    to its snapshot table until re-apply catches up, so "every ack has
    a current table witness" is not crash-stable — the ack-time
    witness requirement lives in the client transition itself and in
    the oracle differential, tests/test_clients.py.)"""
    return inv.client_safety(st.nodes.applied, st.nodes.session_seq,
                             st.clients.done, xp=jnp)


def predicate_report(st: State, log_cap: int) -> dict:
    """name -> bool[G]: `tick_safety`'s clauses SEPARATELY — the
    nemesis search (raft_tpu/nemesis/search.py) scores near-misses per
    predicate and its safety-violation triage names WHICH invariant a
    state breaks, not just that one did. Key order is stable (report/
    artifact fields; new keys append so pre-r18 artifacts' leaf names
    stay valid). THE clause registry: `all_invariants` (and hence
    `tick_safety`) is its AND-reduce, so a predicate added here is
    automatically folded and nameable — they cannot drift."""
    out = {"election_safety": election_safety(st),
           "digest_agreement": digest_agreement(st),
           "window_bounds": window_bounds(st, log_cap)}
    if st.clients is not None:
        out["client_safety"] = client_safety(st)
    out["leader_completeness"] = leader_completeness(st, log_cap)
    return out


def all_invariants(st: State, log_cap: int):
    ok = None
    for v in predicate_report(st, log_cap).values():
        ok = v if ok is None else ok & v
    return ok


def tick_safety(st: State, log_cap: int):
    """bool[G]: the per-tick safety predicate ANDed into
    `Metrics.safety` on both engines — election safety, digest
    agreement, window bounds, leader completeness, and (with scheduled
    clients on) the exactly-once invariant. A named alias of
    `all_invariants` so the fold's contract ("what exactly does the
    safety bit attest?") has one definition site; pkernel's
    `_safety_tick` must mirror any change here term-for-term (pinned by
    the kernel differentials and scripts/check_metric_parity.py's field
    parity). Pre-r18 checkpoints resume cleanly under the stronger
    fold: `safety` is an AND accumulator, so a resumed run simply
    starts attesting the new clause from its first resumed tick."""
    return all_invariants(st, log_cap)
