"""Batched invariant checks over a `State` — the `Cluster` safety
checkers (cluster.py:73-96) lifted to `[G, K]` arrays.

Used two ways (DESIGN.md §8):

- Point-in-time: tests and `__graft_entry__.dryrun_multichip` call
  `all_invariants` on an endpoint state — cheap gross-violation catch
  at 10^5-group scale where lockstep comparison is impractical.
- Per-tick: `tick_safety` is folded into `Metrics.safety` EVERY tick by
  `run.metrics_update` (and its k-state port `pkernel._safety_tick`),
  turning every bench run into a continuous runtime-verification soak —
  a violation that exists for a single tick between check boundaries
  can no longer hide.

The differential suite remains the strong correctness gate; these
predicates are the cheap always-on safety net.
"""

from __future__ import annotations

import itertools

import jax.numpy as jnp

from raft_tpu.core.node import LEADER
from raft_tpu.sim.state import State


def election_safety(st: State):
    """bool[G]: no two current leaders share a term (point-in-time form of
    cluster._check_election_safety; crashed leaders still hold their term)."""
    nodes = st.nodes
    k = nodes.term.shape[1]
    ok = jnp.ones(nodes.term.shape[0], jnp.bool_)
    for a, b in itertools.combinations(range(k), 2):
        clash = ((nodes.role[:, a] == LEADER) & (nodes.role[:, b] == LEADER)
                 & (nodes.term[:, a] == nodes.term[:, b]))
        ok &= ~clash
    return ok


def digest_agreement(st: State):
    """bool[G]: nodes that applied the same prefix hold the same state-
    machine digest (commit-identity, cluster._on_apply's invariant)."""
    nodes = st.nodes
    k = nodes.term.shape[1]
    ok = jnp.ones(nodes.term.shape[0], jnp.bool_)
    for a, b in itertools.combinations(range(k), 2):
        clash = ((nodes.applied[:, a] == nodes.applied[:, b])
                 & (nodes.digest[:, a] != nodes.digest[:, b]))
        ok &= ~clash
    return ok


def window_bounds(st: State, log_cap: int):
    """bool[G]: per-node structural sanity — applied == commit (phase A
    drains), snap <= commit <= last, window within the ring capacity."""
    n = st.nodes
    ok = ((n.applied == n.commit)
          & (n.snap_index <= n.commit) & (n.commit <= n.last_index)
          & (n.last_index - n.snap_index <= log_cap))
    return jnp.all(ok, axis=1)


def client_safety(st: State):
    """bool[G]: the exactly-once invariant (DESIGN.md §10), checked
    every tick when the scheduled client traffic is on. Two clauses:

    - dedup-decision agreement: nodes with the SAME applied prefix hold
      element-identical (sid -> seq) tables — a divergent dedup
      decision (one node skipping a duplicate another folded) trips
      this even if the digests happen to collide;
    - no phantom apply: no node's table entry exceeds the slot's issued
      frontier (`clients.done` — the client never issued a higher seq,
      and dedup-table entries only ever come from applied commands).

    A duplicate retry that double-applied would desynchronize either
    the tables (clause 1) or the digest chain (digest_agreement); the
    pair is what lets the bench assert "a duplicate never
    double-applies" per segment instead of per run. (A table LOWER
    bound is deliberately absent: restart rewinds a node's live table
    to its snapshot table until re-apply catches up, so "every ack has
    a current table witness" is not crash-stable — the ack-time
    witness requirement lives in the client transition itself and in
    the oracle differential, tests/test_clients.py.)"""
    nodes = st.nodes
    cl = st.clients
    k = nodes.term.shape[1]
    table = nodes.session_seq                       # [G, K, S]
    ok = jnp.all(table <= cl.done[:, None, :], axis=(1, 2))
    for a, b in itertools.combinations(range(k), 2):
        clash = ((nodes.applied[:, a] == nodes.applied[:, b])
                 & jnp.any(table[:, a] != table[:, b], axis=-1))
        ok &= ~clash
    return ok


def predicate_report(st: State, log_cap: int) -> dict:
    """name -> bool[G]: `tick_safety`'s clauses SEPARATELY — the
    nemesis search (raft_tpu/nemesis/search.py) scores near-misses per
    predicate and its safety-violation triage names WHICH invariant a
    state breaks, not just that one did. Key order is stable (report/
    artifact fields). THE clause registry: `all_invariants` (and hence
    `tick_safety`) is its AND-reduce, so a predicate added here is
    automatically folded and nameable — they cannot drift."""
    out = {"election_safety": election_safety(st),
           "digest_agreement": digest_agreement(st),
           "window_bounds": window_bounds(st, log_cap)}
    if st.clients is not None:
        out["client_safety"] = client_safety(st)
    return out


def all_invariants(st: State, log_cap: int):
    ok = None
    for v in predicate_report(st, log_cap).values():
        ok = v if ok is None else ok & v
    return ok


def tick_safety(st: State, log_cap: int):
    """bool[G]: the per-tick safety predicate ANDed into
    `Metrics.safety` on both engines — election safety, digest
    agreement, window bounds, and (with scheduled clients on) the
    exactly-once invariant. A named alias of `all_invariants` so the
    fold's contract ("what exactly does the safety bit attest?") has
    one definition site; pkernel's `_safety_tick` must mirror any
    change here term-for-term (pinned by the kernel differentials and
    scripts/check_metric_parity.py's field parity)."""
    return all_invariants(st, log_cap)
