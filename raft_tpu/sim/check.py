"""Batched invariant checks over a `State` — the `Cluster` safety
checkers (cluster.py:73-96) lifted to `[G, K]` arrays.

Used two ways (DESIGN.md §8):

- Point-in-time: tests and `__graft_entry__.dryrun_multichip` call
  `all_invariants` on an endpoint state — cheap gross-violation catch
  at 10^5-group scale where lockstep comparison is impractical.
- Per-tick: `tick_safety` is folded into `Metrics.safety` EVERY tick by
  `run.metrics_update` (and its k-state port `pkernel._safety_tick`),
  turning every bench run into a continuous runtime-verification soak —
  a violation that exists for a single tick between check boundaries
  can no longer hide.

The differential suite remains the strong correctness gate; these
predicates are the cheap always-on safety net.
"""

from __future__ import annotations

import itertools

import jax.numpy as jnp

from raft_tpu.core.node import LEADER
from raft_tpu.sim.state import State


def election_safety(st: State):
    """bool[G]: no two current leaders share a term (point-in-time form of
    cluster._check_election_safety; crashed leaders still hold their term)."""
    nodes = st.nodes
    k = nodes.term.shape[1]
    ok = jnp.ones(nodes.term.shape[0], jnp.bool_)
    for a, b in itertools.combinations(range(k), 2):
        clash = ((nodes.role[:, a] == LEADER) & (nodes.role[:, b] == LEADER)
                 & (nodes.term[:, a] == nodes.term[:, b]))
        ok &= ~clash
    return ok


def digest_agreement(st: State):
    """bool[G]: nodes that applied the same prefix hold the same state-
    machine digest (commit-identity, cluster._on_apply's invariant)."""
    nodes = st.nodes
    k = nodes.term.shape[1]
    ok = jnp.ones(nodes.term.shape[0], jnp.bool_)
    for a, b in itertools.combinations(range(k), 2):
        clash = ((nodes.applied[:, a] == nodes.applied[:, b])
                 & (nodes.digest[:, a] != nodes.digest[:, b]))
        ok &= ~clash
    return ok


def window_bounds(st: State, log_cap: int):
    """bool[G]: per-node structural sanity — applied == commit (phase A
    drains), snap <= commit <= last, window within the ring capacity."""
    n = st.nodes
    ok = ((n.applied == n.commit)
          & (n.snap_index <= n.commit) & (n.commit <= n.last_index)
          & (n.last_index - n.snap_index <= log_cap))
    return jnp.all(ok, axis=1)


def all_invariants(st: State, log_cap: int):
    return election_safety(st) & digest_agreement(st) & window_bounds(
        st, log_cap)


def tick_safety(st: State, log_cap: int):
    """bool[G]: the per-tick safety predicate ANDed into
    `Metrics.safety` on both engines — election safety, digest
    agreement, window bounds. A named alias of `all_invariants` so the
    fold's contract ("what exactly does the safety bit attest?") has
    one definition site; pkernel's `_safety_tick` must mirror any
    change here term-for-term (pinned by the kernel differentials and
    scripts/check_metric_parity.py's field parity)."""
    return all_invariants(st, log_cap)
