"""Batched invariant checks over a `State` — the `Cluster` safety
checkers (cluster.py:73-96) lifted to `[G, K]` arrays.

Used by tests and `__graft_entry__.dryrun_multichip`; not part of the
hot path. The differential suite is the strong correctness gate; these
catch gross violations cheaply at 10^5-group scale where lockstep
comparison is impractical.
"""

from __future__ import annotations

import itertools

import jax.numpy as jnp

from raft_tpu.core.node import LEADER
from raft_tpu.sim.state import State


def election_safety(st: State):
    """bool[G]: no two current leaders share a term (point-in-time form of
    cluster._check_election_safety; crashed leaders still hold their term)."""
    nodes = st.nodes
    k = nodes.term.shape[1]
    ok = jnp.ones(nodes.term.shape[0], jnp.bool_)
    for a, b in itertools.combinations(range(k), 2):
        clash = ((nodes.role[:, a] == LEADER) & (nodes.role[:, b] == LEADER)
                 & (nodes.term[:, a] == nodes.term[:, b]))
        ok &= ~clash
    return ok


def digest_agreement(st: State):
    """bool[G]: nodes that applied the same prefix hold the same state-
    machine digest (commit-identity, cluster._on_apply's invariant)."""
    nodes = st.nodes
    k = nodes.term.shape[1]
    ok = jnp.ones(nodes.term.shape[0], jnp.bool_)
    for a, b in itertools.combinations(range(k), 2):
        clash = ((nodes.applied[:, a] == nodes.applied[:, b])
                 & (nodes.digest[:, a] != nodes.digest[:, b]))
        ok &= ~clash
    return ok


def window_bounds(st: State, log_cap: int):
    """bool[G]: per-node structural sanity — applied == commit (phase A
    drains), snap <= commit <= last, window within the ring capacity."""
    n = st.nodes
    ok = ((n.applied == n.commit)
          & (n.snap_index <= n.commit) & (n.commit <= n.last_index)
          & (n.last_index - n.snap_index <= log_cap))
    return jnp.all(ok, axis=1)


def all_invariants(st: State, log_cap: int):
    return election_safety(st) & digest_agreement(st) & window_bounds(
        st, log_cap)
